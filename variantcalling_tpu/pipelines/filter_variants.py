"""filter_variants_pipeline — ML filtering of a called VCF on TPU.

Drop-in surface of the reference tool (docs/filter_variants_pipeline.md:
same flags), re-founded: VCF -> columnar table -> featurization + forest
inference as one jitted device program over the variants axis -> VCF
writeback with TREE_SCORE / PASS / LOW_SCORE / COHORT_FP / HPOL_RUN.

Hot-path structure (BASELINE north_star): per-variant work is a (N, F)
tensor; scoring shards over the mesh dp axis; chunked execution bounds
host memory with one compile per chunk shape.
"""

from __future__ import annotations

import argparse
import os
import pickle
import sys
import threading

import numpy as np

import jax
import jax.numpy as jnp

from variantcalling_tpu import engine as engine_mod
from variantcalling_tpu import knobs, logger, obs
from variantcalling_tpu.engine import EngineError
from variantcalling_tpu.utils import degrade
from variantcalling_tpu.featurize import host_featurize
from variantcalling_tpu.io import bed as bedio
from variantcalling_tpu.io.fasta import FastaReader
from variantcalling_tpu.io.vcf import FactorizedColumn, VariantTable, read_vcf, write_vcf
from variantcalling_tpu.models import dan as dan_mod
from variantcalling_tpu.models import forest as forest_mod
from variantcalling_tpu.models import registry as registry_mod
from variantcalling_tpu.models import threshold as threshold_mod
from variantcalling_tpu.models.dan import DanModel
from variantcalling_tpu.models.forest import FlatForest
from variantcalling_tpu.models.registry import load_model
from variantcalling_tpu.models.threshold import ThresholdModel
from variantcalling_tpu.ops import intervals as iops

#: model types that ride the fused featurize+score device program
#: (everything else falls back to the host predict_proba path)
_FUSED_MODEL_TYPES = (FlatForest, ThresholdModel, DanModel)

LOW_SCORE = "LOW_SCORE"
COHORT_FP = "COHORT_FP"
HPOL_RUN = "HPOL_RUN"
PASS = "PASS"
CHUNK = 1 << 18

#: sidecar collecting the ORIGINAL records of quarantined chunks
#: (``VCTPU_QUARANTINE=1`` — docs/robustness.md "Recovery ladder")
QUARANTINE_SUFFIX = ".quarantine"


def quarantine_path(out_path: str) -> str:
    return str(out_path) + QUARANTINE_SUFFIX


def _traced_chunks(tables):
    """Causal-tracing ingest boundary (docs/observability.md "Causal
    chunk tracing"): every chunk table gets a run-scoped TRACE id here —
    the root ``ingest`` span of its DAG — carried on the table object
    (``_obs_trace``) so every downstream stage (featurize, score,
    megabatch dispatch, render, compress, sequenced commit) and every
    recovery-ladder action can link its span/event to the chunk. A
    no-op pass-through when tracing is off (``obs.new_trace`` returns
    None). The wrapper wraps ALL four streaming layouts' sources, so
    trace ids are allocated in canonical chunk order everywhere."""
    import time as _time

    it = iter(tables)
    while True:
        t0 = _time.perf_counter()  # vctpu-lint: disable=VCT006 — obs trace-span timing
        try:
            table = next(it)
        except StopIteration:
            return
        tid = obs.new_trace()
        if tid is not None:
            table._obs_trace = tid
            obs.trace_span(tid, "ingest",
                           _time.perf_counter() - t0,  # vctpu-lint: disable=VCT006 — obs trace-span timing
                           records=len(table))
        yield table


def _guard_chunk(table, what: str, body):
    """Rung 3 of the supervised recovery ladder for one chunk body.

    Runs ``body()``; on failure either re-raises (the DEFAULT — byte
    parity stays untouchable, a poison chunk fails the run loudly) or,
    when ``VCTPU_QUARANTINE=1`` and this is the FINAL re-dispatch attempt
    of the chunk's retry budget (:func:`pipeline.on_final_attempt`),
    diverts the chunk by returning ``None`` — the render stage then
    writes the ORIGINAL records to the ``<out>.quarantine`` sidecar and
    zero bytes to the main output. Diversion is loud by construction: it
    routes through ``degrade.record(warn=True)`` and a ``recovery`` obs
    event, so no record can leave the output silently.
    """
    from variantcalling_tpu.parallel import pipeline as pipeline_mod
    from variantcalling_tpu.utils import faults

    try:
        # injection point: deterministic per-chunk poison
        # (tests/unit/test_streaming_faults.py, tools/chaoshunt)
        faults.check("pipeline.chunk")
        return body()
    except (EngineError, pipeline_mod.StageTimeoutError,
            pipeline_mod.LadderEscalation):
        raise
    # quarantine records via degrade.record; every other path re-raises
    except Exception as e:  # noqa: BLE001  # vctpu-lint: disable=VCT002 — opt-in quarantine routes through degrade.record(warn=True) in record_quarantine; default re-raises
        if not knobs.get_bool("VCTPU_QUARANTINE") \
                or not pipeline_mod.on_final_attempt():
            raise
        pipeline_mod.record_quarantine(what, len(table), e)
        return None


def get_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="filter_variants_pipeline", description="Filter VCF")
    ap.add_argument("--input_file", required=True, help="Name of the input VCF file")
    ap.add_argument("--model_file", required=True, help="Pickle model file")
    ap.add_argument("--model_name", required=True, help="Model name inside the pickle")
    ap.add_argument(
        "--hpol_filter_length_dist",
        nargs=2,
        type=int,
        default=[10, 10],
        help="Length and distance to the hpol run to mark",
    )
    ap.add_argument("--runs_file", help="Homopolymer runs BED file")
    ap.add_argument("--blacklist", help="Blacklist file (bed/h5/pkl of loci)")
    ap.add_argument("--blacklist_cg_insertions", action="store_true", help="Filter CCG/GGC insertions")
    ap.add_argument("--reference_file", required=True, help="Indexed reference FASTA file")
    ap.add_argument("--output_file", required=True, help="Output VCF file")
    ap.add_argument("--is_mutect", action="store_true", help="Input is a Mutect callset")
    ap.add_argument("--flow_order", default="TGCA", help="Sequencing flow order (4 cycle)")
    ap.add_argument(
        "--annotate_intervals",
        action="append",
        default=[],
        help="interval files for annotation (multiple possible)",
    )
    ap.add_argument("--backend", default="tpu", choices=["tpu", "cpu"], help="Execution backend")
    ap.add_argument("--limit_to_contig", default=None, help="Process a single contig")
    return ap


def _interval_name(path: str) -> str:
    base = os.path.basename(path)
    for suffix in (".bed.gz", ".bed", ".interval_list"):
        if base.endswith(suffix):
            return base[: -len(suffix)]
    return base


def read_blacklist(path: str) -> tuple[np.ndarray, np.ndarray]:
    """Blacklist loci -> (chrom object array, pos 1-based). Accepts bed/h5/pkl."""
    if path.endswith((".bed", ".bed.gz")):
        iv = bedio.read_bed(path)
        return iv.chrom, (iv.start + 1).astype(np.int64)
    if path.endswith((".h5", ".hdf", ".hdf5")):
        from variantcalling_tpu.utils.h5_utils import list_keys, read_hdf

        df = read_hdf(path, key=list_keys(path)[0])
        if isinstance(df.index, __import__("pandas").MultiIndex):
            df = df.reset_index()
        return df["chrom"].to_numpy(dtype=object), df["pos"].to_numpy(dtype=np.int64)
    with open(path, "rb") as fh:
        obj = pickle.load(fh)
    chroms, poss = zip(*obj) if obj else ((), ())
    out_c = np.empty(len(chroms), dtype=object)
    out_c[:] = chroms
    return out_c, np.asarray(poss, dtype=np.int64)


def _is_cg_insertion(table: VariantTable, windows: np.ndarray, center: int) -> np.ndarray:
    """CCG/GGC insertion artifacts (--blacklist_cg_insertions,
    docs/filter_variants_pipeline.md "Should CCG/GGC insertions be filtered out?").

    A single-base insertion of C between C and G (anchor C, next ref base G
    -> CCG) or of G between G and C (anchor G, next C -> GGC). Vectorized:
    the inserted base is the native scan's indel_nuc (single-base diff), the
    anchor and next reference base come from the gathered window tensor.
    """
    n = len(table)
    from variantcalling_tpu.featurize import classify_alleles

    alle = classify_alleles(table)
    aux = table.aux
    if aux is not None:
        prefix_ins = (aux.alle["aclass"] & 8).astype(bool)
        ref_len = aux.alle["ref_len"]
    else:
        ref_len = np.fromiter(map(len, table.ref), dtype=np.int64, count=n)
        alt0_len = np.fromiter(
            (len(a) if "," not in a else a.index(",") for a in table.alt), dtype=np.int64, count=n
        )
        cand = alle.is_ins & (alt0_len == ref_len + 1)
        prefix_ins = np.zeros(n, dtype=bool)
        for i in np.nonzero(cand)[0]:
            prefix_ins[i] = table.alt[i].split(",")[0].startswith(table.ref[i])
    # single-base left-anchored insertion; anchor base = ref[-1]. The window
    # is centered on POS (first ref base), so anchor sits at center+ref_len-1
    # and the next reference base right after it.
    cand = alle.is_ins & prefix_ins & (alle.indel_length == 1)
    anchor_idx = np.minimum(center + ref_len - 1, windows.shape[1] - 1)
    next_idx = np.minimum(anchor_idx + 1, windows.shape[1] - 1)
    rows = np.arange(n)
    anchor = windows[rows, anchor_idx].astype(np.int32)
    nxt = windows[rows, next_idx].astype(np.int32)
    ins = alle.indel_nuc  # C=1, G=2
    return cand & (((ins == 1) & (anchor == 1) & (nxt == 2)) | ((ins == 2) & (anchor == 2) & (nxt == 1)))


# Compiled predictors keyed on (model identity, feature order[, flow order]).
# A fresh jax.jit per call would recompile the forest program on every
# pipeline invocation; cached entries hold the model reference so id() stays
# valid for the cache lifetime. Bounded FIFO so a long-lived process scoring
# many models does not accumulate compiled programs forever.
_PREDICTOR_CACHE: dict[tuple, tuple[object, object]] = {}
_PREDICTOR_CACHE_MAX = 8


#: chunk prep/scoring fans out on the IO pool (vctpu-lint VCT010): the
#: eviction loop's pop-next-iter is NOT atomic — two workers inserting
#: concurrently could pop the same key (KeyError) or evict past the cap
_PREDICTOR_CACHE_LOCK = threading.Lock()


def _cache_put(key: tuple, value: tuple) -> None:
    with _PREDICTOR_CACHE_LOCK:
        while len(_PREDICTOR_CACHE) >= _PREDICTOR_CACHE_MAX:
            _PREDICTOR_CACHE.pop(next(iter(_PREDICTOR_CACHE)))
        _PREDICTOR_CACHE[key] = value


def _strategy_token(strategy: str | None) -> tuple:
    """Predictor-cache key component: the pinned strategy (or the live env
    request) PLUS the wide-path knobs — tests flip these between calls,
    and a cached program compiled under the old values must not answer
    for the new."""
    return (strategy or knobs.raw(forest_mod.FOREST_STRATEGY_ENV) or "auto",
            knobs.raw(forest_mod.WIDE_CHUNK_ENV) or "",
            knobs.raw(forest_mod.WIDE_BLOCK_ENV) or "")


def _raw_predictor(model, feature_names: list[str], strategy: str | None = None):
    """-> (program, host_finalize|None).

    ``program`` is jit-safe; ``host_finalize`` (if set) turns its fetched
    output into TREE_SCOREs on the host. FlatForests return canonical-order
    MARGINS from the strategy-resolved device program
    (:func:`forest_mod.make_margin_predictor` — gather walk, scan GEMM,
    wide-contraction GEMM or the pallas wide-block kernel, all bit-identical)
    and finalize through :func:`forest_mod.finalize_margin` — the same
    shared code the native engine uses, so every engine/strategy's score
    bits are identical by construction (sigmoid/exp is not bit-portable
    across XLA and libm). ``strategy`` pins the run-level resolution
    (FilterContext); None reads ``VCTPU_FOREST_STRATEGY``.
    """
    if isinstance(model, FlatForest):
        ordered = forest_mod.with_feature_order(model, feature_names)
        program = forest_mod.make_margin_predictor(
            ordered, len(feature_names), strategy=strategy)
        return program, (lambda m: forest_mod.finalize_margin(m, ordered))
    if isinstance(model, DanModel):
        # GEMM-native family: the fused forward pass IS the score (f32
        # end-to-end, docs/models.md) — no host finalize stage.
        return dan_mod.make_score_predictor(model, feature_names), None
    return (lambda xx: threshold_mod.predict_score(model, xx, feature_names)), None


def _predictor_for(model, feature_names: list[str], strategy: str | None = None,
                   mesh=None):
    key = ("x", id(model), tuple(feature_names), _strategy_token(strategy), mesh)
    hit = _PREDICTOR_CACHE.get(key)
    if hit is not None and hit[0] is model:
        return hit[1]
    program, finalize = _raw_predictor(model, feature_names, strategy=strategy)
    if mesh is not None:
        # data-parallel mesh plan (>1 device): the SAME program body runs
        # per device over its dp shard of the feature matrix — a pure
        # map, margins never cross devices (docs/streaming_executor.md
        # "Mesh-sharded scoring")
        from variantcalling_tpu.parallel import shard_score

        program = shard_score.shard_program(program, mesh, n_data_args=1)
    pair = (jax.jit(program), finalize)
    _cache_put(key, (model, pair))
    return pair


def _fused_program(model, feature_names: list[str], flow_order: str,
                   genome_resident: bool = False, strategy: str | None = None,
                   mesh=None):
    """One jitted device program: windows + host columns -> TREE_SCORE.

    Fuses the window featurization kernels (gc/hmer/motif/cycle-skip) with
    forest inference so only the per-variant score crosses back to the host
    — on TPU the feature tensors never leave HBM. Host columns arrive as a
    TUPLE of 1-D arrays in ``host_names`` order, each in whatever narrow
    dtype the caller chose (uint8 for integral flag/code columns) — the
    f32 feature matrix is assembled on device, so the wire carries 1 byte
    instead of 4 for most columns (the tunnel is the e2e bottleneck).

    ``genome_resident=True``: the first two arguments become the
    HBM-resident global genome and the uint32 PACKED per-variant global
    position — windows are gathered on device, so per-run transfer is
    4 bytes a variant instead of the 41-byte window row.
    """
    from variantcalling_tpu.featurize import (CENTER, DEVICE_FEATURES,
                                              device_feature_dict, windows_from_packed)

    key = ("fused", id(model), tuple(feature_names), flow_order,
           genome_resident, _strategy_token(strategy), mesh)
    hit = _PREDICTOR_CACHE.get(key)
    if hit is not None and hit[0] is model:
        return hit[1]

    # This is the JIT engine's program: featurize + forest inference fused
    # into one device program (engine contract, docs/robustness.md — the
    # native engine short-circuits in fused_featurize_score and never
    # reaches here, so no native split hides inside the "jit" engine).
    # FlatForest programs return margins and `finalize` (shared with the
    # native engine) produces the final score bits on the host.
    predictor, finalize = _raw_predictor(model, feature_names, strategy=strategy)
    host_names = [f for f in feature_names if f not in DEVICE_FEATURES]
    host_idx = {f: i for i, f in enumerate(host_names)}

    def body(windows, host_cols, is_indel, indel_nuc, ref_code, alt_code, is_snp):
        dev = device_feature_dict(windows, is_indel.astype(bool),
                                  indel_nuc.astype(jnp.int32),
                                  ref_code.astype(jnp.int32),
                                  alt_code.astype(jnp.int32),
                                  is_snp.astype(bool),
                                  center=CENTER, flow_order=flow_order)
        cols = [
            dev[f].astype(jnp.float32) if f in dev
            else host_cols[host_idx[f]].astype(jnp.float32)
            for f in feature_names
        ]
        return predictor(jnp.stack(cols, axis=1))

    if genome_resident:
        def fn(genome_blocks, gpos, host_cols, is_indel, indel_nuc,
               ref_code, alt_code, is_snp):
            return body(windows_from_packed(genome_blocks, gpos), host_cols,
                        is_indel, indel_nuc, ref_code, alt_code, is_snp)
    else:
        fn = body

    if mesh is not None:
        # the mesh-sharded layout: the SAME fused body runs per device
        # over its dp shard (genome replicated, every data argument's
        # leading axis sharded) — a pure map with no collectives, so
        # per-row score bits cannot depend on the device count
        from variantcalling_tpu.parallel import shard_score

        fn = shard_score.shard_program(
            fn, mesh, n_data_args=7,
            replicated_leading=1 if genome_resident else 0)

    jitted = (jax.jit(fn), host_names, finalize)
    _cache_put(key, (model, jitted))
    return jitted


def _narrow_column(a: np.ndarray) -> np.ndarray:
    """Cheapest exact wire dtype for a host feature column.

    uint8 when every value is an exact small non-negative integer (flags,
    base codes, interval membership, n_alts), else float32. Exactness is
    checked, not assumed — scores must be bit-identical to the f32 path.
    """
    a = np.asarray(a)
    if a.dtype == np.uint8 or a.dtype == np.bool_:
        return a
    small = a.astype(np.uint8, copy=True) if a.dtype.kind in "iu" else None
    if small is None and a.dtype.kind == "f":
        if not np.isfinite(a).all():  # NaN/inf: the uint8 probe cast is UB
            return a.astype(np.float32, copy=False)
        small = a.astype(np.uint8)
        if not np.array_equal(small.astype(a.dtype), a):
            return a.astype(np.float32, copy=False)
        return small
    if small is not None and np.array_equal(small.astype(a.dtype), a):
        return small
    return a.astype(np.float32, copy=False)


def _fused_native_chunk_score(ordered, hf, fo: np.ndarray, table,
                              fasta) -> np.ndarray | None:
    """The single-call native chunk body (ROADMAP item 4): contig runs +
    encoded contigs + host columns + forest go across the ctypes boundary
    ONCE per chunk (``native.fused_chunk_score``) and canonical-order
    margins come back — window gather, featurize, matrix fill and the
    forest walk all happen tile-at-a-time in C++, with no intermediate
    feature columns and no per-call Python between them. Margins are
    bit-identical to the unfused reference path below (shared C++ row
    featurize / tile fill / walk; locked by the parity matrix in
    tests/unit/test_fused_native.py). Returns finalized scores, or None
    when this chunk cannot take the fused path (unsorted chunk, no
    native library) — the caller falls through to the reference path.
    """
    from variantcalling_tpu import native
    from variantcalling_tpu.featurize import (CENTER, DEVICE_FEATURES,
                                              _contig_runs)

    n = len(table)
    codes, uniques, bounds = _contig_runs(table, n)
    if bounds is None:  # unsorted chunk: reference path masks per contig
        return None
    empty = np.empty(0, dtype=np.uint8)
    seqs = [fasta.fetch_encoded(c) if c in fasta.references else empty
            for c in uniques]
    dev_cols = np.asarray(
        [hf.names.index(k) if k in hf.names else -1 for k in DEVICE_FEATURES],
        dtype=np.int32)
    cols = [None if f in DEVICE_FEATURES else np.asarray(hf.cols[f])
            for f in hf.names]
    alle = hf.alle
    margin = native.fused_chunk_score(
        seqs, bounds, table.pos - 1, CENTER,
        alle.is_indel, alle.indel_nuc, alle.ref_code, alle.alt_code,
        alle.is_snp, fo, cols, dev_cols,
        ordered.feature, ordered.threshold, ordered.left, ordered.right,
        ordered.value, ordered.default_left, ordered.max_depth, "sum", 0.0)
    if margin is None:
        return None
    return forest_mod.finalize_margin(margin, ordered)


def _native_cpu_featurize_score(model, hf, flow_order: str, table, fasta) -> np.ndarray | None:
    """All-native CPU hot path: numpy window gather + C++ featurize + C++
    forest walk; returns scores or None when the native engine cannot
    serve this batch.

    Engine contract (docs/robustness.md): the CALLER decides what None
    means. When the run's resolved engine is ``native``, None raises
    :class:`EngineError` — the pre-contract behavior of silently falling
    back to the jitted path made output bytes depend on machine load
    (round-5 VERDICT Weak #1) and is forbidden."""
    from variantcalling_tpu import native
    from variantcalling_tpu.featurize import CENTER, DEVICE_FEATURES, gather_windows
    from variantcalling_tpu.ops.features import A, C, G, T

    ordered = forest_mod.with_feature_order(model, hf.names)
    if not native.available() or ordered.aggregation not in ("mean", "logit_sum"):
        return None
    if hf.windows is None and (table is None or fasta is None):
        return None
    alle = hf.alle
    fo = np.asarray([{"A": A, "C": C, "G": G, "T": T}[c] for c in flow_order],
                    dtype=np.int32)
    if hf.windows is None and knobs.get_bool("VCTPU_NATIVE_FUSED"):
        # the fused per-chunk entry: ONE native call for the whole
        # parse-output -> featurize -> score body. The unfused path
        # below stays as the byte-parity reference (VCTPU_NATIVE_FUSED=0)
        score = _fused_native_chunk_score(ordered, hf, fo, table, fasta)
        if score is not None:
            forest_mod.last_strategy = "native-cpp"  # vctpu-lint: disable=VCT010 — run-scoped diagnostic; GIL-atomic store, every concurrent chunk writes the same value
            return score
    dev = None
    if hf.windows is None:
        # fused gather+featurize: windows stream out of the encoded contig
        # without ever materializing the (N, 41) tensor
        from variantcalling_tpu.featurize import featurize_gather_fused

        dev = featurize_gather_fused(table, fasta, alle, fo)
    if dev is None:
        windows = hf.windows if hf.windows is not None else gather_windows(table, fasta)
        dev = native.featurize_windows(windows, CENTER, alle.is_indel, alle.indel_nuc,
                                       alle.ref_code, alle.alt_code, alle.is_snp, fo)
    if dev is None:
        return None
    raw = [np.asarray(dev[f] if f in dev else hf.cols[f]) for f in hf.names]
    # fused column->tile->walk first: no (n, f) matrix ever materializes
    cf = forest_mod.native_cols_predictor(ordered)
    score = cf(raw) if cf is not None else None
    if score is None:
        nf = forest_mod.native_host_predictor(ordered, strict=True)
        if nf is None:
            return None
        x = native.build_matrix(raw)
        if x is None:  # unsupported column dtype: numpy assembly
            x = np.stack([c.astype(np.float32, copy=False) for c in raw], axis=1)
        score = nf(x)
    # no XLA program exists on this path — record that for perf evidence
    # (bench distinguishes real jit compile from plain warmup by this)
    forest_mod.last_strategy = "native-cpp"  # vctpu-lint: disable=VCT010 — run-scoped diagnostic; GIL-atomic store, every concurrent chunk writes the same value
    return score


class _FusedInputs:
    """One chunk's prepared inputs for the fused featurize+score program —
    the unit :func:`_dispatch_fused` packs into device megabatches
    (parallel/shard_score.py). ``program`` is the cached
    ``(_fused_program)`` triple; chunks sharing it concatenate into one
    megabatch, chunks that resolved a different layout dispatch alone."""

    __slots__ = ("n", "program", "genome", "gpos", "gpos_fill", "windows",
                 "host_cols", "alle", "model")

    def __init__(self, n, program, genome, gpos, gpos_fill, windows,
                 host_cols, alle, model):
        self.n = n
        self.program = program
        self.genome = genome
        self.gpos = gpos
        self.gpos_fill = gpos_fill
        self.windows = windows
        self.host_cols = host_cols
        self.alle = alle
        self.model = model


def _prepare_fused_inputs(model, hf, flow_order: str,
                          table: VariantTable | None = None,
                          fasta: FastaReader | None = None,
                          strategy: str | None = None,
                          plan=None) -> _FusedInputs:
    """Host half of the fused scoring path for ONE chunk: window/genome
    layout decision, program build (strategy + mesh pinned), narrowed
    host columns.

    With ``table``+``fasta`` and no precomputed host windows, the
    device-resident-genome path runs: the encoded genome lives in HBM
    (featurize.device_genome, replicated over the run's scoring mesh)
    and windows are gathered inside the fused program from 4-byte PACKED
    uint32 global positions. Genomes whose positions cannot pack into 4
    bytes (> ~4 Gbp incl. N gaps) fall back to the host window gather —
    checked from contig lengths before any encode/upload is paid.
    """
    from variantcalling_tpu.parallel import shard_score

    plan = plan or shard_score.resolve_plan("jit")
    mesh = shard_score.mesh_for(plan)
    windows = hf.windows
    genome = gpos_all = None
    gpos_fill = 0
    genome_resident = windows is None and table is not None and fasta is not None
    if genome_resident:
        from variantcalling_tpu.featurize import (device_genome, gather_windows,
                                                  genome_packable,
                                                  globalize_positions,
                                                  pack_global_positions,
                                                  packed_position_fill)

        if not genome_packable(fasta):
            # positions won't fit 4-byte packing (> ~4 Gbp incl. gaps):
            # host window gather, without paying the genome upload
            genome_resident = False
            windows = gather_windows(table, fasta)
        else:
            # replicate the genome across the run mesh so chunk dispatches
            # never reshard the multi-GB array (a 1-device plan falls
            # through to the process-default policy); the helper keeps
            # the cache key identical across every consumer
            from variantcalling_tpu.featurize import standard_genome_sharding

            genome = device_genome(
                fasta, sharding=standard_genome_sharding(mesh))
            blk_all, off_all = globalize_positions(table, genome)
            gpos_all = pack_global_positions(blk_all, off_all, genome)
            if gpos_all is None:  # safety net: packable() and the packer disagree
                genome_resident = False
                windows = gather_windows(table, fasta)
            else:
                gpos_fill = packed_position_fill(genome)

    program = _fused_program(model, hf.names, flow_order,
                             genome_resident=genome_resident,
                             strategy=strategy, mesh=mesh)
    host_cols = tuple(_narrow_column(hf.cols[f]) for f in program[1])
    n = len(table) if table is not None else len(windows)
    return _FusedInputs(n, program, genome, gpos_all, gpos_fill, windows,
                        host_cols, hf.alle, model)


def _dispatch_fused(inputs: list[_FusedInputs], plan) -> np.ndarray:
    """Score one or more prepared chunks as padded device megabatches;
    returns the PACKED ``(sum(n),)`` score vector in chunk order (callers
    split per chunk with ``shard_score.unpack_scores``).

    Every input must share the same compiled program (the caller groups
    by ``program`` identity). The megabatch is cut into power-of-two
    buckets rounded up to a dp multiple — ``shard_map`` requires
    dp-divisible shapes and distinct batch sizes must reuse compiled
    programs instead of retracing — and padding rows are dropped on
    unpack. Scoring is row-local, so the packed scores are bit-identical
    to per-chunk dispatch at any device count (the mesh parity matrix in
    tests/unit/test_shard_score.py locks this).
    """
    from variantcalling_tpu.featurize import _bucket
    from variantcalling_tpu.parallel import shard_score
    from variantcalling_tpu.parallel.mesh import data_sharding

    first = inputs[0]
    fn, _host_names, finalize = first.program
    mesh = shard_score.mesh_for(plan)
    n_dev = plan.devices
    shard2 = data_sharding(mesh, 2) if mesh is not None else None
    chunk_size = max(CHUNK, n_dev) - (CHUNK % n_dev if n_dev > 1 else 0)

    def cat(arrs):
        return np.asarray(arrs[0]) if len(arrs) == 1 else \
            np.concatenate([np.asarray(a) for a in arrs])

    genome_resident = first.gpos is not None
    genome = first.genome
    gpos_fill = first.gpos_fill
    if genome_resident:
        gpos_all, windows = cat([i.gpos for i in inputs]), None
    else:
        gpos_all, windows = None, cat([i.windows for i in inputs])
    host_cols = tuple(cat([i.host_cols[k] for i in inputs])
                      for k in range(len(first.host_cols)))
    is_indel = cat([i.alle.is_indel for i in inputs])
    indel_nuc = cat([i.alle.indel_nuc for i in inputs])
    ref_code = cat([i.alle.ref_code for i in inputs])
    alt_code = cat([i.alle.alt_code for i in inputs])
    is_snp = cat([i.alle.is_snp for i in inputs])

    n = sum(i.n for i in inputs)
    out = np.empty(n, dtype=np.float32)
    pending: list[tuple[int, int, object]] = []

    # CPU: the jit program returns canonical-order margins; the SHARED
    # host finalization (forest.finalize_margin) produces the score bits
    # both engines agree on. Accelerators return device-final scores.
    def finish(res, k):
        arr = np.asarray(res)[:k]
        return finalize(arr) if finalize is not None else arr

    for lo in range(0, n, chunk_size):
        hi = min(lo + chunk_size, n)
        # power-of-two bucket (rounded up to a dp multiple) so distinct batch
        # sizes reuse the same compiled program instead of retracing
        target = min(chunk_size, -(-_bucket(hi - lo) // n_dev) * n_dev)
        pad = target - (hi - lo)

        def prep(a, fill=0):
            c = np.asarray(a)[lo:hi]
            if pad:
                c = np.pad(c, [(0, pad)] + [(0, 0)] * (c.ndim - 1), constant_values=fill)
            if shard2 is not None:
                return jax.device_put(c, shard2 if c.ndim == 2 else data_sharding(mesh, 1))
            return jnp.asarray(c)

        # async dispatch overlaps chunk i+1's upload with chunk i's compute;
        # the bounded in-flight window keeps device residency at O(chunk)
        # (plus the resident genome) instead of the whole dataset
        common = (
            tuple(prep(c) for c in host_cols),
            prep(is_indel),
            prep(indel_nuc, fill=4),
            prep(ref_code, fill=4),
            prep(alt_code, fill=4),
            prep(is_snp),
        )
        if genome_resident:
            # padding positions sit past the genome end -> all-N windows
            call_args = (genome.blocks, prep(gpos_all, fill=gpos_fill), *common)
        else:
            call_args = (prep(windows, fill=4), *common)
        pending.append((lo, hi, fn(*call_args)))
        last_call = (call_args, target)
        while len(pending) > 2:
            plo, phi, res = pending.pop(0)
            out[plo:phi] = finish(res, phi - plo)
    for lo, hi, res in pending:
        out[lo:hi] = finish(res, hi - lo)
    if n and obs.active() and isinstance(first.model, FlatForest):
        # runtime MFU/roofline attribution (obs v2): the XLA compiler's
        # own FLOP count for the compiled fused program that scored this
        # run, per resolved strategy — replaces bench.py's analytic
        # projection with a measurement. Post-loop so the lower+compile
        # walk never sits in the chunk cadence; shapes only are read.
        from variantcalling_tpu.obs import profile as profile_mod

        profile_mod.record_scoring_cost(
            forest_mod.last_strategy, fn, last_call[0], last_call[1])
    return out


def fused_featurize_score(model, hf, flow_order: str, table: VariantTable | None = None,
                          fasta: FastaReader | None = None,
                          engine: engine_mod.EngineDecision | None = None,
                          strategy: str | None = None,
                          plan=None) -> np.ndarray:
    """Chunked fused featurize+score over a HostFeatures batch; returns scores.

    The scoring engine is the RUN-LEVEL decision from
    :mod:`variantcalling_tpu.engine` (``VCTPU_ENGINE``): ``native`` runs
    the whole hot path in the C++ engine and RAISES if it cannot
    (never a silent jit fallback — output bytes must not depend on which
    engine happened to load); ``jit`` never touches the native scorer.
    ``plan`` pins the run-level scoring-mesh decision
    (``FilterContext.mesh_plan``); None resolves per call — with >1
    devices the fused program runs inside a ``shard_map`` over the mesh
    dp axis (parallel/shard_score.py), byte-identical to single-device.
    """
    eng = engine or engine_mod.resolve()
    # native engine: window gather -> featurize -> forest walk in C++ —
    # one pass per 41-byte window row, ~10x XLA:CPU's multi-kernel
    # lowering, byte-parity with the jit engine locked by
    # tests/unit/test_engine_contract.py. Meshes and accelerators resolve
    # to jit and keep the fused on-device program below.
    if isinstance(model, FlatForest) and eng.name == "native":
        score = _native_cpu_featurize_score(model, hf, flow_order, table, fasta)
        if score is None:
            raise EngineError(
                "the resolved scoring engine 'native' could not serve this "
                "batch (native library unloadable mid-run, unsupported "
                "aggregation, or windows unavailable). Refusing to silently "
                "fall back to the jit engine — rerun with VCTPU_ENGINE=jit "
                "to opt into the jitted scorer. See docs/robustness.md.")
        return score

    from variantcalling_tpu.parallel import shard_score

    plan = plan or shard_score.resolve_plan(eng.name)
    fi = _prepare_fused_inputs(model, hf, flow_order, table=table, fasta=fasta,
                               strategy=strategy, plan=plan)
    return _dispatch_fused([fi], plan)


def score_variants(model, x: np.ndarray, feature_names: list[str],
                   engine: engine_mod.EngineDecision | None = None,
                   strategy: str | None = None, plan=None) -> np.ndarray:
    """Jitted chunked scoring, sharded over the mesh dp axis; returns TREE_SCORE per row.

    Multi-device (a >1-device mesh plan): the feature chunk is device_put
    with a dp sharding and the scoring program runs in a ``shard_map``
    over the variants axis (model arrays replicated); a single-device
    plan degrades to plain jit. The scoring engine follows the run-level
    contract (``VCTPU_ENGINE``): ``native`` runs the C++ walk or raises —
    never a silent jit fallback.
    """
    if not isinstance(model, _FUSED_MODEL_TYPES):
        # raw sklearn estimator that escaped conversion
        return np.asarray(model.predict_proba(x)[:, 1])
    eng = engine or engine_mod.resolve()
    if isinstance(model, FlatForest) and eng.name == "native":
        nf = forest_mod.native_host_predictor(
            forest_mod.with_feature_order(model, feature_names), strict=True)
        if nf is None:
            raise EngineError(
                "the resolved scoring engine 'native' could not serve this "
                "run (native library unloadable mid-run or unsupported "
                "aggregation). Refusing to silently fall back to the jit "
                "engine; rerun with VCTPU_ENGINE=jit. See docs/robustness.md.")
        return nf(np.ascontiguousarray(x, dtype=np.float32))  # C++ walk

    from variantcalling_tpu.parallel import shard_score
    from variantcalling_tpu.parallel.mesh import data_sharding

    plan = plan or shard_score.resolve_plan(eng.name)
    mesh = shard_score.mesh_for(plan)
    fn, finalize = _predictor_for(model, feature_names, strategy=strategy,
                                  mesh=mesh)
    n_dev = plan.devices
    sharding = data_sharding(mesh, 2) if mesh is not None else None
    chunk_size = max(CHUNK, n_dev) - (CHUNK % n_dev if n_dev > 1 else 0)

    n = x.shape[0]
    out = np.empty(n, dtype=np.float32)
    for lo in range(0, n, chunk_size):
        hi = min(lo + chunk_size, n)
        chunk = x[lo:hi]
        if hi - lo < chunk_size and (n > chunk_size or n_dev > 1):
            # pad the tail chunk: steady-state shape (one compile) + dp divisibility
            target = chunk_size if n > chunk_size else ((hi - lo + n_dev - 1) // n_dev) * n_dev
            chunk = np.pad(chunk, ((0, target - (hi - lo)), (0, 0)))
        dev_chunk = jax.device_put(chunk, sharding) if sharding is not None else jnp.asarray(chunk)
        res = np.asarray(fn(dev_chunk))[: hi - lo]
        out[lo:hi] = finalize(res) if finalize is not None else res
    return out


class FilterContext:
    """Chunk-invariant scoring state for the filter pipeline.

    Built once per run (model wiring, blacklist, hpol runs file, interval
    sets), then :meth:`score_table` is applied to the whole table (serial
    path) or to each streamed chunk (streaming executor). Every product is
    row-local by construction — a variant's TREE_SCORE and FILTER depend
    only on that variant's record plus this shared state — which is what
    makes chunked scoring bit-identical to whole-table scoring.
    """

    def __init__(
        self,
        model,
        fasta: FastaReader,
        runs_file: str | None = None,
        hpol_length: int = 10,
        hpol_dist: int = 10,
        blacklist: tuple[np.ndarray, np.ndarray] | None = None,
        blacklist_cg_insertions: bool = False,
        annotate_intervals: dict[str, bedio.IntervalSet] | None = None,
        flow_order: str = "TGCA",
        is_mutect: bool = False,
        engine: engine_mod.EngineDecision | None = None,
        mesh_plan=None,
        rank_plan=None,
    ):
        # the run-level scoring engine (VCTPU_ENGINE): resolved once and
        # held here so every chunk of a run scores on the SAME engine.
        # Only FlatForests have a native scorer — an EXPLICIT native
        # request with another model type fails loudly, while an
        # auto-resolved native downgrades to jit HERE (once, before any
        # scoring) so the recorded engine matches what actually scores.
        eng = engine or engine_mod.resolve()
        if eng.name == "native" and not isinstance(model, FlatForest):
            if eng.requested == "native":
                raise EngineError(
                    "the native scoring engine was explicitly required but "
                    f"only FlatForest models have a native scorer (got "
                    f"{type(model).__name__}) — rerun with VCTPU_ENGINE=jit "
                    "or auto. See docs/robustness.md.")
            from dataclasses import replace

            eng = replace(eng, name="jit",
                          reason=f"{type(model).__name__} has no native scorer")
        self.engine = eng
        # per-RUN resolution event: engine.resolve() caches per process,
        # so emitting here (where the run pins its engine) is the only way
        # every run's stream records the decision that scored it
        if obs.active():
            obs.event("resolve", "engine", value=eng.name,
                      requested=eng.requested, reason=eng.reason)
        # the run-level FOREST STRATEGY (VCTPU_FOREST_STRATEGY): resolved
        # once here, recorded next to ##vctpu_engine= in the output header
        # and in the chunk-journal resume identity, then PINNED into every
        # scoring call — the predictor build honors it or raises
        # (EngineError, exit 2), so the recorded name can never silently
        # diverge from the program that scored. The native engine's C++
        # walk has no XLA strategy; it records "native-cpp" — but a
        # MALFORMED env value (strategy name, wide chunk/block knobs) is a
        # configuration error on every engine (same rule as a bad
        # VCTPU_ENGINE), so validate them all up front.
        forest_mod.validate_strategy_env()
        if eng.name == "native":
            self.forest_strategy = "native-cpp"
            if obs.active():
                obs.event("resolve", "forest_strategy", value="native-cpp",
                          requested="-", reason="native engine: C++ walk, no "
                          "XLA strategy")
        elif isinstance(model, FlatForest):
            self.forest_strategy = forest_mod.resolve_strategy(model)
        else:
            self.forest_strategy = "jit"  # threshold/dan/sklearn program
        # the run-level MODEL FAMILY (VCTPU_MODEL_FAMILY): resolved once
        # here under the exact contract the engine/strategy obey — auto
        # resolves to the loaded model's family; an EXPLICIT request for
        # a family the model file didn't serve fails loudly (EngineError,
        # exit 2) instead of silently scoring with the other family. The
        # resolved family is recorded as ##vctpu_model_family= when it is
        # not the forest default, pinned into the journal resume identity
        # and the chunk-cache fingerprint (io/identity.py) together with
        # a DAN weights digest, and emitted as a resolve obs event.
        fam_req = knobs.get("VCTPU_MODEL_FAMILY")
        fam = registry_mod.family_of(model)
        if fam_req != "auto" and fam_req != fam:
            raise EngineError(
                f"VCTPU_MODEL_FAMILY={fam_req} was explicitly requested but "
                f"the loaded model is family {fam!r} "
                f"({type(model).__name__}) — point --model_file/--model_name "
                f"at a {fam_req} model or rerun with VCTPU_MODEL_FAMILY="
                "auto. See docs/models.md.")
        self.model_family = fam
        self.model_digest = (dan_mod.weights_digest(model)
                             if isinstance(model, DanModel) else None)
        if obs.active():
            obs.event("resolve", "model_family", value=fam,
                      requested=fam_req,
                      reason=f"model type {type(model).__name__}")
        # the run-level SCORING MESH (VCTPU_MESH_DEVICES): resolved once
        # here next to the engine and strategy, recorded as
        # ##vctpu_mesh= in the output header when >1 device and pinned
        # into the chunk-journal resume identity — then every scoring
        # dispatch of the run shards over exactly this mesh
        # (parallel/shard_score.py). Output bytes are identical at every
        # device count by construction (pure data-parallel map; parity
        # matrix in tests/unit/test_shard_score.py), so the header line
        # is the only byte that names the layout.
        # ``mesh_plan`` pins an externally-decided plan — the recovery
        # ladder's dp=1 restart after device OOM (run_streaming) is the
        # one caller; everything else resolves here as before
        from variantcalling_tpu.parallel import shard_score

        self.mesh_plan = mesh_plan if mesh_plan is not None \
            else shard_score.resolve_plan(eng.name)
        shard_score.log_plan(self.mesh_plan)
        # the run-level RANK plan (VCTPU_RANK/VCTPU_NUM_PROCESSES or an
        # initialized jax.distributed runtime): resolved once here next
        # to the mesh plan, recorded as ##vctpu_ranks= when >1 rank and
        # pinned into the chunk-journal resume identity — the scale-out
        # layout every rank of a pod run agrees on (docs/scaleout.md).
        # ``rank_plan`` pins an externally-resolved plan (the scale-out
        # driver passes the one it partitioned by).
        from variantcalling_tpu.parallel import rank_plan as rank_plan_mod

        self.rank_plan = rank_plan if rank_plan is not None \
            else rank_plan_mod.resolve()
        rank_plan_mod.log_plan(self.rank_plan)
        self.model = model
        self.fasta = fasta
        self.hpol_length = hpol_length
        self.hpol_dist = hpol_dist
        self.blacklist = blacklist
        self.blacklist_cg_insertions = blacklist_cg_insertions
        self.annotate_intervals = annotate_intervals
        self.flow_order = flow_order
        self.is_mutect = is_mutect
        # xgboost models define missing-value semantics on NaN (default_left
        # routing): zero-filling absent fields would walk the wrong branch
        self.keep_nan = getattr(model, "default_left", None) is not None
        self.extra_info = ["TLOD"] if is_mutect else []
        # hpol runs load once (length-filtered); globalization waits for the
        # first table so contig lengths come from its header exactly as the
        # single-shot path did
        self._runs: bedio.IntervalSet | None = None
        if runs_file:
            runs = bedio.read_bed(runs_file)
            keep = (runs.end - runs.start) >= hpol_length
            self._runs = bedio.IntervalSet(runs.chrom[keep], runs.start[keep], runs.end[keep])
        self._runs_global: tuple | None = None

    def _hpol_near(self, table: VariantTable) -> np.ndarray | None:
        if self._runs is None or not len(self._runs):
            return None
        if self._runs_global is None:
            contig_lengths = table.header.contig_lengths or {
                c: self.fasta.get_reference_length(c) for c in self.fasta.references
            }
            coords = iops.GenomeCoords(contig_lengths)
            self._runs_global = (coords, *coords.globalize_intervals(self._runs))
        coords, gs, ge = self._runs_global
        gpos = coords.globalize(np.asarray(table.chrom), table.pos - 1)
        return iops.distance_to_nearest(gpos, gs, ge) <= self.hpol_dist

    @property
    def mesh(self):
        """The run's scoring Mesh (None for a single-device plan)."""
        from variantcalling_tpu.parallel import shard_score

        return shard_score.mesh_for(self.mesh_plan)

    def _pinned_strategy(self) -> str | None:
        # pin the run-level strategy into the predictor build (registry
        # names only — "native-cpp"/"jit" rides the engine decision)
        return self.forest_strategy \
            if self.forest_strategy in forest_mod.FOREST_STRATEGIES else None

    def host_features(self, table: VariantTable):
        """Host featurization for one table/chunk — the CPU half of
        scoring, shared by :meth:`score_table` and the mesh megabatch
        path (it fans out on the IO pool in the streaming executor)."""
        model, fasta = self.model, self.fasta
        # host windows are needed only by the cg-insertion check and the
        # raw-sklearn fallback; the fused path gathers windows from the
        # device-resident genome instead — unless the job is too small to
        # justify the whole-genome HBM upload (_genome_resident_worthwhile)
        from variantcalling_tpu.featurize import (_genome_resident_worthwhile,
                                                  standard_genome_sharding)

        mesh = self.mesh
        genome_sharding = standard_genome_sharding(mesh)
        needs_host_windows = (
            self.blacklist_cg_insertions
            or not isinstance(model, _FUSED_MODEL_TYPES)
            or not _genome_resident_worthwhile(table, fasta, sharding=genome_sharding)
        )
        hf = host_featurize(table, fasta, annotate_intervals=self.annotate_intervals,
                            extra_info_fields=self.extra_info,
                            compute_windows=needs_host_windows, keep_nan=self.keep_nan)
        if self.is_mutect and "TLOD" in hf.cols:
            hf.cols["tlod"] = hf.cols.pop("TLOD")
            hf.names[hf.names.index("TLOD")] = "tlod"
        return hf

    def _score_hf(self, table: VariantTable, hf) -> np.ndarray:
        model, fasta = self.model, self.fasta
        strat = self._pinned_strategy()
        if isinstance(model, _FUSED_MODEL_TYPES):
            # fused featurize+score: window features and the model program
            # (forest walk or DAN forward) run as one device program, only
            # TREE_SCORE returns to the host
            return fused_featurize_score(model, hf, self.flow_order, table=table,
                                         fasta=fasta, engine=self.engine,
                                         strategy=strat, plan=self.mesh_plan)
        # raw sklearn estimator: materialize the matrix from the same hf
        from variantcalling_tpu.featurize import materialize_features

        fs = materialize_features(hf, flow_order=self.flow_order)
        return score_variants(model, fs.matrix(), fs.feature_names,
                              engine=self.engine, strategy=strat,
                              plan=self.mesh_plan)

    def score_table(self, table: VariantTable) -> tuple[np.ndarray, np.ndarray]:
        """Score one table (whole callset or one streamed chunk); returns
        (tree_score float array, FILTER FactorizedColumn)."""
        hf = self.host_features(table)
        score = self._score_hf(table, hf)
        return score, self.assemble_filters(table, score, hf)

    def score_packed(self, pairs) -> list[tuple]:
        """Score a GROUP of consecutive chunks as one packed megabatch —
        the mesh-sharded streaming path (shard_score.megabatch_stream).

        ``pairs`` is ``[(table, host_features), ...]`` in canonical chunk
        order. Chunks whose prepared inputs share one compiled program
        concatenate into a single padded, dp-sharded dispatch; scores
        unpack back per chunk by slicing (scoring is row-local, so the
        packed bits equal per-chunk dispatch bits — the streaming==serial
        invariant, now also across packing). Chunks that resolved a
        different program layout (e.g. a host-window tail next to
        genome-resident neighbors) score alone, preserving order.
        Returns ``[(table, score, filters), ...]``.
        """
        model = self.model
        if self.mesh_plan.devices <= 1 or self.engine.name == "native" \
                or not isinstance(model, _FUSED_MODEL_TYPES):
            out = []
            for table, hf in pairs:
                score = self._score_hf(table, hf)
                out.append((table, score, self.assemble_filters(table, score, hf)))
            return out
        from variantcalling_tpu.parallel import shard_score

        strat = self._pinned_strategy()
        prepped = [
            (table, hf,
             _prepare_fused_inputs(model, hf, self.flow_order, table=table,
                                   fasta=self.fasta, strategy=strat,
                                   plan=self.mesh_plan))
            for table, hf in pairs]
        out = []
        run: list = []  # consecutive chunks sharing one compiled program

        def flush_run():
            if not run:
                return
            scores = _dispatch_fused([fi for _, _, fi in run], self.mesh_plan)
            for (table, hf, fi), score in zip(
                    run, shard_score.unpack_scores(
                        scores, [fi.n for _, _, fi in run])):
                out.append((table, score,
                            self.assemble_filters(table, score, hf)))
            run.clear()

        for item in prepped:
            if run and item[2].program is not run[-1][2].program:
                flush_run()
            run.append(item)
        flush_run()
        return out

    def assemble_filters(self, table: VariantTable, score: np.ndarray,
                         hf) -> FactorizedColumn:
        """FILTER assembly from a table's scores — row-local, shared by
        the per-chunk and packed-megabatch paths."""
        model = self.model
        pass_thr = getattr(model, "pass_threshold", 0.5)
        n = len(table)
        low = score < pass_thr

        cohort_fp = np.zeros(n, dtype=bool)
        blacklist = self.blacklist
        if blacklist is not None and len(blacklist[0]):
            # vectorized (chrom, pos) join: map chroms to small ints, pack into
            # one int64 key, sorted-membership — no per-record Python on the 5M path
            chroms = {c: i for i, c in enumerate(dict.fromkeys(np.concatenate([blacklist[0], table.chrom]).tolist()))}
            cidx_bl = np.fromiter((chroms[c] for c in blacklist[0]), dtype=np.int64, count=len(blacklist[0]))
            cidx_tb = np.fromiter((chroms[c] for c in table.chrom), dtype=np.int64, count=n)
            key_bl = np.sort((cidx_bl << 40) | blacklist[1].astype(np.int64))
            key_tb = (cidx_tb << 40) | table.pos.astype(np.int64)
            loc = np.searchsorted(key_bl, key_tb)
            loc = np.minimum(loc, len(key_bl) - 1)
            cohort_fp = key_bl[loc] == key_tb
        if self.blacklist_cg_insertions and hf.windows is not None:
            from variantcalling_tpu.featurize import CENTER

            cohort_fp |= _is_cg_insertion(table, hf.windows, CENTER)

        near = self._hpol_near(table)
        hpol_near = near if near is not None else np.zeros(n, dtype=bool)

        # FILTER assembly as integer codes over the 6 possible values (no
        # per-record Python and no factorize on the 5M writeback path):
        # COHORT_FP beats LOW_SCORE; HPOL_RUN appends with ';'
        base_idx = np.where(cohort_fp, 1, np.where(low, 2, 0)).astype(np.int32)
        return FactorizedColumn(
            base_idx + 3 * hpol_near,
            [PASS, COHORT_FP, LOW_SCORE, HPOL_RUN,
             f"{COHORT_FP};{HPOL_RUN}", f"{LOW_SCORE};{HPOL_RUN}"],
        )


def filter_variants(
    table: VariantTable,
    model,
    fasta: FastaReader,
    runs_file: str | None = None,
    hpol_length: int = 10,
    hpol_dist: int = 10,
    blacklist: tuple[np.ndarray, np.ndarray] | None = None,
    blacklist_cg_insertions: bool = False,
    annotate_intervals: dict[str, bedio.IntervalSet] | None = None,
    flow_order: str = "TGCA",
    is_mutect: bool = False,
    engine: engine_mod.EngineDecision | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Core: returns (tree_score float array, new FILTER object array)."""
    ctx = FilterContext(
        model, fasta, runs_file=runs_file, hpol_length=hpol_length,
        hpol_dist=hpol_dist, blacklist=blacklist,
        blacklist_cg_insertions=blacklist_cg_insertions,
        annotate_intervals=annotate_intervals, flow_order=flow_order,
        is_mutect=is_mutect, engine=engine,
    )
    return ctx.score_table(table)


def _replace_or_append_meta(header, prefix: str, line: str) -> None:
    """A stale line inherited from a previously-filtered input must not
    mislabel THIS run: replace in place (position preserved), append when
    absent."""
    replaced = False
    for i, old in enumerate(header.lines):
        if old.startswith(prefix):
            header.lines[i] = line
            replaced = True
    if not replaced:
        header.add_meta_line(line)


def _ensure_output_header(header, engine: engine_mod.EngineDecision | None = None,
                          strategy: str | None = None,
                          mesh_plan=None, rank_plan=None,
                          model_family: str | None = None) -> None:
    """The filter pipeline's header additions — ONE place so the serial and
    streaming writers emit identical header bytes. Records the scoring
    engine (``##vctpu_engine=...``), the resolved forest strategy
    (``##vctpu_forest_strategy=...``), the model family when it is not
    the forest default (``##vctpu_model_family=dan``) and — for
    >1-device runs — the scoring-mesh layout (``##vctpu_mesh=dp=N``) so
    every output file names the full scoring configuration that produced
    it (engine contract, docs/robustness.md)."""
    header.ensure_filter(LOW_SCORE, "Model score below threshold")
    header.ensure_filter(COHORT_FP, "Blacklisted cohort false-positive locus")
    header.ensure_filter(HPOL_RUN, "Variant close to long homopolymer run")
    header.ensure_info("TREE_SCORE", "1", "Float", "Filtering model confidence score")
    eng = engine or engine_mod.resolve()
    _replace_or_append_meta(header, f"##{engine_mod.HEADER_KEY}=",
                            eng.header_line())
    if strategy is not None:
        key = forest_mod.STRATEGY_HEADER_KEY
        _replace_or_append_meta(header, f"##{key}=", f"##{key}={strategy}")
    # model-family provenance: non-forest families record the family that
    # scored; forest runs emit NO line (and strip a stale one inherited
    # from a re-filtered input) so pre-existing forest outputs stay
    # byte-identical to every prior release
    fam_prefix = f"##{dan_mod.FAMILY_HEADER_KEY}="
    if model_family is not None and model_family != "forest":
        _replace_or_append_meta(header, fam_prefix,
                                f"{fam_prefix}{model_family}")
    else:
        header.lines[:] = [ln for ln in header.lines
                           if not ln.startswith(fam_prefix)]
    # mesh provenance: >1-device runs record the dp layout; single-device
    # runs emit NO line (and strip a stale one inherited from a
    # re-filtered input) — record bytes are identical at every device
    # count, so the header line is the only byte naming the layout
    from variantcalling_tpu.parallel.shard_score import MESH_HEADER_KEY

    mesh_prefix = f"##{MESH_HEADER_KEY}="
    if mesh_plan is not None and mesh_plan.devices > 1:
        _replace_or_append_meta(header, mesh_prefix, mesh_plan.header_line())
    else:
        header.lines[:] = [ln for ln in header.lines
                           if not ln.startswith(mesh_prefix)]
    # rank provenance (docs/scaleout.md): >1-rank runs record the pod
    # layout; single-rank runs emit NO line (and strip a stale one from
    # a re-filtered input) — record bytes are rank-count-invariant, so
    # this line is the only byte naming the scale-out layout. The line
    # names only n (never the rank id): every rank's segment must emit
    # IDENTICAL header bytes for the seam commit's cross-rank check.
    from variantcalling_tpu.parallel.rank_plan import RANKS_HEADER_KEY

    ranks_prefix = f"##{RANKS_HEADER_KEY}="
    if rank_plan is not None and rank_plan.ranks > 1:
        _replace_or_append_meta(header, ranks_prefix,
                                rank_plan.header_line())
    else:
        header.lines[:] = [ln for ln in header.lines
                           if not ln.startswith(ranks_prefix)]
    # explicitly-set scoring knobs (wide chunk/block, pallas opt-out):
    # full provenance next to the engine/strategy lines. Execution-only
    # knobs are excluded so streaming/serial/resumed runs stay
    # byte-identical (knobs.header_line contract). With nothing set (the
    # common case) no line is emitted — and a stale line inherited from a
    # re-filtered input is REMOVED, so it cannot mislabel this run.
    knob_line = knobs.header_line()
    knob_prefix = f"##{knobs.HEADER_KEY}="
    if knob_line != knob_prefix:
        _replace_or_append_meta(header, knob_prefix, knob_line)
    else:
        header.lines[:] = [ln for ln in header.lines
                           if not ln.startswith(knob_prefix)]


def streaming_eligible(args_limit_to_contig=None,
                       allow_multiprocess: bool = False) -> bool:
    """The streaming executor runs when host threads are available
    (``VCTPU_THREADS`` != 1, ``VCTPU_STREAM`` != 0), the native engine is
    built, and the job is single-process / whole-file. Anything else —
    including ``VCTPU_THREADS=1`` — cleanly selects the serial path.
    ``allow_multiprocess`` is the rank-partitioned scale-out driver's
    opt-in (parallel/rank_plan.py): each rank IS one of N processes by
    design, streaming over its own span."""
    from variantcalling_tpu import native
    from variantcalling_tpu.parallel.pipeline import resolve_threads

    if not knobs.get_bool("VCTPU_STREAM") or resolve_threads() <= 1:
        return False
    if not native.available() or args_limit_to_contig:
        return False
    if not allow_multiprocess:
        try:
            if jax.process_count() > 1:
                return False
        except Exception as e:  # noqa: BLE001 — uninitialized backend == single process
            degrade.record("pipeline.process_count_probe", e,
                           fallback="assume single process")
    return True


def _sink_write(sink, data) -> None:
    """Write ``data`` to the output sink with bounded retry on transient
    IO errors (ENOSPC, EIO — docs/robustness.md failure matrix).

    Retry is only attempted on REWINDABLE sinks (plain files): the
    pre-write position is restored with seek+truncate before each retry,
    so a partially-flushed attempt cannot duplicate bytes. Non-rewindable
    sinks (the BGZF writer buffers and may have flushed some compressed
    blocks when the error surfaced) do NOT retry — a duplicate-free
    partial file cannot be guaranteed there, so the failure propagates and
    the atomic commit discards the torn ``.partial`` instead of ever
    committing duplicated records.
    """
    from variantcalling_tpu.parallel.pipeline import retry_transient
    from variantcalling_tpu.utils import faults

    pos = None
    try:
        pos = sink.tell()
    except (AttributeError, OSError):
        pos = None

    def attempt() -> None:
        if pos is not None and sink.tell() != pos:
            sink.seek(pos)
            sink.truncate()
        # injection point "io.writeback": fires before bytes move, so the
        # injected failure is always cleanly retryable
        faults.check("io.writeback")
        sink.write(data)

    retry_transient(attempt, "output writeback",
                    attempts=None if pos is not None else 1)


def run_streaming(args, model, fasta: FastaReader, annotate, blacklist,
                  engine: engine_mod.EngineDecision | None = None,
                  rank_plan=None) -> dict | None:
    """Chunked three-stage streaming execution: BGZF/VCF chunk ingest ->
    fused featurize+score -> ordered VCF writeback, overlapped on the
    bounded-queue stage executor (parallel/pipeline.py).

    The FASTA 2-bit encode rides a prefetch thread (threaded native encode
    + persistent ``.venc`` cache), so the genome encode hides behind
    scoring instead of serializing in front of the run — the round-5
    "warmup cliff". Output is byte-identical to the serial path: chunks
    are sequence-numbered, written strictly in order, and every stage runs
    the same code the whole-table path runs.

    Failure semantics (docs/robustness.md):

    - output is committed ATOMICALLY: bytes accumulate in
      ``<out>.partial`` and are renamed onto the destination only after
      the last chunk — a crash/SIGKILL never leaves a partial file at the
      destination path;
    - plain ``.vcf`` outputs keep a chunk JOURNAL (``<out>.journal``,
      io/journal.py) so an interrupted run RESUMES: journaled chunks are
      skipped (their bytes are already in the partial file) and the
      continuation is byte-identical to an uninterrupted run
      (``VCTPU_RESUME=0`` opts out; ``.gz`` outputs restart — BGZF block
      state does not survive a kill);
    - transient ingest/writeback IO errors are retried with backoff
      (``VCTPU_IO_RETRIES``/``VCTPU_IO_BACKOFF_S``), a hung stage trips
      the executor watchdog (``VCTPU_STAGE_TIMEOUT_S``), and every
      failure path joins the prefetch thread and drains/joins the stage
      workers before re-raising.

    Returns a stats dict, or None when ineligible (caller runs serial).
    """
    multiproc = rank_plan is not None and (rank_plan.ranks > 1
                                           or rank_plan.span is not None)
    if not streaming_eligible(args.limit_to_contig,
                              allow_multiprocess=multiproc):
        return None

    # telemetry: callers that came through run() already opened the obs
    # run (start_run returns None and events just join it); direct
    # callers (bench legs, tests) get their own stream here
    inputs = {"input": args.input_file}
    if getattr(args, "model_file", None):
        inputs["model"] = args.model_file
    obs_run = obs.start_run("filter_variants_pipeline",
                            default_path=str(args.output_file) + ".obs.jsonl",
                            inputs=inputs)
    try:
        from variantcalling_tpu.parallel import shard_score

        try:
            stats = _run_streaming_impl(args, model, fasta, annotate,
                                        blacklist, engine=engine,
                                        rank_plan=rank_plan)
        except shard_score.MeshDegradeRestart as e:
            # recovery ladder, top rung: device OOM survived the
            # megabatch shrink — restart the WHOLE stream on a dp=1
            # plan. The journal restarts with it: the resume identity
            # and the output header both pin the mesh layout, so the
            # dp>1 partial can never splice into a dp=1 continuation.
            from variantcalling_tpu.io import journal as journal_mod

            degrade.record("shard_score.device_oom", e, warn=True,
                           fallback="restarting the streaming run on a "
                                    "dp=1 mesh plan")
            if obs.active():
                obs.event("recovery", "dp_degrade",
                          devices_from=e.devices, devices_to=1)
                obs.counter("recovery.dp_degrades").add(1)
            logger.warning("%s — restarting the stream single-device", e)
            journal_mod.discard(str(args.output_file))
            plan1 = shard_score.MeshPlan(
                1, "degraded",
                f"recovery ladder: device OOM at dp={e.devices}, "
                "degraded to dp=1")
            stats = _run_streaming_impl(args, model, fasta, annotate,
                                        blacklist, engine=engine,
                                        mesh_plan=plan1,
                                        rank_plan=rank_plan)
    except BaseException as e:
        obs.end_run(obs_run, f"error: {type(e).__name__}")
        raise
    obs.end_run(obs_run, "ok")
    return stats


def _run_streaming_impl(args, model, fasta: FastaReader, annotate, blacklist,
                        engine: engine_mod.EngineDecision | None = None,
                        mesh_plan=None, rank_plan=None) -> dict:
    import contextvars
    import threading
    import time as _time
    import zlib

    from variantcalling_tpu.utils import cancellation
    from variantcalling_tpu.utils import faults
    from variantcalling_tpu.io import chunk_cache as chunk_cache_mod
    from variantcalling_tpu.io import identity as identity_mod
    from variantcalling_tpu.io import journal as journal_mod
    from variantcalling_tpu.io.vcf import (VcfChunkReader, assemble_table_bytes,
                                           render_table_bytes_python)
    from variantcalling_tpu.parallel.pipeline import (StagePipeline,
                                                      resolve_stage_timeout,
                                                      resolve_threads,
                                                      retry_chunk,
                                                      retry_transient)

    # obs v2 attribution: created BEFORE the reader so the parallel-IO
    # worker pools (shard inflate / chunk parse) attribute their work
    # from the very first shard; the executor feeds per-stage work/
    # queue-wait/backpressure into the same profile and this loop adds
    # writeback work and the IO byte totals. One emit at commit time ->
    # `vctpu obs bottleneck` names the limiting stage (ROADMAP item 1).
    from variantcalling_tpu.obs import profile as profile_mod
    from variantcalling_tpu.obs import sampler as sampler_mod

    prof = profile_mod.StageProfiler() if profile_mod.enabled() else None
    # continuous-profiler attribution (obs v3): this thread runs the
    # sequenced single-writer commit loop for the duration of the run
    sampler_mod.register_current("committer")
    # rank-partitioned ingest (docs/scaleout.md): a multi-rank plan
    # restricts the reader to THIS rank's contiguous line-aligned span —
    # chunk boundaries, the journal and the output segment are all
    # rank-local, and the rank-sequenced committer splices the segments.
    # Resolved HERE when not passed (direct callers under a launcher
    # env), so the reader's span and the header's ##vctpu_ranks= line
    # can never disagree.
    if rank_plan is None:
        from variantcalling_tpu.parallel import rank_plan as rank_plan_mod

        rank_plan = rank_plan_mod.resolve()
    span = (rank_plan.rank, rank_plan.ranks) \
        if rank_plan is not None and rank_plan.ranks > 1 else None
    # elastic span workers (docs/scaleout.md "Elastic membership") carry
    # absolute byte targets instead of a rank fraction — same cut rule,
    # so re-cut spans tile the record body exactly like rank spans
    targets = rank_plan.span if rank_plan is not None else None
    reader = VcfChunkReader(args.input_file, profiler=prof, rank_span=span,
                            span_targets=targets)
    header = reader.header
    ctx = FilterContext(
        model, fasta, runs_file=args.runs_file,
        hpol_length=args.hpol_filter_length_dist[0],
        hpol_dist=args.hpol_filter_length_dist[1],
        blacklist=blacklist,
        blacklist_cg_insertions=args.blacklist_cg_insertions,
        annotate_intervals=annotate, flow_order=args.flow_order,
        is_mutect=args.is_mutect, engine=engine, mesh_plan=mesh_plan,
        rank_plan=rank_plan,
    )
    _ensure_output_header(header, engine=ctx.engine, strategy=ctx.forest_strategy,
                          mesh_plan=ctx.mesh_plan, rank_plan=ctx.rank_plan,
                          model_family=ctx.model_family)

    # kill the warmup cliff: encode (and persist) the genome on a prefetch
    # thread; scoring's per-contig fetch_encoded waits only for the contig
    # it needs, so encode overlaps scoring instead of preceding it. The
    # cancel event stops the prefetch between contigs once the run is done
    # (a tiny job on a huge genome must not block on untouched contigs),
    # and the join guarantees process exit never kills a .venc write
    # mid-file.
    prefetch_cancel = threading.Event()
    # the prefetch runs in the CALLER's context (fresh copy — a Context
    # object is single-threaded) so request-scoped knobs (genome-cache
    # settings) follow it, like every pooled worker (pipeline.IoPool)
    _prefetch_ctx = contextvars.copy_context()
    prefetch = threading.Thread(
        target=lambda: _prefetch_ctx.run(fasta.encode_all,
                                         cancel=prefetch_cancel),
        name="genome-prefetch", daemon=True)
    prefetch.start()

    def score_stage(table):
        # the chunk body rides the recovery ladder: the executor (serial
        # layout) or raw_chunk_worker (pooled layout) provides the bounded
        # re-dispatch; the guard provides the opt-in quarantine rung —
        # a diverted chunk flows on as a (table, None, None) marker.
        # The chunk's trace binds to the thread for the duration so
        # ladder events link to it, and the body emits its trace span.
        tid = getattr(table, "_obs_trace", None)
        with obs.trace_scope(tid):
            t0 = _time.perf_counter()  # vctpu-lint: disable=VCT006 — obs trace-span timing
            out = _guard_chunk(table, "score_stage",
                               lambda: ctx.score_table(table))
            if tid is not None:
                obs.trace_span(tid, "score_stage",
                               _time.perf_counter() - t0,  # vctpu-lint: disable=VCT006 — obs trace-span timing
                               records=len(table))
        if out is None:
            return table, None, None
        score, filters = out
        return table, score, filters

    def _timed_worker(fn, stage_name, item, n_records):
        """Run one stage callable on an IO-pool worker with the same
        span/histogram telemetry the executor would emit for that stage,
        plus a per-worker attribution row (``<stage>.w<idx>``)."""
        if not obs.active():
            return fn(item)
        t0 = _time.perf_counter()  # vctpu-lint: disable=VCT006 — obs span timing
        out = fn(item)
        dt = _time.perf_counter() - t0  # vctpu-lint: disable=VCT006 — obs span timing
        tname = threading.current_thread().name
        obs.span(stage_name, dt, tname)
        obs.histogram(f"stage.{stage_name}.s").observe(dt)
        if prof is not None:
            prof.stage(f"{stage_name}.{tname.rsplit('-', 1)[-1]}").add_work(
                dt, records=n_records)
        return out

    def raw_chunk_worker(item):
        """The ZERO-WAIT pooled chunk body: parse -> fused featurize+
        score -> render as ONE task over a RAW chunk buffer
        (``VcfChunkReader.iter_raw``). A chunk is parsed immediately
        before it scores on the same worker, so no parsed table ever
        waits in a queue between a parse task and a score task — the
        ``score_stage.wait`` edge that dominated the p95 critical path
        (BENCH_r12) is gone structurally, not hidden. Parse rides inside
        the chunk's retry budget (it is a pure function of the held
        buffer, so re-dispatch cannot change bytes; its own transient-IO
        retry stays inside ``parse_chunk``). Trace ids were allocated at
        the raw feed in canonical chunk order; the ingest span is
        emitted here with the parse duration — ONCE per chunk, whatever
        the retry budget spends (a re-dispatched body re-parses but must
        not grow a second root span), so the chunk DAG keeps the exact
        shape every obs consumer expects.

        Chunk-cache fast path (VCTPU_CACHE=1, docs/caching.md): the
        worker keys the RAW span (CRC32 + length under the scoring
        fingerprint) BEFORE parsing — a hit replays the stored rendered
        body straight to the sequenced commit, skipping parse→featurize→
        score→render entirely (its chunk DAG is one ``cache_hit`` span
        plus the committer's writeback). A miss computes as always and
        STAGES the result by sequence number; the committer publishes it
        only after the chunk commits."""
        seq, buf_np, lazy_buf, tid = item
        ckey = None
        if cache_session is not None:
            ckey = cache_session.key_of(buf_np)
            hit = cache_session.get(ckey)
            if hit is not None:
                cbody, k, p = hit
                if tid is not None:
                    obs.trace_span(tid, "cache_hit", 0.0, records=k)
                return cbody, k, p, None, tid
        ingest_span_emitted = [False]

        def body():
            faults.check("pipeline.stage")
            faults.check("pipeline.stage_hang")
            t0 = _time.perf_counter()  # vctpu-lint: disable=VCT006 — obs trace-span timing
            table = reader.parse_chunk(buf_np, lazy_buf)
            if tid is not None:
                table._obs_trace = tid
                if not ingest_span_emitted[0]:
                    ingest_span_emitted[0] = True
                    obs.trace_span(tid, "ingest",
                                   _time.perf_counter() - t0,  # vctpu-lint: disable=VCT006 — obs trace-span timing
                                   records=len(table))
            scored = _timed_worker(score_stage, "score_stage", table,
                                   len(table))
            return _timed_worker(render_stage, "render_stage", scored,
                                 len(table))

        with obs.trace_scope(tid):
            out = retry_chunk(body, "chunk_worker")
        if ckey is not None and out[3] is None:
            # clean chunks only: a quarantined chunk's zero-byte body is
            # a degradation artifact, not a pure function of the input
            cache_session.stage(seq, ckey, out[0], out[1], out[2])
        return out

    def _traced_raw(raws):
        """Allocate trace ids at the raw feed, in canonical chunk order
        (the ``_traced_chunks`` contract, kept for the raw layout — the
        pooled workers parse concurrently, so allocation cannot wait
        until parse time). The sequence number rides along: it is the
        chunk-cache staging key, matched against the committer's chunk
        counter at publish time (both count post-skip delivery order)."""
        for seq, (buf_np, lazy_buf) in enumerate(raws):
            yield seq, buf_np, lazy_buf, obs.new_trace()

    def render_stage(item):
        table, score, filters = item
        # the trace id rides the rendered tuple from here on — the table
        # is dropped after render, but compress + the sequenced commit
        # still emit spans of this chunk's DAG
        tid = getattr(table, "_obs_trace", None)
        t0 = _time.perf_counter()  # vctpu-lint: disable=VCT006 — obs trace-span timing
        if score is None:
            # quarantined chunk (recovery ladder): ZERO bytes reach the
            # main output; the ORIGINAL records (no TREE_SCORE, original
            # FILTER) go to the <out>.quarantine sidecar for triage
            qbody = assemble_table_bytes(table)
            if qbody is None:
                qbody = render_table_bytes_python(table)
            out = b"", len(table), 0, bytes(qbody), tid
        else:
            extra = {"TREE_SCORE": np.round(score, 4)}
            body = assemble_table_bytes(table, new_filters=filters,
                                        extra_info=extra)
            if body is None:  # native hiccup mid-run: Python renderer, same bytes
                body = render_table_bytes_python(table, new_filters=filters,
                                                 extra_info=extra)
            out = (body, len(table), int(np.sum(filters.codes == 0)), None,
                   tid)
        if tid is not None:
            obs.trace_span(tid, "render_stage",
                           _time.perf_counter() - t0,  # vctpu-lint: disable=VCT006 — obs trace-span timing
                           records=len(table))
        return out

    out_path = str(args.output_file)
    gz = out_path.endswith(".gz")
    header_bytes = (b"".join((line + "\n").encode() for line in header.lines)
                    + (header.column_header() + "\n").encode())

    # parallel writeback (gz outputs): rendered chunk bodies compress to
    # BGZF blocks in their own pipeline stage — block framing tracked by
    # a deterministic carry identical to the serial BgzfWriter's, deflate
    # fanned out (native block-sharded compressor, or the IO pool) — and
    # the consumer below is the sequenced single-writer merge: it drains
    # compressed chunks strictly in sequence order through the same
    # .partial + os.replace atomic path plain outputs use.
    compressor = None
    if gz:
        from variantcalling_tpu.io.bgzf import BgzfChunkCompressor
        from variantcalling_tpu.parallel.pipeline import resolve_io_threads

        compress_pool = (reader.shared_pool() if resolve_io_threads() > 1
                         else None)
        compressor = BgzfChunkCompressor(pool=compress_pool)

        def compress_stage(item):
            body, k, p, q, tid = item
            if not len(body):  # quarantined chunk: nothing to compress
                return b"", k, p, q, tid
            data = memoryview(body) if isinstance(body, np.ndarray) else body
            t0 = _time.perf_counter()  # vctpu-lint: disable=VCT006 — obs trace-span timing
            out = compressor.add(data)
            if tid is not None:
                obs.trace_span(tid, "compress_stage",
                               _time.perf_counter() - t0,  # vctpu-lint: disable=VCT006 — obs trace-span timing
                               bytes_in=len(data))
            return out, k, p, q, tid

        # the ONE stage that is NOT a pure chunk body: the compressor's
        # block carry absorbs every byte it sees, so a re-dispatch (chunk
        # retry or watchdog duplicate) would silently drop or duplicate
        # compressed records — the executor must run it exactly once per
        # item and fail loudly instead (the pre-ladder gz semantics)
        compress_stage.retry_safe = False

    # the WHOLE scoring configuration, spelled ONCE (io/identity.py):
    # already-committed chunks carry the old run's scores, so resuming —
    # or replaying a cached chunk body — under a different model/flags/
    # engine would silently mix configurations. Built unconditionally:
    # the chunk cache needs the identity even for .gz / resume-opted-out
    # runs. Per-field rationale (strategy/mesh/ranks) lives with the
    # spelling in identity_mod.scoring_config.
    scoring_cfg = identity_mod.scoring_config(
        args, engine=ctx.engine.name, forest_strategy=ctx.forest_strategy,
        mesh_devices=ctx.mesh_plan.devices,
        rank=ctx.rank_plan.rank, ranks=ctx.rank_plan.ranks,
        span=ctx.rank_plan.span,
        model_family=ctx.model_family, model_digest=ctx.model_digest)

    # resume only for plain-text outputs: a killed BGZF writer's in-flight
    # block state is unrecoverable, so .gz runs restart (still atomic)
    resume_enabled = not gz and knobs.get_bool("VCTPU_RESUME")
    resume = None
    journal: journal_mod.ChunkJournal | None = None
    meta = None
    if resume_enabled:
        meta = identity_mod.resume_meta(args, chunk_bytes=reader.chunk_bytes,
                                        header_bytes=header_bytes,
                                        config=scoring_cfg)
        # claim=True: the re-tokened partial is OURS from the instant it
        # exists — this writer releases the token on every exit path
        resume = journal_mod.try_resume(out_path, meta, claim=True)

    n_total = n_pass = n_chunks = 0
    q_path = quarantine_path(out_path)
    if resume is None:
        # fresh run: a stale quarantine sidecar from an older run must
        # not mix its records with this run's diversions (a RESUMED run
        # keeps it — journaled quarantined chunks are skipped, so their
        # sidecar records are not regenerated)
        try:
            os.remove(q_path)
        except OSError:
            pass
    # the partial path carries a UNIQUE per-run suffix (pid + random,
    # recorded in the journal header so resume finds it): two concurrent
    # runs targeting the same output accumulate independent partials and
    # the atomic os.replace commit makes the destination last-complete-
    # writer-wins — the old fixed <out>.partial let them silently
    # clobber each other's bytes. A resumed run reopens the token its
    # journal recorded; abandoned partials are swept by
    # journal_mod.discard's cleanup. The token is CLAIMED before the
    # file exists (a concurrent run's discard/sweep must always see it
    # as in use — io/journal.token_in_use, the serve same-process
    # concurrency case) and every raise between the claim and the main
    # try/finally below releases it: a long-lived daemon must not
    # accrete phantom claims from failed sink opens. The main body's
    # teardown/commit paths own the release from there on. The
    # remaining fallible setup (executor-knob parses, input stat) runs
    # BEFORE the claim for the same reason.
    resolve_threads()
    resolve_stage_timeout()
    # a rank-span reader processes only its share: heartbeat progress
    # divides by the SPAN's bytes, not the whole file's
    input_bytes = reader.span_bytes if reader.span_bytes is not None \
        else os.path.getsize(args.input_file)
    part_token = None
    try:
        if gz:
            journal_mod.discard(out_path)  # stale leftovers of older runs
            part_token = journal_mod.new_partial_token()
            journal_mod.claim_token(part_token)
            part_path = journal_mod.partial_path(out_path, part_token)
            # the compress stage produces finished BGZF blocks; the
            # committer writes them raw (and rewindably, so transient
            # write errors are retryable — the old in-consumer
            # BgzfWriter could not rewind)
            sink = journal_mod.open_partial(out_path, part_token, "wb")
            if obs.active():
                obs.event("journal", "resume_decision", outcome="disabled",
                          reason="gz output: BGZF block state does not "
                                 "survive a kill")
        elif resume is not None:
            n_chunks = resume.chunks
            n_total = resume.n_records
            n_pass = resume.n_pass
            part_token = resume.partial_token  # re-tokened + claimed by try_resume
            part_path = journal_mod.partial_path(out_path, part_token)
            reader.skip(resume.chunks)
            # truncated to the watermark already
            sink = journal_mod.open_partial(out_path, part_token, "ab")
            journal = journal_mod.ChunkJournal(out_path)
            journal.reopen()
            logger.info("streaming resume: %d chunks (%d records) already "
                        "committed", resume.chunks, resume.n_records)
            if obs.active():
                obs.event("journal", "resume_decision", outcome="resumed",
                          chunks=resume.chunks, records=resume.n_records,
                          watermark=resume.watermark)
        else:
            journal_mod.discard(out_path)
            part_token = journal_mod.new_partial_token()
            journal_mod.claim_token(part_token)
            part_path = journal_mod.partial_path(out_path, part_token)
            sink = journal_mod.open_partial(out_path, part_token, "wb")
            if resume_enabled:
                journal = journal_mod.ChunkJournal(out_path)
                journal.begin(dict(meta, partial=part_token))
            if obs.active():
                obs.event("journal", "resume_decision",
                          outcome="fresh" if resume_enabled else "opted_out",
                          journaling=resume_enabled)
    except BaseException:
        if part_token is not None:
            journal_mod.release_token(part_token)
        raise

    wb = prof.stage("writeback") if prof is not None else None
    # the parallel layout (VCTPU_IO_THREADS > 1): scoring AND record
    # render ride the SAME ordered-window fan-out as chunk parse — all
    # per-chunk work shares the IO pool, reassembled into canonical
    # sequence order before the stream enters the stage pipeline, so the
    # committer sees exactly the serial chunk sequence. Only the
    # order-dependent tail stays sequenced: the BGZF carry (compress
    # stage) and the single-writer commit. The serial-IO layout
    # (VCTPU_IO_THREADS=1) keeps the dedicated score/render stage
    # threads, as before.
    #
    # MESH layout (ctx.mesh_plan.devices > 1, docs/streaming_executor.md
    # "Mesh-sharded scoring"): host featurization still fans out per
    # chunk on the IO pool, but the DEVICE dispatch packs consecutive
    # chunks into device-count-sized megabatches scored by ONE shard_map
    # program over the mesh dp axis (shard_score.megabatch_stream), with
    # per-chunk scores unpacked back into canonical chunk order before
    # the pooled render fan-out. The chunk sequence, journal identity
    # and output bytes are identical to the single-device layouts.
    source_pooled = reader.io_threads > 1
    mesh_scoring = ctx.mesh_plan.devices > 1
    # chunk-result cache (VCTPU_CACHE=1, docs/caching.md): opened AFTER
    # the resume decision so a resumed run's cache spans key identically
    # (reader.skip preserves the deterministic chunk cut; seq numbers
    # below count post-skip delivery order on both sides). The mesh
    # megabatch layout bypasses the cache — its device-count-sized
    # batches span chunks, so there is no per-chunk raw-span fast path
    # to skip (documented limitation; record bytes would still match).
    cache_session = None
    if not mesh_scoring:
        cache_session = chunk_cache_mod.open_session(
            scoring_cfg, rank=ctx.rank_plan.rank, ranks=ctx.rank_plan.ranks)
    if mesh_scoring:
        from variantcalling_tpu.parallel import shard_score
        from variantcalling_tpu.parallel.pipeline import imap_ordered

        def prep_worker(table):
            def body():
                faults.check("pipeline.stage")
                faults.check("pipeline.stage_hang")
                # hf None == featurize-stage quarantine marker; the
                # megabatch stream passes it through to the render path
                hf = _guard_chunk(
                    table, "featurize_stage",
                    lambda: _timed_worker(ctx.host_features,
                                          "featurize_stage", table,
                                          len(table)))
                return table, hf

            tid = getattr(table, "_obs_trace", None)
            with obs.trace_scope(tid):
                t0 = _time.perf_counter()  # vctpu-lint: disable=VCT006 — obs trace-span timing
                out = retry_chunk(body, "featurize prep")
                if tid is not None:
                    obs.trace_span(tid, "featurize_stage",
                                   _time.perf_counter() - t0,  # vctpu-lint: disable=VCT006 — obs trace-span timing
                                   records=len(table))
            return out

        def render_worker(item):
            return _timed_worker(render_stage, "render_stage", item,
                                 len(item[0]))

        if source_pooled:
            window = reader.io_threads + 2
            prepped = imap_ordered(reader.shared_pool(), prep_worker,
                                   _traced_chunks(reader), window=window)
            scored = shard_score.megabatch_stream(prepped, ctx, profiler=prof)
            source = imap_ordered(reader.shared_pool(), render_worker,
                                  scored, window=window)
            stages = []
        else:
            def timed_tables():
                # serial-IO mesh layout: the reader's inflate/parse work
                # is attributed HERE, per table — the executor's feed
                # sees the whole featurize+score megabatch wall in its
                # next(), and that wall already belongs to the
                # featurize_stage/score.dN rows recorded inside this
                # source chain; booking it as ingest work again would
                # double-count it (the pipeline books its feed-blocked
                # time as queue-wait instead: source_pooled below)
                it = iter(reader)
                while True:
                    if obs.active():
                        t0 = _time.perf_counter()  # vctpu-lint: disable=VCT006 — obs span timing
                        try:
                            table = next(it)
                        except StopIteration:
                            return
                        dt = _time.perf_counter() - t0  # vctpu-lint: disable=VCT006 — obs span timing
                        obs.span("ingest", dt,
                                 threading.current_thread().name)
                        obs.histogram("stage.ingest.s").observe(dt)
                        if prof is not None:
                            # items=0: the executor feed counts the
                            # pulled items on this row (the pooled-source
                            # rule) — work seconds only here
                            prof.stage("ingest").add_work(dt, items=0)
                    else:
                        try:
                            table = next(it)
                        except StopIteration:
                            return
                    yield table

            source = shard_score.megabatch_stream(
                map(prep_worker, _traced_chunks(timed_tables())), ctx,
                profiler=prof)
            stages = [render_stage]
    elif source_pooled:
        from variantcalling_tpu.parallel.pipeline import imap_ordered

        # the zero-wait feed: the in-flight window holds RAW BYTE
        # buffers, and each pooled task runs the chunk's WHOLE body
        # (parse -> fused featurize+score -> render) — nothing parsed
        # ever queues between stages (ROADMAP item 4)
        source = imap_ordered(reader.shared_pool(), raw_chunk_worker,
                              _traced_raw(reader.iter_raw()),
                              window=reader.io_threads + 2)
        stages = []
    elif cache_session is not None:
        # serial-IO cached layout: the same raw-buffer chunk body, run
        # inline on the feed — lookups must key on the RAW span (parsed
        # tables have no stable byte identity), so the cache rides the
        # raw feed here too; stages collapse into the worker exactly as
        # in the pooled layout, keeping one code path for hit/miss/stage
        source = map(raw_chunk_worker, _traced_raw(reader.iter_raw()))
        stages = []
    else:
        source = _traced_chunks(reader)
        stages = [score_stage, render_stage]
    if compressor is not None:
        stages.append(compress_stage)
    pipe = StagePipeline(stages, queue_depth=2,
                         profiler=prof, source_name="ingest",
                         # mesh serial-IO counts too: the source chain
                         # attributes its own ingest/featurize/score work
                         # (timed_tables + _timed_worker + score.dN), so
                         # feed-blocked time is queue-wait, never work —
                         # and the serial cached layout likewise runs the
                         # self-attributing chunk body inline on the feed
                         consumer_name="writeback",
                         source_pooled=(source_pooled or mesh_scoring
                                        or cache_session is not None),
                         # SUPERVISED mode (docs/robustness.md "Recovery
                         # ladder"): stage-item re-dispatch, watchdog v2
                         # (stack dump + one wedged-chunk retry before
                         # abort), duplicate-delivery drop
                         recover=True)
    gen = pipe.run(source)
    ok = False
    # heartbeat bookkeeping (obs only). Progress (pct) counts ALL
    # committed chunks incl. resumed ones; rate (vps) and ETA use only
    # THIS session's work over this session's elapsed time, so a resumed
    # run neither inflates its rate nor stalls its ETA. Chunk boundaries
    # are a pure function of (input bytes, chunk_bytes) — but only for
    # PLAIN-TEXT inputs: a .gz reader consumes chunk_bytes of
    # decompressed text while getsize() is compressed, so gz runs emit
    # heartbeats without pct/eta rather than a clamped-to-100 lie.
    # (input_bytes was stat'ed above, before the token claim.)
    bytes_comparable = not args.input_file.endswith(".gz")
    resumed_chunks = n_chunks
    resumed_records = n_total
    n_quar_chunks = n_quar_records = 0
    qsink = None
    t_start = _time.perf_counter()  # vctpu-lint: disable=VCT006 — obs heartbeat timing
    try:
        with sink:
            if resume is None:
                if compressor is not None:
                    # the header rides the SAME block stream the chunk
                    # bodies do (it usually just seeds the carry — the
                    # serial BgzfWriter buffered it identically). Safe
                    # ordering: the compress stage has not started — the
                    # pipeline workers spin up on the first next() below.
                    _sink_write(sink, compressor.add(header_bytes))
                else:
                    _sink_write(sink, header_bytes)
            for body, k, p, qbody, trace_id in gen:
                # cooperative per-request cancellation (vctpu serve
                # deadlines/drain, docs/serving.md): chunk-granular by
                # design — raising here unwinds through the normal
                # failure teardown (workers joined, journal+partial
                # kept for resume), never a torn commit. One contextvar
                # read per chunk outside a serve request.
                cancellation.check("streaming filter run")
                if qbody:
                    # quarantined chunk: its ORIGINAL records append to
                    # the sidecar (plain text, never compressed) and the
                    # main output gets zero bytes for this chunk — the
                    # journal entry below records body_len=0, so resume
                    # stays consistent. The sidecar itself is BEST-EFFORT
                    # triage, appended BEFORE the journal claims the
                    # chunk: a kill inside that window re-processes the
                    # chunk on resume, which can DUPLICATE records in the
                    # sidecar — never lose them (the reverse order would
                    # lose them from both outputs). docs/robustness.md.
                    if qsink is None:
                        qsink = open(q_path, "ab")
                    _sink_write(qsink, qbody)
                    qsink.flush()
                    n_quar_chunks += 1
                    n_quar_records += k
                data = memoryview(body) if isinstance(body, np.ndarray) else body
                if wb is not None or trace_id is not None:
                    t0 = _time.perf_counter()  # vctpu-lint: disable=VCT006 — obs writeback attribution
                    _sink_write(sink, data)
                    dt = _time.perf_counter() - t0  # vctpu-lint: disable=VCT006 — obs writeback attribution
                    if wb is not None:
                        wb.add_work(dt, bytes_out=len(data))
                    if trace_id is not None:
                        # the sequenced commit: the TERMINAL span of the
                        # chunk's DAG (named like the profiler's consumer
                        # stage so critical-path reconciles against it)
                        obs.trace_span(trace_id, "writeback", dt,
                                       chunk=n_chunks, bytes_out=len(data))
                        obs.end_trace(trace_id)
                else:
                    _sink_write(sink, data)
                n_total += k
                n_pass += p
                n_chunks += 1
                if obs.active():
                    obs.counter("records").add(k)
                    obs.counter("records_pass").add(p)
                    obs.histogram("chunk.records").observe(k)
                    elapsed = _time.perf_counter() - t_start  # vctpu-lint: disable=VCT006 — obs heartbeat timing
                    hb = {"chunks": n_chunks, "records": n_total,
                          "records_pass": n_pass,
                          "vps": round((n_total - resumed_records) / elapsed)
                          if elapsed > 0 else 0}
                    if bytes_comparable:
                        done = min(n_chunks * reader.chunk_bytes, input_bytes)
                        session_done = min(
                            (n_chunks - resumed_chunks) * reader.chunk_bytes,
                            input_bytes)
                        hb["pct"] = round(100.0 * done / input_bytes, 2)
                        hb["eta_s"] = round(
                            elapsed * (input_bytes - done) / session_done, 2) \
                            if 0 < session_done and done < input_bytes else 0.0
                    obs.event("heartbeat", "stream", **hb)
                if journal is not None:
                    # the journal must never claim bytes still sitting in
                    # the Python write buffer — a SIGKILL would then leave
                    # the partial file behind the watermark and resume
                    # would (safely but wastefully) start fresh
                    sink.flush()
                    if journal_mod.fsync_enabled():
                        # durability knob (VCTPU_JOURNAL_FSYNC): the
                        # chunk's bytes reach the platter before the
                        # journal claims them (journal.append fsyncs its
                        # own line next) — a power cut can then cost at
                        # most the in-flight chunk
                        os.fsync(sink.fileno())
                    journal.append(n_chunks - 1, k, p, len(data),
                                   zlib.crc32(data),
                                   in_end=reader.chunk_end(n_chunks - 1))
                if cache_session is not None:
                    # committed-prefix publication: entries become
                    # visible (disk store / serve warm index) only once
                    # their chunk's bytes are in the partial file — and
                    # past the journal line when journaling — so a
                    # cancelled request or failed run never publishes
                    # an entry no output carried (docs/caching.md)
                    cache_session.publish_up_to(
                        n_chunks - resumed_chunks - 1)
            if compressor is not None:
                # the final partial block + EOF sentinel — the committer
                # (this thread) is the only writer, in sequence order
                _sink_write(sink, compressor.finish())
        ok = True
    finally:
        # guaranteed teardown on EVERY exit path: stage workers drained and
        # joined (generator close runs StagePipeline's finally), the IO
        # worker pool shut down, prefetch cancelled and joined (a dying
        # process must not kill a .venc persist mid-file), journal handle
        # closed.
        try:
            gen.close()
        finally:
            reader.close()
            prefetch_cancel.set()
            prefetch.join()
        if qsink is not None:
            qsink.close()
        if journal is not None:
            journal.close()
        if cache_session is not None and not ok:
            # failure/cancellation: drop everything unpublished — the
            # stores hold only committed chunks' entries
            cache_session.discard()
        if not ok:
            # failure exit: the partial (if kept) now awaits a RESUME —
            # release the claim so the resumer (or a superseding fresh
            # run's discard) may take the file over
            journal_mod.release_token(part_token)
            if journal is None:
                # non-resumable run: never leave droppings next to the
                # destination (the destination itself was never touched)
                journal_mod.remove_partial(out_path, part_token)
            else:
                logger.info("streaming run failed after %d chunks; partial "
                            "output + journal kept for resume at %s",
                            n_chunks, part_path)
                if obs.active():
                    obs.event("journal", "kept_for_resume", chunks=n_chunks)

    def _commit():
        # injection point "io.commit": fires BEFORE the rename, so an
        # injected ENOSPC is cleanly retryable and a persistent one
        # leaves journal + partial behind for resume
        faults.check("io.commit")
        journal_mod.commit_partial(out_path, part_token)  # vctpu-lint: disable=VCT008 — THE one sanctioned atomic commit

    # the journal outlives the commit attempt (recovery ladder): an
    # ENOSPC on the rename itself must leave journal + partial behind so
    # the NEXT run resumes (skipping every chunk) instead of recomputing
    # — journal.finish() therefore runs only after the rename landed
    try:
        retry_transient(_commit, "output commit")
    except BaseException:
        journal_mod.release_token(part_token)
        if journal is None:
            # non-resumable run: never leave droppings at the destination
            journal_mod.remove_partial(out_path, part_token)
        else:
            logger.info("output commit failed after %d chunks; partial "
                        "output + journal kept for resume at %s",
                        n_chunks, part_path)
            if obs.active():
                obs.event("journal", "kept_for_resume", chunks=n_chunks)
        raise
    journal_mod.release_token(part_token)  # committed: the partial is gone
    if journal is not None:
        journal.finish()
    if cache_session is not None:
        cache_session.finish()
    if obs.active():
        obs.event("journal", "committed", chunks=n_chunks, records=n_total)
    if n_quar_chunks:
        logger.warning("quarantine: %d chunk(s), %d record(s) diverted to %s "
                       "— the main output is INCOMPLETE by that many records",
                       n_quar_chunks, n_quar_records, q_path)
    if prof is not None:
        # ingest byte attribution: the reader consumes chunk_bytes of
        # (decompressed) text per chunk; cap at the file size only when
        # the two are comparable (plain-text inputs, heartbeat contract)
        approx = n_chunks * reader.chunk_bytes
        prof.stage("ingest").bytes_in = \
            min(approx, input_bytes) if bytes_comparable else approx
        prof.emit(wall_s=_time.perf_counter() - t_start,  # vctpu-lint: disable=VCT006 — obs profile wall clock
                  records=n_total - resumed_records)
    if gz:
        from variantcalling_tpu.io.tabix import build_tabix_index

        try:
            build_tabix_index(out_path)
        except (ValueError, OSError):
            pass  # unsorted/odd inputs: the VCF itself is still valid
    return {"n": n_total, "n_pass": n_pass, "chunks": n_chunks,
            "engine": ctx.engine.name,
            "resumed_chunks": resume.chunks if resume is not None else 0,
            "quarantined_chunks": n_quar_chunks,
            "quarantined_records": n_quar_records,
            "cache": cache_session.stats() if cache_session is not None
            else None,
            "mode": "streaming" if pipe.parallel else "serial-chunked"}


def run(argv: list[str]) -> int:
    args = get_parser().parse_args(argv)
    if args.backend == "cpu":
        jax.config.update("jax_platforms", "cpu")

    # whole-registry knob validation FIRST (docs/static_analysis.md): any
    # malformed VCTPU_* value exits 2 here with a clear message, uniformly
    # across engines and forest strategies, before any ingest or scoring
    # work starts — and before obs opens a run stream (a run that cannot
    # start leaves no half-written telemetry)
    try:
        knobs.validate_all()
    except EngineError as e:
        logger.error("%s", e)
        return 2

    # the run manifest opens the telemetry stream (VCTPU_OBS=1): resolved
    # knobs, topology, input identity, argv — then every span/degradation/
    # resolution/heartbeat of this run lands in the same ordered JSONL
    # (docs/observability.md). Output bytes are identical either way.
    obs_run = obs.start_run(
        "filter_variants_pipeline",
        default_path=str(args.output_file) + ".obs.jsonl", argv=argv,
        inputs={"input": args.input_file, "model": args.model_file,
                "reference": args.reference_file})
    status = "error"
    try:
        rc = _run_impl(args)
        status = "ok" if rc == 0 else f"exit {rc}"
        return rc
    except BaseException as e:
        status = f"error: {type(e).__name__}"
        raise
    finally:
        obs.end_run(obs_run, status)


def _run_impl(args) -> int:
    # resolve the scoring engine ONCE, up front (engine contract,
    # docs/robustness.md): an explicitly required native engine that
    # cannot build/load fails the run HERE with a clear message — never a
    # silent jit fallback half-way through scoring. Multi-host runs also
    # agree on one engine across ranks so the allgathered score slices
    # cannot mix engines within one output file.
    try:
        eng = engine_mod.resolve_for_run()
    except EngineError as e:
        logger.error("%s", e)
        return 2

    model = load_model(args.model_file, args.model_name)
    fasta = FastaReader(args.reference_file)
    annotate = {_interval_name(p): bedio.read_intervals(p) for p in args.annotate_intervals}
    blacklist = read_blacklist(args.blacklist) if args.blacklist else None
    return run_loaded(args, model, fasta, annotate, blacklist, engine=eng)


def run_loaded(args, model, fasta: FastaReader, annotate, blacklist,
               engine: engine_mod.EngineDecision | None = None) -> int:
    """The filter pipeline over ALREADY-LOADED resources — the split
    that lets ``vctpu serve`` (docs/serving.md) run requests against its
    resident model/genome caches without re-paying the load, while the
    cold CLI (:func:`_run_impl`) rides the same code so serve output is
    byte-identical to the batch path by construction."""
    from variantcalling_tpu.utils import cancellation
    from variantcalling_tpu.utils.trace import report, stage

    eng = engine if engine is not None else engine_mod.resolve_for_run()
    # rank-partitioned scale-out FIRST (docs/scaleout.md): a multi-rank
    # plan (VCTPU_RANK under the local launcher, or an initialized
    # jax.distributed runtime) runs this process as ONE rank of a pod —
    # full sharded ingest -> fused score -> render over its contiguous
    # span, staged into a rank segment for the rank-sequenced committer.
    from variantcalling_tpu.parallel import rank_plan as rank_plan_mod

    try:
        plan = rank_plan_mod.resolve()
        partitioned = plan.ranks > 1 or plan.span is not None
        if partitioned and rank_plan_mod.scaleout_eligible(args):
            if plan.span is not None:
                logger.info("elastic scale-out: span [%d,%d) gen %d",
                            plan.span[0], plan.span[1], plan.gen)
            else:
                logger.info("rank-partitioned scale-out: rank %d of %d "
                            "(%s)", plan.rank, plan.ranks, plan.source)
            with stage("scaleout"):
                try:
                    return rank_plan_mod.run_scaleout(
                        args, model, fasta, annotate, blacklist,
                        engine=eng, plan=plan)
                except Exception as e:
                    from variantcalling_tpu.parallel import elastic

                    if isinstance(e, elastic.LeaseLost):
                        # benign: another worker holds this (span, gen)
                        # lease — exit distinctly so the coordinator can
                        # tell a lost race from a real failure
                        logger.info("%s — yielding (exit %d)", e,
                                    elastic.EXIT_LEASE_LOST)
                        return elastic.EXIT_LEASE_LOST
                    raise
        if partitioned and plan.source in ("env", "span"):
            # an env/span-launched worker has NO collectives to merge
            # scores through — silently writing the FULL output would
            # make N workers race on one destination; fail loudly
            raise EngineError(
                f"VCTPU_{'SPAN' if plan.span is not None else 'RANK'} is "
                "set but this job cannot run the rank-partitioned "
                "streaming executor (it needs the native engine, "
                "VCTPU_STREAM=1, VCTPU_THREADS>1 and no "
                "--limit_to_contig) — unset it or fix the "
                "configuration; docs/scaleout.md")
    except EngineError as e:
        logger.error("%s", e)
        return 2
    # streaming executor next: overlapped ingest/score/writeback with
    # byte-identical output; falls through to the serial path when
    # ineligible (VCTPU_THREADS=1, multi-process, region-limited, no
    # native engine)
    if streaming_eligible(args.limit_to_contig):
        logger.info("streaming %s", args.input_file)
        try:
            with stage("stream"):
                stats = run_streaming(args, model, fasta, annotate, blacklist,
                                      engine=eng)
        except EngineError as e:
            logger.error("%s", e)
            return 2
        if stats is not None:
            logger.debug("%s", report())
            logger.info("wrote %s: %d variants, %d PASS (engine %s)",
                        args.output_file, stats["n"], stats["n_pass"],
                        stats["engine"])
            return 0

    logger.info("reading %s", args.input_file)
    with stage("ingest"):
        table = read_vcf(args.input_file)
    # serial path: cancellation polls at stage boundaries (the
    # streaming path polls per chunk)
    cancellation.check("filter run")
    if args.limit_to_contig:
        keep = np.asarray(table.chrom) == args.limit_to_contig
        table = _subset(table, keep)

    # multi-host launch (VCTPU_COORDINATOR set -> __main__ initialized
    # jax.distributed): ranks score CONTIGUOUS slices of the callset on
    # their local-device meshes, then allgather scores+filters so every
    # rank holds the full result and writes an identical file. Work is
    # sharded by variant range, collectives ride the global mesh.
    try:
        n_proc = jax.process_count()
    except Exception as e:  # noqa: BLE001 — uninitialized backend == single process
        degrade.record("pipeline.process_count_probe", e, fallback="n_proc=1")
        n_proc = 1
    work = table
    if n_proc > 1:
        bounds = np.linspace(0, len(table), n_proc + 1).astype(np.int64)
        pid = jax.process_index()
        mask = np.zeros(len(table), dtype=bool)
        mask[bounds[pid]:bounds[pid + 1]] = True
        work = _subset(table, mask)
        logger.info("rank %d/%d scoring variants [%d, %d)", pid, n_proc,
                    int(bounds[pid]), int(bounds[pid + 1]))

    try:
        ctx = FilterContext(
            model, fasta, runs_file=args.runs_file,
            hpol_length=args.hpol_filter_length_dist[0],
            hpol_dist=args.hpol_filter_length_dist[1],
            blacklist=blacklist,
            blacklist_cg_insertions=args.blacklist_cg_insertions,
            annotate_intervals=annotate, flow_order=args.flow_order,
            is_mutect=args.is_mutect, engine=eng,
        )
        with stage("featurize+score"):
            score, filters = ctx.score_table(work)
    except EngineError as e:
        logger.error("%s", e)
        return 2

    if n_proc > 1:
        from variantcalling_tpu.parallel import distributed as dist

        # keep the score's own dtype: a float32 cast here could round a
        # float64 score differently than the single-process run writes it
        score = dist.allgather_concat(np.asarray(score))
        # the FILTER uniques table is a fixed literal identical on every
        # rank, so only the int32 codes cross the wire — writeback stays
        # integer-only (no 5M-string gather, no re-factorize)
        filters = FactorizedColumn(dist.allgather_concat(filters.codes),
                                   filters.uniques)
        assert len(score) == len(table), (len(score), len(table))
        if jax.process_index() != 0 and not knobs.get_bool("VCTPU_ALL_RANKS_WRITE"):
            # every rank holds the full result, but only rank 0 touches the
            # output path: concurrent identical-byte writes to a shared
            # filesystem race benignly at best (truncate-then-write), and a
            # straggler could transiently truncate a finished file.
            # VCTPU_ALL_RANKS_WRITE=1 restores every-rank writes for
            # deployments whose output path is per-host local disk.
            logger.info("rank %d/%d: writeback delegated to rank 0",
                        jax.process_index(), n_proc)
            return 0

    cancellation.check("filter run")
    _ensure_output_header(table.header, engine=ctx.engine,
                          strategy=ctx.forest_strategy,
                          mesh_plan=ctx.mesh_plan,
                          rank_plan=ctx.rank_plan,
                          model_family=ctx.model_family)
    with stage("writeback"):
        # verbatim_core: this pipeline never edits CHROM..QUAL, so record
        # assembly can splice FILTER/TREE_SCORE between original byte spans
        write_vcf(args.output_file, table, new_filters=filters,
                  extra_info={"TREE_SCORE": np.round(score, 4)}, verbatim_core=True)
    logger.debug("%s", report())
    logger.info(
        "wrote %s: %d variants, %d PASS", args.output_file, len(table), int(np.sum(filters == PASS))
    )
    return 0


def _subset(table: VariantTable, keep: np.ndarray) -> VariantTable:
    return table.subset(keep)


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
