"""run_no_gt_report — callset statistics without ground truth.

Drop-in surface of the reference tool (ugvc/pipelines/run_no_gt_report.py:
598-664): subcommands ``full_analysis`` / ``variant_eval`` /
``somatic_analysis``. The GATK VariantEval subprocess is replaced by
in-process device reductions (reports/variant_eval); the SigProfiler
somatic stage reduces to the 96-channel SBS matrix (signature assignment
needs the external SigProfiler package and is gated on its presence).
Outputs the same HDF5 key layout (``ins_del_hete``, ``ins_del_homo``,
``af_hist``, ``snp_motifs``, ``eval_<Table>``, ``callable_size``).
"""

from __future__ import annotations

import argparse
import sys

import numpy as np
import pandas as pd

from variantcalling_tpu import logger
from variantcalling_tpu.io.vcf import read_vcf
from variantcalling_tpu.reports import no_gt_stats
from variantcalling_tpu.reports.variant_eval import compute_eval_tables, dbsnp_membership
from variantcalling_tpu.utils.h5_utils import write_hdf


def _sample_index(table, sample_id: int | None, sample_name: str | None) -> int:
    if sample_name is not None and sample_name in table.header.samples:
        return table.header.samples.index(sample_name)
    return sample_id or 0


def run_full_analysis(args) -> None:
    out_h5 = f"{args.output_prefix}.h5"
    mode = "w"
    if args.callable_region is not None:
        from variantcalling_tpu.io.bed import read_bed

        size = read_bed(args.callable_region).total_length()
        write_hdf(pd.DataFrame({"callable_size": [size]}), out_h5, key="callable_size", mode=mode)
        mode = "a"

    table = read_vcf(args.input_file)
    sample = _sample_index(table, args.sample_id, args.sample_name)
    known = dbsnp_membership(table, args.dbsnp) if args.dbsnp else None
    eval_tables = compute_eval_tables(table, known=known, sample=sample)

    logger.info("annotating %d records", len(table))
    cols, windows, hmer_len, hmer_nuc = no_gt_stats._annotate(table, args.reference)

    logger.info("insertion/deletion statistics")
    ins_del = no_gt_stats.insertion_deletion_statistics(table, cols, hmer_len, hmer_nuc, sample=sample)

    logger.info("allele frequency histogram")
    vtype = no_gt_stats.variant_type_labels(cols, hmer_len)
    af = no_gt_stats._compute_af(table, sample=sample)  # shared with the scatters
    af_df = no_gt_stats.allele_freq_hist(table, vtype, sample=sample, af=af)

    logger.info("snp motif statistics")
    snp_motifs = no_gt_stats.snp_statistics(table, cols, windows)

    write_hdf(ins_del["hete"].T.reset_index(names="hmer_len"), out_h5, key="ins_del_hete", mode=mode)
    write_hdf(ins_del["homo"].T.reset_index(names="hmer_len"), out_h5, key="ins_del_homo", mode="a")
    write_hdf(af_df, out_h5, key="af_hist", mode="a")
    motif_df = snp_motifs.reset_index()
    write_hdf(motif_df, out_h5, key="snp_motifs", mode="a")
    for name, tbl in eval_tables.items():
        write_hdf(tbl, out_h5, key=f"eval_{name}", mode="a")

    # notebook report_wo_gt "Variants Statistics" merged table + the two
    # per-variant AF scatters ("AF along genome positions", "AF vs depth"),
    # stored downsampled so the report h5 stays small at WGS scale
    vc = pd.Series(vtype).value_counts()
    vstats = vc.rename_axis("variant_type").reset_index(name="count")
    write_hdf(vstats, out_h5, key="variants_statistics", mode="a")
    dp = table.info_field("DP")
    if np.all(np.isnan(dp)):  # no INFO/DP: depth from the sample column,
        dp = table.format_numeric("DP", sample=sample, max_len=1,  # matching the AF source
                                  missing=np.nan)[:, 0]
    ok = ~np.isnan(af)
    idx = np.nonzero(ok)[0]
    if len(idx) > 50_000:  # even stride keeps the genome-position spread
        idx = idx[:: len(idx) // 50_000]
    scatter = pd.DataFrame({
        "chrom": np.asarray(table.chrom)[idx],
        "pos": table.pos[idx],
        "af": af[idx].astype(np.float32),
        "dp": dp[idx].astype(np.float32),
    })
    write_hdf(scatter, out_h5, key="af_scatter", mode="a")
    logger.info("wrote %s", out_h5)


def run_eval_tables_only(args) -> None:
    table = read_vcf(args.input_file)
    sample = _sample_index(table, args.sample_id, args.sample_name)
    known = dbsnp_membership(table, args.dbsnp) if args.dbsnp else None
    eval_tables = compute_eval_tables(table, known=known, sample=sample)
    mode = "w"
    for name, tbl in eval_tables.items():
        write_hdf(tbl, f"{args.output_prefix}.h5", key=f"eval_{name}", mode=mode)
        mode = "a"


def run_somatic_analysis(args) -> None:
    """96-channel SBS matrix (+ optional SigProfiler assignment when installed)."""
    table = read_vcf(args.input_file)
    cols, windows, hmer_len, _hmer_nuc = no_gt_stats._annotate(table, args.reference)
    snp_motifs = no_gt_stats.snp_statistics(table, cols, windows)
    # SBS96 channel labels: C>A style with flanks, e.g. A[C>A]G
    labels = [f"{m[0]}[{m[1]}>{a}]{m[2]}" for (m, a) in snp_motifs.index]
    sbs = pd.DataFrame({"MutationType": labels, args.output_prefix.split("/")[-1]: snp_motifs.values})
    sbs_path = f"{args.output_prefix}.SBS96.all"
    sbs.to_csv(sbs_path, sep="\t", index=False)
    logger.info("wrote SBS96 matrix: %s", sbs_path)
    if getattr(args, "signatures_file", None):
        # native device fitting: KL-NNLS against the provided catalog
        from variantcalling_tpu.reports import signatures as sigmod

        catalog = sigmod.load_signature_matrix(args.signatures_file)
        catalog = catalog.reindex(labels).fillna(0.0)  # align channel order
        exposures = sigmod.fit_signatures(snp_motifs.values[None, :], catalog.to_numpy())
        exposures = sigmod.sparsify_exposures(exposures)
        meta = (
            sigmod.load_signature_metadata(args.signatures_metadata)
            if getattr(args, "signatures_metadata", None)
            else None
        )
        tbl = sigmod.assignment_table(
            exposures, list(catalog.columns), meta, [args.output_prefix.split("/")[-1]]
        )
        write_hdf(tbl, f"{args.output_prefix}.h5", key="signature_exposures", mode="a")
        logger.info("fitted %d active signatures (device NNLS)", int((exposures > 0).sum()))
        return
    try:  # optional external signature assignment (reference :334-595)
        from SigProfilerAssignment import Analyzer as Analyze  # type: ignore

        Analyze.cosmic_fit(
            samples=sbs_path,
            output=f"{args.output_prefix}_sig",
            input_type="matrix",
            cosmic_version=float(args.cosmic_version),
        )
    except ImportError:
        logger.warning(
            "SigProfilerAssignment not installed and no --signatures_file given; skipping fitting"
        )


def run(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(prog="run_no_gt_report", description="Collect metrics for runs without ground truth")
    sub = ap.add_subparsers(dest="cmd", required=True)

    full = sub.add_parser("full_analysis", description="Run the full analysis of no_gt_report")
    full.add_argument("--input_file", required=True)
    full.add_argument("--dbsnp", required=True)
    full.add_argument("--reference", required=True)
    full.add_argument("--output_prefix", required=True)
    full.add_argument("--sample_id", type=int, default=0)
    full.add_argument("--sample_name", type=str, default=None)
    full.add_argument("--callable_region", type=str, default=None)
    full.set_defaults(func=run_full_analysis)

    ev = sub.add_parser("variant_eval", description="Run variant eval only")
    ev.add_argument("--input_file", required=True)
    ev.add_argument("--dbsnp", required=True)
    ev.add_argument("--reference", required=True)
    ev.add_argument("--output_prefix", required=True)
    ev.add_argument("--sample_name", type=str, default=None)
    ev.add_argument("--sample_id", type=int, default=None)
    ev.add_argument("--annotation_names", nargs="*", default=None)
    ev.set_defaults(func=run_eval_tables_only)

    som = sub.add_parser("somatic_analysis", description="Run mutation signatures and motif graphs")
    som.add_argument("--input_file", required=True)
    som.add_argument("--reference", required=True, help="Reference FASTA (for motif windows)")
    som.add_argument("--reference_name", type=str, default="GRCh38")
    som.add_argument("--output_prefix", required=True)
    som.add_argument("--cosmic_version", type=str, default="3.3")
    som.add_argument("--signatures_file", default=None,
                     help="COSMIC-style signature matrix (tsv) -> native device NNLS fitting")
    som.add_argument("--signatures_metadata", default=None,
                     help="cosmic_signatures json (descriptions/links) for annotation")
    som.set_defaults(func=run_somatic_analysis)

    args = ap.parse_args(argv)
    args.func(args)
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
