"""run_no_gt_report — callset statistics without ground truth.

Drop-in surface of the reference tool (ugvc/pipelines/run_no_gt_report.py:
598-664): subcommands ``full_analysis`` / ``variant_eval`` /
``somatic_analysis``. The GATK VariantEval subprocess is replaced by
in-process device reductions (reports/variant_eval); the SigProfiler
somatic stage reduces to the 96-channel SBS matrix (signature assignment
needs the external SigProfiler package and is gated on its presence).
Outputs the same HDF5 key layout (``ins_del_hete``, ``ins_del_homo``,
``af_hist``, ``snp_motifs``, ``eval_<Table>``, ``callable_size``) plus the
ID83/DBS78 channel spectra (``id83_channels``, ``dbs78_channels``) the
notebook's signature cells render alongside SBS96.
"""

from __future__ import annotations

import argparse
import sys

import numpy as np
import pandas as pd

from variantcalling_tpu import logger
from variantcalling_tpu.io.vcf import read_vcf
from variantcalling_tpu.reports import no_gt_stats
from variantcalling_tpu.reports.variant_eval import compute_eval_tables, dbsnp_membership
from variantcalling_tpu.utils.h5_utils import write_hdf


def _sample_index(table, sample_id: int | None, sample_name: str | None) -> int:
    if sample_name is not None and sample_name in table.header.samples:
        return table.header.samples.index(sample_name)
    return sample_id or 0


def run_full_analysis(args) -> None:
    out_h5 = f"{args.output_prefix}.h5"
    mode = "w"
    if args.callable_region is not None:
        from variantcalling_tpu.io.bed import read_bed

        size = read_bed(args.callable_region).total_length()
        write_hdf(pd.DataFrame({"callable_size": [size]}), out_h5, key="callable_size", mode=mode)
        mode = "a"

    table = read_vcf(args.input_file)
    sample = _sample_index(table, args.sample_id, args.sample_name)
    known = dbsnp_membership(table, args.dbsnp) if args.dbsnp else None
    eval_tables = compute_eval_tables(table, known=known, sample=sample)

    logger.info("annotating %d records", len(table))
    cols, windows, hmer_len, hmer_nuc = no_gt_stats._annotate(table, args.reference)

    logger.info("insertion/deletion statistics")
    ins_del = no_gt_stats.insertion_deletion_statistics(table, cols, hmer_len, hmer_nuc, sample=sample)

    logger.info("allele frequency histogram")
    vtype = no_gt_stats.variant_type_labels(cols, hmer_len)
    af = no_gt_stats._compute_af(table, sample=sample)  # shared with the scatters
    af_df = no_gt_stats.allele_freq_hist(table, vtype, sample=sample, af=af)

    logger.info("snp motif statistics")
    snp_motifs = no_gt_stats.snp_statistics(table, cols, windows)

    # ID83 / DBS78 channel spectra (notebook cells 24-27 render all three
    # COSMIC catalogs, not just SBS96 — the docs/report_parity.md gap):
    # same classifiers the somatic stage uses (reports/signatures.py)
    logger.info("ID83/DBS78 channel spectra")
    from variantcalling_tpu.io.fasta import FastaReader
    from variantcalling_tpu.reports import signatures as sigmod

    id83 = sigmod.id83_matrix(_indel_records(table), FastaReader(args.reference))
    dbs78 = sigmod.dbs78_matrix(table)

    write_hdf(ins_del["hete"].T.reset_index(names="hmer_len"), out_h5, key="ins_del_hete", mode=mode)
    write_hdf(ins_del["homo"].T.reset_index(names="hmer_len"), out_h5, key="ins_del_homo", mode="a")
    write_hdf(af_df, out_h5, key="af_hist", mode="a")
    motif_df = snp_motifs.reset_index()
    write_hdf(motif_df, out_h5, key="snp_motifs", mode="a")
    write_hdf(id83.rename_axis("channel").reset_index(), out_h5,
              key="id83_channels", mode="a")
    write_hdf(dbs78.rename_axis("channel").reset_index(), out_h5,
              key="dbs78_channels", mode="a")
    for name, tbl in eval_tables.items():
        write_hdf(tbl, out_h5, key=f"eval_{name}", mode="a")

    # notebook report_wo_gt "Variants Statistics" merged table + the two
    # per-variant AF scatters ("AF along genome positions", "AF vs depth"),
    # stored downsampled so the report h5 stays small at WGS scale
    vc = pd.Series(vtype).value_counts()
    vstats = vc.rename_axis("variant_type").reset_index(name="count")
    write_hdf(vstats, out_h5, key="variants_statistics", mode="a")
    dp = table.info_field("DP")
    if np.all(np.isnan(dp)):  # no INFO/DP: depth from the sample column,
        dp = table.format_numeric("DP", sample=sample, max_len=1,  # matching the AF source
                                  missing=np.nan)[:, 0]
    ok = ~np.isnan(af)
    idx = np.nonzero(ok)[0]
    if len(idx) > 50_000:  # even stride keeps the genome-position spread
        idx = idx[:: len(idx) // 50_000]
    scatter = pd.DataFrame({
        "chrom": np.asarray(table.chrom)[idx],
        "pos": table.pos[idx],
        "af": af[idx].astype(np.float32),
        "dp": dp[idx].astype(np.float32),
    })
    write_hdf(scatter, out_h5, key="af_scatter", mode="a")
    logger.info("wrote %s", out_h5)


def run_eval_tables_only(args) -> None:
    table = read_vcf(args.input_file)
    sample = _sample_index(table, args.sample_id, args.sample_name)
    known = dbsnp_membership(table, args.dbsnp) if args.dbsnp else None
    eval_tables = compute_eval_tables(table, known=known, sample=sample)
    mode = "w"
    for name, tbl in eval_tables.items():
        write_hdf(tbl, f"{args.output_prefix}.h5", key=f"eval_{name}", mode=mode)
        mode = "a"


def _indel_records(table):
    """(chrom, pos, REF, first-ALT) tuples for the ID83 classifier — the
    one place that encodes the first-allele + length-mismatch convention,
    shared by full_analysis and the somatic stage."""
    chrom = np.asarray(table.chrom)
    refs = np.asarray(table.ref)
    alts = np.asarray(table.alt)
    return ((chrom[i], int(table.pos[i]), refs[i].upper(),
             alts[i].split(",")[0].upper())
            for i in range(len(table))
            if len(refs[i]) != len(alts[i].split(",")[0]))


def _somatic_matrices(vcf_path: str, reference: str) -> dict[str, pd.Series]:
    """SBS96 + ID83 + DBS78 channel counts for one callset (the three
    catalogs the reference's SigProfiler stage generates,
    run_no_gt_report.py:334-595)."""
    from variantcalling_tpu.io.fasta import FastaReader
    from variantcalling_tpu.reports import signatures as sigmod

    table = read_vcf(vcf_path)
    cols, windows, _hmer_len, _hmer_nuc = no_gt_stats._annotate(table, reference)
    # adjacent-SNV pairs reclassify as doublets and leave SBS96 (the
    # SigProfilerMatrixGenerator convention: one catalog per mutation)
    dbs, paired = sigmod.dbs78_matrix(table, return_paired=True)
    snp_motifs = no_gt_stats.snp_statistics(table, cols, windows, exclude=paired)
    sbs = pd.Series(snp_motifs.values,
                    index=[f"{m[0]}[{m[1]}>{a}]{m[2]}" for (m, a) in snp_motifs.index],
                    name="size")
    return {
        "SBS96": sbs,
        "ID83": sigmod.id83_matrix(_indel_records(table), FastaReader(reference)),
        "DBS78": dbs,
    }


def _unique_name(base: str, taken: set[str]) -> str:
    """Basename-derived sample names must not collide (two control paths
    with the same filename would silently overwrite each other)."""
    name, k = base, 2
    while name in taken:
        name = f"{base}_{k}"
        k += 1
    return name


def _fit_catalog(counts_by_sample: dict[str, pd.Series], catalog_path: str,
                 metadata: dict | None, catalog_name: str) -> pd.DataFrame:
    """Device KL-NNLS exposures for every sample against one catalog."""
    from variantcalling_tpu.reports import signatures as sigmod

    catalog = sigmod.load_signature_matrix(catalog_path)
    samples = list(counts_by_sample)
    labels = list(next(iter(counts_by_sample.values())).index)
    catalog = catalog.reindex(labels).fillna(0.0)  # align channel order
    mat = np.stack([counts_by_sample[s].values for s in samples])
    exposures = sigmod.sparsify_exposures(
        sigmod.fit_signatures(mat, catalog.to_numpy()))
    tbl = sigmod.assignment_table(exposures, list(catalog.columns), metadata, samples)
    tbl.insert(1, "catalog", catalog_name)
    return tbl


def run_somatic_analysis(args) -> None:
    """SBS96 + ID83 + DBS78 matrices, device NNLS fitting per catalog, and
    an optional control cohort (reference cells: control signature
    analysis — exposures for every control plus a case-vs-control
    enrichment table)."""
    prefix_name = args.output_prefix.split("/")[-1]
    case = _somatic_matrices(args.input_file, args.reference)
    controls = {}
    for path in (getattr(args, "control_vcfs", None) or []):
        name = _unique_name(
            path.split("/")[-1].removesuffix(".gz").removesuffix(".vcf"),
            set(controls) | {prefix_name})
        controls[name] = _somatic_matrices(path, args.reference)

    out_h5 = f"{args.output_prefix}.h5"
    h5_mode = "a"
    for cat in ("SBS96", "ID83", "DBS78"):
        df = pd.DataFrame({"MutationType": list(case[cat].index),
                           prefix_name: case[cat].values})
        for name, mats in controls.items():
            df[name] = mats[cat].values
        path = f"{args.output_prefix}.{cat}.all"
        df.to_csv(path, sep="\t", index=False)
        logger.info("wrote %s matrix: %s", cat, path)

    catalog_paths = {
        "SBS96": getattr(args, "signatures_file", None),
        "ID83": getattr(args, "id_signatures_file", None),
        "DBS78": getattr(args, "dbs_signatures_file", None),
    }
    if any(catalog_paths.values()):
        from variantcalling_tpu.reports import signatures as sigmod

        meta = (sigmod.load_signature_metadata(args.signatures_metadata)
                if getattr(args, "signatures_metadata", None) else None)
        tables = []
        for cat, cpath in catalog_paths.items():
            if not cpath:
                continue
            by_sample = {prefix_name: case[cat]}
            by_sample.update({name: mats[cat] for name, mats in controls.items()})
            tables.append(_fit_catalog(by_sample, cpath, meta, cat))
        tbl = pd.concat(tables, ignore_index=True)
        write_hdf(tbl, out_h5, key="signature_exposures", mode=h5_mode)
        logger.info("fitted exposures over %d catalog(s), %d sample(s)",
                    len(tables), 1 + len(controls))
        if controls:
            # case-vs-control enrichment: fraction of mutations per
            # signature in the case against the control-cohort mean
            frac = tbl.pivot_table(index=["catalog", "signature"],
                                   columns="sample", values="fraction",
                                   fill_value=0.0)
            ctrl_cols = [c for c in frac.columns if c != prefix_name]
            case_frac = frac.get(prefix_name, pd.Series(0.0, index=frac.index))
            ctrl_mean = frac[ctrl_cols].mean(axis=1)
            cmp_tbl = pd.DataFrame({
                "case_fraction": case_frac,
                "control_mean_fraction": ctrl_mean,
                "enrichment": case_frac / ctrl_mean.clip(lower=1e-9),
            }).reset_index()
            write_hdf(cmp_tbl, out_h5, key="signature_control_comparison", mode="a")
        return
    try:  # optional external signature assignment (reference :334-595)
        from SigProfilerAssignment import Analyzer as Analyze  # type: ignore

        Analyze.cosmic_fit(
            samples=f"{args.output_prefix}.SBS96.all",
            output=f"{args.output_prefix}_sig",
            input_type="matrix",
            cosmic_version=float(args.cosmic_version),
        )
    except ImportError:
        logger.warning(
            "SigProfilerAssignment not installed and no --signatures_file given; skipping fitting"
        )


def run(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(prog="run_no_gt_report", description="Collect metrics for runs without ground truth")
    sub = ap.add_subparsers(dest="cmd", required=True)

    full = sub.add_parser("full_analysis", description="Run the full analysis of no_gt_report")
    full.add_argument("--input_file", required=True)
    full.add_argument("--dbsnp", required=True)
    full.add_argument("--reference", required=True)
    full.add_argument("--output_prefix", required=True)
    full.add_argument("--sample_id", type=int, default=0)
    full.add_argument("--sample_name", type=str, default=None)
    full.add_argument("--callable_region", type=str, default=None)
    full.set_defaults(func=run_full_analysis)

    ev = sub.add_parser("variant_eval", description="Run variant eval only")
    ev.add_argument("--input_file", required=True)
    ev.add_argument("--dbsnp", required=True)
    ev.add_argument("--reference", required=True)
    ev.add_argument("--output_prefix", required=True)
    ev.add_argument("--sample_name", type=str, default=None)
    ev.add_argument("--sample_id", type=int, default=None)
    ev.add_argument("--annotation_names", nargs="*", default=None)
    ev.set_defaults(func=run_eval_tables_only)

    som = sub.add_parser("somatic_analysis", description="Run mutation signatures and motif graphs")
    som.add_argument("--input_file", required=True)
    som.add_argument("--reference", required=True, help="Reference FASTA (for motif windows)")
    som.add_argument("--reference_name", type=str, default="GRCh38")
    som.add_argument("--output_prefix", required=True)
    som.add_argument("--cosmic_version", type=str, default="3.3")
    som.add_argument("--signatures_file", default=None,
                     help="COSMIC-style SBS96 signature matrix (tsv) -> native device NNLS fitting")
    som.add_argument("--id_signatures_file", default=None,
                     help="COSMIC ID83 signature matrix (tsv)")
    som.add_argument("--dbs_signatures_file", default=None,
                     help="COSMIC DBS78 signature matrix (tsv)")
    som.add_argument("--signatures_metadata", default=None,
                     help="cosmic_signatures json (descriptions/links) for annotation")
    som.add_argument("--control_vcfs", nargs="*", default=None,
                     help="control-cohort VCFs: exposures fitted per control plus a "
                          "case-vs-control enrichment table (signature_control_comparison)")
    som.set_defaults(func=run_somatic_analysis)

    args = ap.parse_args(argv)
    args.func(args)
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
