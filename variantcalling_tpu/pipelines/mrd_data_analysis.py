"""mrd_data_analysis — automatic MRD analysis report.

Reference surface: ugvc/reports/mrd_automatic_data_analysis.ipynb (the
ugbio_mrd reporting layer). Consumes the mrd_analysis summary h5 (tumor
fraction + CI + detection call) and, when given the scored featuremap,
adds ML_QUAL distributions for on- vs off-signature reads. Emits h5
sections + self-contained HTML.
"""

from __future__ import annotations

import argparse
import sys

import numpy as np
import pandas as pd

from variantcalling_tpu import logger
from variantcalling_tpu.reports.html import HtmlReport, add_figure_safe
from variantcalling_tpu.utils.h5_utils import read_hdf, write_hdf


def parse_args(argv):
    ap = argparse.ArgumentParser(prog="mrd_data_analysis", description=run.__doc__)
    ap.add_argument("--mrd_summary_h5", required=True, help="mrd_analysis output")
    ap.add_argument("--featuremap", default=None, help="scored featuremap (srsnv_inference)")
    ap.add_argument("--signature_vcf", default=None)
    ap.add_argument("--h5_output", default="mrd_report.h5")
    ap.add_argument("--html_output", required=True)
    return ap.parse_args(argv)


def qual_distributions(featuremap: str, signature_vcf: str | None) -> pd.DataFrame:
    from variantcalling_tpu.io.vcf import read_vcf

    fm = read_vcf(featuremap)
    qual = fm.info_field("ML_QUAL")
    on_sig = np.zeros(len(fm), dtype=bool)
    if signature_vcf:
        sig = read_vcf(signature_vcf)
        loci = {(c, int(p)) for c, p in zip(sig.chrom, sig.pos)}
        on_sig = np.fromiter(
            ((c, int(p)) in loci for c, p in zip(fm.chrom, fm.pos)), dtype=bool, count=len(fm)
        )
    bins = np.arange(0, 65, 5)
    rows = []
    for name, mask in (("on_signature", on_sig), ("off_signature", ~on_sig)):
        q = qual[mask & ~np.isnan(qual)]
        hist, _ = np.histogram(q, bins=bins)
        for lo, n in zip(bins[:-1], hist):
            rows.append({"population": name, "ml_qual_bin": int(lo), "n_reads": int(n)})
    return pd.DataFrame(rows)


def run(argv) -> int:
    """Render the automatic MRD analysis report."""
    args = parse_args(argv)
    summary = read_hdf(args.mrd_summary_h5, key="mrd_summary")
    rep = HtmlReport("MRD Automatic Data Analysis")
    rep.add_section("Tumor fraction estimate")
    rep.add_table(summary)
    row = summary.iloc[0]
    rep.add_text(
        f"MRD {'DETECTED' if bool(row['mrd_detected']) else 'not detected'}: "
        f"tumor fraction {row['tumor_fraction']:.3g} "
        f"[{row['tf_ci_low']:.3g}, {row['tf_ci_high']:.3g}] from "
        f"{int(row['n_supporting_reads'])} supporting reads over "
        f"{int(row['n_signature_loci'])} signature loci."
    )
    write_hdf(summary, args.h5_output, key="mrd_summary", mode="w")
    if args.featuremap:
        dist = qual_distributions(args.featuremap, args.signature_vcf)
        rep.add_section("ML_QUAL distribution (on vs off signature)")
        piv = dist.pivot(index="ml_qual_bin", columns="population", values="n_reads")
        rep.add_table(piv)

        def _qual_fig(plt):
            fig, ax = plt.subplots(figsize=(7, 3))
            piv.plot.bar(ax=ax)
            ax.set_xlabel("ML_QUAL bin")
            ax.set_ylabel("# reads")
            ax.set_yscale("symlog")
            return fig

        add_figure_safe(rep, _qual_fig, "ML_QUAL figure")
        write_hdf(dist, args.h5_output, key="ml_qual_distribution", mode="a")
    rep.write(args.html_output)
    logger.info("MRD report -> %s", args.html_output)
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
