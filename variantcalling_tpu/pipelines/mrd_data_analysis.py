"""mrd_data_analysis — automatic MRD analysis report.

Reference surface: ugvc/reports/mrd_automatic_data_analysis.ipynb (the
ugbio_mrd reporting layer), section by section:

- Filters applied (cells 8-9): human-readable read/signature filter
  terms -> ``filters_applied``
- Matched signature analysis: mutation types (cell 12) ->
  ``mutation_types``; allele fractions (cell 15) -> ``allele_fractions``
- Tumor fractions (cells 18-29): filtered/unfiltered reads x
  filtered/unfiltered signature -> the notebook's six h5 keys
  (``df_tf_*`` + ``df_supporting_reads_per_locus_*``)
- ML_QUAL distribution for on- vs off-signature reads (framework
  addition; the notebook's X_SCORE likelihood section analog)
- cfDNA read length distributions (cells 35-36) -> ``read_lengths``

Consumes the mrd_analysis summary h5 (tumor fraction + CI + detection
call) and, when given the scored featuremap + signature, computes the
sections above from the columnar INFO tensors. Emits h5 sections +
self-contained HTML.
"""

from __future__ import annotations

import argparse
import re
import sys

import numpy as np
import pandas as pd

from variantcalling_tpu import logger
from variantcalling_tpu.reports.html import HtmlReport, add_figure_safe
from variantcalling_tpu.utils.h5_utils import read_hdf, write_hdf

# notebook cell 9's filter glossary
FILTER_DESCRIPTIONS = {
    "ug_hcr": "In UG High Confidence Region",
    "giab_hcr": "In GIAB (HG001-007) High Confidence Region",
    "ug_mrd_blacklist": "Not in UG MRD Blacklist",
    "id": "Not in dbsnp",
    "af": "Allele fraction filter",
    "filtering_ratio": "Minimum ratio of reads passing read filters in locus",
    "norm_coverage": "Filtering by coverage, normalized to median",
    "X_SCORE": "Filtering by log likelihood score (effective BQ)",
    "X_EDIST": "Filtering by edit distance from the reference",
    "max_softclip_len": "Filtering by maximal softclip length",
    "X_LENGTH": "Filtering by fragment length",
    "rq": "Filtering by read quality",
    "ML_QUAL": "Filtering by single-read model quality",
}


def parse_args(argv):
    ap = argparse.ArgumentParser(prog="mrd_data_analysis", description=run.__doc__)
    ap.add_argument("--mrd_summary_h5", required=True, help="mrd_analysis output")
    ap.add_argument("--featuremap", default=None, help="scored featuremap (srsnv_inference)")
    ap.add_argument("--signature_vcf", default=None)
    ap.add_argument("--control_signature_vcfs", nargs="*", default=None,
                    help="control signature VCFs (notebook cells 30-34: the "
                         "signature_type != 'matched' analyses) — mutation-type "
                         "and allele-fraction sections per control")
    ap.add_argument("--read_filter_query", default=None,
                    help="pandas query over featuremap INFO columns (e.g. 'ML_QUAL >= 40')")
    ap.add_argument("--signature_filter_query", default=None,
                    help="pandas query over signature INFO columns (e.g. 'AF >= 0.05')")
    ap.add_argument("--coverage_per_locus", type=float, default=None,
                    help="tumor-fraction denominator per locus (defaults from the summary h5)")
    ap.add_argument("--read_pass_fraction", type=float, default=1.0,
                    help="SRSNV test-set read pass fraction (denominator correction, notebook cell 20)")
    ap.add_argument("--h5_output", default="mrd_report.h5")
    ap.add_argument("--html_output", required=True)
    return ap.parse_args(argv)


def _query_identifiers(query: str) -> list[str]:
    keywords = {"and", "or", "not", "in", "True", "False"}
    return [t for t in dict.fromkeys(re.findall(r"[A-Za-z_][A-Za-z0-9_.]*", query))
            if t not in keywords and not t[0].isdigit()]


def describe_filters(query: str) -> pd.DataFrame:
    """Notebook cells 8-9: one row per filter term with its description."""
    rows = []
    for term in re.split(r"\band\b", query.replace("(", "").replace(")", "")):
        term = term.strip()
        if not term:
            continue
        name = re.split(r"[<>=!\s]", term.removeprefix("not").strip())[0].strip()
        rows.append({"query": term,
                     "description": FILTER_DESCRIPTIONS.get(name, "<Description unavailable>")})
    return pd.DataFrame(rows)


def _info_frame(table, query: str | None, extra: tuple[str, ...] = ()) -> pd.DataFrame:
    """Featuremap/signature VCF -> DataFrame of the INFO columns a query
    (plus standard report columns) needs."""
    fields = set(extra)
    if query:
        fields.update(_query_identifiers(query))
    df = pd.DataFrame({"chrom": np.asarray(table.chrom), "pos": table.pos})
    for f in sorted(fields - {"chrom", "pos"}):  # never clobber the locus columns
        df[f] = table.info_field(f)
    return df


def _apply_query(df: pd.DataFrame, query: str | None) -> pd.Series:
    if not query:
        return pd.Series(True, index=df.index)
    try:
        return df.eval(query).fillna(False).astype(bool)
    except Exception as e:
        # silently passing everything would publish UNFILTERED numbers
        # under the "filtered" h5 keys of a clinical report — hard error
        raise ValueError(f"filter query {query!r} failed: {e}") from e


def _loci_mask(fm_df: pd.DataFrame, loci: set[tuple]) -> pd.Series:
    """Vectorized (chrom, pos) membership — the per-read loop version cost
    minutes on WGS featuremaps."""
    if not loci:
        return pd.Series(False, index=fm_df.index)
    mi = pd.MultiIndex.from_arrays([fm_df["chrom"], fm_df["pos"].astype(int)])
    return pd.Series(mi.isin(list(loci)), index=fm_df.index)


def mutation_type_counts(sig) -> pd.DataFrame:
    """ref>alt counts over the signature (notebook 'Mutation types')."""
    refs = np.asarray([r.upper() if len(r) == 1 else "." for r in sig.ref])
    alts = np.asarray([a.split(",")[0].upper() if a and len(a.split(",")[0]) == 1 else "."
                       for a in sig.alt])
    ok = (refs != ".") & (alts != ".")
    pairs = pd.Series([f"{r}>{a}" for r, a in zip(refs[ok], alts[ok])])
    if not len(pairs):
        return pd.DataFrame(columns=["mutation", "count", "fraction"])
    out = pairs.value_counts().rename_axis("mutation").reset_index(name="count")
    out["fraction"] = out["count"] / max(int(out["count"].sum()), 1)
    return out


def af_histogram(sig, nbins: int = 50) -> pd.DataFrame:
    af = sig.info_field("AF")
    af = af[~np.isnan(af)]
    hist, edges = np.histogram(af, bins=np.linspace(0, 1, nbins + 1))
    return pd.DataFrame({"af_bin_low": edges[:-1].round(4), "n_variants": hist})


def qual_distributions(fm_df: pd.DataFrame, matched: pd.Series) -> pd.DataFrame:
    bins = np.arange(0, 65, 5)
    rows = []
    qual = fm_df.get("ML_QUAL", pd.Series(np.nan, index=fm_df.index))
    for name, mask in (("on_signature", matched), ("off_signature", ~matched)):
        q = qual[mask & qual.notna()]
        hist, _ = np.histogram(q, bins=bins)
        rows.extend({"population": name, "ml_qual_bin": int(lo), "n_reads": int(n)}
                    for lo, n in zip(bins[:-1], hist))
    return pd.DataFrame(rows)


def tumor_fraction_tables(fm_df: pd.DataFrame, sig_df: pd.DataFrame,
                          read_query: str | None, sig_query: str | None,
                          denominator_per_locus: float,
                          pass_fraction: float) -> dict[str, pd.DataFrame]:
    """The notebook's six h5 tables (cell 29): tumor fraction and
    per-locus supporting-read counts for (filtered reads x filtered
    signature), (unfiltered reads x filtered signature), (filtered reads
    x unfiltered signature).

    tf = supporting reads / (loci * coverage * read-pass-fraction)
    (cell 20's denominator correction).
    """
    read_pass = _apply_query(fm_df, read_query)
    sig_pass = _apply_query(sig_df, sig_query)
    sig_loci_all = set(zip(sig_df["chrom"], sig_df["pos"].astype(int)))
    sig_loci_filt = set(zip(sig_df.loc[sig_pass, "chrom"], sig_df.loc[sig_pass, "pos"].astype(int)))
    on_filt = _loci_mask(fm_df, sig_loci_filt)
    on_all = _loci_mask(fm_df, sig_loci_all)

    all_reads = pd.Series(True, index=fm_df.index)
    # key halves name (signature filter state, featuremap/read filter state)
    combos = {
        "filt_signature_filt_featuremap": (read_pass, on_filt, sig_loci_filt),
        "unfilt_signature_filt_featuremap": (read_pass, on_all, sig_loci_all),
        "filt_signature_unfilt_featuremap": (all_reads, on_filt, sig_loci_filt),
    }
    out: dict[str, pd.DataFrame] = {}
    for tag, (rmask, on, loci) in combos.items():
        support = fm_df[on & rmask]
        per_locus = (support.groupby(["chrom", "pos"]).size().rename("n_supporting_reads")
                     .reset_index()) if len(support) else \
            pd.DataFrame(columns=["chrom", "pos", "n_supporting_reads"])
        denom = max(len(loci), 1) * max(denominator_per_locus, 1e-12) * max(pass_fraction, 1e-12)
        tf = len(support) / denom
        out[f"df_tf_{tag}"] = pd.DataFrame(
            [{"signature_type": "matched", "n_loci": len(loci),
              "n_supporting_reads": len(support), "tf": tf}])
        out[f"df_supporting_reads_per_locus_{tag}"] = per_locus
    return out


def read_length_table(fm_df: pd.DataFrame, matched: pd.Series,
                      read_query: str | None) -> pd.DataFrame | None:
    """Notebook cells 35-36: X_LENGTH histograms for matched/unmatched x
    unfiltered/filtered reads."""
    if "X_LENGTH" not in fm_df.columns or fm_df["X_LENGTH"].notna().sum() == 0:
        return None
    read_pass = _apply_query(fm_df, read_query)
    length = fm_df["X_LENGTH"]
    top = int(max(250, np.nanmax(length))) + 1
    bins = np.arange(0, top + 10, 10)
    rows = []
    for name, mask in (
        ("matched_unfiltered", matched),
        ("matched_filtered", matched & read_pass),
        ("unmatched_unfiltered", ~matched),
        ("unmatched_filtered", ~matched & read_pass),
    ):
        vals = length[mask & length.notna()]
        hist, _ = np.histogram(vals, bins=bins)
        rows.extend({"population": name, "length_bin_low": int(lo), "n_reads": int(n)}
                    for lo, n in zip(bins[:-1], hist) if n or name.startswith("matched"))
    return pd.DataFrame(rows)


def run(argv) -> int:
    """Render the automatic MRD analysis report."""
    args = parse_args(argv)
    summary = read_hdf(args.mrd_summary_h5, key="mrd_summary")
    rep = HtmlReport("MRD Automatic Data Analysis")
    mode = "w"

    def save(df: pd.DataFrame, key: str) -> None:
        nonlocal mode
        write_hdf(df, args.h5_output, key=key, mode=mode)
        mode = "a"

    # --- filters applied (cells 8-9) --------------------------------------
    if args.read_filter_query or args.signature_filter_query:
        rep.add_section("Filters applied")
        tabs = []
        for label, q in (("signature", args.signature_filter_query),
                         ("reads", args.read_filter_query)):
            if q:
                t = describe_filters(q)
                t.insert(0, "applies_to", label)
                tabs.append(t)
        filters = pd.concat(tabs, ignore_index=True)
        rep.add_table(filters)
        save(filters, "filters_applied")

    # --- tumor fraction summary (cells 18-19) -----------------------------
    rep.add_section("Tumor fraction estimate")
    rep.add_table(summary)
    row = summary.iloc[0]
    rep.add_text(
        f"MRD {'DETECTED' if bool(row['mrd_detected']) else 'not detected'}: "
        f"tumor fraction {row['tumor_fraction']:.3g} "
        f"[{row['tf_ci_low']:.3g}, {row['tf_ci_high']:.3g}] from "
        f"{int(row['n_supporting_reads'])} supporting reads over "
        f"{int(row['n_signature_loci'])} signature loci."
    )
    save(summary, "mrd_summary")

    fm_df = sig = None
    if args.featuremap:
        from variantcalling_tpu.io.vcf import read_vcf

        fm = read_vcf(args.featuremap)
        fm_df = _info_frame(fm, args.read_filter_query, extra=("ML_QUAL", "X_LENGTH"))
        if args.signature_vcf:
            sig = read_vcf(args.signature_vcf)

    matched = pd.Series(False, index=fm_df.index) if fm_df is not None else None
    if sig is not None and fm_df is not None:
        sig_df = _info_frame(sig, args.signature_filter_query, extra=("AF",))
        matched = _loci_mask(fm_df, set(zip(sig_df["chrom"], sig_df["pos"].astype(int))))

        # --- matched signature analysis (cells 10-15) ---------------------
        mut = mutation_type_counts(sig)
        if len(mut):
            rep.add_section("Matched signature — mutation types")
            rep.add_table(mut)

            def _mut_fig(plt):
                fig, ax = plt.subplots(figsize=(7, 3))
                ax.bar(mut["mutation"], mut["count"])
                ax.set_ylabel("# mutations")
                return fig

            add_figure_safe(rep, _mut_fig, "mutation types figure")
            save(mut, "mutation_types")
        afh = af_histogram(sig)
        if afh["n_variants"].sum():
            rep.add_section("Matched signature — allele fractions")

            def _af_fig(plt):
                fig, ax = plt.subplots(figsize=(7, 3))
                ax.bar(afh["af_bin_low"], afh["n_variants"], width=0.018)
                ax.set_xlabel("Allele fraction")
                ax.set_ylabel("# variants")
                return fig

            add_figure_safe(rep, _af_fig, "AF figure")
            save(afh, "allele_fractions")

        # --- control signature analyses (cells 30-34): the notebook
        # repeats the mutation-type and allele-fraction sections for every
        # signature with signature_type != 'matched' ----------------------
        seen_names: set[str] = set()
        for path in (args.control_signature_vcfs or []):
            base = path.split("/")[-1].removesuffix(".gz").removesuffix(".vcf")
            name, k = base, 2
            while name in seen_names:  # same filename from two dirs
                name = f"{base}_{k}"
                k += 1
            seen_names.add(name)
            ctrl = read_vcf(path)
            cmut = mutation_type_counts(ctrl)
            if len(cmut):
                rep.add_section(f"Control signature '{name}' — mutation types")
                rep.add_table(cmut)
                save(cmut.assign(signature=name), f"mutation_types_{name}")
            cafh = af_histogram(ctrl)
            if cafh["n_variants"].sum():
                rep.add_section(f"Control signature '{name}' — allele fractions")

                def _caf_fig(plt, _h=cafh):
                    fig, ax = plt.subplots(figsize=(7, 3))
                    ax.bar(_h["af_bin_low"], _h["n_variants"], width=0.018)
                    ax.set_xlabel("Allele fraction")
                    ax.set_ylabel("# variants")
                    return fig

                add_figure_safe(rep, _caf_fig, f"AF figure ({name})")
                save(cafh.assign(signature=name), f"allele_fractions_{name}")

        # --- tumor fractions, filtered x unfiltered (cells 19-29) ---------
        denom = args.coverage_per_locus or float(row.get("coverage_per_locus", 1.0) or 1.0)
        tf_tables = tumor_fraction_tables(fm_df, sig_df, args.read_filter_query,
                                          args.signature_filter_query, denom,
                                          args.read_pass_fraction)
        rep.add_section("Tumor fractions (filtered/unfiltered reads and signature)")
        tf_summary = pd.concat([t.assign(variant=k.removeprefix("df_tf_"))
                                for k, t in tf_tables.items() if k.startswith("df_tf_")],
                               ignore_index=True)
        rep.add_table(tf_summary)
        for key, tab in tf_tables.items():
            save(tab, key)

    if fm_df is not None:
        # --- ML_QUAL on/off signature -------------------------------------
        dist = qual_distributions(fm_df, matched)
        if dist["n_reads"].sum():
            rep.add_section("ML_QUAL distribution (on vs off signature)")
            piv = dist.pivot(index="ml_qual_bin", columns="population", values="n_reads")
            rep.add_table(piv)

            def _qual_fig(plt):
                fig, ax = plt.subplots(figsize=(7, 3))
                piv.plot.bar(ax=ax)
                ax.set_xlabel("ML_QUAL bin")
                ax.set_ylabel("# reads")
                ax.set_yscale("symlog")
                return fig

            add_figure_safe(rep, _qual_fig, "ML_QUAL figure")
            save(dist, "ml_qual_distribution")

        # --- read length distributions (cells 35-36) ----------------------
        rl = read_length_table(fm_df, matched, args.read_filter_query)
        if rl is not None and len(rl):
            rep.add_section("cfDNA read length distributions")

            def _rl_fig(plt):
                fig, axs = plt.subplots(2, 2, figsize=(11, 5), sharex=True)
                for ax, pop in zip(axs.flatten(), rl["population"].unique()):
                    sub = rl[rl["population"] == pop]
                    ax.bar(sub["length_bin_low"], sub["n_reads"], width=9)
                    ax.set_title(pop, fontsize=9)
                for ax in axs[-1, :]:
                    ax.set_xlabel("Read length")
                fig.tight_layout()
                return fig

            add_figure_safe(rep, _rl_fig, "read length figure")
            save(rl, "read_lengths")

    rep.write(args.html_output)
    logger.info("MRD report -> %s", args.html_output)
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
