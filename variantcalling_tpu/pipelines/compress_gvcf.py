"""compress_gvcf — shrink a gVCF by merging similar sequential records.

Drop-in surface of the reference tool (ugvc/joint/compress_gvcf.py:64-216):
``--input_path/--output_path/--refcall_gq_threshold/--merge_gq_threshold``.
Core algorithm in :mod:`variantcalling_tpu.joint.gvcf` (vectorized PL
collapse + one merge scan over columnar arrays).
"""

from __future__ import annotations

import argparse
import sys

from variantcalling_tpu.joint.gvcf import compress_gvcf


def parse_args(argv: list[str]):
    ap = argparse.ArgumentParser(prog="compress_gvcf", description="Compress GVCF file by merging similar rows")
    ap.add_argument("--input_path", required=True, help="Input gvcf file path")
    ap.add_argument("--output_path", required=True, help="Output gvcf file path")
    ap.add_argument(
        "--refcall_gq_threshold",
        type=int,
        default=22,
        help="Keep RefCall records with GQ<refcall_threshold and not merge them",
    )
    ap.add_argument(
        "--merge_gq_threshold",
        type=int,
        default=10,
        help="Merge records whose GQ stays within this band of the group",
    )
    return ap.parse_args(argv)


def run(argv: list[str]):
    args = parse_args(argv)
    n_in, n_out = compress_gvcf(args.input_path, args.output_path, args.refcall_gq_threshold, args.merge_gq_threshold)
    sys.stderr.write(f"Compressed {n_in} into {n_out} records\n")
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
