"""create_sv_report — SV accuracy report from sv_stats_collect results.

Reference surface: ugvc/reports/createSVReport.ipynb (papermill). Consumes
the pickled results dict of sv_stats_collect (keys: type_counts,
size_histograms, concordance stats per svtype/length-bin, fp_stats) and
emits the same artifact set directly: section tables in h5 + HTML.
"""

from __future__ import annotations

import argparse
import pickle
import sys

import numpy as np
import pandas as pd

from variantcalling_tpu import logger
from variantcalling_tpu.reports.html import HtmlReport
from variantcalling_tpu.utils.h5_utils import write_hdf


def parse_args(argv):
    ap = argparse.ArgumentParser(prog="create_sv_report", description=run.__doc__)
    ap.add_argument("--statistics_file", required=True, help="sv_stats_collect pickle")
    ap.add_argument("--run_id", default="NA")
    ap.add_argument("--pipeline_version", default="NA")
    ap.add_argument("--reference_version", default="hg38")
    ap.add_argument("--truth_sample_name", default="NA")
    ap.add_argument("--h5_output", default="sv_report.h5")
    ap.add_argument("--html_output", default=None)
    return ap.parse_args(argv)


def run(argv) -> int:
    """Generate the SV report (h5 sections + optional HTML)."""
    args = parse_args(argv)
    with open(args.statistics_file, "rb") as fh:
        results = pickle.load(fh)
    sv_stats = results.get("sv_stats", results if isinstance(results, dict) else {})
    concordance = results.get("concordance_stats", {})
    fp_stats = results.get("fp_stats", pd.Series(dtype="int64"))

    rep = HtmlReport("SV Report")
    rep.add_params(
        {
            "run_id": args.run_id,
            "pipeline_version": args.pipeline_version,
            "reference_version": args.reference_version,
            "truth_sample_name": args.truth_sample_name,
            "statistics_file": args.statistics_file,
        }
    )
    mode = "w"
    if "type_counts" in sv_stats:
        tc = pd.DataFrame(sv_stats["type_counts"]).T if isinstance(sv_stats["type_counts"], dict) else pd.DataFrame(sv_stats["type_counts"])
        rep.add_section("SV type counts")
        rep.add_table(tc)
        write_hdf(tc.reset_index(), args.h5_output, key="type_counts", mode=mode)
        mode = "a"
    if "size_histograms" in sv_stats:
        sh = sv_stats["size_histograms"]
        sh = pd.DataFrame(sh) if not isinstance(sh, pd.DataFrame) else sh
        rep.add_section("SV size histograms")
        rep.add_table(sh)
        write_hdf(sh.reset_index(), args.h5_output, key="size_histograms", mode=mode)
        mode = "a"
    if concordance:
        conc_rows = {k: v for k, v in concordance.items() if isinstance(v, pd.Series)}
        if conc_rows:
            conc = pd.DataFrame(conc_rows).T
            rep.add_section("Concordance vs ground truth")
            rep.add_table(conc)
            write_hdf(conc.reset_index(), args.h5_output, key="concordance", mode=mode)
            mode = "a"
    if len(fp_stats):
        rep.add_section("False positives by type and size")
        fp_df = fp_stats.rename("count").reset_index()
        fp_df = fp_df.astype({c: str for c in fp_df.columns if fp_df[c].dtype == "category"})
        rep.add_table(fp_df)
        write_hdf(fp_df, args.h5_output, key="fp_stats", mode=mode)
        mode = "a"
    if args.html_output:
        rep.write(args.html_output)
    logger.info("SV report -> %s%s", args.h5_output, f" + {args.html_output}" if args.html_output else "")
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
