"""create_sv_report — SV accuracy report from sv_stats_collect results.

Reference surface: ugvc/reports/createSVReport.ipynb (papermill). Consumes
the pickled results dict of sv_stats_collect and emits the notebook's full
artifact set directly — h5 keys ``parameters`` / ``type_counts`` /
``length_counts`` / ``length_by_type_counts`` / ``concordance`` /
``recall_per_length_and_type`` / ``fp_counts_per_length_and_type`` plus
the figure set (type pie, log-scale length bars, per-category PR-ROC
grid, recall and FP bars) and an HTML summary.
"""

from __future__ import annotations

import argparse
import os
import pickle
import sys

import numpy as np
import pandas as pd

from variantcalling_tpu import logger
from variantcalling_tpu.reports.html import HtmlReport
from variantcalling_tpu.utils.h5_utils import write_hdf

SV_TYPE_ORDER = ["CNV", "DEL", "INS", "DUP", "BND"]  # notebook cell 19


def parse_args(argv):
    ap = argparse.ArgumentParser(prog="create_sv_report", description=run.__doc__)
    ap.add_argument("--statistics_file", required=True, help="sv_stats_collect pickle")
    ap.add_argument("--run_id", default="NA")
    ap.add_argument("--pipeline_version", default="NA")
    ap.add_argument("--reference_version", default="hg38")
    ap.add_argument("--truth_sample_name", default="NA")
    ap.add_argument("--h5_output", default="sv_report.h5")
    ap.add_argument("--html_output", default=None)
    ap.add_argument("--plot_dir", default=None, help="directory for figure PNGs")
    return ap.parse_args(argv)


def _plots_dir(args):
    d = args.plot_dir
    if d is None and args.html_output:
        d = os.path.splitext(args.html_output)[0] + "_figs"
    if d:
        os.makedirs(d, exist_ok=True)
    return d


def _save(fig, plots, name, rep):
    import matplotlib.pyplot as plt

    rep.add_figure(fig)  # base64-embedded in the standalone HTML
    if plots:
        fig.savefig(os.path.join(plots, name), dpi=120, bbox_inches="tight")
    plt.close(fig)


def run(argv) -> int:
    """Generate the SV report (h5 sections + figures + optional HTML)."""
    args = parse_args(argv)
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    with open(args.statistics_file, "rb") as fh:
        results = pickle.load(fh)
    sv_stats = results.get("sv_stats", results if isinstance(results, dict) else {})
    concordance = results.get("concordance")
    fp_stats = results.get("fp_stats", pd.Series(dtype="int64"))
    plots = _plots_dir(args)

    rep = HtmlReport("SV/CNV Calling report")
    params = {
        "statistics_file": os.path.basename(args.statistics_file),
        "run_id": args.run_id,
        "reference_version": args.reference_version,
        "pipeline_version": args.pipeline_version,
        "truth_sample_name": args.truth_sample_name,
        "h5outfile": args.h5_output,
    }
    rep.add_params(params)
    params_df = pd.DataFrame.from_dict(params, orient="index", columns=["value"])
    write_hdf(params_df.reset_index(), args.h5_output, key="parameters", mode="w")

    # --- general statistics (notebook cells 9-20) -------------------------
    if "type_counts" in sv_stats:
        tc = pd.DataFrame(sv_stats["type_counts"]).T
        rep.add_section("SV type distribution")
        rep.add_table(tc)
        write_hdf(tc.reset_index(), args.h5_output, key="type_counts", mode="a")
        fig, ax = plt.subplots(subplot_kw={"aspect": "equal"})
        ax.pie(tc.values[0], labels=[str(c) for c in tc.columns], autopct="%1.1f%%",
               startangle=90, pctdistance=0.9, labeldistance=1.1)
        _save(fig, plots, "sv_type_pie.png", rep)

    if "length_counts" in sv_stats:
        lc = pd.DataFrame(sv_stats["length_counts"]).T
        lc.columns = lc.columns.astype(str)
        rep.add_section("SV length distribution")
        rep.add_table(lc)
        write_hdf(lc.reset_index(), args.h5_output, key="length_counts", mode="a")
        fig, ax = plt.subplots()
        lc.T.plot.bar(ax=ax, legend=False)
        ax.set_xlabel("Length")
        ax.set_ylabel("# Calls")
        ax.set_yscale("log")
        _save(fig, plots, "sv_length_bar.png", rep)

    if "length_by_type_counts" in sv_stats:
        lbt = sv_stats["length_by_type_counts"]
        lbt = pd.DataFrame(lbt) if not isinstance(lbt, pd.DataFrame) else lbt.copy()
        # collector emits index=svtype, columns=length bins
        # (sv_stats_collect.collect_size_type_histograms); the notebook
        # transposes before plotting (createSVReport cell 18) so length is
        # the x axis and SV type the legend
        if any(t in lbt.index for t in SV_TYPE_ORDER):
            lbt = lbt.T
        order = [t for t in SV_TYPE_ORDER if t in lbt.columns] + \
            [t for t in lbt.columns if t not in SV_TYPE_ORDER]
        lbt = lbt.reindex(order, axis=1).dropna(how="all", axis=1)
        rep.add_section("Length and type distribution")
        rep.add_table(lbt)
        save_lbt = lbt.copy()
        save_lbt.columns = [str(c) for c in save_lbt.columns]
        save_lbt.index = [str(i) for i in save_lbt.index]
        write_hdf(save_lbt.reset_index(), args.h5_output, key="length_by_type_counts", mode="a")
        fig, ax = plt.subplots(figsize=(8, 6))
        lbt.plot(kind="bar", stacked=False, ax=ax)
        ax.set_xlabel("Length")
        ax.set_ylabel("# Calls")
        ax.set_yscale("log")
        ax.legend(title="SV Type", loc="upper right", fontsize=10)
        _save(fig, plots, "sv_length_by_type.png", rep)

    # --- concordance (notebook cells 21-27) -------------------------------
    if concordance is not None and len(concordance):
        conc = concordance.copy()
        rep.add_section("Concordance evaluation")
        roc_cols = [c for c in ("precision roc", "recall roc", "thresholds") if c in conc.columns]
        overall = conc
        if isinstance(conc.index, pd.MultiIndex) and "SV length" in conc.index.names:
            overall = conc[conc.index.get_level_values("SV length") == ""]
        values_df = overall.drop(columns=roc_cols, errors="ignore")
        keep = [c for c in ("TP_base", "TP_calls", "FP", "FN", "Recall", "Precision", "F1")
                if c in values_df.columns]
        if keep:
            values_df = values_df[keep]
        rep.add_table(values_df.reset_index())
        write_hdf(values_df.reset_index().astype(str), args.h5_output, key="concordance", mode="a")
        # notebook cell 23 writes the same overall table under recall_per_type
        write_hdf(values_df.reset_index().astype(str), args.h5_output, key="recall_per_type", mode="a")

        # ROC grid per overall category
        if roc_cols and len(overall):
            rocs = [(idx, row) for idx, row in overall[roc_cols].iterrows()
                    if len(np.atleast_1d(row.get("precision roc", [])))]
            if rocs:
                fig, axs = plt.subplots(1, len(rocs), figsize=(3 * len(rocs), 3), squeeze=False)
                for ax, (idx, row) in zip(axs[0], rocs):
                    ax.plot(row["recall roc"], row["precision roc"])
                    ax.set_title(str(idx if not isinstance(idx, tuple) else idx[0]))
                    ax.set_xlabel("Recall")
                    ax.set_xlim(0, 0.8)
                    ax.set_ylim(0.6, 1)
                axs[0][0].set_ylabel("Precision")
                _save(fig, plots, "sv_pr_roc.png", rep)

        # recall per length and type (length-binned rows)
        if isinstance(conc.index, pd.MultiIndex) and "SV length" in conc.index.names:
            binned = conc[conc.index.get_level_values("SV length") != ""]
            keep = [c for c in ("TP_base", "TP_calls", "FN", "Recall") if c in binned.columns]
            if len(binned) and keep:
                rec = binned[keep].copy()
                for c in ("TP_base", "TP_calls", "FN"):
                    if c in rec.columns:
                        rec[c] = rec[c].astype(float).astype(int)
                rep.add_section("Recall per variant length and type")
                rep.add_table(rec.reset_index())
                out = rec.reset_index()
                out.columns = [str(c).replace(" ", "_") for c in out.columns]
                write_hdf(out.astype(str), args.h5_output,
                          key="recall_per_length_and_type", mode="a")
                fig, ax = plt.subplots(figsize=(8, 4))
                piv = out.pivot_table(index="SV_length", columns="SV_type", values="Recall",
                                      aggfunc="first")
                piv = piv.astype(float)
                piv.plot(kind="bar", ax=ax)
                ax.set_ylabel("Recall")
                _save(fig, plots, "sv_recall_per_length.png", rep)

    if len(fp_stats):
        rep.add_section("False positives per variant length and type")
        fp_df = fp_stats.rename("FP count").reset_index()
        # name by the collector's index names, not positional order
        # (sv_stats_collect emits (svtype, binned_svlens))
        fp_df = fp_df.rename(columns={"svtype": "SV type", "binned_svlens": "SV length"})
        fp_df = fp_df.astype({c: str for c in fp_df.columns if fp_df[c].dtype == "category"})
        rep.add_table(fp_df)
        if {"SV length", "SV type", "FP count"} <= set(fp_df.columns):
            piv = fp_df.pivot_table(index="SV length", columns="SV type", values="FP count",
                                    aggfunc="sum").fillna(0).astype(int)
            piv.columns = piv.columns.astype(str)
            write_hdf(piv.reset_index().astype(str), args.h5_output,
                      key="fp_counts_per_length_and_type", mode="a")
            fig, ax = plt.subplots(figsize=(10, 5))
            piv.plot.bar(ax=ax, width=0.8)
            ax.legend(title="SV Type", bbox_to_anchor=(1.05, 1), loc="upper left")
            _save(fig, plots, "sv_fp_per_length.png", rep)
        else:
            write_hdf(fp_df, args.h5_output, key="fp_counts_per_length_and_type", mode="a")

    if args.html_output:
        rep.write(args.html_output)
    logger.info("SV report -> %s%s", args.h5_output,
                f" + {args.html_output}" if args.html_output else "")
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
