"""correct_genotypes_by_imputation — imputation-weighted PL/GQ/GT rewrite.

Reference behavior (correct_genotypes_by_imputation.py:361-492): subset ->
high-GQ filter -> beagle -> collapse -> annotate FORMAT/DS -> per-record
PL update. The beagle stages are external Java plumbing the reference
shells out to; this tool TPU-izes the hot loop (SURVEY §3.5: the PL update
is "trivially batchable to vmap") and consumes a beagle-annotated VCF
directly via ``--beagle_annotated_vcf``. PASS records with a called alt
genotype and FORMAT/DS get new PL/GQ/GT (old values preserved as
PL0/GQ0/GT0, :281-303); batching groups records by alt count so every
group is one fused kernel call. A stats csv mirrors the reference's
counter categories (:276, 455-473).
"""

from __future__ import annotations

import argparse
import sys
from collections import defaultdict

import numpy as np

from variantcalling_tpu import logger
from variantcalling_tpu.io.vcf import read_vcf, write_vcf
from variantcalling_tpu.ops.genotypes import genotype_ordering, n_genotypes
from variantcalling_tpu.ops.imputation import gt_to_index, modify_stats_with_imp_batch

import jax.numpy as jnp

MAX_ALTS = 3
COUNTER_KEYS = ("pass", "has_non_ref_imp", "imp_has_different_gt", "changed_gt")


def _new_counter() -> dict:
    return dict.fromkeys(COUNTER_KEYS, 0)


def parse_args(argv: list[str]):
    ap = argparse.ArgumentParser(prog="correct_genotypes_by_imputation", description=run.__doc__)
    ap.add_argument("--beagle_annotated_vcf",
                    help="VCF annotated with beagle FORMAT/DS (skip the stage chain)")
    # full orchestration surface (reference get_parser :42-130)
    ap.add_argument("--input_vcf", help="VCF to be corrected (runs the full stage chain)")
    ap.add_argument("--chrom_to_cohort_vcfs_json",
                    help="json mapping chromosome names to reference-cohort VCFs")
    ap.add_argument("--chrom_to_plink_json",
                    help="json mapping chromosome names to plink genomic maps")
    ap.add_argument("--single_chrom", help="single chromosome to work on (cromwell mode)")
    ap.add_argument("--single_cohort_vcf", help="reference cohort VCF for --single_chrom")
    ap.add_argument("--single_genomic_map_plink", help="plink genomic map for --single_chrom")
    ap.add_argument("--temp_dir", default=None, help="directory for stage files")
    ap.add_argument("--threads_for_contig", type=int, default=1, help="(accepted; in-process stages)")
    ap.add_argument("--threads_beagle", type=int, default=1)
    ap.add_argument("--beagle_cmd", default="beagle", help="beagle executable (testing seam)")
    ap.add_argument("--output_vcf", required=True)
    ap.add_argument("--epsilon", type=float, default=0.01,
                    help="imputation weight in the new PL (0..1)")
    ap.add_argument("--stats_file", default=None)
    ap.add_argument("--add_imp_effect", action="store_true")
    ap.add_argument("--verbosity", default="INFO")
    return ap.parse_args(argv)


def run(argv: list[str]) -> int:
    """Correct a vcf based on imputation."""
    args = parse_args(argv)
    if args.input_vcf and args.beagle_annotated_vcf:
        raise SystemExit(
            "--input_vcf (full stage chain) and --beagle_annotated_vcf "
            "(pre-annotated input) are mutually exclusive"
        )
    if args.input_vcf:
        return _run_stage_chain(args)
    if not args.beagle_annotated_vcf:
        raise SystemExit("provide --beagle_annotated_vcf, or --input_vcf with cohort/map args")
    return _correct_annotated(args.beagle_annotated_vcf, args)


def _run_stage_chain(args) -> int:
    """The reference's per-chromosome orchestration (:361-453), in-process.

    subset -> high-GQ filter -> beagle (external) -> collapse -> annotate,
    then the vmap'd PL update per chromosome and a final concat.
    """
    import json
    import tempfile

    from variantcalling_tpu.pipelines import imputation_stages as st

    if args.chrom_to_cohort_vcfs_json and args.chrom_to_plink_json:
        with open(args.chrom_to_cohort_vcfs_json, encoding="utf-8") as fh:
            chrom_to_cohort = json.load(fh)
        with open(args.chrom_to_plink_json, encoding="utf-8") as fh:
            chrom_to_plink = json.load(fh)
    elif args.single_chrom and args.single_cohort_vcf and args.single_genomic_map_plink:
        chrom_to_cohort = {args.single_chrom: args.single_cohort_vcf}
        chrom_to_plink = {args.single_chrom: args.single_genomic_map_plink}
    else:
        raise SystemExit(
            "define chrom_to_cohort_vcfs_json + chrom_to_plink_json, or the three single_* args"
        )
    missing_maps = set(chrom_to_cohort) - set(chrom_to_plink)
    if missing_maps:
        raise SystemExit(
            f"chrom_to_plink_json lacks genomic maps for {sorted(missing_maps)} "
            "(every cohort chromosome needs a plink map)"
        )

    tmp = args.temp_dir or tempfile.mkdtemp(prefix="imputation_")
    import os

    os.makedirs(tmp, exist_ok=True)
    part_files = []
    all_counters: dict = defaultdict(_new_counter)
    input_table = read_vcf(args.input_vcf)  # parse once for all chromosomes
    for chrom in chrom_to_cohort:
        subset_path = os.path.join(tmp, f"subset.{chrom}.vcf.gz")
        high_gq_path = os.path.join(tmp, f"high_gq.{chrom}.vcf.gz")
        beagle_path = os.path.join(tmp, f"beagle.{chrom}.vcf.gz")
        collapsed_path = os.path.join(tmp, f"beagle_collapsed.{chrom}.vcf.gz")
        anno_path = os.path.join(tmp, f"beagle_anno.{chrom}.vcf.gz")
        part_path = os.path.join(tmp, f"add_imp.{chrom}.vcf.gz")

        sub = st.subset_vcf(input_table, chrom, subset_path)
        st.filter_high_gq(sub, high_gq_path)
        st.run_beagle(high_gq_path, chrom_to_cohort[chrom], chrom_to_plink[chrom],
                      beagle_path, nthreads=args.threads_beagle, beagle_cmd=args.beagle_cmd)
        collapsed = st.collapse_beagle(beagle_path, collapsed_path)
        st.annotate_with_beagle(sub, collapsed, anno_path)

        counters = _correct_annotated(anno_path, args, output_override=part_path)
        for vt, c in counters.items():
            for k, v in c.items():
                all_counters[vt][k] += v
        part_files.append(part_path)

    st.concat_vcfs(part_files, args.output_vcf)
    _write_stats(args, all_counters)
    return 0


def _write_stats(args, counters) -> None:
    stats_file = args.stats_file or args.output_vcf.replace(".vcf.gz", "").replace(".vcf", "") + "_counts.csv"
    with open(stats_file, "w") as fh:
        fh.write("variant_type," + ",".join(COUNTER_KEYS) + "\n")
        for vt, c in sorted(counters.items()):
            fh.write(vt + "," + ",".join(str(c[k]) for k in COUNTER_KEYS) + "\n")


def _correct_annotated(annotated_vcf: str, args, output_override: str | None = None):
    """The TPU-ized PL/GQ/GT rewrite over a beagle-annotated VCF."""
    table = read_vcf(annotated_vcf)
    n = len(table)

    gts = table.genotypes()
    n_alts = table.n_alts()
    ds_raw = table.format_numeric("DS", missing=np.nan)
    has_ds = np.array([r is not None for r in table.format_field("DS")])
    is_pass = np.array([f in ("PASS", ".", "") for f in table.filters])
    has_alt = (gts > 0).any(axis=1)
    # diploid fully-called only: haploid / half-missing GTs have no row in
    # the genotype-ordering table and must not be force-rewritten
    diploid_called = (gts >= 0).all(axis=1)
    eligible = is_pass & has_alt & diploid_called & has_ds & (n_alts >= 1) & (n_alts <= MAX_ALTS)

    # outputs default to passthrough
    new_gt_str = np.array([None] * n, dtype=object)
    new_gq = np.full(n, -1, dtype=np.int64)
    new_pl_str = np.array([None] * n, dtype=object)
    counters: dict[str, dict] = defaultdict(_new_counter)
    vtypes = np.where(n_alts > 1, "multi", np.where(
        np.array([len(r) == len(a.split(",")[0]) if a not in (".", "") else True
                  for r, a in zip(table.ref, table.alt)]), "snp", "indel"))
    for i in np.nonzero(is_pass & has_alt)[0]:
        counters[vtypes[i]]["pass"] += 1

    changed = 0
    # parse PL once for the whole table at the widest genotype count; each
    # alt-count group slices its prefix
    pl_all = table.format_numeric("PL", max_len=n_genotypes(MAX_ALTS), missing=np.nan)
    for num_alt in range(1, MAX_ALTS + 1):
        m = eligible & (n_alts == num_alt)
        if not m.any():
            continue
        g = n_genotypes(num_alt)
        pl = pl_all[m][:, :g]
        ok = ~np.isnan(pl).any(axis=1)
        idx = np.nonzero(m)[0][ok]
        if len(idx) == 0:
            continue
        pl = pl[ok]
        ds = ds_raw[m][ok][:, :num_alt] if ds_raw.shape[1] >= num_alt else np.full((len(idx), num_alt), np.nan)
        cur_idx = gt_to_index(gts[idx], num_alt)
        valid_gt = cur_idx >= 0
        idx, pl, ds, cur_idx = idx[valid_gt], pl[valid_gt], ds[valid_gt], cur_idx[valid_gt]
        if len(idx) == 0:
            continue
        npl, ngq, nidx = modify_stats_with_imp_batch(
            jnp.asarray(pl), jnp.asarray(ds), jnp.asarray(cur_idx), num_alt, args.epsilon
        )
        npl, ngq, nidx = np.asarray(npl), np.asarray(ngq), np.asarray(nidx)
        gt_table = genotype_ordering(num_alt)
        for row, i in enumerate(idx):
            vt = vtypes[i]
            counters[vt]["has_non_ref_imp"] += 1
            imp_is_hom = bool(np.nanmax(ds[row]) >= 1.5) if not np.isnan(ds[row]).all() else False
            gt_is_hom = gts[i, 0] == gts[i, 1]
            if imp_is_hom != gt_is_hom:
                counters[vt]["imp_has_different_gt"] += 1
            new_pair = tuple(gt_table[nidx[row]])
            new_pl_str[i] = ",".join(str(int(v)) for v in npl[row])
            new_gq[i] = int(ngq[row])
            new_gt_str[i] = f"{new_pair[0]}/{new_pair[1]}"
            if set(new_pair) != {a for a in gts[i] if a >= 0}:
                counters[vt]["changed_gt"] += 1
                changed += 1

    # rebuild sample strings with GT0/GQ0/PL0 retention (idempotent on re-run)
    table.header.ensure_format("GT0", "1", "String", "Genotype (pre-imputation)")
    table.header.ensure_format("GQ0", "1", "Integer", "GQ (pre-imputation)")
    table.header.ensure_format("PL0", "G", "Integer", "PL (pre-imputation)")
    retained = ("GT0", "GQ0", "PL0")
    table.materialize_format()  # sample-string rewrite needs the raw columns
    fmt_override = np.array(table.fmt_keys, dtype=object)
    sample0 = np.array(table.sample_cols[:, 0], dtype=object)
    for i in range(n):
        if new_gt_str[i] is None:
            continue
        keys = table.fmt_keys[i].split(":")
        vals = table.sample_cols[i][0].split(":")
        kv = dict(zip(keys, vals))
        old_gt, old_gq, old_pl = kv.get("GT", "./."), kv.get("GQ", "."), kv.get("PL", ".")
        kv["GT"] = new_gt_str[i]
        kv["GQ"] = str(new_gq[i])
        kv["PL"] = new_pl_str[i]
        kv["GT0"] = old_gt.replace("/", "|")
        kv["GQ0"] = old_gq
        kv["PL0"] = old_pl
        order = [k for k in keys if k not in retained]
        # the rewrite always produces GQ/PL values — emit them even when the
        # input FORMAT lacked the key (GQ right after GT per convention)
        if "GQ" not in order:
            order.insert(1 if order and order[0] == "GT" else 0, "GQ")
        if "PL" not in order:
            order.append("PL")
        order += list(retained)
        fmt_override[i] = ":".join(order)
        sample0[i] = ":".join(kv.get(k, ".") for k in order)

    out_path = output_override or args.output_vcf
    write_vcf(out_path, table, fmt_override=fmt_override, sample_overrides={0: sample0})
    logger.info("rewrote %d genotypes -> %s", changed, out_path)
    if output_override is not None:
        return dict(counters)  # stage-chain caller aggregates + writes stats
    _write_stats(args, counters)
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
