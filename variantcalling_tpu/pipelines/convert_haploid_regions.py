"""convert_haploid_regions — rewrite diploid PL/GQ/GT as haploid in given regions.

Drop-in surface of the reference tool
(ugvc/pipelines/convert_haploid_regions.py:9-103): ``--input_vcf
--output_vcf --haploid_regions <bed|hg38_non_par>``. The PL conversion runs
as one batched device kernel per alt-count bucket
(:func:`variantcalling_tpu.ops.genotypes.diploid_pl_to_haploid`) instead of
the reference's per-record Python loop.
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

from variantcalling_tpu.io.bed import read_bed
from variantcalling_tpu.io.vcf import MISSING, read_vcf, write_vcf
from variantcalling_tpu.ops.genotypes import diploid_pl_to_haploid, n_genotypes

# reference hardcodes hg38 non-pseudoautosomal X/Y spans
# (convert_haploid_regions.py:85-89); 1-based inclusive (chrom, start, end)
HG38_NON_PAR = [
    ("chrX", 1, 10001),
    ("chrX", 2781479, 155701383),
    ("chrX", 156030895, 156040895),
    ("chrY", 1, 10001),
    ("chrY", 2781479, 56887903),
]


def parse_args(argv: list[str]):
    ap = argparse.ArgumentParser(prog="convert_haploid_regions", description=__doc__)
    ap.add_argument("--input_vcf", required=True)
    ap.add_argument("--output_vcf", required=True)
    ap.add_argument(
        "--haploid_regions",
        required=True,
        help="BED of haploid regions, or 'hg38_non_par' for the hardcoded hg38 non-PAR X/Y spans",
    )
    return ap.parse_args(argv)


def _in_regions_mask(chrom: np.ndarray, pos: np.ndarray, regions: list[tuple[str, int, int]]) -> np.ndarray:
    mask = np.zeros(len(pos), dtype=bool)
    for rc, rs, re in regions:
        mask |= (chrom == rc) & (pos > rs) & (pos <= re)
    return mask


def convert_haploid(table, regions: list[tuple[str, int, int]]):
    """New (fmt-preserving) sample strings with haploid GT/GQ/PL in regions."""
    table.materialize_format()  # sample-string rewrite needs the raw columns
    n = len(table)
    in_region = _in_regions_mask(table.chrom, table.pos, regions)
    gt_raw = table.format_field("GT")
    pl_raw = table.format_field("PL")
    n_alts = table.n_alts()
    new_sample = np.array(table.sample_cols[:, 0], dtype=object, copy=True)

    # bucket region records by alt count; one device kernel call per bucket
    for a in np.unique(n_alts[in_region]):
        a = int(a)
        g = n_genotypes(a)
        rows = [
            i
            for i in np.nonzero(in_region & (n_alts == a))[0]
            if pl_raw[i] not in (None, MISSING, "") and len(pl_raw[i].split(",")) == g
        ]
        if not rows:
            continue
        pl = np.asarray([[float(x) for x in pl_raw[i].split(",")] for i in rows])
        if pl.shape[1] == 2:  # already haploid
            continue
        hpl, gq, gt = (np.asarray(x) for x in diploid_pl_to_haploid(pl, a))
        for bi, i in enumerate(rows):
            keys = table.fmt_keys[i].split(":")
            vals = table.sample_cols[i][0].split(":")
            vals += [MISSING] * (len(keys) - len(vals))
            d = dict(zip(keys, vals))
            # maintain no-call (reference convert_haploid_regions.py:65-66)
            d["GT"] = MISSING if gt_raw[i] in (None, MISSING, "") or gt_raw[i].split("/")[0].split("|")[0] == MISSING else str(int(gt[bi]))
            if "GQ" in d:
                d["GQ"] = str(int(gq[bi]))
            d["PL"] = ",".join(str(int(x)) for x in hpl[bi])
            new_sample[i] = ":".join(d.get(k, MISSING) for k in keys)
    return new_sample, int(in_region.sum())


def run(argv: list[str]):
    """Convert genotypes of specified regions to haploid calls, maintaining GT,GQ,PL."""
    args = parse_args(argv)
    if args.haploid_regions == "hg38_non_par":
        regions = HG38_NON_PAR
    else:
        bed = read_bed(args.haploid_regions)
        regions = [(str(c), int(s), int(e)) for c, s, e in zip(bed.chrom, bed.start, bed.end)]
    table = read_vcf(args.input_vcf)
    new_sample, n_conv = convert_haploid(table, regions)
    write_vcf(args.output_vcf, table, sample_overrides={0: new_sample})
    sys.stderr.write(f"convert_haploid_regions: {n_conv} records in haploid regions\n")
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
