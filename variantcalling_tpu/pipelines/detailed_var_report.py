"""detailed_var_report — stratified germline accuracy report.

Reference surface: ugvc/reports/detailedVarReport.v0.ipynb +
detailed_var_report.config. Reproduces the notebook's artifact set:

- the ``detailed_vars`` long frame (+ csv): one row per
  (Region, Category, Variant) cell over regions (All/annotation tracks),
  GC bins (0-20/20-80/80-100), coverage bins (0-20/20-40/40-100) and the
  notebook's variant categories (All/SNP/Indel/non-hmer/hmer bins), each
  carrying # pos/neg, avg coverage, max recall, static precision/recall/
  F1 at the shipped thresholds, and the re-optimized F1 from a
  tree_score threshold sweep (calcPerformanceOptimized);
- the colored performance-matrix figures (genome + exome, F1-stat and
  F1-opt, RdYlGn by value) embedded in the HTML;
- per-track inside/outside accuracy tables (kept from the basic flavor).
"""

from __future__ import annotations

import argparse
import sys

import numpy as np
import pandas as pd

from variantcalling_tpu import logger
from variantcalling_tpu.concordance.concordance_utils import calc_accuracy_metrics
from variantcalling_tpu.reports.html import HtmlReport, add_figure_safe
from variantcalling_tpu.utils.h5_utils import write_hdf

ANNOTATION_PREFIXES = ("LCR", "exome", "mappability", "ug_hcr", "callable")
VAR_CATS = ["All", "SNP", "Indel", "non-hmer", "hmer 0-1", "hmer 2-4",
            "hmer 5-8", "hmer 9-10", "hmer 11+"]
GC_BINS = [(0.0, 0.2), (0.2, 0.8), (0.8, 1.01)]
CVG_BINS = [(0, 20), (20, 40), (40, 100)]


def parse_args(argv):
    ap = argparse.ArgumentParser(prog="detailed_var_report", description=run.__doc__)
    ap.add_argument("--h5_concordance_file", required=True)
    ap.add_argument("--h5_output", default="detailed_var_report.h5")
    ap.add_argument("--csv_output", default=None, help="detailed_vars csv (config DetailedReport.csv)")
    ap.add_argument("--html_output", required=False, default=None)
    ap.add_argument("--reference_version", default="hg38")
    ap.add_argument("--exome_column_name", default="exome.twist")
    ap.add_argument("--coverage_column", default="well_mapped_coverage")
    return ap.parse_args(argv)


def _indel_len(d: pd.DataFrame) -> pd.Series:
    if "indel_length" not in d.columns:
        return pd.Series(0.0, index=d.index)
    return pd.to_numeric(d["indel_length"], errors="coerce").fillna(0)


def _var_mask(d: pd.DataFrame, cat: str) -> pd.Series:
    indel = d["indel"].astype(bool)
    # the loader renames hmer_indel_length -> hmer_length; accept either
    hmer_col = "hmer_length" if "hmer_length" in d.columns else "hmer_indel_length"
    hmer = (pd.to_numeric(d[hmer_col], errors="coerce").fillna(0)
            if hmer_col in d.columns else pd.Series(0.0, index=d.index))
    if cat == "All":
        return pd.Series(True, index=d.index)
    if cat == "SNP":
        return ~indel
    if cat == "Indel":
        return indel
    if cat == "non-hmer":
        return indel & (hmer == 0) & (_indel_len(d) > 1)
    if cat == "hmer 0-1":
        return indel & (hmer <= 1) & ~((hmer == 0) & (_indel_len(d) > 1))
    if cat == "hmer 2-4":
        return indel & (hmer >= 2) & (hmer <= 4)
    if cat == "hmer 5-8":
        return indel & (hmer >= 5) & (hmer <= 8)
    if cat == "hmer 9-10":
        return indel & (hmer >= 9) & (hmer <= 10)
    if cat == "hmer 11+":
        return indel & (hmer >= 11)
    raise ValueError(cat)


def _perf(d: pd.DataFrame, classify_col: str, cvg: pd.Series) -> dict | None:
    """Static + threshold-reoptimized performance of one stratum cell."""
    label = np.where(d[classify_col].astype(str) == "fp", 0, 1)
    n_pos = int(label.sum())
    n_neg = int(len(d) - n_pos)
    if len(d) == 0 or n_pos == 0:
        return {"# pos": n_pos, "# neg": n_neg, "avg cvg": float("nan"),
                "max recall": np.nan, "recall": np.nan, "precision": np.nan,
                "F1-stat": np.nan, "F1-opt": np.nan}
    is_fn = d[classify_col].astype(str) == "fn"
    passes = d["filter"].astype(str) == "PASS"
    tp = int(((label == 1) & ~is_fn & passes).sum())
    fp = int(((label == 0) & passes).sum())
    fn = int((is_fn | ((label == 1) & ~passes)).sum())
    recall = tp / (tp + fn) if tp + fn else np.nan
    precision = tp / (tp + fp) if tp + fp else np.nan
    f1 = tp / (tp + 0.5 * fn + 0.5 * fp) if tp + fn + fp else np.nan
    max_recall = 1.0 - float(is_fn.sum()) / n_pos

    # threshold sweep over tree_score (calcPerformanceOptimized): at each
    # cut, calls below it flip to negatives — vectorized cumulative counts
    if "tree_score" in d.columns:
        score = pd.to_numeric(d["tree_score"], errors="coerce").fillna(0.0).to_numpy()
    else:
        score = np.zeros(len(d))  # no score: sweep degenerates to one point
    callable_mask = ~is_fn.to_numpy()
    base_fn = int(is_fn.sum())
    order = np.argsort(score[callable_mask])
    lab = label[callable_mask][order]
    cum_pos_dropped = np.concatenate([[0], np.cumsum(lab)])
    cum_neg_dropped = np.concatenate([[0], np.cumsum(1 - lab)])
    total_pos = lab.sum()
    total_neg = len(lab) - total_pos
    tp_k = total_pos - cum_pos_dropped
    fp_k = total_neg - cum_neg_dropped
    fn_k = base_fn + cum_pos_dropped
    with np.errstate(invalid="ignore", divide="ignore"):
        f1_k = tp_k / (tp_k + 0.5 * fn_k + 0.5 * fp_k)
    # only cuts BETWEEN distinct scores are realizable thresholds — a cut
    # inside a tie run would report an F1 no threshold achieves
    s_sorted = score[callable_mask][order]
    realizable = np.ones(len(f1_k), dtype=bool)
    if len(s_sorted) > 1:
        realizable[1:-1] = s_sorted[1:] != s_sorted[:-1]
    f1_k = np.where(realizable, f1_k, np.nan)
    f1_opt = float(np.nanmax(f1_k)) if len(f1_k) and np.isfinite(f1_k).any() else np.nan

    has_cvg_vals = cvg is not None and len(cvg) and np.isfinite(cvg).any()
    return {"# pos": n_pos, "# neg": n_neg,
            "avg cvg": float(np.nanmean(cvg)) if has_cvg_vals else np.nan,
            "max recall": max_recall, "recall": recall, "precision": precision,
            "F1-stat": f1, "F1-opt": f1_opt}


def _bool_mask(vals: pd.Series) -> pd.Series:
    """Annotation-column truthiness that survives h5 object-string round trips
    (astype(bool) would map the string 'False' to True)."""
    if vals.dtype == object:
        return vals.astype(str).isin(["True", "1", "1.0", "true"])
    return vals.astype(bool)


def build_detailed_vars(df: pd.DataFrame, regions: list[str], classify_col: str,
                        coverage_column: str) -> pd.DataFrame:
    """All strata cells from precomputed boolean masks.

    Region/variant/bin masks are each computed ONCE on the full frame and
    combined per cell; _perf sees only a 2-3 column core slice — on a
    multi-million-row frame this avoids thousands of full-width DataFrame
    copies.
    """
    rows = []
    has_cvg = coverage_column in df.columns
    core_cols = [classify_col, "filter"] + (["tree_score"] if "tree_score" in df.columns else [])
    core = df[core_cols].reset_index(drop=True)
    cvg_arr = pd.to_numeric(df[coverage_column], errors="coerce").to_numpy() if has_cvg else None
    gc_arr = pd.to_numeric(df["gc_content"], errors="coerce").to_numpy() \
        if "gc_content" in df.columns else None
    var_masks = {v: _var_mask(df, v).to_numpy() for v in VAR_CATS}
    region_masks = {"All": np.ones(len(df), dtype=bool)}
    for region in regions:
        if region.startswith("Non-"):
            region_masks[region] = ~_bool_mask(df[region[4:]]).to_numpy()
        else:
            region_masks[region] = _bool_mask(df[region]).to_numpy()

    def add(mask: np.ndarray, region: str, category: str, var: str):
        p = _perf(core[mask], classify_col, cvg_arr[mask] if cvg_arr is not None else None)
        rows.append({"Region": region, "Category": category, "Variant": var, **p})

    for region, rmask in region_masks.items():
        for var in VAR_CATS:
            m = rmask & var_masks[var]
            add(m, region, "All", var)
            if gc_arr is not None:
                for lo, hi in GC_BINS:
                    add(m & (gc_arr >= lo) & (gc_arr < hi), region,
                        f"GC {lo * 100:.0f}-{min(hi, 1) * 100:.0f}", var)
            if cvg_arr is not None:
                for lo, hi in CVG_BINS:
                    add(m & (cvg_arr >= lo) & (cvg_arr < hi), region, f"CVG {lo}-{hi}", var)
    return pd.DataFrame(rows)


def _matrix_figure(out: pd.DataFrame, rows: list[str], metric: str, title: str):
    """Colored performance matrix (notebook cells 9-14)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    def cell(region, var):
        x = out[(((out["Category"] == "All") & (out["Region"] == region)) |
                 ((out["Category"] == region) & (out["Region"] == "All"))) &
                (out["Variant"] == var)]
        if not len(x):
            return "-", "white"
        v = x[metric].iloc[0]
        n = x["# pos"].iloc[0]
        cvg = x["avg cvg"].iloc[0]
        if not np.isfinite(v):
            return "-", "white"
        num = f"{int(n / 1000):d}k" if n > 1000 else f"{int(n):d}"
        cvg_s = f"{cvg:.1f}" if np.isfinite(cvg) else "-"
        color = "white" if n < 30 else plt.cm.RdYlGn(max(min((v - 0.8) / 0.2, 1.0), 0.0))
        return f"{v:.1%}\n({num},{cvg_s})", color

    present = [r for r in rows if r == "All" or len(out[(out["Region"] == r) | (out["Category"] == r)])]
    tabl, tabcol = [], []
    for r in present:
        txts, cols = zip(*(cell(r, c) for c in VAR_CATS))
        tabl.append(list(txts))
        tabcol.append(list(cols))
    fig, ax = plt.subplots(figsize=(20, 1 + len(present)))
    ax.set_axis_off()
    table = ax.table(cellText=tabl, rowLabels=present, colLabels=VAR_CATS,
                     cellColours=tabcol, cellLoc="center", loc="upper left")
    table.set_fontsize(12)
    table.scale(1, 2.2)
    ax.set_title(title, fontsize=18)
    return fig


def run(argv) -> int:
    """Generate the detailed (context-stratified) variant report."""
    args = parse_args(argv)
    from variantcalling_tpu.reports.report_data_loader import ReportDataLoader

    try:
        loader = ReportDataLoader(args.h5_concordance_file, args.reference_version,
                                  args.exome_column_name)
        df = loader.load_concordance_df()
    except KeyError:
        # frames without the genotype columns (gt_ultima/gt_ground_truth)
        # still stratify fine on classify/filter alone
        from variantcalling_tpu.utils.h5_utils import read_hdf

        df = read_hdf(args.h5_concordance_file, key="all")
    classify_col = "classify_gt" if "classify_gt" in df.columns else "classify"
    rep = HtmlReport("Detailed Variant Report")
    rep.add_params({"input": args.h5_concordance_file, "records": len(df),
                    "classify_column": classify_col})

    ann_cols = [c for c in df.columns
                if any(str(c).startswith(p) for p in ANNOTATION_PREFIXES)]
    regions = []
    for c in ann_cols:
        regions += [str(c), f"Non-{c}"]

    detailed = build_detailed_vars(df, regions, classify_col, args.coverage_column)
    params_df = pd.DataFrame.from_dict(
        {"h5_concordance_file": str(args.h5_concordance_file), "records": str(len(df))},
        orient="index", columns=["value"])
    write_hdf(params_df, args.h5_output, key="det_parameters", mode="w")
    write_hdf(detailed, args.h5_output, key="detailed_vars", mode="a")
    if args.csv_output:
        detailed.to_csv(args.csv_output, index=False)

    rep.add_section("Summary performance — Genome")
    matrix_rows = ["All", "GC 0-20", "GC 20-80", "GC 80-100", "CVG 0-20",
                   "CVG 20-40", "CVG 40-100"] + regions
    for metric, title in (("F1-stat", "Genome — F1 (n,cvg)"),
                          ("F1-opt", "Genome — re-optimized F1 (n,cvg)")):
        add_figure_safe(rep, lambda plt, m=metric, t=title: _matrix_figure(
            detailed, matrix_rows, m, t), "performance matrix")

    exome_col = args.exome_column_name if args.exome_column_name in df.columns else None
    if exome_col:
        rep.add_section("Summary performance — Exome")
        exome_detailed = build_detailed_vars(
            df[_bool_mask(df[exome_col])],
            [r for r in regions if not r.startswith(("Non-" + exome_col, exome_col))],
            classify_col, args.coverage_column)
        write_hdf(exome_detailed, args.h5_output, key="detailed_vars_exome", mode="a")
        for metric, title in (("max recall", "Exome — max recall (n,cvg)"),
                              ("F1-stat", "Exome — F1 (n,cvg)"),
                              ("F1-opt", "Exome — re-optimized F1 (n,cvg)")):
            add_figure_safe(rep, lambda plt, m=metric, t=title: _matrix_figure(
                exome_detailed, matrix_rows, m, t), "exome matrix")

    # per-track inside/outside accuracy tables (kept from the basic flavor)
    for col in ann_cols:
        mask = _bool_mask(df[col])
        for label, m in ((f"inside {col}", mask), (f"outside {col}", ~mask)):
            sub = df[m]
            if not len(sub):
                continue
            tab = calc_accuracy_metrics(sub, "classify", ["HPOL_RUN"])
            key = label.replace(" ", "_").replace(".", "_").replace("-", "_")
            rep.add_section(f"Accuracy {label} ({int(m.sum())} records)")
            rep.add_table(tab)
            write_hdf(tab, args.h5_output, key=key, mode="a")

    rep.add_section("Detailed performance (all strata)")
    rep.add_table(detailed.head(1000))
    if args.html_output:
        rep.write(args.html_output)
    logger.info("detailed report: %d strata rows, %d tracks -> %s",
                len(detailed), len(ann_cols), args.h5_output)
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
