"""detailed_var_report — stratified germline accuracy report.

Reference surface: ugvc/reports/detailedVarReport.v0.ipynb +
detailed_var_report.config. The detailed flavor adds genomic-context
stratification on top of createVarReport: per-category accuracy inside and
outside each annotation track (LCR, exome, mappability, ug_hcr), coverage
bins when a coverage column exists, and the SEC re-filtered view — all from
the same concordance frame with boolean-mask algebra (no extra passes over
the data).
"""

from __future__ import annotations

import argparse
import sys

import numpy as np
import pandas as pd

from variantcalling_tpu import logger
from variantcalling_tpu.concordance.concordance_utils import calc_accuracy_metrics
from variantcalling_tpu.reports.html import HtmlReport
from variantcalling_tpu.reports.report_data_loader import ReportDataLoader
from variantcalling_tpu.utils.h5_utils import write_hdf

ANNOTATION_PREFIXES = ("LCR", "exome", "mappability", "ug_hcr")


def parse_args(argv):
    ap = argparse.ArgumentParser(prog="detailed_var_report", description=run.__doc__)
    ap.add_argument("--h5_concordance_file", required=True)
    ap.add_argument("--h5_output", default="detailed_var_report.h5")
    ap.add_argument("--html_output", default=None)
    ap.add_argument("--reference_version", default="hg38")
    ap.add_argument("--exome_column_name", default="exome.twist")
    ap.add_argument("--coverage_column", default="coverage")
    ap.add_argument("--coverage_bins", nargs="*", type=float, default=[0, 10, 20, 30, 40, 1e9])
    return ap.parse_args(argv)


def run(argv) -> int:
    """Generate the detailed (context-stratified) variant report."""
    args = parse_args(argv)
    try:
        loader = ReportDataLoader(args.h5_concordance_file, args.reference_version, args.exome_column_name)
        df = loader.load_concordance_df()
    except KeyError:
        # frames without the genotype columns (gt_ultima/gt_ground_truth)
        # still stratify fine on classify/filter alone
        from variantcalling_tpu.utils.h5_utils import read_hdf

        df = read_hdf(args.h5_concordance_file, key="all")
    rep = HtmlReport("Detailed Variant Report")
    rep.add_params({"input": args.h5_concordance_file, "records": len(df)})
    mode = "w"

    overall = calc_accuracy_metrics(df, "classify", ["HPOL_RUN"])
    rep.add_section("Overall accuracy")
    rep.add_table(overall)
    write_hdf(overall, args.h5_output, key="overall", mode=mode)
    mode = "a"

    ann_cols = [
        c for c in df.columns if any(str(c).startswith(p) for p in ANNOTATION_PREFIXES)
    ]
    for col in ann_cols:
        vals = df[col]
        mask = vals.astype(bool) if vals.dtype != object else vals.astype(str).isin(["True", "1", "1.0"])
        for label, m in ((f"inside {col}", mask), (f"outside {col}", ~mask)):
            sub = df[m]
            if not len(sub):
                continue
            tab = calc_accuracy_metrics(sub, "classify", ["HPOL_RUN"])
            key = label.replace(" ", "_").replace(".", "_")
            rep.add_section(f"Accuracy {label} ({int(m.sum())} records)")
            rep.add_table(tab)
            write_hdf(tab, args.h5_output, key=key, mode=mode)

    if args.coverage_column in df.columns:
        cov = pd.to_numeric(df[args.coverage_column], errors="coerce")
        bins = args.coverage_bins
        for lo, hi in zip(bins[:-1], bins[1:]):
            m = (cov >= lo) & (cov < hi)
            if not m.any():
                continue
            tab = calc_accuracy_metrics(df[m], "classify", ["HPOL_RUN"])
            label = f"coverage [{lo:g}, {hi:g})"
            rep.add_section(f"Accuracy at {label}")
            rep.add_table(tab)
            write_hdf(tab, args.h5_output, key=f"coverage_{lo:g}_{hi:g}".replace(".", "_"), mode=mode)

    if args.html_output:
        rep.write(args.html_output)
    logger.info("detailed report (%d annotation tracks) -> %s", len(ann_cols), args.h5_output)
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
