"""create_var_report — germline accuracy report from a concordance h5.

The reference renders ugvc/reports/createVarReport.ipynb through papermill
+ nbconvert (test_vc_report.py:15-26), parameterized by a VarReport INI
config (report_utils.parse_config). This framework generates the same
artifact set directly — no notebook runtime: per-category accuracy tables
(+SEC re-filtered variants), error-type decomposition, PR-curve PNGs, and
a self-contained HTML summary, all derived from one loaded concordance
frame.
"""

from __future__ import annotations

import argparse
import os
import sys

import pandas as pd

from variantcalling_tpu import logger
from variantcalling_tpu.reports.report_data_loader import ReportDataLoader
from variantcalling_tpu.reports.report_utils import DEFAULT_CATEGORIES, ReportUtils, parse_config


def parse_args(argv: list[str]):
    ap = argparse.ArgumentParser(prog="create_var_report", description=__doc__)
    ap.add_argument("--config", help="VarReport INI config (reference var_report.config surface)")
    ap.add_argument("--h5_concordance_file", help="run_comparison output h5 (overrides config)")
    ap.add_argument("--h5_output", default=None, help="output h5 (default var_report.h5)")
    ap.add_argument("--html_output", default=None, help="optional HTML summary path")
    ap.add_argument("--reference_version", default="hg38")
    ap.add_argument("--exome_column_name", default="exome.twist")
    ap.add_argument("--verbosity", type=int, default=5)
    ap.add_argument("--plot_dir", default=None, help="directory for PR-curve PNGs")
    return ap.parse_args(argv)


def run(argv: list[str]) -> int:
    args = parse_args(argv)
    h5_in = args.h5_concordance_file
    h5_out = args.h5_output
    verbosity = args.verbosity
    ref_version = args.reference_version
    if args.config:
        params, _ = parse_config(args.config)
        h5_in = h5_in or params["h5_concordance_file"]
        h5_out = h5_out or params.get("h5outfile")
        verbosity = int(params.get("verbosity", verbosity))
        ref_version = params.get("reference_version", ref_version)
    h5_out = h5_out or "var_report.h5"

    loader = ReportDataLoader(h5_in, ref_version, args.exome_column_name)
    df = loader.load_concordance_df()
    logger.info("loaded %d records from %s", len(df), h5_in)

    ru = ReportUtils(verbosity, h5_out, plot_dir=args.plot_dir)
    sections: dict[str, pd.DataFrame] = {}

    opt_tab, err_tab = ru.basic_analysis(df, list(DEFAULT_CATEGORIES), "all_data", out_key_sec="all_data_sec")
    sections["General accuracy (all data)"] = opt_tab
    if len(err_tab):
        sections["Error types (all data)"] = err_tab

    # PASS-only view (reference notebook's filtered section)
    df_pass = df[df["filter"] == "PASS"]
    if len(df_pass):
        opt_pass, _ = ru.basic_analysis(df_pass, list(DEFAULT_CATEGORIES), "pass_data")
        sections["General accuracy (PASS only)"] = opt_pass

    # homozygous genotyping + base stratification (reference :108-126)
    try:
        sections["Homozygous accuracy"] = ru.homozygous_genotyping_analysis(df, ["SNP", "Indel"], "homozygous")
    except Exception as e:  # noqa: BLE001 — section optional when columns absent
        logger.warning("homozygous section skipped: %s", e)
    for bases in (("A", "T"), ("G", "C")):
        try:
            sections[f"Base stratification {bases}"] = ru.base_stratification_analysis(
                df, ["SNP", "hmer Indel <=4"], bases
            )
        except Exception as e:  # noqa: BLE001
            logger.warning("base stratification %s skipped: %s", bases, e)

    if args.html_output:
        with open(args.html_output, "w", encoding="utf-8") as fh:
            fh.write("<html><head><title>Variant Report</title></head><body>\n")
            fh.write("<h1>Variant calling accuracy report</h1>\n")
            for title, tab in sections.items():
                fh.write(f"<h2>{title}</h2>\n")
                fh.write(tab.to_html(float_format=lambda x: f"{x:.4f}"))
            fh.write("</body></html>\n")
        logger.info("wrote %s", args.html_output)
    logger.info("wrote %s", h5_out)
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
