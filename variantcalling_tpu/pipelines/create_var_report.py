"""create_var_report — germline accuracy report from a concordance h5.

The reference renders ugvc/reports/createVarReport.ipynb through papermill
+ nbconvert (test_vc_report.py:15-26), parameterized by a VarReport INI
config (report_utils.parse_config). This framework generates the same
artifact set directly — no notebook runtime — with the notebook's full
section inventory (cells 4-20):

1. parameters (+ mean_var_depth when well_mapped_coverage exists)
2. all data: fine-grained category accuracy (+SEC refilter), base
   stratification (A,T)+(G,C) -> ``all_data_per_base``, homozygous
   genotyping -> ``all_data_homozygous``
3. UG high-confidence regions (``ug_hcr`` column) + homozygous
4. exome (+ indel/SNP error example tables -> ``exome_*_errors``)
5. well-covered well-mapped regions (coverage>=20 & mappability.0)
6. callable regions
7. indel analysis histograms (wg / ug-hcr / exome) — per-factor
   fp/tp/fn + per-bin precision/recall, ins/del and hmer/non-hmer split

Every section lands in the output h5 under the notebook's key names, and
optionally in a self-contained HTML summary + PNGs.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np
import pandas as pd

from variantcalling_tpu import logger
from variantcalling_tpu.reports.report_data_loader import ReportDataLoader
from variantcalling_tpu.reports.report_utils import ReportUtils, parse_config

# notebook cell 8 (verbosity > 1) — the full stratification list
FINE_CATEGORIES = [
    "SNP", "Indel", "non-hmer Indel", "non-hmer Indel w/o LCR",
    "hmer Indel <=4", "hmer Indel >4,<=8",
    "hmer Indel 4", "hmer Indel 5", "hmer Indel 6", "hmer Indel 7", "hmer Indel 8",
    "hmer Indel >8,<=10", "hmer Indel >10,<=12", "hmer Indel >12,<=14",
    "hmer Indel >15,<=19", "hmer Indel >=20",
]
BASE_STRAT_CATEGORIES = [
    "SNP", "Indel", "hmer Indel <=4", "hmer Indel >4,<=8", "hmer Indel >8,<=10",
    "hmer Indel >10,<=12", "hmer Indel >12,<=14", "hmer Indel >15,<=19", "hmer Indel >=20",
]
HOM_CATEGORIES = [
    "SNP", "Indel", "non-hmer Indel", "hmer Indel <=4", "hmer Indel >4,<=8",
    "hmer Indel >8,<=10", "hmer Indel >10,<=12", "hmer Indel >12,<=14",
    "hmer Indel >15,<=19", "hmer Indel >=20",
]
REGION_CATEGORIES = [
    "SNP", "Indel", "non-hmer Indel", "non-hmer Indel w/o LCR", "hmer Indel <=4",
    "hmer Indel >4,<=8", "hmer Indel 4", "hmer Indel 5", "hmer Indel 6",
    "hmer Indel 7", "hmer Indel 8", "hmer Indel >8,<=10",
]
EXOME_CATEGORIES = ["SNP", "Indel", "non-hmer Indel", "hmer Indel <=4",
                    "hmer Indel >4,<=8", "hmer Indel >8,<=10"]
ERROR_EXAMPLE_COLUMNS = ["alleles", "call", "base", "gt_ultima", "gt_ground_truth", "ad",
                         "max_vaf", "ug_hcr", "mappability.0", "hmer_length"]


def parse_args(argv: list[str]):
    ap = argparse.ArgumentParser(prog="create_var_report", description=__doc__)
    ap.add_argument("--config", help="VarReport INI config (reference var_report.config surface)")
    ap.add_argument("--h5_concordance_file", help="run_comparison output h5 (overrides config)")
    ap.add_argument("--h5_output", default=None, help="output h5 (default var_report.h5)")
    ap.add_argument("--html_output", default=None, help="optional HTML summary path")
    ap.add_argument("--reference_version", default="hg38")
    ap.add_argument("--exome_column_name", default="exome.twist")
    ap.add_argument("--run_id", default="NA")
    ap.add_argument("--pipeline_version", default="NA")
    ap.add_argument("--truth_sample_name", default="NA")
    ap.add_argument("--verbosity", type=int, default=5)
    ap.add_argument("--plot_dir", default=None, help="directory for PR-curve / indel PNGs")
    return ap.parse_args(argv)


def _section(sections, title, tab):
    if tab is not None and len(tab):
        sections[title] = tab


def run(argv: list[str]) -> int:
    args = parse_args(argv)
    h5_in = args.h5_concordance_file
    h5_out = args.h5_output
    verbosity = args.verbosity
    ref_version = args.reference_version
    if args.config:
        params, _ = parse_config(args.config)
        h5_in = h5_in or params["h5_concordance_file"]
        h5_out = h5_out or params.get("h5outfile")
        verbosity = int(params.get("verbosity", verbosity))
        ref_version = params.get("reference_version", ref_version)
    h5_out = h5_out or "var_report.h5"
    if os.path.exists(h5_out):
        if h5_in and os.path.exists(h5_in) and os.path.samefile(h5_out, h5_in):
            raise SystemExit("--h5_output must differ from --h5_concordance_file "
                             f"(both point at {h5_out})")
        os.remove(h5_out)

    loader = ReportDataLoader(h5_in, ref_version, args.exome_column_name)
    data = loader.load_concordance_df()
    logger.info("loaded %d records from %s", len(data), h5_in)

    ru = ReportUtils(verbosity, h5_out, plot_dir=args.plot_dir)
    sections: dict[str, pd.DataFrame] = {}

    # --- 1. parameters (notebook cells 2, 5) ------------------------------
    parameters = {
        "h5_concordance_file": str(h5_in),
        "run_id": args.run_id,
        "pipeline_version": str(args.pipeline_version),
        "verbosity": str(verbosity),
        "reference_version": ref_version,
        "truth_sample_name": args.truth_sample_name,
        "h5outfile": h5_out,
    }
    if "well_mapped_coverage" in data.columns:
        parameters["mean_var_depth"] = f"{data['well_mapped_coverage'].mean():.2f}"
    params_df = pd.DataFrame.from_dict(parameters, orient="index", columns=["value"])
    ru._to_hdf(params_df, "parameters")
    _section(sections, "Input parameters", params_df)

    cats = FINE_CATEGORIES if verbosity > 1 else ["SNP", "Indel"]

    # --- 2. all data ------------------------------------------------------
    opt, err = ru.basic_analysis(data, cats, "all_data", "sec_data")
    _section(sections, "2. All data — General accuracy", opt)
    _section(sections, "2. All data — error types", err)
    if verbosity > 1:
        # optional sections: a concordance frame missing their columns must
        # not take down the whole report (loader drops absent columns)
        try:
            at_df = ru.base_stratification_analysis(data, BASE_STRAT_CATEGORIES, ("A", "T"))
            gc_df = ru.base_stratification_analysis(
                data, ["SNP", "Indel", "hmer Indel <=4", "hmer Indel >4,<=8", "hmer Indel >8,<=10"],
                ("G", "C"))
            base_strat = pd.concat([at_df, gc_df])
            out = base_strat.copy()
            ru.make_multi_index(out)
            ru._to_hdf(out, "all_data_per_base")
            _section(sections, "2.1 Stratified by base", base_strat)
        except KeyError as e:
            logger.warning("base stratification skipped (missing column %s)", e)
        try:
            hom = ru.homozygous_genotyping_analysis(data, HOM_CATEGORIES, "all_data_homozygous")
            _section(sections, "2.2 Homozygous genotyping accuracy", hom)
        except KeyError as e:
            logger.warning("homozygous section skipped (missing column %s)", e)

    # --- 3. UG high confidence regions ------------------------------------
    ug_hcr_data = pd.DataFrame()
    if "ug_hcr" in data.columns:
        ug_hcr_data = data[data["ug_hcr"].astype(bool)].copy()
    if len(ug_hcr_data):
        rcats = REGION_CATEGORIES if verbosity > 1 else ["SNP", "Indel"]
        opt, err = ru.basic_analysis(ug_hcr_data, rcats, "ug_hcr", "ug_hcr_sec_data")
        _section(sections, "3. UG-HCR — General accuracy", opt)
        _section(sections, "3. UG-HCR — error types", err)
        if verbosity > 1:
            try:
                hom = ru.homozygous_genotyping_analysis(ug_hcr_data, EXOME_CATEGORIES,
                                                        "ug_hcr_homozygous")
                _section(sections, "3.1 UG-HCR homozygous accuracy", hom)
            except KeyError as e:
                logger.warning("ug_hcr homozygous section skipped (missing column %s)", e)

    # --- 4. exome ---------------------------------------------------------
    exome_data = pd.DataFrame()
    if args.exome_column_name in data.columns:
        exome_data = data[data[args.exome_column_name].astype(bool)].copy()
    if len(exome_data):
        ecats = EXOME_CATEGORIES if verbosity > 1 else ["SNP", "Indel"]
        opt, err = ru.basic_analysis(exome_data, ecats, "exome", "exome_sec_data")
        _section(sections, "4. Exome — General accuracy", opt)
        _section(sections, "4. Exome — error types", err)
        if verbosity > 1:
            present = [c for c in ERROR_EXAMPLE_COLUMNS if c in exome_data.columns]
            indel_errors = exome_data["indel"].astype(bool) & (
                (exome_data["fp"] & (exome_data["filter"] == "PASS")) | exome_data["fn"])
            hmer_len = np.nan_to_num(np.asarray(exome_data.get("hmer_length", 0), dtype=float))
            hmer_err = exome_data[indel_errors & (hmer_len > 0)][present]
            non_hmer_err = exome_data[indel_errors & (hmer_len == 0)][present]
            snp_err = exome_data[~exome_data["tp"] & ~exome_data["indel"].astype(bool)
                                 & (exome_data["filter"] == "PASS")][present].head(20)
            for key, tab in (("exome_hmer_indel_errors", hmer_err),
                             ("exome_non_hmer_indel_errors", non_hmer_err),
                             ("exome_snp_errors", snp_err)):
                if len(tab):
                    ru._to_hdf(tab.reset_index(drop=True).astype(str), key)
            _section(sections, "4.1 Exome hmer-indel error examples", hmer_err)
            _section(sections, "4.2 Exome non-hmer-indel error examples", non_hmer_err)
            _section(sections, "4.3 Exome SNP error examples", snp_err)

    # --- 5. well-covered, well-mapped regions (notebook cell 18) ----------
    if verbosity > 1 and "well_mapped_coverage" in data.columns and "mappability.0" in data.columns:
        good = data[(data["well_mapped_coverage"] >= 20) & data["mappability.0"].astype(bool)].copy()
        if len(good):
            opt, _ = ru.basic_analysis(good, REGION_CATEGORIES, "good_cvg_data")
            _section(sections, "5. Coverage>=20 w/ mappability — accuracy", opt)
            try:
                hom = ru.homozygous_genotyping_analysis(
                    good, ["SNP", "Indel", "non-hmer Indel", "non-hmer Indel w/o LCR",
                           "hmer Indel <=4", "hmer Indel >4,<=8"], "good_cvg_data_homozygous")
                _section(sections, "5.1 Homozygous accuracy", hom)
            except KeyError as e:
                logger.warning("good-coverage homozygous section skipped (missing column %s)", e)

    # --- 6. callable regions (notebook cell 19) ---------------------------
    if verbosity > 1 and "callable" in data.columns:
        callable_data = data[data["callable"].astype(bool)].copy()
        if len(callable_data):
            opt, _ = ru.basic_analysis(callable_data, FINE_CATEGORIES, "callable_data")
            _section(sections, "6. Callable regions — accuracy", opt)

    # --- 7. indel analysis (notebook cell 20) -----------------------------
    if verbosity > 2:
        ru.indel_analysis(data, "wg")
        if len(ug_hcr_data):
            ru.indel_analysis(ug_hcr_data, "ug-hcr")
        if len(exome_data):
            ru.indel_analysis(exome_data, "exome")

    if args.html_output:
        with open(args.html_output, "w", encoding="utf-8") as fh:
            fh.write("<html><head><title>Variant Report</title></head><body>\n")
            fh.write(f"<h1>Variant calling accuracy report {args.pipeline_version}</h1>\n")
            for title, tab in sections.items():
                fh.write(f"<h2>{title}</h2>\n")
                fh.write(tab.to_html(float_format=lambda x: f"{x:.4f}"))
            fh.write("</body></html>\n")
        logger.info("wrote %s", args.html_output)
    logger.info("wrote %s (%d sections)", h5_out, len(sections))
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
