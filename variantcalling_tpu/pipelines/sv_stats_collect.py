"""sv_stats_collect — SV size/type histograms + ground-truth concordance.

Drop-in surface of the reference tool (ugvc/pipelines/sv_stats_collect.py:
16-262): positional ``svcall_vcf output_file`` with ``--concordance_h5`` /
``--ignore_filter``; pickled results dict with keys ``type_counts``,
``length_counts``, ``length_by_type_counts`` and, with a concordance h5
(keys ``base``/``calls``), ``concordance`` + ``fp_stats``. Histograms are
computed from the columnar VCF table; PR/ROC uses the FN-mask-aware curve
(utils/stats_utils, parity with ugbio_core.stats_utils).
"""

from __future__ import annotations

import argparse
import pickle
import sys

import numpy as np
import pandas as pd

from variantcalling_tpu.io.vcf import MISSING, read_vcf
from variantcalling_tpu.utils.stats_utils import precision_recall_curve

SVBINS = [0, 100, 300, 500, 1000, 2000, 3000, 5000, 10000, 100000, 1000000, float("inf")]
SVLABELS = ["50-100", "100-300", "300-500", "0.5-1k", "1k-2k", "2k-3k", "3k-5k", "5k-10k", "10k-100k", "100k-1M", ">1M"]

MIN_CLASS_COUNTS_TO_OUTPUT = 20


def collect_size_type_histograms(svcall_vcf: str, ignore_filter: bool = False) -> dict[str, pd.DataFrame]:
    """Size and type histograms from an SV call VCF (reference :16-60)."""
    table = read_vcf(svcall_vcf, drop_format=True)
    svlen = table.info_field("SVLEN", dtype=np.float64, missing=np.nan)
    svtype = np.array(
        [_info_str(s, "SVTYPE") for s in table.info], dtype=object
    )
    df = pd.DataFrame({"svlen": svlen, "svtype": svtype, "filter": table.filters})
    if not ignore_filter:
        df = df[df["filter"].isin(["PASS", "", MISSING])]
    df["svlen"] = df["svlen"].fillna(0)
    df["binned_svlens"] = pd.cut(df["svlen"].abs(), bins=SVBINS, labels=SVLABELS, right=False)
    type_counts = df["svtype"].value_counts()
    length_counts = df["binned_svlens"].value_counts().sort_index()
    by_type = df.groupby(["svtype", "binned_svlens"], observed=False).size().unstack().fillna(0)
    by_type = by_type.reindex(columns=SVLABELS, fill_value=0)
    by_type = by_type.drop("CTX", errors="ignore")
    return {"type_counts": type_counts, "length_counts": length_counts, "length_by_type_counts": by_type}


def _info_str(info: str, key: str) -> str:
    if info in (None, MISSING, ""):
        return ""
    for part in info.split(";"):
        if part.startswith(key + "="):
            return part.split("=", 1)[1]
    return ""


def concordance_with_gt(df_base: pd.DataFrame, df_calls: pd.DataFrame) -> pd.Series:
    """TP/FN/FP + precision/recall/F1 from labeled base/calls frames (:63-97)."""
    tp_base = int((df_base["label"] == "TP").sum())
    tp_calls = int((df_calls["label"] == "TP").sum())
    fn = int((df_base["label"] == "FN").sum())
    fp = int((df_calls["label"] == "FP").sum())
    precision = tp_calls / (tp_calls + fp) if (tp_calls + fp) > 0 else 0
    recall = tp_base / (tp_base + fn) if (tp_base + fn) > 0 else 0
    f1 = 2 * precision * recall / (precision + recall) if (precision + recall) > 0 else 0
    return pd.Series(
        {"TP_base": tp_base, "TP_calls": tp_calls, "FN": fn, "FP": fp, "Precision": precision, "Recall": recall, "F1": f1}
    )


def concordance_with_gt_roc(df_base: pd.DataFrame, df_calls: pd.DataFrame) -> pd.Series:
    """Precision/recall/threshold arrays; FN records fold into recall (:100-130)."""
    gt = pd.concat((df_base[df_base["label"] == "FN"], df_calls))
    predictions = gt["qual"].fillna(0)
    fn_mask = gt["label"] == "FN"
    labels = gt["label"].replace({"FN": "TP"})
    precision, recall, thresholds, _ = precision_recall_curve(
        np.array(labels),
        np.array(predictions),
        np.array(fn_mask),
        pos_label="TP",
        min_class_counts_to_output=MIN_CLASS_COUNTS_TO_OUTPUT,
    )
    return pd.Series(dict(zip(["precision", "recall", "thresholds"], [precision, recall, thresholds])))


def collect_sv_stats(
    svcall_vcf: str, concordance_h5: str | None = None, ignore_filter: bool = False
) -> tuple[dict, dict, pd.Series]:
    sv_stats = collect_size_type_histograms(svcall_vcf, ignore_filter=ignore_filter)
    concordance_stats: dict = {}
    fp_stats = pd.Series(dtype="int64")
    if concordance_h5 is not None:
        from variantcalling_tpu.utils.h5_utils import read_hdf

        df_base = read_hdf(concordance_h5, key="base")
        df_calls = read_hdf(concordance_h5, key="calls")
        for df in (df_base, df_calls):
            df["binned_svlens"] = pd.cut(df["svlen_int"].abs(), bins=SVBINS, labels=SVLABELS, right=False)

        for svtype in ["ALL", "DEL", "DUP", "INV", "INS", "CTX"]:
            b = df_base if svtype == "ALL" else df_base[df_base["svtype"] == svtype]
            c = df_calls if svtype == "ALL" else df_calls[df_calls["svtype"] == svtype]
            concordance_stats[f"{svtype}_concordance"] = concordance_with_gt(b, c)
            concordance_stats[f"{svtype}_roc"] = concordance_with_gt_roc(b, c)

        for svtype in ["ALL", "DEL", "INS"]:
            for len_bin in SVLABELS:
                b = df_base if svtype == "ALL" else df_base[df_base["svtype"] == svtype]
                c = df_calls if svtype == "ALL" else df_calls[df_calls["svtype"] == svtype]
                b = b[b["binned_svlens"] == len_bin]
                c = c[c["binned_svlens"] == len_bin]
                concordance_stats[f"{svtype}_{len_bin}_concordance"] = concordance_with_gt(b, c).drop(
                    ["FP", "Precision", "F1"]
                )
        fp_stats = (
            df_calls[df_calls["label"] == "FP"][["svtype", "binned_svlens"]]
            .value_counts()
            .sort_index()
            .astype("int64")
        )
    return sv_stats, concordance_stats, fp_stats


def run(argv: list[str]):
    ap = argparse.ArgumentParser(
        prog="sv_stats_collect",
        description="Collect SV statistics from a VCF file and (optionally) concordance H5.",
    )
    ap.add_argument("svcall_vcf", type=str, help="Path to the SV call VCF file.")
    ap.add_argument("output_file", type=str, help="Output PKL file.")
    ap.add_argument("--concordance_h5", type=str, default=None)
    ap.add_argument("--ignore_filter", action="store_true", default=False)
    args = ap.parse_args(argv)

    sv_stats, concordance_stats, fp_stats = collect_sv_stats(args.svcall_vcf, args.concordance_h5, args.ignore_filter)
    results: dict = {}
    if concordance_stats:
        concordance_df = pd.DataFrame({k: v for k, v in concordance_stats.items() if "concordance" in k}).T
        idx = pd.DataFrame(
            [x.split("_") if x.count("_") == 2 else x.replace("_", "__").split("_") for x in concordance_df.index]
        )
        idx = idx.drop(2, axis=1)
        idx.columns = ["SV type", "SV length"]
        concordance_df = pd.concat([idx, concordance_df.reset_index().drop("index", axis=1)], axis=1).set_index(
            ["SV type", "SV length"]
        )
        roc_df = pd.DataFrame({k: v for k, v in concordance_stats.items() if "roc" in k}).T
        roc_df = pd.concat([idx, roc_df.reset_index().drop("index", axis=1)], axis=1).set_index(["SV type", "SV length"])
        roc_df = roc_df.rename(columns={"precision": "precision roc", "recall": "recall roc"})
        results["concordance"] = pd.concat((concordance_df, roc_df), axis=1)
        results["fp_stats"] = fp_stats
    results.update(sv_stats)
    with open(args.output_file, "wb") as f:
        pickle.dump(results, f)
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
