"""quick_fingerprinting — verify sample identity of BAMs vs known ground truths.

Drop-in surface of the reference CLI
(ugvc/pipelines/comparison/quick_fingerprinting.py:14-81): JSON conf with
``cram_files`` (sample -> [paths]), ``ground_truth_vcf_files``,
``ground_truth_hcr_files``, ``references.ref_fasta``. This framework's
caller reads BAM directly (use ``samtools view -b`` upstream for CRAM).
"""

from __future__ import annotations

import argparse
import json
import sys

from variantcalling_tpu.comparison.pileup_caller import VariantHitFractionCaller
from variantcalling_tpu.comparison.quick_fingerprinter import QuickFingerprinter


def run(argv: list[str]):
    """quick fingerprinting to identify known samples in bams/crams"""
    ap = argparse.ArgumentParser(prog="quick_fingerprinting", description=run.__doc__)
    ap.add_argument("--json_conf", required=True, help="json with sample-names, crams, and ground truth files")
    ap.add_argument(
        "--region_str",
        type=str,
        default="chr15:26000000-26200000",
        help="region subset string, compare variants only in this region",
    )
    VariantHitFractionCaller.add_args_to_parser(ap)
    ap.add_argument("--out_dir", type=str, required=True, help="output directory")
    args = ap.parse_args(argv)

    with open(args.json_conf, encoding="utf-8") as fh:
        conf = json.load(fh)

    QuickFingerprinter(
        conf["cram_files"],
        conf["ground_truth_vcf_files"],
        conf["ground_truth_hcr_files"],
        conf["references"]["ref_fasta"],
        args.region_str,
        args.min_af_snps,
        args.min_hit_fraction_target,
        args.out_dir,
    ).check()
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
