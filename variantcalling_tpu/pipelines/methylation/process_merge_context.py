"""process_merge_context — CpG-context methylation metrics (strand-merged).

Reference surface: ugvc/__main__.py:23 (internals in missing submodule).
Merges +/- strand CpG rows (--mergeContext semantics), then reduces
genome-wide metrics on device: methylation-fraction histogram, coverage ×
methylation stats, global summary. Output: h5 keys ``summary``,
``histogram``, ``coverage_stats``, and optionally the merged bedGraph.
"""

from __future__ import annotations

import argparse
import sys

import numpy as np
import pandas as pd

from variantcalling_tpu import logger
from variantcalling_tpu.utils.h5_utils import write_hdf
from variantcalling_tpu.methyl import (
    coverage_methylation_stats,
    global_methylation_summary,
    merge_cpg_strands,
    methylation_histogram,
    read_extract_bedgraph,
)


def parse_args(argv, prog="process_merge_context"):
    ap = argparse.ArgumentParser(prog=prog, description=run.__doc__)
    ap.add_argument("--input", required=True, help="MethylDackel extract bedGraph (CpG context)")
    ap.add_argument("--output", required=True, help="metrics h5")
    ap.add_argument("--merged_bedgraph", help="also write the strand-merged bedGraph here")
    ap.add_argument("--verbosity", default="INFO")
    return ap.parse_args(argv)


def process(df: pd.DataFrame, output: str, merged_bedgraph: str | None, merge_strands: bool) -> None:
    if merge_strands:
        df = merge_cpg_strands(df)
    if merged_bedgraph:
        df.to_csv(merged_bedgraph, sep="\t", index=False, header=False)
    nm, nu = df["n_meth"].to_numpy(), df["n_unmeth"].to_numpy()
    write_hdf(global_methylation_summary(df), output, key="summary", mode="w")
    hist = methylation_histogram(nm, nu)
    write_hdf(pd.DataFrame({"bin": np.arange(len(hist)), "n_sites": hist}), output, key="histogram", mode="a")
    write_hdf(coverage_methylation_stats(nm, nu), output, key="coverage_stats", mode="a")


def run(argv) -> int:
    """CpG-context methylation metrics with strand merging."""
    args = parse_args(argv)
    df = read_extract_bedgraph(args.input)
    process(df, args.output, args.merged_bedgraph, merge_strands=True)
    logger.info("merge-context metrics -> %s", args.output)
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
