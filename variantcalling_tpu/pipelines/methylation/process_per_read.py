"""process_per_read — per-read methylation distribution metrics.

Reference surface: ugvc/__main__.py:25 (internals in missing submodule;
MethylDackel perRead format is public: read, chrom, pos, meth_fraction,
n_sites). Device-reduces the per-read methylation histogram and the
n_sites-weighted summary.
"""

from __future__ import annotations

import argparse
import sys

import numpy as np
import pandas as pd

from variantcalling_tpu import logger
from variantcalling_tpu.methyl import methylation_histogram
from variantcalling_tpu.utils.h5_utils import write_hdf

PER_READ_COLS = ["read", "chrom", "pos", "meth_fraction", "n_sites"]


def parse_args(argv):
    ap = argparse.ArgumentParser(prog="process_per_read", description=run.__doc__)
    ap.add_argument("--input", required=True, help="MethylDackel perRead output")
    ap.add_argument("--output", required=True, help="metrics h5")
    ap.add_argument("--min_sites", type=int, default=1, help="ignore reads with fewer CpG sites")
    ap.add_argument("--verbosity", default="INFO")
    return ap.parse_args(argv)


def run(argv) -> int:
    """Per-read methylation metrics."""
    args = parse_args(argv)
    df = pd.read_csv(args.input, sep="\t", header=None, names=PER_READ_COLS, comment="#")
    df = df[pd.to_numeric(df["meth_fraction"], errors="coerce").notna()]
    df["meth_fraction"] = pd.to_numeric(df["meth_fraction"])
    df["n_sites"] = pd.to_numeric(df["n_sites"])
    df = df[df["n_sites"] >= args.min_sites]
    frac = df["meth_fraction"].to_numpy()
    # reuse the fraction histogram kernel: frac == nm/(nm+nu) with unit mass
    hist = methylation_histogram(frac, 1.0 - frac)
    write_hdf(pd.DataFrame({"bin": np.arange(len(hist)), "n_reads": hist}), args.output, key="histogram", mode="w")
    summary = pd.DataFrame(
        [
            {
                "n_reads": len(df),
                "mean_read_methylation": round(float(frac.mean()) if len(df) else 0.0, 5),
                "median_read_methylation": round(float(np.median(frac)) if len(df) else 0.0, 5),
                "mean_sites_per_read": round(float(df["n_sites"].mean()) if len(df) else 0.0, 3),
            }
        ]
    )
    write_hdf(summary, args.output, key="summary", mode="a")
    logger.info("per-read metrics (%d reads) -> %s", len(df), args.output)
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
