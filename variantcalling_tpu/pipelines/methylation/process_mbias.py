"""process_mbias — M-bias curves + trim-bound suggestion from MethylDackel mbias.

Reference surface: ugvc/__main__.py:22 (internals in missing submodule;
mbias --txt format is public). Outputs h5 keys ``mbias`` (per strand/read/
position curves) and ``inclusion_bounds`` (suggested trimming).
"""

from __future__ import annotations

import argparse
import sys

from variantcalling_tpu import logger
from variantcalling_tpu.utils.h5_utils import write_hdf
from variantcalling_tpu.methyl import mbias_curves, mbias_inclusion_bounds, read_mbias_txt


def parse_args(argv):
    ap = argparse.ArgumentParser(prog="process_mbias", description=run.__doc__)
    ap.add_argument("--input", required=True, help="MethylDackel mbias --txt output")
    ap.add_argument("--output", required=True, help="metrics h5")
    ap.add_argument("--tolerance", type=float, default=0.05)
    ap.add_argument("--verbosity", default="INFO")
    return ap.parse_args(argv)


def run(argv) -> int:
    """Process an M-bias table into curves and inclusion bounds."""
    args = parse_args(argv)
    df = read_mbias_txt(args.input)
    curves = mbias_curves(df)
    bounds = mbias_inclusion_bounds(curves, args.tolerance)
    write_hdf(curves, args.output, key="mbias", mode="w")
    write_hdf(bounds, args.output, key="inclusion_bounds", mode="a")
    logger.info("mbias curves (%d rows) + bounds (%d) -> %s", len(curves), len(bounds), args.output)
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
