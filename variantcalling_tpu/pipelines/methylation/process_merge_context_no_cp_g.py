"""process_merge_context_no_cp_g — non-CpG (CHG/CHH) methylation metrics.

Reference surface: ugvc/__main__.py:24. Same reductions as
process_merge_context but without strand merging (non-CpG contexts are not
palindromic).
"""

from __future__ import annotations

import sys

from variantcalling_tpu import logger
from variantcalling_tpu.methyl import read_extract_bedgraph
from variantcalling_tpu.pipelines.methylation.process_merge_context import parse_args, process


def run(argv) -> int:
    """Non-CpG-context methylation metrics (no strand merge)."""
    args = parse_args(argv, prog="process_merge_context_no_cp_g")
    df = read_extract_bedgraph(args.input)
    process(df, args.output, args.merged_bedgraph, merge_strands=False)
    logger.info("non-CpG metrics -> %s", args.output)
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
