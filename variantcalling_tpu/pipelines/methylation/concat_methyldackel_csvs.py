"""concat_methyldackel_csvs — merge per-shard MethylDackel extract outputs.

Reference surface: ugbio_methylation concat_methyldackel_csvs
(ugvc/__main__.py:21; internals missing — MethylDackel bedGraph format is
public). Concatenates per-region/per-chunk extract CSVs in genomic order
and merges duplicate sites by summing counts.
"""

from __future__ import annotations

import argparse
import sys

from variantcalling_tpu import logger
from variantcalling_tpu.methyl import read_extract_bedgraph


def parse_args(argv):
    ap = argparse.ArgumentParser(prog="concat_methyldackel_csvs", description=run.__doc__)
    ap.add_argument("--inputs", nargs="+", required=True, help="per-shard extract bedGraph/CSV files")
    ap.add_argument("--output", required=True, help="merged CSV")
    ap.add_argument("--verbosity", default="INFO")
    return ap.parse_args(argv)


def run(argv) -> int:
    """Concatenate and sort MethylDackel extract shards."""
    import pandas as pd

    args = parse_args(argv)
    frames = [read_extract_bedgraph(p) for p in args.inputs]
    df = pd.concat(frames, ignore_index=True)
    df = (
        df.groupby(["chrom", "start", "end"], as_index=False)[["n_meth", "n_unmeth"]]
        .sum()
        .sort_values(["chrom", "start"])
    )
    tot = (df["n_meth"] + df["n_unmeth"]).clip(lower=1)
    df["meth_pct"] = (100.0 * df["n_meth"] / tot).round(2)
    df = df[["chrom", "start", "end", "meth_pct", "n_meth", "n_unmeth"]]
    df.to_csv(args.output, sep="\t", index=False, header=False)
    logger.info("%d sites from %d shards -> %s", len(df), len(args.inputs), args.output)
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
