"""run_comparison_pipeline — compare a callset to ground truth.

Drop-in surface of the reference tool (docs/run_comparison_pipeline.md):
produces the per-chromosome-keyed concordance HDF5 (schema per
report_data_loader.py:66-104) and the intersected-intervals BED. The
matching engine is the native haplotype matcher
(variantcalling_tpu.comparison.matcher) instead of an rtg vcfeval
subprocess; annotation runs through the shared device featurization
kernels, so classification + annotation of a 5M-variant callset is a
handful of jitted batches rather than per-record Python.

Genotype columns are stored as "a/b" strings (the h5 store is columnar);
downstream consumers parse them with utils column helpers.
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np
import pandas as pd

from variantcalling_tpu import logger
from variantcalling_tpu.comparison.matcher import make_side, match_contig
from variantcalling_tpu.featurize import featurize
from variantcalling_tpu.io import bed as bedio
from variantcalling_tpu.io.fasta import FastaReader
from variantcalling_tpu.io.vcf import VariantTable, read_vcf
from variantcalling_tpu.ops import intervals as iops
from variantcalling_tpu.utils.h5_utils import write_hdf


def parse_args(argv: list[str]):
    ap = argparse.ArgumentParser(prog="run_comparison_pipeline", description=run.__doc__)
    ap.add_argument("--n_parts", type=int, default=0, help="Number of parts the VCF is split into")
    ap.add_argument("--input_prefix", required=True, help="Prefix of the input file (or full path)")
    ap.add_argument("--output_file", required=True, help="Output h5 file")
    ap.add_argument("--output_interval", required=True, help="Output bed of intersected intervals")
    ap.add_argument("--gtr_vcf", required=True, help="Ground truth VCF")
    ap.add_argument("--cmp_intervals", help="Ranges on which to perform comparison (bed/interval_list)")
    ap.add_argument("--highconf_intervals", required=True, help="High confidence intervals")
    ap.add_argument("--runs_intervals", help="Runs intervals (bed/interval_list)")
    ap.add_argument("--annotate_intervals", action="append", default=[])
    ap.add_argument("--reference", required=True, help="Reference FASTA")
    ap.add_argument("--reference_dict", help="(accepted for drop-in compatibility; unused)")
    ap.add_argument("--coverage_bw_high_quality", action="append", default=None,
                    help="BigWig file with coverage only on high mapq reads")
    ap.add_argument("--coverage_bw_all_quality", action="append", default=None,
                    help="BigWig file with coverage on all mapq reads")
    ap.add_argument("--call_sample_name", default="sm1")
    ap.add_argument("--truth_sample_name", default="HG001")
    ap.add_argument("--header_file", help="(accepted; unused)")
    ap.add_argument("--filter_runs", action="store_true")
    ap.add_argument("--hpol_filter_length_dist", nargs=2, type=int, default=[10, 10])
    ap.add_argument("--ignore_filter_status", action="store_true")
    ap.add_argument("--flow_order", default="TGCA")
    ap.add_argument("--output_suffix", default="")
    ap.add_argument("--concordance_tool", default="native",
                    choices=["native", "VCFEVAL", "vcfeval", "GC"],
                    help="native/vcfeval: haplotype matcher (VCFEVAL-equivalent); "
                         "GC: exact-position GenotypeConcordance joins "
                         "(docs/run_comparison_pipeline.md:76-77)")
    ap.add_argument("--disable_reinterpretation", action="store_true",
                    help="skip the haplotype-rescue (representation repair) matching stage")
    ap.add_argument("--is_mutect", action="store_true")
    ap.add_argument("--n_jobs", type=int, default=-1, help="(accepted; XLA owns parallelism)")
    ap.add_argument("--verbosity", default="INFO")
    return ap.parse_args(argv)


def _input_path(prefix: str, n_parts: int) -> list[str]:
    if os.path.exists(prefix):
        return [prefix]
    if n_parts and n_parts > 1:
        parts = []
        for i in range(1, n_parts + 1):
            for ext in (f"{prefix}.{i}.vcf.gz", f"{prefix}.{i}.vcf"):
                if os.path.exists(ext):
                    parts.append(ext)
                    break
        if parts:
            return parts
    for ext in (prefix + ".vcf.gz", prefix + ".vcf"):
        if os.path.exists(ext):
            return [ext]
    raise FileNotFoundError(f"no VCF found for prefix {prefix!r}")


def _concat_tables(tables: list[VariantTable]) -> VariantTable:
    if len(tables) == 1:
        return tables[0]
    base = tables[0]
    for t in tables:
        t.materialize_format()  # cross-buffer concat cannot keep lazy spans
    kw = {}
    for f in ("chrom", "pos", "vid", "ref", "alt", "qual", "filters", "info"):
        kw[f] = np.concatenate([getattr(t, f) for t in tables])
    out = VariantTable(header=base.header, **kw)
    if base.fmt_keys is not None:
        out.fmt_keys = np.concatenate([t.fmt_keys for t in tables])
        out.sample_cols = np.concatenate([t.sample_cols for t in tables], axis=0)
    return out


def _subset(table: VariantTable, mask: np.ndarray) -> VariantTable:
    return table.subset(mask)


def _gt_strings(table: VariantTable) -> list[str]:
    gts = table.genotypes()
    return ["/".join(str(a) if a >= 0 else "." for a in g) for g in gts]


def _restrict(table: VariantTable, intervals: bedio.IntervalSet) -> VariantTable:
    if intervals is None or len(intervals) == 0:
        return table
    mask = intervals.contains(np.asarray(table.chrom), table.pos - 1)
    return _subset(table, np.asarray(mask))


class _GCResult:
    """match_contig-shaped result from the genotype-concordance join."""

    __slots__ = ("call_tp", "call_tp_gt", "truth_tp", "truth_tp_gt", "call_truth_idx")

    def __init__(self, call_tp, call_tp_gt, truth_tp, truth_tp_gt, call_truth_idx):
        self.call_tp = call_tp
        self.call_tp_gt = call_tp_gt
        self.truth_tp = truth_tp
        self.truth_tp_gt = truth_tp_gt
        self.call_truth_idx = call_truth_idx


def genotype_concordance_match(calls: VariantTable, truth: VariantTable) -> _GCResult:
    """The "GC" comparison flavor (--concordance_tool GC,
    docs/run_comparison_pipeline.md:76-77): picard GenotypeConcordance-
    style EXACT position joins — no haplotype search, no representation
    repair. A call is tp when a truth record at the same (pos) carries an
    overlapping called ALT allele; tp_gt additionally requires the same
    called-allele multiset.
    """
    def called_alleles(table):
        gts = table.genotypes()
        out = []
        for i in range(len(table)):
            alleles = [table.ref[i]] + ([] if table.alt[i] in (".", "") else table.alt[i].split(","))
            called = [alleles[a] for a in gts[i] if 0 <= a < len(alleles)]
            alt_called = {alleles[a] for a in gts[i] if 0 < a < len(alleles)}
            out.append((tuple(sorted(called)), alt_called))
        return out

    c_all = called_alleles(calls)
    t_all = called_alleles(truth)
    # every truth record per position — decomposed multiallelics put
    # several records at one pos, and a call must match against ANY of them
    t_by_pos: dict[int, list[int]] = {}
    for j in range(len(truth)):
        t_by_pos.setdefault(int(truth.pos[j]), []).append(j)

    n_c, n_t = len(calls), len(truth)
    call_tp = np.zeros(n_c, dtype=bool)
    call_tp_gt = np.zeros(n_c, dtype=bool)
    truth_tp = np.zeros(n_t, dtype=bool)
    truth_tp_gt = np.zeros(n_t, dtype=bool)
    call_truth_idx = np.full(n_c, -1, dtype=np.int64)
    for i in range(n_c):
        cands = t_by_pos.get(int(calls.pos[i]), [])
        if not cands:
            continue
        best, exact = -1, False
        for j in cands:
            if c_all[i][1] & t_all[j][1]:
                if c_all[i][0] == t_all[j][0]:
                    best, exact = j, True
                    break
                if best < 0:
                    best = j
        # unmatched calls keep -1 (same semantics as the native matcher);
        # annotating fp calls with an unrelated co-located truth GT made
        # the call_truth_gt column mean different things per tool
        call_truth_idx[i] = best
        if best >= 0:
            call_tp[i] = truth_tp[best] = True
            if exact:
                call_tp_gt[i] = truth_tp_gt[best] = True
    return _GCResult(call_tp, call_tp_gt, truth_tp, truth_tp_gt, call_truth_idx)


def build_concordance_frame(
    calls: VariantTable,
    truth: VariantTable,
    fasta: FastaReader,
    annotate_intervals: dict[str, bedio.IntervalSet] | None = None,
    runs_intervals: bedio.IntervalSet | None = None,
    hpol_length: int = 10,
    hpol_dist: int = 10,
    flow_order: str = "TGCA",
    is_mutect: bool = False,
    reinterpret: bool = True,
    tool: str = "native",
) -> pd.DataFrame:
    """Match + annotate -> one concordance DataFrame over calls ∪ FN-truth.

    ``reinterpret=False`` (--disable_reinterpretation) turns off the
    matcher's haplotype-rescue stage, leaving exact-representation joins —
    the reference's reinterpretation stage exists to repair representation
    artifacts of the black-box comparator, and the haplotype search is this
    framework's native form of that repair.
    """
    contigs = list(dict.fromkeys(list(calls.chrom) + list(truth.chrom)))
    call_tp = np.zeros(len(calls), dtype=bool)
    call_tp_gt = np.zeros(len(calls), dtype=bool)
    truth_tp = np.zeros(len(truth), dtype=bool)
    truth_tp_gt = np.zeros(len(truth), dtype=bool)
    call_truth_gt = np.full(len(calls), "./.", dtype=object)

    for contig in contigs:
        cm = np.asarray(calls.chrom) == contig
        tm = np.asarray(truth.chrom) == contig
        if contig not in fasta.references:
            continue
        if tool == "GC":
            res = genotype_concordance_match(_subset(calls, cm), _subset(truth, tm))
        else:
            # only the haplotype matcher needs the contig sequence
            seq = fasta.fetch(contig, 0, fasta.get_reference_length(contig))
            cs = make_side(calls.pos[cm], list(calls.ref[cm]),
                           [a.split(",") if a not in (".", "") else [] for a in calls.alt[cm]],
                           calls.genotypes()[cm])
            ts = make_side(truth.pos[tm], list(truth.ref[tm]),
                           [a.split(",") if a not in (".", "") else [] for a in truth.alt[tm]],
                           truth.genotypes()[tm])
            res = match_contig(cs, ts, seq, haplotype_rescue=reinterpret)
        call_tp[cm] = res.call_tp
        call_tp_gt[cm] = res.call_tp_gt
        truth_tp[tm] = res.truth_tp
        truth_tp_gt[tm] = res.truth_tp_gt
        t_gt = np.asarray(_gt_strings(_subset(truth, tm)), dtype=object) if tm.any() else np.array([], object)
        matched = res.call_truth_idx >= 0
        sub = call_truth_gt[cm]
        sub[matched] = t_gt[res.call_truth_idx[matched]]
        call_truth_gt[cm] = sub

    fn_mask = ~truth_tp
    fn_truth = _subset(truth, fn_mask)

    frames = []
    for table, is_call in ((calls, True), (fn_truth, False)):
        if len(table) == 0:
            continue
        fs = featurize(table, fasta, annotate_intervals=annotate_intervals, flow_order=flow_order,
                       extra_info_fields=["TLOD"] if is_mutect else [])
        cols: dict[str, np.ndarray] = {
            "chrom": np.asarray(table.chrom),
            "pos": table.pos,
            "ref": np.asarray(table.ref),
            "alleles": np.asarray(table.alt),
            "qual": np.nan_to_num(table.qual, nan=0.0),
            "filter": _filters_norm(table),
        }
        for f in ("dp", "af", "gq", "indel_length", "hmer_indel_length", "hmer_indel_nuc",
                  "gc_content", "left_motif", "right_motif", "cycleskip_status", "sor"):
            cols[f] = np.asarray(fs.columns[f])
        cols["vaf"] = cols.pop("af")
        cols["indel"] = np.asarray(fs.columns["is_indel"], dtype=bool)
        ic = np.full(len(table), None, dtype=object)
        ic[np.asarray(fs.columns["is_ins"], dtype=bool)] = "ins"
        ic[cols["indel"] & ~np.asarray(fs.columns["is_ins"], dtype=bool)] = "del"
        cols["indel_classify"] = ic
        cols["tree_score"] = table.info_field("TREE_SCORE")
        cols["ad"] = [",".join(f"{int(v)}" for v in row if v >= 0) for row in table.format_numeric("AD")]
        for name in (annotate_intervals or {}):
            cols[name] = np.asarray(fs.columns[name], dtype=bool)
        if is_call:
            cols["classify"] = np.where(call_tp, "tp", "fp")
            cols["classify_gt"] = np.where(call_tp_gt, "tp", "fp")
            cols["call"] = np.where(call_tp, "TP", "FP")
            cols["base"] = np.where(call_tp, "TP", None)
            cols["gt_ultima"] = np.asarray(_gt_strings(table), dtype=object)
            cols["gt_ground_truth"] = call_truth_gt
        else:
            cols["classify"] = np.full(len(table), "fn", dtype=object)
            cols["classify_gt"] = np.full(len(table), "fn", dtype=object)
            cols["call"] = np.full(len(table), "NA", dtype=object)
            cols["base"] = np.full(len(table), "FN", dtype=object)
            cols["gt_ultima"] = np.full(len(table), "./.", dtype=object)
            cols["gt_ground_truth"] = np.asarray(_gt_strings(table), dtype=object)
        cols["blacklst"] = np.full(len(table), "", dtype=object)
        frames.append(pd.DataFrame(cols))

    df = pd.concat(frames, ignore_index=True) if frames else pd.DataFrame()
    if len(df):
        df = df.sort_values(["chrom", "pos"], kind="stable").reset_index(drop=True)
        if runs_intervals is not None and len(runs_intervals):
            keep = (runs_intervals.end - runs_intervals.start) >= hpol_length
            runs = bedio.IntervalSet(runs_intervals.chrom[keep], runs_intervals.start[keep],
                                     runs_intervals.end[keep])
            contig_lengths = {c: fasta.get_reference_length(c) for c in fasta.references}
            coords = iops.GenomeCoords(contig_lengths)
            gpos = coords.globalize(df["chrom"].to_numpy(), df["pos"].to_numpy() - 1)
            if len(runs):
                gs, ge = coords.globalize_intervals(runs)
                df["hpol_run"] = np.asarray(iops.distance_to_nearest(gpos, gs, ge) <= hpol_dist)
            else:
                df["hpol_run"] = False
        else:
            df["hpol_run"] = False
    return df


def _filters_norm(table: VariantTable) -> np.ndarray:
    return np.asarray(["PASS" if f in (".", "", None) else f for f in table.filters], dtype=object)


def annotate_coverage(df: pd.DataFrame, bw_high: list[str] | None, bw_all: list[str] | None) -> None:
    """Per-variant coverage columns from bigWig tracks (in place).

    ``well_mapped_coverage`` from the high-mapq track(s), ``coverage`` from
    the all-mapq track(s) — the schema report_data_loader.py:77 consumes
    (reference annotates these inside ugbio_comparison from the same two
    --coverage_bw_* flag sets). Multiple files per flag are concatenated
    (reference accepts per-contig splits).
    """
    from variantcalling_tpu.io.bigwig import BigWigReader

    max_span = 1 << 22  # decode at most 4 Mb per query window

    for name, paths in (("well_mapped_coverage", bw_high), ("coverage", bw_all)):
        if not paths:
            continue
        out = np.full(len(df), np.nan)
        readers = [BigWigReader(p) for p in paths]
        for contig in dict.fromkeys(df["chrom"].tolist()):
            m = (df["chrom"] == contig).to_numpy()
            pos0 = df.loc[m, "pos"].to_numpy() - 1
            order = np.argsort(pos0)
            sorted_pos = pos0[order]
            vals = np.full(m.sum(), np.nan)
            for bw in readers:
                if bw.chroms(str(contig)) is None:
                    continue
                # bounded windows over the sorted positions: whole-chromosome
                # spans (WGS) would otherwise decode GB-scale arrays
                got_sorted = np.full(len(sorted_pos), np.nan)
                i = 0
                while i < len(sorted_pos):
                    lo = int(sorted_pos[i])
                    j = int(np.searchsorted(sorted_pos, lo + max_span, side="left"))
                    hi = int(sorted_pos[j - 1]) + 1
                    window = bw.values(str(contig), lo, hi)
                    got_sorted[i:j] = window[sorted_pos[i:j] - lo]
                    i = j
                got = np.empty_like(got_sorted)
                got[order] = got_sorted
                vals = np.where(np.isnan(vals), got, vals)
            out[m] = vals
        df[name] = out


def run(argv: list[str]) -> int:
    """Compare VCF to ground truth."""
    args = parse_args(argv)
    import logging

    logger.setLevel(getattr(logging, args.verbosity))

    paths = _input_path(args.input_prefix, args.n_parts)
    logger.info("reading calls: %s", paths)
    calls = _concat_tables([read_vcf(p) for p in paths])
    truth = read_vcf(args.gtr_vcf)

    highconf = bedio.read_intervals(args.highconf_intervals)
    region = highconf
    if args.cmp_intervals:
        region = highconf.intersect(bedio.read_intervals(args.cmp_intervals))
    bedio.write_bed(args.output_interval, region)

    calls = _restrict(calls, region)
    truth = _restrict(truth, region)
    logger.info("restricted to %d calls, %d truth variants", len(calls), len(truth))

    annotate = {}
    for path in args.annotate_intervals:
        name = os.path.basename(path)
        for suf in (".gz", ".bed", ".interval_list"):
            name = name[: -len(suf)] if name.endswith(suf) else name
        annotate[name] = bedio.read_intervals(path)
    runs = bedio.read_intervals(args.runs_intervals) if args.runs_intervals else None

    with FastaReader(args.reference) as fasta:
        df = build_concordance_frame(
            calls, truth, fasta,
            annotate_intervals=annotate,
            runs_intervals=runs,
            hpol_length=args.hpol_filter_length_dist[0],
            hpol_dist=args.hpol_filter_length_dist[1],
            flow_order=args.flow_order,
            is_mutect=args.is_mutect,
            reinterpret=not args.disable_reinterpretation,
            tool=args.concordance_tool,
        )

    if len(df) and (args.coverage_bw_high_quality or args.coverage_bw_all_quality):
        annotate_coverage(df, args.coverage_bw_high_quality, args.coverage_bw_all_quality)

    first = True
    for contig in dict.fromkeys(df["chrom"].tolist()) if len(df) else []:
        write_hdf(df[df["chrom"] == contig], args.output_file, key=str(contig), mode="w" if first else "a")
        first = False
    if len(df) == 0:
        write_hdf(df, args.output_file, key="all", mode="w")
    logger.info("wrote %d rows to %s", len(df), args.output_file)
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
