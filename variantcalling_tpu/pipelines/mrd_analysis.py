"""mrd_analysis — tumor-informed minimal-residual-disease estimation.

Reference surface: the ugbio_mrd package (setup.py:4-8; README:13 "set of
tools for MRD"; report ugvc/reports/mrd_automatic_data_analysis.ipynb).
Tumor-informed MRD: given the patient's somatic signature loci (tumor
mutations VCF) and a cfDNA featuremap of candidate supporting reads scored
by the single-read model (srsnv_inference ML_QUAL), estimate the tumor
fraction as a binomial maximum-likelihood over signature-locus read counts
with an error-rate background, plus an exact Clopper–Pearson interval.
The likelihood profile is evaluated on device as one vectorized sweep.
"""

from __future__ import annotations

import argparse
import sys

import numpy as np
import pandas as pd

import jax.numpy as jnp

from variantcalling_tpu import logger
from variantcalling_tpu.io.vcf import read_vcf
from variantcalling_tpu.utils.h5_utils import write_hdf


def parse_args(argv):
    ap = argparse.ArgumentParser(prog="mrd_analysis", description=run.__doc__)
    ap.add_argument("--signature_vcf", required=True, help="patient somatic mutations (tumor-informed)")
    ap.add_argument("--featuremap", required=True, help="cfDNA featuremap (srsnv_inference output)")
    ap.add_argument("--coverage_per_locus", type=float, required=True,
                    help="mean effective coverage per signature locus")
    ap.add_argument("--ml_qual_threshold", type=float, default=40.0)
    ap.add_argument("--background_error_rate", type=float, default=1e-6,
                    help="residual per-base error rate after filtering")
    ap.add_argument("--output_h5", required=True)
    ap.add_argument("--verbosity", default="INFO")
    return ap.parse_args(argv)


def count_supporting_reads(signature_vcf: str, featuremap: str, ml_qual_threshold: float) -> tuple[int, int]:
    """(n signature loci, reads supporting them above the quality bar)."""
    sig = read_vcf(signature_vcf)
    sig_loci = {(c, int(p)) for c, p in zip(sig.chrom, sig.pos)}
    fm = read_vcf(featuremap)
    qual = fm.info_field("ML_QUAL")
    n_support = 0
    for c, p, q in zip(fm.chrom, fm.pos, qual):
        if (c, int(p)) in sig_loci and (np.isnan(q) or q >= ml_qual_threshold):
            n_support += 1
    return len(sig_loci), n_support


def estimate_tumor_fraction(
    n_loci: int,
    n_support: int,
    coverage: float,
    background_rate: float,
    grid_size: int = 4001,
) -> dict:
    """Binomial ML estimate + 95% Clopper–Pearson over the support counts.

    Model: supporting reads ~ Binomial(n_trials, tf/2 + e) with
    n_trials = n_loci * coverage (tf/2: heterozygous somatic allele).
    """
    n_trials = max(int(round(n_loci * coverage)), 1)
    k = min(n_support, n_trials)
    # device-side likelihood profile over the tf grid
    tf_grid = jnp.linspace(0.0, 1.0, grid_size)
    p = jnp.clip(tf_grid / 2.0 + background_rate, 1e-12, 1 - 1e-12)
    log_l = k * jnp.log(p) + (n_trials - k) * jnp.log1p(-p)
    tf_hat = float(tf_grid[int(jnp.argmax(log_l))])
    # exact binomial CI on p, then back out tf = 2*(p - e)
    from scipy import stats

    lo_p = stats.beta.ppf(0.025, k, n_trials - k + 1) if k > 0 else 0.0
    hi_p = stats.beta.ppf(0.975, k + 1, n_trials - k) if k < n_trials else 1.0
    tf_lo = max(0.0, 2.0 * (lo_p - background_rate))
    tf_hi = min(1.0, 2.0 * (hi_p - background_rate))
    expected_bg = n_trials * background_rate
    # one-sided Poisson tail: P(X >= k | background only)
    detected = bool(k > 0 and stats.poisson.sf(k - 1, expected_bg) < 0.05)
    return {
        "n_signature_loci": n_loci,
        "n_supporting_reads": n_support,
        "n_trials": n_trials,
        "tumor_fraction": tf_hat,
        "tf_ci_low": tf_lo,
        "tf_ci_high": tf_hi,
        "expected_background_reads": expected_bg,
        "mrd_detected": detected,
    }


def run(argv) -> int:
    """Estimate tumor fraction from signature-locus supporting reads."""
    args = parse_args(argv)
    n_loci, n_support = count_supporting_reads(
        args.signature_vcf, args.featuremap, args.ml_qual_threshold
    )
    result = estimate_tumor_fraction(
        n_loci, n_support, args.coverage_per_locus, args.background_error_rate
    )
    write_hdf(pd.DataFrame([result]), args.output_h5, key="mrd_summary", mode="w")
    logger.info(
        "MRD: %d/%d supporting reads, tf=%.2e [%.2e, %.2e], detected=%s -> %s",
        result["n_supporting_reads"],
        result["n_trials"],
        result["tumor_fraction"],
        result["tf_ci_low"],
        result["tf_ci_high"],
        result["mrd_detected"],
        args.output_h5,
    )
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
