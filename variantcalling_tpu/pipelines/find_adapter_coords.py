"""find_adapter_coords — locate adapters/UMIs in uBAM reads, write XF/XT/RX tags.

Reference surface: ugvc/bash/find_adapter_coords.sh — samtools fastq →
cutadapt (mask adapters) → awk coordinate extraction → paste back into the
BAM. Same record semantics in-process, no fastq round-trip:

- XF:i = 1-based first coordinate after the 5' adapter (+ left UMI), 1 if
  no 5' adapter found, 0 if the whole read is adapter;
- XT:i = 1-based start of the 3' adapter (− right UMI), read_len+1 if no
  3' adapter found, 0 if the whole read is adapter;
- RX:Z = left UMI, revcomp(right UMI), or "left-right" (N-filled when the
  flanking adapter was not found).

Matching is cutadapt-style semi-global with per-overlap error budget
(``max_error_rate`` × overlap), mismatches only (no indels — flow-based
adapters are matched well by substitution-only scoring); partial matches
at the read start (5') / end (3') honor ``min_overlap``. Records stream
through untouched except for the appended tags (raw-bytes passthrough over
the BGZF layer), so names/quals/existing tags survive byte-identical.
"""

from __future__ import annotations

import argparse
import struct
import sys

import numpy as np

from variantcalling_tpu import logger
from variantcalling_tpu.io.bgzf import BgzfWriter

_NIB2CH = np.array(list("=ACMGRSVTWYHKDBN"), dtype="U1")
_COMP = {"A": "T", "C": "G", "G": "C", "T": "A", "N": "N"}


def parse_args(argv):
    ap = argparse.ArgumentParser(prog="find_adapter_coords", description=run.__doc__)
    ap.add_argument("--input_bam", required=True)
    ap.add_argument("--output_bam", required=True)
    ap.add_argument("--left_adapter", default="")
    ap.add_argument("--right_adapter", default="")
    ap.add_argument("--left_umi_length", type=int, default=0)
    ap.add_argument("--right_umi_length", type=int, default=0)
    ap.add_argument("--error_rate_5p", type=float, default=0.15)
    ap.add_argument("--error_rate_3p", type=float, default=0.2)
    ap.add_argument("--min_overlap_5p", type=int, default=5)
    ap.add_argument("--min_overlap_3p", type=int, default=5)
    return ap.parse_args(argv)


def _encode(seq: str) -> np.ndarray:
    return np.frombuffer(seq.encode(), dtype=np.uint8)


def find_left(read: np.ndarray, adapter: np.ndarray, error_rate: float, min_overlap: int) -> int:
    """Index AFTER the 5' adapter match (0 = none). Partial at read start OK."""
    la, lr = len(adapter), len(read)
    best_end = 0
    # offset o: adapter start relative to read start (negative = truncated)
    for o in range(-(la - min_overlap), lr - min_overlap + 1):
        a_lo = max(0, -o)
        overlap = min(la - a_lo, lr - max(o, 0))
        if overlap < min_overlap:
            continue
        r_lo = max(o, 0)
        errs = int(np.count_nonzero(adapter[a_lo : a_lo + overlap] != read[r_lo : r_lo + overlap]))
        if errs <= int(error_rate * overlap):
            return r_lo + overlap  # first occurrence wins (cutadapt -g)
    return best_end


def find_right(read: np.ndarray, adapter: np.ndarray, error_rate: float, min_overlap: int) -> int:
    """0-based start of the 3' adapter match (-1 = none). Partial at read end OK."""
    la, lr = len(adapter), len(read)
    for o in range(0, lr - min_overlap + 1):
        overlap = min(la, lr - o)
        if overlap < min_overlap:
            continue
        errs = int(np.count_nonzero(adapter[:overlap] != read[o : o + overlap]))
        if errs <= int(error_rate * overlap):
            return o
    return -1


def analyze_read(seq: str, args) -> tuple[int, int, str | None]:
    """(XF, XT, RX) per the reference awk logic."""
    read = _encode(seq)
    lr = len(read)
    end5 = find_left(read, _encode(args.left_adapter), args.error_rate_5p, args.min_overlap_5p) if args.left_adapter else 0
    start3 = find_right(read, _encode(args.right_adapter), args.error_rate_3p, args.min_overlap_3p) if args.right_adapter else -1
    coord1 = end5 + 1  # 1-based first non-adapter base (1 when no 5' adapter)
    coord2 = (start3 + 1) if start3 >= 0 else lr + 1
    if coord2 <= coord1:  # entire read masked
        coord1 = coord2 = 0
    umi1 = umi2 = None
    if args.left_umi_length > 0:
        if coord1 > 1:
            umi1 = seq[coord1 - 1 : coord1 - 1 + args.left_umi_length]
            coord1 += args.left_umi_length
        else:
            umi1 = "N" * args.left_umi_length
    if args.right_umi_length > 0:
        if start3 >= 0 and coord2 > 0:
            coord2 -= args.right_umi_length
            raw = seq[max(coord2 - 1, 0) : max(coord2 - 1, 0) + args.right_umi_length]
            umi2 = "".join(_COMP.get(b, "N") for b in reversed(raw))
        else:
            umi2 = "N" * args.right_umi_length
    if umi1 is not None and umi2 is not None:
        rx = f"{umi1}-{umi2}"
    else:
        rx = umi1 if umi1 is not None else umi2
    return coord1, coord2, rx


def _decode_seq(rec: bytes) -> str:
    lrn, flag_nc, l_seq = struct.unpack_from("<IIi", rec, 8)
    l_read_name = lrn & 0xFF
    n_cigar = flag_nc & 0xFFFF
    off = 32 + l_read_name + 4 * n_cigar
    packed = np.frombuffer(rec, dtype=np.uint8, count=(l_seq + 1) // 2, offset=off)
    nib = np.empty(len(packed) * 2, dtype=np.uint8)
    nib[0::2] = packed >> 4
    nib[1::2] = packed & 0xF
    return "".join(_NIB2CH[nib[:l_seq]])


def run(argv) -> int:
    """Tag every read with adapter coordinates (+UMIs)."""
    args = parse_args(argv)
    from variantcalling_tpu import native

    with open(args.input_bam, "rb") as fh:
        raw = fh.read()
    buf = native.bgzf_decompress(raw)
    if buf is None:
        import gzip

        buf = gzip.decompress(raw)
    if buf[:4] != b"BAM\x01":
        raise SystemExit(f"{args.input_bam}: not a BAM")
    (l_text,) = struct.unpack_from("<i", buf, 4)
    off = 8 + l_text
    (n_ref,) = struct.unpack_from("<i", buf, off)
    off += 4
    for _ in range(n_ref):
        (l_name,) = struct.unpack_from("<i", buf, off)
        off += 8 + l_name
    n = 0
    with BgzfWriter(args.output_bam) as out:
        out.write(buf[:off])  # header + reference list verbatim
        while off + 4 <= len(buf):
            (bs,) = struct.unpack_from("<i", buf, off)
            rec = buf[off + 4 : off + 4 + bs]
            off += 4 + bs
            xf, xt, rx = analyze_read(_decode_seq(rec), args)
            extra = b"XFi" + struct.pack("<i", xf) + b"XTi" + struct.pack("<i", xt)
            if rx is not None:
                extra += b"RXZ" + rx.encode() + b"\x00"
            new_rec = rec + extra
            out.write(struct.pack("<i", len(new_rec)) + new_rec)
            n += 1
    logger.info("tagged %d reads -> %s", n, args.output_bam)
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
