"""evaluate_concordance — precision/recall evaluation of a compared callset.

Drop-in surface of the reference tool (ugvc/pipelines/evaluate_concordance.py:
32-108): reads a concordance frame (h5 from run_comparison_pipeline), writes
``<prefix>.h5`` keys ``optimal_recall_precision`` / ``recall_precision_curve``
plus ``.stats.csv`` (';'-separated) and ``.thresholds.csv``. The per-category
tally runs as one device matmul (ops/concordance).
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

from variantcalling_tpu import logger
from variantcalling_tpu.concordance.concordance_utils import calc_accuracy_metrics, calc_recall_precision_curve
from variantcalling_tpu.utils.h5_utils import read_hdf, write_hdf


def parse_args(argv: list[str]):
    ap = argparse.ArgumentParser(prog="evaluate_concordance", description=run.__doc__)
    ap.add_argument("--input_file", required=True, help="Input concordance h5 file")
    ap.add_argument("--output_prefix", required=True, help="Prefix to output files")
    ap.add_argument("--dataset_key", default="all", help="h5 dataset name, such as chromosome name")
    ap.add_argument("--score_key", default="tree_score", help="column for calculating the score")
    ap.add_argument("--ignore_genotype", action="store_true", help="ignore genotype when comparing to ground-truth")
    ap.add_argument("--ignore_filters", default="HPOL_RUN", help="comma separated list of filters to ignore")
    ap.add_argument("--output_bed", action="store_true", help="output bed files of fp/fn/tp per variant type")
    ap.add_argument("--use_for_group_testing", type=str, default=None, help="Column to use for grouping")
    ap.add_argument("--verbosity", default="INFO")
    return ap.parse_args(argv)


def bed_files_output(df, prefix: str, classify_column: str) -> None:
    """fp/fn/tp BED triplet (vcftools.bed_files_output surface)."""
    from variantcalling_tpu.io.bed import BedWriter

    for cls in ("fp", "fn", "tp"):
        sel = df[df[classify_column].astype(str) == cls]
        with BedWriter(f"{prefix}_{cls}.bed") as bw:
            for chrom, pos in zip(sel["chrom"], sel["pos"]):
                bw.write(str(chrom), int(pos) - 1, int(pos))


def run(argv: list[str]) -> int:
    """Calculate precision and recall for compared HDF5."""
    args = parse_args(argv)
    import logging

    logger.setLevel(getattr(logging, args.verbosity))
    skip = ["concordance", "scored_concordance", "input_args", "comparison_result"] if args.dataset_key == "all" else []
    df = read_hdf(args.input_file, key=args.dataset_key, skip_keys=skip)

    score_column = args.score_key.lower()
    if score_column not in df.columns or bool(np.all(np.isnan(np.asarray(df[score_column], dtype=float)))):
        df[score_column] = 1
        logger.warning("No %s field in comparison hdf input, expect invalid recall/precision curves", score_column)
    df["tree_score"] = df[score_column]
    classify_column = "classify" if args.ignore_genotype else "classify_gt"
    if classify_column not in df.columns:  # single-classification frames
        classify_column = "classify"
    ignored = args.ignore_filters.split(",")

    accuracy_df = calc_accuracy_metrics(df, classify_column, ignored, args.use_for_group_testing)
    write_hdf(accuracy_df, f"{args.output_prefix}.h5", key="optimal_recall_precision", mode="w")
    accuracy_df.to_csv(f"{args.output_prefix}.stats.csv", sep=";", index=False)

    curve_df = calc_recall_precision_curve(df, classify_column, ignored, args.use_for_group_testing)
    write_hdf(curve_df, f"{args.output_prefix}.h5", key="recall_precision_curve", mode="a")
    curve_df[["group", "threshold"]].to_csv(f"{args.output_prefix}.thresholds.csv", index=False)

    if args.output_bed:
        bed_files_output(df, args.output_prefix, classify_column)
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
