"""intersect_bed_regions — intersect N BED files into one merged BED.

Reference surface: ugbio_core.vcfbed intersect_bed_regions
(ugvc/__main__.py vcfbed_modules; internals in the missing submodule —
the reference otherwise shells out to ``bedtools intersect``). Here the
intersection is the sorted-interval sweep from io/bed.IntervalSet (the
same host kernels the annotation join uses).
"""

from __future__ import annotations

import argparse
import sys

from variantcalling_tpu import logger
from variantcalling_tpu.io.bed import read_bed, write_bed


def parse_args(argv):
    ap = argparse.ArgumentParser(prog="intersect_bed_regions", description=run.__doc__)
    ap.add_argument("--include-regions", nargs="+", required=True, help="BEDs to intersect")
    ap.add_argument("--exclude-regions", nargs="*", default=None, help="BEDs to subtract")
    ap.add_argument("--output-bed", required=True)
    return ap.parse_args(argv)


def run(argv) -> int:
    """Intersect (and optionally subtract) BED files."""
    args = parse_args(argv)
    acc = read_bed(args.include_regions[0]).merged()
    for path in args.include_regions[1:]:
        acc = acc.intersect(read_bed(path).merged())
    if args.exclude_regions:
        for path in args.exclude_regions:
            acc = acc.subtract(read_bed(path).merged())
    write_bed(args.output_bed, acc)
    logger.info("%d intervals (%d bp) -> %s", len(acc), acc.total_length(), args.output_bed)
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
