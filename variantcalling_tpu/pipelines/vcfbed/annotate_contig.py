"""annotate_contig — add interval-membership INFO flags to one contig's VCF.

Reference surface: ugbio_core.vcfbed.annotate_contig (setup.py:37,
ugvc/__main__.py vcfbed_modules; internals in the missing submodule). The
WDL scatters per contig; each shard annotates its records with a flag per
annotation BED (the same membership join the filter pipeline's
featurization uses — ops/intervals over globalized coordinates).
"""

from __future__ import annotations

import argparse
import os
import sys

import numpy as np

from variantcalling_tpu import logger
from variantcalling_tpu.io.bed import read_bed
from variantcalling_tpu.io.vcf import read_vcf, write_vcf


def parse_args(argv):
    ap = argparse.ArgumentParser(prog="annotate_contig", description=run.__doc__)
    ap.add_argument("--input_vcf", required=True)
    ap.add_argument("--output_vcf", required=True)
    ap.add_argument("--annotate_intervals", nargs="+", required=True, help="annotation BEDs")
    ap.add_argument("--contig", default=None, help="restrict to this contig")
    return ap.parse_args(argv)


def run(argv) -> int:
    """Annotate VCF records with interval-membership INFO flags."""
    args = parse_args(argv)
    region = (args.contig, 1, 1 << 60) if args.contig else None
    table = read_vcf(args.input_vcf, region=region)
    chrom = np.asarray(table.chrom)
    pos0 = np.asarray(table.pos, dtype=np.int64) - 1
    extra = {}
    for path in args.annotate_intervals:
        name = os.path.basename(path)
        for suffix in (".gz", ".bed", ".interval_list"):
            name = name.removesuffix(suffix)
        iv = read_bed(path).merged()
        member = iv.contains(chrom, pos0)
        table.header.ensure_info(name, "0", "Flag", f"Position overlaps {os.path.basename(path)}")
        extra[name] = np.where(member, True, None)  # Flag: present or absent
    write_vcf(args.output_vcf, table, extra_info=extra)
    logger.info("%d records, %d annotations -> %s", len(table), len(extra), args.output_vcf)
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
