"""training_set_consistency_check — validate DV training CRAMs/BAMs vs ground truth.

Drop-in surface of the reference tool
(ugvc/pipelines/deepvariant/training_set_consistency_check.py:13-244):
JSON conf keyed by ``<workflow>.{cram_files, background_cram_files,
ground_truth_vcf_files, training_hcr_files, training_intervals,
references}``; per subset, target samples must match their ground truth
(hit fraction >= target), normals must anti-correlate, and suspected
normal-in-tumor targets must match some normal's germline calls. The
bcftools/bedtools/picard chain is replaced by the in-process pileup caller
+ interval algebra.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as np

from variantcalling_tpu.comparison.pileup_caller import VariantHitFractionCaller, snp_set_from_vcf
from variantcalling_tpu.comparison.quick_fingerprinter import parse_region
from variantcalling_tpu.io.bed import read_bed, read_intervals


class TrainingSetConsistency:
    def __init__(
        self,
        target_bams: list[str],
        normal_bams: list[str] | None,
        ground_truth_vcf: str,
        hcr: str,
        training_intervals_file: str,
        ref: str,
        max_vars: int,
        region: str,
        min_af_snps: float,
        min_af_germline_snps: float,
        min_hit_fraction_target: float,
        out_dir: str,
    ):
        self.target_bams = target_bams
        self.normal_bams = normal_bams
        self.max_vars = max_vars
        self.region = parse_region(region)
        self.min_af_snps = min_af_snps
        self.min_af_germline_snps = min_af_germline_snps
        self.min_hit_fraction_target = min_hit_fraction_target
        self.out_dir = out_dir
        os.makedirs(out_dir, exist_ok=True)
        self.vc = VariantHitFractionCaller(ref, out_dir, min_af_snps, region)
        # ground truth SNPs within HCR ∩ training intervals ∩ region
        restrict = read_bed(hcr).intersect(read_intervals(training_intervals_file))
        chrom, start, end = self.region
        truth = snp_set_from_vcf(ground_truth_vcf, (chrom, start + 1, end), restrict)
        self.ground_truth = set(sorted(truth)[: self.max_vars])
        self.restrict = restrict

    def check(self) -> list[str]:
        errors: list[str] = []
        suspected_normal_in_tumor: list[str] = []
        chrom, start, end = self.region

        target_calls: dict[str, set] = {}
        for target in self.target_bams:
            called = self.vc.call_variants(target, chrom, start, end, self.min_af_snps)
            target_calls[target] = called
            hit_fraction, hit_count, _ = self.vc.calc_hit_fraction(called, self.ground_truth)
            if hit_fraction < self.min_hit_fraction_target:
                if self.normal_bams is None:
                    errors.append(
                        f"{target} - target sample does not match ground truth, "
                        f"hit_fraction={hit_fraction}, hit_count={hit_count}"
                    )
                elif hit_fraction > 1 - self.min_hit_fraction_target:
                    errors.append(
                        f"{target} - target sample does not match ground truth, "
                        f"and is also not complementary to it, hit_fraction={hit_fraction}, count={hit_count}"
                    )
                else:
                    print(f"{target} - target sample can be normal-in-tumor sample, hit_fraction={hit_fraction}")
                    suspected_normal_in_tumor.append(target)
            else:
                print(f"{target} - target sample match ground truth hit_fraction={hit_fraction}")

        normal_germline_sets: list[set] = []
        for normal in self.normal_bams or []:
            called = self.vc.call_variants(normal, chrom, start, end, self.min_af_snps)
            hit_fraction, _, _ = self.vc.calc_hit_fraction(called, self.ground_truth)
            if hit_fraction > 1 - self.min_hit_fraction_target:
                errors.append(
                    f"{normal} - normal sample is not complementary to ground truth, hit_fraction={hit_fraction}"
                )
            else:
                print(f"{normal} - normal sample is complementary to ground truth, hit_fraction={hit_fraction}")
            germline = self.vc.call_variants(normal, chrom, start, end, self.min_af_germline_snps)
            # restrict germline calls to the HCR ∩ training-interval space
            by_chrom = self.restrict.merged().by_chrom()
            if chrom in by_chrom:
                s, e = by_chrom[chrom]
                germline = {
                    k for k in germline if (j := np.searchsorted(s, k[1] - 1, side="right") - 1) >= 0 and k[1] - 1 < e[j]
                }
            normal_germline_sets.append(germline)

        if self.normal_bams:
            for suspect in suspected_normal_in_tumor:
                called = target_calls[suspect]
                max_hit_fraction, best_match = 0.0, ""
                for k, germline in enumerate(normal_germline_sets):
                    hit_fraction, _, _ = self.vc.calc_hit_fraction(called, germline)
                    if hit_fraction > max_hit_fraction:
                        max_hit_fraction = hit_fraction
                        best_match = (self.normal_bams or [])[k]
                if max_hit_fraction < self.min_hit_fraction_target:
                    errors.append(
                        f"{suspect} - suspected normal-in-tumor sample does "
                        f"not match any normal sample max_hit_fraction={max_hit_fraction}"
                    )
                else:
                    print(f"{suspect} - suspected normal-in-tumor sample matches {best_match} with hit_fraction={max_hit_fraction}")
        for error in errors:
            print(f"ERROR: {error}")
        return errors


def run(argv: list[str]):
    """Training set consistency check pipeline."""
    ap = argparse.ArgumentParser(prog="training_set_consistency_check", description=run.__doc__)
    ap.add_argument("--training_json_conf", required=True, help="json file with training configuration")
    ap.add_argument("--region_str", type=str, default="chr15:26000000-30000000")
    VariantHitFractionCaller.add_args_to_parser(ap)
    ap.add_argument("--out_dir", type=str, required=True)
    args = ap.parse_args(argv)

    with open(args.training_json_conf, encoding="utf-8") as fh:
        conf = json.load(fh)
    workflow_id = list(conf.keys())[0].split(".")[0]
    ref = conf[f"{workflow_id}.references"]["ref_fasta"]
    bam_files = conf[f"{workflow_id}.cram_files"]
    background_bam_files = conf[f"{workflow_id}.background_cram_files"]
    ground_truth_vcf_files = conf[f"{workflow_id}.ground_truth_vcf_files"]
    training_hcr_files = conf[f"{workflow_id}.training_hcr_files"]
    training_intervals_files = conf[f"{workflow_id}.training_intervals"]

    os.makedirs(args.out_dir, exist_ok=True)
    errors: list[str] = []
    for i, target_bams in enumerate(bam_files):
        if len(background_bam_files) == len(bam_files):
            normals = background_bam_files[i]
        elif len(background_bam_files) > 0:
            raise RuntimeError("Number of background bam files does not match number of bam files")
        else:
            normals = None
        print(f"subset {i}")
        errors.extend(
            TrainingSetConsistency(
                target_bams,
                normals,
                ground_truth_vcf_files[i],
                training_hcr_files[i],
                training_intervals_files[i],
                ref,
                args.max_vars,
                args.region_str,
                args.min_af_snps,
                args.min_af_germline_snps,
                args.min_hit_fraction_target,
                f"{args.out_dir}/subset_{i}",
            ).check()
        )
    if errors:
        raise RuntimeError("\n".join(errors))
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
