"""cleanup_gvcf_before_calling — drop ./. records overlapping called deletions.

Drop-in surface of the reference tool
(ugvc/joint/cleanup_gvcf_before_calling.py:11-95): positional
``input_gvcf output_gvcf``. GLNexus joint-calling pre-pass.
"""

from __future__ import annotations

import argparse
import sys

from variantcalling_tpu.joint.gvcf import cleanup_gvcf


def run(argv: list[str]):
    ap = argparse.ArgumentParser(prog="cleanup_gvcf_before_calling", description=__doc__)
    ap.add_argument("input_gvcf")
    ap.add_argument("output_gvcf")
    args = ap.parse_args(argv)
    n_written, n_removed = cleanup_gvcf(args.input_gvcf, args.output_gvcf)
    sys.stderr.write(f"Written {n_written} records, removed {n_removed} records\n")
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
