"""cnv_calling — CNV segments from a BAM or binned-coverage parquet.

Reference surface: the ugbio_cnv package CLI (setup.py:4-8; the reference
runs cn.mops/cnvpytor in dedicated conda envs). Here calling runs on the
same depth tensors the coverage pipeline produces: BAM -> per-contig depth
(native C++ walker) -> binned means (device reshape-mean) -> GC-corrected
log2 ratios -> HMM Viterbi segmentation (device scan). Output: BED of
segments (chrom, start, end, CN, n_bins, mean_log2) + optional VCF with
symbolic <DEL>/<DUP> alleles.
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

from variantcalling_tpu import logger
from variantcalling_tpu.cnv.caller import call_cnvs
from variantcalling_tpu.io.bam import depth_diff_arrays, depth_vectors
from variantcalling_tpu.io.fasta import FastaReader


def parse_args(argv):
    ap = argparse.ArgumentParser(prog="cnv_calling", description=run.__doc__)
    ap.add_argument("--input_bam", required=True)
    ap.add_argument("--output_bed", required=True)
    ap.add_argument("--output_vcf", default=None)
    ap.add_argument("--bin_size", type=int, default=1000)
    ap.add_argument("--reference", default=None, help="FASTA for GC correction")
    ap.add_argument("--min_contig_length", type=int, default=1_000_000)
    ap.add_argument("--min_bins", type=int, default=3)
    ap.add_argument("--sigma", type=float, default=0.35)
    ap.add_argument("--mapq", type=int, default=1)
    ap.add_argument("--verbosity", default="INFO")
    return ap.parse_args(argv)


def binned_depth(depth: np.ndarray, bin_size: int) -> np.ndarray:
    n_bins = len(depth) // bin_size
    if n_bins == 0:
        return np.zeros(0, dtype=np.float32)
    return depth[: n_bins * bin_size].reshape(n_bins, bin_size).mean(axis=1).astype(np.float32)


def gc_per_bin(fasta: FastaReader, contig: str, n_bins: int, bin_size: int) -> np.ndarray:
    seq = fasta.fetch(contig, 0, n_bins * bin_size).upper()  # fetch is 0-based half-open
    arr = np.frombuffer(seq.encode(), dtype=np.uint8)[: n_bins * bin_size]
    if len(arr) < n_bins * bin_size:
        arr = np.pad(arr, (0, n_bins * bin_size - len(arr)), constant_values=ord("N"))
    is_gc = (arr == ord("G")) | (arr == ord("C"))
    return is_gc.reshape(n_bins, bin_size).mean(axis=1).astype(np.float32)


def run(argv) -> int:
    """Call CNVs from coverage depth via the device HMM."""
    args = parse_args(argv)
    header, diffs = depth_diff_arrays(args.input_bam, min_mapq=args.mapq)
    depth = depth_vectors(header, diffs)
    per_contig: dict[str, np.ndarray] = {}
    gc: dict[str, np.ndarray] | None = {} if args.reference else None
    fasta = FastaReader(args.reference) if args.reference else None
    for name, d in depth.items():
        if header.lengths[name] < args.min_contig_length:
            continue
        b = binned_depth(d, args.bin_size)
        if not len(b):
            continue
        per_contig[name] = b
        if fasta is not None:
            gc[name] = gc_per_bin(fasta, name, len(b), args.bin_size)
    segs = call_cnvs(
        per_contig, args.bin_size, gc, sigma=args.sigma, min_bins=args.min_bins
    )
    with open(args.output_bed, "w") as fh:
        for s in segs:
            fh.write(f"{s.chrom}\t{s.start}\t{s.end}\tCN{s.copy_number}\t{s.n_bins}\t{s.mean_log2:.3f}\n")
    if args.output_vcf:
        _write_vcf(args.output_vcf, segs, header)
    logger.info("%d CNV segments -> %s", len(segs), args.output_bed)
    return 0


def _write_vcf(path: str, segs, header) -> None:
    from variantcalling_tpu.io.bgzf import BgzfWriter

    opener = BgzfWriter(path) if path.endswith(".gz") else open(path, "w")
    with opener as fh:
        fh.write("##fileformat=VCFv4.2\n")
        fh.write('##ALT=<ID=DEL,Description="Deletion">\n##ALT=<ID=DUP,Description="Duplication">\n')
        fh.write('##INFO=<ID=END,Number=1,Type=Integer,Description="Segment end">\n')
        fh.write('##INFO=<ID=CN,Number=1,Type=Integer,Description="Copy number">\n')
        fh.write('##INFO=<ID=SVTYPE,Number=1,Type=String,Description="SV type">\n')
        for name, length in header.lengths.items():
            fh.write(f"##contig=<ID={name},length={length}>\n")
        fh.write("#CHROM\tPOS\tID\tREF\tALT\tQUAL\tFILTER\tINFO\n")
        for s in segs:
            svtype = "DEL" if s.copy_number < 2 else "DUP"
            fh.write(
                f"{s.chrom}\t{s.start + 1}\t.\tN\t<{svtype}>\t.\tPASS\t"
                f"END={s.end};CN={s.copy_number};SVTYPE={svtype}\n"
            )


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
