"""coverage_analysis — whole-genome depth collection + binning + histograms.

Drop-in surface of the reference tool (coverage_analysis.py:76-245:
``full_analysis`` / ``collect_coverage`` subcommands, -q/-Q/-l samtools
filters, window cascade). Re-founded per BASELINE config 4: the native BAM
reader produces one int32 depth vector per contig (difference-array
cumsum), and every downstream product — window binning cascade, per-
interval histograms, percentiles, stats — is a fused device reduction
(ops/coverage) instead of samtools|awk text plumbing.

Outputs (reference-shaped):
- ``collect_coverage``: per-contig bedGraph (.bedgraph.gz, run-length) +
  sibling .bw via the native bigWig writer (io/bigwig), or .bw directly
  when the output name asks for it;
- ``full_analysis``: ``<out>.coverage_stats.h5`` with keys ``histogram`` /
  ``stats`` / ``percentiles`` (Q0..Q100 rows, interval columns, as read by
  generate_coverage_boxplot, coverage_analysis.py:960-1068) and binned
  parquet per window in {100, 1000, 10000, 100000}.
"""

from __future__ import annotations

import argparse
import gzip
import os
import sys

import numpy as np
import pandas as pd

import jax.numpy as jnp

from variantcalling_tpu import logger
from variantcalling_tpu.utils import degrade
from variantcalling_tpu.io import bed as bedio
from variantcalling_tpu.io.bam import depth_diff_arrays, depth_vectors
from variantcalling_tpu.ops import coverage as cops

DEFAULT_WINDOWS = [100, 1000, 10000, 100000]
MIN_CONTIG_LENGTH = 1_000_000  # contigs below this are skipped (reference :62)
PERCENTILE_QS = np.arange(0, 101, 5)


def parse_args(argv: list[str], command: str):
    ap = argparse.ArgumentParser(prog=command, description=run.__doc__)
    ap.add_argument("-i", "--input", required=True, help="input bam file")
    ap.add_argument("-o", "--output", required=True, help="output path/basename")
    if command == "full_analysis":
        ap.add_argument("-c", "--coverage_intervals", default=None,
                        help="tsv of (name, bed path) rows with per-interval categories")
        ap.add_argument("-w", "--windows", type=int, nargs="*", default=None)
    ap.add_argument("-r", "--region", nargs="*", default=None)
    ap.add_argument("-q", "-bq", dest="bq", type=int, default=0)
    ap.add_argument("-Q", "-mapq", dest="mapq", type=int, default=0)
    ap.add_argument("-l", dest="min_read_length", type=int, default=0)
    ap.add_argument("--reference", default=None,
                    help="reference FASTA (CRAM decode is reference-free for depth)")
    ap.add_argument("--reference-gaps", default=None)
    ap.add_argument("--centromeres", default=None)
    ap.add_argument("-j", "--jobs", type=int, default=-1, help="(accepted; XLA owns parallelism)")
    ap.add_argument("--no_progress_bar", action="store_true")
    return ap.parse_args(argv)


def collect_depth(args) -> dict[str, np.ndarray]:
    header, diffs = depth_diff_arrays(
        args.input,
        min_bq=args.bq,
        min_mapq=args.mapq,
        min_read_length=args.min_read_length,
        regions=args.region,
    )
    depths = depth_vectors(header, diffs)
    return {c: d for c, d in depths.items() if len(d) >= MIN_CONTIG_LENGTH or len(depths) <= 3}


def write_bedgraph(path: str, depths: dict[str, np.ndarray]) -> None:
    """Run-length bedGraph (the samtools-depth-to-bedGraph equivalent)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "wt") as out:
        for contig, d in depths.items():
            if len(d) == 0:
                continue
            change = np.flatnonzero(np.diff(d)) + 1
            starts = np.concatenate([[0], change])
            ends = np.concatenate([change, [len(d)]])
            vals = d[starts]
            for s, e, v in zip(starts, ends, vals):
                out.write(f"{contig}\t{s}\t{e}\t{v}\n")


def _interval_categories(args, depths: dict[str, np.ndarray]) -> dict[str, dict[str, np.ndarray]]:
    """category name -> {contig: bool mask}; always includes 'Genome'."""
    cats: dict[str, dict[str, np.ndarray]] = {
        "Genome": {c: np.ones(len(d), dtype=bool) for c, d in depths.items()}
    }
    if getattr(args, "coverage_intervals", None):
        tbl = pd.read_csv(args.coverage_intervals, sep="\t", header=None, names=["category", "path"])
        for _, row in tbl.iterrows():
            iv = bedio.read_intervals(str(row["path"]))
            by_chrom = iv.by_chrom()
            masks = {}
            for contig, d in depths.items():
                if contig in by_chrom:
                    s, e = by_chrom[contig]
                    masks[contig] = cops.mask_from_intervals(len(d), s, e)
                else:
                    masks[contig] = np.zeros(len(d), dtype=bool)
            cats[str(row["category"])] = masks
    return cats


def full_analysis(args) -> int:
    depths = collect_depth(args)
    if not depths:
        raise SystemExit("no contigs passed the length filter")
    os.makedirs(os.path.dirname(os.path.abspath(args.output)) or ".", exist_ok=True)
    base = args.output
    windows = args.windows if args.windows else DEFAULT_WINDOWS

    # --- windowed binning cascade: each window derives from the previous ---
    for w in sorted(windows):
        rows = []
        for contig, d in depths.items():
            means = np.asarray(cops.binned_mean(jnp.asarray(d), w))
            rows.append(pd.DataFrame({
                "chrom": contig,
                "chromStart": np.arange(len(means), dtype=np.int64) * w + 1,
                "chromEnd": np.minimum((np.arange(len(means), dtype=np.int64) + 1) * w, len(d)),
                "coverage": means,
            }))
        pd.concat(rows, ignore_index=True).to_parquet(f"{base}.w{w}.parquet")

    # --- per-category histograms -> stats + percentiles -------------------
    cats = _interval_categories(args, depths)
    hist_cols: dict[str, np.ndarray] = {}
    for cat, masks in cats.items():
        hist = np.zeros(cops.MAX_DEPTH_BIN + 1, dtype=np.float64)
        for contig, d in depths.items():
            hist += np.asarray(cops.depth_histogram(jnp.asarray(d), jnp.asarray(masks[contig])))
        hist_cols[cat] = hist
    df_hist = pd.DataFrame(hist_cols)
    df_hist.index.name = "coverage"

    stats_cols = {}
    pct_cols = {}
    for cat, hist in hist_cols.items():
        st = cops.stats_from_histogram(jnp.asarray(hist))
        stats_cols[cat] = {k: float(v) for k, v in st.items()}
        pct = np.asarray(cops.percentiles_from_histogram(jnp.asarray(hist), PERCENTILE_QS / 100.0))
        pct_cols[cat] = pct
    df_stats = pd.DataFrame(stats_cols)
    df_pct = pd.DataFrame(pct_cols, index=[f"Q{q}" for q in PERCENTILE_QS])

    from variantcalling_tpu.utils.h5_utils import write_hdf

    out_h5 = f"{base}.coverage_stats.h5"
    write_hdf(df_hist, out_h5, key="histogram", mode="w")
    write_hdf(df_stats.reset_index().rename(columns={"index": "stat"}), out_h5, key="stats", mode="a")
    write_hdf(df_pct.reset_index().rename(columns={"index": "percentile"}), out_h5, key="percentiles", mode="a")

    # --- plots (reference :536-544 boxplot, :596-609 per-window profiles) --
    try:
        generate_coverage_boxplot(df_pct, out_path=f"{base}.coverage_boxplot.png")
        for w in sorted(windows):
            if w >= 1000:
                plot_coverage_profile(
                    f"{base}.w{w}.parquet",
                    centromere_file=getattr(args, "centromeres", None),
                    reference_gaps_file=getattr(args, "reference_gaps", None),
                    title=f"(window {w})",
                    out_path=f"{base}.w{w}.profile.png",
                )
    except Exception as e:  # plotting must never fail the numeric outputs
        degrade.record("coverage_analysis.plots", e, fallback="plots skipped")
        logger.warning("coverage plots skipped: %s", e)
    logger.info("wrote %s (histogram/stats/percentiles) + %d binned parquets", out_h5, len(windows))
    return 0


def collect_coverage(args) -> int:
    depths = collect_depth(args)
    out = args.output
    if out.endswith((".bw", ".bigwig", ".bigWig")):
        # native bigWig export (reference depth_to_bigwig,
        # coverage_analysis.py:686-714, via UCSC bedGraphToBigWig)
        from variantcalling_tpu.io.bigwig import write_bigwig

        write_bigwig(out, depths)
        logger.info("wrote %s", out)
        return 0
    if not out.endswith((".bedgraph", ".bedgraph.gz", ".bg", ".bg.gz")):
        out = out + ".bedgraph.gz"
    write_bedgraph(out, depths)
    bw_out = out
    for suf in (".gz", ".bedgraph", ".bg"):
        bw_out = bw_out.removesuffix(suf)
    bw_out += ".bw"
    from variantcalling_tpu.io.bigwig import write_bigwig

    write_bigwig(bw_out, depths)
    logger.info("wrote %s + %s", out, bw_out)
    return 0


def run(argv: list[str]) -> int:
    """Full coverage analysis of an aligned BAM: depth, binning, histograms."""
    if not argv or argv[0] not in ("full_analysis", "collect_coverage"):
        print("usage: coverage_analysis {full_analysis,collect_coverage} [args]", file=sys.stderr)
        return 2
    command = argv[0]
    args = parse_args(argv[1:], command)
    if command == "full_analysis":
        return full_analysis(args)
    return collect_coverage(args)


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))


# ---------------------------------------------------------------------------
# plots (reference coverage_analysis.py:960-1068 boxplot, :1071-1209 profile)
# ---------------------------------------------------------------------------

MIN_LENGTH_TO_SHOW = 10_000_000  # contigs below this are not profiled (:63)


def generate_coverage_boxplot(df_percentiles: pd.DataFrame, out_path: str | None = None,
                              title: str = "") -> str | None:
    """Percentile boxplot per coverage category, normalized to the Genome median.

    Same figure contract as the reference's generate_coverage_boxplot
    (:960-1068): one box per category from the Q5/Q25/Q50/Q75/Q95 rows,
    median + 5th-percentile value labels, y = coverage relative to median.
    """
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    if isinstance(df_percentiles, str):
        from variantcalling_tpu.utils.h5_utils import read_hdf

        df_percentiles = read_hdf(df_percentiles, key="percentiles").set_index("percentile")
    genome_cols = [c for c in df_percentiles.columns if "Genome" in str(c)]
    denom = float(df_percentiles.loc["Q50", genome_cols[0]]) if genome_cols else \
        float(df_percentiles.loc["Q50"].iloc[0])
    norm = df_percentiles / max(denom, 1e-9)

    bxp = []
    for col in norm.columns:
        bxp.append({
            "label": str(col),
            "med": float(norm.loc["Q50", col]),
            "q1": float(norm.loc["Q25", col]),
            "q3": float(norm.loc["Q75", col]),
            "whislo": float(norm.loc["Q5", col]),
            "whishi": float(norm.loc["Q95", col]),
            "mean": float(norm.loc["Q50", col]),
        })

    plt.figure(figsize=(20, 8))
    fig, ax = plt.gcf(), plt.gca()
    patches = ax.bxp(bxp, widths=0.7, showfliers=False, showmeans=True, patch_artist=True)
    ax.set_title(title)
    for j, bx in enumerate(bxp):
        plt.text(j + 1, bx["med"] + 0.03, f"{bx['med']:.2f}", ha="center", fontsize=12)
        plt.text(j + 1, bx["whislo"] - 0.06, f"{bx['whislo']:.2f}", ha="center", fontsize=12)
    plt.xticks(rotation=90)
    plt.ylim(-0.1, 2)
    plt.grid(axis="x")
    plt.ylabel("Coverage relative to median")
    for box in patches["boxes"]:
        box.set_edgecolor("k")
        box.set_linewidth(2)
    plt.tight_layout()
    if out_path is not None:
        target = out_path if "." in os.path.basename(out_path) else \
            os.path.join(out_path, "coverage_boxplot.png")
        fig.savefig(target, dpi=150, bbox_inches="tight")
        plt.close(fig)
        return target
    return None


def plot_coverage_profile(binned_parquet: str, centromere_file: str | None = None,
                          reference_gaps_file: str | None = None, title: str = "",
                          y_max: float = 3.0, out_path: str | None = None) -> str | None:
    """Per-contig normalized coverage profile grid (reference :1071-1209).

    Reads one binned-coverage parquet (the w>=1000 cascade output), keeps
    contigs >= MIN_LENGTH_TO_SHOW, downsamples each to <=300 points, plots
    coverage/median with optional centromere/gap shading.
    """
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    df_all = pd.read_parquet(binned_parquet)
    spans = {}
    for contig, grp in df_all.groupby("chrom", sort=False):
        if grp["chromEnd"].max() >= MIN_LENGTH_TO_SHOW:
            spans[str(contig)] = grp
    if not spans:
        return None
    med = np.median([g["coverage"].median() for g in spans.values() if len(g) > 100] or
                    [g["coverage"].median() for g in spans.values()])
    med = max(float(med), 1.0)

    def _regions(path, want_type=None):
        if path is None:
            return {}
        tbl = pd.read_csv(path, sep="\t", header=None, comment="#").iloc[:, :5]
        tbl.columns = ["chrom", "chromStart", "chromEnd", "name", "type"][: tbl.shape[1]]
        if want_type is not None and "type" in tbl.columns:
            tbl = tbl[tbl["type"] == want_type]
        return {c: g for c, g in tbl.groupby("chrom")}

    acen = _regions(centromere_file, "acen")
    gaps = _regions(reference_gaps_file)

    n = len(spans)
    rows = -(-n // 2)
    fig, axs = plt.subplots(rows, 2, figsize=(28, rows * 3), sharey="all", squeeze=False)
    fig.subplots_adjust(hspace=0.5, wspace=0.01)
    fig.suptitle(f"Coverage profile (normalized to median) {title}\nMedian coverage = {med:.1f}",
                 y=0.98)
    for ax, (contig, grp) in zip(axs.flatten(), spans.items()):
        if len(grp) > 300:
            grp = grp.iloc[:: len(grp) // 300]
        x = (grp["chromStart"] + grp["chromEnd"]) / 2 / 1e6
        ax.plot(x, np.clip(grp["coverage"] / med, 0, 100), ".", markersize=3)
        ax.set_title(str(contig), fontsize=18)
        ax.set_ylim(0, y_max)
        for tbl, color in ((acen.get(contig), "green"), (gaps.get(contig), "red")):
            if tbl is not None:
                for _, r in tbl.iterrows():
                    ax.axvspan(r["chromStart"] / 1e6, r["chromEnd"] / 1e6, color=color, alpha=0.3)
    for ax in axs.flatten()[n:]:
        ax.axis("off")
    if out_path is not None:
        fig.savefig(out_path, dpi=120, bbox_inches="tight")
        plt.close(fig)
        return out_path
    return None
