"""calibrate_bridging_snvs — un-filter somatic SNVs that bridge long homopolymers.

Drop-in surface of the reference tool
(ugvc/pipelines/vcfbed/calibrate_bridging_snvs.py:9-130): a filtered SNV
whose alt allele joins flanking reference homopolymers into a run of
>= min_query_hmer_size (and is not a symmetric tandem repeat), with high
tumor VAF and low normal VAF (FORMAT AD/DP vs BG_AD/BG_DP), gets PASS and
``--set_qual``. The hmer-bridging test runs as one batched kernel over
reference windows instead of per-record fetches.
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

from variantcalling_tpu import logger
from variantcalling_tpu.featurize import gather_windows
from variantcalling_tpu.io.fasta import FastaReader
from variantcalling_tpu.io.vcf import read_vcf, write_vcf

_BASES = "ACGT"


def bridging_hmer_lengths(windows: np.ndarray, alt_code: np.ndarray, radius: int) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """(upstream_len, downstream_len, tandem) for each variant window.

    upstream/downstream = consecutive reference bases equal to the alt base
    on each side of the variant position; tandem = the bases bounding the
    joined run are equal to each other AND to the reference base at the
    variant, with symmetric arm lengths (reference :51-55).
    """
    import jax.numpy as jnp

    w = jnp.asarray(windows)
    alt = jnp.asarray(alt_code)[:, None]
    right = w[:, radius + 1 :]
    left = jnp.flip(w[:, :radius], axis=1)

    def run_len(arm):
        same = arm == alt
        any_diff = ~jnp.all(same, axis=1)
        first = jnp.argmin(same.astype(jnp.int32), axis=1)
        return jnp.where(any_diff, first, arm.shape[1])

    up = run_len(left)
    down = run_len(right)
    # bounding bases (code 4 when run reaches the window edge)
    up_i = jnp.minimum(up, left.shape[1] - 1)
    down_i = jnp.minimum(down, right.shape[1] - 1)
    before = jnp.where(up < left.shape[1], jnp.take_along_axis(left, up_i[:, None], axis=1)[:, 0], 4)
    after = jnp.where(down < right.shape[1], jnp.take_along_axis(right, down_i[:, None], axis=1)[:, 0], 4)
    ref_base = w[:, radius]
    tandem = (before == after) & (before == ref_base) & (up == down)
    return np.asarray(up), np.asarray(down), np.asarray(tandem)


def run(argv: list[str]):
    """Un-filter SNVs which generate a long homopolymer, have borderline quality
    and have a high VAF in the tumor and low VAF in the normal."""
    ap = argparse.ArgumentParser(prog="calibrate_bridging_snvs", description=run.__doc__)
    ap.add_argument("--vcf", required=True, help="Path to the VCF file")
    ap.add_argument("--reference", required=True, help="Path to the reference genome")
    ap.add_argument("--output", required=True, help="name of output vcf file")
    ap.add_argument("--min_query_hmer_size", default=5, type=int)
    ap.add_argument("--min_initial_qual", default=5, type=int)
    ap.add_argument("--min_tumor_vaf", default=0.2, type=float)
    ap.add_argument("--max_normal_vaf", default=0.1, type=float)
    ap.add_argument("--min_normal_depth", default=10, type=int)
    ap.add_argument("--min_distance_from_edge", default=0, type=int)
    ap.add_argument("--set_qual", default=20, type=int)
    args = ap.parse_args(argv)

    table = read_vcf(args.vcf)
    n = len(table)
    code = {b: i for i, b in enumerate(_BASES)}

    is_snv = np.zeros(n, dtype=bool)
    alt_code = np.full(n, 4, dtype=np.int32)
    for i in range(n):
        alts = table.alt[i].split(",")
        if len(table.ref[i]) == 1 and len(alts) == 1 and len(alts[0]) == 1 and alts[0] in code:
            is_snv[i] = True
            alt_code[i] = code[alts[0]]
    not_pass = np.array([f not in ("PASS",) and "PASS" not in str(f).split(";") for f in table.filters])
    qual_ok = np.nan_to_num(table.qual, nan=-1) >= args.min_initial_qual
    candidate = is_snv & not_pass & qual_ok

    radius = args.min_query_hmer_size
    with FastaReader(args.reference) as fa:
        windows = gather_windows(table, fa, radius=radius)
    up, down, tandem = bridging_hmer_lengths(windows, alt_code, radius)
    hmer_size = 1 + up + down
    bridging = (
        candidate
        & (hmer_size >= args.min_query_hmer_size)
        & ~tandem
        & (np.minimum(up, down) >= args.min_distance_from_edge)
    )

    ad = table.format_numeric("AD", missing=0)
    dp = table.format_numeric("DP", max_len=1, missing=0)[:, 0]
    bg_ad = table.format_numeric("BG_AD", missing=0)
    bg_dp = table.format_numeric("BG_DP", max_len=1, missing=0)[:, 0]
    with np.errstate(invalid="ignore", divide="ignore"):
        tumor_vaf = np.where(dp > 0, ad[:, 1:].sum(axis=1) / np.maximum(dp, 1), 0.0)
        normal_vaf = bg_ad[:, 1:].sum(axis=1) / np.maximum(bg_dp, 0.01)
    rescued = (
        bridging
        & (tumor_vaf >= args.min_tumor_vaf)
        & (normal_vaf <= args.max_normal_vaf)
        & (bg_dp > args.min_normal_depth)
    )

    new_filters = np.array(table.filters, dtype=object, copy=True)
    new_filters[rescued] = "PASS"
    table.qual = np.where(rescued, float(args.set_qual), table.qual)
    write_vcf(args.output, table, new_filters=new_filters)
    logger.info("calibrate_bridging_snvs: rescued %d of %d candidate SNVs", int(rescued.sum()), int(candidate.sum()))
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
