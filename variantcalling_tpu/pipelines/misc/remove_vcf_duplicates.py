"""remove_vcf_duplicates — drop exact-duplicate VCF records.

Reference surface: ugvc/bash/remove_vcf_duplicates.sh (awk/sort chain).
Duplicates = same (CHROM, POS, REF, ALT); the first occurrence wins.
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

from variantcalling_tpu import logger
from variantcalling_tpu.io.vcf import read_vcf, write_vcf


def parse_args(argv):
    ap = argparse.ArgumentParser(prog="remove_vcf_duplicates", description=run.__doc__)
    ap.add_argument("input", help="input VCF (.vcf/.vcf.gz)")
    ap.add_argument("output", help="output VCF (.vcf/.vcf.gz)")
    return ap.parse_args(argv)


def run(argv) -> int:
    """Remove duplicate records (same CHROM/POS/REF/ALT)."""
    args = parse_args(argv)
    table = read_vcf(args.input)
    seen: set[tuple] = set()
    keep = np.ones(len(table), dtype=bool)
    for i in range(len(table)):
        key = (table.chrom[i], int(table.pos[i]), table.ref[i], table.alt[i])
        if key in seen:
            keep[i] = False
        else:
            seen.add(key)
    from variantcalling_tpu.pipelines.filter_variants import _subset

    write_vcf(args.output, _subset(table, keep))
    logger.info("%d records, %d duplicates removed -> %s", len(table), int((~keep).sum()), args.output)
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
