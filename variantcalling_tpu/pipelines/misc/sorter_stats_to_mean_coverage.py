"""sorter_stats_to_mean_coverage — extract mean coverage from sorter json.

Reference surface: ugbio_core/sorter_stats_to_mean_coverage.py
(setup.py:38; internals in the missing submodule). Reads the sorter's json
stats, derives mean aligned coverage = aligned bases / genome length, and
writes it as a bare integer file (the WDL consumes it as a downsampling
input).
"""

from __future__ import annotations

import argparse
import json
import sys

from variantcalling_tpu import logger

HUMAN_GENOME_BP = 3_100_000_000


def mean_coverage(stats: dict, genome_length: int = HUMAN_GENOME_BP) -> float:
    for key in ("mean_coverage", "mean_cvg", "coverage"):
        if key in stats:
            return float(stats[key])
    aligned = None
    for key in ("aligned_bases", "pf_aligned_bases", "base_count", "total_bases"):
        if key in stats:
            aligned = float(stats[key])
            break
    if aligned is None:
        raise KeyError("no coverage/aligned-bases field in sorter stats")
    return aligned / genome_length


def parse_args(argv):
    ap = argparse.ArgumentParser(prog="sorter_stats_to_mean_coverage", description=run.__doc__)
    ap.add_argument("--input_sorter_stats_json", required=True)
    ap.add_argument("--output_file", required=True, help="text file holding the rounded mean coverage")
    ap.add_argument("--genome_length", type=int, default=HUMAN_GENOME_BP)
    return ap.parse_args(argv)


def run(argv) -> int:
    """Mean coverage from sorter stats json."""
    args = parse_args(argv)
    with open(args.input_sorter_stats_json) as fh:
        stats = json.load(fh)
    cov = mean_coverage(stats, args.genome_length)
    with open(args.output_file, "w") as fh:
        fh.write(f"{round(cov)}\n")
    logger.info("mean coverage %.2f -> %s", cov, args.output_file)
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
