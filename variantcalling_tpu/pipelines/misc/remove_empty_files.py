"""remove_empty_files — delete zero-length (or header-only gz) files.

Reference surface: ugvc/bash/remove_empty_files.sh.
"""

from __future__ import annotations

import argparse
import os
import sys

from variantcalling_tpu import logger


def parse_args(argv):
    ap = argparse.ArgumentParser(prog="remove_empty_files", description=run.__doc__)
    ap.add_argument("paths", nargs="+", help="files to check")
    ap.add_argument("--dry_run", action="store_true")
    return ap.parse_args(argv)


def run(argv) -> int:
    """Remove empty files from the argument list."""
    args = parse_args(argv)
    removed = 0
    for p in args.paths:
        if os.path.isfile(p) and os.path.getsize(p) == 0:
            if not args.dry_run:
                os.remove(p)
            removed += 1
            logger.info("removed empty file %s", p)
    print(removed)
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
