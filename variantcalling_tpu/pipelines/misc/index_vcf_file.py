"""index_vcf_file — build the .tbi index for a BGZF VCF in-process.

Reference surface: ugvc/bash/index_vcf_file.sh (bgzip+tabix subprocess).
Here the index is written by io/tabix (no external binaries); plain-text
inputs are BGZF-recompressed first.
"""

from __future__ import annotations

import argparse
import sys

from variantcalling_tpu import logger
from variantcalling_tpu.io.bgzf import BgzfWriter
from variantcalling_tpu.io.tabix import build_tabix_index


def parse_args(argv):
    ap = argparse.ArgumentParser(prog="index_vcf_file", description=run.__doc__)
    ap.add_argument("input", help="VCF (.vcf -> recompressed to .vcf.gz first, or .vcf.gz)")
    return ap.parse_args(argv)


def run(argv) -> int:
    """BGZF-compress (if needed) and tabix-index a VCF."""
    args = parse_args(argv)
    path = args.input
    if not path.endswith(".gz"):
        gz = path + ".gz"
        with open(path, "rt") as src, BgzfWriter(gz) as dst:
            for line in src:
                dst.write(line)
        path = gz
    tbi = build_tabix_index(path)
    logger.info("indexed %s -> %s", path, tbi)
    print(tbi)
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
