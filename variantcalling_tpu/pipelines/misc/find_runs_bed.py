"""find_runs_bed — homopolymer-runs BED from a reference FASTA, on device.

The reference treats the runs file (filter_variants --runs_file,
run_comparison --runs_intervals) as an externally produced artifact; this
tool generates it natively. Per contig the encoded sequence goes to the
device once and run detection is a single parallel-scan program
(ops/runs.find_runs); multi-device processes shard the position axis over
the mesh with halo exchange (parallel/halo.sharded_run_lengths) — the
framework's sequence-parallel path.
"""

from __future__ import annotations

import argparse
import sys

import numpy as np

import jax

from variantcalling_tpu import logger
from variantcalling_tpu.io.fasta import FastaReader, encode_seq
from variantcalling_tpu.ops import runs as rops


def parse_args(argv):
    ap = argparse.ArgumentParser(prog="find_runs_bed", description=run.__doc__)
    ap.add_argument("--reference", required=True, help="indexed reference FASTA")
    ap.add_argument("--output_bed", required=True)
    ap.add_argument("--min_length", type=int, default=10,
                    help="minimum homopolymer run length to emit")
    ap.add_argument("--contigs", nargs="*", default=None,
                    help="restrict to these contigs (default: all)")
    ap.add_argument("--halo", type=int, default=256,
                    help="shard halo for the multi-device scan (must be >= min_length; "
                         "longer runs are stitched exactly on the host)")
    return ap.parse_args(argv)


def contig_runs(codes: np.ndarray, min_length: int, halo: int = 256) -> tuple[np.ndarray, np.ndarray]:
    """(starts0, exact lengths) for one contig; sharded scan on multi-device.

    Both paths share ops/runs.select_runs, which also stitches any
    halo-capped lengths — the emitted BED is identical on 1 or N devices.
    """
    n_dev = len(jax.local_devices())
    # tiny contigs (alt/decoy scaffolds) single-device: their shard blocks
    # would clamp the halo below the select_runs correctness floor
    if n_dev > 1 and len(codes) >= n_dev * max(min_length, 64):
        from variantcalling_tpu.parallel.halo import sharded_run_lengths
        from variantcalling_tpu.parallel.mesh import make_mesh

        if halo < min_length:
            raise ValueError(f"--halo {halo} must be >= --min_length {min_length}")
        starts, lengths = sharded_run_lengths(codes, make_mesh(n_model=1), halo=halo,
                                              min_halo=min_length)
        return rops.select_runs(codes, starts, lengths, min_length)
    return rops.find_runs(codes, min_length)


def run(argv) -> int:
    """Write a BED of homopolymer runs >= min_length."""
    args = parse_args(argv)
    n_total = 0
    with FastaReader(args.reference) as fasta, open(args.output_bed, "w") as out:
        contigs = args.contigs or fasta.references
        for contig in contigs:
            seq = encode_seq(fasta.fetch(contig, 0, fasta.get_reference_length(contig)))
            starts, lengths = contig_runs(seq, args.min_length, halo=args.halo)
            for s, ln in zip(starts, lengths):
                out.write(f"{contig}\t{int(s)}\t{int(s + ln)}\n")
            n_total += len(starts)
    logger.info("%d runs >= %dbp -> %s", n_total, args.min_length, args.output_bed)
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
