"""sorter_to_h5 — aggregate Ultima sorter stats (csv + json) into a metrics h5.

Reference surface: ugbio_core sorter_to_h5 (ugvc/__main__.py misc_modules;
internals in the missing submodule). The sorter emits a per-metric csv
(histogram-style: metric,value rows or key,count tables) and a json of
scalar run statistics; both are keyed into one h5 the report loaders read
(the de-facto metrics sink, SURVEY §5.5).
"""

from __future__ import annotations

import argparse
import json
import sys

import pandas as pd

from variantcalling_tpu import logger
from variantcalling_tpu.utils.h5_utils import write_hdf


def parse_args(argv):
    ap = argparse.ArgumentParser(prog="sorter_to_h5", description=run.__doc__)
    ap.add_argument("--input_csv_file", required=True, help="sorter stats csv")
    ap.add_argument("--input_json_file", required=True, help="sorter scalar stats json")
    ap.add_argument("--metric_mapping_file", default=None,
                    help="optional csv mapping sorter metric names -> report names")
    ap.add_argument("--output_file", required=True, help="output h5")
    return ap.parse_args(argv)


def run(argv) -> int:
    """Convert sorter csv+json stats into a keyed h5."""
    args = parse_args(argv)
    csv_df = pd.read_csv(args.input_csv_file)
    with open(args.input_json_file) as fh:
        scalars = json.load(fh)
    if args.metric_mapping_file:
        mapping = pd.read_csv(args.metric_mapping_file)
        cols = {a: b for a, b in zip(mapping.iloc[:, 0], mapping.iloc[:, 1])}
        csv_df = csv_df.rename(columns=cols)
        scalars = {cols.get(k, k): v for k, v in scalars.items()}
    flat = pd.json_normalize(scalars)
    write_hdf(csv_df, args.output_file, key="stats", mode="w")
    write_hdf(flat, args.output_file, key="scalar_stats", mode="a")
    logger.info("sorter stats (%d rows, %d scalars) -> %s", len(csv_df), flat.shape[1], args.output_file)
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
