"""cloud_sync CLI — localize a cloud object and print the local path.

Reference surface: ugvc/__main__.py misc_modules (cloud_sync).
"""

from __future__ import annotations

import argparse
import sys

from variantcalling_tpu.utils.cloud import DEFAULT_CACHE, cloud_sync


def parse_args(argv):
    ap = argparse.ArgumentParser(prog="cloud_sync", description=run.__doc__)
    ap.add_argument("uri", help="gs://, s3://, or local path")
    ap.add_argument("--cache_dir", default=DEFAULT_CACHE)
    ap.add_argument("--force", action="store_true", help="re-download even if cached")
    return ap.parse_args(argv)


def run(argv) -> int:
    """Localize a cloud URI (prints the resulting local path)."""
    args = parse_args(argv)
    print(cloud_sync(args.uri, args.cache_dir, force=args.force))
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
