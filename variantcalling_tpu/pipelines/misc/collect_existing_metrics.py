"""collect_existing_metrics — gather scattered metric files into one h5.

Reference surface: ugbio_core/collect_existing_metrics.py (setup.py:36;
internals in the missing submodule). Accepts picard-style ``.metrics``
files (## HISTOGRAM / ## METRICS sections), csvs, and h5s; each lands
under its own key in the output h5.
"""

from __future__ import annotations

import argparse
import os
import sys

import pandas as pd

from variantcalling_tpu import logger
from variantcalling_tpu.utils.h5_utils import list_keys, read_hdf, write_hdf


def read_picard_metrics(path: str) -> dict[str, pd.DataFrame]:
    """Parse picard-format sections: '## METRICS CLASS ...' / '## HISTOGRAM ...'."""
    out: dict[str, pd.DataFrame] = {}
    with open(path) as fh:
        lines = fh.read().splitlines()
    i = 0
    while i < len(lines):
        line = lines[i]
        if line.startswith("## METRICS CLASS") or line.startswith("## HISTOGRAM"):
            section = "metrics" if "METRICS" in line else "histogram"
            rows = []
            i += 1
            while i < len(lines) and lines[i].strip() and not lines[i].startswith("#"):
                rows.append(lines[i].split("\t"))
                i += 1
            if len(rows) >= 2:
                out[section] = pd.DataFrame(rows[1:], columns=rows[0])
        else:
            i += 1
    return out


def parse_args(argv):
    ap = argparse.ArgumentParser(prog="collect_existing_metrics", description=run.__doc__)
    ap.add_argument("--metric_files", nargs="+", required=True)
    ap.add_argument("--output_h5", required=True)
    return ap.parse_args(argv)


def run(argv) -> int:
    """Collect metric files into one keyed h5."""
    args = parse_args(argv)
    mode = "w"
    n = 0
    for path in args.metric_files:
        stem = os.path.basename(path).split(".")[0]
        if path.endswith((".h5", ".hdf", ".hdf5")):
            for key in list_keys(path):
                write_hdf(read_hdf(path, key=key), args.output_h5, key=f"{stem}_{key}", mode=mode)
                mode = "a"
                n += 1
        elif path.endswith(".csv"):
            write_hdf(pd.read_csv(path), args.output_h5, key=stem, mode=mode)
            mode = "a"
            n += 1
        else:  # picard .metrics / generic sectioned text
            for section, df in read_picard_metrics(path).items():
                write_hdf(df, args.output_h5, key=f"{stem}_{section}", mode=mode)
                mode = "a"
                n += 1
    logger.info("%d tables -> %s", n, args.output_h5)
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
