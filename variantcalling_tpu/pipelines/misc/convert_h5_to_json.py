"""convert_h5_to_json — dump every key of a metrics h5 as one JSON document.

Reference surface: ugbio_core/convert_h5_to_json.py (setup.py:48; internals
in the missing submodule). Output shape: {key: records-or-scalar-map}, the
form the reference's report machinery feeds to external dashboards.
"""

from __future__ import annotations

import argparse
import json
import sys

from variantcalling_tpu import logger


def h5_to_dict(path: str, ignored_substrings: list[str] | None = None) -> dict:
    from variantcalling_tpu.utils.h5_utils import list_keys, read_hdf

    out: dict = {}
    for name in list_keys(path):
        if ignored_substrings and any(sub in name for sub in ignored_substrings):
            continue
        df = read_hdf(path, key=name)
        out[name] = json.loads(df.to_json(orient="records"))
    return out


def parse_args(argv):
    ap = argparse.ArgumentParser(prog="convert_h5_to_json", description=run.__doc__)
    ap.add_argument("--input_h5", required=True)
    ap.add_argument("--output_json", required=True)
    ap.add_argument("--ignored_h5_key_substring", nargs="*", default=None)
    return ap.parse_args(argv)


def run(argv) -> int:
    """Convert a keyed metrics h5 into JSON."""
    args = parse_args(argv)
    data = h5_to_dict(args.input_h5, args.ignored_h5_key_substring)
    with open(args.output_json, "w") as fh:
        json.dump(data, fh, indent=2, default=str)
    logger.info("%d keys -> %s", len(data), args.output_json)
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
