"""joint_calling_report — cohort joint-calling statistics report.

Reference surface: ugvc/reports/joint_calling_report.ipynb: VariantEval-
style known/novel nSNP/nIndel/TiTv tables per annotation + indel length
histogram. Consumes a joint VCF directly (the eval tables come from
reports/variant_eval's device reductions, replacing GATK VariantEval).
"""

from __future__ import annotations

import argparse
import sys

import numpy as np
import pandas as pd

from variantcalling_tpu import logger
from variantcalling_tpu.io.vcf import read_vcf
from variantcalling_tpu.reports.html import HtmlReport, add_figure_safe
from variantcalling_tpu.reports.variant_eval import compute_eval_tables, dbsnp_membership
from variantcalling_tpu.utils.h5_utils import write_hdf


def parse_args(argv):
    ap = argparse.ArgumentParser(prog="joint_calling_report", description=run.__doc__)
    ap.add_argument("--input_vcf", required=True, help="joint-called cohort VCF")
    ap.add_argument("--dbsnp", default=None, help="dbSNP VCF for known/novel split")
    ap.add_argument("--h5_output", default="joint_calling_report.h5")
    ap.add_argument("--html_output", default=None)
    return ap.parse_args(argv)


def run(argv) -> int:
    """Cohort variant statistics: counts, TiTv, indel spectrum, per-sample."""
    args = parse_args(argv)
    table = read_vcf(args.input_vcf)
    known = dbsnp_membership(table, args.dbsnp) if args.dbsnp else None
    rep = HtmlReport("Joint Calling Report")
    rep.add_params({"input": args.input_vcf, "n_records": len(table), "n_samples": table.n_samples})

    mode = "w"
    tables = compute_eval_tables(table, known=known)
    for name in ("CountVariants", "TiTvVariantEvaluator", "IndelSummary", "IndelLengthHistogram"):
        if name in tables:
            rep.add_section(name)
            rep.add_table(tables[name])
            write_hdf(tables[name], args.h5_output, key=name, mode=mode)
            mode = "a"
    if "IndelLengthHistogram" in tables:
        # notebook "Distribution of indel lengths" figure
        def _indel_fig(plt, t=tables["IndelLengthHistogram"]):
            import numpy as _np

            num = t.select_dtypes(include=[_np.number])
            if not len(num.columns):
                return None
            fig, ax = plt.subplots(figsize=(8, 3))
            # label with the Length column (both columns are numeric, so
            # dtype-based selection cannot find it)
            x = t["Length"] if "Length" in t.columns else t.iloc[:, 0]
            ax.bar(_np.arange(len(t)), num.iloc[:, -1])
            ax.set_xticks(_np.arange(len(t)))
            ax.set_xticklabels([str(v) for v in x], rotation=90, fontsize=7)
            ax.set_xlabel("indel length")
            ax.set_ylabel("# variants")
            return fig

        add_figure_safe(rep, _indel_fig, "indel length figure")

    # per-sample: call rate, het/hom ratio
    if table.n_samples:
        rows = []
        for s, name in enumerate(table.header.samples):
            gts = table.genotypes(s)
            called = (gts >= 0).any(axis=1)
            het = called & (gts[:, 0] != gts[:, 1])
            hom_var = called & (gts[:, 0] == gts[:, 1]) & (gts[:, 0] > 0)
            rows.append(
                {
                    "sample": name,
                    "call_rate": round(float(called.mean()), 5),
                    "n_het": int(het.sum()),
                    "n_hom_var": int(hom_var.sum()),
                    "het_hom_ratio": round(float(het.sum() / max(int(hom_var.sum()), 1)), 4),
                }
            )
        per_sample = pd.DataFrame(rows)
        rep.add_section("Per-sample statistics")
        rep.add_table(per_sample)

        def _per_sample_fig(plt):
            fig, ax = plt.subplots(1, 2, figsize=(12, 3))
            ax[0].bar(per_sample["sample"], per_sample["call_rate"])
            ax[0].set_ylabel("call rate")
            ax[0].tick_params(axis="x", rotation=90, labelsize=7)
            ax[1].bar(per_sample["sample"], per_sample["het_hom_ratio"])
            ax[1].set_ylabel("het/hom ratio")
            ax[1].tick_params(axis="x", rotation=90, labelsize=7)
            return fig

        add_figure_safe(rep, _per_sample_fig, "per-sample figure")
        write_hdf(per_sample, args.h5_output, key="per_sample", mode=mode)

    if args.html_output:
        rep.write(args.html_output)
    logger.info("joint calling report -> %s", args.h5_output)
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
