"""joint_calling_report — cohort joint-calling statistics report.

Reference surface: ugvc/reports/joint_calling_report.ipynb: VariantEval-
style known/novel nSNP/nIndel/TiTv tables per annotation + indel length
histogram. Consumes a joint VCF directly (the eval tables come from
reports/variant_eval's device reductions, replacing GATK VariantEval).
"""

from __future__ import annotations

import argparse
import sys

import numpy as np
import pandas as pd

from variantcalling_tpu import logger
from variantcalling_tpu.io.vcf import read_vcf
from variantcalling_tpu.reports.html import HtmlReport, add_figure_safe
from variantcalling_tpu.reports.variant_eval import compute_eval_tables, dbsnp_membership
from variantcalling_tpu.utils.h5_utils import write_hdf


def parse_args(argv):
    ap = argparse.ArgumentParser(prog="joint_calling_report", description=run.__doc__)
    ap.add_argument("--input_vcf", required=True, help="joint-called cohort VCF")
    ap.add_argument("--dbsnp", default=None, help="dbSNP VCF for known/novel split")
    ap.add_argument("--h5_output", default="joint_calling_report.h5")
    ap.add_argument("--html_output", default=None)
    return ap.parse_args(argv)


def run(argv) -> int:
    """Cohort variant statistics: counts, TiTv, indel spectrum, per-sample."""
    args = parse_args(argv)
    table = read_vcf(args.input_vcf)
    known = dbsnp_membership(table, args.dbsnp) if args.dbsnp else None
    rep = HtmlReport("Joint Calling Report")
    rep.add_params({"input": args.input_vcf, "n_records": len(table), "n_samples": table.n_samples})

    mode = "w"
    tables = compute_eval_tables(table, known=known)
    for name in ("CountVariants", "TiTvVariantEvaluator", "IndelSummary", "IndelLengthHistogram"):
        if name in tables:
            rep.add_section(name)
            rep.add_table(tables[name])
            write_hdf(tables[name], args.h5_output, key=name, mode=mode)
            mode = "a"
    if "IndelLengthHistogram" in tables:
        # notebook "Distribution of indel lengths" figure
        def _indel_fig(plt, t=tables["IndelLengthHistogram"]):
            import numpy as _np

            num = t.select_dtypes(include=[_np.number])
            if not len(num.columns):
                return None
            fig, ax = plt.subplots(figsize=(8, 3))
            # label with the Length column (both columns are numeric, so
            # dtype-based selection cannot find it)
            x = t["Length"] if "Length" in t.columns else t.iloc[:, 0]
            ax.bar(_np.arange(len(t)), num.iloc[:, -1])
            ax.set_xticks(_np.arange(len(t)))
            ax.set_xticklabels([str(v) for v in x], rotation=90, fontsize=7)
            ax.set_xlabel("indel length")
            ax.set_ylabel("# variants")
            return fig

        add_figure_safe(rep, _indel_fig, "indel length figure")

    # allele-frequency spectrum (notebook "Allele Frequency" section):
    # cohort-wide alt-allele frequency. One pass over samples, O(N)
    # accumulators — stacking an (S, N, 2) genotype tensor OOMs on the
    # large joint cohorts this report targets. Per-sample stats are
    # collected in the same pass and rendered further down.
    per_sample_rows = []
    if table.n_samples:
        n = len(table)
        n_called = np.zeros(n, dtype=np.int64)
        n_alt = np.zeros(n, dtype=np.int64)
        for s, name in enumerate(table.header.samples):
            gts = table.genotypes(s)
            called = gts >= 0
            n_called += called.sum(axis=1)
            n_alt += ((gts > 0) & called).sum(axis=1)
            any_called = called.any(axis=1)
            het = any_called & (gts[:, 0] != gts[:, 1])
            hom_var = any_called & (gts[:, 0] == gts[:, 1]) & (gts[:, 0] > 0)
            per_sample_rows.append(
                {
                    "sample": name,
                    "call_rate": round(float(any_called.mean()), 5),
                    "n_het": int(het.sum()),
                    "n_hom_var": int(hom_var.sum()),
                    "het_hom_ratio": round(float(het.sum() / max(int(hom_var.sum()), 1)), 4),
                }
            )
        with np.errstate(invalid="ignore"):
            af = np.where(n_called > 0, n_alt / np.maximum(n_called, 1), np.nan)
        hist, edges = np.histogram(af[~np.isnan(af)], bins=np.linspace(0, 1, 51))
        af_df = pd.DataFrame({"af_bin_low": edges[:-1].round(3), "n_variants": hist})
        rep.add_section("Allele frequency spectrum")
        rep.add_table(af_df[af_df["n_variants"] > 0].head(60))

        def _af_fig(plt):
            fig, ax = plt.subplots(figsize=(8, 3))
            ax.bar(af_df["af_bin_low"], af_df["n_variants"], width=0.018)
            ax.set_xlabel("cohort alt-allele frequency")
            ax.set_ylabel("# variants")
            ax.set_yscale("symlog")
            return fig

        add_figure_safe(rep, _af_fig, "AF spectrum figure")
        write_hdf(af_df, args.h5_output, key="af_spectrum", mode=mode)
        mode = "a"

    # per-sample: call rate, het/hom ratio (collected in the AF pass above)
    if per_sample_rows:
        per_sample = pd.DataFrame(per_sample_rows)
        rep.add_section("Per-sample statistics")
        rep.add_table(per_sample)

        def _per_sample_fig(plt):
            fig, ax = plt.subplots(1, 2, figsize=(12, 3))
            ax[0].bar(per_sample["sample"], per_sample["call_rate"])
            ax[0].set_ylabel("call rate")
            ax[0].tick_params(axis="x", rotation=90, labelsize=7)
            ax[1].bar(per_sample["sample"], per_sample["het_hom_ratio"])
            ax[1].set_ylabel("het/hom ratio")
            ax[1].tick_params(axis="x", rotation=90, labelsize=7)
            return fig

        add_figure_safe(rep, _per_sample_fig, "per-sample figure")
        write_hdf(per_sample, args.h5_output, key="per_sample", mode=mode)

    if args.html_output:
        rep.write(args.html_output)
    logger.info("joint calling report -> %s", args.h5_output)
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
