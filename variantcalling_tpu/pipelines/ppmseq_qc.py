"""ppmseq_qc — ppmSeq strand-tag QC categorization.

Reference surface: the ugbio_ppmseq package (setup.py:4-8). ppmSeq reads
carry loop-adapter strand tags at both ends (BAM aux tags, default ``as``/
``ae`` — start/end strand calls: MIXED / MINUS / PLUS / UNDETERMINED).
This tool walks the BAM (native tag-decoding reader), cross-tabulates the
start×end categories, and reports the headline ppmSeq QC rates (mixed-
mixed fraction = usable duplex-like reads; undetermined rate).
"""

from __future__ import annotations

import argparse
import sys
from collections import Counter

import pandas as pd

from variantcalling_tpu import logger
from variantcalling_tpu.io.bam import BamReader
from variantcalling_tpu.utils.h5_utils import write_hdf

CATEGORIES = ["MIXED", "MINUS", "PLUS", "UNDETERMINED", "END_UNREACHED", "MISSING"]


def parse_args(argv):
    ap = argparse.ArgumentParser(prog="ppmseq_qc", description=run.__doc__)
    ap.add_argument("--input_bam", required=True)
    ap.add_argument("--output_h5", required=True)
    ap.add_argument("--start_tag", default="as")
    ap.add_argument("--end_tag", default="ae")
    ap.add_argument("--max_reads", type=int, default=0, help="0 = all")
    ap.add_argument("--verbosity", default="INFO")
    return ap.parse_args(argv)


def _norm(v) -> str:
    if v is None:
        return "MISSING"
    s = str(v).upper()
    return s if s in CATEGORIES else ("UNDETERMINED" if s else "MISSING")


def categorize(bam_path: str, start_tag: str, end_tag: str, max_reads: int = 0) -> Counter:
    counts: Counter = Counter()
    with BamReader(bam_path, decode_tags=True) as bam:
        for i, aln in enumerate(bam):
            if max_reads and i >= max_reads:
                break
            tags = aln.tags or {}
            counts[(_norm(tags.get(start_tag)), _norm(tags.get(end_tag)))] += 1
    return counts


def qc_tables(counts: Counter) -> tuple[pd.DataFrame, pd.DataFrame]:
    cross = pd.DataFrame(0, index=CATEGORIES, columns=CATEGORIES)
    for (s, e), n in counts.items():
        cross.loc[s, e] = n
    total = int(cross.to_numpy().sum())
    mixed_mixed = int(cross.loc["MIXED", "MIXED"])
    undet = int(cross.loc["UNDETERMINED"].sum() + cross["UNDETERMINED"].sum() - cross.loc["UNDETERMINED", "UNDETERMINED"])
    summary = pd.DataFrame(
        [
            {
                "total_reads": total,
                "mixed_mixed": mixed_mixed,
                "pct_mixed_mixed": round(mixed_mixed / total, 5) if total else 0.0,
                "pct_undetermined": round(undet / total, 5) if total else 0.0,
            }
        ]
    )
    return cross, summary


def run(argv) -> int:
    """Cross-tabulate ppmSeq strand tags and write QC rates."""
    args = parse_args(argv)
    counts = categorize(args.input_bam, args.start_tag, args.end_tag, args.max_reads)
    cross, summary = qc_tables(counts)
    write_hdf(cross.reset_index().rename(columns={"index": "start_tag"}), args.output_h5,
              key="strand_tag_crosstab", mode="w")
    write_hdf(summary, args.output_h5, key="summary", mode="a")
    logger.info(
        "%d reads, %.1f%% mixed-mixed -> %s",
        int(summary.iloc[0]["total_reads"]),
        100 * summary.iloc[0]["pct_mixed_mixed"],
        args.output_h5,
    )
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
