"""Run manifest: everything needed to reproduce/attribute a run, emitted
as the FIRST event of every obs stream.

The resume journal taught the shape (io/journal.py meta): a telemetry
stream whose header does not pin the configuration that produced it is
unattributable after the fact. The manifest records:

- the resolved **knob registry** — every ``VCTPU_*`` knob's typed value
  and whether it came from the environment or the declared default
  (``knobs.resolved()``; malformed knobs raised before obs started);
- **topology** — backend, device/process counts, rank, hostname, cpu
  count — the mesh context multi-chip diagnosis needs;
- **input identity** — path, size, mtime_ns per labeled input (same
  signature the chunk journal binds to);
- the package **version** and the tool's argv.

Engine and forest-strategy decisions are NOT here: they resolve after
run start and land as ``resolve`` events in the stream, so the manifest
never claims a decision that was actually made later.
"""

from __future__ import annotations

import os
import socket

from variantcalling_tpu import __version__, knobs


def _topology() -> dict:
    topo: dict = {
        "hostname": socket.gethostname(),
        "cpu_count": os.cpu_count() or 1,
    }
    try:
        import jax

        topo.update(
            backend=jax.default_backend(),
            devices=len(jax.devices()),
            local_devices=len(jax.local_devices()),
            process_count=jax.process_count(),
            process_index=jax.process_index(),
        )
    except Exception as e:  # noqa: BLE001 — an uninitialized backend must not kill telemetry
        from variantcalling_tpu.utils import degrade

        degrade.record("obs.topology_probe", e,
                       fallback="manifest topology omits jax fields")
    return topo


def _input_identity(inputs: dict[str, str] | None) -> dict:
    out: dict = {}
    for label, path in (inputs or {}).items():
        entry: dict = {"path": os.path.abspath(path)}
        try:
            st = os.stat(path)
            entry.update(size=int(st.st_size), mtime_ns=int(st.st_mtime_ns))
        except OSError:
            entry["missing"] = True
        out[label] = entry
    return out


def build_manifest(tool: str, argv: list[str] | None = None,
                   inputs: dict[str, str] | None = None) -> dict:
    """The manifest event body (the envelope is added by the writer)."""
    return {
        "tool": tool,
        "version": __version__,
        "argv": list(argv) if argv is not None else None,
        "knobs": {name: {"value": value if isinstance(
                             value, (bool, int, float, str, type(None)))
                         else str(value),
                         "source": src}
                  for name, value, src in knobs.resolved()},
        "topology": _topology(),
        "inputs": _input_identity(inputs),
    }
