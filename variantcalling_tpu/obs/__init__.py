"""obs — the runtime telemetry subsystem every pipeline run writes through.

The reference ``ugvc`` has essentially no observability (its one
profiling primitive is an unused decorator that prints a negative
duration); this repo's own stopgaps had fragmented the same way —
``utils/trace.py`` spans, ``degrade.record`` degradations, fault-injection
firings, journal/resume decisions and executor lifecycle each went to
their own unstructured log lines. This package unifies them into ONE
run-scoped, schema-versioned JSONL stream (docs/observability.md):

- a **run manifest** (resolved knob registry, topology, input identity,
  package version) opens every stream (:mod:`~variantcalling_tpu.obs.manifest`);
- a **typed metrics registry** (counters/gauges/histograms with lock-free
  recording from worker threads, :mod:`~variantcalling_tpu.obs.metrics`)
  snapshots into the stream at run end;
- **events** — trace spans, degradations, fault firings, retries,
  journal/resume decisions, engine/strategy resolutions, heartbeats —
  append in one globally ordered sequence (``seq``, monotonic ``ts``);
- exporters turn any stream into a Chrome trace-event file for Perfetto
  or a terminal roll-up (:mod:`~variantcalling_tpu.obs.export`,
  ``vctpu obs export`` / ``vctpu obs summary``).

Contract (locked by ``tests/unit/test_obs.py``):

- **output-neutral**: with ``VCTPU_OBS`` on or off, every pipeline's
  output bytes are identical — obs writes only its own sidecar;
- **cheap when off**: every hook bottoms out in one module-bool check
  (:func:`active`); hot-path overhead when ON stays under the 2% budget
  (bench ``obs_overhead_pct``);
- **one ordered stream**: events from any thread serialize through one
  lock that also takes the timestamp, so file order, ``seq`` order and
  ``ts`` order agree.

Knobs: ``VCTPU_OBS=1`` enables recording; ``VCTPU_OBS_PATH`` overrides
the sidecar path (default: ``<output_file>.obs.jsonl`` next to the
pipeline output); ``VCTPU_OBS_PROFILE`` (default on) adds the obs v2
performance-attribution layer (:mod:`~variantcalling_tpu.obs.profile`:
per-stage work/wait attribution, RSS/CPU watermark sampler, runtime
cost_analysis); ``VCTPU_OBS_CPUPROF=1`` starts the obs v3 continuous
CPU sampling profiler (:mod:`~variantcalling_tpu.obs.sampler`:
whole-process stack samples + per-thread CPU clocks folded into a
``sample`` event stream at ``VCTPU_OBS_CPUPROF_HZ`` — ``vctpu obs
flame`` / ``cpuledger`` are the readers); ``VCTPU_OBS_JAXPROF=1``
additionally captures a
``jax.profiler`` device trace next to the run log so host and device
timelines load side by side in Perfetto.

The LIVE telemetry plane (docs/observability.md) rides the same gate:
``VCTPU_OBS_TRACE`` (default on) threads a causal trace through every
chunk's lifecycle — per-chunk trace ids, per-stage ``trace`` spans with
parent links, megabatch fan-in, recovery linkage — the walkable DAG
``vctpu obs critical-path`` consumes; ``VCTPU_OBS_SNAPSHOT_S`` emits
periodic in-run ``snapshot`` metrics (rolling-window quantiles from
``VCTPU_OBS_WINDOW_S``) on the event-flush cadence; ``VCTPU_OBS_MAX_MB``
rotates the log to ``.segN`` segments at the cap; and
``VCTPU_OBS_PROM_FILE`` atomically rewrites a Prometheus textfile on
every snapshot (``vctpu obs tail --follow`` / ``vctpu obs prom`` are
the reader-side faces).

Abnormal exits: the first ``start_run`` registers an ``atexit`` hook
plus SIGTERM and SIGINT handlers that flush the metrics snapshot and
``run_end`` event before the process dies (then re-deliver the signal
with the default disposition — the exit code still says killed-by-
signal), so only a SIGKILL can truncate a stream (the PR 2 SIGKILL
tests own that case — resume recovers the output, and every obs reader
tolerates the torn tail: ``vctpu obs summary``/``tail`` report such a
stream as ``in-flight``).
"""

from __future__ import annotations

import atexit
import itertools
import json
import os
import re
import signal
import threading
import time

from variantcalling_tpu import knobs, logger
from variantcalling_tpu.obs.metrics import NOOP, MetricsRegistry
from variantcalling_tpu.obs.schema import SCHEMA_VERSION

OBS_ENV = "VCTPU_OBS"
OBS_PATH_ENV = "VCTPU_OBS_PATH"
JAXPROF_ENV = "VCTPU_OBS_JAXPROF"
TRACE_ENV = "VCTPU_OBS_TRACE"
SNAPSHOT_ENV = "VCTPU_OBS_SNAPSHOT_S"
WINDOW_ENV = "VCTPU_OBS_WINDOW_S"
MAX_MB_ENV = "VCTPU_OBS_MAX_MB"
PROM_FILE_ENV = "VCTPU_OBS_PROM_FILE"

#: flush the stream every this many events (plus manifest and run end) —
#: a crash loses at most one flush window, without per-event fsync cost
FLUSH_EVERY = 32

#: module fast flag — hot sites check this before doing ANY other work
_ACTIVE = False
#: causal-tracing fast flag: True while a run with tracing is open
#: (``VCTPU_OBS_TRACE``, default on) — the one check trace sites pay
_TRACING = False
_RUN: "ObsRun | None" = None
# re-entrant: the SIGTERM flush handler may fire while the main thread is
# already inside start_run/end_run — a plain Lock would self-deadlock the
# dying process
_LOCK = threading.RLock()

#: trace-id spelling (``t<N>``, run-scoped) — obs.trace_of recognizes a
#: bare id threaded through a stage-item tuple by this shape
_TRACE_ID_RE = re.compile(r"^t\d+$")


def enabled() -> bool:
    """Is obs recording requested by the environment (``VCTPU_OBS``)?"""
    return knobs.get_bool(OBS_ENV)


def active() -> bool:
    """Is a run stream currently open? The ONE check every hot-path hook
    performs before paying any obs cost."""
    return _ACTIVE


class ObsRun:
    """One open run stream: file handle, ordered event writer, metrics."""

    def __init__(self, path: str, tool: str):
        self.path = path
        self.tool = tool
        self.metrics = MetricsRegistry(window_s=knobs.get_float(WINDOW_ENV))
        #: obs v2 attachments, owned by start_run/end_run: the resource
        #: watermark sampler and the jax.profiler trace dir (if any)
        self.sampler = None
        #: obs v3: the continuous CPU sampling profiler
        #: (``VCTPU_OBS_CPUPROF``, obs/sampler.py), owned the same way
        self.cpu_sampler = None
        self.jaxprof_dir: str | None = None
        #: (strategy, kind) pairs whose cost_analysis already emitted —
        #: the per-chunk scoring loop must pay the lower+compile ONCE
        self.cost_recorded: set = set()
        #: causal-tracing state (docs/observability.md "Causal chunk
        #: tracing"): run-scoped id counters plus the per-trace cursor —
        #: trace id -> last span id, so the next stage span of a chunk
        #: knows its parent. Cursor writes are GIL-atomic dict item
        #: assignments, and a chunk's stages execute strictly in
        #: sequence (megabatch fan-in goes through ONE dispatch thread),
        #: so no two threads ever race one trace's cursor.
        self.tracing = knobs.get_bool(TRACE_ENV)
        self.traces: dict[str, str] = {}
        self._trace_n = itertools.count()
        self._span_n = itertools.count()
        #: live-plane state: periodic snapshot throttle + segment
        #: rotation bookkeeping + the Prometheus textfile target
        self._snapshot_s = knobs.get_float(SNAPSHOT_ENV)
        self._last_snapshot = time.perf_counter()
        self._in_snapshot = False
        self._closing = False
        max_mb = knobs.get_int(MAX_MB_ENV)
        self._max_bytes = (max_mb or 0) << 20
        self._bytes = 0
        self._seg = 0
        self.prom_path = knobs.get_str(PROM_FILE_ENV) or None
        self._fh = open(path, "w", encoding="utf-8")
        # re-entrant for the same reason as the module _LOCK: the SIGTERM
        # flush can land while this thread is mid-_emit
        self._lock = threading.RLock()
        self._seq = 0
        self._since_flush = 0
        # ts is derived from ONE wall anchor plus the monotonic clock so
        # the stream's timestamps can never move backwards (NTP steps the
        # wall clock; perf_counter does not step)
        self._t0_wall = time.time()
        self._t0_mono = time.perf_counter()

    def _emit(self, kind: str, name: str, fields: dict, flush: bool = False) -> None:
        pid = os.getpid()
        tid = threading.get_ident()
        flushed = False
        with self._lock:
            # timestamped INSIDE the lock: file order == seq order == ts order
            t = time.perf_counter() - self._t0_mono
            event = dict(fields)  # extras first; the envelope wins on collision
            event.update(v=SCHEMA_VERSION, seq=self._seq,
                         ts=round(self._t0_wall + t, 6), t=round(t, 6),
                         kind=kind, name=name, pid=pid, tid=tid)
            self._seq += 1
            try:
                line = json.dumps(event) + "\n"
                self._fh.write(line)
                self._bytes += len(line)
                self._since_flush += 1
                if flush or self._since_flush >= FLUSH_EVERY:
                    self._fh.flush()
                    self._since_flush = 0
                    flushed = True
                if self._max_bytes and self._bytes >= self._max_bytes:
                    self._rotate()
            except ValueError:
                # a straggler event racing end_run's file close: telemetry
                # must never throw into the recording (worker) thread
                pass
        if flushed:
            # the live plane rides the existing flush cadence: every
            # FLUSH_EVERY events the throttle below may emit an in-run
            # metrics snapshot (kind=snapshot) so an external tail/prom
            # reader sees fresh rolling quantiles without a new thread
            self._maybe_snapshot()

    def _rotate(self) -> None:
        """Segment rollover (``VCTPU_OBS_MAX_MB``): close the current
        file and continue the SAME ordered stream (seq keeps counting)
        in ``<path>.seg<N>`` — readers merge segments exactly like
        ``.rankN`` siblings. Called with the event lock held."""
        try:
            nxt = open(f"{self.path}.seg{self._seg + 1}", "w",
                       encoding="utf-8")
        except OSError as e:
            # rotation failing must never lose events: disable the cap
            # and keep writing the current segment
            self._max_bytes = 0
            logger.warning("obs: cannot open rotation segment for %s: %s — "
                           "size cap disabled for this run", self.path, e)
            return
        old, self._fh = self._fh, nxt
        self._seg += 1
        self._bytes = 0
        self._since_flush = 0
        try:
            old.close()
        except OSError:
            pass

    def _maybe_snapshot(self) -> None:
        """Throttled periodic in-run metrics snapshot (the live plane's
        heartbeat): at most one per ``VCTPU_OBS_SNAPSHOT_S``, emitted on
        the event-flush cadence — an idle stream emits none, a busy one
        emits on schedule. Also rewrites the Prometheus textfile when
        ``VCTPU_OBS_PROM_FILE`` is set."""
        if self._snapshot_s <= 0 or self._in_snapshot or self._closing:
            return
        now = time.perf_counter()
        if now - self._last_snapshot < self._snapshot_s:
            return
        self._in_snapshot = True
        try:
            self._last_snapshot = now
            snap = self.metrics.snapshot()
            self._emit("snapshot", "metrics", snap, flush=True)
            self._write_prom(snap, in_flight=True)
        finally:
            self._in_snapshot = False

    def _write_prom(self, snap: dict, in_flight: bool) -> None:
        if not self.prom_path:
            return
        from variantcalling_tpu.obs import prom
        from variantcalling_tpu.utils import degrade

        try:
            prom.write_textfile(
                self.prom_path,
                prom.snapshot_to_prom(snap, tool=self.tool,
                                      in_flight=in_flight))
        except OSError as e:
            degrade.record("obs.prom_write", e,
                           fallback="Prometheus textfile skipped")

    def close(self, status: str) -> None:
        self._closing = True  # run_end must be the stream's last event
        with self._lock:
            dur = time.perf_counter() - self._t0_mono
        snap = self.metrics.snapshot()
        self._emit("metrics", "final", snap)
        self._emit("run_end", self.tool, {"status": status,
                                          "dur": round(dur, 6)}, flush=True)
        self._fh.close()
        self._write_prom(snap, in_flight=False)


def _rank_suffixed(path: str) -> str:
    """Multi-rank runs must not interleave one file: rank N > 0 writes
    ``<path>.rankN``. Rank resolution is the ONE shared spelling
    (``parallel/distributed.rank``): ``VCTPU_RANK`` first — a local
    scale-out launcher's worker (tools/podrun) must suffix correctly
    WITHOUT initializing a jax backend — then the guarded
    ``jax.process_index()`` fallback the coordinator mode uses."""
    from variantcalling_tpu.parallel.distributed import rank as _rank

    r = _rank()
    return f"{path}.rank{r}" if r else path


def start_run(tool: str, default_path: str | None = None,
              argv: list[str] | None = None,
              inputs: dict[str, str] | None = None,
              force_path: str | None = None) -> ObsRun | None:
    """Open a run stream and emit its manifest; returns None when obs is
    disabled or a run is already active (the caller that got the ObsRun
    back owns :func:`end_run`; joiners just record into the open stream).

    ``force_path`` bypasses the ``VCTPU_OBS`` gate — for the tier-0
    schema check and tests that must record regardless of environment.
    """
    global _ACTIVE, _RUN, _TRACING
    if force_path is None and not enabled():
        return None
    with _LOCK:
        if _RUN is not None:
            return None  # join the open stream, don't nest
        path = force_path or knobs.get_str(OBS_PATH_ENV) or default_path
        if not path:
            return None  # nowhere to write (no output file context)
        path = _rank_suffixed(path)
        from variantcalling_tpu.obs.manifest import build_manifest

        try:
            run = ObsRun(path, tool)
        except OSError as e:
            logger.warning("obs: cannot open run log %s: %s — recording "
                           "disabled for this run", path, e)
            return None
        run._emit("manifest", tool, build_manifest(tool, argv=argv,
                                                   inputs=inputs), flush=True)
        _RUN = run
        _ACTIVE = True
        _TRACING = run.tracing
        _register_flush_handlers()
        if knobs.get_bool(profile_mod().PROFILE_ENV):
            # RSS/CPU watermark sampler (obs v2): daemon thread, stopped
            # (and its watermark event emitted) by end_run
            run.sampler = profile_mod().ResourceSampler(run)
            run.sampler.start()
        if knobs.get_bool(sampler_mod().CPUPROF_ENV):
            # continuous CPU sampling profiler (obs v3): daemon thread
            # folding whole-process stack samples into the stream;
            # stopped (final flush + cpuprof summary event) by end_run
            run.cpu_sampler = sampler_mod().CpuSampler(run)
            run.cpu_sampler.start()
        if knobs.get_bool(JAXPROF_ENV):
            _start_jaxprof(run)
        logger.info("obs: recording run telemetry to %s", path)
        return run


def end_run(run: ObsRun | None, status: str = "ok") -> None:
    """Close the stream opened by the matching :func:`start_run` (no-op
    for joiners, who were handed None)."""
    global _ACTIVE, _RUN, _TRACING
    if run is None:
        return
    with _LOCK:
        if _RUN is not run:
            return
        # attachments stop while the stream still accepts events (the
        # samplers' summary events must precede the metrics snapshot)
        if run.cpu_sampler is not None:
            try:
                run.cpu_sampler.stop()
            except RuntimeError:  # never started (racing interpreter exit)
                pass
            run.cpu_sampler = None
        if run.sampler is not None:
            try:
                run.sampler.stop()
            except RuntimeError:  # never started (racing interpreter exit)
                pass
            run.sampler = None
        if run.jaxprof_dir is not None:
            _stop_jaxprof(run)
        _ACTIVE = False
        _TRACING = False
        _RUN = None
    try:
        run.close(status)
    except OSError as e:  # a full disk must not mask the run's own error
        logger.warning("obs: failed to finalize run log %s: %s", run.path, e)


def profile_mod():
    """The profiler module, imported lazily (it imports this package)."""
    from variantcalling_tpu.obs import profile

    return profile


def sampler_mod():
    """The continuous-profiler module, imported lazily (same reason)."""
    from variantcalling_tpu.obs import sampler

    return sampler


def _start_jaxprof(run: ObsRun) -> None:
    """``VCTPU_OBS_JAXPROF=1``: capture a ``jax.profiler`` device trace
    for the whole run into ``<run log>.jaxprof/``. The device trace and
    the Perfetto export of this stream share the host wall clock (the
    stream's ``ts`` is wall-anchored) and the pid/tid convention (real
    OS ids on both sides), so the two files load side by side in one
    Perfetto session ("Open trace file" twice)."""
    from variantcalling_tpu.utils import degrade

    logdir = run.path + ".jaxprof"
    try:
        import jax

        jax.profiler.start_trace(logdir)
    except Exception as e:  # noqa: BLE001 — profiling must not kill the run
        degrade.record("obs.jaxprof_start", e, fallback="no device trace")
        return
    run.jaxprof_dir = logdir
    run._emit("profile", "jaxprof_start", {"logdir": logdir})


def _stop_jaxprof(run: ObsRun) -> None:
    from variantcalling_tpu.utils import degrade

    logdir, run.jaxprof_dir = run.jaxprof_dir, None
    try:
        import jax

        jax.profiler.stop_trace()
        run._emit("profile", "jaxprof_stop", {"logdir": logdir})
        logger.info("obs: jax.profiler device trace written to %s", logdir)
    except Exception as e:  # noqa: BLE001 — a failed stop must not mask the run's exit
        degrade.record("obs.jaxprof_stop", e, fallback="device trace may be "
                       "incomplete")


# -- abnormal-exit flush (satellite: no silently truncated streams) --------

_ATEXIT_REGISTERED = False
_SIGTERM_REGISTERED = False
_SIGINT_REGISTERED = False


def _flush_open_run(status: str) -> None:
    run = _RUN
    if run is not None:
        end_run(run, status)


def _atexit_flush() -> None:
    # a tool that crashed between start_run and its finally (or that
    # never had one) still gets its metrics snapshot and run_end written
    _flush_open_run("atexit")


def _register_flush_handlers() -> None:
    """Idempotent: atexit once; SIGTERM/SIGINT only when the process
    still has the default disposition (a host app's own handler must
    win; for SIGINT "default" is Python's ``default_int_handler``) and
    only from the main thread (signal.signal raises elsewhere). The
    signal attempts RETRY on later start_runs — a first run opened from
    a worker thread must not permanently forfeit the flush for runs the
    main thread opens afterwards."""
    global _ATEXIT_REGISTERED, _SIGTERM_REGISTERED, _SIGINT_REGISTERED
    if not _ATEXIT_REGISTERED:
        _ATEXIT_REGISTERED = True
        atexit.register(_atexit_flush)
    main = threading.current_thread() is threading.main_thread()
    if not _SIGTERM_REGISTERED:
        try:
            if main and signal.getsignal(signal.SIGTERM) is signal.SIG_DFL:
                signal.signal(signal.SIGTERM, _sigterm_flush)
                _SIGTERM_REGISTERED = True
        except (ValueError, OSError):  # exotic platform / embedded interp
            pass
    if not _SIGINT_REGISTERED:
        # Ctrl-C previously tore the stream mid-write (no metrics, no
        # run_end): Python's default SIGINT handler raises
        # KeyboardInterrupt wherever the main thread happens to be, and
        # a consumer loop blocked in a queue get dies without reaching
        # end_run. Same re-deliver pattern as SIGTERM below.
        try:
            if main and signal.getsignal(signal.SIGINT) \
                    is signal.default_int_handler:
                signal.signal(signal.SIGINT, _sigint_flush)
                _SIGINT_REGISTERED = True
        except (ValueError, OSError):
            pass


def _sigterm_flush(signum, frame) -> None:
    _flush_open_run("sigterm")
    # restore the default disposition and re-deliver so the exit code
    # still says "killed by SIGTERM" — obs observes, it never rescues
    signal.signal(signal.SIGTERM, signal.SIG_DFL)
    os.kill(os.getpid(), signal.SIGTERM)


def _sigint_flush(signum, frame) -> None:
    _flush_open_run("sigint")
    # same pattern as SIGTERM: default disposition + re-deliver, so the
    # parent still sees "killed by SIGINT" (WIFSIGNALED, exit -2) — obs
    # observes, it never rescues
    signal.signal(signal.SIGINT, signal.SIG_DFL)
    os.kill(os.getpid(), signal.SIGINT)


def event(kind: str, name: str, **fields) -> None:
    """Append one event to the open stream (no-op when inactive).

    ``fields`` must be JSON-serializable; keep them small — this is a
    telemetry stream, not a data channel."""
    if not _ACTIVE:
        return
    run = _RUN
    if run is not None:
        run._emit(kind, name, fields)


def span(name: str, dur: float, thread: str, depth: int = 0, **fields) -> None:
    """Record one closed wall-clock span (called by ``utils.trace`` and
    the stage executor). ``dur`` in seconds."""
    if not _ACTIVE:
        return
    run = _RUN
    if run is not None:
        run._emit("span", name, dict(fields, dur=round(dur, 6),
                                     thread=thread, depth=depth))


# -- causal chunk tracing (docs/observability.md "Causal chunk tracing") ---
#
# Every chunk gets a TRACE at ingest; every stage execution appends a
# trace span carrying (trace_id, span_id, parents) so the chunk's full
# history — including megabatch fan-in, retries and recovery actions —
# is a walkable DAG. `vctpu obs critical-path` consumes it; the Perfetto
# exporter renders the parent links as flow arrows.

_TRACE_TLS = threading.local()


def tracing() -> bool:
    """Is causal tracing recording (an open run with VCTPU_OBS_TRACE on)?
    The ONE check trace sites pay before any other work."""
    return _TRACING


def new_trace() -> str | None:
    """Allocate a fresh run-scoped trace id (one per chunk, at ingest);
    None when tracing is off."""
    run = _RUN if _TRACING else None
    if run is None:
        return None
    return f"t{next(run._trace_n)}"


def trace_span(tid: str | None, name: str, dur: float,
               parents: list[str] | None = None,
               traces: list[str] | None = None, **fields) -> str | None:
    """Record one causal span of trace ``tid`` and advance the trace's
    cursor so the chunk's NEXT span parents to this one.

    ``parents`` overrides the implicit parent (the trace's cursor);
    ``traces`` marks a FAN-IN span (one megabatch dispatch serving many
    chunks): the event lists every member trace id, its parents are each
    member's cursor, and every member's cursor advances to this span —
    the DAG edge set `vctpu obs critical-path` walks. Returns the new
    span id (None when tracing is off)."""
    run = _RUN if _TRACING else None
    if run is None or tid is None:
        return None
    sid = f"s{next(run._span_n)}"
    if parents is None:
        last = run.traces.get(tid)
        parents = [last] if last is not None else []
    body = dict(fields, trace_id=tid, span_id=sid, dur=round(dur, 6))
    if parents:
        body["parents"] = list(parents)
    if traces:
        body["traces"] = list(traces)
    run._emit("trace", name, body)
    for t in (traces if traces else (tid,)):
        run.traces[t] = sid
    return sid


def trace_cursor(tid: str | None) -> str | None:
    """The trace's current last-span id (fan-in callers collect these as
    the dispatch span's parents)."""
    run = _RUN if _TRACING else None
    if run is None or tid is None:
        return None
    return run.traces.get(tid)


def end_trace(tid: str | None) -> None:
    """Drop the trace's cursor (the chunk committed — its DAG is done);
    keeps the per-run cursor table bounded at in-flight chunks."""
    run = _RUN if _TRACING else None
    if run is not None and tid is not None:
        run.traces.pop(tid, None)


def set_current_trace(tid: str | None) -> None:
    """Bind ``tid`` as this thread's current chunk trace — recovery
    sites (retry_chunk, quarantine) read it to link their events to the
    chunk they are recovering."""
    _TRACE_TLS.tid = tid  # vctpu-lint: disable=VCT010 — threading.local IS a per-thread cell (the obs/metrics pattern); no cross-thread visibility exists


def current_trace() -> str | None:
    """This thread's current chunk trace id (None outside a chunk body
    or with tracing off)."""
    return getattr(_TRACE_TLS, "tid", None)


class trace_scope:
    """Context manager: bind a chunk's trace id to this thread for the
    duration of its stage body (restores the previous binding, so nested
    bodies and pool workers reusing a thread stay correct)."""

    __slots__ = ("tid", "_prev")

    def __init__(self, tid: str | None):
        self.tid = tid

    def __enter__(self):
        self._prev = getattr(_TRACE_TLS, "tid", None)
        _TRACE_TLS.tid = self.tid  # vctpu-lint: disable=VCT010 — threading.local IS a per-thread cell (the obs/metrics pattern); no cross-thread visibility exists
        return self.tid

    def __exit__(self, *exc):
        _TRACE_TLS.tid = self._prev  # vctpu-lint: disable=VCT010 — threading.local IS a per-thread cell (the obs/metrics pattern); no cross-thread visibility exists
        return False


def trace_of(item) -> str | None:
    """Best-effort trace id of a stage item: the ``_obs_trace`` attribute
    a traced chunk table carries, or — for the render/compress tuples —
    a bare ``t<N>`` id threaded through the tuple. The watchdog uses this
    to link its re-dispatch events to the wedged chunk's trace."""
    tid = getattr(item, "_obs_trace", None)
    if isinstance(tid, str):
        return tid
    if isinstance(item, tuple):
        for x in item:
            tid = getattr(x, "_obs_trace", None)
            if isinstance(tid, str):
                return tid
            if isinstance(x, str) and _TRACE_ID_RE.match(x):
                return x
    return None


def counter(name: str):
    """The named counter of the open run, or a shared no-op."""
    run = _RUN if _ACTIVE else None
    return run.metrics.counter(name) if run is not None else NOOP


def gauge(name: str):
    run = _RUN if _ACTIVE else None
    return run.metrics.gauge(name) if run is not None else NOOP


def histogram(name: str):
    run = _RUN if _ACTIVE else None
    return run.metrics.histogram(name) if run is not None else NOOP


def current() -> ObsRun | None:
    """The open run (tests/manifest introspection)."""
    return _RUN
