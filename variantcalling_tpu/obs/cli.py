"""CLI: ``vctpu obs
<export|summary|bottleneck|critical-path|flame|cpuledger|diff|tail|prom>``
— open any obs run log in Perfetto, roll it up in the terminal, name
the limiting stage or the dominant critical-path edge, export the
continuous profiler's samples as a flame graph (``flame``; ``--diff``
ranks per-frame CPU-share deltas between two runs), print the measured
cpu-budget ledger (``cpuledger``), diff two runs with a noise band,
tail an in-flight run, or render a Prometheus text exposition.

Multi-rank runs and size-capped rotation segments are merged
transparently: every subcommand reads the given log PLUS any ``.rankN``
sibling logs and ``.segN`` rotation segments (one timeline, rank as the
Perfetto pid — docs/observability.md "Multi-host runs" / "Log rotation").
Every reader tolerates an IN-FLIGHT log — a truncated final line is
dropped and a missing ``run_end`` reports status ``in-flight`` instead
of stack-tracing (``tail --follow`` is built on exactly that).

Exit codes follow the repo-wide CLI contract: 0 success, 2 usage error /
unreadable or malformed log (argparse's own usage failures also exit 2).
``diff`` additionally exits 1 when the candidate regresses beyond the
noise band — the sentry contract shared with ``tools/bench_gate.py``.
Covered by ``tests/unit/test_obs.py`` / ``test_obs_profile.py`` /
``test_obs_trace.py``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

from variantcalling_tpu import knobs
from variantcalling_tpu.obs import critical as critical_mod
from variantcalling_tpu.obs import export as export_mod
from variantcalling_tpu.obs import prom as prom_mod
from variantcalling_tpu.obs import sampler as sampler_mod
from variantcalling_tpu.utils.jsonio import emit_json


def get_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="vctpu obs",
        description="inspect/export obs run telemetry (docs/observability.md)")
    sub = ap.add_subparsers(dest="command", required=True)

    exp = sub.add_parser("export",
                         help="convert a run log to a Perfetto-loadable "
                              "Chrome trace-event file")
    exp.add_argument("log", help="obs run log (JSONL)")
    exp.add_argument("--format", default="perfetto", choices=["perfetto"],
                     help="output format (perfetto == Chrome trace events)")
    exp.add_argument("-o", "--output", default=None,
                     help="output path (default: <log>.trace.json)")

    summ = sub.add_parser("summary",
                          help="terminal roll-up: per-stage time, throughput, "
                               "degradations, slowest chunks")
    summ.add_argument("log", help="obs run log (JSONL)")
    summ.add_argument("--json", action="store_true",
                      help="emit the summary as JSON")

    bott = sub.add_parser("bottleneck",
                          help="per-stage work/wait attribution: name the "
                               "limiting stage (obs v2 profile events)")
    bott.add_argument("log", help="obs run log (JSONL)")
    bott.add_argument("--json", action="store_true",
                      help="emit the attribution as JSON")

    crit = sub.add_parser("critical-path",
                          help="per-chunk critical-path attribution from "
                               "the causal trace DAG: which work/wait "
                               "edges dominate p50/p95 chunk latency")
    crit.add_argument("log", help="obs run log (JSONL)")
    crit.add_argument("--json", action="store_true",
                      help="emit the roll-up as JSON")

    fl = sub.add_parser("flame",
                        help="export the continuous profiler's samples "
                             "(VCTPU_OBS_CPUPROF) as speedscope JSON + "
                             "collapsed stacks; --diff ranks per-frame "
                             "CPU-share deltas between two runs")
    fl.add_argument("log", nargs="+",
                    help="obs run log (two logs with --diff: "
                         "CANDIDATE BASELINE)")
    fl.add_argument("--diff", action="store_true",
                    help="compare two logs: ranked per-frame CPU "
                         "self-share delta report (attribution, not a "
                         "gate — always exits 0 on a readable pair)")
    fl.add_argument("-o", "--output", default=None,
                    help="speedscope output path "
                         "(default <log>.speedscope.json)")
    fl.add_argument("--collapsed", default=None,
                    help="also write collapsed-stack text here "
                         "(default <log>.collapsed.txt)")
    fl.add_argument("--top", type=int, default=20,
                    help="--diff: frames to report (default %(default)s)")
    fl.add_argument("--json", action="store_true",
                    help="--diff: emit the delta report as JSON")

    cl = sub.add_parser("cpuledger",
                        help="measured cpu-budget ledger from the "
                             "continuous profiler's samples: cpu-s (and "
                             "cpu-s per 1M variants) per stage")
    cl.add_argument("log", help="obs run log (JSONL)")
    cl.add_argument("--json", action="store_true",
                    help="emit the ledger as JSON")

    tail = sub.add_parser("tail",
                          help="progress/SLO view of an (in-flight) run "
                               "log; --follow keeps reading as it grows")
    tail.add_argument("log", help="obs run log (JSONL; may be growing)")
    tail.add_argument("--follow", action="store_true",
                      help="poll the log until run_end (Ctrl-C to stop)")
    tail.add_argument("--interval-s", type=float, default=None,
                      help="--follow poll interval (default: the "
                           "VCTPU_OBS_TAIL_POLL_S knob, 1.0s)")
    tail.add_argument("--json", action="store_true",
                      help="emit the (non-follow) tail state as JSON")

    pr = sub.add_parser("prom",
                        help="Prometheus text exposition of the run's "
                             "latest metrics state (in-flight snapshots "
                             "included)")
    pr.add_argument("log", help="obs run log (JSONL)")
    pr.add_argument("-o", "--output", default=None,
                    help="write atomically to this textfile-collector "
                         "path instead of stdout")

    diff = sub.add_parser("diff",
                          help="compare a candidate run against a baseline "
                               "run with an explicit noise band; exit 1 on "
                               "regression")
    diff.add_argument("candidate", help="candidate obs run log")
    diff.add_argument("baseline", help="baseline obs run log")
    diff.add_argument("--tolerance-pct", type=float,
                      default=100.0 * export_mod.DIFF_TOLERANCE,
                      help="noise band as a percentage (default %(default)s)")
    diff.add_argument("--json", action="store_true",
                      help="emit the diff report as JSON")
    return ap


def _load(path: str) -> list[dict]:
    # read_run merges .rankN siblings AND .segN rotation segments
    return export_mod.read_run(path)


def tail_state(events: list[dict]) -> dict:
    """The compact progress/SLO view ``vctpu obs tail`` renders: run
    status, last heartbeat, recovery counts, and the freshest rolling
    quantiles (from the last periodic snapshot or final metrics)."""
    summary = export_mod.summarize(events)
    snap = next((e for e in reversed(events)
                 if e.get("kind") in ("snapshot", "metrics")), None)
    rolling = {}
    if snap is not None:
        for name, h in (snap.get("histograms") or {}).items():
            r = h.get("rolling") if isinstance(h, dict) else None
            if isinstance(r, dict) and r.get("count"):
                rolling[name] = {k: r.get(k)
                                 for k in ("window_s", "count", "p50",
                                           "p95", "p99")}
    # multi-rank merged timelines: each rank reported its own progress —
    # SUM the per-rank last heartbeats (the summarize() rule), so the
    # progress line and the summary's record total cannot contradict
    last_hb_by_rank: dict = {}
    for e in events:
        if e.get("kind") == "heartbeat":
            last_hb_by_rank[e.get("rank", 0)] = e
    progress: dict = {}
    if last_hb_by_rank:
        hbs = list(last_hb_by_rank.values())
        for key in ("chunks", "records", "records_pass"):
            vals = [hb[key] for hb in hbs if key in hb]
            if vals:
                progress[key] = sum(vals)
        for key in ("vps", "pct", "eta_s"):  # rate/pct don't sum: report
            vals = [hb[key] for hb in hbs if key in hb]  # the mean
            if vals:
                progress[key] = round(sum(vals) / len(vals), 2)
    return {
        "run": summary["run"],
        "progress": progress,
        "recoveries": summary.get("recoveries", {}),
        "degradations": summary.get("degradations", {}),
        "rolling": rolling,
        "snapshots": sum(1 for e in events if e.get("kind") == "snapshot"),
    }


def render_tail(state: dict) -> str:
    run = state["run"]
    lines = [f"run: {run.get('tool')} — {run.get('status')} "
             f"({run.get('events')} events, {run.get('duration_s')}s)"]
    p = state["progress"]
    if p:
        bits = [f"chunks={p.get('chunks')}", f"records={p.get('records')}"]
        if "vps" in p:
            bits.append(f"vps={p['vps']}")
        if "pct" in p:
            bits.append(f"pct={p['pct']}")
        if "eta_s" in p:
            bits.append(f"eta_s={p['eta_s']}")
        lines.append("progress: " + " ".join(bits))
    for name, r in sorted(state["rolling"].items()):
        lines.append(f"rolling[{name}] (last ~{r.get('window_s')}s, "
                     f"n={r.get('count')}): p50={r.get('p50')} "
                     f"p95={r.get('p95')} p99={r.get('p99')}")
    if state["recoveries"]:
        lines.append("recovery actions: " + ", ".join(
            f"{k} x{v}" for k, v in sorted(state["recoveries"].items())))
    return "\n".join(lines)


def _render_live_event(e: dict) -> str | None:
    """One follow-mode line per interesting event (None = stay quiet)."""
    kind = e.get("kind")
    if kind == "heartbeat":
        bits = [f"chunks={e.get('chunks')}", f"records={e.get('records')}"]
        for key in ("vps", "pct", "eta_s"):
            if key in e:
                bits.append(f"{key}={e[key]}")
        return "heartbeat: " + " ".join(bits)
    if kind == "recovery":
        extra = f" trace={e['trace_id']}" if "trace_id" in e else ""
        return f"recovery: {e.get('name')}{extra}"
    if kind == "degrade":
        return f"degrade: {e.get('name')} ({e.get('fallback')})"
    if kind == "snapshot":
        # headline: the busiest rolling histogram, whatever the tool
        # named its stages (the same generic rule tail_state applies)
        best_name, best = None, None
        for name, h in (e.get("histograms") or {}).items():
            r = h.get("rolling") if isinstance(h, dict) else None
            if isinstance(r, dict) and r.get("count"):
                if best is None or r["count"] > best["count"]:
                    best_name, best = name, r
        if best is not None:
            return (f"snapshot: rolling {best_name} p95={best.get('p95')} "
                    f"(n={best['count']})")
        return "snapshot: metrics"
    if kind == "run_end":
        return f"run_end: {e.get('status')} after {e.get('dur')}s"
    return None


def _follow(path: str, interval_s: float) -> int:
    """Poll a growing JSONL (tolerating a partially-written final line),
    printing live lines until ``run_end`` lands. A size-capped run
    rotates to ``.segN`` — when the current file stops growing and the
    next segment exists, the tail switches to it. A log that does not
    exist YET is waited for (announced once — the run may not have
    started); any other unreadable-path error exits 2 like every other
    subcommand."""
    import errno

    current = path
    seg = 0
    offset = 0
    buf = ""
    announced_wait = False
    while True:
        try:
            with open(current, encoding="utf-8") as fh:
                fh.seek(offset)
                chunk = fh.read()
                offset = fh.tell()
        except OSError as e:
            if e.errno != errno.ENOENT:
                print(f"error: {e}", file=sys.stderr)
                return 2
            if not announced_wait:
                # a silent spin on a typo'd path would look like a hung
                # run: say what is being waited for, once
                print(f"waiting for {current} (no such file yet)",
                      file=sys.stderr)
                announced_wait = True
            time.sleep(interval_s)
            continue
        buf += chunk
        *complete, buf = buf.split("\n")
        for line in complete:
            line = line.strip()
            if not line:
                continue
            try:
                e = json.loads(line)
            except ValueError:
                continue  # torn line mid-file: the writer will re-land it
            out = _render_live_event(e)
            if out:
                print(out, flush=True)
            if e.get("kind") == "run_end":
                return 0
        if not chunk:
            # the current file stopped growing: a size-capped writer may
            # have rotated to the next segment(s) since the last poll —
            # advance WITHOUT sleeping (and without re-reading anything
            # already consumed; each segment is read once, from 0)
            nxt = f"{path}.seg{seg + 1}"
            if os.path.exists(nxt):
                seg += 1
                current, offset, buf = nxt, 0, ""
                continue
            time.sleep(interval_s)


def _flame(args) -> int:
    """``vctpu obs flame`` / ``flame --diff`` (obs v3). Exit 2 when a
    log is unreadable OR holds no ``sample`` events (an export of
    nothing must fail loudly, not write an empty artifact)."""
    if args.diff:
        if len(args.log) != 2:
            print("flame --diff takes exactly two logs: CANDIDATE "
                  "BASELINE", file=sys.stderr)
            return 2
        try:
            candidate, baseline = _load(args.log[0]), _load(args.log[1])
        except (OSError, export_mod.ObsLogError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        for path, events in ((args.log[0], candidate),
                             (args.log[1], baseline)):
            if not any(e.get("kind") == "sample" for e in events):
                print(f"error: {path} holds no sample events — rerun "
                      "with VCTPU_OBS=1 VCTPU_OBS_CPUPROF=1",
                      file=sys.stderr)
                return 2
        report = sampler_mod.diff_folds(candidate, baseline, top=args.top)
        if args.json:
            emit_json(report)
        else:
            print(sampler_mod.render_diff(report))
        return 0
    if len(args.log) != 1:
        print("flame takes one log (two only with --diff)",
              file=sys.stderr)
        return 2
    log = args.log[0]
    try:
        events = _load(log)
    except (OSError, export_mod.ObsLogError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    scope = sampler_mod.to_speedscope(events, name=os.path.basename(log))
    if scope is None:
        print(f"error: {log} holds no sample events — rerun with "
              "VCTPU_OBS=1 VCTPU_OBS_CPUPROF=1", file=sys.stderr)
        return 2
    out_path = args.output or f"{log}.speedscope.json"
    collapsed_path = args.collapsed or f"{log}.collapsed.txt"
    lines = sampler_mod.collapsed_lines(events)
    try:
        with open(out_path, "w", encoding="utf-8") as fh:
            json.dump(scope, fh)  # compact: profiles get big
            fh.write("\n")
        with open(collapsed_path, "w", encoding="utf-8") as fh:
            fh.write("\n".join(lines) + "\n")
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    n = sum(sum(p["weights"]) for p in scope["profiles"])
    print(f"wrote {out_path} ({n} samples, "
          f"{len(scope['shared']['frames'])} frames — open in "
          "https://speedscope.app) and "
          f"{collapsed_path} ({len(lines)} collapsed stacks)")
    return 0


def run(argv: list[str]) -> int:
    args = get_parser().parse_args(argv)
    if args.command == "tail" and args.follow:
        interval = args.interval_s if args.interval_s is not None \
            else knobs.get_float("VCTPU_OBS_TAIL_POLL_S")
        try:
            return _follow(args.log, interval)
        except KeyboardInterrupt:
            return 0
    if args.command == "flame":
        return _flame(args)
    try:
        if args.command == "diff":
            candidate = _load(args.candidate)
            baseline = _load(args.baseline)
        else:
            events = _load(args.log)
    except (OSError, export_mod.ObsLogError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.command == "cpuledger":
        ledger = sampler_mod.cpuledger(events)
        if ledger is None:
            print(f"error: {args.log} holds no sample events — rerun "
                  "with VCTPU_OBS=1 VCTPU_OBS_CPUPROF=1", file=sys.stderr)
            return 2
        if args.json:
            emit_json(ledger)
        else:
            print(sampler_mod.render_cpuledger(ledger))
        return 0
    if args.command == "critical-path":
        cp = critical_mod.critical_path(events)
        if args.json:
            emit_json(cp)
        else:
            print(critical_mod.render(cp))
        return 0
    if args.command == "tail":
        state = tail_state(events)
        if args.json:
            emit_json(state)
        else:
            print(render_tail(state))
        return 0
    if args.command == "prom":
        text = prom_mod.events_to_prom(events)
        if args.output:
            try:
                prom_mod.write_textfile(args.output, text)
            except OSError as e:
                print(f"error: {e}", file=sys.stderr)
                return 2
            print(f"wrote {args.output}")
        else:
            sys.stdout.write(text)
        return 0
    if args.command == "export":
        out_path = args.output or f"{args.log}.trace.json"
        trace = export_mod.to_chrome_trace(events)
        try:
            import json

            with open(out_path, "w", encoding="utf-8") as fh:
                json.dump(trace, fh)  # compact: trace files get big
                fh.write("\n")
        except OSError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        print(f"wrote {out_path}: {len(trace['traceEvents'])} trace events "
              "(open in https://ui.perfetto.dev)")
        return 0
    if args.command == "bottleneck":
        b = export_mod.bottleneck(events)
        if args.json:
            emit_json(b)
        else:
            print(export_mod.render_bottleneck(b))
        return 0
    if args.command == "diff":
        report = export_mod.diff_runs(candidate, baseline,
                                      tolerance=args.tolerance_pct / 100.0)
        if args.json:
            emit_json(report)
        else:
            print(export_mod.render_diff(report))
        return 1 if report["regressed"] else 0
    summary = export_mod.summarize(events)
    if args.json:
        emit_json(summary)
    else:
        print(export_mod.render_summary(summary))
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
