"""CLI: ``vctpu obs <export|summary|bottleneck|diff>`` — open any obs
run log in Perfetto, roll it up in the terminal, name the limiting
stage, or diff two runs with a noise band.

Multi-rank runs are merged transparently: every subcommand reads the
given log PLUS any ``.rankN`` sibling logs (one timeline, rank as the
Perfetto pid — docs/observability.md "Multi-host runs").

Exit codes follow the repo-wide CLI contract: 0 success, 2 usage error /
unreadable or malformed log (argparse's own usage failures also exit 2).
``diff`` additionally exits 1 when the candidate regresses beyond the
noise band — the sentry contract shared with ``tools/bench_gate.py``.
Covered by ``tests/unit/test_obs.py`` / ``test_obs_profile.py``.
"""

from __future__ import annotations

import argparse
import sys

from variantcalling_tpu.obs import export as export_mod
from variantcalling_tpu.utils.jsonio import emit_json


def get_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="vctpu obs",
        description="inspect/export obs run telemetry (docs/observability.md)")
    sub = ap.add_subparsers(dest="command", required=True)

    exp = sub.add_parser("export",
                         help="convert a run log to a Perfetto-loadable "
                              "Chrome trace-event file")
    exp.add_argument("log", help="obs run log (JSONL)")
    exp.add_argument("--format", default="perfetto", choices=["perfetto"],
                     help="output format (perfetto == Chrome trace events)")
    exp.add_argument("-o", "--output", default=None,
                     help="output path (default: <log>.trace.json)")

    summ = sub.add_parser("summary",
                          help="terminal roll-up: per-stage time, throughput, "
                               "degradations, slowest chunks")
    summ.add_argument("log", help="obs run log (JSONL)")
    summ.add_argument("--json", action="store_true",
                      help="emit the summary as JSON")

    bott = sub.add_parser("bottleneck",
                          help="per-stage work/wait attribution: name the "
                               "limiting stage (obs v2 profile events)")
    bott.add_argument("log", help="obs run log (JSONL)")
    bott.add_argument("--json", action="store_true",
                      help="emit the attribution as JSON")

    diff = sub.add_parser("diff",
                          help="compare a candidate run against a baseline "
                               "run with an explicit noise band; exit 1 on "
                               "regression")
    diff.add_argument("candidate", help="candidate obs run log")
    diff.add_argument("baseline", help="baseline obs run log")
    diff.add_argument("--tolerance-pct", type=float,
                      default=100.0 * export_mod.DIFF_TOLERANCE,
                      help="noise band as a percentage (default %(default)s)")
    diff.add_argument("--json", action="store_true",
                      help="emit the diff report as JSON")
    return ap


def _load(path: str) -> list[dict]:
    # read_run merges .rankN siblings into one timeline
    return export_mod.read_run(path)


def run(argv: list[str]) -> int:
    args = get_parser().parse_args(argv)
    try:
        if args.command == "diff":
            candidate = _load(args.candidate)
            baseline = _load(args.baseline)
        else:
            events = _load(args.log)
    except (OSError, export_mod.ObsLogError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if args.command == "export":
        out_path = args.output or f"{args.log}.trace.json"
        trace = export_mod.to_chrome_trace(events)
        try:
            import json

            with open(out_path, "w", encoding="utf-8") as fh:
                json.dump(trace, fh)  # compact: trace files get big
                fh.write("\n")
        except OSError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        print(f"wrote {out_path}: {len(trace['traceEvents'])} trace events "
              "(open in https://ui.perfetto.dev)")
        return 0
    if args.command == "bottleneck":
        b = export_mod.bottleneck(events)
        if args.json:
            emit_json(b)
        else:
            print(export_mod.render_bottleneck(b))
        return 0
    if args.command == "diff":
        report = export_mod.diff_runs(candidate, baseline,
                                      tolerance=args.tolerance_pct / 100.0)
        if args.json:
            emit_json(report)
        else:
            print(export_mod.render_diff(report))
        return 1 if report["regressed"] else 0
    summary = export_mod.summarize(events)
    if args.json:
        emit_json(summary)
    else:
        print(export_mod.render_summary(summary))
    return 0


if __name__ == "__main__":
    sys.exit(run(sys.argv[1:]))
