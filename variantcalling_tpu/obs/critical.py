"""Critical-path engine: which per-chunk edges actually gate latency?

``vctpu obs bottleneck`` answers "which stage works the most" — a
per-stage *fraction* of a finished run. It cannot say which per-chunk
EDGE (parse→featurize glue, megabatch pack wait, dispatch, render,
commit) sits on the latency critical path, which is the question the
scoring-wall teardown (ROADMAP item 4) needs answered before fusing
anything — the same profiling-before-kernels argument the GPU-cluster
pipeline work (arXiv 2509.09058) and Endeavor (arXiv 2606.25738) make.

This module reconstructs the per-chunk span DAG from the causal
``trace`` events (docs/observability.md "Causal chunk tracing"), walks
the end-to-end critical path of every chunk, and aggregates which edges
dominate p50/p95 chunk latency:

- a **work** edge is a stage span's own duration (``<stage>.work``);
- a **wait** edge is the gap between the critical parent's end and the
  span's start (``<stage>.wait``) — the time the chunk sat in a queue,
  a megabatch pack buffer, or a retry/backoff window. Reusing the PR 6
  vocabulary: from the waiting stage's side this is queue-wait; from
  the producing stage's side the same seconds are backpressure — the
  per-stage ``wait_in``/``wait_out`` split in ``obs bottleneck`` names
  the direction, this module names the chunks it cost.

At megabatch fan-in (one dispatch span, many chunk parents) the critical
parent is the LATEST-arriving member — the chunk the dispatch actually
waited for. The per-stage work sums are reconciled against the
``profile``-event attribution so the two views cannot silently drift
(``reconciliation`` in the roll-up; locked by a synthetic-geometry test
in ``tests/unit/test_obs_trace.py``).
"""

from __future__ import annotations

from variantcalling_tpu.obs import export as export_mod


def _rank_key(e: dict, ident) -> str | None:
    """Scope an id to its rank on a merged multi-rank timeline
    (``export.read_run`` tags every event with ``rank``): each rank's
    writer allocated its own ``t<N>``/``s<N>`` sequences, so bare ids
    COLLIDE across ranks — two ranks' chunk DAGs would silently fuse.
    Single-rank logs keep the bare id (no ``rank`` field)."""
    if not isinstance(ident, str):
        return None
    return f"r{e['rank']}:{ident}" if "rank" in e else ident


def span_records(events: list[dict]) -> dict[str, dict]:
    """``span_id -> normalized span record`` for every ``trace`` event
    (start/end derived from the envelope ``t`` = emission time ≈ span
    end). On a rank-merged timeline every id is rank-scoped — parent
    links never cross ranks (ranks share no chunks)."""
    spans: dict[str, dict] = {}
    for e in events:
        if e.get("kind") != "trace":
            continue
        sid = _rank_key(e, e.get("span_id"))
        if sid is None:
            continue
        end = float(e.get("t", 0.0))
        dur = max(0.0, float(e.get("dur", 0.0)))
        traces = e.get("traces")
        spans[sid] = {
            "id": sid,
            "name": e.get("name", "?"),
            "trace": _rank_key(e, e.get("trace_id")),
            "traces": ([_rank_key(e, t) for t in traces]
                       if traces else None),
            "start": end - dur,
            "end": end,
            "dur": dur,
            "parents": [k for k in (_rank_key(e, p)
                                    for p in e.get("parents", ()))
                        if k is not None],
        }
    return spans


def chunk_paths(events: list[dict]) -> list[dict]:
    """One critical path per chunk: ``{trace, latency_s, edges}`` where
    ``edges`` alternates wait/work in execution order.

    The terminal span of a chunk is its latest span (by end time) —
    normally the sequenced commit. The walk follows parent links
    backwards; at fan-in the critical parent is the latest-ending one
    (the arrival the span actually waited for), and the gap to it is the
    wait edge."""
    spans = span_records(events)
    terminal: dict[str, dict] = {}
    for s in spans.values():
        for tid in (s["traces"] or (s["trace"],)):
            if not isinstance(tid, str):
                continue
            cur = terminal.get(tid)
            if cur is None or s["end"] > cur["end"]:
                terminal[tid] = s
    paths: list[dict] = []
    for tid, term in sorted(terminal.items(), key=lambda kv: kv[1]["end"]):
        edges: list[dict] = []
        cur = term
        seen: set[str] = set()
        while cur["id"] not in seen:
            seen.add(cur["id"])
            edges.append({"edge": f"{cur['name']}.work", "kind": "work",
                          "stage": cur["name"], "s": cur["dur"]})
            parents = [spans[p] for p in cur["parents"] if p in spans]
            if not parents:
                break
            parent = max(parents, key=lambda s: s["end"])
            edges.append({"edge": f"{cur['name']}.wait", "kind": "wait",
                          "stage": cur["name"],
                          "s": max(0.0, cur["start"] - parent["end"]),
                          # absolute (run-relative) interval: the join
                          # key the sampler's wait-edge reconciliation
                          # overlaps CPU-sample windows against
                          "t0": parent["end"],
                          "t1": max(parent["end"], cur["start"])})
            cur = parent
        edges.reverse()
        paths.append({"trace": tid,
                      "latency_s": max(0.0, term["end"] - cur["start"]),
                      "edges": edges})
    return paths


def _aggregate_edges(paths: list[dict]) -> dict[str, dict]:
    total: dict[str, dict] = {}
    for p in paths:
        for e in p["edges"]:
            d = total.setdefault(e["edge"], {"kind": e["kind"],
                                             "stage": e["stage"],
                                             "total_s": 0.0, "count": 0})
            d["total_s"] += e["s"]
            d["count"] += 1
    grand = sum(d["total_s"] for d in total.values())
    for d in total.values():
        d["share_pct"] = round(100.0 * d["total_s"] / grand, 1) \
            if grand > 0 else 0.0
        d["mean_s"] = round(d["total_s"] / d["count"], 6) if d["count"] else 0.0
        d["total_s"] = round(d["total_s"], 6)
    return dict(sorted(total.items(), key=lambda kv: -kv[1]["total_s"]))


def _quantile(sorted_vals: list[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * len(sorted_vals)))
    return sorted_vals[idx]


def critical_path(events: list[dict]) -> dict:
    """The roll-up behind ``vctpu obs critical-path``: per-chunk latency
    quantiles, the edge composition over ALL chunks and over the p95
    latency tail, the dominant edges, and the reconciliation of
    trace-derived per-stage work against the ``profile``-event
    attribution (``obs bottleneck``)."""
    paths = chunk_paths(events)
    if not paths:
        return {"chunks": 0, "source": "none",
                "note": "no trace events in this log — rerun with "
                        "VCTPU_OBS=1 (tracing is on by default; "
                        "VCTPU_OBS_TRACE=0 opts out)"}
    lat = sorted(p["latency_s"] for p in paths)
    p50 = _quantile(lat, 0.5)
    p95 = _quantile(lat, 0.95)
    tail = [p for p in paths if p["latency_s"] >= p95] or paths[-1:]
    edges = _aggregate_edges(paths)
    p95_edges = _aggregate_edges(tail)
    out = {
        "chunks": len(paths),
        "source": "trace",
        "latency_p50_s": round(p50, 6),
        "latency_p95_s": round(p95, 6),
        "edges": edges,
        "dominant_edge": next(iter(edges), None),
        "p95_chunks": len(tail),
        "p95_edges": p95_edges,
        "dominant_p95_edge": next(iter(p95_edges), None),
    }

    # reconciliation with the profile-event attribution: per-stage work
    # summed over UNIQUE spans (a fan-in dispatch counts once here even
    # though it sits on many chunks' paths) vs the bottleneck work_s.
    # Device families (score.dN) book the dispatch wall once PER LANE in
    # the profile rows, so the comparable number is work_s / devices.
    b = export_mod.bottleneck(events)
    if b.get("stages") and b.get("source") == "profile":
        per_stage: dict[str, float] = {}
        for s in span_records(events).values():
            per_stage[s["name"]] = per_stage.get(s["name"], 0.0) + s["dur"]
        recon: dict[str, dict] = {}
        for name, trace_work in sorted(per_stage.items()):
            prof = b["stages"].get(name)
            if prof is None:
                continue
            prof_work = prof["work_s"] / prof.get("devices", 1)
            entry = {"trace_work_s": round(trace_work, 6),
                     "profile_work_s": round(prof_work, 6)}
            if prof_work > 0:
                entry["delta_pct"] = round(
                    100.0 * (trace_work - prof_work) / prof_work, 1)
            recon[name] = entry
        out["reconciliation"] = recon
        out["bottleneck_limiting_stage"] = b.get("limiting_stage")

    # obs v3 reconciliation: when the run carried the continuous CPU
    # profiler, answer "what were the cores DOING during the dominant
    # wait edges" by overlap-joining CPU-sample windows against the wait
    # intervals collected above — the measured explanation the round-13
    # `writeback.wait` diagnosis needed (docs/perf_notes.md)
    from variantcalling_tpu.obs import sampler as sampler_mod

    wait_edges = [name for name, d in p95_edges.items()
                  if d["kind"] == "wait"][:3]
    if wait_edges and any(e.get("kind") == "sample" for e in events):
        intervals: dict[str, list[tuple[float, float]]] = {}
        for p in paths:
            for e in p["edges"]:
                if e["kind"] == "wait" and e["edge"] in wait_edges \
                        and e["s"] > 0 and "t0" in e:
                    intervals.setdefault(e["edge"], []).append(
                        (e["t0"], e["t1"]))
        wait_cpu = sampler_mod.explain_waits(events, intervals)
        if wait_cpu:
            out["wait_cpu"] = wait_cpu
    return out


def compact(cp: dict) -> dict:
    """The compact roll-up the bench ``e2e`` row commits next to its
    ``attribution`` blob (the full edge table stays in the obs log)."""
    if cp.get("chunks", 0) == 0:
        return {"chunks": 0}
    out = {
        "chunks": cp["chunks"],
        "latency_p50_s": cp["latency_p50_s"],
        "latency_p95_s": cp["latency_p95_s"],
        "dominant_edge": cp["dominant_edge"],
        "dominant_p95_edge": cp["dominant_p95_edge"],
        "p95_edge_share_pct": {
            name: d["share_pct"]
            for name, d in list(cp["p95_edges"].items())[:5]},
    }
    # the "cores were running X" answer for the dominant wait edge
    # (obs v3 reconciliation) rides into the committed bench row
    dom = cp.get("dominant_p95_edge")
    wc = (cp.get("wait_cpu") or {}).get(dom)
    if wc:
        out["dominant_p95_wait_cpu"] = {
            "edge": dom,
            "frames": wc["frames"][:3],
        }
    return out


def render(cp: dict) -> str:
    """Human-readable roll-up (``vctpu obs critical-path``)."""
    if cp.get("chunks", 0) == 0:
        return cp.get("note", "no trace events in this log")
    lines = [f"critical path over {cp['chunks']} chunk trace(s): "
             f"latency p50 {cp['latency_p50_s']:.4f}s, "
             f"p95 {cp['latency_p95_s']:.4f}s",
             f"dominant edge (all chunks): {cp['dominant_edge']}; "
             f"dominant p95 edge ({cp['p95_chunks']} tail chunk(s)): "
             f"{cp['dominant_p95_edge']}"]
    width = max(len(n) for n in cp["edges"])
    lines.append(f"  {'edge':<{width}}  {'kind':<5} {'share%':>7} "
                 f"{'total_s':>9} {'mean_s':>9}  p95-share%")
    for name, d in cp["edges"].items():
        tail_share = cp["p95_edges"].get(name, {}).get("share_pct", 0.0)
        lines.append(f"  {name:<{width}}  {d['kind']:<5} "
                     f"{d['share_pct']:>7.1f} {d['total_s']:>9.3f} "
                     f"{d['mean_s']:>9.4f}  {tail_share:>9.1f}")
    recon = cp.get("reconciliation")
    if recon:
        lines.append("reconciliation vs `obs bottleneck` work seconds "
                     "(trace vs profile):")
        for name, r in recon.items():
            delta = r.get("delta_pct")
            lines.append(f"  {name:<{width}}  {r['trace_work_s']:>9.3f} vs "
                         f"{r['profile_work_s']:>9.3f}"
                         + (f"  ({delta:+.1f}%)" if delta is not None else ""))
    if cp.get("bottleneck_limiting_stage"):
        lines.append(f"bottleneck limiting stage: "
                     f"{cp['bottleneck_limiting_stage']}")
    wait_cpu = cp.get("wait_cpu")
    if wait_cpu:
        lines.append("cores were running (CPU samples joined against the "
                     "wait intervals — obs v3 continuous profiler):")
        for edge, wc in wait_cpu.items():
            frames = ", ".join(f"{f['frame']} {f['share_pct']}%"
                               for f in wc["frames"])
            lines.append(f"  during {edge} ({wc['wait_s']:.3f}s waited): "
                         f"{frames}")
    return "\n".join(lines)
