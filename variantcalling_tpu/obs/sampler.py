"""Continuous in-process sampling profiler (obs v3): whole-process CPU
truth.

Everything before this module *derives* where the cores go: the PR 6
stage profiler attributes wall-clock to stage bodies it was told about,
the PR 11 critical-path engine walks per-chunk wait edges, and
docs/perf_notes.md carries an *analytic* cpu-budget table built from a
one-off cProfile. None of them can answer the round-13 question — the
dominant p95 edge is ``writeback.wait`` (ordered-commit turn-taking),
so **what were the cores actually doing while the committed chunk's
successors waited?** — because nothing in the tree samples the process.

This module is that lens, the same measurement-before-scheduling move
the GPU-cluster pipeline work (arXiv 2509.09058, PAPERS.md) builds on:

- :class:`CpuSampler` — a daemon thread (``vctpu-sampler``) that every
  ``1/VCTPU_OBS_CPUPROF_HZ`` seconds snapshots ``sys._current_frames()``
  plus each thread's **CPU clock** (``/proc/self/task/<tid>/stat``,
  fds held open, read with a GIL-keeping ``pread``) and folds the
  result into collapsed form — each thread's LEAF frame every tick,
  whole stacks every :data:`STACK_EVERY`-th tick (the walk is the one
  body long enough to risk a mid-GIL-hold deschedule on a saturated
  host). Each sample is classified:

  * ``native`` — the thread is inside a registered **native span**
    (:class:`native_span` — ``native.fused_chunk_score``, BGZF
    inflate/deflate), kernel state ``R`` at the instant *and* its CPU
    clock advanced: off-GIL native compute. The Python leaf is overlaid
    with ``[native:<name>]`` so flames show the native frame that owns
    the samples.
  * ``gil`` — no native span, state ``R`` with the CPU clock advanced:
    the thread is running Python bytecode (which holds the GIL) or
    GIL-releasing numpy inside a Python frame; either way the frame
    shown is the code that owns the core. (Both on-CPU categories
    require state ``R`` at the sample instant — clock-advance alone
    would attribute an earlier burst to whatever frame the thread is
    parked in now.)
  * ``runnable`` — state ``R`` but the CPU clock did NOT advance: the
    thread *wants* a core and is waiting for one (or for the GIL) —
    the CPU-pressure category.
  * ``wait`` — blocked (lock, queue, IO, condition): the frame shown is
    what it is blocked *in*.

- every thread family is attributed by an explicit registration
  (:func:`register_current` — pool workers, pipeline stages, the
  committer) with a name-based fallback (:func:`classify`), so samples
  always land somewhere meaningful;
- folded stacks emit as schema'd ``sample`` events in bounded windows
  (:data:`EMIT_EVERY_S`), each carrying ``win_t0`` so readers can join
  samples against trace-span wait intervals (:func:`explain_waits` —
  the "cores were running X during this wait edge" join the
  critical-path engine surfaces);
- exporters: :func:`to_speedscope` / :func:`collapsed_lines`
  (``vctpu obs flame``), :func:`diff_folds` (``obs flame --diff A B``,
  the before/after bench comparison), and :func:`cpuledger` — the
  **measured** cpu-seconds-per-1M-variants-per-stage ledger
  (``vctpu obs cpuledger``) that bench.py commits into the e2e row and
  ``tools/bench_gate.py`` gates, turning docs/perf_notes.md's analytic
  budget table into a regression-gated artifact.

Knobs: ``VCTPU_OBS_CPUPROF=1`` (with ``VCTPU_OBS=1``) starts the
sampler for the run; ``VCTPU_OBS_CPUPROF_HZ`` sets the rate. The
default (7 Hz) is deliberately conservative: every tick must hold the
GIL briefly, and on a SATURATED 2-core host the measured tax grows
~linearly with rate (47 Hz cost ~10% e2e on this container) — the
bench ``obs`` phase pairs plane-only legs against plane+sampler legs
and gates the sampler's marginal cost at ≤2%
(``obs.cpuprof_overhead_pct``), with output bytes asserted identical.
Hosts with spare cores can raise the rate freely — the sampler's own
thread then rides an idle core. Off, the only cost anywhere is one
module-bool check at the native-span sites.

Lock discipline: the family registry is written under ``_REG_LOCK``;
the native-span table is per-thread-key dict item assignment (the
obs/metrics pattern — GIL-atomic, each thread writes only its own key,
the sampler thread only reads).
"""

from __future__ import annotations

import os
import re
import sys
import threading
import time

from variantcalling_tpu import knobs, obs

CPUPROF_ENV = "VCTPU_OBS_CPUPROF"
HZ_ENV = "VCTPU_OBS_CPUPROF_HZ"

#: seconds per fold window: the sampler flushes its fold table on this
#: cadence, so every emitted ``sample`` event covers a bounded window
#: (``win_t0`` .. envelope ``t``) — the join key for wait-edge
#: reconciliation — and the table never grows with run length
EMIT_EVERY_S = 2.0

#: distinct stacks kept per window; overflow folds into one
#: ``(truncated)`` bucket per (family, category) so a pathological
#: stack churn bounds event volume instead of exploding it
MAX_STACKS_PER_WINDOW = 400

#: frames kept per stack (root-most dropped first — the leaf is the
#: attribution signal)
MAX_DEPTH = 48

#: full-stack ticks are DECIMATED: every tick samples each thread's
#: LEAF frame (cheap — a few bytecodes per thread), and every Nth tick
#: walks whole stacks. A long GIL-held tick body is the profiler's real
#: hazard on a saturated host — the OS can deschedule the sampler
#: MID-BODY with the GIL held, stalling every Python-needing thread for
#: a scheduling period — so the expensive walk runs at ~1/N the rate
#: while the ledger/wait-attribution (leaf-driven) keep the full rate
STACK_EVERY = 8

#: sample categories that represent a core actually consumed —
#: the cpu-ledger numerator
CPU_CATEGORIES = ("gil", "native")

_CLK_TCK = os.sysconf("SC_CLK_TCK") if hasattr(os, "sysconf") else 100

# GIL-KEEPING pread for the per-tick clock reads: ``os.pread`` releases
# the GIL around its syscall, so N threads × hz reads/s means hundreds
# of forced GIL handoffs per second — measured at ~8% e2e on the 2-core
# box. ``ctypes.PyDLL`` calls do NOT release the GIL: a /proc stat read
# is ~2µs, so holding the GIL across it turns the whole tick into ONE
# short hold instead of a convoy of release/reacquire cycles.
try:
    import ctypes as _ctypes

    _libc = _ctypes.PyDLL(None)
    _libc.pread.restype = _ctypes.c_ssize_t
    _libc.pread.argtypes = [_ctypes.c_int, _ctypes.c_void_p,
                            _ctypes.c_size_t, _ctypes.c_long]
    _PREAD_BUF = _ctypes.create_string_buffer(1024)

    def _pread_stat(fd: int) -> bytes | None:
        n = _libc.pread(fd, _PREAD_BUF, 1024, 0)
        return _PREAD_BUF.raw[:n] if n > 0 else None
except Exception:  # noqa: BLE001  # vctpu-lint: disable=VCT002 — exotic libc: fall back to the GIL-releasing read; sampling stays correct, just costlier
    def _pread_stat(fd: int) -> bytes | None:
        try:
            raw = os.pread(fd, 1024, 0)
        except OSError:
            return None
        return raw or None

#: fast flag native-span sites check before touching the table
_SAMPLING = False

#: kernel tid -> open native-span name. Each worker thread writes only
#: its own key; the sampler thread reads.
_NATIVE_SPANS: dict[int, str] = {}

#: kernel tid -> registered thread family (register_current); written
#: under _REG_LOCK (threads register once at start-of-life, never hot)
_FAMILIES: dict[int, str] = {}
_REG_LOCK = threading.Lock()


def register_current(family: str) -> None:
    """Attribute the calling thread's samples to ``family`` (pool
    workers, pipeline stage workers and the committer register
    themselves; unregistered threads fall back to :func:`classify`).
    Cheap and unconditional — one dict write per thread lifetime."""
    try:
        tid = threading.get_native_id()
    except (AttributeError, OSError):  # exotic platform: fallback naming
        return
    with _REG_LOCK:
        _FAMILIES[tid] = family


def classify(name: str) -> str:
    """Thread family from a thread NAME — the fallback for threads that
    never called :func:`register_current` (matches the executor/pool
    naming conventions, docs/observability.md)."""
    if name.startswith("vctpu-io"):
        return "io"
    if name.startswith("vctpu-mesh"):
        return "mesh"
    if name.startswith(("vctpu-sampler", "obs-sampler")):
        return "obs"
    if name == "pipe-src":
        return "pipe.src"
    if name.startswith("pipe-stage"):
        return "pipe.stage"
    if name == "genome-prefetch":
        return "prefetch"
    if name == "MainThread":
        return "main"
    return "other"


class native_span:
    """Marks the calling thread as inside a named native call for the
    sampler's overlay (``native/__init__.py`` wraps
    ``fused_chunk_score`` and the BGZF inflate/deflate entries).

    A native call releases the GIL, so the Python frame the sampler
    sees is frozen at the call site; the overlay names the native frame
    that actually owns the samples. One module-bool check when the
    sampler is off."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def __enter__(self):
        if _SAMPLING:
            # per-thread key item assignment — GIL-atomic, sampler reads
            _NATIVE_SPANS[threading.get_native_id()] = self.name  # vctpu-lint: disable=VCT010 — per-thread-key dict cell (the obs/metrics pattern); each thread writes only its own key
        return self

    def __exit__(self, *exc):
        # unconditional pop (guarded by emptiness): a sampler stopping
        # mid-span must not leave a stale overlay for the next run
        if _NATIVE_SPANS:
            _NATIVE_SPANS.pop(threading.get_native_id(), None)  # vctpu-lint: disable=VCT010 — per-thread-key dict cell (the obs/metrics pattern); each thread writes only its own key
        return False


def _parse_stat(raw: bytes) -> tuple[float, str] | None:
    """(cpu seconds, kernel run state) from a ``/proc/.../stat`` read."""
    try:
        # comm may contain spaces/parens: split after the LAST ')'
        rest = raw.rsplit(b")", 1)[1].split()
        state = rest[0].decode("ascii", "replace")
        utime, stime = int(rest[11]), int(rest[12])
    except (IndexError, ValueError):
        return None
    return (utime + stime) / _CLK_TCK, state


def _task_stat(tid: int) -> tuple[float, str] | None:
    """(cpu seconds, kernel run state) of one kernel thread from
    ``/proc/self/task/<tid>/stat``; None when unreadable (thread died,
    or not Linux — callers then degrade to wall-only sampling)."""
    try:
        with open(f"/proc/self/task/{tid}/stat", "rb") as fh:
            raw = fh.read()
    except OSError:
        return None
    return _parse_stat(raw)


def thread_families() -> dict[int, str]:
    """kernel tid -> family for every live Python thread (registered
    name first, thread-name classification as the fallback). Registry
    entries of DEAD tids are pruned here — the kernel reuses tids, and
    a stale entry would book an unrelated new thread's samples under a
    long-gone worker's family."""
    out: dict[int, str] = {}
    live: set[int] = set()
    with _REG_LOCK:
        registered = dict(_FAMILIES)
    for t in threading.enumerate():
        tid = getattr(t, "native_id", None)
        if tid is None:
            continue
        live.add(tid)
        out[tid] = registered.get(tid) or classify(t.name)
    dead = set(registered) - live
    if dead:
        with _REG_LOCK:
            for tid in dead:
                _FAMILIES.pop(tid, None)
    return out


def family_cpu_seconds() -> dict[str, float]:
    """Cumulative CPU seconds per thread family right now — the
    substrate for the ResourceSampler's per-family ``proc.cpu_pct.*``
    gauges (obs/profile.py). Families of dead threads age out with the
    threads; callers diff successive snapshots."""
    out: dict[str, float] = {}
    for tid, family in thread_families().items():
        stat = _task_stat(tid)
        if stat is None:
            continue
        out[family] = out.get(family, 0.0) + stat[0]
    return out


def _frame_label(frame) -> str:
    """``module:function`` with the package prefix stripped — short
    enough for collapsed stacks, unambiguous enough to click through."""
    mod = frame.f_globals.get("__name__", "?")
    if mod.startswith("variantcalling_tpu."):
        mod = mod[len("variantcalling_tpu."):]
    return f"{mod}:{frame.f_code.co_name}"


class CpuSampler(threading.Thread):
    """The continuous profiler: one daemon thread sampling every live
    thread's stack + CPU clock at ``hz``, folding into ``sample``
    events on the open obs run (started by ``obs.start_run`` when
    ``VCTPU_OBS_CPUPROF=1``, stopped — with a final flush and a
    ``profile``/``cpuprof`` summary event — by ``obs.end_run``)."""

    #: seconds between thread-list refreshes: ``threading.enumerate`` +
    #: family resolution move OFF the per-tick path (vctpu threads are
    #: long-lived pools/stages; a thread born mid-window starts being
    #: sampled at the next refresh)
    REFRESH_S = 0.5

    def __init__(self, run, hz: float | None = None):
        super().__init__(name="vctpu-sampler", daemon=True)
        self.obs_run = run
        self.hz = knobs.get_float(HZ_ENV) if hz is None else float(hz)
        self.interval_s = 1.0 / max(self.hz, 0.001)
        self._halt = threading.Event()
        self.samples = 0
        self.cpu_samples = 0
        #: achieved ticks + wall span: GIL-held Python bursts DELAY the
        #: sampler past its nominal interval, so seconds-per-sample is
        #: ``elapsed/ticks`` (measured), never ``1/hz`` (aspirational) —
        #: the ledger and the summary both use the achieved rate
        self.ticks = 0
        self._t_started = time.perf_counter()
        #: whole-process CPU clock at start: the ledger calibrates its
        #: totals against the kernel's own accounting (sampling is
        #: biased AWAY from GIL-held bursts — the sampler cannot run
        #: during exactly the moments Python is busiest — so sampled
        #: totals undercount; the clock cannot)
        t = os.times()
        self._proc_cpu0 = t[0] + t[1]
        self._threads_seen: set[int] = set()
        #: kernel tid -> last-seen cumulative cpu seconds
        self._cpu_prev: dict[int, float] = {}
        #: (family, category, stack tuple) -> count, current window
        self._fold: dict[tuple, int] = {}
        self._win_t0 = self._now()
        self._last_emit = time.perf_counter()
        #: family -> cpu-category sample count (whole run, the summary)
        self._family_cpu: dict[str, int] = {}
        # -- per-tick cost containment: the tick body runs UNDER the
        # GIL, so every avoidable allocation/syscall directly stalls
        # GIL-needing workload threads (measured: a naive body cost
        # ~10% e2e at 47 Hz on the 2-core box; with these caches <2%)
        #: code object -> "module:function" label (frames repeat the
        #: same code objects tick after tick — label building happens
        #: once per code object, not once per frame per tick)
        self._label_cache: dict = {}
        #: kernel tid -> open /proc/self/task/<tid>/stat fd: ONE pread
        #: per thread per tick instead of open+read+close
        self._stat_fds: dict[int, int] = {}
        #: cached (python ident, kernel tid, family) rows, refreshed on
        #: REFRESH_S — never enumerated per tick
        self._threads: list[tuple[int, int | None, str]] = []
        self._last_refresh = 0.0

    def _now(self) -> float:
        """Run-relative time on the stream's own clock (the join key
        wait-edge reconciliation uses must match the envelope ``t``)."""
        return time.perf_counter() - self.obs_run._t0_mono

    def _refresh_threads(self) -> None:
        """Rebuild the sampled-thread cache (every REFRESH_S, off the
        per-tick path): enumerate live threads, resolve families, open
        missing /proc stat fds, drop dead ones."""
        my_ident = threading.get_ident()
        with _REG_LOCK:
            registered = dict(_FAMILIES)
        rows: list[tuple[int, int | None, str]] = []
        live: set[int] = set()
        for t in threading.enumerate():
            ident = t.ident
            tid = getattr(t, "native_id", None)
            if ident is None or ident == my_ident:
                continue
            family = (registered.get(tid) if tid is not None else None) \
                or classify(t.name)
            rows.append((ident, tid, family))
            if tid is not None:
                live.add(tid)
                self._threads_seen.add(tid)
                if tid not in self._stat_fds:
                    try:
                        self._stat_fds[tid] = os.open(
                            f"/proc/self/task/{tid}/stat", os.O_RDONLY)
                    except OSError:
                        pass  # not Linux / thread died: wall-only below
        self._threads = rows
        # prune registry entries of dead tids (tid reuse would book a
        # new unrelated thread under a long-gone worker's family)
        dead = set(registered) - live
        if dead:
            with _REG_LOCK:
                for tid in dead:
                    _FAMILIES.pop(tid, None)
        for tid in list(self._stat_fds):
            if tid not in live:
                try:
                    os.close(self._stat_fds.pop(tid))
                except OSError:
                    pass
        for tid in list(self._cpu_prev):
            if tid not in live:
                del self._cpu_prev[tid]

    def _close_fds(self) -> None:
        for tid in list(self._stat_fds):
            try:
                os.close(self._stat_fds.pop(tid))
            except OSError:
                pass

    def _stack_of(self, frame, overlay: str | None) -> tuple:
        cache = self._label_cache
        rev: list[str] = []
        f = frame
        while f is not None and len(rev) < MAX_DEPTH:
            code = f.f_code
            label = cache.get(code)
            if label is None:
                cache[code] = label = _frame_label(f)
            rev.append(label)
            f = f.f_back
        rev.reverse()  # root first, leaf last — collapsed-stack order
        if overlay is not None:
            rev.append(f"[native:{overlay}]")
        return tuple(rev)

    def sample_once(self) -> None:
        """One tick: snapshot frames + per-thread CPU clocks (one pread
        each, fds held open), classify, fold. The body is deliberately
        allocation-light — it runs under the GIL, so every wasted
        microsecond here stalls a workload thread. Never raises — the
        profiler observes, it must not kill the run."""
        now = time.perf_counter()
        if now - self._last_refresh >= self.REFRESH_S:
            self._last_refresh = now
            self._refresh_threads()
        frames = sys._current_frames()
        self.ticks += 1
        full_stacks = self.ticks % STACK_EVERY == 1
        fold = self._fold
        cache = self._label_cache
        spans = _NATIVE_SPANS
        for ident, tid, family in self._threads:
            frame = frames.get(ident)
            if frame is None:
                continue
            ran = False
            state = ""
            fd = self._stat_fds.get(tid) if tid is not None else None
            if fd is not None:
                raw = _pread_stat(fd)  # GIL kept: no handoff per read
                stat = _parse_stat(raw) if raw else None
                if stat is not None:
                    cpu_now, state = stat
                    prev = self._cpu_prev.get(tid)
                    self._cpu_prev[tid] = cpu_now
                    ran = prev is not None and cpu_now > prev
                else:
                    ran = True  # wall-only degradation: book as on-CPU
            elif tid is not None:
                # /proc unavailable (not Linux): honest wall-only
                # degradation — everything books as on-CPU
                ran = True
            overlay = spans.get(tid) if tid is not None else None
            # on-CPU needs BOTH signals: kernel state R at the sample
            # instant AND the thread's CPU clock advanced over the
            # interval — clock-advance alone would attribute an earlier
            # burst to whatever frame the thread is parked in NOW (the
            # "threading:wait ran hot" artifact); state R alone is just
            # runnable (waiting for a core or the GIL)
            if ran and (state == "R" or not state):
                cat = "native" if overlay is not None else "gil"
                self.cpu_samples += 1
                self._family_cpu[family] = self._family_cpu.get(family, 0) + 1
            elif state == "R":
                cat = "runnable"
            else:
                cat = "wait"
            if full_stacks:
                stack = self._stack_of(frame, overlay)
            else:
                # leaf-only tick: minimum bytecodes under the GIL
                code = frame.f_code
                label = cache.get(code)
                if label is None:
                    cache[code] = label = _frame_label(frame)
                stack = (label,) if overlay is None \
                    else (label, f"[native:{overlay}]")
            key = (family, cat, stack)
            if key not in fold and len(fold) >= MAX_STACKS_PER_WINDOW:
                key = (family, cat, ("(truncated)",))
            fold[key] = fold.get(key, 0) + 1
            self.samples += 1

    def _flush(self) -> None:
        """Emit the window's fold as ``sample`` events and open the
        next window."""
        fold, self._fold = self._fold, {}
        win_t0 = self._win_t0
        self._win_t0 = self._now()
        for (family, cat, stack), n in sorted(fold.items(),
                                              key=lambda kv: -kv[1]):
            obs.event("sample", family, stack=";".join(stack), n=n,
                      cat=cat, family=family, win_t0=round(win_t0, 6))

    def run(self) -> None:  # noqa: A003 — Thread API
        global _SAMPLING
        _SAMPLING = True
        try:
            while not self._halt.wait(self.interval_s):
                try:
                    self.sample_once()
                except Exception:  # noqa: BLE001  # vctpu-lint: disable=VCT002 — the profiler observes; a torn tick is dropped, never fatal to the run
                    pass
                if time.perf_counter() - self._last_emit >= EMIT_EVERY_S:
                    self._last_emit = time.perf_counter()
                    self._flush()
        finally:
            _SAMPLING = False
            self._close_fds()

    def stop(self) -> None:
        """Halt, final-flush, and emit the ``profile``/``cpuprof``
        summary (called by ``obs.end_run`` while the stream still
        accepts events)."""
        self._halt.set()
        self.join(timeout=2.0)
        self._flush()
        elapsed = max(time.perf_counter() - self._t_started, 1e-9)
        # MEASURED seconds each tick stands for: GIL-held bursts starve
        # the sampler below its nominal rate, and dividing by nominal hz
        # would then undercount CPU seconds by exactly the starvation
        spt = elapsed / self.ticks if self.ticks else 1.0 / self.hz
        cpu_s = {f: round(n * spt, 6)
                 for f, n in sorted(self._family_cpu.items())}
        t = os.times()
        obs.event("profile", "cpuprof", hz=self.hz,
                  interval_s=round(self.interval_s, 6),
                  samples=self.samples, cpu_samples=self.cpu_samples,
                  ticks=self.ticks, elapsed_s=round(elapsed, 6),
                  effective_hz=round(self.ticks / elapsed, 2),
                  threads=len(self._threads_seen),
                  cpu_s_total=round(self.cpu_samples * spt, 6),
                  proc_cpu_s=round(t[0] + t[1] - self._proc_cpu0, 6),
                  families=cpu_s)


# ---------------------------------------------------------------------------
# readers: fold / flame / diff / ledger (the `vctpu obs flame|cpuledger`
# substrate — pure functions over a parsed obs event list)
# ---------------------------------------------------------------------------


def fold_events(events: list[dict]) -> dict[tuple, int]:
    """Merge every ``sample`` event back into one
    ``(family, cat, stack string) -> samples`` fold table."""
    fold: dict[tuple, int] = {}
    for e in events:
        if e.get("kind") != "sample":
            continue
        key = (e.get("family", "?"), e.get("cat", "?"), e.get("stack", ""))
        fold[key] = fold.get(key, 0) + int(e.get("n", 0))
    return fold


def profiled_rate(events: list[dict]) -> tuple[float, float, float] | None:
    """``(nominal hz, measured seconds-per-sample, process cpu-s)``
    from the log's ``profile``/``cpuprof`` summaries, or None when the
    run never sampled. Seconds-per-sample is ``elapsed/ticks`` when the
    summary recorded the achieved rate (GIL starvation makes nominal
    1/hz undercount); ``1/hz`` is the legacy fallback. The process
    cpu-seconds (0 when absent) calibrate the ledger's totals.

    Multi-rank merged timelines (``export.read_run``): each rank wrote
    its own summary — the LAST summary per rank is aggregated (cpu
    seconds and ticks/elapsed SUM across ranks, matching the summed
    sample fold), so the ledger stays correct on a merged log."""
    last_by_rank: dict = {}
    for e in events:
        if e.get("kind") == "profile" and e.get("name") == "cpuprof" \
                and isinstance(e.get("hz"), (int, float)) and e["hz"] > 0:
            last_by_rank[e.get("rank", 0)] = e
    if not last_by_rank:
        return None
    hz = float(next(iter(last_by_rank.values()))["hz"])
    proc = ticks = elapsed = 0.0
    legacy_spt: float | None = None
    for e in last_by_rank.values():
        p = e.get("proc_cpu_s")
        if isinstance(p, (int, float)) and p > 0:
            proc += float(p)
        t, el = e.get("ticks"), e.get("elapsed_s")
        if isinstance(t, int) and t > 0 \
                and isinstance(el, (int, float)) and el > 0:
            ticks += t
            elapsed += el
        else:
            legacy_spt = 1.0 / float(e["hz"])
    spt = elapsed / ticks if ticks else (legacy_spt or 1.0 / hz)
    return hz, spt, proc


def collapsed_lines(events: list[dict]) -> list[str]:
    """Brendan-Gregg collapsed-stack text: ``family;cat;frame;...;leaf
    N`` per line, heaviest first — feed to any flamegraph tool."""
    fold = fold_events(events)
    return [f"{family};{cat};{stack} {n}"
            for (family, cat, stack), n in
            sorted(fold.items(), key=lambda kv: -kv[1])]


def to_speedscope(events: list[dict], name: str = "vctpu") -> dict | None:
    """The https://speedscope.app sampled-profile JSON of a log's
    ``sample`` events (one profile per category, shared frame table);
    None when the log holds no samples."""
    fold = fold_events(events)
    if not fold:
        return None
    frame_index: dict[str, int] = {}
    frames: list[dict] = []

    def fidx(label: str) -> int:
        i = frame_index.get(label)
        if i is None:
            i = frame_index[label] = len(frames)
            frames.append({"name": label})
        return i

    by_cat: dict[str, tuple[list, list]] = {}
    for (family, cat, stack), n in sorted(fold.items(),
                                          key=lambda kv: -kv[1]):
        samples, weights = by_cat.setdefault(cat, ([], []))
        labels = [family] + [s for s in stack.split(";") if s]
        samples.append([fidx(x) for x in labels])
        weights.append(n)
    profiles = []
    for cat in sorted(by_cat):
        samples, weights = by_cat[cat]
        profiles.append({
            "type": "sampled", "name": f"{name} [{cat}]",
            "unit": "none", "startValue": 0, "endValue": sum(weights),
            "samples": samples, "weights": weights,
        })
    return {
        "$schema": "https://www.speedscope.app/file-format-schema.json",
        "name": name,
        "shared": {"frames": frames},
        "profiles": profiles,
    }


def _frame_weights(events: list[dict],
                   cpu_only: bool = True) -> tuple[dict[str, int], int]:
    """Per-frame SELF sample weight (the leaf owns the sample) plus the
    total — the unit ``flame --diff`` ranks."""
    weights: dict[str, int] = {}
    total = 0
    for (family, cat, stack), n in fold_events(events).items():
        if cpu_only and cat not in CPU_CATEGORIES:
            continue
        leaf = stack.rsplit(";", 1)[-1] if stack else f"({family})"
        weights[leaf] = weights.get(leaf, 0) + n
        total += n
    return weights, total


def diff_folds(candidate: list[dict], baseline: list[dict],
               top: int = 20) -> dict:
    """The ``obs flame --diff A B`` report: per-frame CPU self-share in
    the candidate vs the baseline (shares, so runs of different length
    compare), ranked by absolute share delta. An attribution report,
    not a gate — ``tools/bench_gate.py`` owns pass/fail."""
    cw, ct = _frame_weights(candidate)
    bw, bt = _frame_weights(baseline)
    if not ct or not bt:
        return {"frames": [], "candidate_cpu_samples": ct,
                "baseline_cpu_samples": bt,
                "note": "one of the logs holds no CPU samples"}
    rows = []
    for frame in set(cw) | set(bw):
        c_share = 100.0 * cw.get(frame, 0) / ct
        b_share = 100.0 * bw.get(frame, 0) / bt
        rows.append({"frame": frame,
                     "candidate_pct": round(c_share, 2),
                     "baseline_pct": round(b_share, 2),
                     "delta_pct": round(c_share - b_share, 2)})
    rows.sort(key=lambda r: -abs(r["delta_pct"]))
    return {"candidate_cpu_samples": ct, "baseline_cpu_samples": bt,
            "frames": rows[:max(1, top)]}


def render_diff(report: dict) -> str:
    if not report["frames"]:
        return report.get("note", "no samples to diff")
    lines = [f"flame diff (CPU self-share per frame; candidate "
             f"{report['candidate_cpu_samples']} vs baseline "
             f"{report['baseline_cpu_samples']} cpu samples):"]
    width = max(len(r["frame"]) for r in report["frames"])
    lines.append(f"  {'frame':<{width}}  {'base%':>7} {'cand%':>7} "
                 f"{'delta':>7}")
    for r in report["frames"]:
        lines.append(f"  {r['frame']:<{width}}  {r['baseline_pct']:>7.2f} "
                     f"{r['candidate_pct']:>7.2f} {r['delta_pct']:>+7.2f}")
    return "\n".join(lines)


# -- the measured cpu-budget ledger ----------------------------------------

#: stage attribution markers, matched LEAF-FIRST against each stack's
#: frames: the first frame (from the leaf) matching a pattern names the
#: stage. Mirrors the docs/perf_notes.md budget-table rows; frames that
#: match nothing book under their thread family as ``other.<family>``.
STAGE_MARKERS: tuple[tuple[str, re.Pattern], ...] = tuple(
    (stage, re.compile(pat)) for stage, pat in (
        ("score", r"fused_chunk_score|score_table|score_stage|"
                  r"predict_margin|forest_predict|megabatch"),
        ("parse", r"parse_chunk|iter_raw|bgzf_inflate|_inflate|"
                  r"scan_block|read_chunk|VcfChunkReader|:_scan|"
                  r"_table_from_parsed|vcf_parse"),
        ("featurize", r"host_features|featurize|build_matrix|classify_vcf"),
        ("render", r"render_stage|render_table_bytes|assemble_table_bytes|"
                   r"format_float"),
        ("compress", r"bgzf_deflate|compress_stage|BgzfChunkCompressor|"
                     r"bgzf_compress"),
        ("commit", r"_sink_write|journal|writeback|filter_variants:attempt"),
        ("prefetch", r"encode_all|fasta_encode|_encode_contig"),
        ("obs", r"obs\.|obs/|:_emit|:snapshot"),
    ))


#: family -> ledger stage when no frame marker matches: a family whose
#: every CPU second belongs to one budget row by construction books
#: there even when the sampled frame is glue (heartbeats, journal
#: bookkeeping on the committer thread)
_FAMILY_STAGES = {"committer": "commit", "prefetch": "prefetch",
                  "obs": "obs"}


def _stage_of(stack: str, family: str) -> str:
    for frame in reversed(stack.split(";")):
        for stage, pat in STAGE_MARKERS:
            if pat.search(frame):
                return stage
    return _FAMILY_STAGES.get(family, f"other.{family}")


def _records_of(events: list[dict]) -> int:
    """Total records the log's run(s) processed: the final metrics
    snapshot's ``records`` counter (counters accumulate across every
    pipeline run recorded into one stream), heartbeat fallback. On a
    multi-rank merged timeline each rank reported its own counter —
    the last metrics event PER RANK sums (the read_run rule)."""
    last_by_rank: dict = {}
    for e in events:
        if e.get("kind") == "metrics":
            n = (e.get("counters") or {}).get("records")
            if isinstance(n, (int, float)) and n > 0:
                last_by_rank[e.get("rank", 0)] = int(n)
    if last_by_rank:
        return sum(last_by_rank.values())
    last_hb_by_rank: dict = {}
    for e in events:
        if e.get("kind") == "heartbeat":
            last_hb_by_rank[e.get("rank", 0)] = e.get("records", 0)
    return int(sum(last_hb_by_rank.values()))


def cpuledger(events: list[dict]) -> dict | None:
    """The measured cpu-budget ledger: CPU seconds per stage (samples in
    CPU categories / hz, attributed by :data:`STAGE_MARKERS`) and —
    when the log records how many variants the run processed —
    **cpu-s per 1M variants per stage**, the unit docs/perf_notes.md's
    budget table is written in. None when the log holds no samples."""
    rate = profiled_rate(events)
    fold = fold_events(events)
    if rate is None or not fold:
        return None
    hz, spt, proc_cpu_s = rate
    stage_samples: dict[str, int] = {}
    total = 0
    for (family, cat, stack), n in fold.items():
        if cat not in CPU_CATEGORIES:
            continue
        stage = _stage_of(stack, family)
        stage_samples[stage] = stage_samples.get(stage, 0) + n
        total += n
    records = _records_of(events)
    # CALIBRATION: sampled totals systematically undercount GIL-held
    # Python (the sampler cannot run during exactly those moments), so
    # when the summary carries the whole-process CPU clock the totals
    # anchor on it — the kernel's accounting is the truth, the sampled
    # fold provides the per-stage SPLIT
    sampled_s = total * spt
    total_s = proc_cpu_s if proc_cpu_s > 0 else sampled_s
    scale_s = total_s / sampled_s if sampled_s > 0 else 0.0
    out: dict = {
        "hz": hz,
        "effective_hz": round(1.0 / spt, 2),
        "cpu_samples": total,
        "records": records,
        "sampled_cpu_s": round(sampled_s, 4),
        "proc_cpu_s": round(proc_cpu_s, 4),
        "total_cpu_s": round(total_s, 4),
        "stages_cpu_s": {s: round(n * spt * scale_s, 4)
                         for s, n in sorted(stage_samples.items(),
                                            key=lambda kv: -kv[1])},
    }
    if records > 0:
        scale = 1e6 / records
        out["total_cpu_s_per_1m"] = round(total_s * scale, 4)
        out["stages"] = {s: round(n * spt * scale_s * scale, 4)
                         for s, n in sorted(stage_samples.items(),
                                            key=lambda kv: -kv[1])}
    return out


def render_cpuledger(ledger: dict) -> str:
    lines = [f"cpu-budget ledger ({ledger['cpu_samples']} CPU samples at "
             f"{ledger.get('effective_hz', ledger['hz']):g} Hz achieved "
             f"({ledger['hz']:g} nominal) over "
             f"{ledger['records']} records):"]
    if ledger.get("proc_cpu_s"):
        lines.append(f"  totals calibrated on the process CPU clock "
                     f"({ledger['proc_cpu_s']:.3f} cpu-s; sampling alone "
                     f"saw {ledger.get('sampled_cpu_s', 0):.3f} — the "
                     "sampler cannot run during GIL-held bursts)")
    per_1m = ledger.get("stages")
    stages = per_1m if per_1m is not None else ledger["stages_cpu_s"]
    width = max(len(s) for s in stages) if stages else 5
    if per_1m is not None:
        lines.append(f"  {'stage':<{width}}  {'cpu_s':>8}  {'cpu-s/1M':>9}")
        for s in stages:
            lines.append(f"  {s:<{width}}  "
                         f"{ledger['stages_cpu_s'][s]:>8.3f}  "
                         f"{per_1m[s]:>9.4f}")
        lines.append(f"  {'TOTAL':<{width}}  {ledger['total_cpu_s']:>8.3f}  "
                     f"{ledger['total_cpu_s_per_1m']:>9.4f}")
    else:
        lines.append(f"  {'stage':<{width}}  {'cpu_s':>8}")
        for s in stages:
            lines.append(f"  {s:<{width}}  {stages[s]:>8.3f}")
        lines.append("  (no record count in this log — per-1M column "
                     "unavailable)")
    return "\n".join(lines)


def compact_ledger(ledger: dict) -> dict:
    """The bench-row shape (``e2e.cpuledger``) tools/bench_gate.py
    gates: flat per-stage cpu-s/1M numbers plus the total."""
    out = {"hz": ledger["hz"], "cpu_samples": ledger["cpu_samples"],
           "records": ledger["records"]}
    if "stages" in ledger:
        out["total_cpu_s_per_1m"] = ledger["total_cpu_s_per_1m"]
        out["stages"] = dict(ledger["stages"])
    else:
        out["total_cpu_s"] = ledger["total_cpu_s"]
    return out


# -- wait-edge reconciliation ----------------------------------------------


def explain_waits(events: list[dict],
                  edge_intervals: dict[str, list[tuple[float, float]]],
                  top: int = 5) -> dict[str, dict]:
    """For each named wait edge: which frames were consuming CPU while
    chunks sat on that edge — the "cores were running X" answer the
    critical-path engine attaches to its dominant wait edges.

    ``edge_intervals`` maps edge name -> absolute (run-relative)
    ``(start, end)`` wait intervals (obs/critical.py collects them from
    the trace spans). Sample windows (``win_t0`` .. envelope ``t``)
    overlap-weight against the merged intervals: a sample batch whose
    window half-overlaps the edge's waits contributes half its count.
    Windowed, not exact — but measured, which the analytic budget never
    was."""
    batches = [(float(e.get("win_t0", 0.0)), float(e.get("t", 0.0)),
                e.get("cat"), e.get("stack", ""), int(e.get("n", 0)))
               for e in events if e.get("kind") == "sample"]
    if not batches:
        return {}
    out: dict[str, dict] = {}
    for edge, intervals in edge_intervals.items():
        merged: list[list[float]] = []
        for t0, t1 in sorted(intervals):
            if merged and t0 <= merged[-1][1]:
                merged[-1][1] = max(merged[-1][1], t1)
            else:
                merged.append([t0, t1])
        wait_s = sum(t1 - t0 for t0, t1 in merged)
        if wait_s <= 0:
            continue
        frames: dict[str, float] = {}
        total = 0.0
        for w0, w1, cat, stack, n in batches:
            if cat not in CPU_CATEGORIES or w1 <= w0:
                continue
            overlap = sum(max(0.0, min(w1, t1) - max(w0, t0))
                          for t0, t1 in merged)
            if overlap <= 0:
                continue
            weight = n * (overlap / (w1 - w0))
            leaf = stack.rsplit(";", 1)[-1] if stack else "?"
            frames[leaf] = frames.get(leaf, 0.0) + weight
            total += weight
        if total < 1.0:
            # less than one whole sample overlapped the edge's waits:
            # reporting frames off that would be noise, not measurement
            continue
        ranked = sorted(frames.items(), key=lambda kv: -kv[1])[:top]
        out[edge] = {
            "wait_s": round(wait_s, 6),
            "cpu_samples": round(total, 1),
            "frames": [{"frame": f,
                        "share_pct": round(100.0 * w / total, 1)}
                       for f, w in ranked],
        }
    return out
