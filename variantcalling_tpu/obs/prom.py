"""Prometheus text exposition of obs metrics — the external-scraper face
of the live telemetry plane.

Two consumers share one renderer:

- ``vctpu obs prom <log>`` converts any obs run log's latest metrics
  state (the final ``metrics`` snapshot of a finished run, or the last
  periodic ``snapshot`` of an in-flight one) into the Prometheus text
  exposition format, for ad-hoc scraping of a genome-scale run;
- the live textfile writer (``VCTPU_OBS_PROM_FILE``) atomically rewrites
  a node-exporter-style textfile on every periodic snapshot, so a
  standing scraper watches the run — and the future ``vctpu serve``
  daemon — without parsing JSONL.

Mapping: counters -> ``vctpu_<name>_total``; gauges -> ``vctpu_<name>``
plus ``vctpu_<name>_peak``; histograms -> a summary family
(``quantile`` label, ``_count``/``_sum`` series) from the CUMULATIVE
buckets plus a ``_rolling`` gauge family (same quantile labels,
``window_s`` label) from the rolling-window rings — rolling p95 means
"recent", the SLO signal. Metric names are sanitized to the Prometheus
charset; everything else is verbatim.
"""

from __future__ import annotations

import os
import re
import tempfile

#: Prometheus metric-name charset (values and label values are free-form)
_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")

#: histogram snapshot percentile keys -> Prometheus quantile label values
_QUANTILES = (("p50", "0.5"), ("p95", "0.95"), ("p99", "0.99"))


def _san(name: str) -> str:
    return _NAME_RE.sub("_", name)


#: per-endpoint metric convention (vctpu serve, docs/serving.md): a
#: metric named ``<base>.by_endpoint.<endpoint>`` renders as the base
#: family with a real ``{endpoint="…"}`` label, so per-endpoint request
#: series (rolling p99s, shed/accepted/failed counters) are one
#: Prometheus family each instead of a family per endpoint
_ENDPOINT_SEP = ".by_endpoint."


def _split_endpoint(name: str) -> tuple[str, str | None]:
    base, sep, endpoint = name.partition(_ENDPOINT_SEP)
    return (base, endpoint) if sep and endpoint else (name, None)


def _label_str(labels: list[tuple[str, str]]) -> str:
    if not labels:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in labels) + "}"


def _num(v) -> str:
    if v is None:
        return "NaN"
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    return format(float(v), ".9g")


def snapshot_to_prom(snap: dict, tool: str = "vctpu",
                     in_flight: bool = True,
                     extra: dict[str, float] | None = None) -> str:
    """Render one metrics snapshot (``{counters, gauges, histograms}``,
    the ``metrics``/``snapshot`` event body) as text exposition."""
    lines: list[str] = []
    seen_families: set[str] = set()

    def family(name: str, mtype: str, help_text: str) -> None:
        # one HELP/TYPE per family: endpoint-labeled series of one base
        # (``.by_endpoint.`` convention) share a single family header
        if name in seen_families:
            return
        seen_families.add(name)
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {mtype}")

    family("vctpu_run_in_flight", "gauge",
           "1 while the run is still writing its obs stream")
    lines.append(f'vctpu_run_in_flight{{tool="{tool}"}} '
                 f"{1 if in_flight else 0}")

    for name, value in sorted((extra or {}).items()):
        m = f"vctpu_{_san(name)}"
        family(m, "gauge", f"obs run field {name}")
        lines.append(f"{m} {_num(value)}")

    for name, value in sorted((snap.get("counters") or {}).items()):
        base, endpoint = _split_endpoint(name)
        m = f"vctpu_{_san(base)}_total"
        family(m, "counter", f"obs counter {base}")
        labels = [("endpoint", endpoint)] if endpoint else []
        lines.append(f"{m}{_label_str(labels)} {_num(value)}")

    for name, g in sorted((snap.get("gauges") or {}).items()):
        if not isinstance(g, dict):
            continue
        base, endpoint = _split_endpoint(name)
        m = f"vctpu_{_san(base)}"
        labels = [("endpoint", endpoint)] if endpoint else []
        family(m, "gauge", f"obs gauge {base}")
        lines.append(f"{m}{_label_str(labels)} {_num(g.get('value'))}")
        family(f"{m}_peak", "gauge", f"obs gauge {base} run peak")
        lines.append(f"{m}_peak{_label_str(labels)} {_num(g.get('peak'))}")

    for name, h in sorted((snap.get("histograms") or {}).items()):
        if not isinstance(h, dict):
            continue
        base, endpoint = _split_endpoint(name)
        m = f"vctpu_{_san(base)}"
        ep_labels = [("endpoint", endpoint)] if endpoint else []
        family(m, "summary", f"obs histogram {base} (cumulative)")
        for key, q in _QUANTILES:
            if h.get(key) is not None:
                lines.append(
                    f"{m}{_label_str(ep_labels + [('quantile', q)])} "
                    f"{_num(h[key])}")
        lines.append(f"{m}_sum{_label_str(ep_labels)} "
                     f"{_num(h.get('sum', 0))}")
        lines.append(f"{m}_count{_label_str(ep_labels)} "
                     f"{_num(h.get('count', 0))}")
        rolling = h.get("rolling")
        if isinstance(rolling, dict):
            rm = f"{m}_rolling"
            family(rm, "gauge",
                   f"obs histogram {base} rolling-window quantiles")
            window = _num(rolling.get("window_s"))
            for key, q in _QUANTILES:
                if rolling.get(key) is not None:
                    lines.append(
                        f"{rm}{_label_str(ep_labels + [('quantile', q), ('window_s', window)])} "
                        f"{_num(rolling[key])}")
            lines.append(
                f"{rm}_count{_label_str(ep_labels + [('window_s', window)])} "
                f"{_num(rolling.get('count', 0))}")
    return "\n".join(lines) + "\n"


def events_to_prom(events: list[dict]) -> str:
    """Text exposition of an obs log's LATEST metrics state: the last
    ``snapshot``/``metrics`` event wins (an in-flight run has periodic
    snapshots, a finished one ends with the final ``metrics``)."""
    manifest = next((e for e in events if e.get("kind") == "manifest"), None)
    run_end = next((e for e in reversed(events)
                    if e.get("kind") == "run_end"), None)
    snap_ev = next((e for e in reversed(events)
                    if e.get("kind") in ("snapshot", "metrics")), None)
    snap = {k: snap_ev.get(k, {}) for k in
            ("counters", "gauges", "histograms")} if snap_ev else {}
    extra: dict[str, float] = {}
    hb = next((e for e in reversed(events)
               if e.get("kind") == "heartbeat"), None)
    if hb is not None:
        for key in ("chunks", "records", "vps", "pct", "eta_s"):
            if isinstance(hb.get(key), (int, float)):
                extra[f"progress.{key}"] = hb[key]
    if run_end is not None:
        extra["run_duration_seconds"] = float(run_end.get("dur", 0.0))
    return snapshot_to_prom(
        snap, tool=(manifest or {}).get("tool", "vctpu"),
        in_flight=run_end is None, extra=extra)


def write_textfile(path: str, text: str) -> None:
    """Atomic textfile-collector write: a scraper must never read a
    half-written exposition (tmp file + ``os.replace`` in one dir)."""
    d = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(prefix=".vctpu_prom_", dir=d)
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as fh:
            fh.write(text)
        os.replace(tmp, path)
    except OSError:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
