"""Exporters: obs JSONL -> Chrome trace-event JSON (Perfetto) or a
terminal summary.

The Chrome trace-event format (the ``traceEvents`` array Perfetto and
``chrome://tracing`` both load) is the lingua franca of the JAX stack's
profiling UIs — ``jax.profiler`` device traces land in the same viewer —
so exporting the host-side obs stream there puts pipeline stages, chunk
spans, degradations and fault firings on the SAME timeline a device
trace uses (open both in one Perfetto session via "Open trace file").

Mapping (validated by ``tests/unit/test_obs.py`` and the tier-0 schema
stage):

- ``span``       -> ``ph: "X"`` complete events (``ts`` = span start in
  µs since run start, ``dur`` = µs), one track per recording thread;
- ``degrade`` / ``fault`` / ``retry`` / ``journal`` / ``resolve`` /
  ``stage`` -> ``ph: "i"`` instant events (thread scope);
- ``heartbeat``  -> ``ph: "C"`` counter tracks (records, chunks, vps);
- manifest/tool  -> ``ph: "M"`` process/thread name metadata.

Every emitted event carries ``pid``/``tid``/``ph``/``ts``; the list is
sorted by ``ts`` so consumers that stream it see a monotonically
consistent timeline.
"""

from __future__ import annotations

import json

from variantcalling_tpu.obs.schema import SCHEMA_VERSION

#: event kinds rendered as instant markers on their thread's track
_INSTANT_KINDS = ("degrade", "fault", "retry", "journal", "resolve", "stage")

#: envelope fields not repeated into a trace event's args
_ENVELOPE = ("v", "seq", "ts", "t", "kind", "name", "pid", "tid")


class ObsLogError(ValueError):
    """The file is not a readable obs run log."""


def read_events(path: str) -> list[dict]:
    """Parse one obs JSONL log; raises :class:`ObsLogError` on garbage
    (missing file surfaces as OSError for the CLI to map to exit 2)."""
    events: list[dict] = []
    with open(path, encoding="utf-8") as fh:
        for i, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            try:
                event = json.loads(line)
            except ValueError as e:
                raise ObsLogError(f"{path}:{i}: not JSON: {e}") from None
            if not isinstance(event, dict) or "kind" not in event:
                raise ObsLogError(f"{path}:{i}: not an obs event")
            events.append(event)
    if not events:
        raise ObsLogError(f"{path}: empty obs log")
    version = events[0].get("v")
    if version != SCHEMA_VERSION:
        raise ObsLogError(f"{path}: schema version {version!r} != "
                          f"{SCHEMA_VERSION} (regenerate or upgrade)")
    return events


def _args_of(event: dict) -> dict:
    return {k: v for k, v in event.items() if k not in _ENVELOPE}


def to_chrome_trace(events: list[dict]) -> dict:
    """The ``{"traceEvents": [...]}`` object Perfetto loads."""
    trace: list[dict] = []
    manifest = next((e for e in events if e.get("kind") == "manifest"), None)
    pids = {e.get("pid", 0) for e in events}
    tool = (manifest or {}).get("tool", "vctpu")
    threads: dict[tuple, str] = {}
    for e in events:
        key = (e.get("pid", 0), e.get("tid", 0))
        name = e.get("thread") if e.get("kind") == "span" else None
        if key not in threads or (name and threads[key] == "thread"):
            threads[key] = name or "thread"
    for pid in sorted(pids):
        trace.append({"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                      "ts": 0, "args": {"name": tool}})
    for (pid, tid), name in sorted(threads.items()):
        trace.append({"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                      "ts": 0, "args": {"name": name}})

    for e in events:
        kind = e.get("kind")
        pid, tid = e.get("pid", 0), e.get("tid", 0)
        t_us = float(e.get("t", 0.0)) * 1e6
        if kind == "span":
            dur_us = float(e.get("dur", 0.0)) * 1e6
            trace.append({"name": e.get("name", "span"), "ph": "X", "cat": "span",
                          "ts": max(0.0, t_us - dur_us), "dur": dur_us,
                          "pid": pid, "tid": tid, "args": _args_of(e)})
        elif kind in _INSTANT_KINDS:
            trace.append({"name": f"{kind}:{e.get('name', '')}", "ph": "i",
                          "cat": kind, "s": "t", "ts": t_us,
                          "pid": pid, "tid": tid, "args": _args_of(e)})
        elif kind == "heartbeat":
            for track in ("records", "chunks", "vps"):
                if track in e:
                    trace.append({"name": track, "ph": "C", "ts": t_us,
                                  "pid": pid, "tid": tid,
                                  "args": {track: e[track]}})
    trace.sort(key=lambda ev: (ev["ts"], 0 if ev["ph"] == "M" else 1))
    return {
        "traceEvents": trace,
        "displayTimeUnit": "ms",
        "otherData": {"tool": tool, "schema_version": SCHEMA_VERSION,
                      "source": "variantcalling_tpu obs"},
    }


def summarize(events: list[dict]) -> dict:
    """Terminal roll-up: per-stage time, throughput, degradations,
    slowest chunks, final metrics."""
    manifest = next((e for e in events if e.get("kind") == "manifest"), None)
    run_end = next((e for e in reversed(events)
                    if e.get("kind") == "run_end"), None)
    metrics = next((e for e in reversed(events)
                    if e.get("kind") == "metrics"), None)

    stages: dict[str, dict] = {}
    chunk_spans: list[dict] = []
    for e in events:
        if e.get("kind") != "span":
            continue
        name = e.get("name", "span")
        dur = float(e.get("dur", 0.0))
        s = stages.setdefault(name, {"count": 0, "total_s": 0.0, "max_s": 0.0})
        s["count"] += 1
        s["total_s"] += dur
        s["max_s"] = max(s["max_s"], dur)
        if "chunk" in e:
            chunk_spans.append(e)
    for s in stages.values():
        s["total_s"] = round(s["total_s"], 6)
        s["mean_s"] = round(s["total_s"] / s["count"], 6)
        s["max_s"] = round(s["max_s"], 6)

    degradations: dict[str, int] = {}
    faults: dict[str, int] = {}
    for e in events:
        if e.get("kind") == "degrade":
            degradations[e.get("name", "?")] = \
                degradations.get(e.get("name", "?"), 0) + 1
        elif e.get("kind") == "fault":
            faults[e.get("name", "?")] = faults.get(e.get("name", "?"), 0) + 1

    slowest = sorted(chunk_spans, key=lambda e: -float(e.get("dur", 0.0)))[:5]
    heartbeats = [e for e in events if e.get("kind") == "heartbeat"]
    records = heartbeats[-1].get("records") if heartbeats else None
    dur = float(run_end.get("dur", 0.0)) if run_end else None

    return {
        "run": {
            "tool": (manifest or {}).get("tool"),
            "version": (manifest or {}).get("version"),
            "status": run_end.get("status") if run_end else "incomplete",
            "duration_s": round(dur, 3) if dur is not None else None,
            "events": len(events),
        },
        "stages": dict(sorted(stages.items())),
        "throughput": {
            "records": records,
            "records_per_s": round(records / dur) if records and dur else None,
        },
        "degradations": degradations,
        "faults": faults,
        "slowest_chunks": [{"name": e.get("name"), "chunk": e.get("chunk"),
                            "dur_s": round(float(e.get("dur", 0.0)), 6)}
                           for e in slowest],
        "metrics": _args_of(metrics) if metrics else {},
    }


def render_summary(summary: dict) -> str:
    """Human-readable roll-up (``vctpu obs summary`` without ``--json``)."""
    run = summary["run"]
    lines = [f"run: {run.get('tool')} v{run.get('version')} — "
             f"{run.get('status')} in {run.get('duration_s')}s "
             f"({run.get('events')} events)"]
    if summary["stages"]:
        lines.append("stages (total / mean / max seconds):")
        width = max(len(n) for n in summary["stages"])
        for name, s in summary["stages"].items():
            lines.append(f"  {name:<{width}}  x{s['count']:<5} "
                         f"{s['total_s']:>9.3f} {s['mean_s']:>9.4f} "
                         f"{s['max_s']:>9.4f}")
    tp = summary["throughput"]
    if tp.get("records"):
        lines.append(f"throughput: {tp['records']} records"
                     + (f" ({tp['records_per_s']}/s)"
                        if tp.get("records_per_s") else ""))
    if summary["degradations"]:
        lines.append("degradations: " + ", ".join(
            f"{k} x{v}" for k, v in sorted(summary["degradations"].items())))
    if summary["faults"]:
        lines.append("injected faults: " + ", ".join(
            f"{k} x{v}" for k, v in sorted(summary["faults"].items())))
    if summary["slowest_chunks"]:
        lines.append("slowest chunks: " + ", ".join(
            f"{c['name']}#{c['chunk']} {c['dur_s']:.3f}s"
            for c in summary["slowest_chunks"]))
    return "\n".join(lines)
