"""Exporters: obs JSONL -> Chrome trace-event JSON (Perfetto) or a
terminal summary.

The Chrome trace-event format (the ``traceEvents`` array Perfetto and
``chrome://tracing`` both load) is the lingua franca of the JAX stack's
profiling UIs — ``jax.profiler`` device traces land in the same viewer —
so exporting the host-side obs stream there puts pipeline stages, chunk
spans, degradations and fault firings on the SAME timeline a device
trace uses (open both in one Perfetto session via "Open trace file").

Mapping (validated by ``tests/unit/test_obs.py`` and the tier-0 schema
stage):

- ``span``       -> ``ph: "X"`` complete events (``ts`` = span start in
  µs since run start, ``dur`` = µs), one track per recording thread;
- ``degrade`` / ``fault`` / ``retry`` / ``journal`` / ``resolve`` /
  ``stage`` -> ``ph: "i"`` instant events (thread scope);
- ``heartbeat``  -> ``ph: "C"`` counter tracks (records, chunks, vps);
- manifest/tool  -> ``ph: "M"`` process/thread name metadata.

Every emitted event carries ``pid``/``tid``/``ph``/``ts``; the list is
sorted by ``ts`` so consumers that stream it see a monotonically
consistent timeline.
"""

from __future__ import annotations

import glob
import json
import re

from variantcalling_tpu.obs.schema import SCHEMA_VERSION

#: event kinds rendered as instant markers on their thread's track
_INSTANT_KINDS = ("degrade", "fault", "retry", "journal", "resolve", "stage")

#: envelope fields not repeated into a trace event's args
_ENVELOPE = ("v", "seq", "ts", "t", "kind", "name", "pid", "tid")


class ObsLogError(ValueError):
    """The file is not a readable obs run log."""


def read_events(path: str, continuation: bool = False) -> list[dict]:
    """Parse one obs JSONL file; raises :class:`ObsLogError` on garbage
    (missing file surfaces as OSError for the CLI to map to exit 2).

    A malformed or non-event FINAL line is DROPPED, not raised: an
    in-flight or crashed run's last line is routinely a partial write,
    and every reader (summary/bottleneck/diff/export/tail) must tolerate
    it — mid-file garbage still raises. ``continuation=True`` marks a
    rotation segment (``.segN``): an empty file is then legal (a run
    killed right after rotating) and returns ``[]``.
    """
    events: list[dict] = []
    # streaming parse with ONE line of lookahead: a bad line is held as
    # pending and only raised when a LATER non-empty line proves it was
    # mid-file garbage — at EOF the held line is the torn tail and drops
    pending_error: str | None = None
    with open(path, encoding="utf-8") as fh:
        for i, line in enumerate(fh, 1):
            line = line.strip()
            if not line:
                continue
            if pending_error is not None:
                raise ObsLogError(pending_error)
            try:
                event = json.loads(line)
            except ValueError as e:
                pending_error = f"{path}:{i}: not JSON: {e}"
                continue
            if not isinstance(event, dict) or "kind" not in event:
                pending_error = f"{path}:{i}: not an obs event"
                continue
            events.append(event)
    if not events:
        if continuation:
            return []
        raise ObsLogError(f"{path}: empty obs log")
    version = events[0].get("v")
    if version != SCHEMA_VERSION:
        raise ObsLogError(f"{path}: schema version {version!r} != "
                          f"{SCHEMA_VERSION} (regenerate or upgrade)")
    return events


def _numbered_siblings(path: str, suffix: str) -> list[tuple[int, str]]:
    """``(N, <path>.<suffix>N)`` sibling files in N order (rotation
    segments and rank logs share the discovery shape)."""
    out: list[tuple[int, str]] = []
    for p in glob.glob(glob.escape(path) + f".{suffix}*"):
        m = re.match(rf".*\.{suffix}(\d+)$", p)
        if m:
            out.append((int(m.group(1)), p))
    return sorted(out)


def read_log(path: str) -> list[dict]:
    """One recording process's full stream: the base file plus any
    ``.segN`` rotation segments (``VCTPU_OBS_MAX_MB``), concatenated in
    rotation order — ``seq`` keeps counting across segments, so the
    result is the same ordered stream an uncapped run would have
    written."""
    events = read_events(path)
    for _, seg in _numbered_siblings(path, "seg"):
        events.extend(read_events(seg, continuation=True))
    return events


def read_run(path: str) -> list[dict]:
    """Read one RUN: the given log (merged across its rotation segments)
    plus any ``.rankN`` sibling logs a multi-host run wrote next to it,
    merged into one timeline.

    Rank 0's path is the base path; every rank N > 0 wrote
    ``<path>.rankN`` (obs._rank_suffixed), each with its own optional
    ``.segN`` rotation segments. With rank siblings present every event
    gains a ``rank`` field and its Perfetto ``pid`` becomes the rank, so
    the exported trace shows one process track per rank; a single-rank
    run returns exactly :func:`read_log` (no ``rank`` field, OS pid
    preserved).
    """
    siblings = _numbered_siblings(path, "rank")
    events = read_log(path)
    if siblings:
        merged: list[dict] = []
        for rank, rank_path in [(0, path)] + sorted(siblings):
            rank_events = events if rank == 0 else read_log(rank_path)
            for e in rank_events:
                e = dict(e, rank=rank)
                e["pid"] = rank  # rank as Perfetto pid: one track per rank
                merged.append(e)
        merged.sort(key=lambda e: (e.get("ts", 0), e.get("rank", 0),
                                   e.get("seq", 0)))
        return merged
    # the serving fabric's spelling of the same shape: the router's log
    # is the base path, backend H wrote <path>.backendH next to it
    # (tools/podrun --fabric) — merge the tiers into one timeline, the
    # backend id as the Perfetto pid (0 = the router's track)
    backends = _numbered_siblings(path, "backend")
    if not backends:
        return events
    merged = []
    for n, b_path in [(0, path)] + sorted(backends):
        for e in (events if n == 0 else read_log(b_path)):
            e = dict(e, backend=n)
            e["pid"] = n
            merged.append(e)
    merged.sort(key=lambda e: (e.get("ts", 0), e.get("backend", 0),
                               e.get("seq", 0)))
    return merged


def _args_of(event: dict) -> dict:
    return {k: v for k, v in event.items() if k not in _ENVELOPE}


def _last_t(events: list[dict]) -> float:
    """Run-relative offset of the last event — the wall-clock stand-in
    for an in-flight log whose ``run_end`` has not landed yet."""
    return max((float(e.get("t", 0.0)) for e in events
                if isinstance(e.get("t"), (int, float))), default=0.0)


def to_chrome_trace(events: list[dict]) -> dict:
    """The ``{"traceEvents": [...]}`` object Perfetto loads."""
    trace: list[dict] = []
    manifest = next((e for e in events if e.get("kind") == "manifest"), None)
    pids = {e.get("pid", 0) for e in events}
    tool = (manifest or {}).get("tool", "vctpu")
    threads: dict[tuple, str] = {}
    for e in events:
        key = (e.get("pid", 0), e.get("tid", 0))
        name = e.get("thread") if e.get("kind") == "span" else None
        if key not in threads or (name and threads[key] == "thread"):
            threads[key] = name or "thread"
    ranked = any("rank" in e for e in events)
    fabric = not ranked and any("backend" in e for e in events)
    for pid in sorted(pids):
        # rank-merged timelines use the rank AS the pid (read_run), so
        # the process track is labeled by rank; fabric-merged timelines
        # use the backend id (0 = the router tier)
        if fabric:
            name = f"{tool} (router)" if pid == 0 \
                else f"{tool} (backend {pid})"
        else:
            name = f"{tool} (rank {pid})" if ranked else tool
        trace.append({"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
                      "ts": 0, "args": {"name": name}})
    for (pid, tid), name in sorted(threads.items()):
        trace.append({"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                      "ts": 0, "args": {"name": name}})

    # causal trace spans: ph X slices like ordinary spans, PLUS flow
    # arrows (ph s/f pairs) along every parent link — Perfetto then draws
    # the chunk DAG (megabatch fan-in included) across thread tracks
    # index keyed by (pid, span_id): on a rank-merged timeline the pid
    # IS the rank and every rank allocated its own s<N> sequence, so a
    # bare-id index would draw flow arrows across unrelated ranks' spans
    span_index: dict[tuple, dict] = {}
    for e in events:
        if e.get("kind") == "trace" and isinstance(e.get("span_id"), str):
            span_index[(e.get("pid", 0), e["span_id"])] = e
    flow_id = 0

    for e in events:
        kind = e.get("kind")
        pid, tid = e.get("pid", 0), e.get("tid", 0)
        t_us = float(e.get("t", 0.0)) * 1e6
        if kind == "span":
            dur_us = float(e.get("dur", 0.0)) * 1e6
            trace.append({"name": e.get("name", "span"), "ph": "X", "cat": "span",
                          "ts": max(0.0, t_us - dur_us), "dur": dur_us,
                          "pid": pid, "tid": tid, "args": _args_of(e)})
        elif kind == "trace":
            dur_us = float(e.get("dur", 0.0)) * 1e6
            start_us = max(0.0, t_us - dur_us)
            trace.append({"name": e.get("name", "trace"), "ph": "X",
                          "cat": "trace", "ts": start_us, "dur": dur_us,
                          "pid": pid, "tid": tid, "args": _args_of(e)})
            for parent_id in e.get("parents", ()):
                parent = span_index.get((pid, parent_id))
                if parent is None:
                    continue
                flow_id += 1
                p_end = float(parent.get("t", 0.0)) * 1e6
                p_dur = float(parent.get("dur", 0.0)) * 1e6
                flow = {"name": "chunk", "cat": "trace.flow", "id": flow_id}
                # the s/f pair binds to the slice CONTAINING its ts: put
                # the start just inside the parent slice's end and the
                # finish at the child slice's start
                trace.append(dict(flow, ph="s",
                                  ts=max(p_end - p_dur, p_end - 1.0),
                                  pid=parent.get("pid", 0),
                                  tid=parent.get("tid", 0)))
                trace.append(dict(flow, ph="f", bp="e",
                                  ts=min(start_us + 1.0, t_us),
                                  pid=pid, tid=tid))
        elif kind in _INSTANT_KINDS:
            trace.append({"name": f"{kind}:{e.get('name', '')}", "ph": "i",
                          "cat": kind, "s": "t", "ts": t_us,
                          "pid": pid, "tid": tid, "args": _args_of(e)})
        elif kind == "heartbeat":
            for track in ("records", "chunks", "vps"):
                if track in e:
                    trace.append({"name": track, "ph": "C", "ts": t_us,
                                  "pid": pid, "tid": tid,
                                  "args": {track: e[track]}})
    trace.sort(key=lambda ev: (ev["ts"], 0 if ev["ph"] == "M" else 1))
    return {
        "traceEvents": trace,
        "displayTimeUnit": "ms",
        "otherData": {"tool": tool, "schema_version": SCHEMA_VERSION,
                      "source": "variantcalling_tpu obs"},
    }


def summarize(events: list[dict]) -> dict:
    """Terminal roll-up: per-stage time, throughput, degradations,
    slowest chunks, final metrics."""
    manifest = next((e for e in events if e.get("kind") == "manifest"), None)
    run_end = next((e for e in reversed(events)
                    if e.get("kind") == "run_end"), None)
    metrics = next((e for e in reversed(events)
                    if e.get("kind") == "metrics"), None)

    stages: dict[str, dict] = {}
    chunk_spans: list[dict] = []
    for e in events:
        if e.get("kind") != "span":
            continue
        name = e.get("name", "span")
        dur = float(e.get("dur", 0.0))
        s = stages.setdefault(name, {"count": 0, "total_s": 0.0, "max_s": 0.0})
        s["count"] += 1
        s["total_s"] += dur
        s["max_s"] = max(s["max_s"], dur)
        if "chunk" in e:
            chunk_spans.append(e)
    for s in stages.values():
        s["total_s"] = round(s["total_s"], 6)
        s["mean_s"] = round(s["total_s"] / s["count"], 6)
        s["max_s"] = round(s["max_s"], 6)

    degradations: dict[str, int] = {}
    faults: dict[str, int] = {}
    recoveries: dict[str, int] = {}
    membership: dict[str, int] = {}
    for e in events:
        if e.get("kind") == "degrade":
            degradations[e.get("name", "?")] = \
                degradations.get(e.get("name", "?"), 0) + 1
        elif e.get("kind") == "fault":
            faults[e.get("name", "?")] = faults.get(e.get("name", "?"), 0) + 1
        elif e.get("kind") == "recovery":
            # recovery-ladder actions (chunk_retry / watchdog_retry /
            # megabatch_shrink / megabatch_split / quarantine /
            # dp_degrade) — docs/robustness.md
            recoveries[e.get("name", "?")] = \
                recoveries.get(e.get("name", "?"), 0) + 1
        elif e.get("kind") == "membership":
            # elastic pod transitions (join / leave / steal / recut /
            # reassign / shed / claim_lost / join_refused) — rolled up
            # by ACTION, the span label stays in the raw stream
            # (docs/scaleout.md "Elastic membership")
            membership[e.get("action", "?")] = \
                membership.get(e.get("action", "?"), 0) + 1

    # chunk-cache roll-up (docs/caching.md): the final metrics snapshot
    # carries the cache.hit / cache.miss / cache.bytes_saved counters the
    # filter pipeline maintains; a stream with no cache traffic (cache
    # off, or predating the cache) rolls up to None, not zeros
    cache = None
    m_counters = (_args_of(metrics).get("counters") or {}) if metrics else {}
    c_hits = int(m_counters.get("cache.hit", 0))
    c_misses = int(m_counters.get("cache.miss", 0))
    if c_hits or c_misses:
        cache = {"hits": c_hits, "misses": c_misses,
                 "bytes_saved": int(m_counters.get("cache.bytes_saved", 0)),
                 "hit_rate": round(c_hits / (c_hits + c_misses), 4)}

    slowest = sorted(chunk_spans, key=lambda e: -float(e.get("dur", 0.0)))[:5]
    heartbeats = [e for e in events if e.get("kind") == "heartbeat"]
    # multi-rank merged timelines (read_run): each rank reported its own
    # progress — total records is the SUM of every rank's last heartbeat
    # (fabric-merged timelines spell the reporter "backend")
    last_hb_by_rank: dict = {}
    for e in heartbeats:
        last_hb_by_rank[(e.get("rank", 0), e.get("backend", 0))] = e
    records = sum(e.get("records", 0) for e in last_hb_by_rank.values()) \
        if last_hb_by_rank else None
    ranks = sorted({e.get("rank", 0) for e in events})
    # no run_end == the run is still writing (or died by SIGKILL):
    # report honestly as in-flight with the last event's offset standing
    # in for the duration — a reader must never stack-trace on it
    dur = float(run_end.get("dur", 0.0)) if run_end else _last_t(events)

    return {
        "run": {
            "tool": (manifest or {}).get("tool"),
            "version": (manifest or {}).get("version"),
            "status": run_end.get("status") if run_end else "in-flight",
            "in_flight": run_end is None,
            "duration_s": round(dur, 3) if dur is not None else None,
            "events": len(events),
            "ranks": len(ranks),
        },
        "stages": dict(sorted(stages.items())),
        "throughput": {
            "records": records,
            "records_per_s": round(records / dur) if records and dur else None,
        },
        "degradations": degradations,
        "faults": faults,
        "recoveries": recoveries,
        "membership": membership,
        "cache": cache,
        "slowest_chunks": [{"name": e.get("name"), "chunk": e.get("chunk"),
                            "dur_s": round(float(e.get("dur", 0.0)), 6)}
                           for e in slowest],
        "metrics": _args_of(metrics) if metrics else {},
    }


# ---------------------------------------------------------------------------
# bottleneck attribution (obs v2): who is the limiting stage?
# ---------------------------------------------------------------------------


def bottleneck(events: list[dict]) -> dict:
    """Roll the ``profile`` events up into a per-stage wall-clock
    attribution and NAME the limiting stage.

    Source of truth is the ``profile``/``stage`` + ``profile``/``pipeline``
    events the streaming executor emits (work vs queue-wait vs
    backpressure-wait per stage); a log without them (a serial run
    predating profiling, or ``VCTPU_OBS_PROFILE=0``) falls back to
    depth-0 trace spans — work attribution only, waits unknown. Every
    stage's ``work/wait_in/wait_out/other`` percentages sum to ~100% of
    the pipeline wall clock (``other`` = the stage thread's untracked
    time: startup, teardown, span bookkeeping). The limiting stage is
    the one with the largest work share — in a pipelined executor its
    work IS the wall clock floor, so it is the stage ROADMAP item 1 must
    shrink.
    """
    stage_events = [e for e in events
                    if e.get("kind") == "profile" and e.get("name") == "stage"]
    pipe_events = [e for e in events
                   if e.get("kind") == "profile" and e.get("name") == "pipeline"]
    run_end = next((e for e in reversed(events)
                    if e.get("kind") == "run_end"), None)

    stages: dict[str, dict] = {}
    if stage_events:
        source = "profile"
        wall = sum(float(e.get("wall_s", 0.0)) for e in pipe_events) or \
            (float(run_end.get("dur", 0.0)) if run_end else _last_t(events))
        records = sum(int(e.get("records", 0)) for e in pipe_events)
        # parallel host-IO pools profile one stage PER WORKER
        # (parse.w0, inflate.w1, ...) and the mesh-sharded scoring path
        # one PER DEVICE (score.d0, score.d1, ...;
        # docs/streaming_executor.md): merge each family into one row
        # and remember its lane count — the percentage denominator
        # becomes lanes × wall, so a stage's work/wait/other fractions
        # still sum to ~100% of ITS capacity and the table keeps reading
        # as fractions of wall-clock. Device families additionally carry
        # ``devices`` (a device lane is hardware, not a host thread).
        worker_re = re.compile(r"^(.+)\.([wd])(\d+)$")
        for e in stage_events:  # several pipelines in one stream: sum
            name = e.get("stage", "?")
            m = worker_re.match(name)
            base = m.group(1) if m else name
            s = stages.setdefault(base, {
                "work_s": 0.0, "wait_in_s": 0.0, "wait_out_s": 0.0,
                "items": 0, "bytes_in": 0, "bytes_out": 0,
                "stage_records": 0, "_workers": set()})
            if m:
                s["_workers"].add(m.group(2) + m.group(3))
                if m.group(2) == "d":
                    s["_device_family"] = True
            s["work_s"] += float(e.get("work_s", 0.0))
            s["wait_in_s"] += float(e.get("wait_in_s", 0.0))
            s["wait_out_s"] += float(e.get("wait_out_s", 0.0))
            s["items"] += int(e.get("items", 0))
            s["bytes_in"] += int(e.get("bytes_in", 0))
            s["bytes_out"] += int(e.get("bytes_out", 0))
            s["stage_records"] += int(e.get("records", 0)) if m else 0
        for s in stages.values():
            s["workers"] = max(1, len(s.pop("_workers")))
            if s.pop("_device_family", False):
                s["devices"] = s["workers"]  # device lanes, not host threads
    else:
        # fallback: depth-0 spans (serial runs, profiling off) — honest
        # about what it is: work only, waits unattributable. An in-flight
        # log (no run_end) uses the last event's offset as the wall.
        source = "spans"
        records = 0
        wall = float(run_end.get("dur", 0.0)) if run_end else _last_t(events)
        for e in events:
            if e.get("kind") != "span" or e.get("depth", 0) != 0:
                continue
            s = stages.setdefault(e.get("name", "span"), {
                "work_s": 0.0, "wait_in_s": 0.0, "wait_out_s": 0.0,
                "items": 0, "bytes_in": 0, "bytes_out": 0})
            s["work_s"] += float(e.get("dur", 0.0))
            s["items"] += 1

    for s in stages.values():
        k = s.get("workers", 1)
        capacity = wall * k  # a k-worker family can spend k×wall working
        tracked = s["work_s"] + s["wait_in_s"] + s["wait_out_s"]
        s["other_s"] = max(0.0, capacity - tracked) if source == "profile" \
            else 0.0
        for key in ("work", "wait_in", "wait_out", "other"):
            s[f"{key}_pct"] = round(100.0 * s[f"{key}_s"] / capacity, 1) \
                if capacity > 0 else 0.0
            s[f"{key}_s"] = round(s[f"{key}_s"], 6)
        n_rec = s.pop("stage_records", 0) or records
        if n_rec and s["work_s"] > 0:
            # standalone throughput: what the stage (all its workers
            # together) sustains while busy
            s["vps"] = round(n_rec / (s["work_s"] / k))

    # the limiting stage is the largest per-capacity work share: a
    # k-worker family's wall-clock floor is work_s / k, so families rank
    # by normalized work (== work_pct ranking)
    def _norm_work(s: dict) -> float:
        return s["work_s"] / s.get("workers", 1)

    limiting = max(stages, key=lambda n: _norm_work(stages[n])) \
        if stages else None
    out = {
        "source": source,
        "wall_s": round(wall, 6),
        "records": records or None,
        "e2e_vps": round(records / wall) if records and wall > 0 else None,
        "limiting_stage": limiting,
        "limiting_work_pct": stages[limiting]["work_pct"] if limiting else None,
        "stages": dict(sorted(stages.items(),
                              key=lambda kv: -_norm_work(kv[1]))),
    }
    cost = [e for e in events if e.get("kind") == "profile"
            and e.get("name") == "cost_analysis"]
    if cost:
        out["cost_analysis"] = _args_of(cost[-1])
    res = [e for e in events if e.get("kind") == "profile"
           and e.get("name") == "resources"]
    if res:
        out["resources"] = _args_of(res[-1])
    return out


def render_bottleneck(b: dict) -> str:
    """Human-readable attribution table (``vctpu obs bottleneck``)."""
    lines = []
    if b["limiting_stage"] is not None:
        lines.append(f"limiting stage: {b['limiting_stage']} "
                     f"({b['limiting_work_pct']:.1f}% of {b['wall_s']:.3f}s "
                     f"wall working)")
    else:
        lines.append("no stage attribution in this log")
    if b.get("e2e_vps"):
        lines.append(f"throughput: {b['records']} records, "
                     f"{b['e2e_vps']}/s end to end")
    if b["stages"]:
        def label(n: str, s: dict) -> str:
            k = s.get("workers", 1)
            return f"{n} x{k}" if k > 1 else n  # merged IO-pool family

        labels = {n: label(n, s) for n, s in b["stages"].items()}
        width = max(len(v) for v in labels.values())
        lines.append(f"  {'stage':<{width}}  {'work%':>6} {'wait-in%':>8} "
                     f"{'wait-out%':>9} {'other%':>6} {'work_s':>9} "
                     f"{'v/s-alone':>10}  bytes")
        for name, s in b["stages"].items():
            byt = []
            if s.get("bytes_in"):
                byt.append(f"{s['bytes_in'] / (1 << 20):.1f}MB in")
            if s.get("bytes_out"):
                byt.append(f"{s['bytes_out'] / (1 << 20):.1f}MB out")
            lines.append(
                f"  {labels[name]:<{width}}  {s['work_pct']:>6.1f} "
                f"{s['wait_in_pct']:>8.1f} {s['wait_out_pct']:>9.1f} "
                f"{s['other_pct']:>6.1f} {s['work_s']:>9.3f} "
                f"{s.get('vps', '-'):>10}  {' '.join(byt)}")
    if b["source"] == "spans":
        lines.append("(span fallback: work attribution only — rerun with "
                     "VCTPU_OBS=1 + profiling for wait attribution)")
    ca = b.get("cost_analysis")
    if ca and ca.get("flops_per_variant"):
        lines.append(f"scoring program ({ca.get('strategy')}): "
                     f"{ca['flops_per_variant']:.0f} FLOP/variant measured by "
                     f"XLA cost_analysis; v5e roofline "
                     f"{ca.get('roofline_vps_v5e', 0)} v/s")
    res = b.get("resources")
    if res:
        lines.append(f"watermarks: rss {res.get('rss_peak_mb')} MB peak, "
                     f"host cpu {res.get('cpu_peak_pct')}% peak "
                     f"({res.get('samples')} samples)")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# run diff (regression sentry): A vs baseline B with explicit noise bands
# ---------------------------------------------------------------------------

#: default per-metric tolerance (fraction) for `vctpu obs diff`
DIFF_TOLERANCE = 0.08


def diff_runs(candidate: list[dict], baseline: list[dict],
              tolerance: float = DIFF_TOLERANCE) -> dict:
    """Compare a candidate run against a baseline run with an explicit
    noise band; the sentry half of `vctpu obs diff A B`.

    Regressions (beyond ``tolerance``, a fraction): wall clock up,
    end-to-end throughput down, or any shared stage's work seconds up.
    Improvements are reported, never fatal. Returns the report dict;
    ``report["regressed"]`` drives the CLI exit code.
    """
    cand, base = bottleneck(candidate), bottleneck(baseline)
    checks: list[dict] = []

    def check(metric: str, new, old, higher_is_better: bool) -> None:
        if not new or not old:
            return
        ratio = new / old
        if higher_is_better:
            regressed = ratio < 1 - tolerance
        else:
            regressed = ratio > 1 + tolerance
        checks.append({"metric": metric, "candidate": new, "baseline": old,
                       "delta_pct": round(100.0 * (ratio - 1), 2),
                       "tolerance_pct": round(100.0 * tolerance, 2),
                       "regressed": regressed})

    check("wall_s", cand["wall_s"], base["wall_s"], higher_is_better=False)
    check("e2e_vps", cand.get("e2e_vps"), base.get("e2e_vps"),
          higher_is_better=True)
    for name in sorted(set(cand["stages"]) & set(base["stages"])):
        check(f"stage.{name}.work_s", cand["stages"][name]["work_s"],
              base["stages"][name]["work_s"], higher_is_better=False)
    return {
        "tolerance_pct": round(100.0 * tolerance, 2),
        "limiting_stage": {"candidate": cand["limiting_stage"],
                           "baseline": base["limiting_stage"]},
        "checks": checks,
        "regressed": any(c["regressed"] for c in checks),
    }


def render_diff(report: dict) -> str:
    lines = [f"obs diff (noise band ±{report['tolerance_pct']}%):"]
    for c in report["checks"]:
        mark = "REGRESSED" if c["regressed"] else "ok"
        lines.append(f"  {c['metric']:<28} {c['baseline']:>12} -> "
                     f"{c['candidate']:>12}  {c['delta_pct']:+7.2f}%  {mark}")
    ls = report["limiting_stage"]
    if ls["candidate"] != ls["baseline"]:
        lines.append(f"  limiting stage moved: {ls['baseline']} -> "
                     f"{ls['candidate']}")
    lines.append("result: " + ("REGRESSION beyond the noise band"
                               if report["regressed"] else
                               "within the noise band"))
    return "\n".join(lines)


def render_summary(summary: dict) -> str:
    """Human-readable roll-up (``vctpu obs summary`` without ``--json``)."""
    run = summary["run"]
    lines = [f"run: {run.get('tool')} v{run.get('version')} — "
             f"{run.get('status')} in {run.get('duration_s')}s "
             f"({run.get('events')} events)"]
    if summary["stages"]:
        lines.append("stages (total / mean / max seconds):")
        width = max(len(n) for n in summary["stages"])
        for name, s in summary["stages"].items():
            lines.append(f"  {name:<{width}}  x{s['count']:<5} "
                         f"{s['total_s']:>9.3f} {s['mean_s']:>9.4f} "
                         f"{s['max_s']:>9.4f}")
    tp = summary["throughput"]
    if tp.get("records"):
        lines.append(f"throughput: {tp['records']} records"
                     + (f" ({tp['records_per_s']}/s)"
                        if tp.get("records_per_s") else ""))
    if summary.get("cache"):
        c = summary["cache"]
        lines.append(f"chunk cache: {c['hits']} hit / {c['misses']} miss "
                     f"({c['hit_rate']:.0%} hit rate), "
                     f"{c['bytes_saved']} rendered bytes replayed")
    if summary["degradations"]:
        lines.append("degradations: " + ", ".join(
            f"{k} x{v}" for k, v in sorted(summary["degradations"].items())))
    if summary["faults"]:
        lines.append("injected faults: " + ", ".join(
            f"{k} x{v}" for k, v in sorted(summary["faults"].items())))
    if summary.get("recoveries"):
        lines.append("recovery actions: " + ", ".join(
            f"{k} x{v}" for k, v in sorted(summary["recoveries"].items())))
    if summary.get("membership"):
        lines.append("membership transitions: " + ", ".join(
            f"{k} x{v}" for k, v in sorted(summary["membership"].items())))
    if summary["slowest_chunks"]:
        lines.append("slowest chunks: " + ", ".join(
            f"{c['name']}#{c['chunk']} {c['dur_s']:.3f}s"
            for c in summary["slowest_chunks"]))
    return "\n".join(lines)
