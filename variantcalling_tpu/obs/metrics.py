"""Typed metrics registry: counters, gauges, and small histograms.

Recording happens on the streaming executor's worker threads while the
hot path is scoring millions of variants, so the design rule is the same
as :mod:`variantcalling_tpu.utils.faults`: **near-zero cost, no shared
lock on the record path**.

- :class:`Counter` keeps one cell per recording thread (dict item
  assignment is atomic under the GIL) and sums the cells at snapshot
  time — increments are lock-free and never lost to a read-modify-write
  race between threads.
- :class:`Gauge` is a single atomic assignment, with a monotonic
  ``peak`` kept per thread the same way counters are.
- :class:`Histogram` tracks count/sum/min/max, a bounded ring of recent
  samples (the "time series" view: enough to see per-chunk variants/sec
  drift without unbounded memory), and a FIXED-BUCKET log-spaced count
  array (HDR-histogram style): every observation lands in one of
  :data:`N_BUCKETS` geometric buckets spanning 1 µs .. ~10⁹, so the
  snapshot can report p50/p95/p99 with bounded relative error
  (≤ ~4.4%, half a bucket) and bounded memory regardless of sample
  count — the substrate for per-stage latency SLOs (``vctpu serve``).
  Observations are per-thread merged at snapshot, like counters.

A registry belongs to one obs run; ``snapshot()`` is called once at run
end (and by ``vctpu obs summary`` via the emitted ``metrics`` event), so
snapshot-side merging can afford to walk the per-thread cells.
"""

from __future__ import annotations

import math
import threading
import time

#: recent-sample ring size per histogram per thread (the merged snapshot
#: interleaves threads; 64 per thread bounds memory at any fan-out)
RECENT = 64

#: fixed log-spaced bucket geometry: bucket i's inclusive upper bound is
#: ``HIST_MIN * HIST_FACTOR**i``. FACTOR = 2**0.125 bounds the quantile
#: estimate's relative error at sqrt(FACTOR)-1 ≈ 4.4% (geometric-midpoint
#: reporting, half a bucket) at 400 int cells per recording thread —
#: HDR-histogram resolution without per-sample storage (range 1µs..~10⁹).
HIST_MIN = 1e-6
HIST_FACTOR = 2.0 ** 0.125
N_BUCKETS = 400
_LOG_FACTOR = math.log(HIST_FACTOR)

#: percentiles published in every histogram snapshot (serve-SLO substrate)
SNAPSHOT_QUANTILES = (0.5, 0.95, 0.99)

#: rolling-window geometry: the window is cut into ROLL_SLOTS bucket
#: rings rotated on the monotonic clock; the rolling quantile merges the
#: last ROLL_SLOTS+1 slots (current partial slot included), so "rolling
#: p95" covers between 1x and 1.25x of the configured window — recent by
#: construction, never all-of-run like the cumulative buckets next to it
ROLL_SLOTS = 4

#: default rolling window span in seconds (VCTPU_OBS_WINDOW_S overrides
#: per run via the MetricsRegistry constructor)
DEFAULT_WINDOW_S = 60.0


def bucket_index(v: float) -> int:
    """The fixed bucket a value lands in (0 = underflow, N-1 = overflow)."""
    if v <= HIST_MIN:
        return 0
    idx = int(math.log(v / HIST_MIN) / _LOG_FACTOR) + 1
    return idx if idx < N_BUCKETS else N_BUCKETS - 1


def bucket_bound(i: int) -> float:
    """Bucket ``i``'s inclusive upper bound."""
    return HIST_MIN * HIST_FACTOR ** i


def quantile_from_buckets(buckets: list[int], count: int, q: float) -> float | None:
    """Quantile estimate from a merged bucket-count array: find the
    bucket holding the q-th ranked sample and report its geometric
    midpoint (half-bucket worst-case error)."""
    if count <= 0:
        return None
    rank = q * count
    cum = 0
    for i, c in enumerate(buckets):
        cum += c
        if cum >= rank:
            hi = bucket_bound(i)
            if i == 0:
                return hi
            return math.sqrt(bucket_bound(i - 1) * hi)
    return bucket_bound(N_BUCKETS - 1)


class Counter:
    """Monotonic counter; ``add`` is lock-free (per-thread cells)."""

    __slots__ = ("name", "_cells")

    def __init__(self, name: str):
        self.name = name
        self._cells: dict[int, float] = {}

    def add(self, n: float = 1) -> None:
        tid = threading.get_ident()
        cells = self._cells
        cells[tid] = cells.get(tid, 0) + n

    @property
    def value(self) -> float:
        return sum(self._cells.values())

    def snapshot(self):
        v = self.value
        return int(v) if float(v).is_integer() else v


class Gauge:
    """Last-write-wins value plus the per-run peak."""

    __slots__ = ("name", "value", "_peaks")

    def __init__(self, name: str):
        self.name = name
        self.value: float = 0
        self._peaks: dict[int, float] = {}

    def set(self, v: float) -> None:
        self.value = v
        tid = threading.get_ident()
        peaks = self._peaks
        prev = peaks.get(tid)
        if prev is None or v > prev:
            peaks[tid] = v

    @property
    def peak(self) -> float:
        return max(self._peaks.values(), default=0)

    def snapshot(self) -> dict:
        def num(v):
            return int(v) if float(v).is_integer() else v

        return {"value": num(self.value), "peak": num(self.peak)}


class _HistCell:
    __slots__ = ("count", "total", "vmin", "vmax", "recent", "buckets",
                 "windows")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.vmin: float | None = None
        self.vmax: float | None = None
        self.recent: list[float] = []
        self.buckets = [0] * N_BUCKETS
        #: rolling bucket rings: {slot ordinal: bucket counts}, bounded
        #: to the last ROLL_SLOTS+2 slots (the windowed sibling of the
        #: cumulative ``buckets`` array next to it)
        self.windows: dict[int, list[int]] = {}


class Histogram:
    """count/sum/min/max + fixed log buckets (p50/p95/p99) + a bounded
    recent-sample ring, per thread — PLUS a rolling-window bucket ring
    (``window_s``) so quantiles can mean "recent", not all-of-run
    (the live-plane/SLO substrate: ``vctpu obs tail``/``prom``)."""

    __slots__ = ("name", "window_s", "_slot_s", "_cells")

    def __init__(self, name: str, window_s: float = DEFAULT_WINDOW_S):
        self.name = name
        self.window_s = window_s
        self._slot_s = max(window_s, 1e-3) / ROLL_SLOTS
        self._cells: dict[int, _HistCell] = {}

    def _slot(self) -> int:
        # the monotonic clock never steps; one clock read per observation
        return int(time.monotonic() / self._slot_s)

    def observe(self, v: float) -> None:
        tid = threading.get_ident()
        cell = self._cells.get(tid)
        if cell is None:
            # dict item assignment is atomic; each thread only writes its
            # own key, so concurrent first-observations cannot clobber
            self._cells[tid] = cell = _HistCell()
        cell.count += 1
        cell.total += v
        if cell.vmin is None or v < cell.vmin:
            cell.vmin = v
        if cell.vmax is None or v > cell.vmax:
            cell.vmax = v
        idx = bucket_index(v)
        cell.buckets[idx] += 1
        slot = self._slot()
        ring = cell.windows.get(slot)
        if ring is None:
            cell.windows[slot] = ring = [0] * N_BUCKETS
            if len(cell.windows) > ROLL_SLOTS + 2:
                # prune rings that aged out of every possible window —
                # only this thread writes this cell, so the delete races
                # nothing (the snapshot reader tolerates either state)
                for old in sorted(cell.windows)[:-(ROLL_SLOTS + 2)]:
                    del cell.windows[old]
        ring[idx] += 1
        cell.recent.append(v)
        if len(cell.recent) > RECENT:
            del cell.recent[0]

    def rolling_buckets(self) -> tuple[list[int], int]:
        """(summed bucket counts, count) over the rolling window: the
        last ROLL_SLOTS complete slots plus the current partial one."""
        floor = self._slot() - ROLL_SLOTS
        merged = [0] * N_BUCKETS
        count = 0
        for c in list(self._cells.values()):
            for slot, ring in list(c.windows.items()):
                if slot < floor:
                    continue
                for i, n in enumerate(ring):
                    if n:
                        merged[i] += n
                        count += n
        return merged, count

    def rolling_quantile(self, q: float) -> float | None:
        """Windowed quantile — "recent" p50/p95/p99 next to the
        cumulative :meth:`quantile`."""
        merged, count = self.rolling_buckets()
        return quantile_from_buckets(merged, count, q)

    def merged_buckets(self) -> tuple[list[int], int]:
        """(summed bucket counts, total count) across recording threads."""
        cells = list(self._cells.values())
        merged = [0] * N_BUCKETS
        for c in cells:
            for i, n in enumerate(c.buckets):
                if n:
                    merged[i] += n
        return merged, sum(c.count for c in cells)

    def quantile(self, q: float) -> float | None:
        """Bucket-resolution quantile (≤ ~4.4% relative error)."""
        merged, count = self.merged_buckets()
        return quantile_from_buckets(merged, count, q)

    def snapshot(self) -> dict:
        cells = list(self._cells.values())
        count = sum(c.count for c in cells)
        total = sum(c.total for c in cells)
        mins = [c.vmin for c in cells if c.vmin is not None]
        maxs = [c.vmax for c in cells if c.vmax is not None]
        recent: list[float] = []
        for c in cells:
            recent.extend(c.recent)
        merged, _ = self.merged_buckets()
        out = {
            "count": count,
            "sum": round(total, 6),
            "mean": round(total / count, 6) if count else 0,
            "min": min(mins) if mins else None,
            "max": max(maxs) if maxs else None,
            "recent": [round(v, 6) for v in recent[-RECENT:]],
        }
        for q in SNAPSHOT_QUANTILES:
            est = quantile_from_buckets(merged, count, q)
            out[f"p{int(q * 100)}"] = round(est, 9) if est is not None else None
        # the windowed view rides next to the cumulative one: rolling
        # p95 means "the last ~window_s", the substrate for in-flight
        # SLO reads (vctpu obs tail / prom) where all-of-run quantiles
        # would average away a current stall
        roll_merged, roll_count = self.rolling_buckets()
        rolling: dict = {"window_s": self.window_s, "count": roll_count}
        for q in SNAPSHOT_QUANTILES:
            est = quantile_from_buckets(roll_merged, roll_count, q)
            rolling[f"p{int(q * 100)}"] = round(est, 9) \
                if est is not None else None
        out["rolling"] = rolling
        return out


class _Noop:
    """Shared do-nothing metric for the obs-disabled fast path — callers
    can record unconditionally without branching on ``obs.active()``."""

    __slots__ = ()
    name = "noop"
    value = 0
    peak = 0

    def add(self, n: float = 1) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass


NOOP = _Noop()


class MetricsRegistry:
    """One run's named metrics. Creation takes a lock (rare); recording
    through the returned objects does not (hot). ``window_s`` sets every
    histogram's rolling-window span (``VCTPU_OBS_WINDOW_S``; the module
    stays knob-free so it imports standalone)."""

    def __init__(self, window_s: float = DEFAULT_WINDOW_S):
        self.window_s = window_s
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._hists: dict[str, Histogram] = {}

    def _get(self, table: dict, name: str, cls):
        metric = table.get(name)
        if metric is None:
            with self._lock:
                metric = table.setdefault(name, cls(name))
        return metric

    def counter(self, name: str) -> Counter:
        return self._get(self._counters, name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(self._gauges, name, Gauge)

    def histogram(self, name: str) -> Histogram:
        metric = self._hists.get(name)
        if metric is None:
            with self._lock:
                metric = self._hists.setdefault(
                    name, Histogram(name, window_s=self.window_s))
        return metric

    def snapshot(self) -> dict:
        """{counters, gauges, histograms} — the ``metrics`` event body."""
        return {
            "counters": {n: c.snapshot()
                         for n, c in sorted(self._counters.items())},
            "gauges": {n: g.snapshot()
                       for n, g in sorted(self._gauges.items())},
            "histograms": {n: h.snapshot()
                           for n, h in sorted(self._hists.items())},
        }
