"""The obs event schema: one committed contract, one validator.

Every line of an obs run log is a JSON object with the common envelope
(``v``/``seq``/``ts``/``t``/``kind``/``name``/``pid``/``tid``) plus its
kind's required fields. The contract lives in the committed
``event_schema.json`` next to this module — NOT in code — so the tier-0
schema stage (``tools/obs_schema_check.py``), the export/summary readers
and external consumers all validate against the same artifact, and a
schema change is a reviewable diff to one file.

The validator is hand-rolled over that artifact (no jsonschema
dependency — the container doesn't ship one): type names are the small
closed set ``int``/``number``/``string``/``object``/``array``/``bool``.
Unknown kinds and extra fields are allowed (forward compatibility);
missing/mistyped REQUIRED fields are errors.
"""

from __future__ import annotations

import json
import os

SCHEMA_VERSION = 1

_SCHEMA_PATH = os.path.join(os.path.dirname(__file__), "event_schema.json")
_SCHEMA: dict | None = None


def load_schema() -> dict:
    """The committed schema artifact (cached)."""
    global _SCHEMA
    if _SCHEMA is None:
        with open(_SCHEMA_PATH, encoding="utf-8") as fh:
            _SCHEMA = json.load(fh)
    return _SCHEMA


def _type_ok(value, type_name: str) -> bool:
    if type_name == "int":
        return isinstance(value, int) and not isinstance(value, bool)
    if type_name == "number":
        return isinstance(value, (int, float)) and not isinstance(value, bool)
    if type_name == "string":
        return isinstance(value, str)
    if type_name == "object":
        return isinstance(value, dict)
    if type_name == "array":
        return isinstance(value, list)
    if type_name == "bool":
        return isinstance(value, bool)
    return True  # unknown type name in the artifact: don't invent failures


def validate_event(event: dict) -> list[str]:
    """Schema errors for one event dict (empty list == valid)."""
    schema = load_schema()
    errors: list[str] = []
    if not isinstance(event, dict):
        return ["event is not a JSON object"]
    for field, type_name in schema["common"].items():
        if field not in event:
            errors.append(f"missing common field {field!r}")
        elif not _type_ok(event[field], type_name):
            errors.append(f"common field {field!r} is not a {type_name}")
    if event.get("v") != schema["schema_version"]:
        errors.append(f"schema version {event.get('v')!r} != "
                      f"{schema['schema_version']}")
    kind = event.get("kind")
    kind_spec = schema["kinds"].get(kind) if isinstance(kind, str) else None
    if kind_spec is not None:
        for field, type_name in kind_spec.get("required", {}).items():
            if field not in event:
                errors.append(f"{kind} event missing field {field!r}")
            elif not _type_ok(event[field], type_name):
                errors.append(f"{kind} field {field!r} is not a {type_name}")
    return errors


def validate_lines(lines: list[str], continuation: bool = False) -> list[str]:
    """Schema errors for a whole JSONL log, prefixed with 1-based line
    numbers; also enforces the stream-level invariants (seq strictly
    increasing from 0, ts monotonically non-decreasing, manifest first).

    ``continuation=True`` validates a ROTATION SEGMENT
    (``<log>.segN``, ``VCTPU_OBS_MAX_MB``): the manifest lives in the
    base file and ``seq`` continues from wherever the previous segment
    stopped, so those two checks anchor on the segment's first event
    instead of the stream origin."""
    errors: list[str] = []
    prev_seq: int | None = None if continuation else -1
    prev_ts = None
    for i, line in enumerate(lines, 1):
        line = line.strip()
        if not line:
            continue
        try:
            event = json.loads(line)
        except ValueError as e:
            errors.append(f"line {i}: not JSON ({e})")
            continue
        for err in validate_event(event):
            errors.append(f"line {i}: {err}")
        seq, ts = event.get("seq"), event.get("ts")
        if isinstance(seq, int):
            if prev_seq is not None and seq != prev_seq + 1:
                errors.append(f"line {i}: seq {seq} breaks the ordered "
                              f"stream (expected {prev_seq + 1})")
            prev_seq = seq
        if isinstance(ts, (int, float)) and not isinstance(ts, bool):
            if prev_ts is not None and ts < prev_ts:
                errors.append(f"line {i}: ts moved backwards "
                              f"({ts} < {prev_ts})")
            prev_ts = ts
        if i == 1 and not continuation and event.get("kind") != "manifest":
            errors.append("line 1: stream must open with the run manifest")
    return errors
