"""Performance-attribution profiler (obs v2): where wall-clock, bytes
and device FLOPs actually go.

PR 5's event stream records *what happened*; this module records *what
it cost*. The post-PR-5 diagnosis (ROADMAP) is that the system is
host-IO-bound — streaming e2e ~0.7–0.86M v/s against a 2.25M v/s hot
path — but nothing could attribute the gap. The GPU-cluster
variant-calling pipeline work (arXiv 2509.09058, PAPERS.md) gets its
speedups from per-stage utilization profiling *before* parallelizing;
this is that layer:

- :class:`StageProfiler` / :class:`StageStats` — per-stage wall-clock
  attribution for the streaming executor: **work** (inside the stage
  callable) vs **wait-in** (blocked on the upstream queue) vs
  **wait-out** (backpressured on the downstream queue), plus
  items/records/bytes in/out. The executor (``parallel/pipeline.py``)
  and the filter writeback loop feed it; :meth:`StageProfiler.emit`
  lands one schema-versioned ``profile``/``stage`` event per stage plus
  a ``profile``/``pipeline`` wall event. ``vctpu obs bottleneck`` rolls
  them up and names the limiting stage.
- :class:`ResourceSampler` — a daemon thread sampling process RSS and
  host-CPU utilization every ``VCTPU_OBS_SAMPLE_S`` seconds into run
  gauges (``proc.rss_mb`` / ``proc.cpu_pct``, peaks kept by the gauge),
  with a final ``profile``/``resources`` watermark event.
- :func:`xla_cost_analysis` / :func:`record_scoring_cost` — runtime
  MFU/roofline attribution: FLOPs from the XLA compiler's
  ``cost_analysis`` on the *compiled* scoring program (replacing
  bench.py's analytic projection with the compiler's own count),
  emitted as a ``profile``/``cost_analysis`` event per run with the
  resolved strategy.

Everything here is gated on ``enabled()`` — obs recording must be on
(``VCTPU_OBS=1``) AND profiling not opted out (``VCTPU_OBS_PROFILE``,
default on). The PR 5 contracts hold with profiling enabled: output
bytes are identical, and total obs+profile overhead stays inside the 2%
budget (bench ``obs_overhead_pct``, now median-of-5 paired runs — since
the live-telemetry plane the measured legs also carry causal tracing
and periodic rolling-window snapshots, and the sampler's gauges ride
those ``snapshot`` events mid-run, so an external ``vctpu obs
tail``/``prom`` reader sees fresh RSS/CPU watermarks while the run is
in flight, not just at ``run_end``).
"""

from __future__ import annotations

import os
import re
import threading
import time

from variantcalling_tpu import knobs, obs

PROFILE_ENV = "VCTPU_OBS_PROFILE"
SAMPLE_ENV = "VCTPU_OBS_SAMPLE_S"

#: per-worker stage rows of the parallel host-IO pools (``<name>.w<idx>``)
#: and per-device rows of the mesh-sharded scoring path
#: (``<name>.d<idx>``) — the same family spellings obs/export.py's
#: bottleneck merge matches
_WORKER_STAGE_RE = re.compile(r"\.[wd]\d+$")

_PAGE_SIZE = os.sysconf("SC_PAGE_SIZE") if hasattr(os, "sysconf") else 4096


def enabled() -> bool:
    """Profiling is on: an obs run is open and not opted out."""
    return obs.active() and knobs.get_bool(PROFILE_ENV)


class StageStats:
    """One stage's attribution accumulators.

    Each stage of the executor runs on exactly ONE thread, so plain
    float adds need no lock on the record path (the snapshot reader
    crosses threads only after the pipeline joined its workers). The
    parallel host-IO pools keep the same invariant by keying one stage
    PER WORKER (``parse.w0``, ``inflate.w1``, …): each pool worker feeds
    only its own stats object, and ``vctpu obs bottleneck`` re-merges
    the ``<name>.w<idx>`` family into one row normalized by worker count
    so the fractions still sum to ~100% of wall.
    """

    __slots__ = ("name", "work_s", "wait_in_s", "wait_out_s",
                 "items", "records", "bytes_in", "bytes_out")

    def __init__(self, name: str):
        self.name = name
        self.work_s = 0.0
        self.wait_in_s = 0.0
        self.wait_out_s = 0.0
        self.items = 0
        self.records = 0
        self.bytes_in = 0
        self.bytes_out = 0

    def add_work(self, dt: float, items: int = 1,
                 bytes_in: int = 0, bytes_out: int = 0,
                 records: int = 0) -> None:
        self.work_s += dt
        self.items += items
        self.bytes_in += bytes_in
        self.bytes_out += bytes_out
        self.records += records

    def add_wait_in(self, dt: float, items: int = 0) -> None:
        self.wait_in_s += dt
        self.items += items

    def add_wait_out(self, dt: float) -> None:
        self.wait_out_s += dt

    def snapshot(self) -> dict:
        out = {
            "stage": self.name,
            "work_s": round(self.work_s, 6),
            "wait_in_s": round(self.wait_in_s, 6),
            "wait_out_s": round(self.wait_out_s, 6),
            "items": self.items,
        }
        if self.records:
            out["records"] = self.records
            if self.work_s > 0:
                # the stage's standalone throughput: what it could sustain
                # if it never waited — the number ROADMAP item 1 must move
                out["vps"] = round(self.records / self.work_s)
        if self.bytes_in:
            out["bytes_in"] = self.bytes_in
        if self.bytes_out:
            out["bytes_out"] = self.bytes_out
        return out


class StageProfiler:
    """Per-stage attribution for one pipeline run; stages are created on
    demand and keyed by name, so the executor and its caller (which owns
    e.g. the writeback loop) can feed the same profile."""

    def __init__(self):
        self._stages: dict[str, StageStats] = {}
        self._lock = threading.Lock()

    def stage(self, name: str) -> StageStats:
        s = self._stages.get(name)
        if s is None:
            with self._lock:
                s = self._stages.setdefault(name, StageStats(name))
        return s

    def set_records(self, n: int) -> None:
        """Every stage of a linear pipeline saw all N records. Worker
        stages (``<name>.w<idx>`` — the parallel host-IO pools) keep the
        per-worker counts they accumulated themselves — INCLUDING a
        byte-only zero (e.g. ``inflate.wN``): each worker saw only its
        share, and assigning the run total to k workers would inflate the
        merged family's records (and its reported standalone v/s) k-fold
        in ``vctpu obs bottleneck``."""
        for name, s in self._stages.items():
            if not s.records and not _WORKER_STAGE_RE.search(name):
                s.records = n

    def emit(self, wall_s: float, records: int | None = None) -> None:
        """Write the attribution into the open obs stream: one
        ``profile``/``stage`` event per stage (executor order is not
        meaningful here — ``vctpu obs bottleneck`` sorts by work share)
        plus the ``profile``/``pipeline`` wall event the roll-up divides
        by."""
        if records is not None:
            self.set_records(records)
        if not obs.active():
            return
        total_in = total_out = 0
        for name in self._stages:
            snap = self._stages[name].snapshot()
            total_in += snap.get("bytes_in", 0)
            total_out += snap.get("bytes_out", 0)
            obs.event("profile", "stage", **snap)
        obs.event("profile", "pipeline", wall_s=round(wall_s, 6),
                  stages=sorted(self._stages),
                  records=records if records is not None else 0,
                  bytes_in=total_in, bytes_out=total_out)


def _rss_bytes() -> int:
    """Current RSS from /proc (Linux); 0 when unreadable (the gauge then
    just never moves — telemetry must not throw)."""
    try:
        with open("/proc/self/statm", "rb") as fh:
            return int(fh.read().split()[1]) * _PAGE_SIZE
    except (OSError, ValueError, IndexError):
        return 0


class ResourceSampler(threading.Thread):
    """Daemon thread: RSS + process-CPU utilization watermarks.

    ``proc.rss_mb`` and ``proc.cpu_pct`` gauges update every interval;
    the Gauge keeps the peak, so the metrics snapshot carries the run's
    high-water marks even though only the last sample's value survives.
    ``cpu_pct`` is process CPU time over wall time — >100 means multiple
    cores busy (the streaming executor's whole point), so the watermark
    doubles as a parallelism check against the ``scaling`` bench rows.
    ``proc.cpu_pct.<family>`` gauges break the same utilization down by
    THREAD FAMILY (io pool, pipeline stages, committer, prefetch, obs)
    from the per-task CPU clocks in ``/proc/self/task`` — the obs v3
    per-thread accounting, visible in snapshots and ``vctpu obs prom``.
    """

    def __init__(self, run, interval_s: float | None = None):
        super().__init__(name="obs-sampler", daemon=True)
        # NB: attribute names must dodge the Thread API (run/_stop are
        # Thread internals)
        self.obs_run = run
        self.interval_s = (knobs.get_float(SAMPLE_ENV)
                           if interval_s is None else interval_s)
        self._halt = threading.Event()
        self.samples = 0
        # run-start baseline: the final sample in stop() measures the
        # WHOLE run against it, so a run shorter than one interval still
        # gets a real CPU utilization (the gauge keeps the peak of both)
        self._t0 = time.perf_counter()
        self._cpu0 = time.process_time()
        # per-thread-FAMILY cpu baselines (obs v3 satellite): cumulative
        # /proc/self/task cpu seconds per family at the previous scan,
        # so a utilization gauge per family (proc.cpu_pct.<family>) can
        # ride next to the process-wide one. Scanned on its OWN slower
        # cadence (~1s): the scan enumerates threads + reads /proc per
        # thread, too heavy for the 0.05s watermark tick
        self._fam_prev: dict[str, float] = self._family_cpu()
        self._fam_t_prev = time.perf_counter()

    @staticmethod
    def _family_cpu() -> dict[str, float]:
        from variantcalling_tpu.obs import sampler as sampler_mod

        try:
            return sampler_mod.family_cpu_seconds()
        except Exception:  # noqa: BLE001  # vctpu-lint: disable=VCT002 — telemetry: no /proc on this platform just drops the per-family series
            return {}

    def sample_once(self, t_prev: float, cpu_prev: float) -> tuple[float, float]:
        t_now = time.perf_counter()
        cpu_now = time.process_time()
        rss = _rss_bytes()
        if rss:
            self.obs_run.metrics.gauge("proc.rss_mb").set(
                round(rss / (1 << 20), 2))
        dt = t_now - t_prev
        if dt > 0:
            self.obs_run.metrics.gauge("proc.cpu_pct").set(
                round(100.0 * (cpu_now - cpu_prev) / dt, 1))
        # per-thread-family utilization from the per-task CPU clocks
        # (pool workers / pipeline stages / committer / prefetch /
        # obs): the same family spellings the continuous profiler
        # attributes samples to, exported as gauges so snapshots and
        # `vctpu obs prom` carry per-family series mid-run. Own ~1s
        # cadence — see __init__.
        fam_dt = t_now - self._fam_t_prev
        # the final stop() sample forces a scan even below the ~1s
        # cadence — a sub-second run still gets its per-family
        # watermark — but never over a window shorter than 0.25s: the
        # per-task clocks tick at 10ms, and dividing one quantum by a
        # tiny window would commit a 20-40% phantom peak to the
        # peak-keeping gauge
        if fam_dt >= 0.25 and (fam_dt >= 1.0 or self._halt.is_set()):
            fam_now = self._family_cpu()
            for family, cpu_s in fam_now.items():
                prev = self._fam_prev.get(family)
                if prev is not None and cpu_s >= prev:
                    self.obs_run.metrics.gauge(
                        f"proc.cpu_pct.{family}").set(
                        round(100.0 * (cpu_s - prev) / fam_dt, 1))
            self._fam_prev = fam_now
            self._fam_t_prev = t_now
        self.samples += 1
        return t_now, cpu_now

    def run(self) -> None:  # noqa: A003 — Thread API
        t_prev, cpu_prev = time.perf_counter(), time.process_time()
        while not self._halt.wait(self.interval_s):
            t_prev, cpu_prev = self.sample_once(t_prev, cpu_prev)

    def stop(self) -> None:
        """Stop sampling, take one final sample, and emit the watermark
        event (called by ``obs.end_run`` before the metrics snapshot so
        the peaks land in it)."""
        self._halt.set()
        self.join(timeout=2.0)
        # final sample: whole-run averages against the start baseline —
        # catches a run shorter than one interval, and the gauges keep
        # the max of this and every periodic sample
        self.sample_once(self._t0, self._cpu0)
        g_rss = self.obs_run.metrics.gauge("proc.rss_mb")
        g_cpu = self.obs_run.metrics.gauge("proc.cpu_pct")
        obs.event("profile", "resources", rss_peak_mb=g_rss.peak,
                  cpu_peak_pct=g_cpu.peak, samples=self.samples,
                  interval_s=self.interval_s)


# ---------------------------------------------------------------------------
# runtime MFU / roofline attribution (XLA cost_analysis)
# ---------------------------------------------------------------------------

#: v5e peak bf16 throughput — the MFU denominator bench.py uses; kept in
#: one place so the run-time and bench-time numbers cannot disagree
TPU_PEAK_FLOPS = 197e12


def xla_cost_analysis(jitted, *args) -> dict | None:
    """FLOPs/bytes from the XLA compiler for ``jitted(*args)``.

    ``args`` may be real arrays or ``jax.ShapeDtypeStruct``\\ s — only
    shapes/dtypes matter. Returns ``{"flops": float, "bytes_accessed":
    float}`` or None when the backend/build has no cost model (recorded
    as a degradation, never raised: attribution is telemetry).
    """
    from variantcalling_tpu.utils import degrade

    try:
        compiled = jitted.lower(*args).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        out = {"flops": float(ca.get("flops", 0.0) or 0.0)}
        if ca.get("bytes accessed"):
            out["bytes_accessed"] = float(ca["bytes accessed"])
        return out
    except Exception as e:  # noqa: BLE001 — attribution is telemetry, never fatal
        degrade.record("obs.cost_analysis", e,
                       fallback="no runtime FLOP attribution for this run")
        return None


def record_scoring_cost(strategy: str, jitted, args, n_variants: int) -> None:
    """Emit the run's ``profile``/``cost_analysis`` event: measured (not
    projected) FLOPs per variant for the compiled scoring program that
    actually ran, named by the resolved forest strategy.

    Emitted ONCE per (run, strategy): the streaming executor scores per
    chunk, and a per-chunk lower+compile would wreck the <2% overhead
    budget — the first chunk's shapes stand for the run (steady-state
    chunks share one bucketed shape by design). ``args`` are one chunk's
    call arguments — shapes only are read.
    """
    if not enabled():
        return
    run = obs.current()
    if run is None or (strategy, "cost") in run.cost_recorded:
        return
    run.cost_recorded.add((strategy, "cost"))
    import jax

    shapes = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), args)
    cost = xla_cost_analysis(jitted, *shapes)
    if cost is None:
        return
    fields = dict(cost, strategy=strategy, n=int(n_variants))
    if n_variants > 0 and cost["flops"] > 0:
        fpv = cost["flops"] / n_variants
        fields["flops_per_variant"] = round(fpv, 1)
        # the v5e roofline this program could reach at 100% MXU duty —
        # docs/perf_notes.md divides measured v/s by this for run MFU
        fields["roofline_vps_v5e"] = round(TPU_PEAK_FLOPS / fpv)
    obs.event("profile", "cost_analysis", **fields)
