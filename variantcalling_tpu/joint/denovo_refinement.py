"""De novo quality refinement: DENOVO_QUAL from child-vs-parent somatic quals.

Parity target: ugvc/joint/denovo_refinement.py:14-126 — for every de novo
call (samples listed in INFO ``hiConfDeNovo``/``loConfDeNovo``), the
recalibrated quality is the minimum of the variant's QUAL in the
child-vs-mother and child-vs-father somatic VCFs (absent → 0); a record's
``DENOVO_QUAL`` is the minimum over its de novo samples. Implemented as
hash joins over columnar tables instead of exploded pandas frames.
"""

from __future__ import annotations

import numpy as np

from variantcalling_tpu.io.vcf import MISSING, read_vcf, write_vcf


def _qual_by_locus(vcf_path: str) -> dict[tuple[str, int], float]:
    t = read_vcf(vcf_path, drop_format=True)
    out: dict[tuple[str, int], float] = {}
    for c, p, q in zip(t.chrom, t.pos, t.qual):
        out[(str(c), int(p))] = 0.0 if np.isnan(q) else float(q)
    return out


def _info_list(table, name: str) -> list[list[str]]:
    """Comma-separated INFO list field per record (case-insensitive key)."""
    out: list[list[str]] = []
    lower = name.lower()
    for s in table.info:
        vals: list[str] = []
        if s not in (None, MISSING, ""):
            for part in s.split(";"):
                if "=" in part:
                    k, v = part.split("=", 1)
                    if k.lower() == lower:
                        vals = [x for x in v.split(",") if x not in ("", MISSING)]
                        break
        out.append(vals)
    return out


def add_parental_qualities(
    denovo_vcf: str,
    maternal_vcfs: dict[str, str],
    paternal_vcfs: dict[str, str],
) -> tuple[object, np.ndarray]:
    """(table, denovo_qual float array w/ nan where absent) for the denovo VCF."""
    assert set(maternal_vcfs) == set(paternal_vcfs), "Mismatch between maternal and paternal samples"
    mother = {s: _qual_by_locus(p) for s, p in maternal_vcfs.items()}
    father = {s: _qual_by_locus(p) for s, p in paternal_vcfs.items()}

    table = read_vcf(denovo_vcf)
    hiconf = _info_list(table, "hiConfDeNovo")
    loconf = _info_list(table, "loConfDeNovo")
    qual = np.full(len(table), np.nan)
    n_hits = 0
    for i in range(len(table)):
        samples = hiconf[i] if hiconf[i] else loconf[i]
        samples = [s for s in samples if s in mother]
        if not samples:
            continue
        locus = (str(table.chrom[i]), int(table.pos[i]))
        pair_quals = [
            min(mother[s].get(locus, 0.0), father[s].get(locus, 0.0))
            for s in samples
        ]
        qual[i] = min(pair_quals)
        n_hits += 1
    if n_hits == 0:
        raise ValueError("No denovo calls found in the VCF or no overlap between the de novo vcf and the somatic calls")
    return table, qual


def write_recalibrated_vcf(denovo_vcf: str, output_vcf: str, maternal_vcfs: dict, paternal_vcfs: dict) -> int:
    table, qual = add_parental_qualities(denovo_vcf, maternal_vcfs, paternal_vcfs)
    table.header.ensure_info("DENOVO_QUAL", "1", "Float", "Pair quality (min of child/parent pair)")
    write_vcf(output_vcf, table, extra_info={"DENOVO_QUAL": qual})
    return int(np.sum(~np.isnan(qual)))
