"""Joint-calling gVCF utilities: compression, overlap cleanup, GQ-band BEDs.

TPU-native counterparts of the reference's ``ugvc/joint`` package
(compress_gvcf.py, cleanup_gvcf_before_calling.py, gvcf_bed.py,
denovo_refinement.py). IO is host-side streaming over columnar
:class:`~variantcalling_tpu.io.vcf.VariantTable` arrays; per-record PL math
is vectorized (ops/genotypes) rather than record-at-a-time.
"""
