"""gVCF block algebra: PL band-compression, record merging, overlap cleanup, GQ BEDs.

Behavioral parity targets (reference, studied not copied):
- ``ugvc/joint/compress_gvcf.py:28-216`` — merge sequential reference-band
  records whose GQ stays within a band; PL collapsed to 3 values.
- ``ugvc/joint/cleanup_gvcf_before_calling.py:11-86`` — drop uncalled
  (./.) records that overlap called deletions (GLNexus pre-pass).
- ``ugvc/joint/gvcf_bed.py:9-69`` — GQ-threshold BED emission with
  overlap/extent suppression.

Design: records are ingested once into columnar arrays; the 3-value PL
collapse is one vectorized masked segment-min over the padded (n, G) PL
tensor for all records at once (the reference recomputes a Python loop per
record); the merge decision scan is a single pass over plain int arrays.
"""

from __future__ import annotations

import numpy as np

from variantcalling_tpu.io.vcf import MISSING, VariantTable, read_vcf, write_vcf
from variantcalling_tpu.ops.genotypes import genotype_ordering

_GQ_SENTINEL = np.iinfo(np.int32).min


def compress_pl_to_3(pl: np.ndarray, n_alts: np.ndarray) -> np.ndarray:
    """Collapse padded diploid PL tensors (n, G_max) to (n, 3) hom-ref bands.

    Output per record: ``[PL(0,0), min_k PL(0,k) k>=1, min of all other
    genotypes]`` — the reference-band summary the merged ``<*>`` record
    carries (reference compress_gvcf.py:28-60). Records with G == 3 (one
    alt) pass through unchanged by construction. Vectorized over all
    records: slot masks depend only on each record's alt count, so records
    are bucketed by alt count and each bucket reduces with one masked min.
    """
    n = pl.shape[0]
    out = np.zeros((n, 3), dtype=pl.dtype)
    big = np.iinfo(np.int64).max if np.issubdtype(pl.dtype, np.integer) else np.inf
    for a in np.unique(n_alts):
        rows = np.nonzero(n_alts == a)[0]
        order = genotype_ordering(int(a))  # (G, 2) rows (j, k), j<=k
        g = order.shape[0]
        j, k = order[:, 0], order[:, 1]
        slot = np.where((j == 0) & (k == 0), 0, np.where(j == 0, 1, 2))
        block = pl[rows][:, :g]
        for s in range(3):
            m = slot == s
            if not m.any():
                continue
            out[rows, s] = np.min(np.where(m[None, :], block, big), axis=1)
    return out


def _int_format_field(table: VariantTable, name: str) -> np.ndarray:
    """Scalar integer FORMAT field as int32; _GQ_SENTINEL where absent."""
    raw = table.format_field(name)
    out = np.full(len(table), _GQ_SENTINEL, dtype=np.int64)
    for i, r in enumerate(raw):
        if r not in (None, MISSING, ""):
            try:
                out[i] = int(float(r))
            except ValueError:
                pass
    return out


def compress_gvcf_table(
    table: VariantTable,
    refcall_gq_threshold: int = 22,
    merge_gq_threshold: int = 10,
) -> tuple[list[str], int, int]:
    """Merge sequential gVCF records within a GQ band; returns output lines.

    A record starts a new group (flushing the previous one) when any holds
    (reference compress_gvcf.py:153-158):
    - it or the previous record is PASS, or is RefCall with
      GQ <= refcall_gq_threshold (these are kept verbatim, unmerged);
    - the chromosome changes;
    - its GQ drifts >= merge_gq_threshold from the group's running
      min or max GQ.

    Groups of size 1 are emitted verbatim. A merged group becomes one
    ``<*>`` block: pos/ref-base of the first record, END of the last,
    GT=0/0, GQ=min GQ, MIN_DP=min(MIN_DP or DP), PL = elementwise min of
    the 3-value collapsed PLs.
    """
    n = len(table)
    assert table.n_samples == 1, "gVCF compression expects a single-sample file"
    table.materialize_format()  # record rewriting needs FORMAT/sample strings
    gq = _int_format_field(table, "GQ")
    min_dp = _int_format_field(table, "MIN_DP")
    dp = _int_format_field(table, "DP")
    n_alts = np.maximum(table.n_alts(), 1)
    g_max = int(np.max((n_alts + 1) * (n_alts + 2) // 2))
    pl = table.format_numeric("PL", max_len=g_max, missing=np.inf)
    pl3 = compress_pl_to_3(pl, n_alts).astype(np.int64)

    filter_sets = [set(f.split(";")) if f not in (MISSING, "") else set() for f in table.filters]
    is_pass = np.fromiter(("PASS" in f for f in filter_sets), dtype=bool, count=n)
    is_low_refcall = np.fromiter(
        (("RefCall" in filter_sets[i]) and gq[i] != _GQ_SENTINEL and gq[i] <= refcall_gq_threshold for i in range(n)),
        dtype=bool,
        count=n,
    )
    # END of each record: INFO END= if present else pos + len(ref) - 1
    end = table.info_field("END", dtype=np.int64, missing=-1)
    ref_len = np.fromiter((len(r) for r in table.ref), dtype=np.int64, count=n)
    end = np.where(end >= 0, end, table.pos + ref_len - 1)

    # keep_verbatim records break groups on both sides (reference checks the
    # condition for the current AND previous record)
    keep = is_pass | is_low_refcall

    def raw_line(i: int) -> str:
        cols = [
            table.chrom[i],
            str(table.pos[i]),
            table.vid[i],
            table.ref[i],
            table.alt[i],
            _fmt_qual(table.qual[i]),
            table.filters[i],
            table.info[i],
            table.fmt_keys[i],
            table.sample_cols[i][0],
        ]
        return "\t".join(cols)

    def merged_line(lo: int, hi: int, grp_gq: int, grp_dp: int, grp_pl: np.ndarray) -> str:
        info = f"END={int(end[hi])}"
        sample = f"0/0:{grp_gq}:{grp_dp}:{int(grp_pl[0])},{int(grp_pl[1])},{int(grp_pl[2])}"
        return "\t".join(
            [
                table.chrom[lo],
                str(table.pos[lo]),
                ".",
                table.ref[lo][0],
                "<*>",
                "0",
                MISSING,
                info,
                "GT:GQ:MIN_DP:PL",
                sample,
            ]
        )

    out_lines: list[str] = []
    lo = 0
    grp_min_gq = grp_max_gq = int(gq[0]) if n else 0
    grp_dp = int(min_dp[0]) if n and min_dp[0] != _GQ_SENTINEL else (int(dp[0]) if n else 0)
    grp_pl = pl3[0].copy() if n else np.zeros(3, dtype=np.int64)

    def flush(hi: int) -> None:
        if hi == lo:
            out_lines.append(raw_line(lo))
        else:
            out_lines.append(merged_line(lo, hi, grp_min_gq, grp_dp, grp_pl))

    for i in range(1, n):
        gqi = int(gq[i]) if gq[i] != _GQ_SENTINEL else 0
        new_group = (
            keep[i]
            or keep[i - 1]
            or table.chrom[i] != table.chrom[i - 1]
            or gqi - grp_min_gq >= merge_gq_threshold
            or grp_max_gq - gqi >= merge_gq_threshold
        )
        if new_group:
            flush(i - 1)
            lo = i
            grp_min_gq = grp_max_gq = gqi
            grp_dp = int(min_dp[i]) if min_dp[i] != _GQ_SENTINEL else int(dp[i]) if dp[i] != _GQ_SENTINEL else 0
            grp_pl = pl3[i].copy()
        else:
            grp_min_gq = min(grp_min_gq, gqi)
            grp_max_gq = max(grp_max_gq, gqi)
            cand = min_dp[i] if min_dp[i] != _GQ_SENTINEL else dp[i]
            if cand != _GQ_SENTINEL:
                grp_dp = min(grp_dp, int(cand)) if grp_dp else int(cand)
            np.minimum(grp_pl, pl3[i], out=grp_pl)
    if n:
        flush(n - 1)
    return out_lines, n, len(out_lines)


def compress_gvcf(input_path: str, output_path: str, refcall_gq_threshold: int = 22, merge_gq_threshold: int = 10):
    table = read_vcf(input_path)
    lines, n_in, n_out = compress_gvcf_table(table, refcall_gq_threshold, merge_gq_threshold)
    _write_lines(output_path, table, lines)
    return n_in, n_out


def _fmt_qual(q) -> str:
    if q is None or (isinstance(q, float) and np.isnan(q)):
        return MISSING
    q = float(q)
    return str(int(q)) if q == int(q) else f"{q:g}"


def _write_lines(path: str, table: VariantTable, lines: list[str]) -> None:
    if str(path).endswith(".gz"):
        from variantcalling_tpu.io.bgzf import BgzfWriter

        out = BgzfWriter(path)
    else:
        out = open(path, "wt", encoding="utf-8")
    with out:
        for line in table.header.lines:
            out.write(line + "\n")
        out.write(table.header.column_header() + "\n")
        for line in lines:
            out.write(line + "\n")


# ---------------------------------------------------------------------------
# overlap cleanup (GLNexus pre-pass)
# ---------------------------------------------------------------------------


def cleanup_gvcf_table(table: VariantTable) -> tuple[np.ndarray, int, int]:
    """Keep-mask over records: drop ./. records overlapping called deletions.

    Reference semantics (cleanup_gvcf_before_calling.py:31-86): maintain a
    buffer of records overlapping a deletion's span; if any record in the
    buffer has a called non-ref GT, every ``./.`` record in the buffer is
    dropped. Implemented as one pass over columnar arrays.
    """
    n = len(table)
    gts = table.genotypes()
    uncalled = gts[:, 0] == -1
    called_alt = (gts[:, 0] > 0) | (gts[:, 1] > 0)
    # max deletion length per record (ref longer than alt)
    ref_len = np.fromiter((len(r) for r in table.ref), dtype=np.int64, count=n)
    max_del = np.zeros(n, dtype=np.int64)
    for i, alts in enumerate(table.alt_lists()):
        best = 0
        for a in alts:
            if a.startswith("<"):
                continue
            d = int(ref_len[i]) - len(a)
            if d > best:
                best = d
        max_del[i] = best

    keep = np.ones(n, dtype=bool)
    buf: list[int] = []
    buf_chrom = ""
    buf_span = -1
    buf_has_called = False

    def flush() -> None:
        nonlocal buf, buf_has_called
        if buf_has_called:
            for idx in buf:
                if uncalled[idx]:
                    keep[idx] = False
        buf = []
        buf_has_called = False

    for i in range(n):
        if buf and (table.chrom[i] != buf_chrom or table.pos[i] > buf_span):
            flush()
        if buf:
            buf.append(i)
            if max_del[i] > 0:
                buf_span = max(buf_span, int(table.pos[i]) + int(max_del[i]))
        elif max_del[i] > 0:
            buf = [i]
            buf_chrom = table.chrom[i]
            buf_span = int(table.pos[i]) + int(max_del[i])
        if buf and called_alt[i]:
            buf_has_called = True
    flush()
    n_written = int(keep.sum())
    return keep, n_written, n - n_written


def cleanup_gvcf(input_path: str, output_path: str) -> tuple[int, int]:
    table = read_vcf(input_path)
    keep, n_written, n_removed = cleanup_gvcf_table(table)
    sub = _subset_table(table, keep)
    write_vcf(output_path, sub)
    return n_written, n_removed


def _subset_table(table: VariantTable, mask: np.ndarray) -> VariantTable:
    sub = table.subset(mask)
    return sub


# ---------------------------------------------------------------------------
# GQ-threshold BED
# ---------------------------------------------------------------------------


def gvcf_to_bed(gvcf_file: str, bed_file: str, gq_threshold: int = 20, gt: bool = True) -> int:
    """Write BED of gVCF spans with GQ >= threshold (or < when ``gt=False``).

    Reference semantics (gvcf_bed.py:9-69): refcall deletion blocks cover
    only their first base; records starting before the running extent are
    skipped; extent tracks the max end seen per chrom. Returns the skipped
    count.
    """
    from variantcalling_tpu.io.bed import BedWriter

    table = read_vcf(gvcf_file)
    n = len(table)
    gq = _int_format_field(table, "GQ")
    gts = table.genotypes()
    ref_len = np.fromiter((len(r) for r in table.ref), dtype=np.int64, count=n)
    end_info = table.info_field("END", dtype=np.int64, missing=-1)
    # 0-based start; stop = END if present else pos+len(ref)-1
    start = table.pos - 1
    stop = np.where(end_info >= 0, end_info, table.pos + ref_len - 1)
    hom_ref = (gts[:, 0] == 0) & (gts[:, 1] == 0)
    uncalled = gts[:, 0] == -1
    no_gq = gq == _GQ_SENTINEL
    refblock_del = (ref_len > 1) & (no_gq | hom_ref | uncalled)
    end = np.where(refblock_del, start + 1, stop)

    skipped = 0
    extent = -1
    last_chrom = ""
    with BedWriter(bed_file) as bed:
        for i in range(n):
            chrom = table.chrom[i]
            if chrom == last_chrom and start[i] < extent:
                skipped += 1
                continue
            if chrom != last_chrom or extent < end[i]:
                last_chrom = chrom
                extent = int(end[i])
            if gt:
                if not no_gq[i] and gq[i] >= gq_threshold:
                    bed.write(chrom, int(start[i]), int(end[i]))
            else:
                if no_gq[i] or gq[i] < gq_threshold:
                    bed.write(chrom, int(start[i]), int(end[i]))
    return skipped
