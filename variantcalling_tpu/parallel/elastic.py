"""Elastic pod membership: mobile spans, leases, work-stealing and the
autoscaling coordinator (docs/scaleout.md "Elastic membership").

The static pod (``parallel/rank_plan.py``) fixes N at launch: a
SIGKILLed rank must be relaunched by hand and one slow rank stalls the
rank-sequenced merge. This module makes the partition itself mobile
while keeping the hard invariant that the merged output is
byte-identical to the serial run no matter how the membership evolved:

- **Spans, not ranks.** A unit of work is an absolute target interval
  ``[lo, hi)`` of the decompressed record region (:class:`Span`). The
  reader maps targets through the ONE deterministic cut rule
  (``VcfChunkReader`` ``span_targets`` — smallest line start >= the
  target), so ANY monotone sequence of targets tiles the record body
  exactly and the concatenation of span segments IS the serial record
  stream. The classic rank fractions ``r/N`` are the special case
  :func:`initial_spans` seeds the pod with.
- **Single-claimant leases.** Every offered (span, generation) has one
  lease file created with ``O_CREAT | O_EXCL`` (:func:`claim_lease`) —
  POSIX-atomic, so two workers offered the same span can never both
  render it: the loser raises :class:`LeaseLost` and exits
  ``EXIT_LEASE_LOST`` (6), which the coordinator treats as benign.
  Re-offers bump the generation, never reuse a lease.
- **Re-cut at the journal watermark.** Every journaled chunk records
  ``in_end`` — the absolute decompressed end offset of its input span,
  always a line start. A dead or stolen span is split there: the
  journaled prefix ``[lo, C)`` becomes an adoptable span whose journal
  is handed off verbatim (:func:`handoff_journal` — the adopter resumes,
  skips every chunk and commits without recomputing), and the unstarted
  suffix ``[C, hi)`` re-cuts fresh. Chunk boundaries are a pure function
  of (input bytes, chunk_bytes, span start), so the adopter's boundaries
  reproduce the dead worker's exactly.
- **The coordinator** (:class:`Coordinator`) is a polling state machine
  over direct child processes: it reaps exits, re-offers dead spans,
  kills and re-cuts stragglers whose journal progress rate falls behind
  the sibling median (:attr:`Coordinator.steal_factor`), grows the pool
  toward ``max_ranks`` when re-cuts queue more spans than workers, and
  sheds below the demand when the host load average says the machine is
  oversubscribed. Every membership transition is one ``membership`` obs
  event (``vctpu obs summary`` rolls them up) and one log line. A hung
  outcome is impossible by construction: every loop tick either
  progresses, re-offers, sheds, or hits the pod deadline (exit 5); a
  span that keeps dying fails the pod loudly with ``EXIT_SPAN_FAILED``
  (7) after bounded attempts.

Byte contract: span workers run as single-rank plans (no
``##vctpu_ranks=`` header line), and :func:`merge_spans` re-carries the
BGZF block carry across every seam through the same splice core as the
classic merge — so the committed output is literally byte-identical to
the single-rank run, not merely modulo headers. Locked by
``tests/unit/test_elastic.py`` / ``tests/system/test_elastic.py`` and
the chaoshunt elastic fault classes.
"""

from __future__ import annotations

import json
import os
import time
from dataclasses import dataclass, field

from variantcalling_tpu import logger, obs
from variantcalling_tpu.engine import EngineError

#: worker exit code: lost the single-claimant lease race — benign to the
#: coordinator (exactly one sibling holds the span)
EXIT_LEASE_LOST = 6
#: pod exit code: a span died more than ``max_attempts`` times — the
#: failure is loud and distinct, never a hang or a silent gap
EXIT_SPAN_FAILED = 7
#: pod exit code for the global deadline (tools/podrun's classic value)
EXIT_TIMEOUT = 5
#: deterministic configuration errors propagate the worker's exit 2
EXIT_USAGE = 2

SPAN_ENV = "VCTPU_SPAN"


class LeaseLost(RuntimeError):
    """Another worker already claimed this (span, generation) lease —
    exit ``EXIT_LEASE_LOST``, compute nothing."""


@dataclass(frozen=True)
class Span:
    """One mobile unit of pod work: absolute decompressed-byte targets
    ``[lo, hi)`` into the record region, plus the lease generation it
    is currently offered under. Targets, not line offsets — the reader
    advances each to the next line start, so adjacent spans always
    share their seam exactly."""

    lo: int
    hi: int
    gen: int = 0

    def label(self) -> str:
        return f"[{self.lo},{self.hi})"


def span_segment_path(out_path: str, lo: int, hi: int) -> str:
    """An elastic span's staged segment, next to the destination like
    the classic ``.rank{r}of{N}.seg`` — the span spelling carries the
    target interval so a re-cut never collides with its parent."""
    return f"{out_path}.span{int(lo)}-{int(hi)}.seg"


def span_env(span: Span) -> str:
    """The ``VCTPU_SPAN`` wire format: ``lo:hi:gen``."""
    return f"{span.lo}:{span.hi}:{span.gen}"


def parse_span_env(value: str) -> tuple[int, int, int]:
    """Parse ``lo:hi:gen``; malformed values are configuration errors
    (exit 2), never a guess."""
    parts = str(value).split(":")
    try:
        lo, hi, gen = (int(p) for p in parts)
    except ValueError:
        raise EngineError(
            f"VCTPU_SPAN={value!r} is malformed — expected lo:hi:gen "
            "(three integers; tools/podrun --elastic sets it)") from None
    if lo < 0 or hi < lo or gen < 0:
        raise EngineError(
            f"VCTPU_SPAN={value!r} is out of range — need "
            "0 <= lo <= hi and gen >= 0")
    return lo, hi, gen


def initial_spans(header_end: int, total: int, n: int) -> list[Span]:
    """Seed a pod with the classic rank fractions: target ``i/n`` of the
    record body for each seam — EXACTLY the targets the static rank
    partition uses, so an elastic pod that never re-cuts produces the
    same segments as ``--ranks n``."""
    if n <= 0:
        raise ValueError(f"need at least one span, got n={n}")
    header_end = int(header_end)
    body = max(0, int(total) - header_end)
    cuts = [header_end + body * i // n for i in range(n + 1)]
    return [Span(cuts[i], cuts[i + 1], 0) for i in range(n)]


# ---------------------------------------------------------------------------
# the single-claimant lease
# ---------------------------------------------------------------------------


def lease_path(seg_path: str, gen: int) -> str:
    return f"{seg_path}.lease.g{int(gen)}"


def claim_lease(seg_path: str, gen: int) -> bool:
    """Claim the (span, generation) lease: ``O_CREAT | O_EXCL``, atomic
    on every POSIX filesystem we target — exactly one claimant per
    offer, however many workers race. The file stays on disk for the
    pod's lifetime (the coordinator sweeps it with the segments), so a
    late duplicate — e.g. a join landing during the merge — is refused
    by the same mechanism."""
    try:
        fd = os.open(lease_path(seg_path, gen),
                     os.O_CREAT | os.O_EXCL | os.O_WRONLY, 0o644)
    except FileExistsError:
        return False
    with os.fdopen(fd, "w", encoding="utf-8") as fh:
        json.dump({"pid": os.getpid(), "gen": int(gen)}, fh)
        fh.write("\n")
    return True


def discard_span_files(out_path: str) -> None:
    """Remove every span segment + marker + lease + journal/partial next
    to ``out_path`` (post-merge sweep; chaos between-leg cleanup)."""
    import glob

    for p in glob.glob(glob.escape(str(out_path)) + ".span*-*.seg*"):
        try:
            os.remove(p)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# journal progress + the re-cut handoff
# ---------------------------------------------------------------------------


def journal_progress(seg_path: str) -> tuple[int, int | None]:
    """``(journaled_chunks, last_in_end)`` of a span segment's journal —
    the coordinator's progress probe and the re-cut point. ``(0, None)``
    when there is no journal, no entries, or the writer predates the
    ``in_end`` field (degrades to whole-span re-assignment)."""
    from variantcalling_tpu.io import journal as journal_mod

    loaded = journal_mod.ChunkJournal.load(seg_path)
    if loaded is None:
        return 0, None
    _, entries = loaded
    if not entries:
        return 0, None
    end = entries[-1].get("in_end")
    return len(entries), (int(end) if end is not None else None)


def handoff_journal(old_seg: str, new_seg: str,
                    new_span: tuple[int, int]) -> bool:
    """Hand a dead worker's journal + partial to the adopted prefix span
    ``new_span``: rename the partial next to the new segment path,
    rewrite the journal with ``config.span`` pinned to the NEW interval
    (the resume identity must describe what the adopter was leased), and
    drop the old journal. The adopter then resumes normally — identity
    match, CRC verification (``VCTPU_RESUME_VERIFY=full`` included),
    skip every chunk, commit — recomputing nothing.

    Returns False (degrade to whole-span re-assignment, which loses only
    compute, never bytes) when the journal is missing/empty, the partial
    is gone, or a LIVE process still owns the partial — a handoff must
    never steal a running writer's file."""
    from variantcalling_tpu.io import journal as journal_mod

    loaded = journal_mod.ChunkJournal.load(old_seg)
    if loaded is None:
        return False
    meta, entries = loaded
    if not entries:
        return False
    token = meta.get("partial") or None
    if token is not None and journal_mod.token_in_use(token):
        return False
    old_part = journal_mod.partial_path(old_seg, token)
    if not os.path.exists(old_part):
        return False
    cfg = meta.get("config")
    if isinstance(cfg, dict):
        meta = dict(meta, config=dict(
            cfg, span=[int(new_span[0]), int(new_span[1])]))
    # order is crash-safe: after the partial rename the OLD journal
    # points at a missing partial (resume degrades to fresh), and until
    # the NEW journal lands the new segment has no journal at all —
    # either interruption costs recompute, never bytes
    os.replace(old_part, journal_mod.partial_path(new_seg, token))
    j = journal_mod.ChunkJournal(new_seg)
    j.begin(meta)
    for e in entries:
        j.append(int(e["seq"]), int(e["records"]), int(e["pass"]),
                 int(e["body_len"]), int(e["crc"]), in_end=e.get("in_end"))
    j.close()
    try:
        os.remove(journal_mod.journal_path(old_seg))
    except OSError:
        pass
    return True


# ---------------------------------------------------------------------------
# the span-plan committer
# ---------------------------------------------------------------------------


def merge_spans(out_path: str, spans: list[Span],
                cleanup: bool = True) -> dict:
    """The span-plan commit: splice however many seams the final plan
    has, through the same verified core as the classic rank merge
    (``rank_plan.splice_segments`` — marker/identity/header checks, one
    BGZF compressor re-carrying the block carry across every seam).
    Refuses non-contiguous plans: adjacent spans must share their
    target seam exactly, or some bytes would be dropped or doubled."""
    from variantcalling_tpu.parallel import rank_plan as rank_plan_mod

    out_path = str(out_path)
    ordered = sorted(spans, key=lambda s: (s.lo, s.hi))
    for a, b in zip(ordered, ordered[1:]):
        if a.hi != b.lo:
            raise rank_plan_mod.MergeError(
                f"span plan is not contiguous: {a.label()} then "
                f"{b.label()} — refusing to splice a gapped or "
                "overlapping partition")
    segs = [(f"span {s.label()}", span_segment_path(out_path, s.lo, s.hi))
            for s in ordered]
    total, markers = rank_plan_mod.splice_segments(out_path, segs)
    stats = {
        "spans": len(ordered),
        "bytes": total,
        "n": sum(int((m.get("stats") or {}).get("n") or 0)
                 for m in markers),
        "n_pass": sum(int((m.get("stats") or {}).get("n_pass") or 0)
                      for m in markers),
    }
    if obs.active():
        obs.event("journal", "span_merge", spans=len(ordered), bytes=total,
                  records=stats["n"])
    if cleanup:
        discard_span_files(out_path)
    logger.info("merged %d span segments -> %s (%d records, %d bytes "
                "uncompressed)", len(ordered), out_path, stats["n"], total)
    return stats


# ---------------------------------------------------------------------------
# membership telemetry
# ---------------------------------------------------------------------------


def emit_membership(action: str, span: Span | None = None,
                    **fields) -> None:
    """One membership transition: a log line always, a ``membership``
    obs event when a stream is open (``vctpu obs summary`` rolls the
    actions up next to the recovery ladder)."""
    detail = " ".join(f"{k}={v}" for k, v in fields.items() if v is not None)
    logger.info("membership: %s %s %s", action,
                span.label() if span is not None else "pod", detail)
    if obs.active():
        obs.event("membership", span.label() if span is not None else "pod",
                  action=action,
                  **{k: v for k, v in fields.items() if v is not None})


# ---------------------------------------------------------------------------
# the pod coordinator
# ---------------------------------------------------------------------------


@dataclass
class _Assignment:
    """One span's place in the coordinator's plan."""

    span: Span
    state: str = "pending"  # pending | running | done | failed
    slot: int | None = None  # initial worker index (per-worker env hooks)
    proc: object = None
    attempts: int = 0
    started: float = 0.0
    finished: float = 0.0
    steal_pending: bool = False  # killed for stealing, reap in flight
    exit_reason: str | None = None


class Coordinator:
    """The elastic pod state machine (tools/podrun ``--elastic``).

    Owns a plan of span assignments and a set of direct child workers
    produced by the injectable ``spawn(span, slot)`` callable (a real
    ``subprocess.Popen`` under podrun; any object with ``pid`` /
    ``poll()`` / ``kill()`` in tests). :meth:`run` polls until the plan
    is fully done, then returns an exit code; the final (possibly
    re-cut) plan is :attr:`spans`, ready for :func:`merge_spans`.

    Membership policy:

    - a worker that EXITS NONZERO (or is killed) has its span re-offered
      under the next lease generation; when its journal recorded
      progress, the span is first re-cut at the last ``in_end`` so the
      journaled prefix is adopted instead of recomputed;
    - a worker whose journal progress rate falls behind
      ``1/steal_factor`` of the sibling median — or that shows NO
      progress long after the sibling rates say it should have
      finished — is killed and re-cut (work stealing);
    - exit ``EXIT_LEASE_LOST`` is benign (the lease kept the span
      single-claimant); exit 2 is a deterministic configuration error
      and fails the pod immediately with 2;
    - a span exceeding ``max_attempts`` deaths fails the pod with
      ``EXIT_SPAN_FAILED`` — loud, never a hang;
    - the pool grows toward ``max_ranks`` whenever re-cuts queue more
      pending spans than running workers, and sheds (no new joins, down
      to ``min_ranks``) while the 1-minute load average exceeds
      ``max_load`` — the autoscaler's signals are the journals'
      progress telemetry plus host pressure.
    """

    def __init__(self, out_path: str, spans: list[Span], spawn, *,
                 max_ranks: int | None = None, min_ranks: int = 1,
                 steal_factor: float = 4.0, grace_s: float = 1.5,
                 poll_s: float = 0.05, steal_check_s: float = 0.5,
                 max_attempts: int = 3, timeout_s: float | None = None,
                 max_load: float | None = None, load_fn=None,
                 chaos: str | None = None, on_state=None):
        self.out = str(out_path)
        self._spawn_fn = spawn
        self._plan = [_Assignment(span=s, slot=i)
                      for i, s in enumerate(spans)]
        self.max_ranks = max_ranks if max_ranks is not None else len(spans)
        self.min_ranks = max(1, int(min_ranks))
        self.steal_factor = float(steal_factor)
        self.grace_s = float(grace_s)
        self.poll_s = float(poll_s)
        self.steal_check_s = float(steal_check_s)
        self.max_attempts = int(max_attempts)
        self.timeout_s = timeout_s
        self.max_load = max_load
        self._load_fn = load_fn
        self.chaos = chaos
        self._on_state = on_state
        self._shadows: list[dict] = []  # chaos duplicate claimants
        self._chaos_fired = False
        self._shed_active = False
        self._last_steal_check = 0.0
        self.claim_lost = 0
        self.join_refused = 0
        self.transitions: list[str] = []

    # -- public surface ----------------------------------------------------

    @property
    def spans(self) -> list[Span]:
        """The current plan, in merge order."""
        return [a.span for a in self._plan]

    def run(self) -> int:
        """Drive the pod to completion; 0 when every span committed."""
        deadline = (time.monotonic() + self.timeout_s
                    if self.timeout_s else None)
        try:
            while True:
                rc = self._reap()
                if rc is not None:
                    self._kill_all()
                    return rc
                if any(a.state == "failed" for a in self._plan):
                    self._kill_all()
                    return EXIT_SPAN_FAILED
                if all(a.state == "done" for a in self._plan):
                    return 0
                if deadline is not None and time.monotonic() > deadline:
                    logger.error("elastic pod: deadline exceeded — "
                                 "killing %d live workers",
                                 sum(1 for a in self._plan
                                     if a.state == "running"))
                    self._kill_all()
                    return EXIT_TIMEOUT
                self._check_stragglers()
                self._spawn_pending()
                time.sleep(self.poll_s)
        except KeyboardInterrupt:
            self._kill_all()
            raise

    def chaos_join_during_merge(self, wait_s: float = 120.0) -> bool:
        """Chaos hook: offer a completed span to a late joiner right
        before the merge — the lease generation already on disk must
        refuse it (worker exits ``EXIT_LEASE_LOST``)."""
        done = [a for a in self._plan if a.state == "done"]
        if not done:
            return False
        a = done[-1]
        proc = self._spawn_fn(a.span, None)
        self._event("join", a.span, pid=getattr(proc, "pid", None),
                    duplicate=1)
        deadline = time.monotonic() + wait_s
        while proc.poll() is None and time.monotonic() < deadline:
            time.sleep(self.poll_s)
        if proc.poll() is None:
            proc.kill()
            return False
        if proc.poll() == EXIT_LEASE_LOST:
            self.join_refused += 1
            self._event("join_refused", a.span, reason="merge in progress")
            return True
        return False

    # -- internals ---------------------------------------------------------

    def _event(self, action: str, span: Span | None, **fields) -> None:
        self.transitions.append(action)
        emit_membership(action, span, **fields)

    def _seg(self, a: _Assignment) -> str:
        return span_segment_path(self.out, a.span.lo, a.span.hi)

    def _notify_state(self) -> None:
        if self._on_state is None:
            return
        self._on_state([
            {"span": [a.span.lo, a.span.hi], "gen": a.span.gen,
             "pid": getattr(a.proc, "pid", None)}
            for a in self._plan if a.state == "running"])

    def _spawn(self, a: _Assignment) -> None:
        a.state = "running"
        a.started = time.monotonic()
        a.steal_pending = False
        a.proc = self._spawn_fn(a.span, a.slot)
        self._event("join", a.span, gen=a.span.gen,
                    pid=getattr(a.proc, "pid", None),
                    attempt=a.attempts)
        if self.chaos == "steal_race" and not self._chaos_fired:
            # offer the SAME (span, generation) to a duplicate claimant:
            # the lease must yield exactly one winner, whichever worker
            # reaches the O_EXCL open first
            self._chaos_fired = True
            sh = self._spawn_fn(a.span, None)
            self._shadows.append({"span": a.span, "proc": sh})
            self._event("join", a.span, gen=a.span.gen,
                        pid=getattr(sh, "pid", None), duplicate=1)
        self._notify_state()

    def _take_shadow(self, span: Span):
        for sh in self._shadows:
            if sh["span"].lo == span.lo and sh["span"].hi == span.hi:
                self._shadows.remove(sh)
                return sh["proc"]
        return None

    def _reap(self) -> int | None:
        from variantcalling_tpu.parallel import rank_plan as rank_plan_mod

        for a in self._plan:
            if a.state != "running":
                continue
            rc = a.proc.poll()
            if rc is None:
                continue
            if rc == 0:
                if rank_plan_mod.load_marker(self._seg(a)) is None:
                    # exited clean without sealing its segment — treat
                    # as a death, the marker is the completion contract
                    self._requeue(a, "exit 0 without a .done marker")
                    continue
                a.state = "done"
                a.finished = time.monotonic()
                self._event("leave", a.span, gen=a.span.gen,
                            pid=getattr(a.proc, "pid", None),
                            reason="complete")
                self._notify_state()
            elif rc == EXIT_LEASE_LOST:
                self.claim_lost += 1
                self._event("claim_lost", a.span, gen=a.span.gen,
                            pid=getattr(a.proc, "pid", None))
                winner = self._take_shadow(a.span)
                if winner is not None:
                    # the duplicate claimant won the race — it is now
                    # the span's worker; keep waiting on it
                    a.proc = winner
                    self._notify_state()
                else:
                    self._requeue(a, "lease lost")
            elif rc == EXIT_USAGE:
                # deterministic configuration error: every re-offer
                # would die the same way — fail the pod with the
                # worker's own code
                self._event("leave", a.span, gen=a.span.gen,
                            reason="config error")
                return EXIT_USAGE
            else:
                reason = a.exit_reason or (
                    f"killed by signal {-rc}" if rc < 0 else f"exit {rc}")
                self._requeue(a, reason)
        for sh in list(self._shadows):
            rc = sh["proc"].poll()
            if rc is None or rc == 0:
                continue  # still racing, or won and completed the span
            self._shadows.remove(sh)
            if rc == EXIT_LEASE_LOST:
                self.claim_lost += 1
                self._event("claim_lost", sh["span"],
                            gen=sh["span"].gen,
                            pid=getattr(sh["proc"], "pid", None))
        return None

    def _requeue(self, a: _Assignment, reason: str) -> None:
        self._event("leave", a.span, gen=a.span.gen,
                    pid=getattr(a.proc, "pid", None), reason=reason)
        a.attempts += 1
        a.proc = None
        a.exit_reason = None
        if a.attempts > self.max_attempts:
            a.state = "failed"
            self._event("give_up", a.span, attempts=a.attempts)
            logger.error("elastic pod: span %s failed %d times — giving "
                         "up (exit %d)", a.span.label(), a.attempts,
                         EXIT_SPAN_FAILED)
            return
        seg = self._seg(a)
        chunks, end = journal_progress(seg)
        if chunks > 0 and end is not None and a.span.lo < end < a.span.hi:
            # re-cut at the journal watermark: the journaled prefix is a
            # complete sub-span (every in_end is a line start, and chunk
            # boundaries re-derive identically from the same span
            # start), adoptable without recompute; the suffix is fresh
            adopt = Span(a.span.lo, end, a.span.gen + 1)
            rest = Span(end, a.span.hi, 0)
            if handoff_journal(seg, span_segment_path(self.out, adopt.lo,
                                                      adopt.hi),
                               (adopt.lo, adopt.hi)):
                i = self._plan.index(a)
                self._plan[i:i + 1] = [
                    _Assignment(span=adopt, attempts=a.attempts),
                    _Assignment(span=rest, attempts=a.attempts),
                ]
                self._event("recut", a.span, at=end,
                            adopted_chunks=chunks)
                self._notify_state()
                return
        # whole-span re-offer under the next generation; any journal
        # stays in place, so the replacement resumes instead of
        # recomputing the journaled prefix
        a.span = Span(a.span.lo, a.span.hi, a.span.gen + 1)
        a.state = "pending"
        self._event("reassign", a.span, gen=a.span.gen)
        self._notify_state()

    def _check_stragglers(self) -> None:
        if self.steal_factor <= 0:
            return
        now = time.monotonic()
        if now - self._last_steal_check < self.steal_check_s:
            return
        self._last_steal_check = now
        rates = [
            (a.span.hi - a.span.lo) / max(a.finished - a.started, 1e-6)
            for a in self._plan
            if a.state == "done" and a.span.hi > a.span.lo
            and a.finished > a.started]
        probes = []
        for a in self._plan:
            if a.state != "running" or a.steal_pending:
                continue
            elapsed = now - a.started
            if elapsed < self.grace_s:
                continue
            _, end = journal_progress(self._seg(a))
            done_b = max(0, (end if end is not None else a.span.lo)
                         - a.span.lo)
            probes.append((a, done_b, elapsed))
            if done_b > 0:
                rates.append(done_b / elapsed)
        if len(rates) < 2:
            return  # stealing needs a sibling rate to compare against
        median = sorted(rates)[len(rates) // 2]
        if median <= 0:
            return
        for a, done_b, elapsed in probes:
            total_b = a.span.hi - a.span.lo
            if total_b <= 0:
                continue
            slow = done_b > 0 and (done_b / elapsed) \
                < median / self.steal_factor
            stuck = done_b == 0 and elapsed > self.grace_s \
                + self.steal_factor * (total_b / median)
            if not (slow or stuck):
                continue
            a.steal_pending = True
            a.exit_reason = "straggler (stolen)"
            self._event("steal", a.span, gen=a.span.gen,
                        pid=getattr(a.proc, "pid", None),
                        done_bytes=done_b,
                        rate=round(done_b / elapsed, 1),
                        median=round(median, 1))
            try:
                a.proc.kill()
            except OSError:
                pass

    def _spawn_pending(self) -> None:
        pending = [a for a in self._plan if a.state == "pending"]
        if not pending:
            return
        running = sum(1 for a in self._plan if a.state == "running")
        cap = self.max_ranks
        load = self._load()
        if self.max_load is not None and load is not None \
                and load > self.max_load:
            shed_cap = max(self.min_ranks, running)
            if shed_cap < cap and not self._shed_active:
                self._shed_active = True
                self._event("shed", None, load=round(load, 2),
                            cap=shed_cap)
            cap = shed_cap
        else:
            self._shed_active = False
        for a in pending:
            if running >= cap:
                break
            self._spawn(a)
            running += 1

    def _load(self) -> float | None:
        fn = self._load_fn
        if fn is None:
            fn = getattr(os, "getloadavg", None)
            if fn is None:
                return None
        try:
            return float(fn()[0])
        except (OSError, ValueError, TypeError, IndexError):
            return None

    def _kill_all(self) -> None:
        procs = [a.proc for a in self._plan
                 if a.state == "running" and a.proc is not None]
        procs += [sh["proc"] for sh in self._shadows]
        for p in procs:
            try:
                p.kill()
            except OSError:
                pass
        for p in procs:
            wait = getattr(p, "wait", None)
            if wait is None:
                continue
            try:
                wait(timeout=5)
            except Exception:  # noqa: BLE001  # vctpu-lint: disable=VCT002 — best-effort reap of already-killed workers
                pass
