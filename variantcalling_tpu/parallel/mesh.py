"""Device mesh + sharding helpers — the framework's distributed substrate.

Replaces the reference's parallelism layer (joblib process fan-out over
genomic regions, SURVEY.md §2.4 / coverage_analysis.py:371-391) with a
``jax.sharding.Mesh`` over which:

- variant-axis data parallelism shards the (variants × features) tensor for
  filter inference ("dp" axis),
- model-parallel training shards hidden/feature dims ("mp" axis),
- contig/window sharding is the sequence-parallel analog for coverage ("dp"
  over contig shards),
- SEC cohort aggregation all-reduces per-sample count tensors (psum over
  "dp").

All helpers degrade gracefully to a single device so every pipeline runs
unchanged on 1 chip, an 8-chip pod slice, or a forced-host CPU mesh in tests.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "dp"
MODEL_AXIS = "mp"


def make_mesh(n_data: int | None = None, n_model: int = 1, devices=None) -> Mesh:
    """Create a (dp, mp) mesh over this process's LOCAL devices.

    ``n_data=None`` uses all devices not claimed by ``n_model``. Pipelines
    run rank-local under multi-host launches (each rank owns its own
    inputs and fetches its own outputs); meshes spanning every host's
    devices are built explicitly via parallel.distributed.global_mesh for
    collective reductions.
    """
    devices = list(devices if devices is not None else jax.local_devices())
    if n_data is None:
        n_data = len(devices) // n_model
    use = n_data * n_model
    if use == 0 or use > len(devices):
        raise ValueError(
            f"mesh shape dp={n_data} x mp={n_model} does not fit {len(devices)} available devices"
        )
    dev_array = np.asarray(devices[:use]).reshape(n_data, n_model)
    return Mesh(dev_array, (DATA_AXIS, MODEL_AXIS))


def data_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Shard the leading (variants/contigs) axis across dp; replicate the rest."""
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_to_multiple(x: np.ndarray, multiple: int, axis: int = 0, fill=0) -> tuple[np.ndarray, int]:
    """Pad ``x`` along ``axis`` to a multiple (static shapes for pjit). Returns (padded, n_orig)."""
    n = x.shape[axis]
    target = ((n + multiple - 1) // multiple) * multiple
    if target == n:
        return x, n
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, target - n)
    return np.pad(x, widths, constant_values=fill), n


def mesh_sum_leading(mesh: Mesh, arr, stage_name: str) -> np.ndarray:
    """Sum a dp-sharded tensor over its LEADING axis into a replicated
    result — THE one device-put + mesh-sum reduction both cohort
    aggregations share (``sec.aggregate.aggregate_on_mesh`` for
    single-host sample shards, ``distributed.aggregate_counts_across_hosts``
    for a global mesh spanning every host's devices).

    ``arr`` is either a HOST array (device_put here with the dp-leading
    sharding — its leading axis must already divide the mesh dp size;
    callers own their padding rule) or an already-global ``jax.Array``
    (the multi-host path built via host_local_to_global). The reduction
    is one jitted ``sum(axis=0)`` constrained to a replicated output —
    psum over ICI/DCN on real meshes — accumulated in f32; the wall time
    lands in the obs stream under ``stage_name``.
    """
    from variantcalling_tpu.utils.trace import stage

    if not isinstance(arr, jax.Array):
        arr = jax.device_put(
            np.asarray(arr), data_sharding(mesh, np.asarray(arr).ndim))
    rep = NamedSharding(mesh, P(*([None] * (arr.ndim - 1))))

    @jax.jit
    def reduce(x):
        return jax.lax.with_sharding_constraint(
            jnp.sum(x, axis=0, dtype=jnp.float32), rep)

    # collective timing flows into the obs stream (docs/observability.md)
    with stage(stage_name):
        with mesh:
            out = reduce(arr)
        # replicated fetch works for local meshes and global multi-host
        # ones (in-function import: distributed top-imports this module)
        from variantcalling_tpu.parallel.distributed import replicated_to_host

        return replicated_to_host(out)


def shard_batch(mesh: Mesh, arrays: dict[str, np.ndarray]) -> tuple[dict[str, jax.Array], int]:
    """Pad every array to the dp-divisible length and device_put with dp sharding.

    Returns (device arrays, original length). A ``valid`` bool mask is added
    so downstream kernels can ignore padding rows.
    """
    if "valid" in arrays:
        raise ValueError("'valid' is reserved for the generated padding mask")
    lengths = {k: np.asarray(v).shape[0] for k, v in arrays.items()}
    if len(set(lengths.values())) > 1:
        raise ValueError(f"all arrays must share the leading axis length, got {lengths}")
    n_data = mesh.shape[DATA_AXIS]
    n_orig = 0
    out: dict[str, jax.Array] = {}
    for k, v in arrays.items():
        padded, n_orig = pad_to_multiple(np.asarray(v), n_data, axis=0)
        out[k] = jax.device_put(padded, data_sharding(mesh, padded.ndim))
    if arrays:
        n_padded = ((n_orig + n_data - 1) // n_data) * n_data
        valid = np.zeros(n_padded, dtype=bool)
        valid[:n_orig] = True
        out["valid"] = jax.device_put(valid, data_sharding(mesh, 1))
    return out, int(n_orig)
