"""Halo exchange over position-sharded sequence data (shard_map + ppermute).

The framework's sequence axis is genomic position (SURVEY §5.7: contig/
window sharding is the long-context analog). Kernels whose stencil peeks
past a shard edge — motif windows (±5 bp), hpol proximity (±12 bp),
run-length scans — need their neighbors' edge bases. ``halo_exchange_1d``
is that primitive: inside a ``shard_map`` body, each shard ppermutes its
edges to its neighbors over ICI, so the composed program reads
``[left halo | local block | right halo]`` with no host gather and no
re-materialized global array.

``sharded_run_lengths`` composes it with the run-length scan
(:mod:`variantcalling_tpu.ops.runs`): runs crossing a shard edge keep
their exact length up to the halo cap.
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from variantcalling_tpu.parallel.mesh import DATA_AXIS, pad_to_multiple


def halo_exchange_1d(block: jnp.ndarray, halo_left: int, halo_right: int,
                     axis_name: str = DATA_AXIS, fill=0,
                     n_shards: int | None = None) -> jnp.ndarray:
    """Pad a shard's local block with its neighbors' edges (traceable,
    call inside a shard_map body).

    Boundary shards (no neighbor on that side) read ``fill``. ppermute
    delivers zeros to devices with no source, so non-zero fills overwrite
    by shard index.

    ``n_shards`` must be the STATIC mesh-axis size (the ppermute
    permutation is a Python list, not a traced value). Callers that know
    their mesh pass it explicitly — ``jax.lax.axis_size`` only exists on
    newer jax releases (0.4.37 lacks it), and a ``psum(1)`` substitute
    would be traced, so the explicit parameter is the portable spelling.
    """
    if n_shards is None:
        axis_size = getattr(jax.lax, "axis_size", None)
        if axis_size is None:
            raise TypeError(
                "halo_exchange_1d needs n_shards= on this jax version "
                "(jax.lax.axis_size is unavailable); pass the mesh axis size")
        n_shards = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    parts = [block]
    if halo_left:
        # my left halo = left neighbor's tail: shard i sends tail -> i+1
        tail = block[-halo_left:]
        recv = jax.lax.ppermute(tail, axis_name,
                                [(i, i + 1) for i in range(n_shards - 1)])
        if fill != 0:
            recv = jnp.where(idx > 0, recv, jnp.full_like(recv, fill))
        parts.insert(0, recv)
    if halo_right:
        head = block[:halo_right]
        recv = jax.lax.ppermute(head, axis_name,
                                [(i, i - 1) for i in range(1, n_shards)])
        if fill != 0:
            recv = jnp.where(idx < n_shards - 1, recv, jnp.full_like(recv, fill))
        parts.append(recv)
    return jnp.concatenate(parts)


def sharded_run_lengths(codes: np.ndarray, mesh: Mesh, halo: int = 256,
                        fill: int = 255,
                        min_halo: int | None = None) -> tuple[np.ndarray, np.ndarray]:
    """(run_starts bool, run_lengths int32) for a position-sharded genome.

    The sequence is padded to a dp multiple with an OUT-OF-BAND code
    (255 — not any base encoding, including N=4, so padding can never
    extend a run of real bases or Ns), sharded over the mesh dp axis, and
    each shard computes the run scan over
    ``[1-left-halo | local | halo-right]``:

    - the 1-base LEFT halo decides whether a local position starts a run;
    - the ``halo``-base RIGHT halo lets a run that crosses the right edge
      keep counting — exact for runs up to ``halo`` past the shard end
      (longer runs report the cap; biological hpols sit far below it).
    """
    from variantcalling_tpu.ops import runs as rops

    n = len(codes)
    n_dp = mesh.shape[DATA_AXIS]
    padded, _ = pad_to_multiple(np.asarray(codes, np.uint8), n_dp, fill=fill)
    # a halo is at most one whole neighbor block (ppermute moves block
    # edges, not transitive chains)
    halo = min(halo, len(padded) // n_dp)
    if min_halo is not None and halo < min_halo:
        raise ValueError(
            f"effective halo {halo} (shards of {len(padded) // n_dp}) is below the "
            f"caller's correctness floor {min_halo}; use fewer shards or the "
            "single-device scan for short sequences")

    def body(local):
        ext = halo_exchange_1d(local, 1, halo, fill=fill, n_shards=n_dp)
        starts = rops.run_starts(ext)[1:-halo] if halo else rops.run_starts(ext)[1:]
        lengths = rops.run_lengths(ext)[1:-halo] if halo else rops.run_lengths(ext)[1:]
        return starts, lengths

    fn = shard_map(body, mesh=mesh, in_specs=P(DATA_AXIS),
                   out_specs=(P(DATA_AXIS), P(DATA_AXIS)))
    with mesh:
        starts, lengths = jax.jit(fn)(jnp.asarray(padded))
    starts = np.asarray(starts)[:n]
    lengths = np.asarray(lengths)[:n]
    return starts, lengths
