"""Device-data-parallel scoring dispatch — the filter hot path on a mesh.

After the parallel host-IO work (docs/streaming_executor.md "Parallel
host IO") the streaming filter executor is compute-bound on SCORING; this
module is ROADMAP item 2's answer: score on more than one device. The
run resolves ONE data-parallel mesh plan (``VCTPU_MESH_DEVICES``, next to
the engine and forest-strategy decisions), the fused featurize+score
program runs inside a ``shard_map`` over the mesh ``dp`` axis — each
device scores its shard of a device-count-multiple megabatch with the
run's pinned strategy — and per-chunk scores unpack back into canonical
chunk order before render/writeback.

Byte parity is the hard invariant (the PR 2 contract, extended to the
mesh layout): ``shard_map`` over the data axis is a pure MAP — every
variant's per-tree margins still reduce through the ONE shared
``forest.sequential_tree_sum`` inside its device's program and finalize
through ``forest.finalize_margin`` on the host, and devices exchange
NOTHING (no psum over margins — vctpu-lint VCT009 guards the merge
site), so output bytes are identical at every device count x engine x
strategy. The mesh layout is still recorded: ``##vctpu_mesh=dp=N``
header provenance when N > 1, the journal resume identity pins the
device count (a resume under a different count RESTARTS cleanly — the
header bytes differ, so splicing is impossible by construction), and
per-device obs attribution rides ``score.dN`` profile rows.

Testable on CPU: ``XLA_FLAGS=--xla_force_host_platform_device_count=N``
provides N virtual devices (tests/conftest.py forces 8), so the parity
matrix runs in any container; real multi-host meshes light up through
the PR 5 collectives capability probe.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

import numpy as np

import jax

from variantcalling_tpu import knobs, logger, obs
from variantcalling_tpu.parallel.pipeline import LadderEscalation

#: VCF header key recording the mesh layout of a >1-device run
MESH_HEADER_KEY = "vctpu_mesh"


class MeshDegradeRestart(LadderEscalation):
    """Device OOM survived the megabatch-shrink rung of the recovery
    ladder: the streaming run must RESTART on a dp=1 plan. A mid-run mesh
    change can never splice — the resume identity and the output header
    both pin the mesh layout (PR-2 contract) — so the supervisor
    (``pipelines/filter_variants.run_streaming``) discards the journal
    and re-runs the whole stream single-device (docs/robustness.md
    "Recovery ladder")."""

    def __init__(self, devices: int, cause: BaseException):
        super().__init__(
            f"device OOM survived megabatch shrink at dp={devices}; "
            f"degrading the run to dp=1 ({type(cause).__name__}: {cause})")
        self.devices = devices


def is_oom(exc: BaseException) -> bool:
    """Does this exception look like device-memory exhaustion? XLA
    surfaces OOM as an ``XlaRuntimeError`` whose text leads with the
    ``RESOURCE_EXHAUSTED`` status code (jaxlib does not export a stable
    exception subclass for it), so classification is textual — plus
    Python's own ``MemoryError`` for host-side allocation failures."""
    if isinstance(exc, MemoryError):
        return True
    text = str(exc)
    return "RESOURCE_EXHAUSTED" in text or "out of memory" in text.lower()

#: default per-device megabatch rows when VCTPU_MESH_MEGABATCH_ROWS unset
MEGABATCH_ROWS_PER_DEVICE = 1 << 14


@dataclass(frozen=True)
class MeshPlan:
    """The run-level scoring-mesh decision — resolved ONCE per run by
    ``FilterContext`` next to the engine and forest strategy, then pinned
    into every scoring dispatch, the output header and the journal
    resume identity."""

    devices: int  # resolved dp size; 1 == single-device (no mesh program)
    requested: str  # "auto" or the explicit VCTPU_MESH_DEVICES value
    reason: str  # human-readable resolution rationale

    def header_line(self) -> str:
        return f"##{MESH_HEADER_KEY}=dp={self.devices}"


#: per-process mesh cache: (device count) -> Mesh. Meshes are cheap but
#: NamedSharding/jit caches key on mesh identity — one object per size
#: keeps every consumer (genome upload, chunk device_put, shard_map
#: program) on literally the same mesh. The lock (vctpu-lint VCT010)
#: keeps pool workers racing a cache miss from minting TWO Mesh objects
#: for one size — distinct identities would silently double every jit
#: cache entry keyed on the mesh.
_MESH_CACHE: dict[int, object] = {}
_MESH_CACHE_LOCK = threading.Lock()


def resolve_plan(engine_name: str) -> MeshPlan:
    """Resolve the scoring-mesh plan for a run scored by ``engine_name``.

    Policy (mirrors ``forest.resolve_strategy``): an EXPLICIT
    ``VCTPU_MESH_DEVICES`` is honored or the run dies loudly
    (EngineError, exit 2) — never silently clamped. Auto keeps one
    device on the cpu backend (forced-host CPU meshes are a test/bench
    construct, opted into explicitly) and takes every local device on
    accelerators. The native C++ engine scores on the host — it has no
    XLA program to shard, so any requested mesh resolves to 1 with the
    reason recorded (the parity matrix still runs native legs at forced
    device counts; they are byte-identical by construction).
    """
    from variantcalling_tpu.engine import EngineError

    req = knobs.get_int("VCTPU_MESH_DEVICES")
    requested = "auto" if req is None else str(req)
    if engine_name == "native":
        return MeshPlan(1, requested,
                        "native engine: host C++ walk, no XLA program")
    n_local = len(jax.local_devices())
    if req is not None:
        if req > n_local:
            raise EngineError(
                f"VCTPU_MESH_DEVICES={req} exceeds the {n_local} local "
                "device(s) — shrink the request or force host devices "
                "(XLA_FLAGS=--xla_force_host_platform_device_count=N). "
                "See docs/streaming_executor.md 'Mesh-sharded scoring'.")
        return MeshPlan(req, requested, "explicitly requested")
    try:
        backend = jax.default_backend()
    except Exception as e:  # backend init failure: single device, recorded
        from variantcalling_tpu.utils import degrade

        degrade.record("shard_score.backend_probe", e, fallback="devices=1")
        return MeshPlan(1, requested, "auto: backend probe failed")
    if backend == "cpu":
        return MeshPlan(1, requested,
                        "auto: cpu backend scores single-device "
                        "(set VCTPU_MESH_DEVICES to force a host mesh)")
    return MeshPlan(n_local, requested,
                    f"auto: {backend} backend, all {n_local} local devices")


def mesh_for(plan: MeshPlan):
    """The (dp, mp=1) Mesh of a >1-device plan (None for devices == 1).

    One Mesh object per device count per process — jit/NamedSharding
    caches key on mesh identity, so every consumer must share it."""
    if plan.devices <= 1:
        return None
    mesh = _MESH_CACHE.get(plan.devices)
    if mesh is None:
        from variantcalling_tpu.parallel.mesh import make_mesh

        with _MESH_CACHE_LOCK:
            mesh = _MESH_CACHE.get(plan.devices)
            if mesh is None:
                mesh = make_mesh(n_data=plan.devices, n_model=1,
                                 devices=jax.local_devices()[: plan.devices])
                _MESH_CACHE[plan.devices] = mesh
    return mesh


def shard_program(fn, mesh, n_data_args: int, replicated_leading: int = 0):
    """Wrap an UNJITTED scoring program body in a ``shard_map`` over the
    mesh data axis: the first ``replicated_leading`` arguments are
    replicated (the HBM-resident genome), the next ``n_data_args``
    shard their leading axis over ``dp`` (pytree-prefix specs, so a
    tuple-of-columns argument shards every leaf). The output is the
    per-variant margin/score vector, concatenated over ``dp``.

    This is a pure data-parallel MAP — the body contains no collectives;
    per-tree margins reduce inside each device's program through the one
    sanctioned ``forest.sequential_tree_sum`` (vctpu-lint VCT009 flags
    any cross-device margin reduction introduced here later)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    from variantcalling_tpu.parallel.mesh import DATA_AXIS

    dp = P(DATA_AXIS)
    in_specs = tuple([P()] * replicated_leading + [dp] * n_data_args)
    return shard_map(fn, mesh=mesh, in_specs=in_specs, out_specs=dp)


def resolve_megabatch_rows(devices: int) -> int:
    """Target rows per streaming megabatch: enough to fill every device's
    shard (``MEGABATCH_ROWS_PER_DEVICE`` each) unless overridden."""
    rows = knobs.get_int("VCTPU_MESH_MEGABATCH_ROWS")
    if rows is not None:
        return rows
    return max(1, devices) * MEGABATCH_ROWS_PER_DEVICE


def pack_lengths(lengths: list[int]) -> list[tuple[int, int]]:
    """(start, stop) slices of each chunk inside the packed megabatch —
    canonical chunk order is the packing order, so unpacking is pure
    slicing (no reorder)."""
    spans = []
    lo = 0
    for n in lengths:
        spans.append((lo, lo + n))
        lo += n
    return spans


def unpack_scores(scores: np.ndarray, lengths: list[int]) -> list[np.ndarray]:
    """Split one packed megabatch score vector back into per-chunk score
    arrays, in canonical chunk order."""
    total = sum(lengths)
    if len(scores) != total:
        raise ValueError(
            f"packed scores have {len(scores)} rows, chunks sum to {total}")
    return [scores[lo:hi] for lo, hi in pack_lengths(lengths)]


def megabatch_stream(prepped, ctx, profiler=None):
    """Pack the streaming executor's chunk stream into device-count-sized
    megabatches, score each with ONE mesh dispatch, and yield per-chunk
    ``(table, score, filters)`` items in canonical chunk order.

    ``prepped`` yields ``(table, host_features)`` pairs in chunk order
    (host featurization fans out on the IO pool upstream). Consecutive
    chunks accumulate until the megabatch target
    (:func:`resolve_megabatch_rows`); the group scores through
    ``FilterContext.score_packed`` — one padded, dp-sharded device
    dispatch — and scores unpack back per chunk by slicing, so the
    stage downstream (render/writeback) sees exactly the serial chunk
    sequence. Per-device obs attribution: every dispatch adds a
    ``score.dN`` profile row per device (the devices run the same-shape
    shards in lockstep, so each device row carries the dispatch wall and
    its share of the records; ``vctpu obs bottleneck`` merges the family
    like the ``.wN`` worker families).

    ZERO-WAIT FEED (docs/streaming_executor.md "Overlapped megabatch
    dispatch"): the scoring dispatch runs on a dedicated one-worker
    dispatch pool with at most ONE group in flight — while group N
    scores, this generator keeps pulling ``prepped`` and PACKS group
    N+1, so the dispatch never sits idle waiting for the slowest member
    of the next group to featurize (``score_stage.wait`` was the
    dominant p95 critical-path edge before the overlap, BENCH_r12).
    Results still yield strictly in canonical chunk order: group N's
    scores are drained before group N+1's dispatch is submitted, and
    memory stays bounded at two groups (one in flight + one packing).
    ``VCTPU_MESH_OVERLAP=0`` restores the synchronous pack-then-score
    loop. Recovery semantics are unchanged — the whole ladder runs
    inside the dispatched body, and its escalations
    (:class:`MeshDegradeRestart`) surface when the group is drained.

    SUPERVISED dispatch (docs/robustness.md "Recovery ladder"): a failed
    megabatch never kills the run outright. Device OOM
    (``RESOURCE_EXHAUSTED``) first SHRINKS the packing target (halved for
    the rest of the stream) and re-dispatches the group chunk by chunk;
    a chunk that still OOMs alone escalates to
    :class:`MeshDegradeRestart` (the supervisor restarts the run at
    dp=1). Any other megabatch failure re-dispatches chunk by chunk so a
    poison chunk cannot take its group down with it; the poison chunk
    itself gets the bounded ``retry_chunk`` budget and then either
    fails the run loudly (default) or — ``VCTPU_QUARANTINE=1`` — yields
    a ``(table, None, None)`` quarantine marker for the render stage to
    divert. A ``(table, None)`` pair from upstream (featurize-stage
    quarantine) passes through as the same marker, after flushing the
    pending group so canonical chunk order is preserved.
    """
    import threading
    import time as _time

    from variantcalling_tpu.engine import EngineError
    from variantcalling_tpu.parallel.pipeline import (StageTimeoutError,
                                                      record_quarantine,
                                                      retry_chunk)
    from variantcalling_tpu.utils import faults

    devices = ctx.mesh_plan.devices
    state = {"target": resolve_megabatch_rows(devices)}

    def dispatch(group):
        rows = sum(len(t) for t, _ in group)
        # injection point: the OOM/shrink/degrade ladder is proven
        # against this (tests/unit/test_streaming_faults.py)
        faults.check("xla.dispatch_oom")
        t0 = _time.perf_counter()  # vctpu-lint: disable=VCT006 — obs score-dispatch attribution
        scored = ctx.score_packed(group)
        dt = _time.perf_counter() - t0  # vctpu-lint: disable=VCT006 — obs score-dispatch attribution
        if obs.active():
            obs.span("score_stage", dt, threading.current_thread().name)
            obs.histogram("stage.score_stage.s").observe(dt)
        if obs.tracing():
            # megabatch FAN-IN: one dispatch span, MANY chunk parents —
            # the event lists every member trace id and parents to each
            # member's last span, and every member's cursor advances to
            # this span, so each chunk's DAG walks through the shared
            # dispatch (docs/observability.md "Causal chunk tracing")
            tids = [t for t in (getattr(tab, "_obs_trace", None)
                                for tab, _ in group) if t is not None]
            if tids:
                parents = [c for c in (obs.trace_cursor(t) for t in tids)
                           if c is not None]
                obs.trace_span(tids[0], "score_stage", dt, parents=parents,
                               traces=tids, chunks=len(group), rows=rows)
        if profiler is not None:
            share = rows // devices
            for d in range(devices):
                # lockstep data-parallel shards: each device works the
                # dispatch wall on its share of the rows; the family
                # merges to one `score xN` row at N-device capacity
                profiler.stage(f"score.d{d}").add_work(
                    dt, records=share + (rows - share * devices
                                         if d == devices - 1 else 0))
        return scored

    def quarantined(pair, exc):
        table = pair[0]
        record_quarantine("mesh chunk dispatch", len(table), exc)
        return table, None, None

    def chunk_supervised(pair):
        """One chunk through the per-chunk ladder: bounded re-dispatch,
        then OOM escalation or (opt-in) quarantine. The chunk's trace is
        bound to the thread for the duration so every ladder event
        (chunk_retry, quarantine) links to the chunk it recovers."""
        with obs.trace_scope(getattr(pair[0], "_obs_trace", None)):
            try:
                return retry_chunk(lambda: dispatch([pair]),
                                   "mesh chunk dispatch")
            except (EngineError, StageTimeoutError):
                raise
            # routed through degrade.record (quarantine) or re-raised
            except Exception as e:  # noqa: BLE001  # vctpu-lint: disable=VCT002 — quarantine records via degrade.record; every other path re-raises
                if is_oom(e):
                    raise MeshDegradeRestart(devices, e) from e
                if not knobs.get_bool("VCTPU_QUARANTINE"):
                    raise
                return [quarantined(pair, e)]

    def flush(group):
        try:
            scored = dispatch(group)
        except (EngineError, StageTimeoutError):
            raise
        # recovery ladder — every path below re-dispatches or re-raises
        except Exception as e:  # noqa: BLE001  # vctpu-lint: disable=VCT002 — ladder re-dispatches chunk by chunk; failures re-raise from chunk_supervised
            # causal linkage: every ladder event names the member chunks'
            # traces — the failed megabatch is a fan-in of all of them
            tids = [t for t in (getattr(tab, "_obs_trace", None)
                                for tab, _ in group) if t is not None]
            if is_oom(e):
                # rung: megabatch SHRINK — halve the packing target for
                # the rest of the stream, re-dispatch chunk by chunk
                state["target"] = max(1, state["target"] // 2)
                if obs.active():
                    obs.event("recovery", "megabatch_shrink",
                              rows=sum(len(t) for t, _ in group),
                              new_target=state["target"],
                              trace_ids=tids,
                              error=f"{type(e).__name__}: {e}")
                    obs.counter("recovery.megabatch_shrinks").add(1)
                logger.warning(
                    "mesh megabatch dispatch hit device OOM (%s); shrinking "
                    "the megabatch target to %d rows and re-dispatching "
                    "chunk by chunk", e, state["target"])
            else:
                # rung: megabatch SPLIT — one poison chunk must not take
                # its whole group down with it
                if obs.active():
                    obs.event("recovery", "megabatch_split",
                              chunks=len(group), trace_ids=tids,
                              error=f"{type(e).__name__}: {e}")
                    obs.counter("recovery.megabatch_splits").add(1)
            scored = []
            for pair in group:
                scored.extend(chunk_supervised(pair))
        return list(scored)

    from variantcalling_tpu.parallel.pipeline import IoPool

    pool = IoPool(1, name="vctpu-mesh-dispatch") \
        if knobs.get_bool("VCTPU_MESH_OVERLAP") else None
    pending = None  # the ONE in-flight dispatch future (overlap mode)

    def drain():
        """Results of the in-flight dispatch, in order; re-raises its
        failure (the ladder already ran inside the dispatched body)."""
        nonlocal pending
        if pending is None:
            return []
        out, pending = pending.result(), None
        return out

    group: list = []
    rows = 0
    try:
        for table, hf in prepped:
            if hf is None:
                # featurize-stage quarantine marker from upstream: drain
                # the in-flight dispatch and flush the pending group first
                # (canonical chunk order), then pass the marker straight
                # through to the render/quarantine path
                yield from drain()
                if group:
                    yield from flush(group)
                    group, rows = [], 0
                yield (table, None, None)
                continue
            group.append((table, hf))
            rows += len(table)
            if rows >= state["target"]:
                if pool is None:
                    yield from flush(group)
                else:
                    # overlap: drain group N's results, hand group N+1 to
                    # the dispatch worker, keep packing group N+2 from
                    # ``prepped`` while it scores
                    yield from drain()
                    pending = pool.submit(flush, group)
                group, rows = [], 0
        yield from drain()
        if group:
            yield from flush(group)
    finally:
        if pool is not None:
            pool.shutdown()


def log_plan(plan: MeshPlan) -> None:
    """Record the per-run mesh resolution (obs resolve event + log line),
    the same shape the engine/strategy decisions emit."""
    if obs.active():
        obs.event("resolve", "mesh", value=str(plan.devices),
                  requested=plan.requested, reason=plan.reason)
    if plan.devices > 1:
        logger.info("scoring mesh: dp=%d (%s)", plan.devices, plan.reason)
