"""Rank-partitioned scale-out of the streaming filter (docs/scaleout.md).

PR 8 sharded scoring over a single-process mesh; this module promotes
the whole filter hot path — sharded BGZF ingest, fused scoring, render,
journal, recovery ladder — from one process to N cooperating processes,
the way the GPU-cluster pipeline work (arXiv 2509.09058) scales the same
post-alignment workload across machines: partition the input, run a full
pipeline per rank, merge ordered results.

The pieces:

- :class:`RankPlan` — the run-level rank layout, resolved ONCE next to
  the MeshPlan in ``FilterContext`` (``parallel/distributed.rank`` is
  the one rank spelling: ``VCTPU_RANK`` under the local launcher —
  before any jax init — or ``jax.process_index()`` under a real
  ``jax.distributed`` cluster), recorded as ``##vctpu_ranks=`` output
  provenance and pinned into every rank's journal resume identity.
- **Partition rule**: every rank processes a CONTIGUOUS span of the
  record region, split at line boundaries by one deterministic rule
  (``VcfChunkReader`` ``rank_span`` — byte targets at ``r/N`` of the
  record body, advanced to the next line start), so ranks share no state
  and the concatenation of rank outputs is exactly the serial record
  stream. BGZF inputs split at member boundaries (``scan_block_spans``)
  and each rank inflates only ~its share.
- **Rank segments**: rank ``r`` runs the UNCHANGED streaming executor
  against ``<out>.rank{r}of{N}.seg`` — plain text even for ``.gz``
  outputs (compression is deferred to the seam-aware commit), with its
  own chunk journal, so a SIGKILLed rank resumes from ITS journal while
  finished ranks skip via their ``.done`` markers.
- **Rank-sequenced commit** (:func:`merge_ranks`): verifies every
  segment + marker, streams ``header + body_0 + body_1 + ...`` through
  the atomic ``.partial`` + ``os.replace`` protocol; ``.gz`` outputs
  re-compress through ONE :class:`~variantcalling_tpu.io.bgzf.
  BgzfChunkCompressor` so the 65280-byte block carry is re-carried
  deterministically across rank seams — the framing is byte-identical
  to a serial writer of the same stream by the PR 7 carry contract,
  never new framing invented at the seam.

Byte contract: the merged output is identical to the single-rank run
modulo the ``##vctpu_*`` provenance headers (the ``##vctpu_ranks=``
line exists only when N > 1) — locked by the parity matrix in
``tests/unit/test_rank_plan.py`` / ``tests/system/test_scaleout.py``
and by the bench ``scaleout`` digest tripwire.

Launchers: ``tools/podrun`` spawns N local workers with
``VCTPU_RANK``/``VCTPU_NUM_PROCESSES`` set and commits the merge;
``vctpu merge-ranks <out>`` is the standalone commit step; under a real
``jax.distributed`` cluster rank 0 commits after a collective barrier.
"""

from __future__ import annotations

import json
import os
import zlib
from dataclasses import dataclass

from variantcalling_tpu import knobs, logger, obs
from variantcalling_tpu.engine import EngineError

RANKS_HEADER_KEY = "vctpu_ranks"

#: decompressed bytes per merge copy block (bounds merge memory)
_MERGE_BLOCK = 8 << 20


class MergeError(RuntimeError):
    """A rank-merge precondition failed (missing/invalid/mismatched
    segments) — CLI exit 3, distinct from config errors (2)."""


@dataclass(frozen=True)
class RankPlan:
    """The run-level rank layout (docs/scaleout.md).

    Elastic pods (docs/scaleout.md "Elastic membership") add the
    ``span`` spelling: a worker leased an absolute target interval
    ``[lo, hi)`` of the decompressed record region runs as a
    single-rank plan (``ranks=1``) whose reader is span-bounded —
    so its segment header carries NO ``##vctpu_ranks=`` line and the
    merged bytes stay identical to the serial run whatever the final
    span plan looks like. ``gen`` is the lease generation the
    coordinator offered this span under (``parallel/elastic.py``)."""

    ranks: int
    rank: int
    source: str  # "env" (local launcher) | "distributed" | "span" | "single"
    reason: str
    span: tuple | None = None  # absolute (lo, hi) byte targets
    gen: int = 0  # lease generation of an elastic offer

    def header_line(self) -> str:
        # n only — never the rank id: every rank's segment must emit
        # byte-identical header bytes or the seam commit cannot verify
        # cross-rank config agreement
        return f"##{RANKS_HEADER_KEY}=n={self.ranks}"


def resolve() -> RankPlan:
    """Resolve THIS process's rank layout, once per run.

    ``VCTPU_SPAN`` (``lo:hi:gen``, the elastic launcher's spelling —
    ``parallel/elastic.py``) wins first: the worker is one leased span
    of an elastic pod, running as a single-rank plan with a
    span-bounded reader. ``VCTPU_RANK`` (+ ``VCTPU_NUM_PROCESSES``) is
    the classic local launcher's spelling and is read BEFORE any jax
    init; without it, an initialized ``jax.distributed`` runtime
    (coordinator/auto mode) supplies the layout; everything else is the
    single plan. An out-of-range rank is a configuration error
    (exit 2), never a clamp."""
    s = knobs.get_str("VCTPU_SPAN")
    r = knobs.get_int("VCTPU_RANK")
    if s:
        if r is not None:
            raise EngineError(
                "VCTPU_SPAN and VCTPU_RANK are both set — a worker is "
                "either one leased span of an elastic pod or one rank "
                "of a static pod, never both (docs/scaleout.md)")
        from variantcalling_tpu.parallel import elastic

        lo, hi, gen = elastic.parse_span_env(s)
        return RankPlan(ranks=1, rank=0, source="span",
                        reason="VCTPU_SPAN (elastic launcher)",
                        span=(lo, hi), gen=gen)
    if r is not None:
        n = knobs.get_int("VCTPU_NUM_PROCESSES")
        if n is None:
            raise EngineError(
                "VCTPU_RANK is set but VCTPU_NUM_PROCESSES is not — a "
                "rank-partitioned launch needs both (tools/podrun sets "
                "them; see docs/scaleout.md)")
        if r >= n:
            raise EngineError(
                f"VCTPU_RANK={r} is out of range for "
                f"VCTPU_NUM_PROCESSES={n} (ranks are 0-based)")
        return RankPlan(ranks=n, rank=r, source="env",
                        reason="VCTPU_RANK/VCTPU_NUM_PROCESSES (local "
                               "launcher)")
    try:
        import jax

        n = jax.process_count()
        if n > 1:
            return RankPlan(ranks=n, rank=jax.process_index(),
                            source="distributed",
                            reason="jax.distributed runtime")
    except Exception as e:  # noqa: BLE001 — uninitialized backend == single process
        from variantcalling_tpu.utils import degrade

        degrade.record("rank_plan.process_count_probe", e,
                       fallback="single-rank plan")
    return RankPlan(ranks=1, rank=0, source="single",
                    reason="single process")


def log_plan(plan: RankPlan) -> None:
    """Announce a resolved multi-rank plan (obs ``resolve`` event + log);
    single-rank plans stay silent, like the mesh plan. Elastic span
    plans announce their leased interval instead of a rank id."""
    if plan.span is not None:
        logger.info("span plan: [%d,%d) gen %d (%s)", plan.span[0],
                    plan.span[1], plan.gen, plan.reason)
        if obs.active():
            obs.event("resolve", "span_plan",
                      value=f"[{plan.span[0]},{plan.span[1]})",
                      gen=plan.gen, source=plan.source, reason=plan.reason)
        return
    if plan.ranks <= 1:
        return
    logger.info("rank plan: rank %d of %d (%s)", plan.rank, plan.ranks,
                plan.reason)
    if obs.active():
        obs.event("resolve", "rank_plan", value=plan.ranks, rank=plan.rank,
                  source=plan.source, reason=plan.reason)


# ---------------------------------------------------------------------------
# rank segments: paths, completion markers
# ---------------------------------------------------------------------------


def segment_path(out_path: str, rank: int, ranks: int) -> str:
    """Rank ``rank``'s output segment next to the final destination.
    Plain text whatever the destination container — compression happens
    once, at the seam-aware merge."""
    return f"{out_path}.rank{rank}of{ranks}.seg"


def marker_path(seg_path: str) -> str:
    return seg_path + ".done"


def discover_ranks(out_path: str) -> int | None:
    """Infer N from the ``<out>.rank*of*.seg`` siblings on disk (the
    ``vctpu merge-ranks`` no-flag path); None when no segments exist,
    :class:`MergeError` when siblings disagree on N."""
    import glob
    import re

    ns = set()
    for p in glob.glob(glob.escape(str(out_path)) + ".rank*of*.seg"):
        m = re.search(r"\.rank(\d+)of(\d+)\.seg$", p)
        if m:
            ns.add(int(m.group(2)))
    if not ns:
        return None
    if len(ns) > 1:
        raise MergeError(
            f"segments next to {out_path} disagree on the rank count "
            f"({sorted(ns)}) — stale leftovers of a different launch; "
            "remove them or pass --ranks explicitly")
    return ns.pop()


def contig_spans(path: str, n: int, header_end: int | None = None,
                 total: int | None = None,
                 slack: float = 0.2) -> list[tuple[int, int]]:
    """Contig-aware span plan over the record region of a PLAIN-text
    VCF: cut at ~equal byte targets advanced to the next line start
    (the rank-partition rule), then — when a contig boundary lies
    within ``slack`` of the span size past the cut — snap the cut to
    that boundary, so a contig's records land on ONE worker and its
    reference-genome cache stays hot (the serving-fabric placement
    rule, docs/serving_fabric.md). The snap only ever moves a cut
    forward to another line start, so the spans still tile the record
    region exactly and the concatenation of span outputs remains the
    serial record stream whatever the snaps did."""
    if header_end is None or total is None:
        from variantcalling_tpu.io import vcf as vcf_mod

        header_end, total = vcf_mod.scan_record_region(path)
    body = total - header_end
    if body <= 0 or n <= 1:
        return [(header_end, total)]
    n = min(n, body)
    budget = max(1, int(body / n * slack))
    cuts: list[int] = []
    with open(path, "rb") as fh:
        for i in range(1, n):
            cut = _line_start(fh, header_end + (body * i) // n, total)
            cuts.append(_snap_to_contig(fh, cut, total, budget))
    edges = [header_end] + sorted(set(cuts)) + [total]
    return [(lo, hi) for lo, hi in zip(edges, edges[1:]) if hi > lo]


def _line_start(fh, target: int, total: int) -> int:
    """Advance ``target`` to the next line start at or after it (the
    VcfChunkReader rank_span rule: a cut never tears a record)."""
    if target <= 0:
        return 0
    fh.seek(target - 1)
    off = target - 1
    while off < total:
        block = fh.read(min(1 << 16, total - off))
        if not block:
            break
        nl = block.find(b"\n")
        if nl >= 0:
            return min(off + nl + 1, total)
        off += len(block)
    return total


def _snap_to_contig(fh, cut: int, total: int, budget: int) -> int:
    """Move a line-start cut forward to the first contig change within
    ``budget`` bytes; keep the plain cut when the contig runs past the
    budget (locality is best effort, balance is not negotiable)."""
    fh.seek(cut)
    scanned = 0
    first_contig = None
    pos = cut
    while pos < total and scanned <= budget:
        line = fh.readline()
        if not line:
            break
        contig = line.split(b"\t", 1)[0]
        if first_contig is None:
            first_contig = contig
        elif contig != first_contig:
            return pos  # the boundary: records of the next contig start here
        pos += len(line)
        scanned += len(line)
    return cut


def segment_identity(args, plan: RankPlan,
                     engine_name: str | None = None) -> dict:
    """The identity a completed segment is valid FOR: input + model +
    every scoring flag + the rank layout + the engine-selection env.
    Built from the SAME ``io/identity.scoring_fields`` dict the
    streaming resume journal and the chunk cache use (one source of
    truth for "what makes scored bytes a pure function of input") — a
    relaunch under any changed configuration recomputes instead of
    reusing a stale segment."""
    from variantcalling_tpu.io import identity as identity_mod

    ident = identity_mod.scoring_fields(args)
    ident["input"] = identity_mod.file_sig(args.input_file)
    ident["ranks"] = [plan.rank, plan.ranks]
    if plan.span is not None:
        # elastic span workers: the segment is valid for exactly the
        # leased target interval — a re-cut span recomputes (or adopts
        # the handed-off journal), never reuses a different interval's
        # bytes. The splice masks BOTH partition fields when checking
        # cross-segment config agreement.
        ident["span"] = [int(plan.span[0]), int(plan.span[1])]
    # engine-selection env: resolved engine name + the raw strategy/
    # mesh requests — they change the segment's provenance HEADER
    # bytes, so a stale segment under a different selection must
    # recompute (the merge's header equality check backstops this
    # across ranks; identity catches the all-ranks-stale case)
    ident["engine"] = engine_name
    ident["forest_strategy"] = knobs.raw("VCTPU_FOREST_STRATEGY") or "auto"
    ident["mesh_devices"] = knobs.raw("VCTPU_MESH_DEVICES")
    return ident


def write_marker(seg_path: str, identity: dict, stats: dict) -> None:
    """Atomically record a segment's completion: identity + byte length
    + whole-segment CRC + the run stats (for skip-path logging)."""
    doc = {
        "identity": identity,
        "bytes": os.path.getsize(seg_path),
        "crc32": _file_crc(seg_path),
        "stats": {k: stats.get(k) for k in ("n", "n_pass", "chunks")},
    }
    tmp = marker_path(seg_path) + ".tmp"
    with open(tmp, "w", encoding="utf-8") as fh:
        json.dump(doc, fh, sort_keys=True)
        fh.write("\n")
    os.replace(tmp, marker_path(seg_path))


def _file_crc(path: str) -> int:
    crc = 0
    with open(path, "rb") as fh:
        while True:
            block = fh.read(_MERGE_BLOCK)
            if not block:
                return crc & 0xFFFFFFFF
            crc = zlib.crc32(block, crc)


def load_marker(seg_path: str) -> dict | None:
    try:
        with open(marker_path(seg_path), encoding="utf-8") as fh:
            doc = json.load(fh)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) else None


def valid_segment(seg_path: str, identity: dict) -> dict | None:
    """The completed-segment skip check (a relaunch after a partial pod
    failure must not recompute finished ranks): marker present, identity
    equal, segment length matching; ``VCTPU_RESUME_VERIFY=full``
    additionally re-reads and CRC-checks the whole segment (the journal
    v2 rule). Returns the recorded stats, or None → recompute."""
    doc = load_marker(seg_path)
    if doc is None or doc.get("identity") != identity:
        return None
    try:
        size = os.path.getsize(seg_path)
    except OSError:
        return None
    if size != doc.get("bytes"):
        return None
    if knobs.get_str("VCTPU_RESUME_VERIFY") == "full" \
            and _file_crc(seg_path) != doc.get("crc32"):
        logger.info("rank segment %s: CRC mismatch (full verify) — "
                    "recomputing", seg_path)
        return None
    stats = doc.get("stats")
    return stats if isinstance(stats, dict) else {}


def discard_segments(out_path: str) -> None:
    """Remove every rank segment + marker next to ``out_path`` (the
    post-commit sweep, and the chaos harness's between-leg cleanup)."""
    import glob

    for p in glob.glob(glob.escape(str(out_path)) + ".rank*of*.seg*"):
        try:
            os.remove(p)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# the rank-sequenced committer
# ---------------------------------------------------------------------------


def _header_len(path: str) -> int:
    """Byte length of the VCF header (every leading ``#``-prefixed
    line) of ``path`` — the split point between a segment's header copy
    and its record body."""
    size = os.path.getsize(path)
    cap = 1 << 20
    with open(path, "rb") as fh:
        while True:
            fh.seek(0)
            head = fh.read(min(cap, size))
            end = 0
            torn = False
            while end < len(head):
                if head[end:end + 1] != b"#":
                    return end
                nl = head.find(b"\n", end)
                if nl < 0:
                    torn = True  # header line crosses the read window
                    break
                end = nl + 1
            if not torn and cap >= size:
                return end  # header-only segment (an empty rank span)
            if cap >= size:
                raise MergeError(
                    f"{path}: unterminated header line — truncated segment")
            cap *= 8


def merge_ranks(out_path: str, ranks: int | None = None,
                cleanup: bool = True) -> dict:
    """The rank-sequenced commit: merge every rank's segment into the
    final destination, byte-identical to the single-rank run of the same
    header modulo nothing — the segments ARE the serial record stream in
    rank order.

    Plain destinations concatenate ``header + body_0 + ... + body_{N-1}``;
    ``.gz`` destinations stream the same bytes through ONE
    :class:`~variantcalling_tpu.io.bgzf.BgzfChunkCompressor`, so the
    65280-byte block carry crosses every rank seam exactly as a serial
    writer's would (the PR 7 framing contract — the carry is a pure
    function of cumulative stream length, and the committer re-carries
    it at the seams rather than inventing new framing). The write rides
    the run-unique ``.partial`` + atomic ``os.replace`` protocol, so a
    killed merge never tears the destination.

    Raises :class:`MergeError` when a segment is missing, its marker is
    absent/stale, or rank headers disagree (cross-rank config drift).
    """
    out_path = str(out_path)
    if ranks is None:
        ranks = discover_ranks(out_path)
        if ranks is None:
            raise MergeError(f"no rank segments found next to {out_path}")
    segs = [(f"rank {r}/{ranks}", segment_path(out_path, r, ranks))
            for r in range(ranks)]
    total, markers = splice_segments(out_path, segs)
    stats = {
        "ranks": ranks,
        "bytes": total,
        "n": sum(int((m.get("stats") or {}).get("n") or 0)
                 for m in markers),
        "n_pass": sum(int((m.get("stats") or {}).get("n_pass") or 0)
                      for m in markers),
    }
    if obs.active():
        obs.event("journal", "rank_merge", ranks=ranks, bytes=total,
                  records=stats["n"])
    if cleanup:
        discard_segments(out_path)
    logger.info("merged %d rank segments -> %s (%d records, %d bytes "
                "uncompressed)", ranks, out_path, stats["n"], total)
    return stats


def splice_segments(out_path: str,
                    segs: list[tuple[str, str]]) -> tuple[int, list[dict]]:
    """The seam-aware splice core shared by :func:`merge_ranks` and the
    elastic span committer (``parallel/elastic.merge_spans``): verify
    every ``(label, path)`` segment — present, sealed by a ``.done``
    marker, length-consistent with it, produced under ONE configuration
    modulo the partition fields (``ranks``/``span`` are exactly what may
    legitimately differ across a plan), identical header bytes — then
    stream ``header + body_0 + ... + body_{k-1}`` into ``out_path``
    through the run-unique ``.partial`` + atomic ``os.replace``
    protocol. ``.gz`` destinations re-compress through ONE
    :class:`~variantcalling_tpu.io.bgzf.BgzfChunkCompressor` so the
    65280-byte block carry is re-carried across however many seams the
    final plan has. Returns ``(uncompressed_bytes, markers)``."""
    if not segs:
        raise MergeError(f"empty segment plan for {out_path}")
    markers = []
    for label, seg in segs:
        if not os.path.exists(seg):
            raise MergeError(
                f"{label} segment missing: {seg} — that worker has not "
                "completed (relaunch it; finished workers skip via their "
                ".done markers)")
        doc = load_marker(seg)
        if doc is None:
            raise MergeError(
                f"{label} completion marker missing/unreadable "
                f"({marker_path(seg)}) — the segment may be mid-write")
        if os.path.getsize(seg) != doc.get("bytes"):
            raise MergeError(
                f"{label} segment length disagrees with its "
                "marker — torn or concurrently-written segment")
        markers.append(doc)
    idents = {json.dumps(dict(m.get("identity") or {}, ranks=None,
                              span=None), sort_keys=True) for m in markers}
    if len(idents) > 1:
        raise MergeError(
            "segments were produced under DIFFERENT configurations "
            "(identity mismatch across markers) — refusing to splice them")

    header_lens = [_header_len(seg) for _, seg in segs]
    with open(segs[0][1], "rb") as fh:
        header = fh.read(header_lens[0])
    for i in range(1, len(segs)):
        with open(segs[i][1], "rb") as fh:
            if fh.read(header_lens[i]) != header:
                raise MergeError(
                    f"{segs[i][0]} segment header differs from "
                    f"{segs[0][0]}'s — cross-worker configuration drift; "
                    "refusing to splice")

    from variantcalling_tpu.io import journal as journal_mod

    gz = out_path.endswith(".gz")
    token = journal_mod.new_partial_token()
    journal_mod.claim_token(token)
    part = journal_mod.partial_path(out_path, token)
    total = 0
    try:
        with open(part, "wb") as sink:
            if gz:
                from variantcalling_tpu.io.bgzf import BgzfChunkCompressor

                comp = BgzfChunkCompressor()
                sink.write(comp.add(header))
            else:
                sink.write(header)
            total += len(header)
            for i, (_, seg) in enumerate(segs):
                with open(seg, "rb") as fh:
                    fh.seek(header_lens[i])
                    while True:
                        block = fh.read(_MERGE_BLOCK)
                        if not block:
                            break
                        total += len(block)
                        sink.write(comp.add(block) if gz else block)
            if gz:
                sink.write(comp.finish())
        os.replace(part, out_path)  # the one atomic commit of the merge
    except BaseException:
        journal_mod.release_token(token)
        try:
            os.remove(part)
        except OSError:
            pass
        raise
    journal_mod.release_token(token)
    if gz:
        from variantcalling_tpu.io.tabix import build_tabix_index

        try:
            build_tabix_index(out_path)
        except (ValueError, OSError):
            pass  # unsorted/odd inputs: the VCF itself is still valid
    return total, markers


# ---------------------------------------------------------------------------
# the per-rank scale-out driver
# ---------------------------------------------------------------------------


def scaleout_eligible(args) -> bool:
    """Can this job run rank-partitioned? Same gate as the streaming
    executor minus the single-process requirement (a rank IS one of N
    processes by design)."""
    from variantcalling_tpu.pipelines.filter_variants import \
        streaming_eligible

    return streaming_eligible(getattr(args, "limit_to_contig", None),
                              allow_multiprocess=True)


def run_scaleout(args, model, fasta, annotate, blacklist, engine=None,
                 plan: RankPlan | None = None) -> int:
    """One rank's worth of a rank-partitioned filter run: compute (or
    skip, when a valid ``.done`` marker proves a previous launch already
    did) this rank's segment, then commit per the plan's source —
    ``distributed`` runs barrier and rank 0 merges; under the local
    launcher the merge belongs to ``tools/podrun`` (or a standalone
    ``vctpu merge-ranks``), because env-launched workers share no
    collectives to barrier on."""
    from variantcalling_tpu.pipelines import filter_variants as fv

    plan = plan or resolve()
    out_path = str(args.output_file)
    if plan.span is not None:
        from variantcalling_tpu.parallel import elastic

        seg = elastic.span_segment_path(out_path, plan.span[0],
                                        plan.span[1])
        # single-claimant lease: claimed BEFORE any compute or skip
        # check, so two workers offered the same (span, generation) can
        # never render the same segment — the loser exits
        # EXIT_LEASE_LOST (6), benign to the coordinator
        if not elastic.claim_lease(seg, plan.gen):
            raise elastic.LeaseLost(
                f"span [{plan.span[0]},{plan.span[1]}) generation "
                f"{plan.gen}: lease already claimed "
                f"({elastic.lease_path(seg, plan.gen)})")
    else:
        seg = segment_path(out_path, plan.rank, plan.ranks)
    identity = segment_identity(args, plan,
                                engine.name if engine is not None else None)
    prior = valid_segment(seg, identity)
    if prior is not None:
        logger.info("rank %d/%d: segment already complete (%s records) — "
                    "skipping compute", plan.rank, plan.ranks,
                    prior.get("n", "?"))
        if obs.active():
            obs.event("journal", "segment_skip", rank=plan.rank,
                      ranks=plan.ranks, records=prior.get("n"))
        stats = prior
    else:
        import argparse

        args2 = argparse.Namespace(**vars(args))
        args2.output_file = seg
        stats = fv.run_streaming(args2, model, fasta, annotate, blacklist,
                                 engine=engine, rank_plan=plan)
        if stats is None:
            raise EngineError(
                "rank-partitioned scale-out requires the streaming "
                "executor (native engine built, VCTPU_STREAM=1, "
                "VCTPU_THREADS>1, no --limit_to_contig) — rerun "
                "single-rank or fix the configuration; docs/scaleout.md")
        write_marker(seg, identity, stats)
        logger.info("rank %d/%d: wrote segment %s (%d records, %d PASS)",
                    plan.rank, plan.ranks, seg, stats["n"], stats["n_pass"])
    if plan.source == "distributed":
        import numpy as np

        from variantcalling_tpu.parallel import distributed as dist

        # pod-wide completion barrier: the gather returns only when every
        # rank's segment landed, so rank 0's merge can never read a
        # mid-write sibling
        dist.allgather_concat(np.asarray([plan.rank], dtype=np.int32))
        if plan.rank == 0:
            merged = merge_ranks(out_path, plan.ranks)
            logger.info("wrote %s: %d variants, %d PASS (%d ranks)",
                        out_path, merged["n"], merged["n_pass"],
                        plan.ranks)
        else:
            logger.info("rank %d/%d: commit delegated to rank 0",
                        plan.rank, plan.ranks)
    else:
        logger.info("rank %d/%d: segment staged; the launcher commits the "
                    "merge (tools/podrun, or `vctpu merge-ranks %s`)",
                    plan.rank, plan.ranks, out_path)
    return 0


# ---------------------------------------------------------------------------
# ``vctpu merge-ranks`` — the standalone commit step
# ---------------------------------------------------------------------------


def run(argv: list[str]) -> int:
    """CLI: merge staged rank segments into the final output.

    Exit 0 on a committed merge, 2 on usage/config errors, 3 when the
    segments are not mergeable (missing rank, stale marker, cross-rank
    drift) — distinct so a launcher can tell "relaunch the ranks" from
    "fix the invocation"."""
    import argparse
    import sys

    ap = argparse.ArgumentParser(
        prog="vctpu merge-ranks",
        description="rank-sequenced commit: merge <out>.rankNofM.seg "
                    "segments into the final output (docs/scaleout.md)")
    ap.add_argument("output_file",
                    help="the FINAL destination path the workers targeted")
    ap.add_argument("--ranks", type=int, default=None,
                    help="expected rank count (default: inferred from the "
                         "segments on disk)")
    ap.add_argument("--keep-segments", action="store_true",
                    help="keep the per-rank segments + markers after the "
                         "merge (default: swept)")
    args = ap.parse_args(argv)
    if args.ranks is not None and args.ranks <= 0:
        print("error: --ranks must be positive", file=sys.stderr)
        return 2
    try:
        stats = merge_ranks(args.output_file, ranks=args.ranks,
                            cleanup=not args.keep_segments)
    except MergeError as e:
        print(f"error: {e}", file=sys.stderr)
        return 3
    print(f"wrote {args.output_file}: {stats['n']} variants, "
          f"{stats['n_pass']} PASS from {stats['ranks']} rank segments")
    return 0
