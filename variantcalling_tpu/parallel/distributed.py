"""Multi-host runtime: jax.distributed + global meshes + host-local bridging.

The reference's only "distribution" is the filesystem between cromwell
tasks (SURVEY §2.4/§5.8). Here multi-host scale rides JAX's distributed
runtime: every host (process) initializes against one coordinator, the
mesh spans ALL hosts' devices, and cross-host reductions are the same XLA
collectives the single-host mesh uses — psum over ICI within a slice, DCN
between slices, never the filesystem.

Wire-up is env-driven so every CLI tool becomes multi-host without new
flags: launch N copies of the same command with

    VCTPU_COORDINATOR=host0:9731 VCTPU_NUM_PROCESSES=N VCTPU_PROCESS_ID=i

(or rely on JAX's own cluster auto-detection on TPU pods, where
``jax.distributed.initialize()`` needs no arguments).

Proven end to end by tests/system/test_multihost.py: two actual
processes, each holding 4 virtual CPU devices, form one 8-device mesh
and psum host-local SEC sample shards into the identical cohort tensor
on both hosts.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, PartitionSpec as P

from variantcalling_tpu import knobs
from variantcalling_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

_INITIALIZED = False


def rank() -> int:
    """This process's rank, resolved from the env BEFORE any jax init.

    Resolution order: ``VCTPU_RANK`` (the local scale-out launcher,
    tools/podrun — no jax.distributed, no backend init), then
    ``jax.process_index()`` guarded — an uninitialized/failed backend
    means single-process, i.e. rank 0. The ONE rank spelling: obs log
    suffixing (``obs._rank_suffixed``) and the RankPlan resolution
    (``parallel/rank_plan.py``) agree with this by construction — a
    coordinator-mode launch (``VCTPU_PROCESS_ID``) counts as ranked
    only once ``jax.distributed`` actually initialized, so a
    half-configured env can never make the telemetry claim a rank
    separation the work assignment does not have.
    """
    r = knobs.get_int("VCTPU_RANK")
    if r is not None:
        return r
    try:
        return jax.process_index()
    except Exception:  # noqa: BLE001 # vctpu-lint: disable=VCT002 — uninitialized backend == single process == rank 0 by contract
        return 0


def init_from_env() -> bool:
    """Initialize jax.distributed when the env asks for it; idempotent.

    Returns True when running multi-host (after initialization)."""
    global _INITIALIZED
    if _INITIALIZED:
        return jax.process_count() > 1
    coord = knobs.get_str("VCTPU_COORDINATOR")
    if coord:
        missing = [k for k in ("VCTPU_NUM_PROCESSES", "VCTPU_PROCESS_ID")
                   if knobs.get_int(k) is None]
        if missing:
            raise SystemExit(
                f"VCTPU_COORDINATOR is set but {', '.join(missing)} is not — a "
                "multi-host launch needs all three of VCTPU_COORDINATOR, "
                "VCTPU_NUM_PROCESSES, VCTPU_PROCESS_ID")
        jax.distributed.initialize(
            coordinator_address=coord,
            num_processes=knobs.get_int("VCTPU_NUM_PROCESSES"),
            process_id=knobs.get_int("VCTPU_PROCESS_ID"),
        )
        _INITIALIZED = True
        return True
    if knobs.get_bool("VCTPU_AUTO_DISTRIBUTED"):  # matching the CLI gate
        # TPU pods: coordinator/topology come from the cluster environment
        jax.distributed.initialize()
        _INITIALIZED = True
        return jax.process_count() > 1
    return False


def global_mesh(n_model: int = 1) -> Mesh:
    """(dp, mp) mesh over EVERY host's devices (jax.devices() is global
    after jax.distributed.initialize)."""
    devices = jax.devices()
    n_data = len(devices) // n_model
    return Mesh(np.asarray(devices[: n_data * n_model]).reshape(n_data, n_model),
                (DATA_AXIS, MODEL_AXIS))


def host_local_to_global(local: np.ndarray, mesh: Mesh, spec: P) -> jax.Array:
    """Each host contributes its local block of the leading axis; the
    result is one global sharded array."""
    from jax.experimental import multihost_utils

    return multihost_utils.host_local_array_to_global_array(local, mesh, spec)


def replicated_to_host(arr: jax.Array) -> np.ndarray:
    """Fetch a replicated global array on any host."""
    return np.asarray(arr.addressable_data(0))


def allgather_strings(local: list[str]) -> list[str]:
    """Every host's strings, rank order preserved (duplicates kept).

    Encoded as newline-terminated bytes so rank boundaries cannot merge
    adjacent names; empty ranks contribute nothing.
    """
    if jax.process_count() <= 1:
        return list(local)
    blob = "".join(s + "\n" for s in local).encode()
    gathered = allgather_concat(np.frombuffer(blob, dtype=np.uint8))
    text = bytes(bytearray(gathered.tolist())).decode()
    return [s for s in text.split("\n") if s]


def allgather_concat(local: np.ndarray) -> np.ndarray:
    """Concatenate every host's (possibly different-length) 1-D array.

    Two collectives: byte lengths first, then the value BYTES padded to
    the max length (process_allgather needs uniform shapes, and jax
    without x64 would silently truncate int64 values — packed locus keys
    exceed int32, so the wire format is uint8). Single-process returns
    the input unchanged.
    """
    # injection point "dist.rank_timeout": THIS rank enters the collective
    # late (cancellable delay) — proves a slow rank delays but does not
    # corrupt/deadlock the gather (the collective itself synchronizes)
    from variantcalling_tpu.utils import faults

    faults.check("dist.rank_timeout")
    local = np.ascontiguousarray(local)
    if jax.process_count() <= 1:
        return local
    from jax.experimental import multihost_utils

    from variantcalling_tpu.utils.trace import stage

    # collective timing: a straggling rank shows up as a long span here
    # on every OTHER rank (the gather synchronizes), so the obs streams
    # localize multi-host skew without a pod-level profiler
    with stage("dist.allgather_concat"):
        raw = local.view(np.uint8).reshape(-1) if local.size else np.zeros(0, np.uint8)
        lengths = np.asarray(multihost_utils.process_allgather(
            np.asarray([len(raw)], dtype=np.int32))).reshape(-1)
        m = int(lengths.max())
        padded = np.pad(raw, (0, m - len(raw)))
        gathered = np.asarray(multihost_utils.process_allgather(padded))
        blob = b"".join(gathered[p, : int(lengths[p])].tobytes() for p in range(len(lengths)))
        return np.frombuffer(blob, dtype=local.dtype)


def aggregate_counts_across_hosts(local_counts: np.ndarray, mesh: Mesh | None = None) -> np.ndarray:
    """Cohort (L, A) sum of per-sample (S_local, L, A) counts held by EACH
    host — BASELINE config 5 at pod scale: the sample axis spans hosts and
    the reduction is one psum over the global mesh (ICI/DCN), no host
    gather, no intermediate files.

    Every host must call this collectively (same (L, A) trailing shape;
    S_local may differ per host — including ZERO — and need not divide the
    local device count: hosts agree on one per-device shard size (the
    global max, via process_allgather) and zero-pad to it, so every
    device holds the same-shape block and zeros are invisible to the
    sum); each host returns the full cohort tensor.
    """
    from variantcalling_tpu.parallel.mesh import mesh_sum_leading

    mesh = mesh or global_mesh(n_model=1)
    local_counts = np.asarray(local_counts)
    n_local_dev = len(jax.local_devices())
    # host_local_array_to_global_array derives the GLOBAL shape from each
    # process's own local block, so ragged hosts (5-vs-4 samples, or an
    # empty rank) must first agree on a common per-device shard size —
    # otherwise ranks disagree on the global array and the collective
    # deadlocks (or an empty rank silently returns zeros)
    per_dev = -(-local_counts.shape[0] // n_local_dev)  # ceil; 0 for empty
    per_dev = int(allgather_concat(np.asarray([per_dev], dtype=np.int32)).max())
    pad = per_dev * n_local_dev - local_counts.shape[0]
    if pad:
        local_counts = np.concatenate(
            [local_counts, np.zeros((pad, *local_counts.shape[1:]), local_counts.dtype)])
    arr = host_local_to_global(local_counts, mesh, P(DATA_AXIS, None, None))
    # the reduction itself is the ONE shared device-put + replicated mesh
    # sum (parallel/mesh.mesh_sum_leading) — identical program to the
    # single-host SEC aggregation, here over a global multi-host mesh
    return mesh_sum_leading(mesh, arr, "dist.aggregate_counts_psum")
