"""Bounded-queue ordered stage executor — the host-side streaming pipeline.

The flagship filter path used to run its three host stages strictly in
sequence (whole-file ingest -> featurize+score -> whole-file writeback),
so end-to-end wall time was the SUM of the stages even though each stage
leaves cores idle (ingest/writeback are I/O-and-glue heavy, scoring is
compute heavy). This executor runs the stages as a chunked pipeline over
sequence-numbered items: one worker thread per stage, bounded queues
between stages, results consumed strictly in submission order. Stage time
then hides behind the slowest stage instead of summing — the same
argument the GPU variant-calling pipeline literature makes for overlapping
I/O around the compute kernel (PAPERS.md, "Optimizing the Variant Calling
Pipeline Execution ... Using GPU-Enabled Machines"; GenPIP's stage fusion).

Design rules:

- one thread per stage, FIFO queues: per-stage order is preserved by
  construction, so output ordering needs no reorder buffer — items leave
  the last stage in exactly the order the source yielded them (each item
  carries its sequence number and the consumer asserts it);
- bounded queues (``queue_depth``): at most ``queue_depth`` items wait
  between any two stages, so peak memory is O(stages * queue_depth *
  chunk), never O(input);
- ``VCTPU_THREADS=1`` (or a single-core host) degrades to a plain serial
  loop through the same stage callables — byte-identical results, no
  threads, no queues;
- a stage exception cancels the whole pipeline promptly (stop event +
  queue drain) and re-raises in the consumer;
- a WATCHDOG (``timeout`` / ``VCTPU_STAGE_TIMEOUT_S``) bounds how long the
  consumer waits without any pipeline progress: a hung stage (wedged
  native call, dead filesystem) raises :class:`StageTimeoutError` naming
  the stuck stage instead of deadlocking the run, with queues drained and
  every joinable worker joined on the way out (failure semantics locked
  by ``tests/unit/test_streaming_faults.py``).

The GIL is not a problem here: stage bodies are native engine calls,
numpy, and file I/O, all of which release it.
"""

from __future__ import annotations

import contextvars
import os
import queue
import threading
import time
import zlib
from collections.abc import Callable, Iterable, Iterator

from variantcalling_tpu import knobs, logger, obs
from variantcalling_tpu.obs import sampler as obs_sampler
from variantcalling_tpu.utils import faults

_SENTINEL = object()


def _get_timed(q: queue.Queue, stats) -> tuple[bool, object]:
    """One bounded (0.1s) queue get, with the blocked time accounted to
    ``stats.wait_in`` when profiling — the ONE spelling of the wait-in
    attribution (stage workers and the consumer share it, so the
    accounting cannot drift between copies). Returns ``(ok, item)``."""
    if stats is None:
        try:
            return True, q.get(timeout=0.1)
        except queue.Empty:
            return False, None
    t0 = time.perf_counter()  # vctpu-lint: disable=VCT006 — obs queue-wait attribution
    try:
        return True, q.get(timeout=0.1)
    except queue.Empty:
        return False, None
    finally:
        stats.add_wait_in(time.perf_counter() - t0)  # vctpu-lint: disable=VCT006 — obs queue-wait attribution


def _put_timed(put: Callable, q: queue.Queue, item, stats) -> bool:
    """One bounded put through ``put`` (the pipeline's cancellable
    ``_put``), with the blocked time accounted to ``stats.wait_out``
    when profiling — the one spelling of backpressure attribution."""
    if stats is None:
        return put(q, item)
    t0 = time.perf_counter()  # vctpu-lint: disable=VCT006 — obs backpressure-wait attribution
    ok = put(q, item)
    stats.add_wait_out(time.perf_counter() - t0)  # vctpu-lint: disable=VCT006 — obs backpressure-wait attribution
    return ok

#: default per-run watchdog deadline (seconds of NO pipeline progress);
#: generous — chunks normally flow every few hundred ms, and a legitimate
#: slow stage still heartbeats by finishing items. 0 disables. The value
#: lives in the knob registry; this alias cannot drift from it.
DEFAULT_STAGE_TIMEOUT_S = knobs.REGISTRY["VCTPU_STAGE_TIMEOUT_S"].default


class StageTimeoutError(RuntimeError):
    """The pipeline made no progress within the watchdog deadline."""


class LadderEscalation(RuntimeError):
    """Base class for recovery-ladder escalation signals (e.g. the mesh
    dp-degrade restart): :func:`retry_chunk` passes these through
    untouched — re-dispatching the same chunk cannot answer a signal
    that says "change the run configuration"."""


def resolve_threads() -> int:
    """Pipeline thread policy: VCTPU_THREADS overrides, else cpu count.

    ``VCTPU_THREADS=1`` is the documented switch for "run the serial
    path". A malformed value is a configuration error (EngineError, CLI
    exit 2) like every other knob — the registry killed the old
    fall-back-to-auto behavior, where a typo silently changed the
    executor."""
    n = knobs.get_int("VCTPU_THREADS")
    return n if n is not None else (os.cpu_count() or 1)


def resolve_io_threads() -> int:
    """Host-IO worker policy for the parallel ingest/writeback paths
    (sharded BGZF inflate, chunk-parse fan-out, writeback block
    compress): ``VCTPU_IO_THREADS`` overrides, else cpu count. ``1``
    disables parallel IO — the serial code paths run inline, no pool.
    A malformed value is a configuration error (EngineError, exit 2;
    knob-registry contract)."""
    n = knobs.get_int("VCTPU_IO_THREADS")
    return n if n is not None else (os.cpu_count() or 1)


class _IoFuture:
    """Minimal future for :class:`IoPool` (result/exception + done event)."""

    __slots__ = ("_done", "_result", "_exc")

    def __init__(self):
        self._done = threading.Event()
        self._result = None
        self._exc: BaseException | None = None

    def result(self, timeout: float | None = None):
        if not self._done.wait(timeout):
            raise TimeoutError("IO task did not complete in time")
        if self._exc is not None:
            raise self._exc
        return self._result


class IoPool:
    """Tiny DAEMON-thread worker pool for the parallel host-IO paths.

    Unlike ``concurrent.futures.ThreadPoolExecutor`` (non-daemon workers,
    joined at interpreter exit), these workers are daemons: a truly
    wedged native/zlib call inside one cannot block process exit — the
    same policy the stage executor applies to its workers (the watchdog
    names the stuck stage; an unjoinable thread dies with the process).
    Worker threads are named ``<name>-w<idx>`` so the obs profiler can
    attribute per-worker work (docs/observability.md).
    """

    def __init__(self, threads: int, name: str = "vctpu-io"):
        self.threads = max(1, int(threads))
        self._q: queue.SimpleQueue = queue.SimpleQueue()
        self.unjoined: list[str] = []
        self._workers = [
            threading.Thread(target=self._loop, name=f"{name}-w{i}", daemon=True)
            for i in range(self.threads)
        ]
        for w in self._workers:
            w.start()

    def _loop(self) -> None:
        # (no sampler registration needed here: the obs v3 profiler's
        # name-based fallback already classifies "vctpu-io-wN"/"vctpu-
        # mesh-dispatch-wN" workers; explicit registration is for
        # threads whose NAME is not enough — pipeline stage workers and
        # the committer)
        while True:
            item = self._q.get()
            if item is None:
                return
            fut, ctx, fn, args = item
            try:
                # run in the SUBMITTER's context: request-scoped knob
                # overrides, scoped faults and cancel tokens
                # (knobs.scope / faults.scope / utils.cancellation)
                # follow the request's work onto the pool — the
                # per-request isolation contract of vctpu serve
                fut._result = ctx.run(fn, *args)
            # not a swallow: result() re-raises in the consumer
            except BaseException as e:  # noqa: BLE001  # vctpu-lint: disable=VCT002 — relayed through the future and re-raised at result()
                fut._exc = e
            finally:
                fut._done.set()

    def submit(self, fn: Callable, *args) -> _IoFuture:
        fut = _IoFuture()
        self._q.put((fut, contextvars.copy_context(), fn, args))
        return fut

    def shutdown(self, timeout: float = 5.0) -> None:
        """Stop the workers (bounded join — a wedged worker is recorded
        in ``unjoined`` and abandoned, mirroring StagePipeline)."""
        for _ in self._workers:
            self._q.put(None)
        self.unjoined = []
        for w in self._workers:
            w.join(timeout=timeout)
            if w.is_alive():
                self.unjoined.append(w.name)
        if self.unjoined:
            logger.warning("IO pool: %d worker(s) did not join: %s",
                           len(self.unjoined), ", ".join(self.unjoined))


def imap_ordered(pool: IoPool, fn: Callable, items: Iterable,
                 window: int) -> Iterator:
    """Map ``fn`` over ``items`` on ``pool``, yielding results strictly
    in submission order with at most ``window`` tasks in flight — the
    ordered-reassembly primitive of the parallel host-IO paths (shard
    inflate, chunk parse, block compress). The bounded window keeps peak
    memory at O(window × item); a failed task re-raises at its ordinal
    position (downstream consumers see the same exception order a serial
    loop would)."""
    from collections import deque

    pending: deque[_IoFuture] = deque()
    it = iter(items)
    exhausted = False
    while True:
        while not exhausted and len(pending) < max(1, window):
            try:
                item = next(it)
            except StopIteration:
                exhausted = True
                break
            pending.append(pool.submit(fn, item))
        if not pending:
            return
        yield pending.popleft().result()


def resolve_stage_timeout() -> float:
    """Watchdog deadline from ``VCTPU_STAGE_TIMEOUT_S`` (0 disables). A
    malformed value is a configuration error (EngineError, CLI exit 2;
    knob-registry contract) — it can neither disable the watchdog
    silently nor be silently ignored."""
    return knobs.get_float("VCTPU_STAGE_TIMEOUT_S")


def _retry_delay(attempt: int, backoff_s: float, who: str) -> float:
    """Exponential backoff with bounded DETERMINISTIC jitter, seeded by
    the retrying worker's identity: pool workers that hit the same
    transient fault in lockstep (one shared-disk hiccup fans the same
    error to every ``vctpu-io-w<N>``) would otherwise all sleep exactly
    ``backoff_s * 2^k`` and stampede the sink together on wake. The
    jitter spreads wakeups over [1x, 1.5x) of the base delay, is a pure
    function of (worker name, attempt) — reproducible runs stay
    reproducible, no RNG state — and is timing-only: output bytes can
    never depend on it."""
    base = backoff_s * (2 ** attempt)
    frac = (zlib.crc32(f"{who}:{attempt}".encode()) % 1024) / 1024.0
    return base * (1.0 + 0.5 * frac)


def retry_transient(fn: Callable, what: str, attempts: int | None = None,
                    backoff_s: float | None = None,
                    retry_on: tuple[type[BaseException], ...] = (OSError,)):
    """Run ``fn()`` with bounded retry + exponential backoff on transient
    IO errors — the streaming executor's recovery primitive for chunk
    reads and sink writes (docs/robustness.md failure matrix).

    ``attempts`` counts TOTAL tries (default ``VCTPU_IO_RETRIES``+1 = 3);
    backoff doubles from ``backoff_s`` (default ``VCTPU_IO_BACKOFF_S`` =
    0.05s) with deterministic per-worker jitter (:func:`_retry_delay`).
    Non-retryable exceptions propagate immediately; the last retryable
    failure propagates after the budget is spent.
    """
    if attempts is None:
        attempts = 1 + knobs.get_int("VCTPU_IO_RETRIES")
    if backoff_s is None:
        backoff_s = knobs.get_float("VCTPU_IO_BACKOFF_S")
    last: BaseException | None = None
    for k in range(max(1, attempts)):
        try:
            return fn()
        except retry_on as e:  # noqa: PERF203 — retry loop is the point
            last = e
            if k + 1 >= attempts:
                break
            delay = _retry_delay(k, backoff_s,
                                 threading.current_thread().name)
            if obs.active():
                obs.event("retry", what, attempt=k + 1, attempts=attempts,
                          error=f"{type(e).__name__}: {e}")
                obs.counter("io.retries").add(1)
            logger.warning("transient error in %s (attempt %d/%d): %s — retrying in %.2fs",
                           what, k + 1, attempts, e, delay)
            if delay:
                time.sleep(delay)
    raise last  # type: ignore[misc]


# -- supervised chunk recovery (docs/robustness.md "Recovery ladder") ------

#: per-thread re-dispatch context: quarantine guards divert a poison
#: chunk only on the FINAL attempt of the budget, and they learn which
#: attempt they are on through this cell (same thread by construction —
#: retry_chunk runs its body inline)
_RETRY_TLS = threading.local()


def on_final_attempt() -> bool:
    """True when the calling chunk body is on its LAST (or only) dispatch
    attempt. Code not running under :func:`retry_chunk` is always final —
    a guard outside the ladder quarantines on the first strike."""
    return getattr(_RETRY_TLS, "final", True)


def resolve_chunk_retries() -> int:
    """Chunk re-dispatch budget (``VCTPU_CHUNK_RETRIES``, default 1)."""
    return knobs.get_int("VCTPU_CHUNK_RETRIES")


def retry_chunk(fn: Callable, what: str, seq: int | None = None):
    """Task-level re-dispatch of a failed chunk body — the second rung of
    the supervised recovery ladder (docs/robustness.md).

    Chunk bodies (parse, featurize+score, render, the mesh megabatch
    dispatch) are pure functions of their input, so re-running one cannot
    change output bytes — it can only turn a transient failure (an IO
    worker death, a flaky allocator, a cosmic-ray exception) into a
    completed chunk instead of a dead run. Contract errors stay loud and
    unretried: ``EngineError`` (configuration) and
    :class:`StageTimeoutError` (watchdog) propagate immediately, as do
    interpreter-exit exceptions. The final failure re-raises unchanged,
    so callers — including the quarantine guards one rung up — see
    exactly the exception a retry-free run would have seen.
    """
    from variantcalling_tpu.engine import EngineError

    attempts = 1 + resolve_chunk_retries()
    last: BaseException | None = None
    prev = getattr(_RETRY_TLS, "final", True)
    try:
        for k in range(max(1, attempts)):
            if k:
                if obs.active():
                    fields = {"what": what, "attempt": k,
                              "retries": attempts - 1,
                              "chunk": -1 if seq is None else seq,
                              "error": f"{type(last).__name__}: {last}"}
                    # causal linkage: the re-dispatch names the trace of
                    # the chunk it is recovering (the body bound it via
                    # obs.trace_scope), so `obs critical-path`/triage can
                    # walk from the recovery event to the chunk's DAG
                    tid = obs.current_trace()
                    if tid is not None:
                        fields["trace_id"] = tid
                    obs.event("recovery", "chunk_retry", **fields)
                    obs.counter("recovery.chunk_retries").add(1)
                logger.warning(
                    "chunk failure in %s (attempt %d/%d): %s — re-dispatching",
                    what, k, attempts, last)
            _RETRY_TLS.final = k + 1 >= attempts  # vctpu-lint: disable=VCT010 — threading.local IS a per-thread cell (the obs/metrics pattern); no cross-thread visibility exists
            try:
                return fn()
            except (EngineError, StageTimeoutError, LadderEscalation):
                raise
            # the final failure re-raises below — never a swallow
            except Exception as e:  # noqa: BLE001  # vctpu-lint: disable=VCT002 — bounded re-dispatch; the last failure re-raises after the loop
                last = e
    finally:
        _RETRY_TLS.final = prev  # vctpu-lint: disable=VCT010 — threading.local IS a per-thread cell (the obs/metrics pattern); no cross-thread visibility exists
    raise last  # type: ignore[misc]


def record_quarantine(what: str, records: int, exc: BaseException,
                      trace_id: str | None = None) -> None:
    """The loud-divert bookkeeping EVERY quarantine site shares (the
    host-path guard in pipelines/filter_variants and the mesh dispatch
    ladder in parallel/shard_score): a sanctioned degradation with
    ``warn=True``, the ``recovery``/``quarantine`` obs event — carrying
    the diverted chunk's TRACE id so the event resolves to the chunk's
    span DAG — and the quarantined-chunks counter: one spelling, so the
    contract cannot drift between paths."""
    from variantcalling_tpu.utils import degrade

    degrade.record("stream.quarantine", exc, warn=True,
                   fallback=f"chunk of {records} records diverted to the "
                            ".quarantine sidecar")
    if obs.active():
        fields = {"what": what, "records": records,
                  "error": f"{type(exc).__name__}: {exc}"}
        tid = trace_id if trace_id is not None else obs.current_trace()
        if tid is not None:
            fields["trace_id"] = tid
        obs.event("recovery", "quarantine", **fields)
        obs.counter("recovery.quarantined_chunks").add(1)


def _dump_thread_stacks() -> str:
    """Every live thread's current Python stack (the same dump a fatal
    signal would print), captured to a string so the v2 watchdog can put
    it INTO the obs stream — a wedged production run's post-mortem then
    carries the exact frames that were stuck, not just the stage name."""
    import faulthandler
    import tempfile

    try:
        with tempfile.TemporaryFile(mode="w+") as fh:
            faulthandler.dump_traceback(file=fh, all_threads=True)
            fh.seek(0)
            return fh.read()
    except (OSError, ValueError):
        return "(thread-stack dump unavailable)"


class StagePipeline:
    """Run items through ``stages`` (list of callables) with stage overlap.

    ``run(source)`` yields ``stages[-1](...stages[0](item))`` for every
    item of ``source``, in source order. With >1 resolved threads each
    stage runs in its own worker thread connected by bounded queues; with
    1 thread the same callables run inline (the serial path).
    """

    def __init__(self, stages: list[Callable], queue_depth: int = 2,
                 threads: int | None = None, timeout: float | None = None,
                 profiler=None, source_name: str = "source",
                 consumer_name: str = "consume",
                 source_pooled: bool = False, recover: bool = False):
        if stages is None:
            raise ValueError("StagePipeline needs a stage list")
        # an EMPTY stage list is legal with a pooled source (parallel
        # host IO): the pipeline is then source -> bounded queue ->
        # consumer, and the watchdog/error/teardown contracts still hold
        self.stages = list(stages)
        self.queue_depth = max(1, int(queue_depth))
        self.threads = resolve_threads() if threads is None else max(1, int(threads))
        self.timeout = resolve_stage_timeout() if timeout is None else max(0.0, float(timeout))
        #: obs v2 attribution (obs/profile.StageProfiler) — the executor
        #: feeds work vs queue-wait vs backpressure-wait per stage into
        #: it; the CALLER owns emit() (it knows the run's wall clock and
        #: record count). ``source_name``/``consumer_name`` label the
        #: feed thread's reads and the consumer loop's waits (the filter
        #: passes "ingest"/"writeback").
        self.profiler = profiler
        self.source_name = source_name
        self.consumer_name = consumer_name
        #: True when the source is an ordered drain of a worker pool
        #: (parallel host IO): time blocked in next() is then QUEUE-WAIT
        #: on the pool, not work — the workers attribute the real work
        #: under their own ``<stage>.w<idx>`` profile rows
        self.source_pooled = source_pooled
        #: SUPERVISED mode — the streaming filter executor turns this on
        #: (docs/robustness.md "Recovery ladder"): a failed stage item
        #: re-dispatches through :func:`retry_chunk` before the failure
        #: is final; the watchdog's FIRST expiry dumps all thread stacks
        #: into the obs stream, releases injected hangs, re-dispatches
        #: the wedged chunk once on a one-shot thread and grants one
        #: more deadline (duplicate deliveries are dropped by sequence
        #: number — chunk bodies are pure, so duplicates are
        #: byte-identical). Off by default: bare pipelines keep the PR-2
        #: fail-loud-on-first-strike semantics.
        self.recover = bool(recover)
        #: True when the v2 watchdog spent its single retry on the most
        #: recent run (tests / post-mortem introspection)
        self.watchdog_retried = False
        #: threads that refused to join within the cleanup grace period on
        #: the most recent run (a truly wedged native call cannot be
        #: interrupted from Python; they are daemons and die with the
        #: process). Empty after a clean run.
        self.unjoined: list[str] = []

    @property
    def parallel(self) -> bool:
        return self.threads > 1

    # -- serial path -------------------------------------------------------

    def _stage_name(self, i: int) -> str:
        return getattr(self.stages[i], "__name__", None) or f"stage{i}"

    def _active_profiler(self):
        """The attribution sink for this run, or None (profiling rides
        the obs run: no stream, or ``VCTPU_OBS_PROFILE=0``, no cost)."""
        if self.profiler is None or not obs.active():
            return None
        return self.profiler if obs.profile_mod().enabled() else None

    def _record_stage_work(self, name: str, dt: float, seq: int, prof) -> None:
        """One stage item closed: span + latency histogram + attribution."""
        obs.span(name, dt, threading.current_thread().name, chunk=seq)
        obs.histogram(f"stage.{name}.s").observe(dt)
        if prof is not None:
            prof.stage(name).add_work(dt)

    def _next_timed(self, it: Iterator, seq: int, prof) -> tuple[bool, object]:
        """One source read, timed into the source stage when obs is on
        (shared by the serial loop and the feed thread). ``(ok, item)``."""
        if not obs.active():
            try:
                return True, next(it)
            except StopIteration:
                return False, None
        t0 = time.perf_counter()  # vctpu-lint: disable=VCT006 — obs span timing
        try:
            item = next(it)
        except StopIteration:
            return False, None
        dt = time.perf_counter() - t0  # vctpu-lint: disable=VCT006 — obs span timing
        if self.source_pooled:
            # pooled source: blocked-on-pool time is wait-in, not work
            obs.span(self.source_name, dt, threading.current_thread().name,
                     chunk=seq)
            obs.histogram(f"stage.{self.source_name}.s").observe(dt)
            if prof is not None:
                prof.stage(self.source_name).add_wait_in(dt, items=1)
        else:
            self._record_stage_work(self.source_name, dt, seq, prof)
        return True, item

    def _serial_stage_item(self, i: int, fn: Callable, seq: int, item, prof):
        """One stage applied to one item on the serial path — injection
        points fire PER STAGE, exactly like the threaded workers, so the
        recovery ladder sees the same unit in both modes."""
        faults.check("pipeline.stage")
        faults.check("pipeline.stage_hang")
        if not obs.active():
            return fn(item)
        t0 = time.perf_counter()  # vctpu-lint: disable=VCT006 — obs span timing
        out = fn(item)
        self._record_stage_work(
            self._stage_name(i),
            time.perf_counter() - t0, seq, prof)  # vctpu-lint: disable=VCT006 — obs span timing
        return out

    def _apply_stages(self, item, seq: int, prof):
        """One item through the serial stage chain, with PER-STAGE
        re-dispatch in supervised mode — mirroring the threaded path: a
        stage marked ``retry_safe = False`` (the stateful BGZF-carry
        compressor) runs exactly once while every other stage keeps its
        retry budget, so a single-thread .gz run still recovers
        transient scoring failures."""
        for i, fn in enumerate(self.stages):
            if self.recover and getattr(fn, "retry_safe", True):
                # bind the chunk's trace so the re-dispatch events the
                # ladder emits resolve to the chunk they recover (the
                # stage body's own scope has already unwound when the
                # failure reaches this supervisor)
                with obs.trace_scope(
                        obs.trace_of(item) if obs.tracing() else None):
                    item = retry_chunk(
                        lambda it_=item, i_=i, fn_=fn:
                        self._serial_stage_item(i_, fn_, seq, it_, prof),
                        self._stage_name(i), seq=seq)
            else:
                item = self._serial_stage_item(i, fn, seq, item, prof)
        return item

    def _run_serial(self, source: Iterable) -> Iterator:
        prof = self._active_profiler()
        it = iter(source)
        seq = 0
        while True:
            ok, item = self._next_timed(it, seq, prof)
            if not ok:
                break
            item = self._apply_stages(item, seq, prof)
            yield item
            seq += 1

    # -- threaded path -----------------------------------------------------

    def run(self, source: Iterable) -> Iterator:
        if obs.active():
            obs.event("stage", "pipeline_start",
                      stages=[self._stage_name(i) for i in range(len(self.stages))],
                      threads=self.threads, queue_depth=self.queue_depth,
                      mode="threaded" if self.parallel else "serial",
                      # the serial loop runs no watchdog — report 0 so the
                      # stream never claims a deadline that cannot fire
                      watchdog_s=self.timeout if self.parallel else 0)
            if self.timeout and self.parallel:
                obs.counter("watchdog.armed").add(1)
        if not self.parallel:
            done = 0
            try:
                for item in self._run_serial(source):
                    done += 1
                    yield item
            finally:
                if obs.active():  # lifecycle closes on EVERY exit path
                    obs.event("stage", "pipeline_end", chunks=done,
                              unjoined=[])
            return

        stop = threading.Event()
        queues = [queue.Queue(maxsize=self.queue_depth)
                  for _ in range(len(self.stages) + 1)]
        # per-stage heartbeat: monotonic time the stage last STARTED an
        # item, None while idle — lets the watchdog name the stuck stage
        busy_since: list[float | None] = [None] * len(self.stages)
        # the in-flight (seq, item) per stage — what the v2 watchdog
        # re-dispatches when the owning worker is wedged (recover mode)
        busy_item: list[tuple | None] = [None] * len(self.stages)

        def _put(q: queue.Queue, item) -> bool:
            # bounded put that stays responsive to cancellation
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        # NOTE error relay: a failing stage/source puts an (_SENTINEL, exc)
        # tuple downstream and exits — it does NOT set the stop event, or
        # the next stage could observe stop before draining the error and
        # the consumer would see a bare cancellation instead of the real
        # exception. Only the consumer sets stop (on error or completion);
        # upstream workers blocked on full queues unblock when it drains.

        prof = self._active_profiler()

        def _feed() -> None:
            obs_sampler.register_current("pipe.src")
            src = prof.stage(self.source_name) if prof is not None else None
            try:
                it = iter(source)
                seq = 0
                while True:
                    ok, item = self._next_timed(it, seq, prof)
                    if not ok:
                        break
                    if not _put_timed(_put, queues[0], (seq, item), src):
                        return
                    if obs.active():
                        # queue pressure at the pipeline head (with an
                        # empty stage list this is the ONLY queue)
                        obs.gauge("queue.source.depth").set(queues[0].qsize())
                    seq += 1
                _put(queues[0], _SENTINEL)
            # not a swallow: the consumer re-raises the relayed exception
            except BaseException as e:  # noqa: BLE001  # vctpu-lint: disable=VCT002 — relayed to the consumer and re-raised there
                _put(queues[0], (_SENTINEL, e))

        def _run_stage_item(i: int, fn: Callable, seq: int, item):
            """One stage item: injection points + timed stage body — the
            unit the recovery ladder re-dispatches (the watchdog/error
            contracts are proven against the injection points,
            tests/unit/test_streaming_faults.py)."""
            faults.check("pipeline.stage")
            faults.check("pipeline.stage_hang")
            if not obs.active():
                return fn(item)
            t0 = time.perf_counter()  # vctpu-lint: disable=VCT006 — obs span timing
            out = fn(item)
            self._record_stage_work(
                self._stage_name(i),
                time.perf_counter() - t0, seq, prof)  # vctpu-lint: disable=VCT006 — obs span timing
            return out

        def _stage(i: int, fn: Callable) -> None:
            # sampler attribution by STAGE name, not just thread index —
            # the flame then reads "pipe.compress_stage", not "pipe-stage0"
            obs_sampler.register_current(f"pipe.{self._stage_name(i)}")
            q_in, q_out = queues[i], queues[i + 1]
            stats = prof.stage(self._stage_name(i)) if prof is not None else None
            # stateful stages (a ``retry_safe = False`` attribute on the
            # callable — the BGZF compressor's carry is the one real
            # case) must see each item EXACTLY once: no re-dispatch, and
            # duplicates from an upstream watchdog re-dispatch dropped
            # HERE, before the stage body, not only at the consumer
            retryable = self.recover and getattr(fn, "retry_safe", True)
            last_seq = -1
            try:
                while not stop.is_set():
                    ok, got = _get_timed(q_in, stats)
                    if not ok:
                        continue
                    if got is _SENTINEL or (isinstance(got, tuple) and got[0] is _SENTINEL):
                        _put(q_out, got)
                        return
                    seq, item = got
                    if self.recover and seq <= last_seq:
                        # duplicate delivery from a watchdog re-dispatch
                        # of the upstream stage: already processed
                        continue
                    busy_since[i] = time.monotonic()
                    busy_item[i] = got
                    try:
                        if retryable:
                            # same trace binding as the serial supervisor:
                            # ladder events name the chunk they recover
                            with obs.trace_scope(
                                    obs.trace_of(item)
                                    if obs.tracing() else None):
                                out = retry_chunk(
                                    lambda: _run_stage_item(i, fn, seq, item),
                                    self._stage_name(i), seq=seq)
                        else:
                            out = _run_stage_item(i, fn, seq, item)
                        last_seq = seq
                        if obs.active():
                            # queue pressure AFTER this stage produced:
                            # depth ~= items waiting for the next stage
                            obs.gauge(f"queue.stage{i}.depth").set(q_out.qsize())
                    finally:
                        busy_since[i] = None
                        busy_item[i] = None
                    _put_timed(_put, q_out, (seq, out), stats)
            # not a swallow: the consumer re-raises the relayed exception
            except BaseException as e:  # noqa: BLE001  # vctpu-lint: disable=VCT002 — relayed to the consumer and re-raised there
                _put(q_out, (_SENTINEL, e))

        def _watchdog_recover() -> None:
            """Watchdog v2, first expiry (recover mode): dump every
            thread's stack into the obs stream, release injected hangs
            (a cancellable wait resumes its stage normally), and
            re-dispatch each wedged stage's in-flight chunk ONCE on a
            one-shot thread — a truly wedged daemon cannot be
            interrupted, but its chunk's result can still be delivered
            (chunk bodies are pure; the consumer drops duplicate
            sequence numbers). The run then gets one more full deadline
            before the abort path runs as before."""
            msg = self._watchdog_message(busy_since, workers)
            stacks = _dump_thread_stacks()
            logger.warning("stage pipeline watchdog: first deadline "
                           "expired — re-dispatching the wedged chunk "
                           "once before aborting. %s", msg)
            if obs.active():
                # causal linkage: the wedged in-flight chunks' trace ids
                # (the traced table / render tuple each stage holds), so
                # the re-dispatch resolves to the chunk DAGs it revives
                tids = []
                for got in busy_item:
                    if got is None:
                        continue
                    tid = obs.trace_of(got[1])
                    if tid is not None:
                        tids.append(tid)
                obs.event("recovery", "watchdog_retry", detail=msg,
                          stacks=stacks[:20000], trace_ids=tids)
                obs.counter("recovery.watchdog_retries").add(1)
            faults.cancel_hangs()
            for i, got in enumerate(busy_item):
                if got is None:
                    continue
                if not getattr(self.stages[i], "retry_safe", True):
                    # a stateful stage (BGZF carry) cannot absorb the
                    # same item twice: cancel+grace only, no re-dispatch
                    continue
                seq, item = got
                fn, q_out = self.stages[i], queues[i + 1]

                def _redispatch(i=i, fn=fn, seq=seq, item=item, q_out=q_out):
                    try:
                        out = _run_stage_item(i, fn, seq, item)
                    # not a swallow: the consumer re-raises the relay
                    except BaseException as e:  # noqa: BLE001  # vctpu-lint: disable=VCT002 — relayed to the consumer and re-raised there
                        _put(q_out, (_SENTINEL, e))
                        return
                    _put(q_out, (seq, out))

                w = threading.Thread(target=_in_ctx, args=(_redispatch,),
                                     name=f"pipe-stage{i}-retry", daemon=True)
                workers.append(w)
                w.start()

        # every worker runs in the CALLER's context (fresh copy per
        # thread — a Context object is single-threaded): request-scoped
        # knobs/faults/cancel tokens bound where run() was called follow
        # the stage bodies, the per-request isolation contract of
        # vctpu serve (docs/serving.md)
        run_ctx = contextvars.copy_context()

        def _in_ctx(fn: Callable, *args) -> None:
            run_ctx.copy().run(fn, *args)

        workers = [threading.Thread(target=_in_ctx, args=(_feed,),
                                    name="pipe-src", daemon=True)]
        workers += [
            threading.Thread(target=_in_ctx, args=(_stage, i, fn),
                             name=f"pipe-stage{i}", daemon=True)
            for i, fn in enumerate(self.stages)
        ]
        for w in workers:
            w.start()
        expect = 0
        last_progress = time.monotonic()
        self.watchdog_retried = False
        consume = prof.stage(self.consumer_name) if prof is not None else None
        try:
            while True:
                ok, got = _get_timed(queues[-1], consume)
                if not ok:
                    if stop.is_set():
                        # a failed stage may have died before relaying
                        raise RuntimeError("stage pipeline cancelled")
                    if self.timeout and time.monotonic() - last_progress > self.timeout:
                        if self.recover and not self.watchdog_retried:
                            # v2: one supervised retry before the abort
                            self.watchdog_retried = True
                            _watchdog_recover()
                            last_progress = time.monotonic()
                            continue
                        msg = self._watchdog_message(busy_since, workers)
                        if obs.active():
                            obs.event("stage", "watchdog_fire", detail=msg)
                            obs.counter("watchdog.fired").add(1)
                        raise StageTimeoutError(msg)
                    continue
                last_progress = time.monotonic()
                if got is _SENTINEL:
                    return
                if isinstance(got, tuple) and got[0] is _SENTINEL:
                    raise got[1]
                seq, item = got
                if self.recover and seq < expect:
                    # duplicate delivery: the wedged worker woke up after
                    # the watchdog's re-dispatch already delivered its
                    # chunk (both computed identical bytes — pure body)
                    continue
                # single-thread-per-stage FIFO makes this a hard invariant
                assert seq == expect, (seq, expect)
                expect += 1
                yield item
        finally:
            stop.set()
            # release any injected hang so its thread can observe stop and
            # join below (no-op outside fault-injection runs)
            faults.cancel_hangs()
            for q in queues:  # unblock any worker parked on a full queue
                try:
                    while True:
                        q.get_nowait()
                except queue.Empty:
                    pass
            self.unjoined = []
            for w in workers:
                w.join(timeout=5.0)
                if w.is_alive():
                    self.unjoined.append(w.name)
            if self.unjoined:
                # a wedged native call cannot be interrupted from Python;
                # the daemon thread dies with the process. Surface it —
                # silence here would hide a leak.
                logger.warning("stage pipeline: %d worker(s) did not join: %s",
                               len(self.unjoined), ", ".join(self.unjoined))
            if obs.active():
                obs.event("stage", "pipeline_end", chunks=expect,
                          unjoined=list(self.unjoined))

    def _watchdog_message(self, busy_since: list[float | None],
                          workers: list[threading.Thread]) -> str:
        now = time.monotonic()
        stuck = [
            f"stage {i} ({getattr(self.stages[i], '__name__', 'stage')}) busy {now - t:.1f}s"
            for i, t in enumerate(busy_since) if t is not None
        ]
        alive = [w.name for w in workers if w.is_alive()]
        detail = "; ".join(stuck) if stuck else "no stage reports busy (source stalled?)"
        return (f"stage pipeline watchdog: no progress for {self.timeout:.0f}s — "
                f"{detail}; alive workers: {', '.join(alive) or 'none'}. "
                "Raise VCTPU_STAGE_TIMEOUT_S for legitimately slow stages.")


def run_pipeline(source: Iterable, stages: list[Callable],
                 queue_depth: int = 2, threads: int | None = None) -> Iterator:
    """Convenience wrapper: ``StagePipeline(stages, ...).run(source)``."""
    return StagePipeline(stages, queue_depth=queue_depth, threads=threads).run(source)
