"""Bounded-queue ordered stage executor — the host-side streaming pipeline.

The flagship filter path used to run its three host stages strictly in
sequence (whole-file ingest -> featurize+score -> whole-file writeback),
so end-to-end wall time was the SUM of the stages even though each stage
leaves cores idle (ingest/writeback are I/O-and-glue heavy, scoring is
compute heavy). This executor runs the stages as a chunked pipeline over
sequence-numbered items: one worker thread per stage, bounded queues
between stages, results consumed strictly in submission order. Stage time
then hides behind the slowest stage instead of summing — the same
argument the GPU variant-calling pipeline literature makes for overlapping
I/O around the compute kernel (PAPERS.md, "Optimizing the Variant Calling
Pipeline Execution ... Using GPU-Enabled Machines"; GenPIP's stage fusion).

Design rules:

- one thread per stage, FIFO queues: per-stage order is preserved by
  construction, so output ordering needs no reorder buffer — items leave
  the last stage in exactly the order the source yielded them (each item
  carries its sequence number and the consumer asserts it);
- bounded queues (``queue_depth``): at most ``queue_depth`` items wait
  between any two stages, so peak memory is O(stages * queue_depth *
  chunk), never O(input);
- ``VCTPU_THREADS=1`` (or a single-core host) degrades to a plain serial
  loop through the same stage callables — byte-identical results, no
  threads, no queues;
- a stage exception cancels the whole pipeline promptly (stop event +
  queue drain) and re-raises in the consumer.

The GIL is not a problem here: stage bodies are native engine calls,
numpy, and file I/O, all of which release it.
"""

from __future__ import annotations

import os
import queue
import threading
from collections.abc import Callable, Iterable, Iterator

_SENTINEL = object()


def resolve_threads() -> int:
    """Pipeline thread policy: VCTPU_THREADS overrides, else cpu count.

    ``VCTPU_THREADS=1`` is the documented switch for "run the serial
    path"; invalid values fall back to auto so a typo can't crash a run.
    """
    env = os.environ.get("VCTPU_THREADS", "").strip()
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return os.cpu_count() or 1


class StagePipeline:
    """Run items through ``stages`` (list of callables) with stage overlap.

    ``run(source)`` yields ``stages[-1](...stages[0](item))`` for every
    item of ``source``, in source order. With >1 resolved threads each
    stage runs in its own worker thread connected by bounded queues; with
    1 thread the same callables run inline (the serial path).
    """

    def __init__(self, stages: list[Callable], queue_depth: int = 2,
                 threads: int | None = None):
        if not stages:
            raise ValueError("StagePipeline needs at least one stage")
        self.stages = list(stages)
        self.queue_depth = max(1, int(queue_depth))
        self.threads = resolve_threads() if threads is None else max(1, int(threads))

    @property
    def parallel(self) -> bool:
        return self.threads > 1

    # -- serial path -------------------------------------------------------

    def _run_serial(self, source: Iterable) -> Iterator:
        for item in source:
            for fn in self.stages:
                item = fn(item)
            yield item

    # -- threaded path -----------------------------------------------------

    def run(self, source: Iterable) -> Iterator:
        if not self.parallel:
            yield from self._run_serial(source)
            return

        stop = threading.Event()
        queues = [queue.Queue(maxsize=self.queue_depth)
                  for _ in range(len(self.stages) + 1)]

        def _put(q: queue.Queue, item) -> bool:
            # bounded put that stays responsive to cancellation
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        # NOTE error relay: a failing stage/source puts an (_SENTINEL, exc)
        # tuple downstream and exits — it does NOT set the stop event, or
        # the next stage could observe stop before draining the error and
        # the consumer would see a bare cancellation instead of the real
        # exception. Only the consumer sets stop (on error or completion);
        # upstream workers blocked on full queues unblock when it drains.

        def _feed() -> None:
            try:
                for seq, item in enumerate(source):
                    if not _put(queues[0], (seq, item)):
                        return
                _put(queues[0], _SENTINEL)
            except BaseException as e:  # noqa: BLE001 — relay to the consumer
                _put(queues[0], (_SENTINEL, e))

        def _stage(i: int, fn: Callable) -> None:
            q_in, q_out = queues[i], queues[i + 1]
            try:
                while not stop.is_set():
                    try:
                        got = q_in.get(timeout=0.1)
                    except queue.Empty:
                        continue
                    if got is _SENTINEL or (isinstance(got, tuple) and got[0] is _SENTINEL):
                        _put(q_out, got)
                        return
                    seq, item = got
                    _put(q_out, (seq, fn(item)))
            except BaseException as e:  # noqa: BLE001 — relay to the consumer
                _put(q_out, (_SENTINEL, e))

        workers = [threading.Thread(target=_feed, name="pipe-src", daemon=True)]
        workers += [
            threading.Thread(target=_stage, args=(i, fn),
                             name=f"pipe-stage{i}", daemon=True)
            for i, fn in enumerate(self.stages)
        ]
        for w in workers:
            w.start()
        expect = 0
        try:
            while True:
                try:
                    got = queues[-1].get(timeout=0.1)
                except queue.Empty:
                    if stop.is_set():
                        # a failed stage may have died before relaying
                        raise RuntimeError("stage pipeline cancelled")
                    continue
                if got is _SENTINEL:
                    return
                if isinstance(got, tuple) and got[0] is _SENTINEL:
                    raise got[1]
                seq, item = got
                # single-thread-per-stage FIFO makes this a hard invariant
                assert seq == expect, (seq, expect)
                expect += 1
                yield item
        finally:
            stop.set()
            for q in queues:  # unblock any worker parked on a full queue
                try:
                    while True:
                        q.get_nowait()
                except queue.Empty:
                    pass
            for w in workers:
                w.join(timeout=5.0)


def run_pipeline(source: Iterable, stages: list[Callable],
                 queue_depth: int = 2, threads: int | None = None) -> Iterator:
    """Convenience wrapper: ``StagePipeline(stages, ...).run(source)``."""
    return StagePipeline(stages, queue_depth=queue_depth, threads=threads).run(source)
