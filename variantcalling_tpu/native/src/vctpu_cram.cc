// CRAM 3.0 decoder for the native host engine.
//
// The reference consumes CRAM through samtools subprocesses
// (quick_fingerprinter.py:104-108, coverage_analysis BASELINE config 4:
// "30x WGS CRAM"); this is an in-process reader producing per-record
// alignment arrays (ref_id, pos, reference span, mapq, flags, read length)
// that feed the same depth/pileup reductions as the BAM path.
//
// Scope: CRAM 3.0 (the htslib default writer format), block compression
// raw/gzip/rANS-4x8, encodings NULL/EXTERNAL/HUFFMAN/BETA/BYTE_ARRAY_LEN/
// BYTE_ARRAY_STOP/GAMMA. CRAM 3.1 codecs and the rare golomb/subexp
// encodings return an error so callers fall back with a clear message.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <cstdio>
#include <map>
#include <string>
#include <vector>

#include <zlib.h>

namespace cram {

// ---------------------------------------------------------------------------
// byte cursor + ITF8/LTF8
// ---------------------------------------------------------------------------

struct Cursor {
    const uint8_t* p;
    const uint8_t* end;
    bool ok = true;

    uint8_t u8() {
        if (p >= end) { ok = false; return 0; }
        return *p++;
    }
    uint32_t u32le() {
        if (p + 4 > end) { ok = false; return 0; }
        uint32_t v = (uint32_t)p[0] | ((uint32_t)p[1] << 8) | ((uint32_t)p[2] << 16) |
                     ((uint32_t)p[3] << 24);
        p += 4;
        return v;
    }
    void skip(int64_t n) {
        if (p + n > end) { ok = false; p = end; } else { p += n; }
    }
    int32_t itf8() {
        uint8_t b0 = u8();
        if ((b0 & 0x80) == 0) return b0;
        if ((b0 & 0x40) == 0) return ((b0 & 0x3F) << 8) | u8();
        if ((b0 & 0x20) == 0) {
            int32_t v = (b0 & 0x1F) << 16; v |= u8() << 8; v |= u8(); return v;
        }
        if ((b0 & 0x10) == 0) {
            int32_t v = (b0 & 0x0F) << 24; v |= u8() << 16; v |= u8() << 8; v |= u8(); return v;
        }
        int32_t v = (b0 & 0x0F) << 28; v |= u8() << 20; v |= u8() << 12; v |= u8() << 4;
        v |= (u8() & 0x0F);
        return v;
    }
    int64_t ltf8() {
        uint8_t b0 = u8();
        int n = 0;
        for (int i = 7; i >= 0; i--) {
            if (b0 & (1 << i)) n++; else break;
        }
        int64_t v = (n < 8) ? (b0 & ((1 << (7 - n)) - 1)) : 0;
        for (int i = 0; i < n; i++) v = (v << 8) | u8();
        return v;
    }
};

// ---------------------------------------------------------------------------
// rANS 4x8 (order 0 and 1) — spec section 13 / htslib rANS_static
// ---------------------------------------------------------------------------

// corrupt-size guard: real CRAM blocks are <= a few MB (htslib slices hold
// ~10k records); 256 MB bounds pathological headers without rejecting any
// legitimate file
static const int64_t MAX_BLOCK_RAW = int64_t(1) << 28;

static const uint32_t RANS_LOW = 1u << 23;

struct RansSyms {
    uint16_t fc[256];  // freq
    uint16_t cc[256];  // cumulative
    uint8_t rev[4096];
};

static bool read_freq_table0(Cursor& c, RansSyms& t) {
    memset(t.fc, 0, sizeof(t.fc));
    memset(t.cc, 0, sizeof(t.cc));
    int x = 0, rle = 0;
    int j = c.u8();
    do {
        int f = c.u8();
        if (f >= 128) f = ((f & 127) << 8) | c.u8();
        if (!c.ok || x + f > 4096) return false;
        t.fc[j] = f;
        t.cc[j] = x;
        if (f) memset(&t.rev[x], j, f);
        x += f;
        if (!rle && c.p < c.end && *c.p == j + 1) {
            j = c.u8();
            rle = c.u8();
        } else if (rle) {
            rle--;
            j++;
        } else {
            j = c.u8();
        }
    } while (j && c.ok);
    return c.ok;
}

static bool rans_uncompress(const uint8_t* in, int64_t in_len, std::vector<uint8_t>& out) {
    Cursor c{in, in + in_len};
    int order = c.u8();
    uint32_t comp_sz = c.u32le();
    uint32_t raw_sz = c.u32le();
    (void)comp_sz;
    if (!c.ok || raw_sz > (uint32_t)MAX_BLOCK_RAW) return false;
    out.resize(raw_sz);
    if (raw_sz == 0) return true;

    auto renorm = [&](uint32_t& x) {
        while (x < RANS_LOW && c.p < c.end) x = (x << 8) | c.u8();
    };

    if (order == 0) {
        RansSyms t;
        if (!read_freq_table0(c, t)) return false;
        uint32_t R[4];
        for (int i = 0; i < 4; i++) R[i] = c.u32le();
        if (!c.ok) return false;
        for (uint32_t i = 0; i < raw_sz; i++) {
            uint32_t& x = R[i & 3];
            uint32_t m = x & 0xFFF;
            uint8_t s = t.rev[m];
            out[i] = s;
            x = t.fc[s] * (x >> 12) + m - t.cc[s];
            renorm(x);
        }
        return true;
    }
    if (order == 1) {
        static thread_local std::vector<RansSyms> tables;
        tables.assign(256, RansSyms());
        std::vector<bool> present(256, false);
        int rle = 0;
        int i = c.u8();
        do {
            if (!read_freq_table0(c, tables[i])) return false;
            present[i] = true;
            if (!rle && c.p < c.end && *c.p == i + 1) {
                i = c.u8();
                rle = c.u8();
            } else if (rle) {
                rle--;
                i++;
            } else {
                i = c.u8();
            }
        } while (i && c.ok);
        if (!c.ok) return false;
        uint32_t R[4];
        for (int k = 0; k < 4; k++) R[k] = c.u32le();
        if (!c.ok) return false;
        uint32_t isz4 = raw_sz >> 2;
        uint8_t last[4] = {0, 0, 0, 0};
        for (uint32_t pos = 0; pos < isz4; pos++) {
            for (int k = 0; k < 4; k++) {
                uint32_t& x = R[k];
                RansSyms& t = tables[last[k]];
                uint32_t m = x & 0xFFF;
                uint8_t s = t.rev[m];
                out[pos + k * isz4] = s;
                x = t.fc[s] * (x >> 12) + m - t.cc[s];
                renorm(x);
                last[k] = s;
            }
        }
        // tail bytes with state 3
        for (uint32_t pos = 4 * isz4; pos < raw_sz; pos++) {
            uint32_t& x = R[3];
            RansSyms& t = tables[last[3]];
            uint32_t m = x & 0xFFF;
            uint8_t s = t.rev[m];
            out[pos] = s;
            x = t.fc[s] * (x >> 12) + m - t.cc[s];
            renorm(x);
            last[3] = s;
        }
        return true;
    }
    return false;
}

static bool gzip_inflate_vec(const uint8_t* in, int64_t in_len, std::vector<uint8_t>& out,
                             int64_t raw_size) {
    out.resize(raw_size);
    z_stream zs;
    memset(&zs, 0, sizeof(zs));
    if (inflateInit2(&zs, 15 + 32) != Z_OK) return false;
    zs.next_in = const_cast<uint8_t*>(in);
    zs.avail_in = (uInt)in_len;
    zs.next_out = out.data();
    zs.avail_out = (uInt)out.size();
    int rc = inflate(&zs, Z_FINISH);
    inflateEnd(&zs);
    return rc == Z_STREAM_END && zs.total_out == (uLong)raw_size;
}

// ---------------------------------------------------------------------------
// blocks
// ---------------------------------------------------------------------------

struct Block {
    int content_type = -1;
    int content_id = -1;
    std::vector<uint8_t> data;
};

static bool read_block(Cursor& c, Block& b) {
    int method = c.u8();
    b.content_type = c.u8();
    b.content_id = c.itf8();
    int32_t comp_size = c.itf8();
    int32_t raw_size = c.itf8();
    if (!c.ok || comp_size < 0 || raw_size < 0 || raw_size > MAX_BLOCK_RAW ||
        c.p + comp_size > c.end)
        return false;
    const uint8_t* payload = c.p;
    c.skip(comp_size);
    c.skip(4);  // CRC32 (v3)
    if (!c.ok) return false;
    switch (method) {
        case 0:  // raw
            b.data.assign(payload, payload + comp_size);
            return true;
        case 1:  // gzip
            return gzip_inflate_vec(payload, comp_size, b.data, raw_size);
        case 4:  // rANS 4x8
            return rans_uncompress(payload, comp_size, b.data) &&
                   (int64_t)b.data.size() == raw_size;
        default:  // bzip2/lzma/3.1 codecs unsupported
            return false;
    }
}

// ---------------------------------------------------------------------------
// encodings
// ---------------------------------------------------------------------------

struct Encoding {
    int codec = 0;  // 0 null, 1 external, 3 huffman, 4 b.a.len, 5 b.a.stop, 6 beta, 9 gamma
    int content_id = -1;
    // huffman: canonical table precomputed once at parse time (decode runs
    // per record x per feature — rebuilding it per symbol would dominate)
    std::vector<int32_t> symbols;
    std::vector<int32_t> lengths;
    std::vector<int32_t> canon_sym;   // sorted (len, sym) order
    std::vector<int32_t> canon_len;
    std::vector<int64_t> canon_code;
    // beta
    int32_t offset = 0;
    int32_t nbits = 0;
    // byte_array_stop
    uint8_t stop = 0;
    // byte_array_len nested (parsed once: [0]=lengths encoding, [1]=values)
    std::vector<Encoding> children;
};

static void build_canonical(Encoding& e) {
    size_t n = e.symbols.size();
    std::vector<size_t> order(n);
    for (size_t i = 0; i < n; i++) order[i] = i;
    // canonical order: ascending code length, ties by symbol value (spec §3.4)
    std::stable_sort(order.begin(), order.end(), [&](size_t a, size_t b) {
        return e.lengths[a] != e.lengths[b] ? e.lengths[a] < e.lengths[b]
                                            : e.symbols[a] < e.symbols[b];
    });
    e.canon_sym.resize(n);
    e.canon_len.resize(n);
    e.canon_code.resize(n);
    int64_t next_code = 0;
    int prev_len = n ? e.lengths[order[0]] : 0;
    for (size_t i = 0; i < n; i++) {
        e.canon_sym[i] = e.symbols[order[i]];
        e.canon_len[i] = e.lengths[order[i]];
        next_code <<= (e.canon_len[i] - prev_len);
        prev_len = e.canon_len[i];
        e.canon_code[i] = next_code++;
    }
}

struct BitReader {
    const uint8_t* p = nullptr;
    const uint8_t* end = nullptr;
    int bit = 0;
    bool ok = true;

    int read_bit() {
        if (p >= end) { ok = false; return 0; }
        int v = (*p >> (7 - bit)) & 1;
        if (++bit == 8) { bit = 0; p++; }
        return v;
    }
    int64_t read_bits(int n) {
        int64_t v = 0;
        for (int i = 0; i < n; i++) v = (v << 1) | read_bit();
        return v;
    }
};

struct Slice;  // fwd

struct Streams {
    std::map<int, Cursor> ext;  // content id -> cursor over external block
    BitReader core;
};

static bool parse_encoding(Cursor& c, Encoding& e) {
    e.codec = c.itf8();
    int32_t plen = c.itf8();
    if (!c.ok || c.p + plen > c.end) return false;
    Cursor pc{c.p, c.p + plen};
    c.skip(plen);
    switch (e.codec) {
        case 0:
            return true;
        case 1:  // EXTERNAL
            e.content_id = pc.itf8();
            return pc.ok;
        case 3: {  // HUFFMAN
            int32_t n = pc.itf8();
            if (n < 0 || n > (1 << 20)) return false;
            for (int i = 0; i < n && pc.ok; i++) e.symbols.push_back(pc.itf8());
            int32_t m = pc.itf8();
            if (m != n) return false;
            for (int i = 0; i < m && pc.ok; i++) e.lengths.push_back(pc.itf8());
            if (!pc.ok || e.symbols.size() != e.lengths.size()) return false;
            build_canonical(e);
            return true;
        }
        case 4: {  // BYTE_ARRAY_LEN: nested (lengths encoding, values encoding)
            e.children.resize(2);
            return parse_encoding(pc, e.children[0]) && parse_encoding(pc, e.children[1]);
        }
        case 5:  // BYTE_ARRAY_STOP
            e.stop = pc.u8();
            e.content_id = pc.itf8();
            return pc.ok;
        case 6:  // BETA
            e.offset = pc.itf8();
            e.nbits = pc.itf8();
            return pc.ok;
        case 9:  // GAMMA
            e.offset = pc.itf8();
            return pc.ok;
        default:
            return false;  // golomb/subexp/rice unsupported
    }
}

// canonical huffman decode over the precomputed table (build_canonical)
static bool huffman_decode(const Encoding& e, BitReader& br, int32_t& out) {
    size_t n = e.canon_sym.size();
    if (n == 1 || (n > 0 && e.canon_len[0] == 0)) {  // constant
        out = e.canon_sym[0];
        return true;
    }
    int64_t code = 0;
    int len = 0;
    size_t i = 0;  // table is length-sorted: scan forward as bits accrue
    while (br.ok && len <= 31) {
        code = (code << 1) | br.read_bit();
        len++;
        while (i < n && e.canon_len[i] < len) i++;
        for (size_t j = i; j < n && e.canon_len[j] == len; j++) {
            if (e.canon_code[j] == code) {
                out = e.canon_sym[j];
                return true;
            }
        }
    }
    return false;
}

static bool decode_int(const Encoding& e, Streams& s, int32_t& out);

static bool decode_byte(const Encoding& e, Streams& s, uint8_t& out) {
    switch (e.codec) {
        case 1: {
            auto it = s.ext.find(e.content_id);
            if (it == s.ext.end()) return false;
            out = it->second.u8();
            return it->second.ok;
        }
        case 3: {
            int32_t v;
            if (!huffman_decode(e, s.core, v)) return false;
            out = (uint8_t)v;
            return true;
        }
        case 6: {
            out = (uint8_t)(s.core.read_bits(e.nbits) - e.offset);
            return s.core.ok;
        }
        default:
            return false;
    }
}

static bool decode_int(const Encoding& e, Streams& s, int32_t& out) {
    switch (e.codec) {
        case 1: {  // EXTERNAL: ITF8 from the external stream
            auto it = s.ext.find(e.content_id);
            if (it == s.ext.end()) return false;
            out = it->second.itf8();
            return it->second.ok;
        }
        case 3:
            return huffman_decode(e, s.core, out);
        case 6:
            out = (int32_t)(s.core.read_bits(e.nbits)) - e.offset;
            return s.core.ok;
        case 9: {  // GAMMA
            int zeros = 0;
            while (s.core.ok && s.core.read_bit() == 0) zeros++;
            int64_t v = 1;
            for (int i = 0; i < zeros; i++) v = (v << 1) | s.core.read_bit();
            out = (int32_t)v - e.offset;
            return s.core.ok;
        }
        default:
            return false;
    }
}

static bool decode_byte_array(const Encoding& e, Streams& s, std::vector<uint8_t>& out,
                              int32_t known_len = -1) {
    out.clear();
    switch (e.codec) {
        case 1: {  // EXTERNAL with caller-known length
            if (known_len < 0) return false;
            auto it = s.ext.find(e.content_id);
            if (it == s.ext.end()) return false;
            Cursor& c = it->second;
            if (c.p + known_len > c.end) { c.ok = false; return false; }
            out.assign(c.p, c.p + known_len);
            c.skip(known_len);
            return true;
        }
        case 4: {  // BYTE_ARRAY_LEN (children parsed once at header time)
            if (e.children.size() != 2) return false;
            const Encoding& len_enc = e.children[0];
            const Encoding& val_enc = e.children[1];
            int32_t n;
            if (!decode_int(len_enc, s, n) || n < 0 || n > (1 << 28)) return false;
            if (val_enc.codec == 1) return decode_byte_array(val_enc, s, out, n);
            out.resize(n);
            for (int i = 0; i < n; i++)
                if (!decode_byte(val_enc, s, out[i])) return false;
            return true;
        }
        case 5: {  // BYTE_ARRAY_STOP
            auto it = s.ext.find(e.content_id);
            if (it == s.ext.end()) return false;
            Cursor& c = it->second;
            while (c.p < c.end && *c.p != e.stop) out.push_back(*c.p++);
            if (c.p < c.end) c.p++;  // consume stop
            return true;
        }
        default:
            return false;
    }
}

// ---------------------------------------------------------------------------
// compression header
// ---------------------------------------------------------------------------

struct CompHeader {
    bool ap_delta = true;
    bool rn_preserved = true;
    uint8_t sub[5][4] = {{1, 2, 3, 4}, {0, 2, 3, 4}, {0, 1, 3, 4}, {0, 1, 2, 4}, {0, 1, 2, 3}};
    std::map<uint16_t, Encoding> series;      // 2-char key -> encoding
    std::map<int32_t, Encoding> tag_enc;      // packed tag key -> encoding
    std::vector<std::vector<int32_t>> tag_lines;  // TD: tag ids per line
};

// pileup accumulation target: one contig window, (len, 4) base counts
struct PileupCtx {
    int32_t target_ref;
    int64_t start0;  // 0-based inclusive
    int64_t end0;    // 0-based exclusive
    const uint8_t* ref_seq;  // ASCII bases of the FULL target contig
    int64_t ref_len;
    int32_t* counts;  // (end0-start0, 4) row-major
};

// depth accumulation target: per-contig difference arrays with samtools
// depth -a -J -q -Q -l semantics — the CRAM twin of vctpu_bam_depth.
// Aligned (read-backed) positions pass the per-base quality filter from
// the record's quality array (missing qualities read as 0xFF = pass, as
// samtools treats '*' quals); deletions cover iff include_del; N
// (reference skips) never cover.
struct DepthCtx {
    const int64_t* contig_starts;  // per ref_id offset into diff_flat, -1 skip
    const int64_t* contig_lens;
    int32_t n_refs;
    int32_t* diff_flat;
    int32_t min_bq, min_mapq, min_len;
    int32_t include_del;
    uint32_t exclude_flags;
};

static inline int base_code(uint8_t ch) {
    switch (ch) {
        case 'A': case 'a': return 0;
        case 'C': case 'c': return 1;
        case 'G': case 'g': return 2;
        case 'T': case 't': return 3;
        default: return 4;
    }
}

static inline void pileup_add(PileupCtx* pc, int64_t ref_pos1, int code) {
    // ref_pos1 is 1-based; count aligned A/C/G/T bases inside the window
    if (code >= 4) return;
    int64_t off = ref_pos1 - 1 - pc->start0;
    if (off < 0 || off >= pc->end0 - pc->start0) return;
    pc->counts[off * 4 + code]++;
}

static uint16_t key2(const char* k) { return ((uint16_t)k[0] << 8) | (uint8_t)k[1]; }

static bool parse_comp_header(const Block& b, CompHeader& h) {
    Cursor c{b.data.data(), b.data.data() + b.data.size()};
    // preservation map
    int32_t psize = c.itf8();
    (void)psize;
    int32_t n = c.itf8();
    for (int i = 0; i < n && c.ok; i++) {
        uint16_t k = ((uint16_t)c.u8() << 8) | c.u8();
        if (k == key2("RN")) h.rn_preserved = c.u8() != 0;
        else if (k == key2("AP")) h.ap_delta = c.u8() != 0;
        else if (k == key2("RR")) c.u8();
        else if (k == key2("SM")) {
            // substitution matrix: one byte per ref base (ACGTN order); the
            // byte holds 2-bit codes for the four other bases in ACGTN
            // order; BS code k selects the alt whose assigned code == k
            for (int ri = 0; ri < 5; ri++) {
                uint8_t b = c.u8();
                int j = 0;
                for (int alt = 0; alt < 5; alt++) {
                    if (alt == ri) continue;
                    uint8_t code = (b >> (6 - 2 * j)) & 3;
                    h.sub[ri][code] = (uint8_t)alt;
                    j++;
                }
            }
        }
        else if (k == key2("TD")) {
            int32_t tdlen = c.itf8();
            const uint8_t* td = c.p;
            c.skip(tdlen);
            // TD: \0-separated lines of 3-byte tag descriptors
            std::vector<int32_t> line;
            for (int32_t j = 0; j < tdlen; j++) {
                if (td[j] == 0) {
                    h.tag_lines.push_back(line);
                    line.clear();
                } else if (j + 2 < tdlen) {
                    line.push_back(((int32_t)td[j] << 16) | ((int32_t)td[j + 1] << 8) | td[j + 2]);
                    j += 2;
                }
            }
        } else {
            return false;  // unknown preservation key: layout unknown
        }
    }
    // data series encodings
    int32_t dsize = c.itf8();
    (void)dsize;
    n = c.itf8();
    for (int i = 0; i < n && c.ok; i++) {
        uint16_t k = ((uint16_t)c.u8() << 8) | c.u8();
        Encoding e;
        if (!parse_encoding(c, e)) return false;
        h.series[k] = e;
    }
    // tag encodings
    int32_t tsize = c.itf8();
    (void)tsize;
    n = c.itf8();
    for (int i = 0; i < n && c.ok; i++) {
        int32_t k = c.itf8();
        Encoding e;
        if (!parse_encoding(c, e)) return false;
        h.tag_enc[k] = e;
    }
    return c.ok;
}

// ---------------------------------------------------------------------------
// record decode
// ---------------------------------------------------------------------------

struct RecOut {
    int32_t* ref_id;
    int64_t* pos;
    int32_t* span;
    int32_t* mapq;
    int32_t* flags;
    int32_t* read_len;
};

static bool get_enc(const CompHeader& h, const char* k, Encoding& e) {
    auto it = h.series.find(key2(k));
    if (it == h.series.end()) return false;
    e = it->second;
    return true;
}

// decode all records of one slice; returns count or -1
static int64_t decode_slice(const CompHeader& h, int container_ref,
                            const std::vector<Block>& blocks, RecOut out, int64_t out_off,
                            int64_t max_records, PileupCtx* pc = nullptr,
                            DepthCtx* dc = nullptr) {
    // slice header is blocks[0]
    Cursor sh{blocks[0].data.data(), blocks[0].data.data() + blocks[0].data.size()};
    int32_t slice_ref = sh.itf8();
    int32_t slice_start = sh.itf8();
    sh.itf8();  // span
    int32_t n_records = sh.itf8();
    sh.ltf8();  // record counter
    sh.itf8();  // n blocks
    int32_t n_ids = sh.itf8();
    for (int i = 0; i < n_ids; i++) sh.itf8();
    sh.itf8();  // embedded ref content id
    if (!sh.ok) return -1;

    Streams s;
    for (size_t i = 1; i < blocks.size(); i++) {
        const Block& b = blocks[i];
        if (b.content_type == 5)  // core
            s.core = BitReader{b.data.data(), b.data.data() + b.data.size(), 0, true};
        else if (b.content_type == 4)
            s.ext[b.content_id] = Cursor{b.data.data(), b.data.data() + b.data.size()};
    }

    Encoding eBF, eCF, eRI, eRL, eAP, eRG, eRN, eMF, eNS, eNP, eTS, eNF, eTL, eFN, eFC, eFP;
    Encoding eDL, eBA, eQS, eBS, eIN, eSC, eHC, ePD, eRS, eMQ, eBB, eQQ;
    bool hBF = get_enc(h, "BF", eBF), hCF = get_enc(h, "CF", eCF);
    bool hRI = get_enc(h, "RI", eRI), hRL = get_enc(h, "RL", eRL);
    bool hAP = get_enc(h, "AP", eAP), hRG = get_enc(h, "RG", eRG);
    bool hRN = get_enc(h, "RN", eRN), hMF = get_enc(h, "MF", eMF);
    bool hNS = get_enc(h, "NS", eNS), hNP = get_enc(h, "NP", eNP);
    bool hTS = get_enc(h, "TS", eTS), hNF = get_enc(h, "NF", eNF);
    bool hTL = get_enc(h, "TL", eTL), hFN = get_enc(h, "FN", eFN);
    bool hFC = get_enc(h, "FC", eFC), hFP = get_enc(h, "FP", eFP);
    bool hDL = get_enc(h, "DL", eDL), hBA = get_enc(h, "BA", eBA);
    bool hQS = get_enc(h, "QS", eQS), hBS = get_enc(h, "BS", eBS);
    bool hIN = get_enc(h, "IN", eIN), hSC = get_enc(h, "SC", eSC);
    bool hHC = get_enc(h, "HC", eHC), hPD = get_enc(h, "PD", ePD);
    bool hRS = get_enc(h, "RS", eRS), hMQ = get_enc(h, "MQ", eMQ);
    bool hBB = get_enc(h, "BB", eBB), hQQ = get_enc(h, "QQ", eQQ);
    if (!(hBF && hCF && hRL && hAP)) return -1;

    int64_t last_pos = slice_start;
    std::vector<uint8_t> scratch;
    // depth bookkeeping (hoisted: cleared per record)
    struct Seg { int64_t ref_start, read_start, len; int kind; };  // kind 1 = deletion
    std::vector<Seg> segs;
    std::vector<uint8_t> squal;
    for (int32_t r = 0; r < n_records; r++) {
        if (out_off + r >= max_records) return -4;  // caller grows the buffers
        int32_t bf, cf, ri = container_ref, rl, ap, v;
        if (!decode_int(eBF, s, bf)) return -1;
        if (!decode_int(eCF, s, cf)) return -1;
        if (container_ref == -2) {
            if (!hRI || !decode_int(eRI, s, ri)) return -1;
        } else {
            ri = (slice_ref != -2) ? slice_ref : container_ref;
        }
        if (!decode_int(eRL, s, rl)) return -1;
        if (!decode_int(eAP, s, ap)) return -1;
        int64_t pos;
        if (h.ap_delta) {
            pos = last_pos + ap;
            last_pos = pos;
        } else {
            pos = ap;
        }
        if (hRG && !decode_int(eRG, s, v)) return -1;
        if (h.rn_preserved) {
            if (!hRN || !decode_byte_array(eRN, s, scratch)) return -1;
        }
        if (cf & 0x2) {  // detached mate
            if (!hMF || !decode_int(eMF, s, v)) return -1;
            if (!h.rn_preserved) {
                if (!hRN || !decode_byte_array(eRN, s, scratch)) return -1;
            }
            if (!hNS || !decode_int(eNS, s, v)) return -1;
            if (!hNP || !decode_int(eNP, s, v)) return -1;
            if (!hTS || !decode_int(eTS, s, v)) return -1;
        } else if (cf & 0x4) {  // mate downstream
            if (!hNF || !decode_int(eNF, s, v)) return -1;
        }
        int32_t tl = -1;
        if (hTL && !decode_int(eTL, s, tl)) return -1;
        if (hTL && tl >= 0 && (size_t)tl < h.tag_lines.size()) {
            for (int32_t tag_key : h.tag_lines[tl]) {
                auto it = h.tag_enc.find(tag_key);
                if (it == h.tag_enc.end()) return -1;
                if (!decode_byte_array(it->second, s, scratch)) return -1;
            }
        }
        int32_t span = rl;
        int32_t mapq = 0;
        if ((bf & 4) == 0) {  // mapped
            int32_t fn;
            if (!decode_int(eFN, s, fn)) return -1;
            int32_t soft = 0, ins = 0, dels = 0, skips = 0, hard = 0;
            // base reconstruction for pileup: bases between features match
            // the reference; X applies the SM substitution matrix
            bool do_pile = pc != nullptr && ri == pc->target_ref && (bf & 0x704) == 0;
            bool do_depth = dc != nullptr && ri >= 0 && ri < dc->n_refs &&
                            dc->contig_starts[ri] >= 0 &&
                            ((uint32_t)bf & dc->exclude_flags) == 0 && rl >= dc->min_len;
            const bool want_q = do_depth && dc->min_bq > 0;
            const bool track = do_pile || do_depth;
            segs.clear();
            if (want_q) squal.assign((size_t)rl, 0xFF);  // missing quals pass -q
            int64_t fabs_pos = 0;  // absolute 1-based in-read feature position
            int64_t rcur = 1;      // next read position to emit
            int64_t refp = pos;    // its reference position (1-based)
            auto ref_char = [&](int64_t p1) -> int {
                return (p1 >= 1 && p1 <= pc->ref_len) ? base_code(pc->ref_seq[p1 - 1]) : 4;
            };
            auto set_q = [&](int64_t read_pos1, uint8_t q) {
                if (want_q && read_pos1 >= 1 && read_pos1 <= rl) squal[read_pos1 - 1] = q;
            };
            auto aligned_run = [&](int64_t n) {  // n read bases consuming ref
                if (n <= 0) return;
                if (do_depth) segs.push_back({refp, rcur, n, 0});
                rcur += n;
                refp += n;
            };
            auto emit_matches = [&](int64_t upto) {
                int64_t n = upto - rcur;
                if (n <= 0) return;
                if (do_pile)
                    for (int64_t t = 0; t < n; t++) pileup_add(pc, refp + t, ref_char(refp + t));
                aligned_run(n);
            };
            for (int32_t f = 0; f < fn; f++) {
                uint8_t fc;
                int32_t fp;
                if (!decode_byte(eFC, s, fc)) return -1;
                if (!decode_int(eFP, s, fp)) return -1;
                fabs_pos += fp;
                if (track) emit_matches(fabs_pos);
                uint8_t bb;
                switch (fc) {
                    case 'B':
                        if (!hBA || !decode_byte(eBA, s, bb)) return -1;
                        if (do_pile) pileup_add(pc, refp, base_code(bb));
                        if (track) aligned_run(1);
                        if (!hQS || !decode_byte(eQS, s, bb)) return -1;
                        set_q(fabs_pos, bb);
                        break;
                    case 'X':
                        if (!hBS || !decode_int(eBS, s, v)) return -1;
                        if (do_pile) {
                            int rc = ref_char(refp);
                            int alt = rc < 4 ? h.sub[rc][v & 3] : 4;
                            pileup_add(pc, refp, alt);
                        }
                        if (track) aligned_run(1);
                        break;
                    case 'I':
                        if (!hIN || !decode_byte_array(eIN, s, scratch)) return -1;
                        ins += (int32_t)scratch.size();
                        if (track) rcur += (int64_t)scratch.size();
                        break;
                    case 'S':
                        if (!hSC || !decode_byte_array(eSC, s, scratch)) return -1;
                        soft += (int32_t)scratch.size();
                        if (track) rcur += (int64_t)scratch.size();
                        break;
                    case 'D':
                        if (!hDL || !decode_int(eDL, s, v)) return -1;
                        dels += v;
                        if (do_depth && dc->include_del && v > 0)
                            segs.push_back({refp, rcur, v, 1});
                        if (track) refp += v;
                        break;
                    case 'i':
                        if (!hBA || !decode_byte(eBA, s, bb)) return -1;
                        ins += 1;
                        if (track) rcur += 1;
                        break;
                    case 'N':  // reference skip: never covers (samtools parity)
                        if (!hRS || !decode_int(eRS, s, v)) return -1;
                        skips += v;
                        if (track) refp += v;
                        break;
                    case 'P':
                        if (!hPD || !decode_int(ePD, s, v)) return -1;
                        break;
                    case 'H':
                        if (!hHC || !decode_int(eHC, s, v)) return -1;
                        hard += v;
                        break;
                    case 'Q':
                        if (!hQS || !decode_byte(eQS, s, bb)) return -1;
                        set_q(fabs_pos, bb);
                        break;
                    case 'b':
                        if (!hBB || !decode_byte_array(eBB, s, scratch)) return -1;
                        if (do_pile)
                            for (size_t t = 0; t < scratch.size(); t++)
                                pileup_add(pc, refp + (int64_t)t, base_code(scratch[t]));
                        if (track) aligned_run((int64_t)scratch.size());
                        break;
                    case 'q':
                        if (!hQQ || !decode_byte_array(eQQ, s, scratch)) return -1;
                        for (size_t t = 0; t < scratch.size(); t++)
                            set_q(fabs_pos + (int64_t)t, scratch[t]);
                        break;
                    default:
                        return -1;
                }
            }
            if (track) emit_matches((int64_t)rl + 1);
            span = rl - soft - ins + dels + skips;
            if (!hMQ || !decode_int(eMQ, s, mapq)) return -1;
            if (cf & 0x1) {  // quality scores stored as array
                for (int32_t q = 0; q < rl; q++) {
                    uint8_t bb;
                    if (!hQS || !decode_byte(eQS, s, bb)) return -1;
                    if (want_q) squal[q] = bb;
                }
            }
            if (do_depth && mapq >= dc->min_mapq) {
                const int64_t base = dc->contig_starts[ri];
                const int64_t clen = dc->contig_lens[ri];
                for (const Seg& sg : segs) {
                    const int64_t ref0 = sg.ref_start - 1;  // 0-based
                    if (ref0 >= clen) continue;
                    if (sg.kind == 1 || dc->min_bq <= 0) {
                        const int64_t s0 = ref0 < 0 ? 0 : ref0;
                        const int64_t e0 = std::min(ref0 + sg.len, clen);
                        if (e0 > s0) {
                            dc->diff_flat[base + s0] += 1;
                            dc->diff_flat[base + e0] -= 1;
                        }
                    } else {
                        // RLE (qual >= min_bq) into diff updates, clamped by
                        // contig and quality-array bounds (vctpu_bam_depth
                        // run-length semantics)
                        int64_t run_s = -1;
                        int64_t max_j = std::min(sg.len, clen - ref0);
                        max_j = std::min(max_j, (int64_t)squal.size() - (sg.read_start - 1));
                        for (int64_t j = 0; j <= max_j; j++) {
                            bool okq = j < max_j && ref0 + j >= 0 &&
                                       (int32_t)squal[sg.read_start - 1 + j] >= dc->min_bq;
                            if (okq && run_s < 0) {
                                run_s = j;
                            } else if (!okq && run_s >= 0) {
                                dc->diff_flat[base + ref0 + run_s] += 1;
                                dc->diff_flat[base + ref0 + j] -= 1;
                                run_s = -1;
                            }
                        }
                    }
                }
            }
        } else {  // unmapped: bases then quals
            for (int32_t q = 0; q < rl; q++) {
                uint8_t bb;
                if (!hBA || !decode_byte(eBA, s, bb)) return -1;
            }
            if (cf & 0x1) {
                for (int32_t q = 0; q < rl; q++) {
                    uint8_t bb;
                    if (!hQS || !decode_byte(eQS, s, bb)) return -1;
                }
            }
        }
        if (out.ref_id != nullptr) {
            out.ref_id[out_off + r] = ri;
            out.pos[out_off + r] = pos;
            out.span[out_off + r] = span;
            out.mapq[out_off + r] = mapq;
            out.flags[out_off + r] = bf;
            out.read_len[out_off + r] = rl;
        }
    }
    return n_records;
}

}  // namespace cram

#include <algorithm>

extern "C" {

// SAM header text of a CRAM file -> out buffer; returns text length or
// negative (-1 malformed, -2 unsupported compression, -3 buffer too small).
static int64_t cram_header_impl(const uint8_t* buf, int64_t len, uint8_t* out, int64_t out_cap) {
    using namespace cram;
    if (len < 26 || memcmp(buf, "CRAM", 4) != 0) return -1;
    if (buf[4] != 3) return -2;  // major version
    Cursor c{buf + 26, buf + len};
    // first container = file header
    c.u32le();  // container length
    c.itf8(); c.itf8(); c.itf8(); c.itf8();  // ref id, start, span, n records
    c.ltf8(); c.ltf8();                      // counter, bases
    int32_t n_blocks = c.itf8();
    int32_t n_landmarks = c.itf8();
    for (int i = 0; i < n_landmarks; i++) c.itf8();
    c.skip(4);  // CRC
    if (!c.ok || n_blocks < 1) return -1;
    Block b;
    if (!read_block(c, b)) return -2;
    if (b.data.size() < 4) return -1;
    // block payload: int32 text length + SAM text
    int32_t text_len = (int32_t)b.data[0] | ((int32_t)b.data[1] << 8) |
                       ((int32_t)b.data[2] << 16) | ((int32_t)b.data[3] << 24);
    if (text_len < 0 || (size_t)text_len + 4 > b.data.size()) return -1;
    if (text_len > out_cap) return -3;
    memcpy(out, b.data.data() + 4, text_len);
    return text_len;
}

int64_t vctpu_cram_header(const uint8_t* buf, int64_t len, uint8_t* out, int64_t out_cap) {
    try {
        return cram_header_impl(buf, len, out, out_cap);
    } catch (...) {
        return -1;
    }
}

// Total record count across containers (header-only walk, no block decode).
// Lets callers allocate exact output buffers for scan. Negative on error.
int64_t vctpu_cram_count(const uint8_t* buf, int64_t len) {
    using namespace cram;
    if (len < 26 || memcmp(buf, "CRAM", 4) != 0) return -1;
    if (buf[4] != 3) return -2;
    Cursor c{buf + 26, buf + len};
    int64_t total = 0;
    bool first = true;
    bool saw_eof = false;
    while (c.ok && c.p < c.end) {
        int32_t cont_len = (int32_t)c.u32le();
        int32_t ref = c.itf8();
        c.itf8();
        c.itf8();
        int32_t n_rec = c.itf8();
        c.ltf8();
        c.ltf8();
        int32_t n_blocks = c.itf8();
        int32_t n_landmarks = c.itf8();
        for (int i = 0; i < n_landmarks; i++) c.itf8();
        c.skip(4);
        if (!c.ok || cont_len < 0 || c.p + cont_len > c.end) break;
        const uint8_t* body = c.p;
        if (ref == -1 && n_rec == 0 && n_blocks <= 1 && c.p + cont_len >= c.end) {
            saw_eof = true;
            break;
        }
        if (!first) total += n_rec;
        first = false;
        c = Cursor{body + cont_len, buf + len};
    }
    // no EOF container => truncated/corrupt stream, not a short file
    return saw_eof ? total : -1;
}

static int64_t cram_scan_impl(const uint8_t* buf, int64_t len, int64_t max_records,
                              int32_t* ref_id, int64_t* pos, int32_t* span, int32_t* mapq,
                              int32_t* flags, int32_t* read_len,
                              cram::PileupCtx* pctx = nullptr,
                              cram::DepthCtx* dctx = nullptr) {
    using namespace cram;
    if (len < 26 || memcmp(buf, "CRAM", 4) != 0) return -1;
    if (buf[4] != 3) return -2;
    Cursor c{buf + 26, buf + len};
    int64_t total = 0;
    bool first = true;
    bool saw_eof = false;
    while (c.ok && c.p < c.end) {
        const uint8_t* cont_start = c.p;
        int32_t cont_len = (int32_t)c.u32le();
        int32_t ref = c.itf8();
        int32_t start = c.itf8();
        (void)start;
        c.itf8();  // span
        int32_t n_rec = c.itf8();
        c.ltf8();  // counter
        c.ltf8();  // bases
        int32_t n_blocks = c.itf8();
        int32_t n_landmarks = c.itf8();
        for (int i = 0; i < n_landmarks; i++) c.itf8();
        c.skip(4);  // CRC
        // corrupt container length must neither rewind the cursor (infinite
        // loop) nor run past the buffer (OOB read)
        if (!c.ok || cont_len < 0 || c.p + cont_len > c.end) break;
        const uint8_t* body = c.p;
        // EOF container: ref -1, no records, 38-byte standard marker
        if (ref == -1 && n_rec == 0 && n_blocks <= 1 && c.p + cont_len >= c.end) {
            saw_eof = true;
            break;
        }
        if (first) {  // file header container
            first = false;
            c = Cursor{body + cont_len, buf + len};
            continue;
        }
        if (n_rec == 0) {  // e.g. multi-container EOF variants
            c = Cursor{body + cont_len, buf + len};
            continue;
        }
        // pileup/depth-only walks skip single-ref containers whose contig
        // contributes nothing — per-region work must not decode the genome
        // (multi-ref containers, ref == -2, still decode)
        if (ref_id == nullptr && ref >= 0) {
            bool skip = pctx != nullptr && dctx == nullptr && ref != pctx->target_ref;
            if (dctx != nullptr && pctx == nullptr &&
                (ref >= dctx->n_refs || dctx->contig_starts[ref] < 0))
                skip = true;
            if (skip) {
                c = Cursor{body + cont_len, buf + len};
                continue;
            }
        }
        Cursor cc{body, body + cont_len};
        Block chb;
        if (!read_block(cc, chb) || chb.content_type != 1) return -2;
        CompHeader h;
        if (!parse_comp_header(chb, h)) return -2;
        // remaining blocks: slices (each: slice header block + data blocks)
        while (cc.ok && cc.p < cc.end) {
            Block shb;
            if (!read_block(cc, shb)) return -2;
            if (shb.content_type != 2) break;
            // slice header tells how many data blocks follow
            Cursor sh{shb.data.data(), shb.data.data() + shb.data.size()};
            sh.itf8(); sh.itf8(); sh.itf8(); sh.itf8();
            sh.ltf8();
            int32_t s_blocks = sh.itf8();
            if (!sh.ok) return -1;
            std::vector<Block> blocks;
            blocks.push_back(shb);
            for (int32_t i = 0; i < s_blocks; i++) {
                Block db;
                if (!read_block(cc, db)) return -2;
                blocks.push_back(std::move(db));
            }
            RecOut out{ref_id, pos, span, mapq, flags, read_len};
            int64_t n = decode_slice(h, ref, blocks, out, total, max_records, pctx, dctx);
            if (n < 0) return n == -4 ? -4 : -1;
            total += n;
        }
        c = Cursor{body + cont_len, buf + len};
        (void)cont_start;
    }
    // a stream without its EOF container was truncated mid-write/transfer
    return saw_eof ? total : -1;
}

// Decode all alignment records. Returns record count, or negative on error.
// Exception barrier: corrupt inputs must produce error codes at the ctypes
// boundary, never C++ exceptions (which would abort the Python process).
int64_t vctpu_cram_scan(const uint8_t* buf, int64_t len, int64_t max_records,
                        int32_t* ref_id, int64_t* pos, int32_t* span, int32_t* mapq,
                        int32_t* flags, int32_t* read_len) {
    try {
        return cram_scan_impl(buf, len, max_records, ref_id, pos, span, mapq, flags, read_len);
    } catch (...) {
        return -1;
    }
}

// Base-level pileup over [start0, end0) of one contig: records are decoded
// (streams are sequential so every record is consumed) and aligned bases
// reconstructed from the reference + SM substitution matrix. ``counts`` is
// (end0-start0, 4) row-major A/C/G/T. Returns records seen, negative on error.
int64_t vctpu_cram_pileup(const uint8_t* buf, int64_t len, int32_t target_ref,
                          int64_t start0, int64_t end0,
                          const uint8_t* ref_seq, int64_t ref_len, int32_t* counts) {
    try {
        cram::PileupCtx ctx{target_ref, start0, end0, ref_seq, ref_len, counts};
        return cram_scan_impl(buf, len, INT64_MAX, nullptr, nullptr, nullptr, nullptr,
                              nullptr, nullptr, &ctx);
    } catch (...) {
        return -1;
    }
}

// Per-contig depth difference arrays with samtools depth -a -J -q -Q -l
// semantics (the CRAM twin of vctpu_bam_depth; reference call site
// coverage_analysis.py:674-678 — the `-q` base-quality filter applies to
// aligned read bases from the record's quality array, deletions cover iff
// include_del, N skips never cover). diff_flat holds the selected contigs
// back to back; contig_starts[ref_id] is that contig's (length+1)-long
// region offset or -1 to skip. Returns records seen, negative on error.
int64_t vctpu_cram_depth(const uint8_t* buf, int64_t len,
                         const int64_t* contig_starts, const int64_t* contig_lens,
                         int32_t n_refs, int32_t* diff_flat,
                         int32_t min_bq, int32_t min_mapq, int32_t min_len,
                         int32_t include_del, uint32_t exclude_flags) {
    try {
        cram::DepthCtx ctx{contig_starts, contig_lens, n_refs, diff_flat,
                           min_bq, min_mapq, min_len, include_del, exclude_flags};
        return cram_scan_impl(buf, len, INT64_MAX, nullptr, nullptr, nullptr, nullptr,
                              nullptr, nullptr, nullptr, &ctx);
    } catch (...) {
        return -1;
    }
}

}  // extern "C"
