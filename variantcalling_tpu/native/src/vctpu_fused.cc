// Fused whole-chunk scoring: parse output -> window featurize -> forest
// walk in ONE native call (ROADMAP item 4, "tear down the scoring wall").
//
// The pre-fusion native hot path crossed the ctypes boundary four times
// per chunk (per-contig featurize_gather into six full columns, then the
// column->tile->walk pass re-reading them), with Python glue between the
// crossings serializing under the GIL while other chunk workers waited.
// This entry runs the whole per-chunk scoring body tile-at-a-time: each
// 8192-row tile fills its host feature columns (the SAME fill_tile the
// matrix path uses), computes the six window-derived features straight
// out of the encoded contig (the SAME featurize_row the per-contig path
// uses — windows are never materialized), and walks the forest while the
// tile is L2-hot. The six device-feature columns never exist as arrays,
// saving two full sweeps of 24 B/variant, and the chunk makes ONE
// boundary crossing.
//
// Margins are bit-identical to the unfused path by construction: same
// featurize_row, same fill_tile casts, same forest_walk_tile accumulation
// order (the engine contract, docs/robustness.md). The Python-side
// unfused path stays in the tree as the byte-parity reference
// (VCTPU_NATIVE_FUSED=0 selects it; the parity matrix in
// tests/unit/test_fused_native.py locks fused == reference == jit).

#include <atomic>
#include <cstdint>
#include <vector>

#include "vctpu_feat_row.h"
#include "vctpu_forest_tile.h"
#include "vctpu_threads.h"

using vctpu_feat::featurize_geometry_ok;
using vctpu_feat::featurize_row;
using vctpu_feat::flow_lookup_init;
using vctpu_forest::Node;
using vctpu_forest::fill_tile;
using vctpu_forest::forest_walk_tile;
using vctpu_forest::pack_nodes;

extern "C" {

// Score one chunk end to end. Rows are grouped into contig RUNS (sorted
// VCFs put each contig in one contiguous row range — featurize._contig_runs):
// run r covers rows [run_bounds[r], run_bounds[r+1]) and reads windows
// from run_seqs[r] (encoded contig, len run_seq_lens[r]; a contig missing
// from the FASTA passes len 0 and every window reads all-N, exactly like
// the per-contig fallback). Host feature columns arrive as typed column
// pointers in feature order; the six window-derived features name their
// column slot via dev_cols (order: hmer_len, hmer_nuc, gc, cyc,
// left_motif, right_motif) and carry dtype -1 in `dtypes` so fill_tile
// skips them. aggregation: 0 mean / 1 logit_sum / 2 raw sum (engine-
// parity callers use 2 and finalize on the host). Returns 0, or <0 on
// bad arguments.
int64_t vctpu_fused_chunk_score(
    const void* const* run_seqs, const int64_t* run_seq_lens,
    const int64_t* run_bounds, int32_t n_runs,
    const int64_t* pos0, int64_t n, int32_t radius,
    const uint8_t* is_indel, const int32_t* indel_nuc,
    const int32_t* ref_code, const int32_t* alt_code, const uint8_t* is_snp,
    const int32_t* flow_order,
    const void* const* cols, const int32_t* dtypes, int32_t f,
    const int32_t* dev_cols,  // (6,) column index per device feature, or -1
    const int32_t* feat, const float* thr,
    const int32_t* left, const int32_t* right, const float* value,
    const uint8_t* default_left,
    int32_t t, int32_t m, int32_t max_depth,
    int32_t aggregation, float base_score,
    float* out) try
{
    const int32_t w = 2 * radius + 1;
    if (n < 0 || f <= 0 || t <= 0 || m <= 0 || max_depth <= 0) return -1;
    if (aggregation < 0 || aggregation > 2) return -1;
    if (n_runs < 0 || radius <= 0 || w > 512 || !featurize_geometry_ok(w, radius))
        return -1;
    if (n_runs > 0 && (run_bounds[0] != 0 || run_bounds[n_runs] != n)) return -1;
    for (int32_t r = 0; r < n_runs; ++r)
        if (run_bounds[r + 1] < run_bounds[r] || run_seq_lens[r] < 0) return -1;
    for (int32_t j = 0; j < f; ++j)
        if (dtypes[j] > 4) return -2;
    for (int32_t k = 0; k < 6; ++k)
        if (dev_cols[k] >= f) return -2;
    int32_t lookup[5];
    if (!flow_lookup_init(flow_order, lookup)) return -2;

    std::vector<Node> nodes;
    pack_nodes(nodes, feat, thr, left, right, value, default_left, (int64_t)t * m);
    const bool has_dl = default_left != nullptr;

    const int64_t BLOCK = 8192;
    std::atomic<int> failed{0};
    vctpu::for_shards((n + BLOCK - 1) / BLOCK, vctpu::nthreads(),
                      [&](int, int64_t b_lo, int64_t b_hi) {
        std::vector<float> tile;
        std::vector<int32_t> di32;  // hl, hn, cyc, lm, rm per tile row
        std::vector<float> dgc;
        try {
            tile.resize((size_t)BLOCK * f);
            di32.resize((size_t)BLOCK * 5);
            dgc.resize((size_t)BLOCK);
        } catch (...) {
            failed.store(1);
            return;
        }
        int32_t* hl = di32.data();
        int32_t* hn = hl + BLOCK;
        int32_t* cy = hn + BLOCK;
        int32_t* lm = cy + BLOCK;
        int32_t* rm = lm + BLOCK;
        int32_t run = 0;  // per-shard run cursor; rows ascend within a shard
        for (int64_t lo = b_lo * BLOCK; lo < b_hi * BLOCK && lo < n; lo += BLOCK) {
            const int64_t hi = lo + BLOCK < n ? lo + BLOCK : n;
            fill_tile(cols, dtypes, f, lo, hi, tile.data());
            // window features straight out of each row's contig run
            while (run < n_runs && run_bounds[run + 1] <= lo) ++run;
            int32_t rr = run;
            uint8_t pad[512];
            for (int64_t i = lo; i < hi; ++i) {
                while (rr < n_runs && run_bounds[rr + 1] <= i) ++rr;
                const uint8_t* seq = rr < n_runs
                    ? (const uint8_t*)run_seqs[rr] : nullptr;
                const int64_t seq_len = rr < n_runs ? run_seq_lens[rr] : 0;
                const int64_t wlo = pos0[i] - radius;
                const uint8_t* row;
                if (seq != nullptr && wlo >= 0 && wlo + w <= seq_len) {
                    row = seq + wlo;  // interior: zero-copy view
                } else {
                    for (int32_t j = 0; j < w; ++j) {
                        const int64_t p = wlo + j;
                        pad[j] = (seq != nullptr && p >= 0 && p < seq_len)
                                 ? seq[p] : 4;
                    }
                    row = pad;
                }
                const int64_t li = i - lo;
                featurize_row(row, w, radius, li,
                              is_indel + lo, indel_nuc + lo, ref_code + lo,
                              alt_code + lo, is_snp + lo, lookup,
                              hl, hn, dgc.data(), cy, lm, rm);
            }
            // scatter the six device features into their tile columns —
            // the same (float)int32 cast fill_tile's case 1 applies, so
            // the assembled row bits match the unfused reference exactly
            const int64_t count = hi - lo;
            const int32_t* icols[5] = {hl, hn, cy, lm, rm};
            const int32_t islot[5] = {dev_cols[0], dev_cols[1], dev_cols[3],
                                      dev_cols[4], dev_cols[5]};
            for (int32_t k = 0; k < 5; ++k) {
                if (islot[k] < 0) continue;
                float* d = tile.data() + islot[k];
                const int32_t* s = icols[k];
                for (int64_t i = 0; i < count; ++i) d[(size_t)i * f] = (float)s[i];
            }
            if (dev_cols[2] >= 0) {  // gc_content: float32 passthrough
                float* d = tile.data() + dev_cols[2];
                for (int64_t i = 0; i < count; ++i) d[(size_t)i * f] = dgc[i];
            }
            forest_walk_tile(nodes.data(), tile.data(), count, f, t, m,
                             max_depth, has_dl, aggregation, base_score,
                             out + lo);
        }
    }, 2);
    return failed.load() ? -1 : 0;
} catch (...) {
    return -1;  // bad_alloc / thread-spawn failure must not cross the C ABI
}

}  // extern "C"
