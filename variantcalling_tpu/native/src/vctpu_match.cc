// Native haplotype-aware variant matcher (vcfeval-equivalent core).
//
// Faithful port of comparison/matcher.py::match_contig — the reference
// delegates TP/FP/FN matching to rtg vcfeval (Java) as a black box
// (docs/run_comparison_pipeline.md:3-5); this framework's engine is
// in-process. Python remains the specification (and the fallback); the
// parity fuzz test asserts identical outputs on random + adversarial
// inputs. Stages: normalize -> exact join on (pos, ref, alt) -> bounded
// diploid haplotype search over gap-clustered residue, run at the allele
// level then the genotype level with failed-cluster memoization.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <map>
#include <set>
#include <string>
#include <unordered_map>
#include <vector>

namespace vmatch {

static const int MAX_CLUSTER_VARIANTS = 16;  // mirror matcher.py caps
static const int MAX_HETS = 12;
static const size_t PHASING_BEAM = 4096;  // dedup-BFS state cap (matcher.py)
static const int64_t CLUSTER_GAP = 30;
static const int64_t FLANK = 10;

struct Variant {
    int64_t pos = 0;  // 1-based
    std::string ref;
    std::vector<std::string> alts;
    int8_t gt[2] = {-1, -1};
};

struct Key {
    int64_t pos;
    std::string ref;
    std::string alt;
    bool operator==(const Key& o) const {
        return pos == o.pos && ref == o.ref && alt == o.alt;
    }
    bool operator<(const Key& o) const {
        if (pos != o.pos) return pos < o.pos;
        if (ref != o.ref) return ref < o.ref;
        return alt < o.alt;
    }
};

struct KeyHash {
    size_t operator()(const Key& k) const {
        size_t h = std::hash<int64_t>()(k.pos);
        h = h * 1000003 ^ std::hash<std::string>()(k.ref);
        h = h * 1000003 ^ std::hash<std::string>()(k.alt);
        return h;
    }
};

static bool symbolic_alt(const std::string& a) {
    return a == "." || a.empty() || a == "*" || a == "<NON_REF>" ||
           (!a.empty() && a[0] == '<');
}

// matcher.py::normalize_variant — trim shared suffix then prefix
static Key normalize(int64_t pos, std::string ref, std::string alt) {
    while (ref.size() > 1 && alt.size() > 1 && ref.back() == alt.back()) {
        ref.pop_back();
        alt.pop_back();
    }
    while (ref.size() > 1 && alt.size() > 1 && ref[0] == alt[0]) {
        ref.erase(0, 1);
        alt.erase(0, 1);
        pos += 1;
    }
    return Key{pos, std::move(ref), std::move(alt)};
}

// matcher.py::_called_allele_keys
static std::set<Key> called_allele_keys(const Variant& v) {
    std::set<int> called;
    for (int j = 0; j < 2; j++)
        if (v.gt[j] > 0) called.insert(v.gt[j]);
    std::set<Key> out;
    if (called.empty()) {  // no GT: all alts
        for (const auto& a : v.alts)
            if (!symbolic_alt(a)) out.insert(normalize(v.pos, v.ref, a));
        return out;
    }
    for (int ai : called) {
        if (ai - 1 < (int)v.alts.size()) {
            const std::string& a = v.alts[ai - 1];
            if (!symbolic_alt(a)) out.insert(normalize(v.pos, v.ref, a));
        }
    }
    return out;
}

// matcher.py::_gt_equivalent — same zygosity over equivalent alleles
static std::vector<std::string> gt_pattern(const Variant& v) {
    std::vector<int> g;
    for (int j = 0; j < 2; j++)
        if (v.gt[j] >= 0) g.push_back(v.gt[j]);
    std::vector<std::string> keys;
    if (g.empty()) {
        keys.push_back("('any',)");
        return keys;
    }
    std::sort(g.begin(), g.end());
    for (int a : g) {
        if (a == 0) {
            keys.push_back("('ref',)");
        } else if (a - 1 < (int)v.alts.size()) {
            Key k = normalize(v.pos, v.ref, v.alts[a - 1]);
            // mirror python str() of the tuple (pos, ref, alt)
            keys.push_back("(" + std::to_string(k.pos) + ", '" + k.ref + "', '" + k.alt + "')");
        }
    }
    std::sort(keys.begin(), keys.end());
    return keys;
}

static bool gt_equivalent(const Variant& a, const Variant& b) {
    auto pa = gt_pattern(a), pb = gt_pattern(b);
    if (pa == pb) return true;
    std::vector<std::string> any{"('any',)"};
    return pa == any || pb == any;
}

// matcher.py::_apply — non-overlapping edits over the window
static bool apply_edits(const std::string& window,
                        std::vector<std::tuple<int64_t, int64_t, std::string>> edits,
                        std::string& out) {
    std::sort(edits.begin(), edits.end());
    out.clear();
    int64_t cur = 0;
    for (auto& [s0, e0, alt] : edits) {
        if (s0 < cur || e0 > (int64_t)window.size() || s0 < 0) return false;
        out.append(window, cur, s0 - cur);
        out.append(alt);
        cur = e0;
    }
    out.append(window, cur, window.size() - cur);
    return true;
}

// One partial haplotype of the dedup-BFS: sequence built so far + the
// reference position consumed through (matcher.py::_extend_hap).
struct PartialHap {
    std::string built;
    int64_t cur = 0;
    bool operator<(const PartialHap& o) const {
        if (built != o.built) return built < o.built;
        return cur < o.cur;
    }
    bool operator==(const PartialHap& o) const { return built == o.built && cur == o.cur; }
};

static bool extend_hap(const PartialHap& h, const std::string& window, int64_t s0, int64_t e0,
                       const std::string& alt, PartialHap& out) {
    if (s0 < h.cur || e0 > (int64_t)window.size() || s0 < 0) return false;
    out.built.assign(h.built);
    out.built.append(window, h.cur, s0 - h.cur);
    out.built.append(alt);
    out.cur = e0;
    return true;
}

// matcher.py::_diploid_haplotypes — all {hapA, hapB} pairs over phasings,
// enumerated by a dedup-BFS over sorted edits (unordered partial pairs,
// deduplicated per step) instead of 2^hets masks. Exact whenever the
// state count stays within PHASING_BEAM. Returns false with capped=true
// when the search hit MAX_HETS / the beam (caller counts the exact-only
// degradation); false with capped=false when no phasing replays.
static bool diploid_haplotypes(const std::vector<Variant>& side, const std::vector<int>& idx,
                               int64_t lo, const std::string& window,
                               std::set<std::pair<std::string, std::string>>& out,
                               bool& capped) {
    struct Edit {
        int64_t s0, e0;
        std::string alt;
        bool both;
        bool operator<(const Edit& o) const {
            if (s0 != o.s0) return s0 < o.s0;
            if (e0 != o.e0) return e0 < o.e0;
            return alt < o.alt;
        }
    };
    capped = false;
    std::vector<Edit> applied;
    int n_hets = 0;
    for (int k : idx) {
        const Variant& v = side[k];
        std::vector<int> g;
        for (int j = 0; j < 2; j++)
            if (v.gt[j] >= 0) g.push_back(v.gt[j]);
        std::set<int> alleles;
        for (int a : g)
            if (a > 0) alleles.insert(a);
        if (alleles.empty() && !v.alts.empty()) alleles.insert(1);
        for (int ai : alleles) {
            if (ai - 1 >= (int)v.alts.size()) return false;
            const std::string& alt = v.alts[ai - 1];
            if (symbolic_alt(alt)) continue;
            int64_t s0 = v.pos - lo;
            int64_t e0 = s0 + (int64_t)v.ref.size();
            int nz = 0;
            bool has_ref = false;
            int count_ai = 0;
            for (int a : g) {
                if (a > 0) nz++;
                if (a == 0) has_ref = true;
                if (a == ai) count_ai++;
            }
            bool hom = (int)g.size() >= 2 && count_ai == nz && !has_ref;
            applied.push_back({s0, e0, alt, hom});
            if (!hom) n_hets++;
        }
    }
    if (n_hets > MAX_HETS) {
        capped = true;
        return false;
    }
    std::sort(applied.begin(), applied.end());

    using State = std::pair<PartialHap, PartialHap>;  // kept ordered (a <= b)
    std::set<State> states;
    states.insert({PartialHap{}, PartialHap{}});
    PartialHap na, nb;
    for (const Edit& e : applied) {
        std::set<State> next;
        for (const State& st : states) {
            if (e.both) {
                if (extend_hap(st.first, window, e.s0, e.e0, e.alt, na) &&
                    extend_hap(st.second, window, e.s0, e.e0, e.alt, nb)) {
                    if (nb < na) std::swap(na, nb);
                    next.insert({na, nb});
                }
            } else {
                if (extend_hap(st.first, window, e.s0, e.e0, e.alt, na)) {
                    nb = st.second;
                    if (nb < na) std::swap(na, nb);
                    next.insert({na, nb});
                }
                if (extend_hap(st.second, window, e.s0, e.e0, e.alt, nb)) {
                    na = st.first;
                    if (nb < na) std::swap(na, nb);
                    next.insert({na, nb});
                }
            }
        }
        if (next.empty()) return false;  // no phasing can replay these edits
        if (next.size() > PHASING_BEAM) {
            capped = true;
            return false;
        }
        states.swap(next);
    }

    out.clear();
    for (const State& st : states) {
        std::string a = st.first.built;
        a.append(window, st.first.cur, window.size() - st.first.cur);
        std::string b = st.second.built;
        b.append(window, st.second.cur, window.size() - st.second.cur);
        if (a <= b)
            out.insert({a, b});
        else
            out.insert({b, a});
    }
    return !out.empty();
}

// matcher.py::_clusters — gap-bounded residue clusters over both sides
struct Cluster {
    std::vector<int> c_idx, t_idx;
};

static std::vector<Cluster> make_clusters(const std::vector<Variant>& calls,
                                          const std::vector<Variant>& truth,
                                          const std::vector<int>& un_c,
                                          const std::vector<int>& un_t) {
    struct Ev {
        int64_t pos;
        int side;
        int idx;
        bool operator<(const Ev& o) const {
            if (pos != o.pos) return pos < o.pos;
            if (side != o.side) return side < o.side;
            return idx < o.idx;
        }
    };
    std::vector<Ev> evs;
    for (int i : un_c) evs.push_back({calls[i].pos, 0, i});
    for (int j : un_t) evs.push_back({truth[j].pos, 1, j});
    std::sort(evs.begin(), evs.end());
    std::vector<Cluster> out;
    Cluster cur;
    bool have_last = false;
    int64_t last = 0;
    for (const Ev& e : evs) {
        if (have_last && e.pos - last > CLUSTER_GAP && (!cur.c_idx.empty() || !cur.t_idx.empty())) {
            out.push_back(std::move(cur));
            cur = Cluster();
        }
        (e.side == 0 ? cur.c_idx : cur.t_idx).push_back(e.idx);
        last = e.pos;
        have_last = true;
    }
    if (!cur.c_idx.empty() || !cur.t_idx.empty()) out.push_back(std::move(cur));
    return out;
}

static void match_contig(const std::string& ref_seq, std::vector<Variant>& calls,
                         std::vector<Variant>& truth, uint8_t* call_tp, uint8_t* call_tp_gt,
                         uint8_t* truth_tp, uint8_t* truth_tp_gt, int64_t* call_truth_idx,
                         bool haplotype_rescue, int64_t* stats) {
    stats[0] = stats[1] = 0;  // capped clusters / variants in them (allele pass)
    size_t nc = calls.size(), nt = truth.size();
    std::fill(call_tp, call_tp + nc, 0);
    std::fill(call_tp_gt, call_tp_gt + nc, 0);
    std::fill(truth_tp, truth_tp + nt, 0);
    std::fill(truth_tp_gt, truth_tp_gt + nt, 0);
    std::fill(call_truth_idx, call_truth_idx + nc, -1);

    // ---- stage 2: exact normalized-key join (first truth wins, as python
    // dict setdefault) --------------------------------------------------
    std::unordered_map<Key, int, KeyHash> truth_by_key;
    for (size_t j = 0; j < nt; j++)
        for (const Key& k : called_allele_keys(truth[j]))
            truth_by_key.emplace(k, (int)j);
    for (size_t i = 0; i < nc; i++) {
        auto ck = called_allele_keys(calls[i]);
        if (ck.empty()) continue;
        std::set<int> hit_truth;
        size_t hits = 0;
        int first_j = -1;
        for (const Key& k : ck) {
            auto it = truth_by_key.find(k);
            if (it != truth_by_key.end()) {
                hits++;
                hit_truth.insert(it->second);
                if (first_j < 0) first_j = it->second;
            }
        }
        if (hits == ck.size()) {  // every called allele present in truth
            call_tp[i] = 1;
            call_truth_idx[i] = first_j;
            for (int jj : hit_truth) truth_tp[jj] = 1;
            if (hit_truth.size() == 1 && gt_equivalent(calls[i], truth[*hit_truth.begin()])) {
                call_tp_gt[i] = 1;
                truth_tp_gt[*hit_truth.begin()] = 1;
            }
        }
    }

    if (!haplotype_rescue) return;

    // ---- stage 3: bounded haplotype search, allele then genotype level --
    std::set<std::pair<std::vector<int>, std::vector<int>>> failed;
    for (int level = 0; level < 2; level++) {
        std::vector<int> un_c, un_t;
        for (size_t i = 0; i < nc; i++)
            if (!(level == 0 ? call_tp[i] : call_tp_gt[i])) un_c.push_back((int)i);
        for (size_t j = 0; j < nt; j++)
            if (!(level == 0 ? truth_tp[j] : truth_tp_gt[j])) un_t.push_back((int)j);
        for (const Cluster& cl : make_clusters(calls, truth, un_c, un_t)) {
            if (cl.c_idx.empty() || cl.t_idx.empty()) continue;
            auto ckey = std::make_pair(cl.c_idx, cl.t_idx);
            if (failed.count(ckey)) continue;
            if (level == 0) failed.insert(ckey);  // removed below on success
            if ((int)cl.c_idx.size() > MAX_CLUSTER_VARIANTS ||
                (int)cl.t_idx.size() > MAX_CLUSTER_VARIANTS) {
                if (level == 0) {
                    stats[0] += 1;
                    stats[1] += (int64_t)cl.c_idx.size() + (int64_t)cl.t_idx.size();
                }
                continue;
            }
            int64_t lo = INT64_MAX, hi = INT64_MIN;
            for (int i : cl.c_idx) {
                lo = std::min(lo, calls[i].pos);
                hi = std::max(hi, calls[i].pos + (int64_t)calls[i].ref.size());
            }
            for (int j : cl.t_idx) {
                lo = std::min(lo, truth[j].pos);
                hi = std::max(hi, truth[j].pos + (int64_t)truth[j].ref.size());
            }
            lo -= FLANK;
            hi += FLANK;
            lo = std::max<int64_t>(lo, 1);
            int64_t w_lo = lo - 1;
            int64_t w_hi = std::min<int64_t>(hi - 1, (int64_t)ref_seq.size());
            if (w_hi < w_lo) w_hi = w_lo;
            std::string window = ref_seq.substr(
                std::min<int64_t>(w_lo, (int64_t)ref_seq.size()), w_hi - w_lo);
            std::set<std::pair<std::string, std::string>> hc, ht;
            bool cap_c = false, cap_t = false;
            // both sides always evaluated (python parity: capped_t counts
            // even when the call side already failed un-capped)
            bool ok_c = diploid_haplotypes(calls, cl.c_idx, lo, window, hc, cap_c);
            bool ok_t = diploid_haplotypes(truth, cl.t_idx, lo, window, ht, cap_t);
            if (!ok_c || !ok_t) {
                if ((cap_c || cap_t) && level == 0) {
                    stats[0] += 1;
                    stats[1] += (int64_t)cl.c_idx.size() + (int64_t)cl.t_idx.size();
                }
                continue;
            }
            bool inter = false;
            for (const auto& p : hc)
                if (ht.count(p)) {
                    inter = true;
                    break;
                }
            if (inter) {
                failed.erase(ckey);
                for (int i : cl.c_idx) {
                    call_tp[i] = 1;
                    call_tp_gt[i] = 1;
                }
                for (int j : cl.t_idx) {
                    truth_tp[j] = 1;
                    truth_tp_gt[j] = 1;
                }
            }
        }
    }
}

// unpack one side from blob layout: ref/alt strings are plain-concatenated,
// delimited by the (n+1) byte-offset array (native/__init__.py::_pack);
// alts are comma-separated within a record, "" meaning no alts
static void unpack(std::vector<Variant>& out, int64_t n, const int64_t* pos,
                   const uint8_t* ref_blob, const int64_t* ref_offs, const uint8_t* alt_blob,
                   const int64_t* alt_offs, const int8_t* gt) {
    out.resize(n);
    for (int64_t i = 0; i < n; i++) {
        Variant& v = out[i];
        v.pos = pos[i];
        v.ref.assign((const char*)ref_blob + ref_offs[i], ref_offs[i + 1] - ref_offs[i]);
        std::string alts((const char*)alt_blob + alt_offs[i], alt_offs[i + 1] - alt_offs[i]);
        v.alts.clear();
        if (!alts.empty()) {  // "" = no alts; "." stays a literal entry
            size_t start = 0;
            while (start <= alts.size()) {
                size_t comma = alts.find(',', start);
                if (comma == std::string::npos) {
                    v.alts.push_back(alts.substr(start));
                    break;
                }
                v.alts.push_back(alts.substr(start, comma - start));
                start = comma + 1;
            }
        }
        v.gt[0] = gt[i * 2];
        v.gt[1] = gt[i * 2 + 1];
    }
}

}  // namespace vmatch

extern "C" {

int64_t vctpu_match_contig(
    const uint8_t* ref_seq, int64_t ref_len,
    int64_t n_calls, const int64_t* c_pos, const uint8_t* c_ref_blob, const int64_t* c_ref_offs,
    const uint8_t* c_alt_blob, const int64_t* c_alt_offs, const int8_t* c_gt,
    int64_t n_truth, const int64_t* t_pos, const uint8_t* t_ref_blob, const int64_t* t_ref_offs,
    const uint8_t* t_alt_blob, const int64_t* t_alt_offs, const int8_t* t_gt,
    int32_t haplotype_rescue,
    uint8_t* call_tp, uint8_t* call_tp_gt, uint8_t* truth_tp, uint8_t* truth_tp_gt,
    int64_t* call_truth_idx, int64_t* stats) {
    try {
        std::string seq((const char*)ref_seq, ref_len);
        std::vector<vmatch::Variant> calls, truth;
        vmatch::unpack(calls, n_calls, c_pos, c_ref_blob, c_ref_offs, c_alt_blob, c_alt_offs, c_gt);
        vmatch::unpack(truth, n_truth, t_pos, t_ref_blob, t_ref_offs, t_alt_blob, t_alt_offs, t_gt);
        vmatch::match_contig(seq, calls, truth, call_tp, call_tp_gt, truth_tp, truth_tp_gt,
                             call_truth_idx, haplotype_rescue != 0, stats);
        return 0;
    } catch (...) {
        return -1;
    }
}

}  // extern "C"
