// Window featurization — CPU twin of ops/features.py's device kernels.
//
// The TPU path computes these as one fused XLA program so feature tensors
// never leave HBM (ops/features.py: gc_content, hmer_indel_features,
// motif_codes, cycle_skip_status; reference semantics per
// ugbio_core.vcfbed.variant_annotation / ugvc cycleskip column).  On a
// single CPU core the same math is a single pass over each 41-byte window
// row here, ~10x XLA:CPU's multi-kernel lowering.  Semantics are an EXACT
// match of the jitted kernels (locked by tests/unit parity tests):
//
// - gc_content: GC fraction over +-10 around the anchor, N excluded from
//   the denominator (int counts, f32 divide — bitwise-identical result).
// - hmer: run length of the reference homopolymer starting at center+1,
//   capped at min(40, window end); hmer iff indel with single-nucleotide
//   unit matching the base at center+1.
// - motifs: base-5 packed k=5-mers adjacent to the anchor.
// - cycle-skip: flow-signature comparison of ref vs alt local haplotype
//   (context 4): differing flow counts -> 2, same count but different
//   run-carrying flow positions -> 1, else 0; non-SNP -> -1.  The flow
//   signature is the closed form of ops/features._flow_signature: each
//   maximal base run consumes (pos - prev_pos) mod 4 flows (first run:
//   pos + 1), truncated at the first N.

#include <cmath>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

#include "vctpu_feat_row.h"
#include "vctpu_threads.h"

using vctpu_feat::featurize_geometry_ok;
using vctpu_feat::featurize_row;
using vctpu_feat::flow_lookup_init;

extern "C" {

// returns 0 on success, <0 on bad arguments.
int64_t vctpu_featurize_windows(
    const uint8_t* windows,     // (n, w) base codes A0 C1 G2 T3 N4
    int64_t n, int32_t w, int32_t center,
    const uint8_t* is_indel,    // (n,)
    const int32_t* indel_nuc,   // (n,) 0..3 single-nuc unit, else 4
    const int32_t* ref_code,    // (n,)
    const int32_t* alt_code,    // (n,)
    const uint8_t* is_snp,      // (n,)
    const int32_t* flow_order,  // (4,) base codes in flow-cycle order
    int32_t* hmer_len,          // out (n,)
    int32_t* hmer_nuc,          // out (n,)
    float* gc,                  // out (n,)
    int32_t* cyc,               // out (n,)
    int32_t* left_motif,        // out (n,)
    int32_t* right_motif)       // out (n,)
{
    if (n < 0 || !featurize_geometry_ok(w, center)) return -1;
    int32_t lookup[5];
    if (!flow_lookup_init(flow_order, lookup)) return -2;
    // rows are independent and outputs disjoint: shard across threads
    vctpu::for_shards(n, vctpu::nthreads(), [&](int, int64_t r_lo, int64_t r_hi) {
        for (int64_t i = r_lo; i < r_hi; ++i) {
            featurize_row(windows + (size_t)i * w, w, center, i, is_indel, indel_nuc,
                          ref_code, alt_code, is_snp, lookup,
                          hmer_len, hmer_nuc, gc, cyc, left_motif, right_motif);
        }
    });
    return 0;
}

// Fused gather + featurize over one contig: each row's reference window
// is read straight out of the encoded contig (a pointer for interior
// positions, a small padded stack copy at contig edges) — the (n, w)
// window tensor is never materialized, saving two full sweeps of ~8
// bytes/variant/window-byte on the 5M hot path. Semantically identical
// to vctpu_gather_windows (out-of-contig bases read as N) followed by
// vctpu_featurize_windows.
int64_t vctpu_featurize_gather(
    const uint8_t* seq, int64_t seq_len,
    const int64_t* pos0, int64_t n, int32_t radius,
    const uint8_t* is_indel, const int32_t* indel_nuc,
    const int32_t* ref_code, const int32_t* alt_code, const uint8_t* is_snp,
    const int32_t* flow_order,
    int32_t* hmer_len, int32_t* hmer_nuc, float* gc, int32_t* cyc,
    int32_t* left_motif, int32_t* right_motif)
{
    const int32_t w = 2 * radius + 1;
    if (n < 0 || radius <= 0 || w > 512 || seq_len < 0 ||
        !featurize_geometry_ok(w, radius))
        return -1;
    int32_t lookup[5];
    if (!flow_lookup_init(flow_order, lookup)) return -2;
    vctpu::for_shards(n, vctpu::nthreads(), [&](int, int64_t r_lo, int64_t r_hi) {
        uint8_t pad[512];
        for (int64_t i = r_lo; i < r_hi; ++i) {
            const int64_t lo = pos0[i] - radius;
            const uint8_t* row;
            if (lo >= 0 && lo + w <= seq_len) {
                row = seq + lo;  // interior: zero-copy view into the contig
            } else {
                for (int32_t j = 0; j < w; ++j) {
                    const int64_t p = lo + j;
                    pad[j] = (p >= 0 && p < seq_len) ? seq[p] : 4;
                }
                row = pad;
            }
            featurize_row(row, w, radius, i, is_indel, indel_nuc,
                          ref_code, alt_code, is_snp, lookup,
                          hmer_len, hmer_nuc, gc, cyc, left_motif, right_motif);
        }
    });
    return 0;
}

// Reference-window gather for one contig: out[i] = seq[pos0[i]-radius ..
// pos0[i]+radius], out-of-contig positions read as N (code 4) — the
// C++ twin of featurize.gather_windows' padded fancy-index gather.
int64_t vctpu_gather_windows(
    const uint8_t* seq, int64_t seq_len,
    const int64_t* pos0, int64_t n, int32_t radius,
    uint8_t* out)  // (n, 2*radius+1)
{
    if (n < 0 || radius <= 0 || seq_len < 0) return -1;
    const int32_t w = 2 * radius + 1;
    vctpu::for_shards(n, vctpu::nthreads(), [&](int, int64_t r_lo, int64_t r_hi) {
        for (int64_t i = r_lo; i < r_hi; ++i) {
            const int64_t c = pos0[i];
            uint8_t* row = out + (size_t)i * w;
            const int64_t lo = c - radius, hi = c + radius + 1;
            if (lo >= 0 && hi <= seq_len) {  // fully inside: straight copy
                const uint8_t* s = seq + lo;
                for (int32_t j = 0; j < w; ++j) row[j] = s[j];
            } else {
                for (int32_t j = 0; j < w; ++j) {
                    const int64_t p = lo + j;
                    row[j] = (p >= 0 && p < seq_len) ? seq[p] : 4;
                }
            }
        }
    });
    return 0;
}

namespace {

// %g-identical fast formatter for |v| < 100 where v is exactly the
// nearest double to k/10^4 for integer k: at most 6 significant digits,
// fixed notation, trailing zeros trimmed — precisely what printf %g
// emits for this domain. The filter pipeline's TREE_SCORE column
// (np.round(score, 4)) lands here, avoiding ~300ns of snprintf per
// record on the 5M writeback path. Returns length or 0 (use snprintf).
inline int fast_g4(double v, char* out) {
    if (!(v > -100.0 && v < 100.0)) return 0;
    if (v == 0.0 && std::signbit(v)) return 0;  // %g prints -0.0 as "-0"
    const long long k = std::llround(v * 10000.0);
    if ((double)k / 10000.0 != v) return 0;  // not an exact 4-decimal value
    int len = 0;
    long long a = k;
    if (a < 0) {
        out[len++] = '-';
        a = -a;
    }
    const long long ip = a / 10000, fp = a % 10000;
    if (ip >= 10) out[len++] = (char)('0' + ip / 10);
    out[len++] = (char)('0' + ip % 10);
    if (fp) {
        char d[4] = {(char)('0' + fp / 1000), (char)('0' + (fp / 100) % 10),
                     (char)('0' + (fp / 10) % 10), (char)('0' + fp % 10)};
        int last = 3;
        while (d[last] == '0') --last;  // fp != 0 -> terminates
        out[len++] = '.';
        for (int j = 0; j <= last; ++j) out[len++] = d[j];
    }
    return len;
}

}  // namespace

// Per-record ";KEY=<%g>" INFO suffixes for one float column (NaN ->
// empty) — the filter pipeline's TREE_SCORE writeback formatter, printf
// %g exactly like numpy's b"%g" so the byte-splicing output is unchanged.
// DELIBERATELY serial: a provisional-offset sharded variant was measured
// 2x SLOWER at 2 threads (each shard writes into the sparse worst-case
// region of the fresh output buffer and the compaction re-touches it —
// page-fault traffic doubles, dwarfing the ~45ns/row format cost), and
// in the streaming pipeline this call already parallelizes ACROSS chunks
// on the IO pool (ctypes releases the GIL). Returns total bytes written,
// or -1 when cap is too small.
int64_t vctpu_format_float_info(
    const double* vals, int64_t n,
    const uint8_t* prefix, int64_t prefix_len,  // b";KEY="
    uint8_t* out_buf, int64_t cap,
    int64_t* out_offs)                          // (n+1,)
{
    int64_t pos = 0;
    out_offs[0] = 0;
    for (int64_t i = 0; i < n; ++i) {
        const double v = vals[i];
        if (!std::isnan(v)) {
            if (pos + prefix_len + 32 > cap) return -1;
            for (int64_t j = 0; j < prefix_len; ++j) out_buf[pos + j] = prefix[j];
            pos += prefix_len;
            int fl = fast_g4(v, (char*)out_buf + pos);
            pos += fl ? fl : std::snprintf((char*)out_buf + pos, 32, "%g", v);
        }
        out_offs[i + 1] = pos;
    }
    return pos;
}

}  // extern "C"
