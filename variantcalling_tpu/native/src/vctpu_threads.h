// Shard-parallel execution for the native host engine.
//
// The reference parallelizes host work by forking whole processes around
// external binaries (e.g. samtools fan-out, coverage_analysis.py:653-683
// in /root/reference); this engine threads WITHIN the process so flat
// output arrays are produced in place with no IPC or merge copies. Every
// user splits its work into contiguous shards whose outputs land in
// disjoint ranges of preallocated buffers, so no locks are needed and the
// result is byte-identical to the serial path regardless of thread count.
//
// VCTPU_NATIVE_THREADS caps the shard count (default: hardware
// concurrency). On a single-core host the helpers degrade to a plain
// serial call with zero overhead.

#pragma once

#include <algorithm>
#include <cstdint>
#include <cstdlib>
#include <thread>
#include <vector>

namespace vctpu {

inline int nthreads() {
    const char* e = std::getenv("VCTPU_NATIVE_THREADS");
    long n = e ? std::strtol(e, nullptr, 10) : (long)std::thread::hardware_concurrency();
    if (n < 1) n = 1;
    if (n > 128) n = 128;
    return (int)n;
}

// Run f(shard, lo, hi) over [0, n) split into at most max_shards
// contiguous ranges of at least min_per_shard items each (small inputs
// stay serial: thread create+join dwarfs the work below a few thousand
// items — e.g. hundreds of decoy contigs with a handful of variants
// each). Shard 0 runs on the calling thread. Thread-spawn failure
// (bad_alloc / pid-limit system_error) degrades to running the
// unspawned shards serially — no exception ever crosses the caller's
// extern "C" boundary from here. Returns the number of shards used.
template <class F>
inline int for_shards(int64_t n, int max_shards, F&& f, int64_t min_per_shard = 4096) {
    int t_count = max_shards;
    if (min_per_shard > 0 && (int64_t)t_count > n / min_per_shard)
        t_count = (int)std::max<int64_t>(n / min_per_shard, 1);
    if ((int64_t)t_count > n) t_count = n > 0 ? (int)n : 1;
    if (t_count <= 1) {
        f(0, (int64_t)0, n);
        return 1;
    }
    const int64_t per = (n + t_count - 1) / t_count;
    std::vector<std::thread> workers;
    int64_t unspawned_lo = -1;
    try {
        workers.reserve(t_count - 1);
        for (int t = 1; t < t_count; ++t) {
            const int64_t lo = (int64_t)t * per;
            const int64_t hi = std::min(n, lo + per);
            if (lo >= hi) break;
            try {
                workers.emplace_back([&f, t, lo, hi] { f(t, lo, hi); });
            } catch (...) {
                unspawned_lo = lo;  // run [lo, n) on this thread below
                break;
            }
        }
    } catch (...) {
        unspawned_lo = per;  // reserve() threw: nothing spawned yet
    }
    f(0, (int64_t)0, std::min(per, n));
    if (unspawned_lo >= 0 && unspawned_lo < n) {
        // shard indices don't matter to correctness (ranges define the
        // output split); reuse the failed shard's own ranges serially
        for (int64_t lo = unspawned_lo; lo < n; lo += per)
            f((int)(lo / per), lo, std::min(n, lo + per));
    }
    for (auto& w : workers) w.join();
    return t_count;
}

}  // namespace vctpu
