// vctpu native engine: BGZF codec + BAM depth walker + interval membership.
//
// Host-side hot loops behind the TPU ingest layer. The reference gets these
// from external C binaries (samtools depth: coverage_analysis.py:653-683 in
// /root/reference; bgzip/tabix: bash/index_vcf_file.sh) — here they are
// in-process, produce flat arrays ready for device transfer, and are loaded
// via ctypes (no pybind11 in the image). Python fallbacks live beside every
// call site (io/bam.py, io/bgzf.py); this library is the measured path.
//
// Build: g++ -O3 -shared -fPIC vctpu_native.cc -lz  (see native/__init__.py)

#include <zlib.h>

#include <algorithm>
#include <cstdint>
#include <cstring>

namespace {

// Parse one gzip member header starting at src[off]; return the BGZF BSIZE
// (total block length) from the BC extra subfield, or -1 if not BGZF-framed.
int64_t bgzf_block_size(const uint8_t* src, int64_t n, int64_t off) {
    if (off + 18 > n) return -1;
    if (src[off] != 0x1f || src[off + 1] != 0x8b) return -1;
    if (!(src[off + 3] & 4)) return -1;  // FEXTRA required for BGZF
    uint16_t xlen = (uint16_t)src[off + 10] | ((uint16_t)src[off + 11] << 8);
    int64_t xoff = off + 12;
    int64_t xend = xoff + xlen;
    if (xend > n) return -1;
    while (xoff + 4 <= xend) {
        uint8_t s1 = src[xoff], s2 = src[xoff + 1];
        uint16_t slen = (uint16_t)src[xoff + 2] | ((uint16_t)src[xoff + 3] << 8);
        if (xoff + 4 + slen > xend) return -1;
        if (s1 == 'B' && s2 == 'C' && slen == 2) {
            int64_t bsize = ((int64_t)src[xoff + 4] | ((int64_t)src[xoff + 5] << 8)) + 1;
            return bsize;
        }
        xoff += 4 + slen;
    }
    return -1;
}

}  // namespace

extern "C" {

// Sum of ISIZE trailers across BGZF blocks (exact uncompressed size).
// Returns -1 when the stream is not pure BGZF framing (caller falls back).
int64_t vctpu_bgzf_uncompressed_size(const uint8_t* src, int64_t n) {
    int64_t off = 0, total = 0;
    while (off < n) {
        int64_t bsize = bgzf_block_size(src, n, off);
        if (bsize < 0 || bsize < 28 || off + bsize > n) return -1;
        uint32_t isize;
        std::memcpy(&isize, src + off + bsize - 4, 4);
        total += isize;
        off += bsize;
    }
    return off == n ? total : -1;
}

// Inflate a concatenated-gzip-member stream (BGZF is one) into dst.
// Returns bytes written, or -1 on error / capacity overflow.
int64_t vctpu_gzip_inflate(const uint8_t* src, int64_t n, uint8_t* dst, int64_t cap) {
    z_stream zs;
    std::memset(&zs, 0, sizeof zs);
    if (inflateInit2(&zs, 15 + 32) != Z_OK) return -1;  // auto gzip header
    int64_t in_off = 0, out_off = 0;
    int ret = Z_OK;
    uint8_t scratch[64];  // overflow detector for zero-output tail members
    while (in_off < n || ret == Z_OK) {
        uInt in_chunk = (uInt)std::min<int64_t>(n - in_off, 1 << 30);
        uInt out_chunk = (uInt)std::min<int64_t>(cap - out_off, 1 << 30);
        bool use_scratch = out_chunk == 0;
        zs.next_in = const_cast<uint8_t*>(src) + in_off;
        zs.avail_in = in_chunk;
        zs.next_out = use_scratch ? scratch : dst + out_off;
        zs.avail_out = use_scratch ? (uInt)sizeof scratch : out_chunk;
        uInt gave = zs.avail_out;
        ret = inflate(&zs, Z_NO_FLUSH);
        in_off += in_chunk - zs.avail_in;
        int64_t produced = (int64_t)(gave - zs.avail_out);
        if (use_scratch && produced > 0) {
            inflateEnd(&zs);
            return -1;  // capacity exhausted: member produced real output
        }
        if (!use_scratch) out_off += produced;
        if (ret == Z_STREAM_END) {
            if (in_off >= n) break;          // done: all members consumed
            if (inflateReset2(&zs, 15 + 32) != Z_OK) {  // next member
                inflateEnd(&zs);
                return -1;
            }
            ret = Z_OK;
            continue;
        }
        if (ret != Z_OK) {
            inflateEnd(&zs);
            return -1;
        }
        if (zs.avail_in == in_chunk && produced == 0) break;  // no progress
    }
    inflateEnd(&zs);
    return out_off;
}

// Deflate src into independent BGZF blocks (<=65280B payload each) with the
// BC extra field + canonical EOF sentinel. Returns bytes written or -1.
int64_t vctpu_bgzf_compress(const uint8_t* src, int64_t n, uint8_t* dst, int64_t cap, int level) {
    static const uint8_t EOF_BLOCK[28] = {0x1f, 0x8b, 0x08, 0x04, 0, 0, 0, 0, 0, 0xff, 0x06, 0x00,
                                          0x42, 0x43, 0x02, 0x00, 0x1b, 0x00, 0x03, 0x00, 0, 0, 0,
                                          0, 0, 0, 0, 0};
    const int64_t CHUNK = 65280;
    int64_t in_off = 0, out_off = 0;
    while (in_off < n) {
        int64_t len = std::min(CHUNK, n - in_off);
        z_stream zs;
        std::memset(&zs, 0, sizeof zs);
        if (deflateInit2(&zs, level, Z_DEFLATED, -15, 8, Z_DEFAULT_STRATEGY) != Z_OK) return -1;
        uint8_t body[1 << 17];
        zs.next_in = const_cast<uint8_t*>(src) + in_off;
        zs.avail_in = (uInt)len;
        zs.next_out = body;
        zs.avail_out = sizeof body;
        int ret = deflate(&zs, Z_FINISH);
        int64_t deflated = (int64_t)(sizeof body) - zs.avail_out;
        deflateEnd(&zs);
        if (ret != Z_STREAM_END) return -1;
        int64_t bsize = deflated + 26;  // header(18) + crc/isize(8)
        if (bsize > 0xFFFF + 1) return -1;
        if (out_off + bsize > cap) return -1;
        uint8_t* h = dst + out_off;
        const uint8_t head[12] = {0x1f, 0x8b, 0x08, 0x04, 0, 0, 0, 0, 0, 0xff, 0x06, 0x00};
        std::memcpy(h, head, 12);
        h[12] = 'B';
        h[13] = 'C';
        h[14] = 2;
        h[15] = 0;
        uint16_t bs16 = (uint16_t)(bsize - 1);
        std::memcpy(h + 16, &bs16, 2);
        std::memcpy(h + 18, body, deflated);
        uint32_t crc = (uint32_t)crc32(0L, src + in_off, (uInt)len);
        uint32_t isize = (uint32_t)len;
        std::memcpy(h + 18 + deflated, &crc, 4);
        std::memcpy(h + 22 + deflated, &isize, 4);
        out_off += bsize;
        in_off += len;
    }
    if (out_off + 28 > cap) return -1;
    std::memcpy(dst + out_off, EOF_BLOCK, 28);
    return out_off + 28;
}

// Walk uncompressed BAM alignment records (buf starts at the first record,
// i.e. after the header + reference list) and accumulate per-contig depth
// difference arrays with samtools-depth semantics (-a -J -q -Q -l;
// reference call site coverage_analysis.py:653-683).
//
// diff_flat holds all selected contigs back to back; contig_starts[ref_id]
// is the offset of that contig's (length+1)-long diff region, or -1 to skip.
// Returns records seen, or -1 on malformed input.
int64_t vctpu_bam_depth(const uint8_t* buf, int64_t n, const int64_t* contig_starts,
                        const int64_t* contig_lens, int32_t n_refs, int32_t* diff_flat,
                        int32_t min_bq, int32_t min_mapq, int32_t min_len, int32_t include_del,
                        uint32_t exclude_flags) {
    int64_t off = 0, count = 0;
    while (off + 4 <= n) {
        int32_t bs;
        std::memcpy(&bs, buf + off, 4);
        if (bs < 32 || off + 4 + bs > n) return -1;
        const uint8_t* r = buf + off + 4;
        off += 4 + bs;
        count++;
        int32_t ref_id, pos, l_seq;
        uint32_t lrn, flag_nc;
        std::memcpy(&ref_id, r, 4);
        std::memcpy(&pos, r + 4, 4);
        std::memcpy(&lrn, r + 8, 4);
        std::memcpy(&flag_nc, r + 12, 4);
        std::memcpy(&l_seq, r + 16, 4);
        uint32_t l_read_name = lrn & 0xff;
        int32_t mapq = (int32_t)((lrn >> 8) & 0xff);
        uint32_t n_cigar = flag_nc & 0xffff;
        uint32_t flag = flag_nc >> 16;
        if ((flag & exclude_flags) || ref_id < 0 || ref_id >= n_refs || pos < 0) continue;
        if (mapq < min_mapq || l_seq < min_len) continue;
        int64_t base = contig_starts[ref_id];
        if (base < 0) continue;
        int64_t clen = contig_lens[ref_id];
        const uint8_t* cig = r + 32 + l_read_name;
        const uint8_t* qual = cig + 4 * (int64_t)n_cigar + (l_seq + 1) / 2;
        if (cig + 4 * (int64_t)n_cigar > buf + off || qual + l_seq > buf + off) return -1;
        int64_t ref_pos = pos, read_pos = 0;
        for (uint32_t i = 0; i < n_cigar; i++) {
            uint32_t c;
            std::memcpy(&c, cig + 4 * (int64_t)i, 4);
            uint32_t op = c & 0xf;
            int64_t len = c >> 4;
            bool match_like = (op == 0 || op == 7 || op == 8);  // M, =, X
            bool covers = match_like || (include_del && op == 2);
            if (covers && ref_pos < clen) {
                if (!match_like || min_bq <= 0) {
                    int64_t s = ref_pos, e = std::min(ref_pos + len, clen);
                    diff_flat[base + s] += 1;
                    diff_flat[base + e] -= 1;
                } else {
                    // run-length encode (qual >= min_bq) into diff updates;
                    // clamp by l_seq too in case the CIGAR overruns the quals
                    int64_t s = -1;
                    int64_t max_j = std::min({len, clen - ref_pos, (int64_t)l_seq - read_pos});
                    for (int64_t j = 0; j <= max_j; j++) {
                        bool ok = (j < max_j) && ((int32_t)qual[read_pos + j] >= min_bq);
                        if (ok && s < 0) {
                            s = j;
                        } else if (!ok && s >= 0) {
                            diff_flat[base + ref_pos + s] += 1;
                            diff_flat[base + ref_pos + j] -= 1;
                            s = -1;
                        }
                    }
                }
            }
            if (op == 0 || op == 2 || op == 3 || op == 7 || op == 8) ref_pos += len;  // ref-consuming
            if (op == 0 || op == 1 || op == 4 || op == 7 || op == 8) read_pos += len;  // read-consuming
        }
    }
    return count;
}

// Membership of each position in a set of sorted, non-overlapping,
// half-open [start, end) intervals. out[i] = 1 if covered.
void vctpu_interval_membership(const int64_t* starts, const int64_t* ends, int64_t n_iv,
                               const int64_t* pos, int64_t n_pos, uint8_t* out) {
    for (int64_t i = 0; i < n_pos; i++) {
        int64_t p = pos[i];
        // rightmost interval with start <= p
        int64_t lo = 0, hi = n_iv;
        while (lo < hi) {
            int64_t mid = (lo + hi) / 2;
            if (starts[mid] <= p)
                lo = mid + 1;
            else
                hi = mid;
        }
        out[i] = (lo > 0 && p < ends[lo - 1]) ? 1 : 0;
    }
}

}  // extern "C"
