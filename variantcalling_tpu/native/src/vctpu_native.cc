// vctpu native engine: BGZF codec + BAM depth walker + interval membership.
//
// Host-side hot loops behind the TPU ingest layer. The reference gets these
// from external C binaries (samtools depth: coverage_analysis.py:653-683 in
// /root/reference; bgzip/tabix: bash/index_vcf_file.sh) — here they are
// in-process, produce flat arrays ready for device transfer, and are loaded
// via ctypes (no pybind11 in the image). Python fallbacks live beside every
// call site (io/bam.py, io/bgzf.py); this library is the measured path.
//
// Both formats are block-parallel by design (BGZF: independent gzip
// members; VCF: independent record lines), so the hot entry points shard
// across threads (vctpu_threads.h) with byte-identical output to the
// serial path. VCTPU_NATIVE_THREADS controls the fan-out.
//
// Build: g++ -O3 -shared -fPIC vctpu_native.cc -lz  (see native/__init__.py)

#include <zlib.h>

#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <vector>

#include "vctpu_threads.h"

namespace {

// Parse one gzip member header starting at src[off]; return the BGZF BSIZE
// (total block length) from the BC extra subfield, or -1 if not BGZF-framed.
int64_t bgzf_block_size(const uint8_t* src, int64_t n, int64_t off) {
    if (off + 18 > n) return -1;
    if (src[off] != 0x1f || src[off + 1] != 0x8b) return -1;
    if (!(src[off + 3] & 4)) return -1;  // FEXTRA required for BGZF
    uint16_t xlen = (uint16_t)src[off + 10] | ((uint16_t)src[off + 11] << 8);
    int64_t xoff = off + 12;
    int64_t xend = xoff + xlen;
    if (xend > n) return -1;
    while (xoff + 4 <= xend) {
        uint8_t s1 = src[xoff], s2 = src[xoff + 1];
        uint16_t slen = (uint16_t)src[xoff + 2] | ((uint16_t)src[xoff + 3] << 8);
        if (xoff + 4 + slen > xend) return -1;
        if (s1 == 'B' && s2 == 'C' && slen == 2) {
            int64_t bsize = ((int64_t)src[xoff + 4] | ((int64_t)src[xoff + 5] << 8)) + 1;
            return bsize;
        }
        xoff += 4 + slen;
    }
    return -1;
}

}  // namespace

extern "C" {

// Sum of ISIZE trailers across BGZF blocks (exact uncompressed size).
// Returns -1 when the stream is not pure BGZF framing (caller falls back).
int64_t vctpu_bgzf_uncompressed_size(const uint8_t* src, int64_t n) {
    int64_t off = 0, total = 0;
    while (off < n) {
        int64_t bsize = bgzf_block_size(src, n, off);
        if (bsize < 0 || bsize < 28 || off + bsize > n) return -1;
        uint32_t isize;
        std::memcpy(&isize, src + off + bsize - 4, 4);
        total += isize;
        off += bsize;
    }
    return off == n ? total : -1;
}

// Inflate a concatenated-gzip-member stream (BGZF is one) into dst.
// Returns bytes written, or -1 on error / capacity overflow.
int64_t vctpu_gzip_inflate(const uint8_t* src, int64_t n, uint8_t* dst, int64_t cap) {
    z_stream zs;
    std::memset(&zs, 0, sizeof zs);
    if (inflateInit2(&zs, 15 + 32) != Z_OK) return -1;  // auto gzip header
    int64_t in_off = 0, out_off = 0;
    int ret = Z_OK;
    uint8_t scratch[64];  // overflow detector for zero-output tail members
    while (in_off < n || ret == Z_OK) {
        uInt in_chunk = (uInt)std::min<int64_t>(n - in_off, 1 << 30);
        uInt out_chunk = (uInt)std::min<int64_t>(cap - out_off, 1 << 30);
        bool use_scratch = out_chunk == 0;
        zs.next_in = const_cast<uint8_t*>(src) + in_off;
        zs.avail_in = in_chunk;
        zs.next_out = use_scratch ? scratch : dst + out_off;
        zs.avail_out = use_scratch ? (uInt)sizeof scratch : out_chunk;
        uInt gave = zs.avail_out;
        ret = inflate(&zs, Z_NO_FLUSH);
        in_off += in_chunk - zs.avail_in;
        int64_t produced = (int64_t)(gave - zs.avail_out);
        if (use_scratch && produced > 0) {
            inflateEnd(&zs);
            return -1;  // capacity exhausted: member produced real output
        }
        if (!use_scratch) out_off += produced;
        if (ret == Z_STREAM_END) {
            if (in_off >= n) break;          // done: all members consumed
            if (inflateReset2(&zs, 15 + 32) != Z_OK) {  // next member
                inflateEnd(&zs);
                return -1;
            }
            ret = Z_OK;
            continue;
        }
        if (ret != Z_OK) {
            inflateEnd(&zs);
            return -1;
        }
        if (zs.avail_in == in_chunk && produced == 0) break;  // no progress
    }
    inflateEnd(&zs);
    return out_off;
}

// Block-parallel BGZF inflate: every member's output offset is known up
// front from the ISIZE prefix sum, so blocks decompress concurrently into
// disjoint ranges of dst (raw deflate payload + CRC verification — the
// same integrity check zlib's gzip mode performs on the serial path).
// Returns bytes written; -1 when the stream is not pure BGZF framing or
// cap is too small (caller falls back to vctpu_gzip_inflate); -2 on
// corrupt payload (bad deflate stream, ISIZE, or CRC mismatch).
int64_t vctpu_bgzf_inflate(const uint8_t* src, int64_t n, uint8_t* dst, int64_t cap) try {
    struct Block { int64_t off, bsize, out_off; uint32_t isize; };
    std::vector<Block> blocks;
    blocks.reserve((size_t)(n / 4096) + 1);
    int64_t off = 0, total = 0;
    while (off < n) {
        int64_t bsize = bgzf_block_size(src, n, off);
        if (bsize < 28 || off + bsize > n) return -1;
        uint32_t isize;
        std::memcpy(&isize, src + off + bsize - 4, 4);
        blocks.push_back({off, bsize, total, isize});
        total += isize;
        off += bsize;
    }
    if (off != n || total > cap) return -1;
    std::atomic<int> failed{0};
    // blocks are heavyweight (~64KB inflate each): shard at fine grain
    vctpu::for_shards((int64_t)blocks.size(), vctpu::nthreads(),
                      [&](int, int64_t lo, int64_t hi) {
        z_stream zs;
        std::memset(&zs, 0, sizeof zs);
        if (inflateInit2(&zs, -15) != Z_OK) {  // raw deflate per member
            failed.store(1);
            return;
        }
        for (int64_t b = lo; b < hi && !failed.load(std::memory_order_relaxed); ++b) {
            const Block& blk = blocks[b];
            uint16_t xlen = (uint16_t)src[blk.off + 10] | ((uint16_t)src[blk.off + 11] << 8);
            int64_t payload = blk.off + 12 + xlen;
            int64_t clen = blk.bsize - 12 - xlen - 8;
            if (clen < 0) { failed.store(1); break; }
            zs.next_in = const_cast<uint8_t*>(src) + payload;
            zs.avail_in = (uInt)clen;
            zs.next_out = dst + blk.out_off;
            zs.avail_out = blk.isize;
            int ret = inflate(&zs, Z_FINISH);
            if (ret != Z_STREAM_END || zs.avail_out != 0) { failed.store(1); break; }
            uint32_t crc_want;
            std::memcpy(&crc_want, src + blk.off + blk.bsize - 8, 4);
            if ((uint32_t)crc32(0L, dst + blk.out_off, blk.isize) != crc_want) {
                failed.store(1);
                break;
            }
            if (inflateReset2(&zs, -15) != Z_OK) { failed.store(1); break; }
        }
        inflateEnd(&zs);
    }, 16);
    return failed.load() ? -2 : total;
} catch (...) {
    return -1;  // bad_alloc / thread-spawn failure must not cross the C ABI
}

// Deflate src into independent BGZF blocks (<=65280B payload each) with the
// BC extra field + canonical EOF sentinel. Chunks are independent, so they
// compress in parallel into fixed-size scratch slots and compact serially —
// output bytes are identical to the serial path. Returns bytes written or -1.
int64_t vctpu_bgzf_compress(const uint8_t* src, int64_t n, uint8_t* dst, int64_t cap, int level) try {
    static const uint8_t EOF_BLOCK[28] = {0x1f, 0x8b, 0x08, 0x04, 0, 0, 0, 0, 0, 0xff, 0x06, 0x00,
                                          0x42, 0x43, 0x02, 0x00, 0x1b, 0x00, 0x03, 0x00, 0, 0, 0,
                                          0, 0, 0, 0, 0};
    const int64_t CHUNK = 65280;
    const int64_t SLOT = 66560;  // header + compressBound(65280) + trailer, padded
    const int64_t n_chunks = n > 0 ? (n + CHUNK - 1) / CHUNK : 0;
    // uninitialized scratch: every kept byte is written by deflate below,
    // and a value-initializing vector would memset ~1.02x the input first
    std::unique_ptr<uint8_t[]> scratch(new (std::nothrow) uint8_t[(size_t)(n_chunks * SLOT)]);
    if (n_chunks > 0 && !scratch) return -1;  // caller falls back to Python
    std::vector<int64_t> sizes((size_t)n_chunks, -1);
    vctpu::for_shards(n_chunks, vctpu::nthreads(), [&](int, int64_t lo, int64_t hi) {
        for (int64_t c = lo; c < hi; ++c) {
            const int64_t in_off = c * CHUNK;
            const int64_t len = std::min(CHUNK, n - in_off);
            z_stream zs;
            std::memset(&zs, 0, sizeof zs);
            if (deflateInit2(&zs, level, Z_DEFLATED, -15, 8, Z_DEFAULT_STRATEGY) != Z_OK) return;
            uint8_t* h = scratch.get() + c * SLOT;
            zs.next_in = const_cast<uint8_t*>(src) + in_off;
            zs.avail_in = (uInt)len;
            zs.next_out = h + 18;
            zs.avail_out = (uInt)(SLOT - 26);
            int ret = deflate(&zs, Z_FINISH);
            int64_t deflated = (int64_t)(SLOT - 26) - zs.avail_out;
            deflateEnd(&zs);
            if (ret != Z_STREAM_END) return;  // sizes[c] stays -1 -> error
            int64_t bsize = deflated + 26;    // header(18) + crc/isize(8)
            if (bsize > 0xFFFF + 1) return;
            const uint8_t head[12] = {0x1f, 0x8b, 0x08, 0x04, 0, 0, 0, 0, 0, 0xff, 0x06, 0x00};
            std::memcpy(h, head, 12);
            h[12] = 'B';
            h[13] = 'C';
            h[14] = 2;
            h[15] = 0;
            uint16_t bs16 = (uint16_t)(bsize - 1);
            std::memcpy(h + 16, &bs16, 2);
            uint32_t crc = (uint32_t)crc32(0L, src + in_off, (uInt)len);
            uint32_t isize = (uint32_t)len;
            std::memcpy(h + 18 + deflated, &crc, 4);
            std::memcpy(h + 22 + deflated, &isize, 4);
            sizes[c] = bsize;
        }
    }, 16);
    int64_t out_off = 0;
    for (int64_t c = 0; c < n_chunks; ++c) {
        if (sizes[c] < 0) return -1;
        if (out_off + sizes[c] > cap) return -1;
        std::memcpy(dst + out_off, scratch.get() + c * SLOT, sizes[c]);
        out_off += sizes[c];
    }
    if (out_off + 28 > cap) return -1;
    std::memcpy(dst + out_off, EOF_BLOCK, 28);
    return out_off + 28;
} catch (...) {
    return -1;  // bad_alloc / thread-spawn failure must not cross the C ABI
}

// Walk uncompressed BAM alignment records (buf starts at the first record,
// i.e. after the header + reference list) and accumulate per-contig depth
// difference arrays with samtools-depth semantics (-a -J -q -Q -l;
// reference call site coverage_analysis.py:653-683).
//
// diff_flat holds all selected contigs back to back; contig_starts[ref_id]
// is the offset of that contig's (length+1)-long diff region, or -1 to skip.
// Returns records seen, or -1 on malformed input.
int64_t vctpu_bam_depth(const uint8_t* buf, int64_t n, const int64_t* contig_starts,
                        const int64_t* contig_lens, int32_t n_refs, int32_t* diff_flat,
                        int32_t min_bq, int32_t min_mapq, int32_t min_len, int32_t include_del,
                        uint32_t exclude_flags) {
    int64_t off = 0, count = 0;
    while (off + 4 <= n) {
        int32_t bs;
        std::memcpy(&bs, buf + off, 4);
        if (bs < 32 || off + 4 + bs > n) return -1;
        const uint8_t* r = buf + off + 4;
        off += 4 + bs;
        count++;
        int32_t ref_id, pos, l_seq;
        uint32_t lrn, flag_nc;
        std::memcpy(&ref_id, r, 4);
        std::memcpy(&pos, r + 4, 4);
        std::memcpy(&lrn, r + 8, 4);
        std::memcpy(&flag_nc, r + 12, 4);
        std::memcpy(&l_seq, r + 16, 4);
        uint32_t l_read_name = lrn & 0xff;
        int32_t mapq = (int32_t)((lrn >> 8) & 0xff);
        uint32_t n_cigar = flag_nc & 0xffff;
        uint32_t flag = flag_nc >> 16;
        if ((flag & exclude_flags) || ref_id < 0 || ref_id >= n_refs || pos < 0) continue;
        if (mapq < min_mapq || l_seq < min_len) continue;
        int64_t base = contig_starts[ref_id];
        if (base < 0) continue;
        int64_t clen = contig_lens[ref_id];
        const uint8_t* cig = r + 32 + l_read_name;
        const uint8_t* qual = cig + 4 * (int64_t)n_cigar + (l_seq + 1) / 2;
        if (cig + 4 * (int64_t)n_cigar > buf + off || qual + l_seq > buf + off) return -1;
        int64_t ref_pos = pos, read_pos = 0;
        for (uint32_t i = 0; i < n_cigar; i++) {
            uint32_t c;
            std::memcpy(&c, cig + 4 * (int64_t)i, 4);
            uint32_t op = c & 0xf;
            int64_t len = c >> 4;
            bool match_like = (op == 0 || op == 7 || op == 8);  // M, =, X
            bool covers = match_like || (include_del && op == 2);
            if (covers && ref_pos < clen) {
                if (!match_like || min_bq <= 0) {
                    int64_t s = ref_pos, e = std::min(ref_pos + len, clen);
                    diff_flat[base + s] += 1;
                    diff_flat[base + e] -= 1;
                } else {
                    // run-length encode (qual >= min_bq) into diff updates;
                    // clamp by l_seq too in case the CIGAR overruns the quals
                    int64_t s = -1;
                    int64_t max_j = std::min({len, clen - ref_pos, (int64_t)l_seq - read_pos});
                    for (int64_t j = 0; j <= max_j; j++) {
                        bool ok = (j < max_j) && ((int32_t)qual[read_pos + j] >= min_bq);
                        if (ok && s < 0) {
                            s = j;
                        } else if (!ok && s >= 0) {
                            diff_flat[base + ref_pos + s] += 1;
                            diff_flat[base + ref_pos + j] -= 1;
                            s = -1;
                        }
                    }
                }
            }
            if (op == 0 || op == 2 || op == 3 || op == 7 || op == 8) ref_pos += len;  // ref-consuming
            if (op == 0 || op == 1 || op == 4 || op == 7 || op == 8) read_pos += len;  // read-consuming
        }
    }
    return count;
}

}  // extern "C"

// ---------------------------------------------------------------------------
// VCF record scanner: one pass over the uncompressed text buffer producing
// columnar arrays. Replaces the per-line Python split on the 5M-variant
// filter hot path (the reference parses per record via pysam/pandas —
// SURVEY.md §3.1); numeric fields, sample-0 FORMAT numerics, hot INFO keys
// and allele classification all come out as flat arrays ready for device
// transfer, so the Python layer only materializes strings it actually uses.
// Records are independent lines, so the scan shards across threads: byte
// ranges aligned at line starts, per-shard record counts prefix-summed into
// disjoint output ranges, per-shard CHROM dictionaries merged in shard
// order (first-appearance code order is preserved exactly).
// ---------------------------------------------------------------------------

namespace {

inline int base_code(uint8_t c) {
    switch (c) {
        case 'A': case 'a': return 0;
        case 'C': case 'c': return 1;
        case 'G': case 'g': return 2;
        case 'T': case 't': return 3;
        default: return 4;
    }
}

double parse_double_slow(const uint8_t* s, int64_t len) {
    char tmp[64];
    int64_t m = len < 63 ? len : 63;
    std::memcpy(tmp, s, m);
    tmp[m] = 0;
    char* end = nullptr;
    double v = strtod(tmp, &end);
    if (end == tmp) return NAN;
    return v;
}

// Fast decimal parse for the overwhelmingly common VCF shape
// [+-]digits[.digits] with <=15 significant digits: an exactly-held
// integer mantissa divided by an exact power of ten is correctly rounded,
// so the result is bit-identical to strtod. Everything else (exponents,
// inf/nan, long digit strings) falls back to strtod.
inline double parse_double(const uint8_t* s, int64_t len) {
    if (len <= 0 || (len == 1 && s[0] == '.')) return NAN;
    static const double P10[16] = {1.0, 1e1, 1e2, 1e3, 1e4, 1e5, 1e6, 1e7,
                                   1e8, 1e9, 1e10, 1e11, 1e12, 1e13, 1e14, 1e15};
    int64_t i = 0;
    bool neg = false;
    if (s[0] == '-' || s[0] == '+') {
        neg = s[0] == '-';
        i = 1;
    }
    uint64_t mant = 0;
    int digits = 0, frac = 0;
    bool dot = false;
    for (; i < len; ++i) {
        uint8_t c = s[i];
        if (c >= '0' && c <= '9') {
            if (++digits > 15) return parse_double_slow(s, len);
            mant = mant * 10 + (c - '0');
            frac += dot;
        } else if (c == '.' && !dot) {
            dot = true;
        } else {
            return parse_double_slow(s, len);
        }
    }
    if (digits == 0) return parse_double_slow(s, len);
    double v = (double)mant / P10[frac];
    return neg ? -v : v;
}

inline int64_t parse_i64(const uint8_t* s, int64_t len) {
    int64_t v = 0;
    bool neg = false;
    int64_t i = 0;
    if (len > 0 && (s[0] == '-' || s[0] == '+')) { neg = s[0] == '-'; i = 1; }
    for (; i < len; i++) {
        if (s[i] < '0' || s[i] > '9') return -1;
        v = v * 10 + (s[i] - '0');
    }
    return neg ? -v : v;
}

// All output column pointers of the VCF scan, so the per-shard worker and
// the serial path share one record-parsing core.
struct VcfOut {
    int64_t* line_spans;
    int64_t* id_spans;
    int64_t* ref_spans;
    int64_t* alt_spans;
    int64_t* filter_spans;
    int64_t* info_spans;
    int64_t* tail_spans;
    int64_t* pos;
    double* qual;
    int32_t* chrom_codes;
    int8_t* gt;
    uint8_t* gt_phased;
    float* gq;
    float* dpf;
    float* ad;
    uint8_t* aclass;
    int32_t* indel_length;
    int32_t* indel_nuc;
    int32_t* ref_code;
    int32_t* alt_code;
    int32_t* n_alts;
    int32_t* ref_len_out;
    const uint8_t* keys;
    const int32_t* key_lens;
    int32_t n_keys;
    double* info_vals;
    int32_t n_samples;
};

// Parse record lines in buf[start..limit) writing rows [rec_base,
// rec_base+max_rec) of the output columns; CHROM codes go through the
// given dictionary (chrom_uniq: 64B slots, *n_uniq entries, uniq_cap max).
// Returns records parsed, or -1 on malformed input / dictionary overflow.
int64_t vcf_parse_range(const uint8_t* buf, int64_t start, int64_t limit,
                        int64_t rec_base, int64_t max_rec, const VcfOut& o,
                        uint8_t* chrom_uniq, int32_t uniq_cap, int32_t* n_uniq_io) {
    int32_t n_uniq = *n_uniq_io;
    int64_t off = start, parsed = 0;
    while (off < limit && parsed < max_rec) {
        const uint8_t* nl = (const uint8_t*)std::memchr(buf + off, '\n', limit - off);
        int64_t line_end = nl ? (nl - buf) : limit;
        int64_t end = line_end;
        if (end > off && buf[end - 1] == '\r') end--;  // CRLF
        if (end <= off || buf[off] == '#') {
            off = line_end + 1;
            continue;
        }
        const int64_t rec = rec_base + parsed;
        o.line_spans[rec * 2] = off;
        o.line_spans[rec * 2 + 1] = end;

        // tokenize up to 9 tab-separated spans: CHROM POS ID REF ALT QUAL FILTER INFO [FORMAT samples...]
        int64_t fs[9][2];
        int nf = 0;
        int64_t p = off;
        for (; nf < 8 && p <= end; nf++) {
            const uint8_t* tab = (const uint8_t*)std::memchr(buf + p, '\t', end - p);
            int64_t fe = tab ? (tab - buf) : end;
            fs[nf][0] = p;
            fs[nf][1] = fe;
            p = fe + 1;
            if (!tab) { nf++; break; }
        }
        if (nf < 8) return -1;  // malformed record
        int64_t tail_start = p <= end ? p : end;  // FORMAT column onward ('' if absent)

        // CHROM -> dictionary code (linear probe over uniques; contigs are few)
        {
            int64_t cl = fs[0][1] - fs[0][0];
            if (cl > 63) cl = 63;
            int32_t code = -1;
            for (int32_t u = 0; u < n_uniq; u++) {
                const uint8_t* name = chrom_uniq + (int64_t)u * 64;
                if (name[cl] == 0 && std::memcmp(name, buf + fs[0][0], cl) == 0) { code = u; break; }
            }
            if (code < 0) {
                if (n_uniq >= uniq_cap) return -1;
                uint8_t* name = chrom_uniq + (int64_t)n_uniq * 64;
                std::memset(name, 0, 64);
                std::memcpy(name, buf + fs[0][0], cl);
                code = n_uniq++;
            }
            o.chrom_codes[rec] = code;
        }
        o.pos[rec] = parse_i64(buf + fs[1][0], fs[1][1] - fs[1][0]);
        o.qual[rec] = parse_double(buf + fs[5][0], fs[5][1] - fs[5][0]);
        o.id_spans[rec * 2] = fs[2][0];     o.id_spans[rec * 2 + 1] = fs[2][1];
        o.ref_spans[rec * 2] = fs[3][0];    o.ref_spans[rec * 2 + 1] = fs[3][1];
        o.alt_spans[rec * 2] = fs[4][0];    o.alt_spans[rec * 2 + 1] = fs[4][1];
        o.filter_spans[rec * 2] = fs[6][0]; o.filter_spans[rec * 2 + 1] = fs[6][1];
        o.info_spans[rec * 2] = fs[7][0];   o.info_spans[rec * 2 + 1] = fs[7][1];
        o.tail_spans[rec * 2] = tail_start; o.tail_spans[rec * 2 + 1] = end;

        // ---- allele classification (parity: featurize.classify_alleles) ----
        {
            const uint8_t* ref = buf + fs[3][0];
            int64_t rl = fs[3][1] - fs[3][0];
            const uint8_t* alt = buf + fs[4][0];
            int64_t al_full = fs[4][1] - fs[4][0];
            o.ref_len_out[rec] = (int32_t)rl;
            uint8_t cls = 0;
            int32_t ilen = 0, inuc = 4, rc = 4, ac = 4, na = 0;
            if (!(al_full == 0 || (al_full == 1 && alt[0] == '.'))) {
                na = 1;
                for (int64_t i = 0; i < al_full; i++)
                    if (alt[i] == ',') na++;
                const uint8_t* comma = (const uint8_t*)std::memchr(alt, ',', al_full);
                int64_t al = comma ? (comma - alt) : al_full;
                if (al > 0 && alt[0] != '<') {
                    if (rl == 1 && al == 1) {
                        cls |= 1;  // snp
                        rc = base_code(ref[0]);
                        ac = base_code(alt[0]);
                    } else if (rl != al) {
                        cls |= 2;  // indel
                        const uint8_t* diff;
                        int64_t dlen;
                        if (al > rl) {
                            cls |= 4;  // ins
                            bool pref = (al >= rl) && std::memcmp(alt, ref, rl) == 0;
                            if (pref) cls |= 8;
                            diff = pref ? alt + rl : alt + 1;
                            dlen = pref ? al - rl : al - 1;
                        } else {
                            bool pref = (rl >= al) && std::memcmp(ref, alt, al) == 0;
                            if (pref) cls |= 8;
                            diff = pref ? ref + al : ref + 1;
                            dlen = pref ? rl - al : rl - 1;
                        }
                        ilen = (int32_t)(al > rl ? al - rl : rl - al);
                        int u = -2;  // unset
                        for (int64_t i = 0; i < dlen; i++) {
                            int c = base_code(diff[i] >= 'a' ? diff[i] - 32 : diff[i]);
                            if (u == -2) u = c;
                            else if (u != c) { u = -1; break; }
                        }
                        inuc = (u >= 0) ? u : 4;
                    }
                }
            }
            o.aclass[rec] = cls;
            o.indel_length[rec] = ilen;
            o.indel_nuc[rec] = inuc;
            o.ref_code[rec] = rc;
            o.alt_code[rec] = ac;
            o.n_alts[rec] = na;
        }

        // ---- INFO numeric keys ----
        if (o.n_keys > 0) {
            for (int32_t k = 0; k < o.n_keys; k++) o.info_vals[rec * o.n_keys + k] = NAN;
            int64_t ip = fs[7][0], ie = fs[7][1];
            if (!(ie - ip == 1 && buf[ip] == '.')) {
                while (ip < ie) {
                    const uint8_t* semi = (const uint8_t*)std::memchr(buf + ip, ';', ie - ip);
                    int64_t ee = semi ? (semi - buf) : ie;
                    const uint8_t* eq = (const uint8_t*)std::memchr(buf + ip, '=', ee - ip);
                    int64_t klen = eq ? (eq - buf - ip) : (ee - ip);
                    int64_t koff = 0;
                    for (int32_t k = 0; k < o.n_keys; k++) {
                        int32_t kl = o.key_lens[k];
                        if (kl == klen && std::memcmp(o.keys + koff, buf + ip, klen) == 0) {
                            if (!eq) {
                                o.info_vals[rec * o.n_keys + k] = 1.0;  // Flag
                            } else {
                                int64_t vs = ip + klen + 1;
                                const uint8_t* comma = (const uint8_t*)std::memchr(buf + vs, ',', ee - vs);
                                int64_t ve = comma ? (comma - buf) : ee;
                                o.info_vals[rec * o.n_keys + k] = parse_double(buf + vs, ve - vs);
                            }
                            break;
                        }
                        koff += kl;
                    }
                    ip = ee + 1;
                }
            }
        }

        // ---- FORMAT sample-0 numerics (GT / GQ / DP / AD) ----
        o.gt[rec * 2] = -1; o.gt[rec * 2 + 1] = -1; o.gt_phased[rec] = 0;
        o.gq[rec] = NAN; o.dpf[rec] = NAN;
        o.ad[rec * 3] = NAN; o.ad[rec * 3 + 1] = NAN; o.ad[rec * 3 + 2] = NAN;
        if (o.n_samples > 0 && tail_start < end) {
            // FORMAT keys
            const uint8_t* ftab = (const uint8_t*)std::memchr(buf + tail_start, '\t', end - tail_start);
            int64_t fend = ftab ? (ftab - buf) : end;
            int gt_i = -1, gq_i = -1, dp_i = -1, ad_i = -1;
            {
                int idx = 0;
                int64_t kp = tail_start;
                while (kp < fend) {
                    const uint8_t* colon = (const uint8_t*)std::memchr(buf + kp, ':', fend - kp);
                    int64_t ke = colon ? (colon - buf) : fend;
                    int64_t kl = ke - kp;
                    if (kl == 2) {
                        if (buf[kp] == 'G' && buf[kp + 1] == 'T') gt_i = idx;
                        else if (buf[kp] == 'G' && buf[kp + 1] == 'Q') gq_i = idx;
                        else if (buf[kp] == 'D' && buf[kp + 1] == 'P') dp_i = idx;
                        else if (buf[kp] == 'A' && buf[kp + 1] == 'D') ad_i = idx;
                    }
                    idx++;
                    kp = ke + 1;
                }
            }
            if (ftab) {
                int64_t sp = fend + 1;
                const uint8_t* stab = (const uint8_t*)std::memchr(buf + sp, '\t', end - sp);
                int64_t send = stab ? (stab - buf) : end;
                int idx = 0;
                int64_t vp = sp;
                while (vp <= send) {
                    const uint8_t* colon = (const uint8_t*)std::memchr(buf + vp, ':', send - vp);
                    int64_t ve = colon ? (colon - buf) : send;
                    if (idx == gt_i && ve > vp) {
                        // a[/|]b (or haploid a)
                        const uint8_t* s = buf + vp;
                        int64_t l = ve - vp;
                        int64_t sep = -1;
                        for (int64_t i = 0; i < l; i++)
                            if (s[i] == '/' || s[i] == '|') { sep = i; break; }
                        int64_t a_len = sep >= 0 ? sep : l;
                        if (!(a_len == 1 && s[0] == '.')) {
                            int64_t v = parse_i64(s, a_len);
                            if (v >= -128 && v <= 127) o.gt[rec * 2] = (int8_t)v;
                        }
                        if (sep >= 0) {
                            o.gt_phased[rec] = s[sep] == '|';
                            int64_t b_len = l - sep - 1;
                            // second diploid slot only (extra ploidy ignored)
                            const uint8_t* b = s + sep + 1;
                            int64_t b2 = b_len;
                            for (int64_t i = 0; i < b_len; i++)
                                if (b[i] == '/' || b[i] == '|') { b2 = i; break; }
                            if (!(b2 == 1 && b[0] == '.')) {
                                int64_t v = parse_i64(b, b2);
                                if (v >= -128 && v <= 127) o.gt[rec * 2 + 1] = (int8_t)v;
                            }
                        }
                    } else if (idx == gq_i) {
                        o.gq[rec] = (float)parse_double(buf + vp, ve - vp);
                    } else if (idx == dp_i) {
                        o.dpf[rec] = (float)parse_double(buf + vp, ve - vp);
                    } else if (idx == ad_i && ve > vp) {
                        double total = 0;
                        int ai = 0;
                        bool any = false;
                        int64_t ap = vp;
                        while (ap < ve) {
                            const uint8_t* comma = (const uint8_t*)std::memchr(buf + ap, ',', ve - ap);
                            int64_t ae = comma ? (comma - buf) : ve;
                            double v = parse_double(buf + ap, ae - ap);
                            if (v == v) {  // not NaN
                                any = true;
                                if (v > 0) total += v;
                                if (ai < 2) o.ad[rec * 3 + ai] = (float)v;
                            }
                            ai++;
                            ap = ae + 1;
                        }
                        if (any) o.ad[rec * 3 + 2] = (float)total;
                    }
                    idx++;
                    if (!colon || ve >= send) break;
                    vp = ve + 1;
                }
            }
        }
        parsed++;
        off = line_end + 1;
    }
    *n_uniq_io = n_uniq;
    return parsed;
}

// Count record lines (non-empty, not '#') in buf[start..limit).
int64_t count_records_range(const uint8_t* buf, int64_t start, int64_t limit) {
    int64_t off = start, count = 0;
    while (off < limit) {
        const uint8_t* nl = (const uint8_t*)std::memchr(buf + off, '\n', limit - off);
        int64_t end = nl ? (nl - buf) : limit;
        if (end > off && buf[off] != '#') count++;
        off = end + 1;
    }
    return count;
}

}  // namespace

extern "C" {

// Number of record lines (not starting with '#') and offset of the first one.
int64_t vctpu_vcf_count(const uint8_t* buf, int64_t n, int64_t* first_rec_off) {
    int64_t off = 0, count = 0;
    *first_rec_off = n;
    while (off < n) {
        const uint8_t* nl = (const uint8_t*)std::memchr(buf + off, '\n', n - off);
        int64_t end = nl ? (nl - buf) : n;
        if (end > off && buf[off] != '#') {
            if (count == 0) *first_rec_off = off;
            count++;
        }
        off = end + 1;
    }
    return count;
}

// One-pass columnar parse, sharded across threads. All output arrays are
// caller-allocated for n_rec records (from vctpu_vcf_count); each span
// array is an independent contiguous (n_rec, 2) int64 buffer of [start,
// end) byte offsets. Returns records parsed or -1.
//
// aclass bitmask: 1=snp 2=indel 4=ins 8=first-alt-prefixed-by-ref
// gt/gq/dp/ad are sample-0 FORMAT numerics (NaN/-1 when missing);
// ad = (ref_count, alt1_count, total). info_vals = (n_rec, n_keys) doubles
// for the requested INFO keys (first element of comma lists; Flag -> 1).
int64_t vctpu_vcf_parse(
    const uint8_t* buf, int64_t n, int64_t start_off, int64_t n_rec, int32_t n_samples,
    int64_t* line_spans, int64_t* id_spans, int64_t* ref_spans, int64_t* alt_spans,
    int64_t* filter_spans, int64_t* info_spans, int64_t* tail_spans,
    int64_t* pos, double* qual,
    int32_t* chrom_codes, uint8_t* chrom_uniq, int32_t* uniq_inout,
    int8_t* gt, uint8_t* gt_phased, float* gq, float* dpf, float* ad,
    uint8_t* aclass, int32_t* indel_length, int32_t* indel_nuc,
    int32_t* ref_code, int32_t* alt_code, int32_t* n_alts, int32_t* ref_len_out,
    const uint8_t* keys, const int32_t* key_lens, int32_t n_keys, double* info_vals) try {
    const int32_t uniq_cap = *uniq_inout;
    VcfOut o = {line_spans, id_spans, ref_spans, alt_spans, filter_spans, info_spans,
                tail_spans, pos, qual, chrom_codes, gt, gt_phased, gq, dpf, ad,
                aclass, indel_length, indel_nuc, ref_code, alt_code, n_alts,
                ref_len_out, keys, key_lens, n_keys, info_vals, n_samples};

    int t_count = vctpu::nthreads();
    if (t_count > 1 && n_rec >= (int64_t)t_count * 4096) {
        // byte-shard [start_off, n) at line boundaries
        std::vector<int64_t> bounds;
        bounds.push_back(start_off);
        const int64_t span = n - start_off;
        for (int t = 1; t < t_count; ++t) {
            int64_t b = start_off + span * t / t_count;
            if (b < bounds.back()) b = bounds.back();
            const uint8_t* nl = (const uint8_t*)std::memchr(buf + b, '\n', n - b);
            b = nl ? (nl - buf) + 1 : n;
            if (b > bounds.back()) bounds.push_back(b);
        }
        bounds.push_back(n);
        const int shards = (int)bounds.size() - 1;
        std::vector<int64_t> counts(shards), bases(shards + 1, 0);
        vctpu::for_shards((int64_t)shards, shards, [&](int, int64_t lo, int64_t hi) {
            for (int64_t s = lo; s < hi; ++s)
                counts[s] = count_records_range(buf, bounds[s], bounds[s + 1]);
        });
        for (int s = 0; s < shards; ++s) bases[s + 1] = bases[s] + counts[s];
        if (bases[shards] != n_rec) return -1;

        std::vector<std::vector<uint8_t>> uniq(shards);
        std::vector<int32_t> uniq_n(shards, 0);
        std::vector<int64_t> parsed(shards, -1);
        vctpu::for_shards((int64_t)shards, shards, [&](int, int64_t lo, int64_t hi) {
            for (int64_t s = lo; s < hi; ++s) {
                uniq[s].assign((size_t)uniq_cap * 64, 0);
                parsed[s] = vcf_parse_range(buf, bounds[s], bounds[s + 1], bases[s],
                                            counts[s], o, uniq[s].data(), uniq_cap,
                                            &uniq_n[s]);
            }
        });
        // merge per-shard CHROM dictionaries in shard order (preserves
        // global first-appearance code order), then remap shard codes
        int32_t n_uniq = 0;
        std::vector<std::vector<int32_t>> remap(shards);
        for (int s = 0; s < shards; ++s) {
            if (parsed[s] != counts[s]) return -1;
            remap[s].resize(uniq_n[s]);
            for (int32_t u = 0; u < uniq_n[s]; ++u) {
                const uint8_t* name = uniq[s].data() + (int64_t)u * 64;
                int32_t code = -1;
                for (int32_t g = 0; g < n_uniq; ++g) {
                    if (std::memcmp(chrom_uniq + (int64_t)g * 64, name, 64) == 0) {
                        code = g;
                        break;
                    }
                }
                if (code < 0) {
                    if (n_uniq >= uniq_cap) return -1;
                    std::memcpy(chrom_uniq + (int64_t)n_uniq * 64, name, 64);
                    code = n_uniq++;
                }
                remap[s][u] = code;
            }
        }
        vctpu::for_shards((int64_t)shards, shards, [&](int, int64_t lo, int64_t hi) {
            for (int64_t s = lo; s < hi; ++s) {
                bool identity = true;
                for (int32_t u = 0; u < uniq_n[s]; ++u) identity &= remap[s][u] == u;
                if (identity) continue;
                for (int64_t r = bases[s]; r < bases[s + 1]; ++r)
                    chrom_codes[r] = remap[s][chrom_codes[r]];
            }
        });
        *uniq_inout = n_uniq;
        return n_rec;
    }

    int32_t n_uniq = 0;
    int64_t rc = vcf_parse_range(buf, start_off, n, 0, n_rec, o, chrom_uniq, uniq_cap, &n_uniq);
    if (rc < 0) return -1;
    *uniq_inout = n_uniq;
    return rc;
} catch (...) {
    return -1;  // bad_alloc / thread-spawn failure must not cross the C ABI
}

}  // extern "C"

extern "C" {

// Membership of each position in a set of sorted, non-overlapping,
// half-open [start, end) intervals. out[i] = 1 if covered.
void vctpu_interval_membership(const int64_t* starts, const int64_t* ends, int64_t n_iv,
                               const int64_t* pos, int64_t n_pos, uint8_t* out) {
    for (int64_t i = 0; i < n_pos; i++) {
        int64_t p = pos[i];
        // rightmost interval with start <= p
        int64_t lo = 0, hi = n_iv;
        while (lo < hi) {
            int64_t mid = (lo + hi) / 2;
            if (starts[mid] <= p)
                lo = mid + 1;
            else
                hi = mid;
        }
        out[i] = (lo > 0 && p < ends[lo - 1]) ? 1 : 0;
    }
}

}  // extern "C"

namespace {

// Bytes one assembled record will occupy (mirrors assemble_range exactly).
inline int64_t assemble_need(const uint8_t* buf, int64_t i,
                             const int64_t* line_spans, const int64_t* filter_spans,
                             const int64_t* info_spans, const int64_t* tail_spans,
                             const int64_t* filt_offs, const int64_t* sfx_offs) {
    int64_t head = filter_spans[i * 2] - line_spans[i * 2];
    int64_t info_s = info_spans[i * 2], info_e = info_spans[i * 2 + 1];
    int64_t tail = tail_spans[i * 2 + 1] - tail_spans[i * 2];
    int64_t flt = filt_offs[i + 1] - filt_offs[i];
    int64_t sfx = sfx_offs[i + 1] - sfx_offs[i];
    bool info_missing = (info_e - info_s == 1 && buf[info_s] == '.');
    int64_t body = info_missing && sfx > 0 ? sfx - 1 : (info_e - info_s) + sfx;
    return head + flt + 1 + body + (tail > 0 ? 1 + tail : 0) + 1;
}

void assemble_range(const uint8_t* buf, int64_t lo, int64_t hi, int64_t w,
                    const int64_t* line_spans, const int64_t* filter_spans,
                    const int64_t* info_spans, const int64_t* tail_spans,
                    const uint8_t* filt_blob, const int64_t* filt_offs,
                    const uint8_t* sfx_blob, const int64_t* sfx_offs, uint8_t* out) {
    for (int64_t i = lo; i < hi; i++) {
        int64_t head_s = line_spans[i * 2], head_e = filter_spans[i * 2];
        int64_t info_s = info_spans[i * 2], info_e = info_spans[i * 2 + 1];
        int64_t tail_s = tail_spans[i * 2], tail_e = tail_spans[i * 2 + 1];
        int64_t flt_s = filt_offs[i], flt_e = filt_offs[i + 1];
        int64_t sfx_s = sfx_offs[i], sfx_e = sfx_offs[i + 1];
        bool info_missing = (info_e - info_s == 1 && buf[info_s] == '.');
        memcpy(out + w, buf + head_s, head_e - head_s);  // "...QUAL\t"
        w += head_e - head_s;
        memcpy(out + w, filt_blob + flt_s, flt_e - flt_s);
        w += flt_e - flt_s;
        out[w++] = '\t';
        if (info_missing && sfx_e > sfx_s) {
            // "." + ";K=V" -> "K=V" (drop the missing marker and the ';')
            memcpy(out + w, sfx_blob + sfx_s + 1, sfx_e - sfx_s - 1);
            w += sfx_e - sfx_s - 1;
        } else {
            memcpy(out + w, buf + info_s, info_e - info_s);
            w += info_e - info_s;
            memcpy(out + w, sfx_blob + sfx_s, sfx_e - sfx_s);
            w += sfx_e - sfx_s;
        }
        if (tail_e > tail_s) {
            out[w++] = '\t';
            memcpy(out + w, buf + tail_s, tail_e - tail_s);
            w += tail_e - tail_s;
        }
        out[w++] = '\n';
    }
}

}  // namespace

extern "C" {

// Assemble VCF record lines for writeback: the CHROM..QUAL head and the
// FORMAT/sample tail are copied verbatim from the original parse buffer
// (spans from vctpu_vcf_parse); the FILTER column is replaced and an INFO
// suffix spliced in (";K=V" blob per record; replaces a missing "." INFO).
// Two passes, both sharded: exact per-record sizes (prefix-summed into
// shard output offsets), then parallel copies into disjoint ranges.
// Returns bytes written, -1 when out_cap is too small, -2 on bad spans.
int64_t vctpu_vcf_assemble(
    const uint8_t* buf, int64_t buf_len, int64_t n,
    const int64_t* line_spans,    // (n,2) full record line [start,end)
    const int64_t* filter_spans,  // (n,2) original FILTER field
    const int64_t* info_spans,    // (n,2) original INFO field
    const int64_t* tail_spans,    // (n,2) FORMAT..line-end ([s==e] if none)
    const uint8_t* filt_blob, const int64_t* filt_offs,  // n+1 offsets
    const uint8_t* sfx_blob, const int64_t* sfx_offs,    // n+1 offsets
    uint8_t* out, int64_t out_cap) try {
    const int t_count = vctpu::nthreads();
    std::atomic<int> bad{0};
    const int max_shards = (t_count > 1 && n >= 65536) ? t_count : 1;
    std::vector<int64_t> sizes(max_shards, 0);
    int used = vctpu::for_shards(n, max_shards, [&](int t, int64_t lo, int64_t hi) {
        int64_t total = 0;
        for (int64_t i = lo; i < hi; i++) {
            int64_t head_s = line_spans[i * 2], head_e = filter_spans[i * 2];
            if (head_s < 0 || head_e > buf_len || head_e < head_s) {
                bad.store(1, std::memory_order_relaxed);
                return;
            }
            total += assemble_need(buf, i, line_spans, filter_spans, info_spans,
                                   tail_spans, filt_offs, sfx_offs);
        }
        sizes[t] = total;
    });
    if (bad.load()) return -2;
    std::vector<int64_t> w_base(used + 1, 0);
    for (int t = 0; t < used; ++t) w_base[t + 1] = w_base[t] + sizes[t];
    if (w_base[used] > out_cap) return -1;
    vctpu::for_shards(n, max_shards, [&](int t, int64_t lo, int64_t hi) {
        assemble_range(buf, lo, hi, w_base[t], line_spans, filter_spans, info_spans,
                       tail_spans, filt_blob, filt_offs, sfx_blob, sfx_offs, out);
    });
    return w_base[used];
} catch (...) {
    return -1;  // bad_alloc / thread-spawn failure must not cross the C ABI
}

}  // extern "C"

namespace {

struct BaseTable {
    uint8_t t[256];
    BaseTable() {
        for (int i = 0; i < 256; ++i) t[i] = 4;
        t[(int)'A'] = t[(int)'a'] = 0;
        t[(int)'C'] = t[(int)'c'] = 1;
        t[(int)'G'] = t[(int)'g'] = 2;
        t[(int)'T'] = t[(int)'t'] = 3;
    }
};
const BaseTable kBase;

}  // namespace

extern "C" {

// FASTA body 2-bit-class encode: strip the newline framing and map
// ACGTacgt -> 0..3 (anything else 4). ``buf`` points at the first sequence
// byte of one contig (the .fai offset is applied by the caller); the body
// is line_bases content bytes per line_width-byte stride, last line may be
// short. Sharded over OUTPUT positions (pure map, disjoint writes), so the
// result is byte-identical to the serial walk at any thread count.
// Returns 0, or -1 when the framing doesn't cover ``length`` bases.
int64_t vctpu_fasta_encode(const uint8_t* buf, int64_t buf_len,
                           int64_t line_bases, int64_t line_width,
                           int64_t length, uint8_t* out) try {
    if (length <= 0) return length == 0 ? 0 : -1;
    if (line_bases <= 0 || line_width < line_bases) return -1;
    const int64_t last_line = (length - 1) / line_bases;
    const int64_t need =
        last_line * line_width + ((length - 1) - last_line * line_bases) + 1;
    if (need > buf_len) return -1;
    const int64_t gap = line_width - line_bases;
    vctpu::for_shards(length, vctpu::nthreads(), [&](int, int64_t lo, int64_t hi) {
        int64_t line = lo / line_bases;
        int64_t col = lo - line * line_bases;
        const uint8_t* src = buf + line * line_width + col;
        for (int64_t i = lo; i < hi; ++i) {
            out[i] = kBase.t[*src++];
            if (++col == line_bases) {
                col = 0;
                src += gap;
            }
        }
    }, 1 << 16);
    return 0;
} catch (...) {
    return -1;
}

// Fused coverage reduce: per-window mean + clipped depth histogram in ONE
// pass over the depth vector, sharded at window-aligned boundaries with
// per-shard histograms merged at the end (the ops/coverage.py jitted
// program runs three kernels and a second sweep; at genome scale the
// multi-pass working set falls out of cache — this streams it once in
// cache-sized window tiles). ``from_diffs`` != 0 treats the input as a
// difference array whose running cumsum is the depth — the bam/cram depth
// path can reduce without ever materializing the depth vector (a cheap
// per-shard total pre-pass seeds each shard's running depth).
//
// means_out: ceil(n/window) float32 (tail window averages its remainder —
// binned_mean semantics). While every window SUM stays exactly
// representable in f32 (< 2^24 — always true at WGS depth scales, e.g.
// 60x over 1 kb windows sums to ~6e4) the result is bit-identical to the
// jitted f32-accumulation kernel; beyond that the exact int64 sum with
// ONE final rounding here is more accurate than f32 accumulation, not
// equal to it. hist_out: (max_bin+1) int64, depths clipped into
// [0, max_bin]. Returns 0, -1 on bad args.
int64_t vctpu_coverage_stats(const int32_t* data, int64_t n, int64_t window,
                             int32_t max_bin, int32_t from_diffs,
                             float* means_out, int64_t* hist_out) try {
    if (n < 0 || window <= 0 || max_bin < 0) return -1;
    const int64_t n_win = n ? (n + window - 1) / window : 0;
    const int bins = max_bin + 1;
    for (int b = 0; b < bins; ++b) hist_out[b] = 0;
    if (n == 0) return 0;
    const int t_count = vctpu::nthreads();
    const int max_shards = (t_count > 1 && n_win >= 8) ? t_count : 1;
    std::vector<int64_t> base(max_shards + 1, 0);
    if (from_diffs) {
        // pre-pass: per-shard diff totals -> running-depth offset per shard
        // (shard ranges are identical across both for_shards calls: same
        // n_win / max_shards / min_per_shard)
        std::vector<int64_t> tot(max_shards, 0);
        const int used = vctpu::for_shards(n_win, max_shards,
                                           [&](int t, int64_t wlo, int64_t whi) {
            const int64_t lo = wlo * window, hi = std::min(n, whi * window);
            int64_t s = 0;
            for (int64_t i = lo; i < hi; ++i) s += data[i];
            tot[t] = s;
        }, 1);
        for (int t = 0; t < used; ++t) base[t + 1] = base[t] + tot[t];
    }
    std::vector<std::vector<int64_t>> hists(max_shards);
    vctpu::for_shards(n_win, max_shards, [&](int t, int64_t wlo, int64_t whi) {
        std::vector<int64_t>& h = hists[t];
        h.assign(bins, 0);
        int64_t run = base[t];
        for (int64_t w = wlo; w < whi; ++w) {
            const int64_t lo = w * window, hi = std::min(n, lo + window);
            int64_t sum = 0;
            for (int64_t i = lo; i < hi; ++i) {
                const int64_t d = from_diffs ? (run += data[i]) : data[i];
                sum += d;
                const int64_t b = d < 0 ? 0 : (d > max_bin ? max_bin : d);
                ++h[b];
            }
            // f32/f32 divide: bit-identical to the jitted binned_mean
            // while the exact sum fits f32 (see header comment)
            means_out[w] = (float)sum / (float)(hi - lo);
        }
    }, 1);
    for (auto& h : hists)
        if ((int)h.size() == bins)
            for (int b = 0; b < bins; ++b) hist_out[b] += h[b];
    return 0;
} catch (...) {
    return -1;
}

}  // extern "C"
