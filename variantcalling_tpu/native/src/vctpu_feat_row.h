// Single-row window featurization — shared by vctpu_features.cc (the
// materialized-window and per-contig fused-gather entry points) and
// vctpu_fused.cc (the whole-chunk fused parse->featurize->score entry).
// One definition so the three paths can never diverge on feature
// semantics; every function is inline and the namespace keeps them out
// of the C ABI.
//
// Semantics are an EXACT match of the jitted device kernels in
// ops/features.py (gc_content, hmer_indel_features, motif_codes,
// cycle_skip_status) — see the header comment in vctpu_features.cc for
// the per-feature derivation and the parity tests that lock them.

#ifndef VCTPU_FEAT_ROW_H_
#define VCTPU_FEAT_ROW_H_

#include <cstdint>

namespace vctpu_feat {

constexpr int32_t BASE_N = 4;
constexpr int32_t GC_RADIUS = 10, MOTIF_K = 5, CONTEXT = 4, MAX_RUN = 40;

// flow signature of one haplotype: returns run count, fills cums[] with
// the (strictly increasing) cumulative flow position of each run.
// lookup[base] = flow-cycle position of base in the flow order.
inline int32_t flow_signature(const uint8_t* hap, int32_t len,
                              const int32_t* lookup, int32_t* cums) {
    int32_t eff = len;
    for (int32_t i = 0; i < len; ++i) {
        if (hap[i] == BASE_N) { eff = i; break; }
    }
    int32_t n_runs = 0, cum = 0;
    int32_t prev_pos = -1;
    uint8_t prev_base = 255;
    for (int32_t i = 0; i < eff; ++i) {
        const int32_t pos = lookup[hap[i]];
        if (i == 0 || hap[i] != prev_base) {  // run start
            const int32_t d = (i == 0) ? pos + 1 : ((pos - prev_pos) % 4 + 4) % 4;
            cum += d;
            cums[n_runs++] = cum;
        }
        prev_base = hap[i];
        prev_pos = pos;
    }
    return n_runs;
}

// One row of window featurization; aux columns and outputs indexed by i.
inline void featurize_row(
    const uint8_t* row, int32_t w, int32_t center, int64_t i,
    const uint8_t* is_indel, const int32_t* indel_nuc,
    const int32_t* ref_code, const int32_t* alt_code, const uint8_t* is_snp,
    const int32_t* lookup,
    int32_t* hmer_len, int32_t* hmer_nuc, float* gc, int32_t* cyc,
    int32_t* left_motif, int32_t* right_motif) {
    const int32_t hap_len = 2 * CONTEXT + 1;

    // gc_content over +-GC_RADIUS
    int32_t n_gc = 0, n_base = 0;
    for (int32_t j = center - GC_RADIUS; j <= center + GC_RADIUS; ++j) {
        const uint8_t b = row[j];
        n_gc += (b == 1) | (b == 2);   // C or G
        n_base += b != BASE_N;
    }
    gc[i] = (float)n_gc / (float)(n_base > 1 ? n_base : 1);

    // hmer run at center+1, capped at the window edge like the jitted
    // kernel (span = windows[:, start:start+max_run])
    const int32_t start = center + 1;
    const int32_t span = (w - start) < MAX_RUN ? (w - start) : MAX_RUN;
    const uint8_t base0 = row[start];
    int32_t run = 1;
    while (run < span && row[start + run] == base0) ++run;
    const bool hmer = is_indel[i] && indel_nuc[i] < 4 &&
                      indel_nuc[i] == (int32_t)base0;
    hmer_len[i] = hmer ? run : 0;
    hmer_nuc[i] = hmer ? indel_nuc[i] : BASE_N;

    // base-5 packed motifs adjacent to the anchor
    int32_t lm = 0, rm = 0;
    for (int32_t j = 0; j < MOTIF_K; ++j) {
        lm = lm * 5 + row[center - MOTIF_K + j];
        rm = rm * 5 + row[center + 1 + j];
    }
    left_motif[i] = lm;
    right_motif[i] = rm;

    // cycle-skip status (SNPs only)
    if (!is_snp[i]) {
        cyc[i] = -1;
        return;
    }
    uint8_t ref_hap[2 * CONTEXT + 1], alt_hap[2 * CONTEXT + 1];
    for (int32_t j = 0; j < CONTEXT; ++j) {
        ref_hap[j] = alt_hap[j] = row[center - CONTEXT + j];
        ref_hap[CONTEXT + 1 + j] = alt_hap[CONTEXT + 1 + j] = row[center + 1 + j];
    }
    ref_hap[CONTEXT] = (uint8_t)ref_code[i];
    alt_hap[CONTEXT] = (uint8_t)alt_code[i];
    int32_t ref_cums[2 * CONTEXT + 1], alt_cums[2 * CONTEXT + 1];
    const int32_t nr = flow_signature(ref_hap, hap_len, lookup, ref_cums);
    const int32_t na = flow_signature(alt_hap, hap_len, lookup, alt_cums);
    const int32_t ref_flows = nr ? ref_cums[nr - 1] : 0;
    const int32_t alt_flows = na ? alt_cums[na - 1] : 0;
    if (ref_flows != alt_flows) {
        cyc[i] = 2;
    } else {
        bool diff = nr != na;
        for (int32_t j = 0; !diff && j < nr; ++j)
            diff = ref_cums[j] != alt_cums[j];
        cyc[i] = diff ? 1 : 0;
    }
}

inline bool featurize_geometry_ok(int32_t w, int32_t center) {
    return w > 0 && center >= GC_RADIUS && center + GC_RADIUS < w &&
           center >= MOTIF_K && center + MOTIF_K < w &&
           center >= CONTEXT && center + CONTEXT < w;
}

inline bool flow_lookup_init(const int32_t* flow_order, int32_t* lookup) {
    for (int32_t p = 0; p < 5; ++p) lookup[p] = 0;  // N unused (runs truncate)
    for (int32_t p = 0; p < 4; ++p) {
        if (flow_order[p] < 0 || flow_order[p] > 3) return false;
        lookup[flow_order[p]] = p;
    }
    return true;
}

}  // namespace vctpu_feat

#endif  // VCTPU_FEAT_ROW_H_
