// Histogram gradient-boosted trees — native single-host trainer.
//
// The TPU trainer (models/boosting.py) grows complete depth-D trees
// level-by-level with (node, feature, bin) histogram reductions as one
// jitted program; that formulation rides the MXU / psum on accelerator
// meshes but pays XLA's generic scatter on a plain CPU (~13ns per
// update).  This kernel is the CPU-fallback twin of the same algorithm
// (same quantile-binned inputs, same gain formula, same complete-tree
// output arrays), engineered the way CPU tree trainers are
// (LightGBM/sklearn HistGBT): samples kept PARTITIONED by node so each
// node's rows are contiguous, per-node histograms built only for the
// SMALLER child of each split with the sibling derived by subtraction
// (hist parent - hist child), L1-resident per-node histograms.
//
// Reference behavior target: ugvc trains sklearn / xgboost forests on
// CPU (reference docs/train_models_pipeline.md); this replaces that
// engine in-process.  Outputs are identical in layout to the jitted
// trainer: feats/bins (T, D, 2^D) int32 with -1 = dead node, leaves
// (T, 2^D) float32 — models/boosting._to_flat_forest consumes both.

#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <vector>

#include "vctpu_forest_tile.h"
#include "vctpu_threads.h"

extern "C" {

// Quantile binning: out[i,j] = searchsorted(edges[j], x[i,j], side='left'),
// NaN routed to the last bin (numpy's sort order puts NaN above all
// floats) — must match models/boosting.bin_features and the numpy host
// binning exactly, or trained splits shift by one bin.
int64_t vctpu_bin_features(
    const float* x,        // (n, f) row-major
    int64_t n, int32_t f,
    const float* edges,    // (f, n_edges) row-major, non-decreasing rows
    int32_t n_edges,
    uint8_t* out)          // (n, f)
{
    if (n < 0 || f <= 0 || n_edges <= 0 || n_edges > 255) return -1;
    vctpu::for_shards(n, vctpu::nthreads(), [&](int, int64_t r_lo, int64_t r_hi) {
        for (int64_t i = r_lo; i < r_hi; ++i) {
            const float* row = x + (size_t)i * f;
            uint8_t* orow = out + (size_t)i * f;
            for (int32_t j = 0; j < f; ++j) {
                const float v = row[j];
                const float* e = edges + (size_t)j * n_edges;
                if (std::isnan(v)) {
                    orow[j] = (uint8_t)n_edges;
                    continue;
                }
                // branch-light binary search: first index with e[idx] >= v
                int32_t lo = 0, hi = n_edges;
                while (lo < hi) {
                    const int32_t mid = (lo + hi) >> 1;
                    if (e[mid] < v) lo = mid + 1; else hi = mid;
                }
                orow[j] = (uint8_t)lo;
            }
        }
    });
    return 0;
}

using vctpu_forest::Node;
using vctpu_forest::fill_tile;
using vctpu_forest::forest_walk_tile;
using vctpu_forest::pack_nodes;

// Forest inference, CPU twin of models/forest.predict_score: the exact
// gather-walk semantics (x <= thr goes left; NaN takes default_left when
// provided, else right; walk runs max_depth rounds with leaf self-loop;
// mean or sigmoid(sum + base) aggregation), as a per-sample pointer walk
// over a packed node array — 3-5x XLA:CPU's fused-gather lowering on one
// core. aggregation: 0 = mean (RF proba), 1 = logit_sum (GBT margin).
int64_t vctpu_forest_predict(
    const float* x, int64_t n, int32_t f,
    const int32_t* feat, const float* thr,
    const int32_t* left, const int32_t* right, const float* value,
    const uint8_t* default_left,  // (t, m) or nullptr
    int32_t t, int32_t m, int32_t max_depth,
    int32_t aggregation, float base_score,
    float* out) try
{
    if (n < 0 || f <= 0 || t <= 0 || m <= 0 || max_depth <= 0) return -1;
    if (aggregation < 0 || aggregation > 2) return -1;
    std::vector<Node> nodes;
    pack_nodes(nodes, feat, thr, left, right, value, default_left, (int64_t)t * m);
    const bool has_dl = default_left != nullptr;
    vctpu::for_shards(n, vctpu::nthreads(), [&](int, int64_t r_lo, int64_t r_hi) {
        forest_walk_tile(nodes.data(), x + (size_t)r_lo * f, r_hi - r_lo, f,
                         t, m, max_depth, has_dl, aggregation, base_score, out + r_lo);
    });
    return 0;
} catch (...) {
    return -1;  // bad_alloc / thread-spawn failure must not cross the C ABI
}

// Fused column->matrix->forest: each shard builds an L2-resident row tile
// from the typed column pointers and walks it immediately, so the full
// (n, f) float32 matrix never exists — at 5M x 19 that skips ~760 MB of
// DRAM write+read traffic versus vctpu_build_matrix + vctpu_forest_predict.
// Scores are bit-identical to the two-step path (same fills, same walk).
int64_t vctpu_matrix_forest_predict(
    const void* const* cols, const int32_t* dtypes, int64_t n, int32_t f,
    const int32_t* feat, const float* thr,
    const int32_t* left, const int32_t* right, const float* value,
    const uint8_t* default_left,
    int32_t t, int32_t m, int32_t max_depth,
    int32_t aggregation, float base_score,
    float* out) try
{
    if (n < 0 || f <= 0 || t <= 0 || m <= 0 || max_depth <= 0) return -1;
    if (aggregation < 0 || aggregation > 2) return -1;
    for (int32_t j = 0; j < f; ++j)
        if (dtypes[j] < 0 || dtypes[j] > 4) return -2;
    std::vector<Node> nodes;
    pack_nodes(nodes, feat, thr, left, right, value, default_left, (int64_t)t * m);
    const bool has_dl = default_left != nullptr;
    const int64_t BLOCK = 8192;
    std::atomic<int> failed{0};
    vctpu::for_shards((n + BLOCK - 1) / BLOCK, vctpu::nthreads(),
                      [&](int, int64_t b_lo, int64_t b_hi) {
        std::vector<float> tile;
        try {
            tile.resize((size_t)BLOCK * f);
        } catch (...) {
            failed.store(1);
            return;
        }
        for (int64_t lo = b_lo * BLOCK; lo < b_hi * BLOCK && lo < n; lo += BLOCK) {
            const int64_t hi = lo + BLOCK < n ? lo + BLOCK : n;
            fill_tile(cols, dtypes, f, lo, hi, tile.data());
            forest_walk_tile(nodes.data(), tile.data(), hi - lo, f, t, m, max_depth,
                             has_dl, aggregation, base_score, out + lo);
        }
    }, 2);
    return failed.load() ? -1 : 0;
} catch (...) {
    return -1;  // bad_alloc / thread-spawn failure must not cross the C ABI
}

// Assemble the (n, f) float32 feature matrix from per-column pointers —
// the CPU pipeline's column->matrix step without numpy's per-column
// temporaries. dtypes: 0 = float32, 1 = int32, 2 = float64, 3 = uint8,
// 4 = bool/uint8-as-flag.
int64_t vctpu_build_matrix(
    const void* const* cols, const int32_t* dtypes,
    int64_t n, int32_t f, float* out)
{
    if (n < 0 || f <= 0) return -1;
    for (int32_t j = 0; j < f; ++j)
        if (dtypes[j] < 0 || dtypes[j] > 4) return -2;
    // row-blocked: a full per-column pass would sweep the whole (n, f)
    // matrix f times (≈7 GB of traffic at 5M x 19); per block the output
    // tile stays L2-resident so the matrix is written once. Row shards
    // write disjoint ranges, so blocks also spread across threads. The
    // fill itself is the SAME helper the fused matrix+forest path uses,
    // so the two paths cannot diverge on dtype handling.
    const int64_t BLOCK = 8192;
    vctpu::for_shards((n + BLOCK - 1) / BLOCK, vctpu::nthreads(),
                      [&](int, int64_t b_lo, int64_t b_hi) {
        for (int64_t lo = b_lo * BLOCK; lo < b_hi * BLOCK && lo < n; lo += BLOCK) {
            const int64_t hi = lo + BLOCK < n ? lo + BLOCK : n;
            fill_tile(cols, dtypes, f, lo, hi, out + (size_t)lo * f);
        }
    }, 2);
    return 0;
}

// returns 0 on success, <0 on bad arguments.
int64_t vctpu_gbt_fit(
    const uint8_t* binned,   // (n, f) row-major bin ids in [0, b)
    const float* y,          // (n,) labels in {0, 1}
    const float* w,          // (n,) sample weights, or nullptr for all-1
    int64_t n, int32_t f, int32_t b,
    int32_t n_trees, int32_t depth,
    float learning_rate, float reg_lambda, float min_child_weight,
    float base_score,
    int32_t* out_feats,      // (n_trees, depth, 1<<depth)
    int32_t* out_bins,       // (n_trees, depth, 1<<depth)
    float* out_leaves)       // (n_trees, 1<<depth)
{
    if (n <= 0 || f <= 0 || b <= 1 || n_trees <= 0 || depth <= 0 || depth > 16)
        return -1;
    const int32_t leaves = 1 << depth;
    const int64_t fb = (int64_t)f * b;      // histogram cells per node
    const int64_t hs = 2 * fb;              // floats per node hist (g,h pairs)

    std::vector<float> margin((size_t)n, base_score);
    std::vector<float> g((size_t)n), h((size_t)n);
    std::vector<int64_t> idx((size_t)n), scratch((size_t)n);
    // node sample ranges for the current level: node k holds
    // idx[bounds[k] .. bounds[k+1])
    std::vector<int64_t> bounds, next_bounds;
    // per-level histograms, double-buffered parent/child
    std::vector<float> hist_a((size_t)leaves * hs), hist_b((size_t)leaves * hs);
    std::vector<int32_t> feat_lvl(leaves), bin_lvl(leaves);

    for (int32_t t = 0; t < n_trees; ++t) {
        // gradients/hessians of the logistic loss at the current margin
        for (int64_t i = 0; i < n; ++i) {
            float p = 1.0f / (1.0f + std::exp(-margin[i]));
            float wi = w ? w[i] : 1.0f;
            g[i] = wi * (p - y[i]);
            float hi = wi * p * (1.0f - p);
            h[i] = hi > 1e-12f ? hi : 1e-12f;
        }
        for (int64_t i = 0; i < n; ++i) idx[i] = i;
        bounds.assign({0, n});

        float* prev = hist_a.data();
        float* cur = hist_b.data();
        int32_t* tf = out_feats + (size_t)t * depth * leaves;
        int32_t* tb = out_bins + (size_t)t * depth * leaves;

        for (int32_t level = 0; level < depth; ++level) {
            const int32_t n_nodes = 1 << level;

            // ---- histograms for every node of this level -------------
            if (level == 0) {
                std::memset(cur, 0, (size_t)hs * sizeof(float));
                float* hcur = cur;
                for (int64_t i = 0; i < n; ++i) {
                    const uint8_t* row = binned + (size_t)i * f;
                    const float gi = g[i], hi = h[i];
                    for (int32_t j = 0; j < f; ++j) {
                        float* cell = hcur + 2 * ((int64_t)j * b + row[j]);
                        cell[0] += gi;
                        cell[1] += hi;
                    }
                }
            } else {
                // children of parent k sit at 2k (left) and 2k+1 (right);
                // build the smaller child by iteration, derive the
                // sibling as parent - child
                for (int32_t k = 0; k < n_nodes / 2; ++k) {
                    const int64_t s = bounds[2 * k], m = bounds[2 * k + 1],
                                  e = bounds[2 * k + 2];
                    const bool left_small = (m - s) <= (e - m);
                    const int32_t small_node = 2 * k + (left_small ? 0 : 1);
                    const int64_t ss = left_small ? s : m,
                                  se = left_small ? m : e;
                    float* hsmall = cur + (size_t)small_node * hs;
                    std::memset(hsmall, 0, (size_t)hs * sizeof(float));
                    for (int64_t r = ss; r < se; ++r) {
                        const int64_t i = idx[r];
                        const uint8_t* row = binned + (size_t)i * f;
                        const float gi = g[i], hi = h[i];
                        for (int32_t j = 0; j < f; ++j) {
                            float* cell = hsmall + 2 * ((int64_t)j * b + row[j]);
                            cell[0] += gi;
                            cell[1] += hi;
                        }
                    }
                    const float* hpar = prev + (size_t)k * hs;
                    float* hbig = cur + (size_t)(2 * k + (left_small ? 1 : 0)) * hs;
                    for (int64_t c = 0; c < hs; ++c)
                        hbig[c] = hpar[c] - hsmall[c];
                }
            }

            // ---- split search (same gain formula / tie-break order as
            // the jitted trainer: flat argmax over feature-major bins) --
            for (int32_t k = 0; k < n_nodes; ++k) {
                const float* hist = cur + (size_t)k * hs;
                float best_gain = 0.0f;  // dead unless strictly positive
                int32_t best_f = -1, best_b = 0;
                for (int32_t j = 0; j < f; ++j) {
                    const float* hf = hist + 2 * (int64_t)j * b;
                    float gt = 0.0f, ht = 0.0f;
                    for (int32_t c = 0; c < b; ++c) {
                        gt += hf[2 * c];
                        ht += hf[2 * c + 1];
                    }
                    const float parent = gt * gt / (ht + reg_lambda);
                    float gl = 0.0f, hl = 0.0f;
                    for (int32_t c = 0; c < b - 1; ++c) {  // last bin = no split
                        gl += hf[2 * c];
                        hl += hf[2 * c + 1];
                        const float gr = gt - gl, hr = ht - hl;
                        if (hl < min_child_weight || hr < min_child_weight)
                            continue;
                        const float gain = gl * gl / (hl + reg_lambda) +
                                           gr * gr / (hr + reg_lambda) - parent;
                        if (gain > best_gain) {  // strict: first max wins
                            best_gain = gain;
                            best_f = j;
                            best_b = c;
                        }
                    }
                }
                feat_lvl[k] = best_f;
                bin_lvl[k] = best_f >= 0 ? best_b : 0;
                tf[(size_t)level * leaves + k] = best_f;
                tb[(size_t)level * leaves + k] = bin_lvl[k];
            }
            for (int32_t k = n_nodes; k < leaves; ++k) {  // padding lanes
                tf[(size_t)level * leaves + k] = -1;
                tb[(size_t)level * leaves + k] = 0;
            }

            // ---- stable partition of every node's range --------------
            next_bounds.assign((size_t)(2 * n_nodes + 1), 0);
            for (int32_t k = 0; k < n_nodes; ++k) {
                const int64_t s = bounds[k], e = bounds[k + 1];
                const int32_t jf = feat_lvl[k];
                int64_t nl = 0;
                if (jf < 0) {
                    nl = e - s;  // dead: everything routes left
                } else {
                    const uint8_t cut = (uint8_t)bin_lvl[k];
                    int64_t lpos = s, rpos = 0;
                    for (int64_t r = s; r < e; ++r) {
                        const int64_t i = idx[r];
                        if (binned[(size_t)i * f + jf] > cut)
                            scratch[rpos++] = i;
                        else
                            idx[lpos++] = i;
                    }
                    std::memcpy(&idx[lpos], scratch.data(),
                                (size_t)rpos * sizeof(int64_t));
                    nl = lpos - s;
                }
                next_bounds[2 * k + 1] = s + nl;
                next_bounds[2 * k + 2] = e;
            }
            next_bounds[0] = 0;
            bounds.swap(next_bounds);
            std::swap(prev, cur);
        }

        // ---- leaf values + margin update -----------------------------
        float* tl = out_leaves + (size_t)t * leaves;
        for (int32_t k = 0; k < leaves; ++k) {
            const int64_t s = bounds[k], e = bounds[k + 1];
            float lg = 0.0f, lh = 0.0f;
            for (int64_t r = s; r < e; ++r) {
                lg += g[idx[r]];
                lh += h[idx[r]];
            }
            const float leaf = -learning_rate * lg / (lh + reg_lambda);
            tl[k] = leaf;
            for (int64_t r = s; r < e; ++r) margin[idx[r]] += leaf;
        }
    }
    return 0;
}

}  // extern "C"
