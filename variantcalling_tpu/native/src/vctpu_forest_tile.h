// L2-resident forest inference tiles — shared by vctpu_gbt.cc (the
// matrix / column->matrix entry points) and vctpu_fused.cc (the
// whole-chunk fused parse->featurize->score entry). One definition of
// the node walk and the typed column fill so the engines cannot diverge
// on split semantics or dtype casts; inline + namespaced, out of the
// C ABI.

#ifndef VCTPU_FOREST_TILE_H_
#define VCTPU_FOREST_TILE_H_

#include <cmath>
#include <cstdint>
#include <vector>

namespace vctpu_forest {

struct Node {
    float thr;
    float value;
    int32_t feat;
    int32_t left;
    int32_t right;
    int32_t dl;
};

// pack the five SoA arrays into one cache-friendly node table
inline void pack_nodes(std::vector<Node>& nodes, const int32_t* feat, const float* thr,
                       const int32_t* left, const int32_t* right, const float* value,
                       const uint8_t* default_left, int64_t count) {
    nodes.resize((size_t)count);
    for (int64_t k = 0; k < count; ++k) {
        nodes[k] = {thr[k], value[k], feat[k], left[k], right[k],
                    default_left ? (int32_t)default_left[k] : -1};
    }
}

// walk rows [0, count) of a row-major tile; out is per-row. Walks two
// trees concurrently per row: the per-tree pointer chase is a serial
// dependency chain, so interleaving two independent chains hides
// node-load latency (~20% on one core). Accumulation order is the exact
// sequential tree order (t=0,1,...,T-1) — the CANONICAL order the jit
// engine's fori_loop accumulation also uses, so sums are bit-identical
// across engines (the engine contract, docs/robustness.md).
// aggregation: 0 = mean (sum / t; division is IEEE-correctly-rounded so
// both engines agree bit-for-bit), 1 = logit_sum (sigmoid(sum + base);
// exp is implementation-defined — engine-parity callers use mode 2 and
// finalize on the host instead), 2 = raw sum (no finalization).
inline void forest_walk_tile(const Node* nodes, const float* x, int64_t count, int32_t f,
                             int32_t t, int32_t m, int32_t max_depth, bool has_dl,
                             int32_t aggregation, float base_score, float* out) {
    for (int64_t i = 0; i < count; ++i) {
        const float* row = x + (size_t)i * f;
        float acc = 0.0f;
        int32_t ti = 0;
        for (; ti + 1 < t; ti += 2) {
            const Node* ta = nodes + (size_t)ti * m;
            const Node* tb = ta + m;
            int32_t ia = 0, ib = 0;
            for (int32_t d = 0; d < max_depth; ++d) {
                const Node& na = ta[ia];
                const Node& nb = tb[ib];
                if (na.feat >= 0) {
                    const float xv = row[na.feat];
                    bool gl = xv <= na.thr;  // NaN -> false (right)
                    if (has_dl && std::isnan(xv) && na.dl >= 0) gl = na.dl != 0;
                    ia = gl ? na.left : na.right;
                }
                if (nb.feat >= 0) {
                    const float xv = row[nb.feat];
                    bool gl = xv <= nb.thr;
                    if (has_dl && std::isnan(xv) && nb.dl >= 0) gl = nb.dl != 0;
                    ib = gl ? nb.left : nb.right;
                }
            }
            acc += ta[ia].value;
            acc += tb[ib].value;
        }
        for (; ti < t; ++ti) {  // odd tail tree
            const Node* tree = nodes + (size_t)ti * m;
            int32_t idx = 0;
            for (int32_t d = 0; d < max_depth; ++d) {
                const Node& nd = tree[idx];
                if (nd.feat < 0) break;  // leaf (LEAF == -1) self-loops
                const float xv = row[nd.feat];
                bool go_left = xv <= nd.thr;
                if (has_dl && std::isnan(xv) && nd.dl >= 0)
                    go_left = nd.dl != 0;
                idx = go_left ? nd.left : nd.right;
            }
            acc += tree[idx].value;
        }
        out[i] = aggregation == 0 ? acc / (float)t
               : aggregation == 1 ? 1.0f / (1.0f + std::exp(-(acc + base_score)))
                                  : acc;
    }
}

// fill rows [lo, hi) of a row-major f32 tile from typed column pointers
// (dtypes: 0 f32, 1 i32, 2 f64, 3/4 uint8/bool); dst row 0 = source row
// lo. NEGATIVE dtype codes skip the column — the fused chunk scorer
// computes those (window-derived) columns in place and fills the rest
// through this one shared path, so host-column casts cannot diverge.
inline void fill_tile(const void* const* cols, const int32_t* dtypes, int32_t f,
                      int64_t lo, int64_t hi, float* dst) {
    for (int32_t j = 0; j < f; ++j) {
        float* d = dst + j;
        switch (dtypes[j]) {
            case 0: {
                const float* s = (const float*)cols[j] + lo;
                for (int64_t i = 0; i < hi - lo; ++i) d[(size_t)i * f] = s[i];
                break;
            }
            case 1: {
                const int32_t* s = (const int32_t*)cols[j] + lo;
                for (int64_t i = 0; i < hi - lo; ++i) d[(size_t)i * f] = (float)s[i];
                break;
            }
            case 2: {
                const double* s = (const double*)cols[j] + lo;
                for (int64_t i = 0; i < hi - lo; ++i) d[(size_t)i * f] = (float)s[i];
                break;
            }
            case 3:
            case 4: {  // uint8 / bool
                const uint8_t* s = (const uint8_t*)cols[j] + lo;
                for (int64_t i = 0; i < hi - lo; ++i) d[(size_t)i * f] = (float)s[i];
                break;
            }
            default:
                break;  // negative: device-feature slot, computed in place
        }
    }
}

}  // namespace vctpu_forest

#endif  // VCTPU_FOREST_TILE_H_
