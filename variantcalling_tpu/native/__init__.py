"""Native (C++) host engine: BGZF codec, BAM depth walker, interval joins.

The reference's native layer is external subprocessed binaries (samtools,
bgzip/tabix, bedtools — SURVEY.md §2.5); ours is an in-process shared
library (``src/vctpu_native.cc``) compiled on demand with g++ and bound via
ctypes (pybind11 is not in the image). Every entry point has a pure-Python
fallback at its call site (io/bam.py depth walk, io/vcf.py + io/bed.py
compressed-text ingest, io/bgzf.py block writer), so the framework works
without a toolchain; with one, ingest runs at C speed and feeds flat
arrays straight to the device kernels.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

import numpy as np

from variantcalling_tpu.obs.sampler import native_span

_DIR = os.path.dirname(__file__)
_SRC = os.path.join(_DIR, "src", "vctpu_native.cc")
_SRC_CRAM = os.path.join(_DIR, "src", "vctpu_cram.cc")
_SRC_MATCH = os.path.join(_DIR, "src", "vctpu_match.cc")
_SRC_GBT = os.path.join(_DIR, "src", "vctpu_gbt.cc")
_SRC_FEAT = os.path.join(_DIR, "src", "vctpu_features.cc")
_SRC_FUSED = os.path.join(_DIR, "src", "vctpu_fused.cc")
#: shared inline headers — hashed into the build key (an edit must
#: rebuild every TU that includes them) but not compiled standalone
_HDRS = (os.path.join(_DIR, "src", "vctpu_threads.h"),
         os.path.join(_DIR, "src", "vctpu_feat_row.h"),
         os.path.join(_DIR, "src", "vctpu_forest_tile.h"))
_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None = None
_TRIED = False

_i64 = ctypes.c_int64
_u8p = ctypes.POINTER(ctypes.c_uint8)
_i32p = ctypes.POINTER(ctypes.c_int32)
_i64p = ctypes.POINTER(ctypes.c_int64)
_i8p = ctypes.POINTER(ctypes.c_int8)


_CXXFLAGS = ["-O3", "-march=native", "-funroll-loops", "-shared", "-fPIC", "-std=c++17"]


def _cpu_tag() -> str:
    """ISA fingerprint folded into the build cache key: -march=native
    binaries must not be reused by a host lacking the builder's
    extensions (shared site-packages / NFS homes / mixed pods)."""
    try:
        with open("/proc/cpuinfo") as fh:
            for line in fh:
                if line.startswith("flags"):
                    return hashlib.sha256(line.encode()).hexdigest()[:8]
    except OSError:
        pass
    import platform

    return platform.machine()


def _build() -> str | None:
    hasher = hashlib.sha256()
    hasher.update(" ".join(_CXXFLAGS).encode())  # flag changes rebuild too
    hasher.update(_cpu_tag().encode())  # so does a different host ISA
    for src in (_SRC, _SRC_CRAM, _SRC_MATCH, _SRC_GBT, _SRC_FEAT, _SRC_FUSED,
                *_HDRS):
        with open(src, "rb") as fh:
            hasher.update(fh.read())
    tag = hasher.hexdigest()[:12]
    out = os.path.join(_DIR, f"_vctpu_native_{tag}.so")
    if os.path.exists(out):
        return out
    # per-process tmp name keeps os.replace atomic under concurrent builds
    tmp = f"{out}.{os.getpid()}.tmp"
    cmd = ["g++", *_CXXFLAGS, "-o", tmp,
           _SRC, _SRC_CRAM, _SRC_MATCH, _SRC_GBT, _SRC_FEAT, _SRC_FUSED, "-lz"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=300)
        os.replace(tmp, out)
    except (OSError, subprocess.SubprocessError):
        return None
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    return out


def get_lib() -> ctypes.CDLL | None:
    """Compile (once, cached by source hash) and load the native library."""
    from variantcalling_tpu.utils import faults

    # injection point "native.build": simulates a build/load failure (even
    # when a cached .so exists) so REQUIRE_NATIVE / engine-resolution
    # failure paths are testable on a host whose toolchain works
    if faults.should_fire("native.build"):
        return None
    global _LIB, _TRIED
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        from variantcalling_tpu import knobs

        if knobs.get_bool("VCTPU_NO_NATIVE"):
            return None
        path = _build()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        lib.vctpu_bgzf_uncompressed_size.restype = _i64
        lib.vctpu_bgzf_uncompressed_size.argtypes = [_u8p, _i64]
        lib.vctpu_gzip_inflate.restype = _i64
        lib.vctpu_gzip_inflate.argtypes = [_u8p, _i64, _u8p, _i64]
        lib.vctpu_bgzf_inflate.restype = _i64
        lib.vctpu_bgzf_inflate.argtypes = [_u8p, _i64, _u8p, _i64]
        lib.vctpu_bgzf_compress.restype = _i64
        lib.vctpu_bgzf_compress.argtypes = [_u8p, _i64, _u8p, _i64, ctypes.c_int]
        lib.vctpu_bam_depth.restype = _i64
        lib.vctpu_bam_depth.argtypes = [
            _u8p, _i64, _i64p, _i64p, ctypes.c_int32, _i32p,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_uint32,
        ]
        lib.vctpu_interval_membership.restype = None
        lib.vctpu_interval_membership.argtypes = [_i64p, _i64p, _i64, _i64p, _i64, _u8p]
        lib.vctpu_vcf_assemble.restype = _i64
        lib.vctpu_vcf_assemble.argtypes = [
            _u8p, _i64, _i64,
            _i64p, _i64p, _i64p, _i64p,
            _u8p, _i64p, _u8p, _i64p,
            _u8p, _i64,
        ]
        lib.vctpu_cram_header.restype = _i64
        lib.vctpu_cram_header.argtypes = [_u8p, _i64, _u8p, _i64]
        lib.vctpu_cram_count.restype = _i64
        lib.vctpu_cram_count.argtypes = [_u8p, _i64]
        lib.vctpu_match_contig.restype = _i64
        lib.vctpu_match_contig.argtypes = [
            _u8p, _i64,
            _i64, _i64p, _u8p, _i64p, _u8p, _i64p, _i8p,
            _i64, _i64p, _u8p, _i64p, _u8p, _i64p, _i8p,
            ctypes.c_int32,
            _u8p, _u8p, _u8p, _u8p, _i64p, _i64p,
        ]
        lib.vctpu_cram_pileup.restype = _i64
        lib.vctpu_cram_pileup.argtypes = [
            _u8p, _i64, ctypes.c_int32, _i64, _i64, _u8p, _i64, _i32p,
        ]
        lib.vctpu_cram_scan.restype = _i64
        lib.vctpu_cram_scan.argtypes = [
            _u8p, _i64, _i64, _i32p, _i64p, _i32p, _i32p, _i32p, _i32p,
        ]
        lib.vctpu_cram_depth.restype = _i64
        lib.vctpu_cram_depth.argtypes = [
            _u8p, _i64, _i64p, _i64p, ctypes.c_int32, _i32p,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_uint32,
        ]
        lib.vctpu_vcf_count.restype = _i64
        lib.vctpu_vcf_count.argtypes = [_u8p, _i64, _i64p]
        _f32p = ctypes.POINTER(ctypes.c_float)
        _f64p = ctypes.POINTER(ctypes.c_double)
        lib.vctpu_vcf_parse.restype = _i64
        lib.vctpu_vcf_parse.argtypes = [
            _u8p, _i64, _i64, _i64, ctypes.c_int32,
            _i64p, _i64p, _i64p, _i64p, _i64p, _i64p, _i64p,
            _i64p, _f64p,
            _i32p, _u8p, _i32p,
            _i8p, _u8p, _f32p, _f32p, _f32p,
            _u8p, _i32p, _i32p, _i32p, _i32p, _i32p, _i32p,
            _u8p, _i32p, ctypes.c_int32, _f64p,
        ]
        lib.vctpu_bin_features.restype = _i64
        lib.vctpu_bin_features.argtypes = [
            _f32p, _i64, ctypes.c_int32, _f32p, ctypes.c_int32, _u8p,
        ]
        lib.vctpu_gather_windows.restype = _i64
        lib.vctpu_gather_windows.argtypes = [
            _u8p, _i64, _i64p, _i64, ctypes.c_int32, _u8p,
        ]
        lib.vctpu_format_float_info.restype = _i64
        lib.vctpu_format_float_info.argtypes = [
            _f64p, _i64, _u8p, _i64, _u8p, _i64, _i64p,
        ]
        lib.vctpu_featurize_windows.restype = _i64
        lib.vctpu_featurize_windows.argtypes = [
            _u8p, _i64, ctypes.c_int32, ctypes.c_int32,
            _u8p, _i32p, _i32p, _i32p, _u8p, _i32p,
            _i32p, _i32p, _f32p, _i32p, _i32p, _i32p,
        ]
        lib.vctpu_featurize_gather.restype = _i64
        lib.vctpu_featurize_gather.argtypes = [
            _u8p, _i64, _i64p, _i64, ctypes.c_int32,
            _u8p, _i32p, _i32p, _i32p, _u8p, _i32p,
            _i32p, _i32p, _f32p, _i32p, _i32p, _i32p,
        ]
        lib.vctpu_build_matrix.restype = _i64
        lib.vctpu_build_matrix.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), _i32p, _i64, ctypes.c_int32, _f32p,
        ]
        lib.vctpu_fused_chunk_score.restype = _i64
        lib.vctpu_fused_chunk_score.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), _i64p, _i64p, ctypes.c_int32,
            _i64p, _i64, ctypes.c_int32,
            _u8p, _i32p, _i32p, _i32p, _u8p, _i32p,
            ctypes.POINTER(ctypes.c_void_p), _i32p, ctypes.c_int32, _i32p,
            _i32p, _f32p, _i32p, _i32p, _f32p, _u8p,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_float,
            _f32p,
        ]
        lib.vctpu_forest_predict.restype = _i64
        lib.vctpu_forest_predict.argtypes = [
            _f32p, _i64, ctypes.c_int32,
            _i32p, _f32p, _i32p, _i32p, _f32p, _u8p,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_float,
            _f32p,
        ]
        lib.vctpu_matrix_forest_predict.restype = _i64
        lib.vctpu_matrix_forest_predict.argtypes = [
            ctypes.POINTER(ctypes.c_void_p), _i32p, _i64, ctypes.c_int32,
            _i32p, ctypes.POINTER(ctypes.c_float), _i32p, _i32p,
            ctypes.POINTER(ctypes.c_float), _u8p,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_float,
            ctypes.POINTER(ctypes.c_float),
        ]
        lib.vctpu_fasta_encode.restype = _i64
        lib.vctpu_fasta_encode.argtypes = [
            _u8p, _i64, _i64, _i64, _i64, _u8p,
        ]
        lib.vctpu_coverage_stats.restype = _i64
        lib.vctpu_coverage_stats.argtypes = [
            _i32p, _i64, _i64, ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_float), _i64p,
        ]
        lib.vctpu_gbt_fit.restype = _i64
        lib.vctpu_gbt_fit.argtypes = [
            _u8p, _f32p, _f32p,
            _i64, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32,
            ctypes.c_float, ctypes.c_float, ctypes.c_float, ctypes.c_float,
            _i32p, _i32p, _f32p,
        ]
        _LIB = lib
        return _LIB


def available() -> bool:
    return get_lib() is not None


def _u8view(data) -> np.ndarray:
    """Zero-copy uint8 view over bytes / bytearray / ndarray."""
    return np.frombuffer(data, dtype=np.uint8) if not isinstance(data, np.ndarray) else data


def bgzf_decompress_array(data) -> np.ndarray | None:
    """Inflate a whole BGZF/gzip buffer to a uint8 array (no extra copies)."""
    lib = get_lib()
    if lib is None or len(data) == 0:
        return None
    src_arr = np.ascontiguousarray(_u8view(data))
    src = src_arr.ctypes.data_as(_u8p)
    with native_span("bgzf_inflate"):
        size = lib.vctpu_bgzf_uncompressed_size(src, len(src_arr))
        if size < 0:
            # not BGZF-framed: inflate with geometric capacity growth
            cap = max(4 * len(src_arr), 1 << 16)
            for _ in range(8):
                dst = np.empty(cap, dtype=np.uint8)
                n = lib.vctpu_gzip_inflate(src, len(src_arr), dst.ctypes.data_as(_u8p), cap)
                if n >= 0:
                    return dst[:n]
                cap *= 4
            return None
        dst = np.empty(max(int(size), 1), dtype=np.uint8)
        # block-parallel path first (per-member raw inflate at
        # prefix-summed offsets); -2 means the payload itself is corrupt
        # — the serial gzip walk would fail on it too, so fall back only
        # on -1 (framing)
        n = lib.vctpu_bgzf_inflate(src, len(src_arr), dst.ctypes.data_as(_u8p), int(size))
        if n == -1:
            n = lib.vctpu_gzip_inflate(src, len(src_arr), dst.ctypes.data_as(_u8p), int(size))
    if n != size:
        return None
    return dst[:n]


def bgzf_decompress(data: bytes) -> bytes | None:
    """Inflate a whole BGZF/gzip byte string; None → use the Python fallback."""
    out = bgzf_decompress_array(data)
    return None if out is None else out.tobytes()


def bgzf_compress(data, level: int = 6) -> bytes | None:
    """Deflate a bytes-like buffer into BGZF blocks (+EOF sentinel);
    None → Python fallback. Zero-copy on the way in: the engine deflates
    straight from the caller's buffer (bytes, memoryview, uint8 array) —
    the streaming writeback hands multi-MB chunk bodies through here and
    an extra materialization would double the write path's memory
    traffic."""
    lib = get_lib()
    if lib is None:
        return None
    src_arr = np.ascontiguousarray(_u8view(data))
    n_in = len(src_arr)
    src = src_arr.ctypes.data_as(_u8p) if n_in else \
        (ctypes.c_uint8 * 1).from_buffer_copy(b"\x00")
    n_blocks = n_in // 65280 + 1
    cap = n_in + n_blocks * 128 + 64
    dst = np.empty(cap, dtype=np.uint8)
    with native_span("bgzf_deflate"):
        n = lib.vctpu_bgzf_compress(src, n_in, dst.ctypes.data_as(_u8p),
                                    cap, level)
    if n < 0:
        return None
    return dst[:n].tobytes()


def bam_depth(
    records,
    contig_starts: np.ndarray,
    contig_lens: np.ndarray,
    diff_flat: np.ndarray,
    *,
    min_bq: int = 0,
    min_mapq: int = 0,
    min_read_length: int = 0,
    include_deletions: bool = True,
    exclude_flags: int = 0x704,
) -> int | None:
    """Accumulate depth diffs over raw BAM records (bytes or uint8 array view);
    None → Python fallback."""
    lib = get_lib()
    if lib is None:
        return None
    starts = np.ascontiguousarray(contig_starts, dtype=np.int64)
    lens = np.ascontiguousarray(contig_lens, dtype=np.int64)
    assert diff_flat.dtype == np.int32 and diff_flat.flags["C_CONTIGUOUS"]
    src_arr = np.ascontiguousarray(_u8view(records))
    n = lib.vctpu_bam_depth(
        src_arr.ctypes.data_as(_u8p), len(src_arr),
        starts.ctypes.data_as(_i64p), lens.ctypes.data_as(_i64p), len(starts),
        diff_flat.ctypes.data_as(_i32p),
        min_bq, min_mapq, min_read_length, int(include_deletions), exclude_flags,
    )
    return None if n < 0 else int(n)


# INFO keys extracted during the native VCF scan; info_field() serves these
# from the cache without touching the INFO strings (filter/featurize hot set)
VCF_INFO_KEYS = ("DP", "SOR", "AF", "QD", "FS", "MQ", "TLOD", "AS_SOR", "DB", "END")


def vcf_parse(buf, n_samples: int) -> dict | None:
    """One-pass columnar parse of an uncompressed VCF text buffer.

    Returns a dict of flat arrays (see vctpu_vcf_parse in src) or None when
    the native library is unavailable / input malformed — caller falls back
    to the Python line parser.
    """
    lib = get_lib()
    if lib is None:
        return None
    src_arr = np.ascontiguousarray(_u8view(buf))
    src = src_arr.ctypes.data_as(_u8p)
    first_off = _i64(0)
    n = lib.vctpu_vcf_count(src, len(src_arr), ctypes.byref(first_off))
    if n < 0:
        return None
    n = int(n)
    uniq_cap = 4096
    f32, f64, i64, i32 = np.float32, np.float64, np.int64, np.int32
    # every span column is its own contiguous (n, 2) buffer: downstream
    # consumers (NativeAux, the assemble call) use them directly with no
    # strided-slice copies (round-4 writeback profile: 1.2s at 5M records)
    out = {
        "n": n,
        "line_spans": np.empty((n, 2), dtype=i64),
        "id_spans": np.empty((n, 2), dtype=i64),
        "ref_spans": np.empty((n, 2), dtype=i64),
        "alt_spans": np.empty((n, 2), dtype=i64),
        "filter_spans": np.empty((n, 2), dtype=i64),
        "info_spans": np.empty((n, 2), dtype=i64),
        "tail_spans": np.empty((n, 2), dtype=i64),
        "pos": np.empty(n, dtype=i64),
        "qual": np.empty(n, dtype=f64),
        "chrom_codes": np.empty(n, dtype=i32),
        "gt": np.empty((n, 2), dtype=np.int8),
        "gt_phased": np.empty(n, dtype=np.uint8),
        "gq": np.empty(n, dtype=f32),
        "dp_fmt": np.empty(n, dtype=f32),
        "ad": np.empty((n, 3), dtype=f32),
        "aclass": np.empty(n, dtype=np.uint8),
        "indel_length": np.empty(n, dtype=i32),
        "indel_nuc": np.empty(n, dtype=i32),
        "ref_code": np.empty(n, dtype=i32),
        "alt_code": np.empty(n, dtype=i32),
        "n_alts": np.empty(n, dtype=i32),
        "ref_len": np.empty(n, dtype=i32),
        "info_vals": np.empty((n, len(VCF_INFO_KEYS)), dtype=f64),
    }
    if n == 0:
        out["chroms"] = []
        return out
    uniq_buf = np.zeros(uniq_cap * 64, dtype=np.uint8)
    uniq_n = (ctypes.c_int32 * 1)(uniq_cap)
    keys_b = "".join(VCF_INFO_KEYS).encode()
    keys_arr = np.frombuffer(keys_b, dtype=np.uint8)
    key_lens = np.asarray([len(k) for k in VCF_INFO_KEYS], dtype=i32)

    def p(a, typ):
        return a.ctypes.data_as(typ)

    _f32p = ctypes.POINTER(ctypes.c_float)
    _f64p = ctypes.POINTER(ctypes.c_double)
    _i8p = ctypes.POINTER(ctypes.c_int8)
    rc = lib.vctpu_vcf_parse(
        src, len(src_arr), first_off.value, n, int(n_samples),
        p(out["line_spans"], _i64p), p(out["id_spans"], _i64p),
        p(out["ref_spans"], _i64p), p(out["alt_spans"], _i64p),
        p(out["filter_spans"], _i64p), p(out["info_spans"], _i64p),
        p(out["tail_spans"], _i64p),
        p(out["pos"], _i64p), p(out["qual"], _f64p),
        p(out["chrom_codes"], _i32p), p(uniq_buf, _u8p), uniq_n,
        p(out["gt"], _i8p), p(out["gt_phased"], _u8p),
        p(out["gq"], _f32p), p(out["dp_fmt"], _f32p), p(out["ad"], _f32p),
        p(out["aclass"], _u8p), p(out["indel_length"], _i32p), p(out["indel_nuc"], _i32p),
        p(out["ref_code"], _i32p), p(out["alt_code"], _i32p), p(out["n_alts"], _i32p),
        p(out["ref_len"], _i32p),
        p(np.ascontiguousarray(keys_arr), _u8p), p(key_lens, _i32p), len(VCF_INFO_KEYS),
        p(out["info_vals"], _f64p),
    )
    if rc != n:
        return None
    n_uniq = uniq_n[0]
    out["chroms"] = [
        bytes(uniq_buf[i * 64 : (i + 1) * 64]).rstrip(b"\x00").decode() for i in range(n_uniq)
    ]
    return out


def vcf_assemble(
    buf: np.ndarray,
    line_spans: np.ndarray,
    filter_spans: np.ndarray,
    info_spans: np.ndarray,
    tail_spans: np.ndarray,
    filt_blob: bytes,
    filt_offs: np.ndarray,
    sfx_blob: bytes,
    sfx_offs: np.ndarray,
    out: np.ndarray | None = None,
) -> np.ndarray | None:
    """Assemble writeback record lines from parse-buffer spans + new FILTER/INFO.

    Returns the uint8 output buffer (a view of ``out`` when provided and
    large enough — chunked writers reuse one buffer so each call touches
    warm pages), or None -> Python fallback.
    """
    lib = get_lib()
    if lib is None:
        return None
    n = len(line_spans)
    src = np.ascontiguousarray(_u8view(buf))
    # bytes OR uint8 ndarray blobs (ndarray: no copy, no bool ambiguity)
    fb = np.ascontiguousarray(_u8view(filt_blob)) if len(filt_blob) else np.zeros(1, np.uint8)
    sb = np.ascontiguousarray(_u8view(sfx_blob)) if len(sfx_blob) else np.zeros(1, np.uint8)
    cap = int(
        (line_spans[:, 1] - line_spans[:, 0]).sum() + len(filt_blob) + len(sfx_blob) + 4 * n + 64
    )
    if out is None or len(out) < cap or out.dtype != np.uint8 or not out.flags["C_CONTIGUOUS"]:
        out = np.empty(cap, dtype=np.uint8)

    # keep contiguous copies referenced for the duration of the call
    arrs = [
        np.ascontiguousarray(a, dtype=np.int64)
        for a in (line_spans, filter_spans, info_spans, tail_spans, filt_offs, sfx_offs)
    ]
    w = lib.vctpu_vcf_assemble(
        src.ctypes.data_as(_u8p), len(src), n,
        arrs[0].ctypes.data_as(_i64p), arrs[1].ctypes.data_as(_i64p),
        arrs[2].ctypes.data_as(_i64p), arrs[3].ctypes.data_as(_i64p),
        fb.ctypes.data_as(_u8p), arrs[4].ctypes.data_as(_i64p),
        sb.ctypes.data_as(_u8p), arrs[5].ctypes.data_as(_i64p),
        out.ctypes.data_as(_u8p), cap,
    )
    if w < 0:
        return None
    return out[:w]


def cram_header(buf) -> str | None:
    """SAM header text of a CRAM 3.0 buffer; None when unavailable/unsupported."""
    lib = get_lib()
    if lib is None:
        return None
    src = np.ascontiguousarray(_u8view(buf))
    cap = 1 << 20
    for _ in range(4):
        out = np.empty(cap, dtype=np.uint8)
        n = lib.vctpu_cram_header(src.ctypes.data_as(_u8p), len(src), out.ctypes.data_as(_u8p), cap)
        if n == -3:
            cap *= 8
            continue
        if n < 0:
            return None
        return out[:n].tobytes().decode("utf-8", "replace")
    return None


def cram_count(buf) -> int | None:
    """Exact record count from the container headers (no block decode)."""
    lib = get_lib()
    if lib is None:
        return None
    src = np.ascontiguousarray(_u8view(buf))
    n = lib.vctpu_cram_count(src.ctypes.data_as(_u8p), len(src))
    return None if n < 0 else int(n)


def cram_scan(buf, max_records: int) -> dict | None:
    """Per-record alignment arrays from a CRAM 3.0 buffer.

    Returns {ref_id, pos (1-based), span, mapq, flags, read_len} or None on
    unsupported input (caller raises a clear error — there is no Python
    fallback for CRAM decoding).
    """
    lib = get_lib()
    if lib is None:
        return None
    src = np.ascontiguousarray(_u8view(buf))
    out = {
        "ref_id": np.empty(max_records, dtype=np.int32),
        "pos": np.empty(max_records, dtype=np.int64),
        "span": np.empty(max_records, dtype=np.int32),
        "mapq": np.empty(max_records, dtype=np.int32),
        "flags": np.empty(max_records, dtype=np.int32),
        "read_len": np.empty(max_records, dtype=np.int32),
    }
    n = lib.vctpu_cram_scan(
        src.ctypes.data_as(_u8p), len(src), max_records,
        out["ref_id"].ctypes.data_as(_i32p), out["pos"].ctypes.data_as(_i64p),
        out["span"].ctypes.data_as(_i32p), out["mapq"].ctypes.data_as(_i32p),
        out["flags"].ctypes.data_as(_i32p), out["read_len"].ctypes.data_as(_i32p),
    )
    if n == -4:
        return "grow"  # capacity exceeded — caller retries with more room
    if n < 0:
        return None
    return {k: v[:n] for k, v in out.items()}


def cram_depth(
    buf,
    contig_starts: np.ndarray,
    contig_lens: np.ndarray,
    diff_flat: np.ndarray,
    *,
    min_bq: int = 0,
    min_mapq: int = 0,
    min_read_length: int = 0,
    include_deletions: bool = True,
    exclude_flags: int = 0x704,
) -> int | None:
    """Accumulate samtools-depth-semantics diffs over a CRAM buffer (the
    CRAM twin of :func:`bam_depth`, including the per-base ``-q`` filter);
    None when unavailable, negative handled by the caller."""
    lib = get_lib()
    if lib is None:
        return None
    starts = np.ascontiguousarray(contig_starts, dtype=np.int64)
    lens = np.ascontiguousarray(contig_lens, dtype=np.int64)
    assert diff_flat.dtype == np.int32 and diff_flat.flags["C_CONTIGUOUS"]
    src_arr = np.ascontiguousarray(_u8view(buf))
    n = lib.vctpu_cram_depth(
        src_arr.ctypes.data_as(_u8p), len(src_arr),
        starts.ctypes.data_as(_i64p), lens.ctypes.data_as(_i64p), len(starts),
        diff_flat.ctypes.data_as(_i32p),
        min_bq, min_mapq, min_read_length, int(include_deletions), exclude_flags,
    )
    return int(n)


def cram_pileup(buf, target_ref: int, start0: int, end0: int, ref_seq: str) -> np.ndarray | None:
    """(end0-start0, 4) aligned base counts over one contig window.

    ``ref_seq`` is the FULL target contig sequence (bases between CRAM
    features are reference matches; X features go through the SM matrix).
    """
    lib = get_lib()
    if lib is None:
        return None
    src = np.ascontiguousarray(_u8view(buf))
    ref = np.frombuffer(ref_seq.encode("ascii", "replace"), dtype=np.uint8)
    counts = np.zeros((max(end0 - start0, 0), 4), dtype=np.int32)
    n = lib.vctpu_cram_pileup(
        src.ctypes.data_as(_u8p), len(src), target_ref, start0, end0,
        ref.ctypes.data_as(_u8p), len(ref), counts.ctypes.data_as(_i32p),
    )
    if n < 0:
        return None
    return counts




def _pack(items):
    """(uint8 blob, (n+1) int64 offsets) over concatenated strings."""
    blob = "".join(items).encode("latin-1")
    offs = np.zeros(len(items) + 1, dtype=np.int64)
    np.cumsum(np.fromiter(map(len, items), dtype=np.int64, count=len(items)), out=offs[1:])
    return np.frombuffer(blob or b"\x00", dtype=np.uint8), offs


def match_contig_native(ref_seq: str, c_pos, c_ref, c_alt, c_gt,
                        t_pos, t_ref, t_alt, t_gt, haplotype_rescue: bool = True):
    """Native haplotype matcher; None -> Python fallback.

    ``c_ref``/``t_ref`` are per-record REF strings, ``c_alt``/``t_alt`` the
    comma-joined ALT strings; returns (call_tp, call_tp_gt, truth_tp,
    truth_tp_gt, call_truth_idx) as the Python matcher does.
    """
    lib = get_lib()
    if lib is None:
        return None
    nc, nt = len(c_pos), len(t_pos)
    seq = np.frombuffer(ref_seq.encode("latin-1") or b"\x00", dtype=np.uint8)
    crb, cro = _pack(list(c_ref))
    cab, cao = _pack(list(c_alt))
    trb, tro = _pack(list(t_ref))
    tab, tao = _pack(list(t_alt))
    cp = np.ascontiguousarray(c_pos, dtype=np.int64)
    tp = np.ascontiguousarray(t_pos, dtype=np.int64)
    cg = np.ascontiguousarray(c_gt, dtype=np.int8)
    tg = np.ascontiguousarray(t_gt, dtype=np.int8)
    call_tp = np.zeros(max(nc, 1), dtype=np.uint8)
    call_tp_gt = np.zeros(max(nc, 1), dtype=np.uint8)
    truth_tp = np.zeros(max(nt, 1), dtype=np.uint8)
    truth_tp_gt = np.zeros(max(nt, 1), dtype=np.uint8)
    idx = np.full(max(nc, 1), -1, dtype=np.int64)
    stats = np.zeros(2, dtype=np.int64)  # capped clusters, variants in them
    rc = lib.vctpu_match_contig(
        seq.ctypes.data_as(_u8p), len(ref_seq),
        nc, cp.ctypes.data_as(_i64p), crb.ctypes.data_as(_u8p), cro.ctypes.data_as(_i64p),
        cab.ctypes.data_as(_u8p), cao.ctypes.data_as(_i64p), cg.ctypes.data_as(_i8p),
        nt, tp.ctypes.data_as(_i64p), trb.ctypes.data_as(_u8p), tro.ctypes.data_as(_i64p),
        tab.ctypes.data_as(_u8p), tao.ctypes.data_as(_i64p), tg.ctypes.data_as(_i8p),
        1 if haplotype_rescue else 0,
        call_tp.ctypes.data_as(_u8p), call_tp_gt.ctypes.data_as(_u8p),
        truth_tp.ctypes.data_as(_u8p), truth_tp_gt.ctypes.data_as(_u8p),
        idx.ctypes.data_as(_i64p), stats.ctypes.data_as(_i64p),
    )
    if rc != 0:
        return None
    return (call_tp[:nc].astype(bool), call_tp_gt[:nc].astype(bool),
            truth_tp[:nt].astype(bool), truth_tp_gt[:nt].astype(bool), idx[:nc], stats)


def interval_membership(starts: np.ndarray, ends: np.ndarray, pos: np.ndarray) -> np.ndarray | None:
    """1/0 membership of each pos in sorted non-overlapping [start, end)."""
    lib = get_lib()
    if lib is None:
        return None
    s = np.ascontiguousarray(starts, dtype=np.int64)
    e = np.ascontiguousarray(ends, dtype=np.int64)
    p = np.ascontiguousarray(pos, dtype=np.int64)
    out = np.zeros(len(p), dtype=np.uint8)
    lib.vctpu_interval_membership(
        s.ctypes.data_as(_i64p), e.ctypes.data_as(_i64p), len(s),
        p.ctypes.data_as(_i64p), len(p), out.ctypes.data_as(_u8p),
    )
    return out


def bin_features(x: np.ndarray, edges: np.ndarray) -> np.ndarray | None:
    """searchsorted-left quantile binning (NaN -> last bin), uint8 out;
    exact match for the numpy/jnp binning in models/boosting."""
    lib = get_lib()
    if lib is None or edges.shape[1] > 255:
        return None
    _f32p = ctypes.POINTER(ctypes.c_float)
    xx = np.ascontiguousarray(x, dtype=np.float32)
    ee = np.ascontiguousarray(edges, dtype=np.float32)
    n, f = xx.shape
    out = np.empty((n, f), dtype=np.uint8)
    rc = lib.vctpu_bin_features(
        xx.ctypes.data_as(_f32p), n, f,
        ee.ctypes.data_as(_f32p), ee.shape[1], out.ctypes.data_as(_u8p),
    )
    return out if rc == 0 else None


def featurize_windows(windows: np.ndarray, center: int,
                      is_indel: np.ndarray, indel_nuc: np.ndarray,
                      ref_code: np.ndarray, alt_code: np.ndarray,
                      is_snp: np.ndarray, flow_order: np.ndarray) -> dict | None:
    """Native window featurization (ops/features.py device-kernel twin);
    returns the DEVICE_FEATURES columns dict or None when unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    _f32p = ctypes.POINTER(ctypes.c_float)
    ww = np.ascontiguousarray(windows, dtype=np.uint8)
    n, w = ww.shape
    ii = np.ascontiguousarray(is_indel, dtype=np.uint8)
    nu = np.ascontiguousarray(indel_nuc, dtype=np.int32)
    rc_ = np.ascontiguousarray(ref_code, dtype=np.int32)
    ac = np.ascontiguousarray(alt_code, dtype=np.int32)
    sn = np.ascontiguousarray(is_snp, dtype=np.uint8)
    fo = np.ascontiguousarray(flow_order, dtype=np.int32)
    hl = np.empty(n, dtype=np.int32)
    hn = np.empty(n, dtype=np.int32)
    gc = np.empty(n, dtype=np.float32)
    cy = np.empty(n, dtype=np.int32)
    lm = np.empty(n, dtype=np.int32)
    rm = np.empty(n, dtype=np.int32)
    rc = lib.vctpu_featurize_windows(
        ww.ctypes.data_as(_u8p), n, w, center,
        ii.ctypes.data_as(_u8p), nu.ctypes.data_as(_i32p),
        rc_.ctypes.data_as(_i32p), ac.ctypes.data_as(_i32p),
        sn.ctypes.data_as(_u8p), fo.ctypes.data_as(_i32p),
        hl.ctypes.data_as(_i32p), hn.ctypes.data_as(_i32p),
        gc.ctypes.data_as(_f32p), cy.ctypes.data_as(_i32p),
        lm.ctypes.data_as(_i32p), rm.ctypes.data_as(_i32p),
    )
    if rc != 0:
        return None
    return {"hmer_indel_length": hl, "hmer_indel_nuc": hn, "gc_content": gc,
            "cycleskip_status": cy, "left_motif": lm, "right_motif": rm}


def featurize_gather(seq: np.ndarray, pos0: np.ndarray, radius: int,
                     is_indel, indel_nuc, ref_code, alt_code, is_snp,
                     flow_order: np.ndarray,
                     outs: tuple[np.ndarray, ...]) -> bool:
    """Fused gather+featurize over one contig (no window tensor): writes
    the six DEVICE_FEATURES columns into ``outs`` = (hmer_len, hmer_nuc,
    gc, cyc, left_motif, right_motif) — contiguous views so callers
    featurize per-contig row ranges in place. Returns False when the
    native library is unavailable or arguments are rejected."""
    lib = get_lib()
    if lib is None:
        return False
    _f32p = ctypes.POINTER(ctypes.c_float)
    s = np.ascontiguousarray(seq, dtype=np.uint8)
    p = np.ascontiguousarray(pos0, dtype=np.int64)
    ii = np.ascontiguousarray(is_indel, dtype=np.uint8)
    nu = np.ascontiguousarray(indel_nuc, dtype=np.int32)
    rc_ = np.ascontiguousarray(ref_code, dtype=np.int32)
    ac = np.ascontiguousarray(alt_code, dtype=np.int32)
    sn = np.ascontiguousarray(is_snp, dtype=np.uint8)
    fo = np.ascontiguousarray(flow_order, dtype=np.int32)
    hl, hn, gc, cy, lm, rm = outs
    for a, dt in zip(outs, (np.int32, np.int32, np.float32, np.int32, np.int32, np.int32)):
        if a.dtype != dt or not a.flags["C_CONTIGUOUS"] or len(a) != len(p):
            return False
    rc = lib.vctpu_featurize_gather(
        s.ctypes.data_as(_u8p), len(s), p.ctypes.data_as(_i64p), len(p), radius,
        ii.ctypes.data_as(_u8p), nu.ctypes.data_as(_i32p),
        rc_.ctypes.data_as(_i32p), ac.ctypes.data_as(_i32p),
        sn.ctypes.data_as(_u8p), fo.ctypes.data_as(_i32p),
        hl.ctypes.data_as(_i32p), hn.ctypes.data_as(_i32p),
        gc.ctypes.data_as(_f32p), cy.ctypes.data_as(_i32p),
        lm.ctypes.data_as(_i32p), rm.ctypes.data_as(_i32p),
    )
    return rc == 0


def gather_windows_contig(seq: np.ndarray, pos0: np.ndarray, radius: int,
                          out: np.ndarray | None = None) -> np.ndarray | None:
    """(n, 2r+1) uint8 windows over one encoded contig (out-of-range -> N).

    ``out`` lets callers gather straight into a slice of a larger window
    tensor (contiguous uint8, right shape) — no intermediate copy."""
    lib = get_lib()
    if lib is None:
        return None
    s = np.ascontiguousarray(seq, dtype=np.uint8)
    p = np.ascontiguousarray(pos0, dtype=np.int64)
    shape = (len(p), 2 * radius + 1)
    if out is None or out.shape != shape or out.dtype != np.uint8 \
            or not out.flags["C_CONTIGUOUS"]:
        out = np.empty(shape, dtype=np.uint8)
    rc = lib.vctpu_gather_windows(
        s.ctypes.data_as(_u8p), len(s), p.ctypes.data_as(_i64p), len(p),
        radius, out.ctypes.data_as(_u8p),
    )
    return out if rc == 0 else None


def format_float_info(vals: np.ndarray, prefix: bytes) -> tuple[np.ndarray, np.ndarray] | None:
    """Render b";KEY=<%g>" per non-NaN value (empty for NaN); returns
    (byte buffer, (n+1,) offsets) or None when unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    _f64p = ctypes.POINTER(ctypes.c_double)
    v = np.ascontiguousarray(vals, dtype=np.float64)
    n = len(v)
    cap = n * (len(prefix) + 32) + 64
    buf = np.empty(cap, dtype=np.uint8)
    offs = np.empty(n + 1, dtype=np.int64)
    p = np.frombuffer(prefix, dtype=np.uint8) if prefix else np.zeros(0, np.uint8)
    total = lib.vctpu_format_float_info(
        v.ctypes.data_as(_f64p), n, p.ctypes.data_as(_u8p), len(p),
        buf.ctypes.data_as(_u8p), cap, offs.ctypes.data_as(_i64p),
    )
    if total < 0:
        return None
    return buf[:total], offs


_MATRIX_DTYPES = {np.dtype(np.float32): 0, np.dtype(np.int32): 1,
                  np.dtype(np.float64): 2, np.dtype(np.uint8): 3,
                  np.dtype(np.bool_): 4}


def _marshal_cols(cols: list[np.ndarray]):
    """(void* array, dtype codes, n, keep-alive refs) for typed column
    arrays; None when any dtype/shape is unsupported. Shared by every
    column-consuming kernel so they cannot diverge on what they accept."""
    if not cols:
        return None
    arrs = []
    codes = np.empty(len(cols), dtype=np.int32)
    n = len(cols[0])
    for j, c in enumerate(cols):
        a = np.ascontiguousarray(c)
        code = _MATRIX_DTYPES.get(a.dtype)
        if code is None or a.ndim != 1 or len(a) != n:
            return None
        arrs.append(a)
        codes[j] = code
    ptrs = (ctypes.c_void_p * len(arrs))(*[a.ctypes.data for a in arrs])
    return ptrs, codes, n, arrs


def _marshal_forest(feat, thr, left, right, value, default_left):
    """Contiguous typed copies of the packed-forest arrays (shared by the
    forest-walk entry points)."""
    return (np.ascontiguousarray(feat, dtype=np.int32),
            np.ascontiguousarray(thr, dtype=np.float32),
            np.ascontiguousarray(left, dtype=np.int32),
            np.ascontiguousarray(right, dtype=np.int32),
            np.ascontiguousarray(value, dtype=np.float32),
            None if default_left is None
            else np.ascontiguousarray(default_left, dtype=np.uint8))


def build_matrix(cols: list[np.ndarray]) -> np.ndarray | None:
    """(n, f) float32 matrix from per-column arrays without numpy's
    per-column temporaries; None -> numpy fallback."""
    lib = get_lib()
    if lib is None:
        return None
    marshalled = _marshal_cols(cols)
    if marshalled is None:
        return None
    ptrs, codes, n, _arrs = marshalled
    out = np.empty((n, len(cols)), dtype=np.float32)
    _f32p = ctypes.POINTER(ctypes.c_float)
    rc = lib.vctpu_build_matrix(ptrs, codes.ctypes.data_as(_i32p), n, len(cols),
                                out.ctypes.data_as(_f32p))
    return out if rc == 0 else None


def forest_predict(x: np.ndarray, feat: np.ndarray, thr: np.ndarray,
                   left: np.ndarray, right: np.ndarray, value: np.ndarray,
                   default_left: np.ndarray | None, max_depth: int,
                   aggregation: str, base_score: float) -> np.ndarray | None:
    """Native gather-walk forest inference (models/forest.predict_score
    semantics); returns (n,) float32 scores or None when unavailable.
    ``aggregation="sum"`` returns the RAW canonical-order leaf sums
    (no mean/sigmoid) — the engine-parity path finalizes on the host."""
    lib = get_lib()
    if lib is None or aggregation not in ("mean", "logit_sum", "sum"):
        return None
    _f32p = ctypes.POINTER(ctypes.c_float)
    xx = np.ascontiguousarray(x, dtype=np.float32)
    ff, tt, ll, rr, vv, dl = _marshal_forest(feat, thr, left, right, value, default_left)
    n, f = xx.shape
    t, m = ff.shape
    out = np.empty(n, dtype=np.float32)
    rc = lib.vctpu_forest_predict(
        xx.ctypes.data_as(_f32p), n, f,
        ff.ctypes.data_as(_i32p), tt.ctypes.data_as(_f32p),
        ll.ctypes.data_as(_i32p), rr.ctypes.data_as(_i32p),
        vv.ctypes.data_as(_f32p),
        None if dl is None else dl.ctypes.data_as(_u8p),
        t, m, max_depth, {"mean": 0, "logit_sum": 1, "sum": 2}[aggregation], base_score,
        out.ctypes.data_as(_f32p),
    )
    return out if rc == 0 else None


def matrix_forest_predict(cols: list[np.ndarray], feat: np.ndarray, thr: np.ndarray,
                          left: np.ndarray, right: np.ndarray, value: np.ndarray,
                          default_left: np.ndarray | None, max_depth: int,
                          aggregation: str, base_score: float) -> np.ndarray | None:
    """Fused column->matrix->forest inference: L2-resident row tiles are
    built from the typed column pointers and walked immediately, so the
    full (n, f) float32 matrix never exists. Bit-identical scores to
    build_matrix + forest_predict; None -> caller uses the two-step path.
    ``aggregation="sum"`` returns raw canonical-order leaf sums (the
    engine-parity path finalizes on the host)."""
    lib = get_lib()
    if lib is None or aggregation not in ("mean", "logit_sum", "sum"):
        return None
    marshalled = _marshal_cols(cols)
    if marshalled is None:
        return None
    ptrs, codes, n, _arrs = marshalled
    _f32p = ctypes.POINTER(ctypes.c_float)
    ff, tt, ll, rr, vv, dl = _marshal_forest(feat, thr, left, right, value, default_left)
    t, m = ff.shape
    out = np.empty(n, dtype=np.float32)
    rc = lib.vctpu_matrix_forest_predict(
        ptrs, codes.ctypes.data_as(_i32p), n, len(cols),
        ff.ctypes.data_as(_i32p), tt.ctypes.data_as(_f32p),
        ll.ctypes.data_as(_i32p), rr.ctypes.data_as(_i32p),
        vv.ctypes.data_as(_f32p),
        None if dl is None else dl.ctypes.data_as(_u8p),
        t, m, max_depth, {"mean": 0, "logit_sum": 1, "sum": 2}[aggregation], base_score,
        out.ctypes.data_as(_f32p),
    )
    return out if rc == 0 else None


def fused_chunk_score(run_seqs: list[np.ndarray], run_bounds: np.ndarray,
                      pos0: np.ndarray, radius: int,
                      is_indel, indel_nuc, ref_code, alt_code, is_snp,
                      flow_order: np.ndarray,
                      cols: list, dev_cols: np.ndarray,
                      feat: np.ndarray, thr: np.ndarray, left: np.ndarray,
                      right: np.ndarray, value: np.ndarray,
                      default_left: np.ndarray | None, max_depth: int,
                      aggregation: str, base_score: float) -> np.ndarray | None:
    """ONE native call per chunk: contig-run window gather -> featurize ->
    L2-tiled matrix fill -> forest walk, margins out (ROADMAP item 4).

    ``run_seqs`` holds the encoded contig of each contiguous row run
    (``run_bounds``, (n_runs+1,)); a contig missing from the FASTA passes
    an empty array (all-N windows). ``cols`` lists the HOST feature
    columns in feature order with ``None`` at the six window-derived
    slots; ``dev_cols`` (6,) names each device feature's column index
    (DEVICE_FEATURES order). ``aggregation="sum"`` returns raw
    canonical-order leaf sums — the engine-parity path finalizes on the
    host, exactly like :func:`matrix_forest_predict`. Margins are
    bit-identical to the unfused reference (shared row featurize, shared
    tile fill, shared walk). None -> caller uses the unfused path."""
    lib = get_lib()
    if lib is None or aggregation not in ("mean", "logit_sum", "sum"):
        return None
    n = len(pos0)
    _f32p = ctypes.POINTER(ctypes.c_float)
    # columns: typed pointers with dtype -1 at device-feature slots
    arrs = []
    codes = np.empty(len(cols), dtype=np.int32)
    for j, c in enumerate(cols):
        if c is None:
            arrs.append(None)
            codes[j] = -1
            continue
        a = np.ascontiguousarray(c)
        code = _MATRIX_DTYPES.get(a.dtype)
        if code is None or a.ndim != 1 or len(a) != n:
            return None
        arrs.append(a)
        codes[j] = code
    col_ptrs = (ctypes.c_void_p * len(cols))(
        *[None if a is None else a.ctypes.data for a in arrs])
    # contig runs: zero-copy pointers into the encoded contigs
    seqs = [np.ascontiguousarray(_u8view(s), dtype=np.uint8) for s in run_seqs]
    seq_ptrs = (ctypes.c_void_p * max(len(seqs), 1))(
        *([s.ctypes.data for s in seqs] or [None]))
    seq_lens = np.asarray([len(s) for s in seqs], dtype=np.int64)
    bounds = np.ascontiguousarray(run_bounds, dtype=np.int64)
    p = np.ascontiguousarray(pos0, dtype=np.int64)
    ii = np.ascontiguousarray(is_indel, dtype=np.uint8)
    nu = np.ascontiguousarray(indel_nuc, dtype=np.int32)
    rc_ = np.ascontiguousarray(ref_code, dtype=np.int32)
    ac = np.ascontiguousarray(alt_code, dtype=np.int32)
    sn = np.ascontiguousarray(is_snp, dtype=np.uint8)
    fo = np.ascontiguousarray(flow_order, dtype=np.int32)
    dc = np.ascontiguousarray(dev_cols, dtype=np.int32)
    ff, tt, ll, rr, vv, dl = _marshal_forest(feat, thr, left, right, value,
                                             default_left)
    t, m = ff.shape
    out = np.empty(n, dtype=np.float32)
    with native_span("fused_chunk_score"):
        rc = lib.vctpu_fused_chunk_score(
            seq_ptrs, seq_lens.ctypes.data_as(_i64p),
            bounds.ctypes.data_as(_i64p), len(seqs),
            p.ctypes.data_as(_i64p), n, radius,
            ii.ctypes.data_as(_u8p), nu.ctypes.data_as(_i32p),
            rc_.ctypes.data_as(_i32p), ac.ctypes.data_as(_i32p),
            sn.ctypes.data_as(_u8p), fo.ctypes.data_as(_i32p),
            col_ptrs, codes.ctypes.data_as(_i32p), len(cols),
            dc.ctypes.data_as(_i32p),
            ff.ctypes.data_as(_i32p), tt.ctypes.data_as(_f32p),
            ll.ctypes.data_as(_i32p), rr.ctypes.data_as(_i32p),
            vv.ctypes.data_as(_f32p),
            None if dl is None else dl.ctypes.data_as(_u8p),
            t, m, max_depth,
            {"mean": 0, "logit_sum": 1, "sum": 2}[aggregation],
            base_score,
            out.ctypes.data_as(_f32p),
        )
    return out if rc == 0 else None


def fasta_encode(raw: np.ndarray, line_bases: int, line_width: int,
                 length: int, out: np.ndarray | None = None) -> np.ndarray | None:
    """Threaded FASTA body encode (newline strip + ACGT->0..3 table, else 4).

    ``raw`` is the contig's byte region starting at its .fai offset; the
    result is byte-identical to the numpy reshape+lookup fallback in
    io/fasta._encode_contig. ``out`` lets callers encode into a slice of a
    preallocated whole-genome buffer. None -> numpy fallback."""
    lib = get_lib()
    if lib is None:
        return None
    src = np.ascontiguousarray(_u8view(raw))
    if out is None or len(out) != length or out.dtype != np.uint8 \
            or not out.flags["C_CONTIGUOUS"]:
        out = np.empty(length, dtype=np.uint8)
    rc = lib.vctpu_fasta_encode(
        src.ctypes.data_as(_u8p), len(src),
        int(line_bases), int(line_width), int(length),
        out.ctypes.data_as(_u8p),
    )
    return out if rc == 0 else None


def coverage_stats(data: np.ndarray, window: int, max_bin: int = 1000,
                   from_diffs: bool = False) -> tuple[np.ndarray, np.ndarray] | None:
    """Fused single-pass coverage reduce: (per-window f32 means,
    (max_bin+1,) int64 clipped histogram). ``from_diffs`` treats ``data``
    as a difference array (running cumsum = depth) so the bam/cram depth
    path reduces without materializing the depth vector. None -> fallback."""
    lib = get_lib()
    if lib is None:
        return None
    d = np.ascontiguousarray(data, dtype=np.int32)
    n = len(d)
    n_win = -(-n // window) if n else 0
    means = np.empty(n_win, dtype=np.float32)
    hist = np.empty(max_bin + 1, dtype=np.int64)
    _f32p = ctypes.POINTER(ctypes.c_float)
    rc = lib.vctpu_coverage_stats(
        d.ctypes.data_as(_i32p), n, int(window), int(max_bin),
        int(bool(from_diffs)),
        means.ctypes.data_as(_f32p), hist.ctypes.data_as(_i64p),
    )
    if rc != 0:
        return None
    return means, hist


def gbt_fit(binned: np.ndarray, y: np.ndarray, w: np.ndarray | None,
            n_trees: int, depth: int, n_bins: int,
            learning_rate: float, reg_lambda: float, min_child_weight: float,
            base_score: float):
    """Native histogram-GBT fit (src/vctpu_gbt.cc) — the CPU-fallback twin
    of models/boosting's jitted trainer (partitioned samples + sibling-
    subtraction histograms). Returns (feats, bins, leaves) shaped exactly
    like the jitted trainer's outputs, or None when the library is
    unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    _f32p = ctypes.POINTER(ctypes.c_float)
    bn = np.ascontiguousarray(binned, dtype=np.uint8)
    yy = np.ascontiguousarray(y, dtype=np.float32)
    ww = None if w is None else np.ascontiguousarray(w, dtype=np.float32)
    n, f = bn.shape
    leaves = 1 << depth
    feats = np.empty((n_trees, depth, leaves), dtype=np.int32)
    bins = np.empty((n_trees, depth, leaves), dtype=np.int32)
    vals = np.empty((n_trees, leaves), dtype=np.float32)
    rc = lib.vctpu_gbt_fit(
        bn.ctypes.data_as(_u8p), yy.ctypes.data_as(_f32p),
        None if ww is None else ww.ctypes.data_as(_f32p),
        n, f, n_bins, n_trees, depth,
        learning_rate, reg_lambda, min_child_weight, base_score,
        feats.ctypes.data_as(_i32p), bins.ctypes.data_as(_i32p),
        vals.ctypes.data_as(_f32p),
    )
    if rc != 0:
        return None
    return feats, bins, vals
