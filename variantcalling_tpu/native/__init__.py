"""Native (C++) host engine: BGZF codec, BAM depth walker, interval joins.

The reference's native layer is external subprocessed binaries (samtools,
bgzip/tabix, bedtools — SURVEY.md §2.5); ours is an in-process shared
library (``src/vctpu_native.cc``) compiled on demand with g++ and bound via
ctypes (pybind11 is not in the image). Every entry point has a pure-Python
fallback at its call site (io/bam.py depth walk, io/vcf.py + io/bed.py
compressed-text ingest, io/bgzf.py block writer), so the framework works
without a toolchain; with one, ingest runs at C speed and feeds flat
arrays straight to the device kernels.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(__file__)
_SRC = os.path.join(_DIR, "src", "vctpu_native.cc")
_LOCK = threading.Lock()
_LIB: ctypes.CDLL | None = None
_TRIED = False

_i64 = ctypes.c_int64
_u8p = ctypes.POINTER(ctypes.c_uint8)
_i32p = ctypes.POINTER(ctypes.c_int32)
_i64p = ctypes.POINTER(ctypes.c_int64)


def _build() -> str | None:
    with open(_SRC, "rb") as fh:
        tag = hashlib.sha256(fh.read()).hexdigest()[:12]
    out = os.path.join(_DIR, f"_vctpu_native_{tag}.so")
    if os.path.exists(out):
        return out
    # per-process tmp name keeps os.replace atomic under concurrent builds
    tmp = f"{out}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp, _SRC, "-lz"]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=300)
        os.replace(tmp, out)
    except (OSError, subprocess.SubprocessError):
        return None
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    return out


def get_lib() -> ctypes.CDLL | None:
    """Compile (once, cached by source hash) and load the native library."""
    global _LIB, _TRIED
    with _LOCK:
        if _TRIED:
            return _LIB
        _TRIED = True
        if os.environ.get("VCTPU_NO_NATIVE"):
            return None
        path = _build()
        if path is None:
            return None
        try:
            lib = ctypes.CDLL(path)
        except OSError:
            return None
        lib.vctpu_bgzf_uncompressed_size.restype = _i64
        lib.vctpu_bgzf_uncompressed_size.argtypes = [_u8p, _i64]
        lib.vctpu_gzip_inflate.restype = _i64
        lib.vctpu_gzip_inflate.argtypes = [_u8p, _i64, _u8p, _i64]
        lib.vctpu_bgzf_compress.restype = _i64
        lib.vctpu_bgzf_compress.argtypes = [_u8p, _i64, _u8p, _i64, ctypes.c_int]
        lib.vctpu_bam_depth.restype = _i64
        lib.vctpu_bam_depth.argtypes = [
            _u8p, _i64, _i64p, _i64p, ctypes.c_int32, _i32p,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_uint32,
        ]
        lib.vctpu_interval_membership.restype = None
        lib.vctpu_interval_membership.argtypes = [_i64p, _i64p, _i64, _i64p, _i64, _u8p]
        _LIB = lib
        return _LIB


def available() -> bool:
    return get_lib() is not None


def _u8view(data) -> np.ndarray:
    """Zero-copy uint8 view over bytes / bytearray / ndarray."""
    return np.frombuffer(data, dtype=np.uint8) if not isinstance(data, np.ndarray) else data


def bgzf_decompress_array(data) -> np.ndarray | None:
    """Inflate a whole BGZF/gzip buffer to a uint8 array (no extra copies)."""
    lib = get_lib()
    if lib is None or len(data) == 0:
        return None
    src_arr = np.ascontiguousarray(_u8view(data))
    src = src_arr.ctypes.data_as(_u8p)
    size = lib.vctpu_bgzf_uncompressed_size(src, len(src_arr))
    if size < 0:
        # not BGZF-framed: inflate with geometric capacity growth
        cap = max(4 * len(src_arr), 1 << 16)
        for _ in range(8):
            dst = np.empty(cap, dtype=np.uint8)
            n = lib.vctpu_gzip_inflate(src, len(src_arr), dst.ctypes.data_as(_u8p), cap)
            if n >= 0:
                return dst[:n]
            cap *= 4
        return None
    dst = np.empty(max(int(size), 1), dtype=np.uint8)
    n = lib.vctpu_gzip_inflate(src, len(src_arr), dst.ctypes.data_as(_u8p), int(size))
    if n != size:
        return None
    return dst[:n]


def bgzf_decompress(data: bytes) -> bytes | None:
    """Inflate a whole BGZF/gzip byte string; None → use the Python fallback."""
    out = bgzf_decompress_array(data)
    return None if out is None else out.tobytes()


def bgzf_compress(data: bytes, level: int = 6) -> bytes | None:
    """Deflate into BGZF blocks (+EOF sentinel); None → Python fallback."""
    lib = get_lib()
    if lib is None:
        return None
    src = (ctypes.c_uint8 * max(len(data), 1)).from_buffer_copy(data or b"\x00")
    n_blocks = len(data) // 65280 + 1
    cap = len(data) + n_blocks * 128 + 64
    dst = np.empty(cap, dtype=np.uint8)
    n = lib.vctpu_bgzf_compress(src, len(data), dst.ctypes.data_as(_u8p), cap, level)
    if n < 0:
        return None
    return dst[:n].tobytes()


def bam_depth(
    records,
    contig_starts: np.ndarray,
    contig_lens: np.ndarray,
    diff_flat: np.ndarray,
    *,
    min_bq: int = 0,
    min_mapq: int = 0,
    min_read_length: int = 0,
    include_deletions: bool = True,
    exclude_flags: int = 0x704,
) -> int | None:
    """Accumulate depth diffs over raw BAM records (bytes or uint8 array view);
    None → Python fallback."""
    lib = get_lib()
    if lib is None:
        return None
    starts = np.ascontiguousarray(contig_starts, dtype=np.int64)
    lens = np.ascontiguousarray(contig_lens, dtype=np.int64)
    assert diff_flat.dtype == np.int32 and diff_flat.flags["C_CONTIGUOUS"]
    src_arr = np.ascontiguousarray(_u8view(records))
    n = lib.vctpu_bam_depth(
        src_arr.ctypes.data_as(_u8p), len(src_arr),
        starts.ctypes.data_as(_i64p), lens.ctypes.data_as(_i64p), len(starts),
        diff_flat.ctypes.data_as(_i32p),
        min_bq, min_mapq, min_read_length, int(include_deletions), exclude_flags,
    )
    return None if n < 0 else int(n)


def interval_membership(starts: np.ndarray, ends: np.ndarray, pos: np.ndarray) -> np.ndarray | None:
    """1/0 membership of each pos in sorted non-overlapping [start, end)."""
    lib = get_lib()
    if lib is None:
        return None
    s = np.ascontiguousarray(starts, dtype=np.int64)
    e = np.ascontiguousarray(ends, dtype=np.int64)
    p = np.ascontiguousarray(pos, dtype=np.int64)
    out = np.zeros(len(p), dtype=np.uint8)
    lib.vctpu_interval_membership(
        s.ctypes.data_as(_i64p), e.ctypes.data_as(_i64p), len(s),
        p.ctypes.data_as(_i64p), len(p), out.ctypes.data_as(_u8p),
    )
    return out
