"""variantcalling_tpu — a TPU-native (JAX/XLA/Pallas/pjit) variant-calling post-processing framework.

Re-founds the capabilities of Ultimagen/VariantCalling (``ugvc``, reference at
``/root/reference``) on a columnar-tensor + JAX execution model:

- host-side VCF/BED/FASTA/BAM ingest into padded columnar numpy batches
  (:mod:`variantcalling_tpu.io`),
- device-side batched kernels for per-variant featurization, classifier
  inference/training, coverage reductions and SEC cohort statistics
  (:mod:`variantcalling_tpu.ops`, :mod:`variantcalling_tpu.models`),
- a mesh/sharding layer (:mod:`variantcalling_tpu.parallel`) replacing the
  reference's joblib/process fan-out (ref ``SURVEY.md`` §2.4) with
  ``jax.sharding`` + ``shard_map`` collectives,
- per-tool CLI pipelines mirroring the reference's argparse surfaces
  (:mod:`variantcalling_tpu.pipelines`).
"""

from __future__ import annotations

import logging
import sys

__version__ = "0.1.0"

logger = logging.getLogger("vctpu")
if not logger.handlers:
    _h = logging.StreamHandler(sys.stderr)
    _h.setFormatter(logging.Formatter("%(asctime)s %(name)s %(levelname)s %(message)s"))
    logger.addHandler(_h)
    logger.setLevel(logging.INFO)
