"""Variant featurization: VariantTable + reference genome -> device feature tensors.

This is the front half of the north-star hot path
(filter_variants_pipeline, docs/filter_variants_pipeline.md): the reference
computes per-variant annotations in pandas; here host code gathers fixed
-width reference windows and allele scalars, and
:mod:`variantcalling_tpu.ops.features` kernels compute the window-derived
features on device, fused with classifier inference.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

import jax
import jax.numpy as jnp

from variantcalling_tpu.io.bed import IntervalSet
from variantcalling_tpu.io.fasta import FastaReader, encode_seq
from variantcalling_tpu.io.vcf import VariantTable
from variantcalling_tpu.ops import features as fops
from variantcalling_tpu.ops import intervals as iops

WINDOW_RADIUS = 20  # bases either side of the anchor in the gathered window
CENTER = WINDOW_RADIUS

# feature order of the assembled matrix; models store this list as metadata
BASE_FEATURES = [
    "qual",
    "dp",
    "sor",
    "af",
    "gq",
    "is_het",
    "is_snp",
    "is_indel",
    "is_ins",
    "indel_length",
    "hmer_indel_length",
    "hmer_indel_nuc",
    "gc_content",
    "cycleskip_status",
    "left_motif",
    "right_motif",
    "ref_code",
    "alt_code",
    "n_alts",
]


@dataclass
class AlleleColumns:
    """Host-derived per-variant allele scalars (first ALT; multiallelic flagged)."""

    is_snp: np.ndarray
    is_indel: np.ndarray
    is_ins: np.ndarray
    indel_length: np.ndarray
    indel_nuc: np.ndarray  # 0..3 if single-nucleotide indel diff else 4
    ref_code: np.ndarray  # anchor base code for SNPs (else 4)
    alt_code: np.ndarray
    n_alts: np.ndarray


def classify_alleles(table: VariantTable) -> AlleleColumns:
    """Indel/SNP classification from REF/ALT strings (parity: classify_indel,
    ugbio_core.vcfbed.variant_annotation; run_no_gt_report.py:92).

    Served from the native scan cache when the table came through the C++
    ingest (io/vcf._read_vcf_native) — zero per-record Python on that path.
    """
    if table.aux is not None:
        a = table.aux.alle
        cls = a["aclass"]
        return AlleleColumns(  # fresh arrays: the cache must stay pristine
            is_snp=(cls & 1).astype(bool),
            is_indel=(cls & 2).astype(bool),
            is_ins=(cls & 4).astype(bool),
            indel_length=a["indel_length"].copy(),
            indel_nuc=a["indel_nuc"].copy(),
            ref_code=a["ref_code"].copy(),
            alt_code=a["alt_code"].copy(),
            n_alts=a["n_alts"].copy(),
        )
    n = len(table)
    is_snp = np.zeros(n, dtype=bool)
    is_indel = np.zeros(n, dtype=bool)
    is_ins = np.zeros(n, dtype=bool)
    indel_length = np.zeros(n, dtype=np.int32)
    indel_nuc = np.full(n, 4, dtype=np.int32)
    ref_code = np.full(n, 4, dtype=np.int32)
    alt_code = np.full(n, 4, dtype=np.int32)
    n_alts = table.n_alts()
    code = {"A": 0, "C": 1, "G": 2, "T": 3}
    for i in range(n):
        ref = table.ref[i]
        alt_s = table.alt[i]
        if alt_s in (".", ""):
            continue
        alt = alt_s.split(",")[0]
        if alt in ("<NON_REF>", "<*>") or alt.startswith("<"):
            continue
        if len(ref) == len(alt) == 1:
            is_snp[i] = True
            ref_code[i] = code.get(ref.upper(), 4)
            alt_code[i] = code.get(alt.upper(), 4)
        elif len(ref) != len(alt):
            is_indel[i] = True
            if len(alt) > len(ref):
                is_ins[i] = True
                diff = alt[len(ref) :] if alt.startswith(ref) else alt[1:]
            else:
                diff = ref[len(alt) :] if ref.startswith(alt) else ref[1:]
            indel_length[i] = abs(len(alt) - len(ref))
            u = set(diff.upper())
            if len(u) == 1:
                indel_nuc[i] = code.get(next(iter(u)), 4)
    return AlleleColumns(is_snp, is_indel, is_ins, indel_length, indel_nuc, ref_code, alt_code, n_alts)


def gather_windows(table: VariantTable, fasta: FastaReader, radius: int = WINDOW_RADIUS) -> np.ndarray:
    """(N, 2*radius+1) uint8 reference windows centered on each variant anchor.

    One contig-sequence encode per contig, then a vectorized gather — the
    host-side analog of the reference's per-record pyfaidx fetches.
    """
    n = len(table)
    out = np.full((n, 2 * radius + 1), 4, dtype=np.uint8)
    chrom = np.asarray(table.chrom)
    pos0 = table.pos - 1
    for contig in dict.fromkeys(chrom.tolist()):
        m = chrom == contig
        if contig not in fasta.references:
            continue
        seq = encode_seq(fasta.fetch(contig, 0, fasta.get_reference_length(contig)))
        padded = np.concatenate([np.full(radius, 4, np.uint8), seq, np.full(radius, 4, np.uint8)])
        centers = pos0[m].astype(np.int64) + radius
        idx = centers[:, None] + np.arange(-radius, radius + 1)[None, :]
        # positions beyond the contig (wrong reference build / truncated
        # FASTA) read as N instead of crashing the whole ingest
        valid = (idx >= 0) & (idx < len(padded))
        out[m] = np.where(valid, padded[np.clip(idx, 0, len(padded) - 1)], 4)
    return out


@dataclass
class FeatureSet:
    """Named per-variant feature columns + assembly into a (N, F) matrix."""

    columns: dict[str, np.ndarray]
    feature_names: list[str]
    windows: np.ndarray | None = None  # (N, 2*WINDOW_RADIUS+1) uint8 ref context

    def matrix(self, names: list[str] | None = None) -> np.ndarray:
        names = names or self.feature_names
        return np.stack([np.asarray(self.columns[f], dtype=np.float32) for f in names], axis=1)

    def __len__(self) -> int:
        return len(next(iter(self.columns.values())))


def _compute_af(table: VariantTable) -> np.ndarray:
    """Allele fraction per record: FORMAT AD (alt/sum) where present, else INFO AF."""
    info_af = table.info_field("AF", dtype=np.float64).astype(np.float32)
    if table.aux is not None:
        ad1 = table.aux.ad[:, 1]
        tot = np.where(np.isnan(table.aux.ad[:, 2]), 0, table.aux.ad[:, 2])
        alt = np.where(np.isnan(ad1) | (ad1 < 0), 0, ad1)
    else:
        ad = table.format_numeric("AD")
        if ad.shape[1] < 2:
            return info_af
        tot = np.sum(np.where(ad > 0, ad, 0), axis=1)
        alt = np.where(ad[:, 1] > 0, ad[:, 1], 0)
    with np.errstate(invalid="ignore", divide="ignore"):
        ad_af = np.where(tot > 0, alt / np.maximum(tot, 1), np.nan).astype(np.float32)
    return np.where(np.isnan(ad_af), info_af, ad_af)


def featurize(
    table: VariantTable,
    fasta: FastaReader,
    annotate_intervals: dict[str, IntervalSet] | None = None,
    flow_order: str = fops.DEFAULT_FLOW_ORDER,
    extra_info_fields: list[str] | None = None,
) -> FeatureSet:
    """Full featurization: BASE_FEATURES + one 0/1 column per annotation interval.

    Device kernels are jit-compiled once per padded batch shape.
    """
    alle = classify_alleles(table)
    windows = gather_windows(table, fasta)

    jw = jnp.asarray(windows)
    gc = fops.gc_content(jw, CENTER, radius=10)
    hmer_len, hmer_nuc = fops.hmer_indel_features(
        jw, CENTER, jnp.asarray(alle.is_indel), jnp.asarray(alle.indel_nuc)
    )
    left_motif, right_motif = fops.motif_codes(jw, CENTER, k=5)
    cyc = fops.cycle_skip_status(
        jw,
        CENTER,
        jnp.asarray(alle.ref_code),
        jnp.asarray(alle.alt_code),
        jnp.asarray(alle.is_snp),
        flow_order=flow_order,
    )

    gts = table.genotypes()
    is_het = (gts[:, 0] != gts[:, 1]) & (gts[:, 1] >= 0)
    gq = table.format_numeric("GQ", max_len=1, missing=np.nan)[:, 0]

    cols: dict[str, np.ndarray] = {
        "qual": np.nan_to_num(table.qual, nan=0.0),
        "dp": np.nan_to_num(table.info_field("DP"), nan=0.0),
        "sor": np.nan_to_num(table.info_field("SOR"), nan=0.0),
        "af": np.nan_to_num(_compute_af(table), nan=0.0),
        "gq": np.nan_to_num(gq, nan=0.0),
        "is_het": is_het.astype(np.float32),
        "is_snp": alle.is_snp.astype(np.float32),
        "is_indel": alle.is_indel.astype(np.float32),
        "is_ins": alle.is_ins.astype(np.float32),
        "indel_length": alle.indel_length,
        "hmer_indel_length": np.asarray(hmer_len),
        "hmer_indel_nuc": np.asarray(hmer_nuc),
        "gc_content": np.asarray(gc),
        "cycleskip_status": np.asarray(cyc),
        "left_motif": np.asarray(left_motif),
        "right_motif": np.asarray(right_motif),
        "ref_code": alle.ref_code,
        "alt_code": alle.alt_code,
        "n_alts": alle.n_alts,
    }
    names = list(BASE_FEATURES)

    for f in extra_info_fields or []:
        cols[f] = np.nan_to_num(table.info_field(f), nan=0.0).astype(np.float32)
        names.append(f)

    if annotate_intervals:
        coords = iops.GenomeCoords(
            table.header.contig_lengths
            or {c: fasta.get_reference_length(c) for c in fasta.references}
        )
        gpos = coords.globalize(np.asarray(table.chrom), table.pos - 1)
        for name, iv in annotate_intervals.items():
            gs, ge = coords.globalize_intervals(iv)
            cols[name] = iops.membership(gpos, gs, ge).astype(np.float32)
            names.append(name)

    return FeatureSet(columns=cols, feature_names=names, windows=windows)
