"""Variant featurization: VariantTable + reference genome -> device feature tensors.

This is the front half of the north-star hot path
(filter_variants_pipeline, docs/filter_variants_pipeline.md): the reference
computes per-variant annotations in pandas; here host code gathers fixed
-width reference windows and allele scalars, and
:mod:`variantcalling_tpu.ops.features` kernels compute the window-derived
features on device, fused with classifier inference.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from functools import partial

import numpy as np

import jax

from variantcalling_tpu.io.bed import IntervalSet
from variantcalling_tpu.io.fasta import FastaReader, encode_seq
from variantcalling_tpu.io.vcf import VariantTable
from variantcalling_tpu.ops import features as fops
from variantcalling_tpu.ops import intervals as iops

WINDOW_RADIUS = 20  # bases either side of the anchor in the gathered window
CENTER = WINDOW_RADIUS

# feature order of the assembled matrix; models store this list as metadata
BASE_FEATURES = [
    "qual",
    "dp",
    "sor",
    "af",
    "gq",
    "is_het",
    "is_snp",
    "is_indel",
    "is_ins",
    "indel_length",
    "hmer_indel_length",
    "hmer_indel_nuc",
    "gc_content",
    "cycleskip_status",
    "left_motif",
    "right_motif",
    "ref_code",
    "alt_code",
    "n_alts",
]


@dataclass
class AlleleColumns:
    """Host-derived per-variant allele scalars (first ALT; multiallelic flagged)."""

    is_snp: np.ndarray
    is_indel: np.ndarray
    is_ins: np.ndarray
    indel_length: np.ndarray
    indel_nuc: np.ndarray  # 0..3 if single-nucleotide indel diff else 4
    ref_code: np.ndarray  # anchor base code for SNPs (else 4)
    alt_code: np.ndarray
    n_alts: np.ndarray


def classify_alleles(table: VariantTable) -> AlleleColumns:
    """Indel/SNP classification from REF/ALT strings (parity: classify_indel,
    ugbio_core.vcfbed.variant_annotation; run_no_gt_report.py:92).

    Served from the native scan cache when the table came through the C++
    ingest (io/vcf._read_vcf_native) — zero per-record Python on that path.
    """
    if table.aux is not None:
        a = table.aux.alle
        cls = a["aclass"]
        return AlleleColumns(  # fresh arrays: the cache must stay pristine
            is_snp=(cls & 1).astype(bool),
            is_indel=(cls & 2).astype(bool),
            is_ins=(cls & 4).astype(bool),
            indel_length=a["indel_length"].copy(),
            indel_nuc=a["indel_nuc"].copy(),
            ref_code=a["ref_code"].copy(),
            alt_code=a["alt_code"].copy(),
            n_alts=a["n_alts"].copy(),
        )
    n = len(table)
    is_snp = np.zeros(n, dtype=bool)
    is_indel = np.zeros(n, dtype=bool)
    is_ins = np.zeros(n, dtype=bool)
    indel_length = np.zeros(n, dtype=np.int32)
    indel_nuc = np.full(n, 4, dtype=np.int32)
    ref_code = np.full(n, 4, dtype=np.int32)
    alt_code = np.full(n, 4, dtype=np.int32)
    n_alts = table.n_alts()
    code = {"A": 0, "C": 1, "G": 2, "T": 3}
    for i in range(n):
        ref = table.ref[i]
        alt_s = table.alt[i]
        if alt_s in (".", ""):
            continue
        alt = alt_s.split(",")[0]
        if alt in ("<NON_REF>", "<*>") or alt.startswith("<"):
            continue
        if len(ref) == len(alt) == 1:
            is_snp[i] = True
            ref_code[i] = code.get(ref.upper(), 4)
            alt_code[i] = code.get(alt.upper(), 4)
        elif len(ref) != len(alt):
            is_indel[i] = True
            if len(alt) > len(ref):
                is_ins[i] = True
                diff = alt[len(ref) :] if alt.startswith(ref) else alt[1:]
            else:
                diff = ref[len(alt) :] if ref.startswith(alt) else ref[1:]
            indel_length[i] = abs(len(alt) - len(ref))
            u = set(diff.upper())
            if len(u) == 1:
                indel_nuc[i] = code.get(next(iter(u)), 4)
    return AlleleColumns(is_snp, is_indel, is_ins, indel_length, indel_nuc, ref_code, alt_code, n_alts)


# device-resident genome: fasta path -> (blocked device array, offsets, lengths).
# Shipping the genome to HBM once turns per-run window transfer (41 bytes a
# variant) into an on-device gather fed by one (block, offset) int32 pair
# per variant. All contigs are concatenated with 2*WINDOW_RADIUS-wide N
# gaps so windows never leak across contig boundaries, and the array is
# reshaped to (n_blocks, 2^GENOME_BLOCK_BITS): hg38's ~3.1e9 global
# coordinates exceed int32 (the only integer width jax uses without x64),
# so all device-side indexing stays in the (small block id, small offset)
# pair. The fused program compiles ONCE (per-contig arrays would retrace
# per contig length). Two entries cached (the sharded + unsharded variants
# of one genome; ~3.1GB HBM each for hg38).
_DEVICE_GENOME_CACHE: dict = {}
_DEVICE_GENOME_MAX = 2
# chunk featurization fans out on the IO pool (vctpu-lint VCT010): a
# per-KEY build lock makes a cache miss build-once-wait-rest — two
# workers racing the SAME genome would otherwise both encode and upload
# ~3.1GB to HBM — while builds of DISTINCT keys (different fasta/radius/
# sharding) proceed concurrently instead of queueing behind a multi-
# second upload they do not want. The global lock only guards the dicts.
_DEVICE_GENOME_LOCK = threading.Lock()
_DEVICE_GENOME_KEYLOCKS: dict = {}
# tables below this size featurize through the host window gather — a tiny
# job must not pay a whole-genome encode + HBM upload
GENOME_RESIDENT_MIN_VARIANTS = 100_000


def _genome_resident_worthwhile(table, fasta, radius: int | None = None,
                                sharding=None) -> bool:
    """True when the EXACT genome entry the caller would use is already
    resident, or the table is big enough to amortize the upload. Matching
    on path alone would route small jobs onto a cache MISS (different
    radius/sharding key) and re-upload the genome for 50 variants."""
    key = (getattr(fasta, "path", id(fasta)),
           WINDOW_RADIUS if radius is None else radius, str(sharding))
    return key in _DEVICE_GENOME_CACHE or len(table) >= GENOME_RESIDENT_MIN_VARIANTS
GENOME_BLOCK_BITS = 20
_GBLOCK = 1 << GENOME_BLOCK_BITS


_FLAT_MAX = (1 << 31) - 4 * _GBLOCK  # flat int32 layout headroom


class DeviceGenome:
    __slots__ = ("blocks", "offsets", "lengths", "flat")

    def __init__(self, blocks, offsets: dict[str, int], lengths: dict[str, int],
                 flat: bool):
        # flat=True: ``blocks`` is a 1-D array (total length < 2^31) and
        # windows gather with plain int32 indices — the fast path. Larger
        # genomes (hg38 + gaps ~3.2e9 > int32) use the (block, offset)
        # 2-D layout, which costs an extra coordinate per lookup.
        self.blocks = blocks
        self.offsets = offsets
        self.lengths = lengths
        self.flat = flat


def device_genome(fasta: FastaReader, radius: int = WINDOW_RADIUS,
                  sharding=None) -> DeviceGenome:
    key = (getattr(fasta, "path", id(fasta)), radius, str(sharding))
    hit = _DEVICE_GENOME_CACHE.get(key)
    if hit is not None:
        return hit
    with _DEVICE_GENOME_LOCK:
        hit = _DEVICE_GENOME_CACHE.get(key)
        if hit is not None:
            return hit
        # one small Lock per distinct key for the process lifetime —
        # a handful of genomes, never evicted (evicting one while a
        # builder holds it would let a third thread double-build)
        key_lock = _DEVICE_GENOME_KEYLOCKS.setdefault(key, threading.Lock())
    with key_lock:
        with _DEVICE_GENOME_LOCK:
            hit = _DEVICE_GENOME_CACHE.get(key)  # re-check: the builder we waited on
            if hit is not None:
                return hit
        out = _build_device_genome(fasta, radius, sharding)
        with _DEVICE_GENOME_LOCK:
            while len(_DEVICE_GENOME_CACHE) >= _DEVICE_GENOME_MAX:
                _DEVICE_GENOME_CACHE.pop(next(iter(_DEVICE_GENOME_CACHE)))
            _DEVICE_GENOME_CACHE[key] = out
    return out


def _build_device_genome(fasta: FastaReader, radius: int,
                         sharding) -> DeviceGenome:
    gap = np.full(2 * radius, 4, dtype=np.uint8)
    parts = [gap]
    offsets: dict[str, int] = {}
    lengths: dict[str, int] = {}
    cur = len(gap)
    for contig in fasta.references:
        seq = encode_seq(fasta.fetch(contig, 0, fasta.get_reference_length(contig)))
        offsets[contig] = cur
        lengths[contig] = len(seq)
        parts.append(seq)
        parts.append(gap)
        cur += len(seq) + len(gap)
    flat_arr = np.concatenate(parts)
    use_flat = len(flat_arr) < _FLAT_MAX
    if not use_flat:
        pad = (-len(flat_arr)) % _GBLOCK
        if pad:
            flat_arr = np.concatenate([flat_arr, np.full(pad, 4, dtype=np.uint8)])
        flat_arr = flat_arr.reshape(-1, _GBLOCK)
    arr = jax.device_put(flat_arr, sharding) if sharding is not None else jax.device_put(flat_arr)
    return DeviceGenome(arr, offsets, lengths, use_flat)


def globalize_positions(table: VariantTable, genome: DeviceGenome,
                        radius: int = WINDOW_RADIUS) -> tuple[np.ndarray, np.ndarray]:
    """(block int32, within-block offset int32) per record.

    Unknown contigs and positions past the contig end (wrong reference
    build / truncated FASTA) get an out-of-range block so their windows
    read all-N — the host gather's safety behavior. Positions within
    ``radius`` past the end still resolve idx-wise into the N gap, exactly
    like the host path.
    """
    import pandas as pd

    chrom = pd.Series(np.asarray(table.chrom))
    off = chrom.map(genome.offsets).to_numpy(dtype=np.float64)  # NaN = unknown
    clen = chrom.map(genome.lengths).to_numpy(dtype=np.float64)
    pos0 = table.pos.astype(np.int64) - 1
    gpos = pos0 + np.nan_to_num(off, nan=0).astype(np.int64)
    bad = np.isnan(off) | (pos0 < 0) | (pos0 >= np.nan_to_num(clen, nan=-1) + radius)
    if genome.flat:
        gpos[bad] = int(genome.blocks.shape[0]) + _GBLOCK  # past the end
        return np.zeros(len(gpos), dtype=np.int32), gpos.astype(np.int32)
    n_blocks = int(genome.blocks.shape[0])
    gpos[bad] = n_blocks * _GBLOCK + _GBLOCK  # one block past the end
    return (gpos >> GENOME_BLOCK_BITS).astype(np.int32), \
        (gpos & (_GBLOCK - 1)).astype(np.int32)


def genome_packable(fasta: FastaReader, radius: int = WINDOW_RADIUS) -> bool:
    """Whether the genome's positions will fit 4-byte packing — computable
    from contig lengths alone, BEFORE paying the encode + HBM upload."""
    gap = 2 * radius
    total = gap + sum(fasta.get_reference_length(c) + gap for c in fasta.references)
    if total < _FLAT_MAX:
        return True
    n_blocks = -(-total // _GBLOCK)
    return (n_blocks + 3) << GENOME_BLOCK_BITS <= (1 << 32)


def pack_global_positions(block: np.ndarray, off: np.ndarray, genome: DeviceGenome) -> np.ndarray | None:
    """Pack (block, offset) into ONE uint32 per record, or None if it can't fit.

    Transfer-thinning for the fused scoring path: the per-variant position
    pair (8 bytes) becomes 4 bytes on the wire. Fits whenever every
    possible packed value — including the out-of-range sentinel and the
    +1-block headroom the device-side unpack can produce — stays below
    2^32 (hg38 + N gaps ≈ 3.2e9, comfortably in range).
    """
    if genome.flat:
        # flat genomes are < 2^31 by construction (io gather is int32)
        return off.astype(np.uint32)
    n_blocks = int(genome.blocks.shape[0])
    if (n_blocks + 3) << GENOME_BLOCK_BITS > (1 << 32):
        return None
    return ((block.astype(np.int64) << GENOME_BLOCK_BITS) | off.astype(np.int64)).astype(np.uint32)


def packed_position_fill(genome: DeviceGenome) -> int:
    """Padding value for packed positions: one block past the genome end."""
    if genome.flat:
        return int(genome.blocks.shape[0]) + _GBLOCK
    return (int(genome.blocks.shape[0]) + 1) << GENOME_BLOCK_BITS


def windows_from_packed(genome_blocks, gpos, radius: int = WINDOW_RADIUS):
    """Windows gathered from uint32 packed positions (traceable).

    Flat genomes treat the packed value as the flat index; blocked genomes
    unpack the (block, offset) pair before the gather.
    """
    import jax.numpy as jnp

    if genome_blocks.ndim == 1:
        return windows_on_device(genome_blocks, None, gpos.astype(jnp.int32), radius)
    g = gpos.astype(jnp.uint32)
    blk = (g >> GENOME_BLOCK_BITS).astype(jnp.int32)
    off = (g & jnp.uint32(_GBLOCK - 1)).astype(jnp.int32)
    return windows_on_device(genome_blocks, blk, off, radius)


def windows_on_device(genome_blocks, block, off, radius: int = WINDOW_RADIUS):
    """(N, 2R+1) uint8 windows gathered on device; out-of-range reads N=4.

    Traceable — used inside the fused featurize+score program so the window
    tensor never exists host-side. All arithmetic is int32-safe: 1-D
    genomes (< 2^31) gather flat; larger ones use the (block + carry,
    offset within block) pair.
    """
    import jax.numpy as jnp

    if genome_blocks.ndim == 1:  # flat fast path
        idx = off[:, None] + jnp.arange(-radius, radius + 1)[None, :]
        glen = genome_blocks.shape[0]
        valid = (idx >= 0) & (idx < glen)
        vals = genome_blocks[jnp.clip(idx, 0, glen - 1)]
        return jnp.where(valid, vals, 4).astype(jnp.uint8)

    t = off[:, None] + jnp.arange(-radius, radius + 1)[None, :]  # may be +-R out
    blk = block[:, None] + (t >> GENOME_BLOCK_BITS)  # arithmetic shift: floor div
    o2 = t & (_GBLOCK - 1)
    n_blocks = genome_blocks.shape[0]
    valid = (blk >= 0) & (blk < n_blocks)
    vals = genome_blocks[jnp.clip(blk, 0, n_blocks - 1), o2]
    return jnp.where(valid, vals, 4).astype(jnp.uint8)


def _contig_runs(table_or_chrom, n: int):
    """Factorized contig column + contiguous-run bounds (or None).

    Sorted VCFs put each contig in ONE contiguous run, so per-contig work
    can slice row ranges instead of boolean-masking (a mask pass + scatter
    costs ~4 full sweeps of a window tensor at 5M variants). Shared by
    :func:`gather_windows` and :func:`featurize_gather_fused` so the fused
    fast path and its fallback can never disagree on contig handling.
    Returns (codes, uniques, bounds) with bounds None when runs are not
    contiguous (callers fall back to masks).

    Accepts the :class:`VariantTable` itself when available: the native
    scan already factorized CHROM into integer codes, and re-factorizing
    1M Python strings per chunk was ~15% of the streaming score stage's
    GIL-holding glue (the per-chunk pandas factorize on the hot path).
    The derived runs are MEMOIZED on the table — the scoring body asks
    for them up to three times per chunk (window gather, fused
    featurize, the fused native scorer), and re-deriving runs the parser
    already knows was pure repeat work. Native-scan codes are assigned
    in first-appearance order, so the sorted common case skips the
    remap LUT pass entirely (codes returned as-is, zero copies).
    """
    chrom = table_or_chrom
    codes = getattr(table_or_chrom, "chrom_codes", None)
    if codes is not None:
        memo = getattr(table_or_chrom, "_contig_runs_memo", None)
        if memo is not None:
            return memo
        names = table_or_chrom.chrom_names
        change = np.flatnonzero(codes[1:] != codes[:-1]) + 1 if n > 1 \
            else np.empty(0, np.int64)
        starts = np.concatenate([[0], change]).astype(np.int64) if n else \
            np.empty(0, np.int64)
        run_codes = codes[starts] if n else np.empty(0, codes.dtype)
        if len(np.unique(run_codes)) == len(run_codes):
            # each contig appears in exactly one run (the sorted case):
            # remap the dictionary codes to appearance order so callers'
            # enumerate(uniques) indexing matches the mask codes
            uniques = np.asarray([names[c] for c in run_codes], dtype=object)
            bounds = np.concatenate([starts, [n]])
            if np.array_equal(run_codes, np.arange(len(run_codes))):
                # native-scan codes already ARE appearance order (the
                # parser assigns them first-seen): no LUT, no remap copy
                out_codes = codes
            else:
                lut = np.zeros(len(names), dtype=np.int64)
                lut[run_codes] = np.arange(len(run_codes))
                out_codes = lut[codes]
            memo = (out_codes, uniques, bounds)
            try:
                table_or_chrom._contig_runs_memo = memo
            except AttributeError:
                pass  # slotted/frozen table: memo is best-effort
            return memo
        chrom = table_or_chrom.chrom  # unsorted chunk: factorize below
    elif not isinstance(table_or_chrom, np.ndarray) and hasattr(table_or_chrom, "chrom"):
        chrom = table_or_chrom.chrom
    import pandas as pd

    codes, uniques = pd.factorize(np.asarray(chrom), use_na_sentinel=False)
    change = np.flatnonzero(codes[1:] != codes[:-1]) + 1 if n > 1 else np.empty(0, np.int64)
    contiguous = len(change) == len(uniques) - 1
    bounds = np.concatenate([[0], change, [n]]) if contiguous else None
    return codes, uniques, bounds


def gather_windows(table: VariantTable, fasta: FastaReader, radius: int = WINDOW_RADIUS) -> np.ndarray:
    """(N, 2*radius+1) uint8 reference windows centered on each variant anchor.

    One contig-sequence encode per contig, then a vectorized gather — the
    host-side analog of the reference's per-record pyfaidx fetches.
    """
    from variantcalling_tpu import native

    n = len(table)
    out = np.full((n, 2 * radius + 1), 4, dtype=np.uint8)
    codes, uniques, bounds = _contig_runs(table, n)
    contiguous = bounds is not None
    pos0 = table.pos - 1

    def gather_one(seq, sub, target=None):
        rows = native.gather_windows_contig(seq, sub, radius, out=target)
        if rows is None:
            # numpy fallback: padded fancy-index gather; positions beyond
            # the contig (wrong reference build / truncated FASTA) read as
            # N instead of crashing the whole ingest
            padded = np.concatenate([np.full(radius, 4, np.uint8), seq, np.full(radius, 4, np.uint8)])
            idx = (sub + radius)[:, None] + np.arange(-radius, radius + 1)[None, :]
            valid = (idx >= 0) & (idx < len(padded))
            rows = np.where(valid, padded[np.clip(idx, 0, len(padded) - 1)], 4)
        return rows

    for ui, contig in enumerate(uniques):
        if contig not in fasta.references:
            continue
        seq = fasta.fetch_encoded(contig)
        if contiguous:
            lo, hi = int(bounds[ui]), int(bounds[ui + 1])
            target = out[lo:hi]
            rows = gather_one(seq, pos0[lo:hi].astype(np.int64, copy=False), target=target)
            if rows is not target:
                out[lo:hi] = rows  # fallback produced a fresh array
        else:
            m = codes == ui
            out[m] = gather_one(seq, pos0[m].astype(np.int64, copy=False))
    return out


def featurize_gather_fused(table: VariantTable, fasta: FastaReader, alle,
                           flow_order: np.ndarray,
                           radius: int = WINDOW_RADIUS) -> dict | None:
    """The six window-derived DEVICE_FEATURES columns via the fused native
    gather+featurize kernel — the (N, 2r+1) window tensor is never
    materialized (two full sweeps of it saved on the 5M CPU hot path).
    Mirrors :func:`gather_windows`' contig handling exactly: per-contig
    contiguous runs when the VCF is sorted, scatter via masks otherwise,
    contigs missing from the FASTA read as all-N. Returns None when the
    native kernel is unavailable (caller gathers + featurizes separately).
    """
    from variantcalling_tpu import native

    if not native.available():
        return None
    n = len(table)
    outs = (np.empty(n, np.int32), np.empty(n, np.int32), np.empty(n, np.float32),
            np.empty(n, np.int32), np.empty(n, np.int32), np.empty(n, np.int32))
    codes, uniques, bounds = _contig_runs(table, n)
    contiguous = bounds is not None
    pos0 = table.pos - 1
    aux = (alle.is_indel, alle.indel_nuc, alle.ref_code, alle.alt_code, alle.is_snp)
    empty = np.empty(0, dtype=np.uint8)  # missing contig -> every window all-N
    for ui, contig in enumerate(uniques):
        seq = fasta.fetch_encoded(contig) if contig in fasta.references else empty
        if contiguous:
            lo, hi = int(bounds[ui]), int(bounds[ui + 1])
            ok = native.featurize_gather(
                seq, pos0[lo:hi].astype(np.int64, copy=False), radius,
                *(a[lo:hi] for a in aux), flow_order,
                tuple(o[lo:hi] for o in outs))
        else:
            m = codes == ui
            sub_outs = tuple(np.empty(int(m.sum()), o.dtype) for o in outs)
            ok = native.featurize_gather(
                seq, pos0[m].astype(np.int64, copy=False), radius,
                *(a[m] for a in aux), flow_order, sub_outs)
            if ok:
                for o, so in zip(outs, sub_outs):
                    o[m] = so
        if not ok:
            return None
    hl, hn, gc, cy, lm, rm = outs
    return {"hmer_indel_length": hl, "hmer_indel_nuc": hn, "gc_content": gc,
            "cycleskip_status": cy, "left_motif": lm, "right_motif": rm}


@dataclass
class FeatureSet:
    """Named per-variant feature columns + assembly into a (N, F) matrix."""

    columns: dict[str, np.ndarray]
    feature_names: list[str]
    windows: np.ndarray | None = None  # (N, 2*WINDOW_RADIUS+1) uint8 ref context

    def matrix(self, names: list[str] | None = None) -> np.ndarray:
        names = names or self.feature_names
        return np.stack([np.asarray(self.columns[f], dtype=np.float32) for f in names], axis=1)

    def __len__(self) -> int:
        return len(next(iter(self.columns.values())))


def _compute_af(table: VariantTable) -> np.ndarray:
    """Allele fraction per record: FORMAT AD (alt/sum) where present, else INFO AF."""
    info_af = table.info_field("AF", dtype=np.float64).astype(np.float32)
    if table.aux is not None:
        ad1 = table.aux.ad[:, 1]
        tot = np.where(np.isnan(table.aux.ad[:, 2]), 0, table.aux.ad[:, 2])
        alt = np.where(np.isnan(ad1) | (ad1 < 0), 0, ad1)
    else:
        ad = table.format_numeric("AD")
        if ad.shape[1] < 2:
            return info_af
        tot = np.sum(np.where(ad > 0, ad, 0), axis=1)
        alt = np.where(ad[:, 1] > 0, ad[:, 1], 0)
    with np.errstate(invalid="ignore", divide="ignore"):
        ad_af = np.where(tot > 0, alt / np.maximum(tot, 1), np.nan).astype(np.float32)
    return np.where(np.isnan(ad_af), info_af, ad_af)


def device_feature_dict(windows, is_indel, indel_nuc, ref_code, alt_code, is_snp,
                        *, center: int, flow_order: str) -> dict:
    """The window-kernel block, traceable inside any jitted program.

    Single source of truth for the DEVICE_FEATURES columns — featurize()'s
    standalone program and the filter pipeline's fused featurize+score
    program both call this, so train/serve feature parity holds by
    construction.
    """
    gc = fops.gc_content(windows, center, radius=10)
    hmer_len, hmer_nuc = fops.hmer_indel_features(windows, center, is_indel, indel_nuc)
    left_motif, right_motif = fops.motif_codes(windows, center, k=5)
    cyc = fops.cycle_skip_status(windows, center, ref_code, alt_code, is_snp, flow_order=flow_order)
    return {
        "hmer_indel_length": hmer_len,
        "hmer_indel_nuc": hmer_nuc,
        "gc_content": gc,
        "cycleskip_status": cyc,
        "left_motif": left_motif,
        "right_motif": right_motif,
    }


@partial(jax.jit, static_argnames=("center", "flow_order"))
def _device_feature_program(windows, is_indel, indel_nuc, ref_code, alt_code, is_snp,
                            *, center: int, flow_order: str):
    """Jitted standalone wrapper over :func:`device_feature_dict`.

    Module-level so the jit cache persists across featurize() calls — the
    cycle-skip lax.scan in particular must not retrace per call (it costs a
    full XLA compile). Cache key = (padded batch shape, center, flow_order).
    """
    d = device_feature_dict(windows, is_indel, indel_nuc, ref_code, alt_code, is_snp,
                            center=center, flow_order=flow_order)
    return tuple(d[k] for k in DEVICE_FEATURES)


_PAD_MIN = 1 << 10


def _bucket(n: int) -> int:
    """Next power-of-two batch size: bounds distinct compiled shapes to log2(N)."""
    b = _PAD_MIN
    while b < n:
        b <<= 1
    return b


# feature columns produced ON DEVICE by the window kernels; everything else
# in BASE_FEATURES comes from host-side allele/FORMAT/INFO columns
DEVICE_FEATURES = (
    "hmer_indel_length",
    "hmer_indel_nuc",
    "gc_content",
    "cycleskip_status",
    "left_motif",
    "right_motif",
)


@dataclass
class HostFeatures:
    """Host half of featurization: windows + every non-window column.

    ``names`` is the FULL feature order (host + device columns interleaved
    per BASE_FEATURES); consumers either run the device program to fill the
    device columns (featurize) or fuse them into a larger device program
    (filter_variants' featurize+score fusion).
    """

    alle: AlleleColumns
    windows: np.ndarray  # (N, 2*WINDOW_RADIUS+1) uint8
    cols: dict[str, np.ndarray]  # host columns only
    names: list[str]  # full feature order, incl. DEVICE_FEATURES


def host_featurize(
    table: VariantTable,
    fasta: FastaReader,
    annotate_intervals: dict[str, IntervalSet] | None = None,
    extra_info_fields: list[str] | None = None,
    compute_windows: bool = True,
    keep_nan: bool = False,
) -> HostFeatures:
    """``compute_windows=False`` skips the host window gather — for the
    device-resident-genome scoring path, where windows are gathered in HBM.

    ``keep_nan=True`` preserves NaN for absent QUAL/INFO/FORMAT values
    instead of zero-filling — required when the scoring model carries
    xgboost default_left routing, whose semantics are defined ON the
    missing values (the reference feeds raw NaN into predict_proba).
    """
    alle = classify_alleles(table)
    windows = gather_windows(table, fasta) if compute_windows else None

    gts = table.genotypes()
    is_het = (gts[:, 0] != gts[:, 1]) & (gts[:, 1] >= 0)
    gq = table.format_numeric("GQ", max_len=1, missing=np.nan)[:, 0]

    def missing(a):
        return a if keep_nan else np.nan_to_num(a, nan=0.0)

    cols: dict[str, np.ndarray] = {
        "qual": missing(table.qual),
        "dp": missing(table.info_field("DP")),
        "sor": missing(table.info_field("SOR")),
        "af": missing(_compute_af(table)),
        "gq": missing(gq),
        "is_het": is_het.astype(np.float32),
        "is_snp": alle.is_snp.astype(np.float32),
        "is_indel": alle.is_indel.astype(np.float32),
        "is_ins": alle.is_ins.astype(np.float32),
        "indel_length": alle.indel_length,
        "ref_code": alle.ref_code,
        "alt_code": alle.alt_code,
        "n_alts": alle.n_alts,
    }
    names = list(BASE_FEATURES)

    for f in extra_info_fields or []:
        cols[f] = missing(table.info_field(f)).astype(np.float32)
        names.append(f)

    if annotate_intervals:
        coords = iops.GenomeCoords(
            table.header.contig_lengths
            or {c: fasta.get_reference_length(c) for c in fasta.references}
        )
        gpos = coords.globalize(np.asarray(table.chrom), table.pos - 1)
        for name, iv in annotate_intervals.items():
            gs, ge = coords.globalize_intervals(iv)
            cols[name] = iops.membership(gpos, gs, ge).astype(np.float32)
            names.append(name)

    return HostFeatures(alle=alle, windows=windows, cols=cols, names=names)


def standard_genome_sharding(mesh=None):
    """The ONE sharding every consumer passes to device_genome: replicated
    over ``mesh`` when the caller resolved a run scoring mesh (the
    filter pipeline's >1-device mesh plan), else the process-default
    policy (replicate over the full (dp, mp) local mesh on multi-device
    processes, None single-device). Mesh-plan callers route their
    possibly-None mesh through here unconditionally — a single-device
    plan falls through to the SAME default policy as every no-arg
    consumer, so the cache key cannot split on who uploaded first.

    All genome-cache keys include the sharding, so consumers that chose
    shardings independently would split the cache — and the small-job
    guard (_genome_resident_worthwhile) would answer differently
    depending on which consumer ran first (round-2 VERDICT weak #6).
    Routing through this helper makes the key identical by construction;
    mesh-plan callers must pass the SAME resolved mesh everywhere
    (FilterContext does).
    """
    from variantcalling_tpu.parallel.mesh import make_mesh, replicated

    if mesh is not None:
        return replicated(mesh)
    if len(jax.local_devices()) <= 1:
        return None
    return replicated(make_mesh(n_model=1))


def featurize(
    table: VariantTable,
    fasta: FastaReader,
    annotate_intervals: dict[str, IntervalSet] | None = None,
    flow_order: str = fops.DEFAULT_FLOW_ORDER,
    extra_info_fields: list[str] | None = None,
) -> FeatureSet:
    """Full featurization: BASE_FEATURES + one 0/1 column per annotation interval.

    Window features come from the device-resident genome (one HBM upload
    per FASTA, on-device gather — run_comparison/train_models share the
    filter pipeline's hot-path design); device kernels are jit-compiled
    once per padded batch shape.
    """
    resident = _genome_resident_worthwhile(table, fasta, sharding=standard_genome_sharding())
    hf = host_featurize(table, fasta, annotate_intervals=annotate_intervals,
                        extra_info_fields=extra_info_fields,
                        compute_windows=not resident)
    if resident:
        return materialize_features(hf, flow_order=flow_order, table=table, fasta=fasta)
    return materialize_features(hf, flow_order=flow_order)


@partial(jax.jit, static_argnames=("center", "flow_order"))
def _device_feature_program_genome(genome_blocks, block, off, is_indel, indel_nuc,
                                   ref_code, alt_code, is_snp, *, center: int,
                                   flow_order: str):
    """Standalone window-kernel program over the device-resident genome."""
    windows = windows_on_device(genome_blocks, block, off, radius=center)
    d = device_feature_dict(windows, is_indel, indel_nuc, ref_code, alt_code, is_snp,
                            center=center, flow_order=flow_order)
    return tuple(d[k] for k in DEVICE_FEATURES)


def materialize_features(hf: HostFeatures, flow_order: str = fops.DEFAULT_FLOW_ORDER,
                         table: VariantTable | None = None,
                         fasta: FastaReader | None = None) -> FeatureSet:
    """Run the device window kernels over a HostFeatures batch and merge.

    With host windows absent and (table, fasta) given, windows are gathered
    on device from the resident genome (no host window tensor at all).
    """
    alle, windows = hf.alle, hf.windows
    genome_path = windows is None and table is not None and fasta is not None
    n = len(table) if genome_path else len(windows)
    b = _bucket(n)

    def pad(a, fill=0):
        a = np.asarray(a)
        return np.pad(a, [(0, b - n)] + [(0, 0)] * (a.ndim - 1), constant_values=fill)

    alle_args = (
        pad(alle.is_indel),
        pad(alle.indel_nuc, fill=4),
        pad(alle.ref_code, fill=4),
        pad(alle.alt_code, fill=4),
        pad(alle.is_snp),
    )
    if genome_path:
        genome = device_genome(fasta, sharding=standard_genome_sharding())
        blk, off = globalize_positions(table, genome)
        n_blocks = int(genome.blocks.shape[0])
        device_out = _device_feature_program_genome(
            genome.blocks, pad(blk, fill=n_blocks + 1), pad(off), *alle_args,
            center=CENTER, flow_order=flow_order,
        )
    else:
        device_out = _device_feature_program(
            pad(windows, fill=4), *alle_args, center=CENTER, flow_order=flow_order,
        )
    # one bulk fetch for all six outputs (each np.asarray would sync separately)
    fetched = jax.device_get(device_out)
    cols = dict(hf.cols)
    cols.update({k: v[:n] for k, v in zip(DEVICE_FEATURES, fetched)})
    return FeatureSet(columns=cols, feature_names=hf.names, windows=windows)
