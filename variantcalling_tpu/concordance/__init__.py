"""Concordance: callset-vs-ground-truth accounting, metrics, and curves."""
