"""Accuracy metrics + recall/precision curves over a concordance frame.

Re-derivation of ``ugbio_core.concordance.concordance_utils`` (missing
submodule; contract from evaluate_concordance.py:100-108, output table in
docs/evaluate_concordance.md:46-58, filtering semantics from
report_utils.py:415-470). The per-category tally runs as one MXU matmul
(ops/concordance.grouped_confusion); curves use the FN-mask-aware PR curve
(utils/stats_utils.precision_recall_curve, parity stats_utils.py:141-210).

Input frame columns (run_comparison_pipeline schema, report_data_loader.py:
66-104): ``classify``/``classify_gt`` in {tp, fp, fn}, ``filter``,
``tree_score``, ``indel`` (bool), ``hmer_indel_length`` (int), plus any
custom grouping column.
"""

from __future__ import annotations

import numpy as np
import pandas as pd

from variantcalling_tpu.ops.concordance import accuracy_from_counts, grouped_confusion
from variantcalling_tpu.utils.stats_utils import precision_recall_curve

# default variant categories (docs/evaluate_concordance.md:49-58); each is
# (name, selector(df) -> bool mask); categories may overlap (INDELS).
_HMER = "hmer_indel_length"


def default_categories() -> list[tuple[str, callable]]:
    return [
        ("SNP", lambda d: ~_indel(d)),
        ("Non-hmer INDEL", lambda d: _indel(d) & (_hmer(d) == 0)),
        ("HMER indel <= 4", lambda d: _indel(d) & (_hmer(d) > 0) & (_hmer(d) <= 4)),
        ("HMER indel (4:8]", lambda d: _indel(d) & (_hmer(d) > 4) & (_hmer(d) <= 8)),
        ("HMER indel [8:10]", lambda d: _indel(d) & (_hmer(d) > 8) & (_hmer(d) <= 10)),
        ("HMER indel 11:12", lambda d: _indel(d) & (_hmer(d) > 10) & (_hmer(d) <= 12)),
        ("HMER indel > 12", lambda d: _indel(d) & (_hmer(d) > 12)),
        ("INDELS", _indel),
    ]


def _indel(d: pd.DataFrame) -> np.ndarray:
    if "indel" in d.columns:
        return np.asarray(d["indel"], dtype=bool)
    ref = d["ref"].astype(str).str.len()
    alt = d["alleles"].astype(str) if "alleles" in d.columns else d["alt"].astype(str)
    return np.asarray(ref != alt.str.split(",").str[0].str.len())


def _hmer(d: pd.DataFrame) -> np.ndarray:
    if _HMER in d.columns:
        return np.nan_to_num(np.asarray(d[_HMER], dtype=float)).astype(int)
    return np.zeros(len(d), dtype=int)


def category_masks(df: pd.DataFrame, group_testing_column: str | None = None) -> tuple[list[str], np.ndarray]:
    """(names, (G, N) bool mask matrix) for default or custom grouping."""
    if group_testing_column and group_testing_column in df.columns:
        values = df[group_testing_column].astype(str).to_numpy()
        names = sorted(set(values))
        masks = np.stack([values == name for name in names])
        return names, masks
    cats = default_categories()
    names = [name for name, _ in cats]
    masks = np.stack([np.asarray(sel(df), dtype=bool) for _, sel in cats])
    return names, masks


def passes_filter(filters: np.ndarray, ignored_filters: list[str] | None) -> np.ndarray:
    """True where FILTER is PASS after dropping ``ignored_filters``.

    evaluate_concordance defaults to ignoring HPOL_RUN (:44-48): a variant
    filtered *only* by ignored filters still counts as passing.
    """
    ignored = set(ignored_filters or [])
    out = np.empty(len(filters), dtype=bool)
    for i, f in enumerate(filters):
        if f in ("PASS", ".", "", None):
            out[i] = True
        else:
            out[i] = not (set(str(f).split(";")) - ignored - {"PASS"})
    return out


def _classes(df: pd.DataFrame, classify_column: str) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    cls = df[classify_column].astype(str).to_numpy()
    return cls == "tp", cls == "fp", cls == "fn"


def calc_accuracy_metrics(
    df: pd.DataFrame,
    classify_column: str,
    ignored_filters: list[str] | None = None,
    group_testing_column: str | None = None,
) -> pd.DataFrame:
    """Per-category [tp, fp, fn, precision, recall, f1] at the filter operating point."""
    names, masks = category_masks(df, group_testing_column)
    is_tp, is_fp, is_fn = _classes(df, classify_column)
    pf = passes_filter(df["filter"].to_numpy() if "filter" in df.columns else np.array(["PASS"] * len(df)),
                       ignored_filters)
    counts = np.asarray(grouped_confusion(masks, is_tp, is_fp, is_fn, pf))
    acc = np.asarray(accuracy_from_counts(counts))
    out = pd.DataFrame(
        {
            "group": names,
            "tp": counts[:, 0].astype(int),
            "fp": counts[:, 1].astype(int),
            "fn": counts[:, 2].astype(int),
            "precision": np.round(acc[:, 0], 5),
            "recall": np.round(acc[:, 1], 5),
            "f1": np.round(acc[:, 2], 5),
        }
    )
    return out


def calc_recall_precision_curve(
    df: pd.DataFrame,
    classify_column: str,
    ignored_filters: list[str] | None = None,
    group_testing_column: str | None = None,
) -> pd.DataFrame:
    """Per-category score-sweep curve + max-F1 threshold.

    One row per category with array-valued ``precision``/``recall``/``f1``/
    ``predictions`` columns and the scalar ``threshold`` that maximizes F1
    (the value evaluate_concordance writes to ``<prefix>.thresholds.csv``).
    """
    names, masks = category_masks(df, group_testing_column)
    is_tp, is_fp, is_fn = _classes(df, classify_column)
    scores = np.nan_to_num(np.asarray(df["tree_score"], dtype=float)) if "tree_score" in df.columns else np.ones(len(df))

    rows = []
    for gi, name in enumerate(names):
        m = masks[gi]
        # curve sweeps the score over *called* variants (tp/fp); fns carry no
        # score and enter through the FN mask's recall correction
        called = m & (is_tp | is_fp)
        labels = is_tp[m].astype(int)
        preds = np.where(called[m], scores[m], 0.0)
        fn_mask = is_fn[m]
        prec, rec, f1, thr = precision_recall_curve(labels, preds, fn_mask)
        if len(f1) and np.any(np.isfinite(f1)):
            best = int(np.nanargmax(f1))
            best_thr = float(thr[best])
        else:
            best_thr = 0.0
        rows.append(
            {
                "group": name,
                "predictions": thr,
                "precision": prec,
                "recall": rec,
                "f1": f1,
                "threshold": best_thr,
            }
        )
    return pd.DataFrame(rows)
